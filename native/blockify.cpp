// Blocked edge-layout builder — native fast path for ops/blocked.py's
// host-side preprocessing (blockify_edges + pairing_perm).
//
// The blocked MXU aggregation kernels (distegnn_tpu/ops/blocked.py) need each
// 256-node block to own a fixed slice of the edge axis, and the backward
// col-aggregation needs the reverse-edge involution of the symmetric radius
// graph. Both are computed per graph on the host; at LargeFluid scale
// (~1.7M edges/graph) the numpy version costs several O(E log E) lexsorts
// per graph per batch when the prepared-graph cache is off. This is the same
// job as a small dependency-free C++ library (single pass + two pair sorts),
// loaded via ctypes with the numpy implementation as the universal fallback
// (same degradation pattern as native/partition.cpp).
//
// C ABI:
//   int blockify_edges_native(e, row, col, attr, d, n_nodes, block, epb,
//                             out_index, out_attr, out_mask)
//     row must be ascending; returns 0 ok, 2 unsorted, 3 row out of range,
//     4 epb too small.
//   int pairing_perm_native(e, row, col, pair_out)
//     returns 0 and a verified involution-like permutation with
//     (row,col)[P[k]] == (col,row)[k]; 1 if the edge list is not symmetric.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

extern "C" {

int blockify_edges_native(int64_t e, const int64_t* row, const int64_t* col,
                          const float* attr, int64_t d, int64_t n_nodes,
                          int64_t block, int64_t epb, int32_t* out_index,
                          float* out_attr, float* out_mask) {
  if (block <= 0 || n_nodes % block) return 5;
  const int64_t nb = n_nodes / block;
  const int64_t E = nb * epb;

  std::vector<int64_t> counts(nb, 0);
  for (int64_t i = 0; i < e; ++i) {
    if (i && row[i] < row[i - 1]) return 2;
    const int64_t b = row[i] / block;
    if (row[i] < 0 || b >= nb) return 3;
    if (++counts[b] > epb) return 4;
  }

  // padding defaults: each block's slots point at its last node, mask 0
  for (int64_t b = 0; b < nb; ++b) {
    const int32_t pad = static_cast<int32_t>((b + 1) * block - 1);
    std::fill(out_index + b * epb, out_index + (b + 1) * epb, pad);
    std::fill(out_index + E + b * epb, out_index + E + (b + 1) * epb, pad);
  }
  std::fill(out_mask, out_mask + E, 0.0f);
  if (d) std::memset(out_attr, 0, sizeof(float) * E * d);

  // row-sorted input => each block's edges are one contiguous input run
  int64_t i = 0;
  for (int64_t b = 0; b < nb; ++b) {
    const int64_t dst = b * epb;
    for (int64_t k = 0; k < counts[b]; ++k, ++i) {
      out_index[dst + k] = static_cast<int32_t>(row[i]);
      out_index[E + dst + k] = static_cast<int32_t>(col[i]);
      out_mask[dst + k] = 1.0f;
      if (d) std::memcpy(out_attr + (dst + k) * d, attr + i * d, sizeof(float) * d);
    }
  }
  return 0;
}

int pairing_perm_native(int64_t e, const int32_t* row, const int32_t* col,
                        int64_t* pair_out) {
  // pack (major, minor, idx) into one u64 so the two lexicographic sorts run
  // as flat integer sorts (~4x faster than a comparator over index pairs):
  // 20 bits per node id (1M nodes), 24 bits of index (16M edges)
  int32_t mx = 0;
  for (int64_t i = 0; i < e; ++i) {
    if (row[i] < 0 || col[i] < 0) return 2;
    mx = std::max(mx, std::max(row[i], col[i]));
  }
  if (mx >= (1 << 20) || e >= (int64_t{1} << 24)) return 3;  // caller falls back

  std::vector<uint64_t> rc(e), cr(e);
  for (int64_t i = 0; i < e; ++i) {
    const uint64_t r = static_cast<uint64_t>(row[i]);
    const uint64_t c = static_cast<uint64_t>(col[i]);
    rc[i] = (r << 44) | (c << 24) | static_cast<uint64_t>(i);
    cr[i] = (c << 44) | (r << 24) | static_cast<uint64_t>(i);
  }
  std::sort(rc.begin(), rc.end());
  std::sort(cr.begin(), cr.end());
  constexpr uint64_t kIdx = (uint64_t{1} << 24) - 1;
  for (int64_t k = 0; k < e; ++k) pair_out[rc[k] & kIdx] = cr[k] & kIdx;
  for (int64_t i = 0; i < e; ++i) {
    const int64_t p = pair_out[i];
    if (row[p] != col[i] || col[p] != row[i]) return 1;
  }
  return 0;
}

}  // extern "C"
