// In-tree graph partitioner — the native replacement for libmetis.
//
// The reference reaches METIS through torch-sparse / pyg-lib C++ bindings
// (reference datasets/distribute_graphs.py:151-185). This implements the same
// job as a small, dependency-free C++ library: balanced k-way partitioning by
// recursive bisection, each bisection = greedy BFS region growing from a
// random seed followed by Fiduccia–Mattheyses-style boundary refinement
// (single-pass passes with per-node move gains, balance-constrained).
// Deterministic for a given seed.
//
// C ABI (ctypes-friendly):
//   int partition_graph(int64_t n, const int64_t* indptr,
//                       const int64_t* indices, int32_t nparts,
//                       uint64_t seed, int32_t* labels_out)
// Returns 0 on success. CSR adjacency must be symmetric (undirected).

#include <cstdint>
#include <cstring>
#include <queue>
#include <random>
#include <vector>

namespace {

struct Csr {
  int64_t n;
  const int64_t* indptr;
  const int64_t* indices;
};

// Grow a connected region of `take` nodes by BFS from a random seed node.
// Returns a 0/1 side assignment over `nodes` (local indices).
std::vector<uint8_t> grow_bisection(const Csr& g,
                                    const std::vector<int64_t>& nodes,
                                    const std::vector<int64_t>& local_of,
                                    int64_t take, std::mt19937_64& rng) {
  const int64_t n = static_cast<int64_t>(nodes.size());
  std::vector<uint8_t> side(n, 1);  // 1 = right, 0 = left (grown region)
  std::vector<uint8_t> seen(n, 0);
  std::queue<int64_t> q;

  int64_t count = 0;
  int64_t start = static_cast<int64_t>(rng() % n);
  q.push(start);
  seen[start] = 1;
  while (count < take) {
    if (q.empty()) {
      // disconnected remainder: restart from any unseen node
      for (int64_t i = 0; i < n; ++i) {
        if (!seen[i]) { q.push(i); seen[i] = 1; break; }
      }
      if (q.empty()) break;
    }
    int64_t u = q.front(); q.pop();
    side[u] = 0;
    ++count;
    int64_t gu = nodes[u];
    for (int64_t e = g.indptr[gu]; e < g.indptr[gu + 1]; ++e) {
      int64_t lv = local_of[g.indices[e]];
      if (lv >= 0 && !seen[lv]) { seen[lv] = 1; q.push(lv); }
    }
  }
  return side;
}

// One FM-style refinement pass: move boundary nodes with positive gain while
// keeping |left| within +-slack of `take`. Repeats until no improving pass.
void refine(const Csr& g, const std::vector<int64_t>& nodes,
            const std::vector<int64_t>& local_of, std::vector<uint8_t>& side,
            int64_t take, int max_passes = 10) {
  const int64_t n = static_cast<int64_t>(nodes.size());
  const int64_t slack = std::max<int64_t>(1, n / 100);
  // neither side may ever become empty: every partition must receive nodes
  const int64_t lo = std::max<int64_t>(1, take - slack);
  const int64_t hi = std::min<int64_t>(n - 1, take + slack);
  int64_t left = 0;
  for (int64_t i = 0; i < n; ++i) left += (side[i] == 0);

  for (int pass = 0; pass < max_passes; ++pass) {
    int64_t moved = 0;
    for (int64_t i = 0; i < n; ++i) {
      int64_t gi = nodes[i];
      int64_t same = 0, other = 0;
      for (int64_t e = g.indptr[gi]; e < g.indptr[gi + 1]; ++e) {
        int64_t lv = local_of[g.indices[e]];
        if (lv < 0) continue;
        if (side[lv] == side[i]) ++same; else ++other;
      }
      int64_t gain = other - same;  // cut edges removed by moving i
      if (gain <= 0) continue;
      // balance constraint
      if (side[i] == 0) {
        if (left - 1 < lo) continue;
        side[i] = 1; --left;
      } else {
        if (left + 1 > hi) continue;
        side[i] = 0; ++left;
      }
      ++moved;
    }
    if (moved == 0) break;
  }
}

void recurse(const Csr& g, std::vector<int64_t>& nodes,
             std::vector<int64_t>& local_of, int32_t parts, int32_t base,
             std::mt19937_64& rng, int32_t* labels) {
  const int64_t n = static_cast<int64_t>(nodes.size());
  if (parts <= 1) {
    for (int64_t i = 0; i < n; ++i) labels[nodes[i]] = base;
    return;
  }
  if (n <= parts) {  // degenerate: one node per part, surplus parts empty
    for (int64_t i = 0; i < n; ++i) labels[nodes[i]] = base + static_cast<int32_t>(i);
    return;
  }
  const int32_t lparts = parts / 2;
  const int64_t take = (n * lparts + parts / 2) / parts;

  // local index map for this region
  for (int64_t i = 0; i < n; ++i) local_of[nodes[i]] = i;
  auto side = grow_bisection(g, nodes, local_of, take, rng);
  refine(g, nodes, local_of, side, take);
  for (int64_t i = 0; i < n; ++i) local_of[nodes[i]] = -1;

  std::vector<int64_t> lnodes, rnodes;
  lnodes.reserve(take); rnodes.reserve(n - take);
  for (int64_t i = 0; i < n; ++i) {
    (side[i] == 0 ? lnodes : rnodes).push_back(nodes[i]);
  }
  nodes.clear(); nodes.shrink_to_fit();
  recurse(g, lnodes, local_of, lparts, base, rng, labels);
  recurse(g, rnodes, local_of, parts - lparts, base + lparts, rng, labels);
}

}  // namespace

extern "C" {

int partition_graph(int64_t n, const int64_t* indptr, const int64_t* indices,
                    int32_t nparts, uint64_t seed, int32_t* labels_out) {
  if (n <= 0 || nparts <= 0) return 1;
  Csr g{n, indptr, indices};
  std::mt19937_64 rng(seed);
  std::vector<int64_t> nodes(n);
  for (int64_t i = 0; i < n; ++i) nodes[i] = i;
  std::vector<int64_t> local_of(n, -1);
  recurse(g, nodes, local_of, nparts, 0, rng, labels_out);
  return 0;
}

// Edge cut of a labeling (for tests/diagnostics): counts directed CSR entries
// crossing parts (each undirected edge counted twice).
int64_t edge_cut(int64_t n, const int64_t* indptr, const int64_t* indices,
                 const int32_t* labels) {
  int64_t cut = 0;
  for (int64_t u = 0; u < n; ++u) {
    for (int64_t e = indptr[u]; e < indptr[u + 1]; ++e) {
      cut += (labels[u] != labels[indices[e]]);
    }
  }
  return cut;
}

}  // extern "C"
