// In-tree graph partitioner — the native replacement for libmetis.
//
// The reference reaches METIS through torch-sparse / pyg-lib C++ bindings
// (reference datasets/distribute_graphs.py:151-185). Round 3 shipped plain
// recursive bisection + FM refinement, which measured a 0.0421 cut vs
// kmeans's 0.0360 at 113k/8-way (docs/artifacts/partition_quality_113k.json,
// VERDICT r3 weak #4). This version implements the actual multilevel METIS
// scheme the reference depends on:
//
//   1. COARSEN:  heavy-edge matching (HEM) contracts matched pairs until the
//      graph is small; contracted edges/nodes carry summed weights.
//   2. PARTITION: weighted recursive bisection on the coarsest graph — BFS
//      region growing to a target WEIGHT, then weighted FM boundary
//      refinement (balance in node-weight units).
//   3. UNCOARSEN: project labels back level by level, running a k-way
//      boundary refinement (positive-gain moves under a 3% balance cap) at
//      every level — fine-level moves the flat bisection could never see.
//
// Deterministic for a given seed. Dependency-free.
//
// C ABI (ctypes-friendly; unchanged across versions):
//   int partition_graph(int64_t n, const int64_t* indptr,
//                       const int64_t* indices, int32_t nparts,
//                       uint64_t seed, int32_t* labels_out)
// Returns 0 on success. CSR adjacency must be symmetric (undirected).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <queue>
#include <random>
#include <vector>

namespace {

struct Graph {
  int64_t n = 0;
  std::vector<int64_t> indptr, indices, ewt, nwt;
};

// ---------------------------------------------------------------------------
// Coarsening: heavy-edge matching + contraction
// ---------------------------------------------------------------------------

// cmap[v] = coarse node id; returns coarse node count.
int64_t hem_match(const Graph& g, std::mt19937_64& rng,
                  std::vector<int64_t>& cmap) {
  const int64_t n = g.n;
  std::vector<int64_t> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::shuffle(order.begin(), order.end(), rng);
  cmap.assign(n, -1);
  int64_t nc = 0;
  for (int64_t u : order) {
    if (cmap[u] >= 0) continue;
    int64_t best = -1, best_w = -1;
    for (int64_t e = g.indptr[u]; e < g.indptr[u + 1]; ++e) {
      int64_t v = g.indices[e];
      if (v == u || cmap[v] >= 0) continue;
      if (g.ewt[e] > best_w) { best_w = g.ewt[e]; best = v; }
    }
    cmap[u] = nc;
    if (best >= 0) cmap[best] = nc;
    ++nc;
  }
  return nc;
}

Graph contract(const Graph& g, const std::vector<int64_t>& cmap, int64_t nc) {
  Graph c;
  c.n = nc;
  c.nwt.assign(nc, 0);
  for (int64_t v = 0; v < g.n; ++v) c.nwt[cmap[v]] += g.nwt[v];

  // fine nodes grouped by coarse id (counting sort)
  std::vector<int64_t> cstart(nc + 1, 0), members(g.n);
  for (int64_t v = 0; v < g.n; ++v) ++cstart[cmap[v] + 1];
  for (int64_t i = 0; i < nc; ++i) cstart[i + 1] += cstart[i];
  {
    std::vector<int64_t> fill(cstart.begin(), cstart.end() - 1);
    for (int64_t v = 0; v < g.n; ++v) members[fill[cmap[v]]++] = v;
  }

  c.indptr.assign(nc + 1, 0);
  c.indices.reserve(g.indices.size());
  c.ewt.reserve(g.indices.size());
  // timestamped scratch: pos[cv] = index in the adjacency row being built
  std::vector<int64_t> pos(nc, -1), stamp(nc, -1);
  for (int64_t cu = 0; cu < nc; ++cu) {
    for (int64_t m = cstart[cu]; m < cstart[cu + 1]; ++m) {
      int64_t u = members[m];
      for (int64_t e = g.indptr[u]; e < g.indptr[u + 1]; ++e) {
        int64_t cv = cmap[g.indices[e]];
        if (cv == cu) continue;  // contracted self-loop
        if (stamp[cv] != cu) {
          stamp[cv] = cu;
          pos[cv] = static_cast<int64_t>(c.indices.size());
          c.indices.push_back(cv);
          c.ewt.push_back(g.ewt[e]);
        } else {
          c.ewt[pos[cv]] += g.ewt[e];
        }
      }
    }
    c.indptr[cu + 1] = static_cast<int64_t>(c.indices.size());
  }
  return c;
}

// ---------------------------------------------------------------------------
// Coarsest-graph partitioning: weighted recursive bisection
// ---------------------------------------------------------------------------

// Grow a connected region of ~take_w node weight by BFS from a random seed.
std::vector<uint8_t> grow_bisection(const Graph& g,
                                    const std::vector<int64_t>& nodes,
                                    const std::vector<int64_t>& local_of,
                                    int64_t take_w, std::mt19937_64& rng) {
  const int64_t n = static_cast<int64_t>(nodes.size());
  std::vector<uint8_t> side(n, 1);  // 1 = right, 0 = left (grown region)
  std::vector<uint8_t> seen(n, 0);
  std::queue<int64_t> q;

  int64_t w = 0;
  int64_t start = static_cast<int64_t>(rng() % n);
  q.push(start);
  seen[start] = 1;
  while (w < take_w) {
    if (q.empty()) {
      // disconnected remainder: restart from any unseen node
      int64_t nxt = -1;
      for (int64_t i = 0; i < n; ++i) {
        if (!seen[i]) { nxt = i; break; }
      }
      if (nxt < 0) break;
      q.push(nxt);
      seen[nxt] = 1;
    }
    int64_t u = q.front(); q.pop();
    if (side[u] == 0) continue;
    side[u] = 0;
    w += g.nwt[nodes[u]];
    int64_t gu = nodes[u];
    for (int64_t e = g.indptr[gu]; e < g.indptr[gu + 1]; ++e) {
      int64_t lv = local_of[g.indices[e]];
      if (lv >= 0 && !seen[lv]) { seen[lv] = 1; q.push(lv); }
    }
  }
  return side;
}

// Weighted FM refinement: move boundary nodes with positive edge-weight gain
// while the left side's WEIGHT stays within the slack band.
void refine_bisection(const Graph& g, const std::vector<int64_t>& nodes,
                      const std::vector<int64_t>& local_of,
                      std::vector<uint8_t>& side, int64_t take_w,
                      int max_passes = 10) {
  const int64_t n = static_cast<int64_t>(nodes.size());
  int64_t total_w = 0, max_nwt = 1;
  for (int64_t i = 0; i < n; ++i) {
    total_w += g.nwt[nodes[i]];
    max_nwt = std::max(max_nwt, g.nwt[nodes[i]]);
  }
  // slack: at least one (coarse) node, at least 1% of the region weight
  const int64_t slack = std::max(max_nwt, total_w / 100);
  const int64_t lo = std::max<int64_t>(1, take_w - slack);
  const int64_t hi = std::min<int64_t>(total_w - 1, take_w + slack);
  int64_t left_w = 0;
  int64_t left_cnt = 0, right_cnt = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (side[i] == 0) { left_w += g.nwt[nodes[i]]; ++left_cnt; }
    else ++right_cnt;
  }

  for (int pass = 0; pass < max_passes; ++pass) {
    int64_t moved = 0;
    for (int64_t i = 0; i < n; ++i) {
      int64_t gi = nodes[i];
      int64_t same = 0, other = 0;
      for (int64_t e = g.indptr[gi]; e < g.indptr[gi + 1]; ++e) {
        int64_t lv = local_of[g.indices[e]];
        if (lv < 0) continue;
        if (side[lv] == side[i]) same += g.ewt[e]; else other += g.ewt[e];
      }
      int64_t gain = other - same;  // cut weight removed by moving i
      if (gain <= 0) continue;
      int64_t wi = g.nwt[gi];
      if (side[i] == 0) {
        if (left_w - wi < lo || left_cnt <= 1) continue;
        side[i] = 1; left_w -= wi; --left_cnt; ++right_cnt;
      } else {
        if (left_w + wi > hi || right_cnt <= 1) continue;
        side[i] = 0; left_w += wi; ++left_cnt; --right_cnt;
      }
      ++moved;
    }
    if (moved == 0) break;
  }
}

void recurse(const Graph& g, std::vector<int64_t>& nodes,
             std::vector<int64_t>& local_of, int32_t parts, int32_t base,
             std::mt19937_64& rng, int32_t* labels) {
  const int64_t n = static_cast<int64_t>(nodes.size());
  if (parts <= 1) {
    for (int64_t i = 0; i < n; ++i) labels[nodes[i]] = base;
    return;
  }
  if (n <= parts) {  // degenerate: one node per part, surplus parts empty
    for (int64_t i = 0; i < n; ++i)
      labels[nodes[i]] = base + static_cast<int32_t>(i);
    return;
  }
  const int32_t lparts = parts / 2;
  int64_t total_w = 0;
  for (int64_t i = 0; i < n; ++i) total_w += g.nwt[nodes[i]];
  const int64_t take_w = (total_w * lparts + parts / 2) / parts;

  for (int64_t i = 0; i < n; ++i) local_of[nodes[i]] = i;
  auto side = grow_bisection(g, nodes, local_of, take_w, rng);
  refine_bisection(g, nodes, local_of, side, take_w);
  for (int64_t i = 0; i < n; ++i) local_of[nodes[i]] = -1;

  std::vector<int64_t> lnodes, rnodes;
  for (int64_t i = 0; i < n; ++i)
    (side[i] == 0 ? lnodes : rnodes).push_back(nodes[i]);
  // a side may be empty only in pathological cases — fall back to a split
  if (lnodes.empty() || rnodes.empty()) {
    lnodes.clear(); rnodes.clear();
    for (int64_t i = 0; i < n; ++i)
      (i < n / 2 ? lnodes : rnodes).push_back(nodes[i]);
  }
  nodes.clear(); nodes.shrink_to_fit();
  recurse(g, lnodes, local_of, lparts, base, rng, labels);
  recurse(g, rnodes, local_of, parts - lparts, base + lparts, rng, labels);
}

// ---------------------------------------------------------------------------
// Uncoarsening: k-way boundary refinement (greedy positive-gain moves under
// a balance cap), run at every level after label projection.
// ---------------------------------------------------------------------------

void kway_refine(const Graph& g, std::vector<int32_t>& labels, int32_t nparts,
                 int max_passes = 8) {
  const int64_t n = g.n;
  std::vector<int64_t> part_w(nparts, 0), part_cnt(nparts, 0);
  int64_t total_w = 0, max_nwt = 1;
  for (int64_t v = 0; v < n; ++v) {
    part_w[labels[v]] += g.nwt[v];
    ++part_cnt[labels[v]];
    total_w += g.nwt[v];
    max_nwt = std::max(max_nwt, g.nwt[v]);
  }
  // 1% imbalance cap, never tighter than one node of max weight: coarse
  // levels (heavy nodes) get a naturally loose cap that tightens as
  // uncoarsening refines — the classic multilevel balance schedule. The
  // fine-level result matches the balance the quality tests pin.
  const int64_t ideal = (total_w + nparts - 1) / nparts;
  const int64_t cap = ideal + std::max(max_nwt, ideal / 100);

  std::vector<int64_t> conn(nparts);
  std::vector<int32_t> touched;
  touched.reserve(16);
  for (int pass = 0; pass < max_passes; ++pass) {
    int64_t moved = 0;
    for (int64_t v = 0; v < n; ++v) {
      const int32_t pv = labels[v];
      touched.clear();
      for (int64_t e = g.indptr[v]; e < g.indptr[v + 1]; ++e) {
        int32_t pu = labels[g.indices[e]];
        if (conn[pu] == 0) touched.push_back(pu);
        conn[pu] += g.ewt[e];
      }
      int32_t best = pv;
      int64_t best_gain = 0;
      for (int32_t pu : touched) {
        if (pu == pv) continue;
        int64_t gain = conn[pu] - conn[pv];
        if (gain > best_gain && part_w[pu] + g.nwt[v] <= cap) {
          best_gain = gain;
          best = pu;
        }
      }
      for (int32_t pu : touched) conn[pu] = 0;
      if (best != pv && part_cnt[pv] > 1) {
        part_w[pv] -= g.nwt[v]; --part_cnt[pv];
        part_w[best] += g.nwt[v]; ++part_cnt[best];
        labels[v] = best;
        ++moved;
      }
    }
    if (moved == 0) break;
  }

  // Enforce the cap: gain-driven passes never push weight OUT of a part the
  // projection left overweight, so drain overweight parts into their most-
  // connected under-ideal neighbour part (cut-aware), falling back to the
  // globally lightest part.
  for (int guard = 0; guard < 20; ++guard) {
    bool over = false;
    for (int32_t p = 0; p < nparts; ++p) over |= (part_w[p] > cap);
    if (!over) break;
    int64_t moved = 0;
    for (int64_t v = 0; v < n; ++v) {
      const int32_t pv = labels[v];
      if (part_w[pv] <= cap || part_cnt[pv] <= 1) continue;
      touched.clear();
      for (int64_t e = g.indptr[v]; e < g.indptr[v + 1]; ++e) {
        int32_t pu = labels[g.indices[e]];
        if (conn[pu] == 0) touched.push_back(pu);
        conn[pu] += g.ewt[e];
      }
      int32_t best = -1;
      int64_t best_conn = -1;
      for (int32_t pu : touched) {
        if (pu == pv || part_w[pu] + g.nwt[v] > ideal) continue;
        if (conn[pu] > best_conn) { best_conn = conn[pu]; best = pu; }
      }
      for (int32_t pu : touched) conn[pu] = 0;
      if (best < 0) {  // no connected under-ideal part: lightest overall
        int64_t wmin = INT64_MAX;
        for (int32_t p = 0; p < nparts; ++p) {
          if (p != pv && part_w[p] < wmin) { wmin = part_w[p]; best = p; }
        }
        if (best < 0 || part_w[best] + g.nwt[v] > cap) continue;
      }
      part_w[pv] -= g.nwt[v]; --part_cnt[pv];
      part_w[best] += g.nwt[v]; ++part_cnt[best];
      labels[v] = best;
      ++moved;
    }
    if (moved == 0) break;
  }
}

}  // namespace

extern "C" {

int partition_graph(int64_t n, const int64_t* indptr, const int64_t* indices,
                    int32_t nparts, uint64_t seed, int32_t* labels_out) {
  if (n <= 0 || nparts <= 0) return 1;
  std::mt19937_64 rng(seed);

  // level-0 graph (unit weights)
  std::vector<Graph> levels(1);
  Graph& g0 = levels[0];
  g0.n = n;
  g0.indptr.assign(indptr, indptr + n + 1);
  g0.indices.assign(indices, indices + indptr[n]);
  g0.ewt.assign(indptr[n], 1);
  g0.nwt.assign(n, 1);

  // 1. coarsen until small or stalled
  const int64_t coarse_target = std::max<int64_t>(30 * nparts, 256);
  std::vector<std::vector<int64_t>> cmaps;
  while (levels.back().n > coarse_target &&
         static_cast<int64_t>(levels.size()) < 40) {
    std::vector<int64_t> cmap;
    int64_t nc = hem_match(levels.back(), rng, cmap);
    if (nc >= levels.back().n * 95 / 100) break;  // matching stalled
    Graph c = contract(levels.back(), cmap, nc);
    cmaps.push_back(std::move(cmap));
    levels.push_back(std::move(c));
  }

  // 2. partition the coarsest level (weighted recursive bisection). The
  // coarse graph is tiny, so take the best of several seeded restarts —
  // region-growing quality varies with the BFS seed, and a bad coarse cut
  // survives uncoarsening.
  const Graph& gc = levels.back();
  std::vector<int32_t> labels(gc.n, 0);
  {
    int64_t best_cut = INT64_MAX;
    std::vector<int32_t> trial(gc.n, 0);
    for (int restart = 0; restart < 6; ++restart) {
      std::vector<int64_t> nodes(gc.n);
      std::iota(nodes.begin(), nodes.end(), 0);
      std::vector<int64_t> local_of(gc.n, -1);
      recurse(gc, nodes, local_of, nparts, 0, rng, trial.data());
      kway_refine(gc, trial, nparts);
      int64_t cut = 0;
      for (int64_t u = 0; u < gc.n; ++u)
        for (int64_t e = gc.indptr[u]; e < gc.indptr[u + 1]; ++e)
          cut += (trial[u] != trial[gc.indices[e]]) * gc.ewt[e];
      if (cut < best_cut) { best_cut = cut; labels = trial; }
    }
  }

  // 3. uncoarsen: project + refine at every finer level
  for (int64_t lvl = static_cast<int64_t>(cmaps.size()) - 1; lvl >= 0; --lvl) {
    const Graph& gf = levels[lvl];
    std::vector<int32_t> fine(gf.n);
    for (int64_t v = 0; v < gf.n; ++v) fine[v] = labels[cmaps[lvl][v]];
    labels = std::move(fine);
    kway_refine(gf, labels, nparts);
  }

  std::memcpy(labels_out, labels.data(), sizeof(int32_t) * n);
  return 0;
}

// Edge cut of a labeling (for tests/diagnostics): counts directed CSR entries
// crossing parts (each undirected edge counted twice).
int64_t edge_cut(int64_t n, const int64_t* indptr, const int64_t* indices,
                 const int32_t* labels) {
  int64_t cut = 0;
  for (int64_t u = 0; u < n; ++u) {
    for (int64_t e = indptr[u]; e < indptr[u + 1]; ++e) {
      cut += (labels[u] != labels[indices[e]]);
    }
  }
  return cut;
}

}  // extern "C"
