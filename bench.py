"""Benchmark: LargeFluid-scale training-step throughput, nodes/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload (BASELINE.md protocol): Fluid113K shape — 113,140 nodes, ~1.7M
radius-0.075 edges, batch 1, FastEGNN hidden 64 / 4 layers / C=3 with MMD
(sigma 3, w 0.01, n 50) and grad clip 0.3 — the largefluid_distegnn.yaml
configuration on one chip.

Layouts (docs/PERFORMANCE.md):
  plain        — row-sorted padded edge list, XLA scatter/gather aggregation
  plain-cumsum — same layout, --seg cumsum: scatter-free prefix-sum
                 aggregations with gather-only VJPs (ops/segment.py)
  plain-ell    — same layout, --seg ell: scatter-free fixed-degree chained
                 gathers, exact arithmetic (ops/segment.py ELL block)
  blocked      — blocked-CSR layout, one-hot contraction ops (ops/blocked.py;
                 --impl einsum|pallas selects the lowering); hardware-measured
                 slower than plain, kept for explicit runs only
  fused        — blocked layout consumed by the fused edge-pipeline Pallas
                 kernel (model.edge_impl='fused', ops/edge_pipeline.py): one
                 streamed pass per layer over the in-window edges + a compact
                 remote tail through plain ops (docs/PERFORMANCE.md)
  fused_stack  — the cross-layer megakernel (model.edge_impl='fused_stack',
                 ops/layer_pipeline.py): ALL n_layers run inside one Pallas
                 grid with the graph resident in VMEM. The flagship 113k
                 shape exceeds the 16 MiB VMEM budget by design, so this leg
                 runs at a bounded node count (BENCH_STACK_NODES, default
                 1536 — the largest padded shape that passes
                 check_stack_vmem at Fluid113K edge density) and reports no
                 vs_baseline; it is an HBM-traffic A/B against the fused leg
                 at the SAME capped shape, not a flagship headline.
Default is auto: race the production candidates in RACE_ORDER — the fused
edge pipeline first, then cumsum/remat/agg-dtype stacks and the
unfused/unreordered anchor control — each in a child process (so a compiler
surprise on new hardware cannot take down the bench), and report the fastest
real measurement. ELL and both blocked generations are hardware-refuted
(BASELINE.md 2026-08-02) and retired.

Timing methodology (v2, round 2 — see BASELINE.md "Measurement integrity"):
round 1 timed a donated jit with jax.block_until_ready, which RETURNS EARLY
on the axon TPU tunnel for donated executables and under-reported step time
~5x (677k nodes/s claimed vs ~135k real). v2 uses a non-donated jit and
syncs by fetching the loss scalar to host, which provably drains the device
queue. vs_baseline divides by the honest re-measurement of the round-1 tree
with this same v2 harness (commit 6430dd5 @ 837.1 ms/step).
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import time

import numpy as np

# Honest round-1 anchor: commit 6430dd5 measured with the v2 harness on the
# single TPU v5 lite chip (2026-07-29, 837.1 ms/step at N=113140/E=1639080).
BASELINE_NODES_PER_SEC = 135_157.0

def _emit_bench(rec, flush: bool = False) -> None:
    """Print the BENCH contract line AND mirror it as a structured
    ``bench/result`` obs event (logs/bench/obs/events.jsonl), binding a
    sink on first use when no run has configured one. The stdout contract
    must survive a broken obs import, so the mirror is best-effort."""
    print(json.dumps(rec), flush=flush)
    try:
        from distegnn_tpu import obs

        if not obs.get_tracer().enabled:
            obs.configure(log_dir=os.path.join("logs", "bench", "obs"),
                          tags={"run": "bench"})
        obs.event("bench/result", **rec)
        obs.flush()
    except Exception as e:
        print(f"bench: obs mirror failed ({e!r})", file=sys.stderr)


def _env_int(name: str, default: int) -> int:
    """Defensive env override parse: a malformed BENCH_* var must degrade to
    the default, never crash at import — the honest-failure JSON contract
    (ADVICE r3) only holds if main() is reached."""
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        print(f"bench: malformed {name}={os.environ.get(name)!r}; "
              f"using default {default}", file=sys.stderr)
        return default


N_NODES = _env_int("BENCH_NODES", 113_140)  # override for smoke tests
RADIUS = 0.075
TARGET_EDGES_PER_NODE = 15.0
HIDDEN, LAYERS, CHANNELS = 64, 4, 3
WARMUP, STEPS = 3, 10
# Child kill is a last resort: SIGKILLing a live TPU client strands the
# remote claim and wedges the axon tunnel (observed twice, BASELINE.md) — but
# without a bound a wedged tunnel hangs the bench forever. 1200 s clears the
# slowest observed degraded-session child (~6 min) by 3x.
CHILD_TIMEOUT_S = _env_int("BENCH_CHILD_TIMEOUT_S", 1200)
# Total wall budget for the auto race. Round 2's lesson (VERDICT r2, weak #2):
# the driver's own end-of-round timeout killed a bench that was hanging on a
# wedged tunnel, recording NOTHING, even though an honest-failure JSON path
# existed. The budget guarantees bench.py prints its line well inside any
# plausible driver budget, even if that means skipping the tail of the race.
TOTAL_BUDGET_S = _env_int("BENCH_BUDGET_S", 2400)
# Probe child: never acquires the device on a dead tunnel, so it is safe to
# timeout-kill (scripts/tpu_probe.sh contract). 75 s covers the observed
# worst-case healthy first-acquire (~40 s incl. backend init). One auto-retry
# after spacing: the tunnel releases claims slowly, so a probe fired right
# after another client exits can fail once on a HEALTHY tunnel (BENCH_r02-r05
# all died with zero measurements on a single unretried probe-class failure).
PROBE_TIMEOUT_S = 75
PROBE_RETRY_SPACING_S = _env_int("BENCH_PROBE_RETRY_SPACING_S", 45)
# Per-leg clamp inside the race: CHILD_TIMEOUT_S is the absolute last-resort
# bound, but at 1200 s a single wedged leg eats half the TOTAL_BUDGET_S
# before the next leg starts. The leg budget clamps each child to a window
# that still clears the slowest observed degraded-session child (~360 s) with
# margin, so a wedged first leg leaves the rest of the race its wall clock.
LEG_BUDGET_S = _env_int("BENCH_LEG_BUDGET_S", 600)
RACE_ARTIFACT = os.path.join("docs", "artifacts", "bench_race_last.json")
# CPU dev-box races persist HERE, never to RACE_ARTIFACT: a local run must
# not clobber committed hardware evidence (ADVICE r3, medium).
RACE_ARTIFACT_CPU = os.path.join("docs", "artifacts", "bench_race_cpu_last.json")
# Paused-competitor ledger: written BEFORE the SIGSTOPs so a SIGKILLed bench
# (driver hard-timeout / OOM) leaves an out-of-band record; tpu_watch.sh
# CONTs any leftover stopped PIDs from it on startup (ADVICE r3, medium).
PAUSED_PIDS_FILE = "/tmp/bench_paused.pids"

# Auto-race order, one (child argv, extra env) tuple per leg. Rewritten after
# the round-4 session-B contended race (BASELINE.md,
# bench_race_20260802b_contended.json): in-session, cumsum+aggbf16 beat plain
# 1.81x and remat alone beat it 1.65x. The UNMEASURED-on-hardware fused edge
# pipeline goes FIRST — its whole design is to beat the best measured leg on
# HBM traffic (one streamed pass per layer, docs/PERFORMANCE.md), so it is
# the highest-information leg if the session dies early. Then the best
# measured stack guess (cumsum+aggbf16+remat), the measured session-B winner,
# the two single-knob legs that tie this session to session B's ratios, and
# the legacy anchor control (unfused, unreordered scatter — ties the session
# to the committed round-1 anchor). ELL (0.633x) and both blocked generations
# (0.784x, 0.446x) are hardware-refuted and retired.
# tests/test_bench_unlosable.py traces EVERY leg here on CPU.
RACE_ORDER = (
    # Cross-layer megakernel first: unmeasured on hardware and the highest-
    # information leg (it is the direct HBM-traffic answer to the fused leg).
    # Self-caps to BENCH_STACK_NODES (VMEM-resident stack), so its number is
    # an A/B vs the fused leg at the same capped shape, never the headline.
    (["--layout", "fused_stack"], None),
    (["--layout", "fused"], None),
    (["--layout", "plain", "--seg", "cumsum"],
     {"BENCH_AGG_DTYPE": "bf16", "BENCH_REMAT": "1"}),
    (["--layout", "plain", "--seg", "cumsum"], {"BENCH_AGG_DTYPE": "bf16"}),
    (["--layout", "plain"], {"BENCH_REMAT": "1"}),
    (["--layout", "plain"], None),
    (["--layout", "plain", "--fuse", "0"], {"BENCH_REORDER": "0"}),
    # 3D-mesh leg: the shard_mapped distributed step with tensor=2 hidden-dim
    # sharding (docs/PERFORMANCE.md "3D mesh"). Needs 2 devices — on a
    # single-chip tunnel it fail-records in seconds and the race moves on;
    # on CPU (test_bench_unlosable.py) bench provisions virtual devices.
    (["--mesh", "1x1x2"], None),
    # Tiled-serving leg (serve/tiled.py): inference nodes/sec through the
    # giant-scene tile executor — tile count, halo fraction and the
    # H2D-overlap stall fraction on this session's hardware. Its metric is
    # tiled_serve_nodes_per_sec (an INFERENCE number), which never contends
    # for the race's training headline. BENCH_TILED_DEVICES=8 adds the
    # device sweep (D=1 anchor + D=min(8, devices, tiles) mesh rounds +
    # scaling_efficiency); on CPU bench provisions virtual devices, so the
    # sweep is plumbing evidence there, not a speedup claim.
    (["--layout", "tiled"], {"BENCH_TILED_DEVICES": "8"}),
    # Input-pipeline leg LAST (host-side graphs/s + stall fractions for the
    # streamed-shard prefetch A/B, data/stream.py): its metric is
    # io_pipeline_graphs_per_sec, which never contends for the race's
    # nodes/sec headline — it rides the race for a dated stall_fraction
    # record on the same session.
    (["--layout", "io"], None),
)

# TPU v5e peak: 197 TFLOP/s bf16, ~98.5 TFLOP/s fp32 (public spec sheet).
PEAK_F32_FLOPS = 98.5e12
# TPU v5e HBM2 bandwidth, public spec sheet. The step is memory-bound
# (docs/PERFORMANCE.md roofline), so achieved GB/s — not MFU — is the
# compass that says how much headroom a lowering has left (VERDICT r4 #7).
PEAK_HBM_GBPS = 819.0


def make_fluid_cloud(rng):
    """Synthetic fluid-like particle cloud at Fluid113K density, as a raw
    graph dict (pre-padding) — shared by the single-chip measure() path and
    the 3D-mesh leg (which partitions it before padding)."""
    from distegnn_tpu.ops.radius import radius_graph_np

    vol = N_NODES * (4.0 / 3.0) * np.pi * RADIUS**3 / TARGET_EDGES_PER_NODE
    side = max(vol ** (1.0 / 3.0), 2.0 * RADIUS)
    loc = rng.uniform(0, side, size=(N_NODES, 3)).astype(np.float32)
    vel = rng.normal(size=(N_NODES, 3)).astype(np.float32) * 0.01
    if _env_int("BENCH_REORDER", 1):
        # Z-curve node relabeling (ops/order.py): same cloud, same graph,
        # locality-friendly indices — the production loaders offer the same
        # via data.node_order. BENCH_REORDER=0 restores the random labeling
        # for anchor-comparable A/B runs.
        from distegnn_tpu.ops.order import morton_perm

        p = morton_perm(loc)
        loc, vel = loc[p], vel[p]
    edge_index = radius_graph_np(loc, RADIUS)
    n_edges = edge_index.shape[1]
    dist = np.linalg.norm(loc[edge_index[0]] - loc[edge_index[1]], axis=1)
    graph = {
        "node_feat": np.concatenate(
            [np.linalg.norm(vel, axis=1, keepdims=True), vel[:, :2]], axis=1
        ).astype(np.float32),                       # 3 features (largefluid config)
        "node_attr": np.ones((N_NODES, 2), np.float32),  # viscosity, mass
        "loc": loc,
        "vel": vel,
        "target": loc + vel * 0.05,
        "loc_mean": loc.mean(axis=0),
        "edge_index": edge_index,
        "edge_attr": np.repeat(dist[:, None], 2, axis=1).astype(np.float32),
    }
    return graph, n_edges


def make_fluid_batch(rng, edge_block: int = 0, pairing: bool = False,
                     edge_tile: int = 512, split_remote: bool = False):
    """Padded single-chip batch of one fluid cloud (see make_fluid_cloud)."""
    from distegnn_tpu.ops.graph import pad_graphs

    graph, n_edges = make_fluid_cloud(rng)
    kw = ({"edge_block": edge_block, "edge_tile": edge_tile,
           "split_remote": split_remote}
          if edge_block else {"compute_pair": pairing})
    return pad_graphs([graph], **kw), n_edges


def cpu_competitors():
    """PIDs safe to SIGSTOP during the measurement: python processes
    running this repo's heavy CPU work (training/generation/pytest) that
    are PROVABLY CPU-pinned — JAX_PLATFORMS/BENCH_PLATFORM=cpu in their
    startup env or --platform cpu on the command line. Host contention
    degrades step timing ~4x (BASELINE.md), and the driver invokes
    bench.py directly (not through hw_session.sh, which has its own
    pause). Never touch a possibly-live TPU client (SIGSTOP wedges the
    tunnel) and never touch our own ancestors (a pytest running this
    bench as a child must not be frozen by it — deadlock)."""
    repo = os.path.dirname(os.path.abspath(__file__))
    ancestors, p = set(), os.getpid()
    while p > 1:
        ancestors.add(p)
        try:
            with open(f"/proc/{p}/stat") as f:
                p = int(f.read().split(") ")[-1].split()[1])  # ppid
        except OSError:
            break
    pids, ambiguous = [], []
    for pid_s in os.listdir("/proc"):
        if not pid_s.isdigit() or int(pid_s) in ancestors:
            continue
        try:
            with open(f"/proc/{pid_s}/cmdline", "rb") as f:
                argv = f.read().split(b"\0")
            if not argv or b"python" not in os.path.basename(argv[0]):
                continue
            cmd = b" ".join(argv)
            if not any(t in cmd for t in (b"main.py --config_path",
                                          b"generate_nbody", b"pytest")):
                continue
            with open(f"/proc/{pid_s}/environ", "rb") as f:
                env_b = f.read()
            cpu_pinned = (b"JAX_PLATFORMS=cpu" in env_b
                          or b"BENCH_PLATFORM=cpu" in env_b
                          or b"--platform cpu" in cmd)
            # This repo's pytest pins JAX_PLATFORMS=cpu at runtime via
            # tests/conftest.py setdefault(), which is invisible in
            # /proc/pid/environ (startup env only) — classify it CPU the way
            # hw_session.sh does (ADVICE r3, low). THREE guards, because a
            # wrong CPU call here SIGSTOPs a live TPU client (the
            # tunnel-wedging hazard): argv must actually invoke pytest (not
            # merely mention it), cwd must be this repo, and the startup env
            # must carry NO JAX_PLATFORMS at all — setdefault yields to an
            # inherited value, so `JAX_PLATFORMS=tpu pytest` is a genuine
            # TPU client and stays in the untouchable ambiguous bucket.
            if not cpu_pinned:
                invokes_pytest = any(
                    os.path.basename(a) in (b"pytest", b"py.test")
                    for a in argv[1:]
                ) or (b"-m" in argv and b"pytest" in argv)
                if invokes_pytest and b"JAX_PLATFORMS=" not in env_b:
                    try:
                        cwd = os.path.realpath(f"/proc/{pid_s}/cwd")
                        cpu_pinned = cwd == repo or cwd.startswith(repo + os.sep)
                    except OSError:
                        pass
            with open(f"/proc/{pid_s}/stat") as f:
                state = f.read().split(") ")[-1].split()[0]
            if not cpu_pinned:
                # possibly a live TPU client: untouchable, and measuring
                # beside it is degraded — surfaced in the race artifact
                print(f"bench: pid {pid_s} not provably CPU-pinned; may be "
                      f"a live TPU client", file=sys.stderr)
                ambiguous.append(int(pid_s))
            elif state != "T":
                # already-stopped processes (e.g. paused for the whole
                # queue by hw_session.sh) are NOT ours to resume: pausing
                # only what we found running keeps the finally-resume from
                # waking them mid-queue
                pids.append(int(pid_s))
        except OSError:
            continue
    return pids, ambiguous


def layout_tag(edge_block: int, impl: str, seg: str = "scatter",
               edge_impl: str = "plain") -> str:
    """The machine-read layout label shared by bench.py and profile_step.py
    outputs (pasted into BASELINE.md tables)."""
    if edge_impl == "fused_stack":
        return f"fused_stack{edge_block}"
    if edge_impl == "fused":
        return f"fused{edge_block}"
    if edge_block:
        return f"blocked{edge_block}-{impl}"
    return "plain" if seg == "scatter" else f"plain-{seg}"


def measure(edge_block: int, impl: str = "einsum", seg: str = "scatter",
            fuse: bool = True, edge_impl: str = "plain"):
    import jax

    from distegnn_tpu.models.fast_egnn import FastEGNN
    from distegnn_tpu.train import TrainState, make_optimizer, make_train_step

    rng = np.random.default_rng(0)
    edge_tile = _env_int("BENCH_EDGE_TILE", 512)
    batch, n_edges = make_fluid_batch(rng, edge_block,
                                      pairing=(seg in ("cumsum", "ell")),
                                      edge_tile=edge_tile,
                                      split_remote=(edge_impl in
                                                    ("fused", "fused_stack")))

    model = FastEGNN(node_feat_nf=3, node_attr_nf=2, edge_attr_nf=2,
                     hidden_nf=HIDDEN, virtual_channels=CHANNELS, n_layers=LAYERS,
                     compute_dtype="bf16", blocked_impl=impl, segment_impl=seg,
                     fuse_agg=fuse, edge_impl=edge_impl,
                     agg_dtype=os.environ.get("BENCH_AGG_DTYPE") or None,
                     # racing knob: without remat the backward re-reads ~10
                     # GiB of saved [E,.] activations — at the measured
                     # effective HBM bandwidth that can exceed the recompute
                     # cost remat pays instead (profile 2026-08-02: bwd =
                     # 2.8x fwd). Default off = the historical bench config.
                     remat=bool(_env_int("BENCH_REMAT", 0)))
    params = model.init(jax.random.PRNGKey(0), batch)
    tx = make_optimizer(5e-4, weight_decay=1e-12, clip_norm=0.3)
    state = TrainState.create(params, tx)
    # NO donate_argnums: donation makes block_until_ready return early AND
    # slows real execution ~3x on the axon tunnel (measured; BASELINE.md).
    step = jax.jit(make_train_step(model, tx, mmd_weight=0.01, mmd_sigma=3.0,
                                   mmd_samples=50))

    for i in range(WARMUP):
        state, metrics = step(state, batch, jax.random.PRNGKey(i))
    float(metrics["loss"])  # hard sync: drain the device queue

    t0 = time.perf_counter()
    for i in range(STEPS):
        state, metrics = step(state, batch, jax.random.PRNGKey(100 + i))
    float(metrics["loss"])  # hard sync
    dt = time.perf_counter() - t0

    # analytic FLOPs + bytes from XLA cost analysis: MFU for the compute
    # ceiling, achieved HBM GB/s for the (binding) memory ceiling
    try:
        an = step.lower(state, batch, jax.random.PRNGKey(0)).compile().cost_analysis()
        if isinstance(an, list):
            an = an[0]
        flops = float(an.get("flops", float("nan")))
        bytes_moved = float(an.get("bytes accessed", float("nan")))
    except Exception:
        flops = bytes_moved = float("nan")
    mfu = flops / (dt / STEPS) / PEAK_F32_FLOPS
    hbm_gbps = bytes_moved / (dt / STEPS) / 1e9

    nodes_per_sec = N_NODES * STEPS / dt
    platform = jax.devices()[0].platform
    layout = layout_tag(edge_block, impl, seg, edge_impl)
    # self-describing record: the locality / fusion / stream-dtype knobs are
    # part of the measured configuration (VERDICT r3 #1 prepared attack)
    if edge_block and edge_tile != 512:
        layout += f"+t{edge_tile}"
    if not fuse:
        layout += "+nofuse"
    if not _env_int("BENCH_REORDER", 1):
        layout += "+noreorder"
    if os.environ.get("BENCH_AGG_DTYPE"):
        layout += f"+agg{os.environ['BENCH_AGG_DTYPE']}"
    if _env_int("BENCH_REMAT", 0):
        layout += "+remat"
    official = N_NODES == 113_140  # vs_baseline is meaningless off-workload
    return {
        "metric": "largefluid_train_nodes_per_sec_per_chip",
        "value": round(nodes_per_sec, 1),
        "unit": (f"nodes/sec/chip (N={N_NODES}, E={n_edges}, step={dt / STEPS * 1e3:.1f}ms, "
                 f"platform={platform}, layout={layout}, mfu_f32={mfu:.3f}, "
                 f"hbm_gbps={hbm_gbps:.0f} ({hbm_gbps / PEAK_HBM_GBPS:.0%} of peak), "
                 f"sync=fetch)"),
        "vs_baseline": round(nodes_per_sec / BASELINE_NODES_PER_SEC, 3) if official else None,
    }


def measure_mesh(mesh_str: str, seg: str = "scatter", fuse: bool = True):
    """3D-mesh distributed step timing (``--mesh DxGxT``): the shard_mapped
    train step from parallel/launch over a (data, graph, tensor) mesh. Data
    shards hold DIFFERENT clouds; graph>1 splits each cloud with the random
    partitioner (metis at bench node counts would dominate setup time);
    tensor>1 slices the EGCL hidden dims per chip (parallel/collectives.py TP
    ops — docs/PERFORMANCE.md "3D mesh" has the memory/comm model). Plain
    edge layout + scatter aggregation only: the fused kernel's TP dispatch is
    parity-proven in the dryrun (__graft_entry__._tensor_parity); this leg
    answers step-time-vs-mesh-shape. vs_baseline stays None — per-chip
    throughput across mesh shapes is the comparison, not the 1-chip anchor."""
    import jax

    from distegnn_tpu.data.partition import split_graph
    from distegnn_tpu.models.fast_egnn import FastEGNN
    from distegnn_tpu.ops.graph import pad_graphs
    from distegnn_tpu.parallel.launch import (
        batch_layout,
        global_batch_putter,
        make_distributed_steps,
    )
    from distegnn_tpu.parallel.mesh import GRAPH_AXIS, TENSOR_AXIS, make_mesh
    from distegnn_tpu.train import TrainState, make_optimizer

    if seg != "scatter":
        sys.exit(f"--mesh supports --seg scatter only (got {seg})")
    D, G, T = (int(v) for v in mesh_str.lower().split("x"))
    need = D * G * T
    if len(jax.devices()) < need:
        sys.exit(f"--mesh {mesh_str}: needs {need} devices, "
                 f"have {len(jax.devices())}")
    if HIDDEN % T:
        sys.exit(f"--mesh {mesh_str}: hidden {HIDDEN} not divisible by "
                 f"tensor={T}")
    mesh = make_mesh(n_graph=G, n_data=D, n_tensor=T,
                     devices=jax.devices()[:need])

    clouds, n_edges_total = [], 0
    for s in range(D):
        cloud, n_edges = make_fluid_cloud(np.random.default_rng(s))
        n_edges_total += n_edges
        clouds.append(split_graph(cloud, G, "random", inner_radius=RADIUS,
                                  outer_radius=1.5 * RADIUS, seed=s)
                      if G > 1 else [cloud])
    mn = max(p["loc"].shape[0] for parts in clouds for p in parts) + 8
    me = max(p["edge_index"].shape[1] for parts in clouds for p in parts) + 64

    def stack(xs):
        return jax.tree.map(lambda *a: np.stack(a, axis=0), *xs)

    shard_stacks = [stack([pad_graphs([p], max_nodes=mn, max_edges=me)
                           for p in parts]) for parts in clouds]
    host_batch = stack(shard_stacks) if D > 1 else shard_stacks[0]

    model = FastEGNN(
        node_feat_nf=3, node_attr_nf=2, edge_attr_nf=2, hidden_nf=HIDDEN,
        virtual_channels=CHANNELS, n_layers=LAYERS, compute_dtype="bf16",
        fuse_agg=fuse, axis_name=GRAPH_AXIS,
        tensor_axis=(TENSOR_AXIS if T > 1 else None),
        agg_dtype=os.environ.get("BENCH_AGG_DTYPE") or None,
        remat=bool(_env_int("BENCH_REMAT", 0)))
    _, strip = batch_layout(D)
    init_model = (model.copy(axis_name=None, tensor_axis=None) if T > 1
                  else model.copy(axis_name=None))
    params = init_model.init(jax.random.PRNGKey(0),
                             jax.tree.map(strip, host_batch))
    tx = make_optimizer(5e-4, weight_decay=1e-12, clip_norm=0.3)
    state = TrainState.create(params, tx)
    step, _ = make_distributed_steps(model, tx, mesh, mmd_weight=0.01,
                                     mmd_sigma=3.0, mmd_samples=50)
    gb = global_batch_putter(mesh)(host_batch)

    for i in range(WARMUP):
        state, metrics = step(state, gb, jax.random.PRNGKey(i))
    float(metrics["loss"])  # hard sync: drain the device queue

    t0 = time.perf_counter()
    for i in range(STEPS):
        state, metrics = step(state, gb, jax.random.PRNGKey(100 + i))
    float(metrics["loss"])  # hard sync
    dt = time.perf_counter() - t0

    nodes_per_sec = D * N_NODES * STEPS / dt
    platform = jax.devices()[0].platform
    layout = f"mesh{D}x{G}x{T}"
    if _env_int("BENCH_REMAT", 0):
        layout += "+remat"
    if os.environ.get("BENCH_AGG_DTYPE"):
        layout += f"+agg{os.environ['BENCH_AGG_DTYPE']}"
    return {
        "metric": "largefluid_train_nodes_per_sec_per_chip",
        "value": round(nodes_per_sec / need, 1),
        "unit": (f"nodes/sec/chip (N={N_NODES} x D={D}, E={n_edges_total}, "
                 f"step={dt / STEPS * 1e3:.1f}ms, platform={platform}, "
                 f"layout={layout}, devices={need}, sync=fetch)"),
        "vs_baseline": None,
    }


def measure_io():
    """Input-pipeline leg: graphs/s through load -> collate -> device_put
    over the out-of-core shard pipeline (data/stream.py), prefetch ON vs the
    blocking put, with per-mode ``data/stall_s`` deltas. The number is a
    HOST-side throughput (not a training headline): each consumed batch
    sleeps BENCH_IO_COMPUTE_MS to stand in for a device step, so the A/B
    isolates exactly what PrefetchLoader hides — disk read + collate + put
    overlapping compute. Self-caps N to BENCH_IO_NODES (the pipeline cost is
    per-graph collate, not model FLOPs; the flagship 113k cloud would just
    make shard writes slow without changing the ratio)."""
    import tempfile

    import jax

    from distegnn_tpu import obs
    from distegnn_tpu.data import (
        GraphLoader, PrefetchLoader, StreamedGraphDataset, write_shards,
    )

    global N_NODES
    cap = _env_int("BENCH_IO_NODES", 2048)
    if N_NODES > cap:
        print(f"bench: io leg capped at N={cap} (host-pipeline leg; model "
              f"FLOPs are simulated)", file=sys.stderr)
        N_NODES = cap
    n_graphs = _env_int("BENCH_IO_GRAPHS", 24)
    depth = _env_int("BENCH_IO_DEPTH", 2)
    compute_s = _env_int("BENCH_IO_COMPUTE_MS", 25) / 1e3

    graphs, n_edges = [], 0
    for s in range(n_graphs):
        g, e = make_fluid_cloud(np.random.default_rng(s))
        graphs.append(g)
        n_edges = max(n_edges, e)
    reg = obs.get_registry()

    def run_epoch(pf):
        stall = reg.counter("data/stall_s")
        pf.set_epoch(0)
        for batch in pf:  # warm epoch: shard cache, page cache, device path
            jax.block_until_ready(batch)
        pf.set_epoch(1)
        s0, n = stall.value, 0
        t0 = time.perf_counter()
        for batch in pf:
            jax.block_until_ready(batch)
            time.sleep(compute_s)  # simulated device step
            n += 1
        wall = time.perf_counter() - t0
        return {"graphs_per_s": n / wall, "stall_s": stall.value - s0,
                "wall_s": wall, "batches": n}

    with tempfile.TemporaryDirectory() as td:
        write_shards(graphs, td, shard_size=max(1, n_graphs // 6))
        ds = StreamedGraphDataset(td, cache_shards=2)
        loader = GraphLoader(ds, 1, shuffle=True, seed=0)
        blocking = run_epoch(PrefetchLoader(loader, put=jax.device_put,
                                            depth=0))
        prefetch = run_epoch(PrefetchLoader(loader, put=jax.device_put,
                                            depth=depth))

    platform = jax.devices()[0].platform
    return {
        "metric": "io_pipeline_graphs_per_sec",
        "value": round(prefetch["graphs_per_s"], 2),
        "unit": (f"graphs/s through load->collate->put (streamed shards, "
                 f"prefetch depth={depth}, N={N_NODES}, E<={n_edges}, "
                 f"simulated compute {compute_s * 1e3:.0f}ms/step, "
                 f"platform={platform}; host pipeline, not a training "
                 f"headline)"),
        "vs_baseline": None,
        "vs_blocking": round(prefetch["graphs_per_s"]
                             / blocking["graphs_per_s"], 3),
        "stall_s": round(prefetch["stall_s"], 4),
        "stall_s_blocking": round(blocking["stall_s"], 4),
        "stall_fraction": round(prefetch["stall_s"] / prefetch["wall_s"], 4),
        "stall_fraction_blocking": round(
            blocking["stall_s"] / blocking["wall_s"], 4),
        "prefetch_depth": depth,
        "batches_per_epoch": blocking["batches"],
    }


def measure_tiled():
    """Tiled-serving leg: inference nodes/sec for ONE giant scene through
    the fixed-shape tile executor (serve/tiled.py) — the million-node
    serving path's throughput plus its three health gauges (tile count,
    halo fraction, H2D-overlap stall fraction). An INFERENCE number, never
    the training headline. Self-caps via BENCH_TILED_NODES; tile size via
    BENCH_TILE_NODES (default N/6 so the leg always actually tiles);
    BENCH_TILED_IMPL=fused runs the halo-aware fused edge pipeline;
    BENCH_TILED_DEVICES>1 adds the device sweep — the same scene rerun
    through D device-parallel rounds (serve/mesh_tiled.py) with the D=1
    number kept as seq_nodes_per_sec and scaling_efficiency =
    (mesh/seq)/D."""
    import jax

    from distegnn_tpu.models.fast_egnn import FastEGNN
    from distegnn_tpu.ops.graph import pad_graphs
    from distegnn_tpu.serve.engine import InferenceEngine
    from distegnn_tpu.serve.tiled import TiledExecutor

    global N_NODES
    cap = _env_int("BENCH_TILED_NODES", N_NODES)
    if N_NODES > cap:
        print(f"bench: tiled leg capped at N={cap}", file=sys.stderr)
        N_NODES = cap
    impl = os.environ.get("BENCH_TILED_IMPL", "plain")
    if impl not in ("plain", "fused"):
        impl = "plain"
    tile_nodes = _env_int("BENCH_TILE_NODES", 0)
    if tile_nodes <= 0:
        tile_nodes = max(512, (N_NODES // 6 // 512) * 512)
    steps = max(1, _env_int("BENCH_TILED_STEPS", 2))

    cloud, n_edges = make_fluid_cloud(np.random.default_rng(0))
    model = FastEGNN(node_feat_nf=3, node_attr_nf=2, edge_attr_nf=2,
                     hidden_nf=HIDDEN, virtual_channels=CHANNELS,
                     n_layers=LAYERS, edge_impl=impl)
    # params from a tiny same-featured batch (shapes are size-independent)
    small = {k: (v[:64] if k in ("node_feat", "node_attr", "loc", "vel",
                                 "target") else v) for k, v in cloud.items()}
    ei = cloud["edge_index"]
    sel = (ei[0] < 64) & (ei[1] < 64)
    small["edge_index"] = (ei[:, sel] if sel.any()
                           else np.array([[0, 1], [1, 0]], np.int32))
    small["edge_attr"] = (cloud["edge_attr"][sel] if sel.any()
                          else cloud["edge_attr"][:2])
    if impl == "fused":
        init_batch = pad_graphs([small], max_nodes=1536, edge_block=512,
                                edge_tile=512, split_remote=True,
                                compute_pair=False)
        layout = {"edge_block": 512, "split_remote": True}
    else:
        init_batch = pad_graphs([small], node_bucket=1, edge_bucket=1)
        layout = None
    params = model.init(jax.random.PRNGKey(0), init_batch)
    engine = InferenceEngine(model, params, layout_opts=layout)
    tx = TiledExecutor(engine, {"tile_nodes": tile_nodes,
                                "max_nodes": max(N_NODES, 4_194_304)})

    out = tx.predict(dict(cloud))            # warmup: compiles + first pass
    t0 = time.perf_counter()
    for _ in range(steps):
        out = tx.predict(dict(cloud))
    dt = time.perf_counter() - t0

    nodes_per_sec = N_NODES * steps / dt
    platform = jax.devices()[0].platform
    rec = {
        "metric": "tiled_serve_nodes_per_sec",
        "value": round(nodes_per_sec, 1),
        "unit": (f"inference nodes/sec through the tiled executor "
                 f"(N={N_NODES}, E={n_edges}, tiles={out['tiles']} x "
                 f"{tile_nodes} own nodes (padded {out['padded_nodes']}), "
                 f"impl={impl}, layers={LAYERS}, platform={platform}; "
                 f"serving leg, not a training headline)"),
        "vs_baseline": None,
        "tiles": out["tiles"],
        "tile_nodes": tile_nodes,
        "padded_nodes": out["padded_nodes"],
        "halo_fraction": round(out["halo_fraction"], 4),
        "h2d_stall_fraction": round(out["stall_fraction"], 4),
        "work_imbalance": round(out["work_imbalance"], 4),
        "pass_ms": round(dt / steps * 1e3, 1),
        "devices": 1,
        "tiled_rounds": out["rounds"],
        "scaling_efficiency": None,
    }

    # device sweep (serve/mesh_tiled.py): rerun the SAME scene and plan at
    # D = min(BENCH_TILED_DEVICES, local devices, tiles). The headline value
    # becomes the D-device number; seq_nodes_per_sec keeps the D=1 anchor and
    # scaling_efficiency = (mesh/seq)/D. On CPU this traces the mesh path
    # only — virtual devices share one host, so the ratio is evidence-grade
    # plumbing proof, never a speedup claim (BASELINE.md rules); real
    # multi-chip numbers come from the hw_session bench_tiled_mesh leg.
    req = _env_int("BENCH_TILED_DEVICES", 0)
    D = min(req, jax.local_device_count(), out["tiles"])
    if D > 1:
        tx.devices = D
        mout = tx.predict(dict(cloud))       # warmup: pmap compile
        t0 = time.perf_counter()
        for _ in range(steps):
            mout = tx.predict(dict(cloud))
        mdt = time.perf_counter() - t0
        mesh_nps = N_NODES * steps / mdt
        rec.update({
            "value": round(mesh_nps, 1),
            "unit": (f"inference nodes/sec through the tiled executor at "
                     f"D={D} device-parallel rounds (N={N_NODES}, "
                     f"E={n_edges}, tiles={out['tiles']} -> "
                     f"{mout['rounds']} rounds, impl={impl}, "
                     f"layers={LAYERS}, platform={platform}; serving leg; "
                     f"CPU sweep is plumbing evidence, not a speedup claim)"),
            "devices": D,
            "tiled_rounds": mout["rounds"],
            "seq_nodes_per_sec": round(nodes_per_sec, 1),
            "scaling_efficiency": round((mesh_nps / nodes_per_sec) / D, 4),
            "round_ms": round(mout["round_ms"], 2),
            "halo_gather_ms": round(mout["halo_gather_ms"], 2),
            "h2d_stall_fraction": round(mout["stall_fraction"], 4),
            "pass_ms": round(mdt / steps * 1e3, 1),
        })
    return rec


def main():
    # BENCH_PLATFORM=cpu pins the backend for smoke tests — NOTE env var
    # JAX_PLATFORMS alone is not enough on axon-tunnel hosts (the tunnel
    # plugin's get_backend hook initializes every discovered platform and a
    # wedged tunnel then hangs the process); config.update is honored.
    # Persistent XLA compile cache (same /tmp/jax_cache as hw_session.sh,
    # setdefault yields to an inherited value). Must be set BEFORE any jax
    # import — jax snapshots it at import time. The three race children
    # compile three DIFFERENT programs (one per lowering), so this does not
    # dedupe within one cold race; it amortizes compiles across repeat
    # invocations (re-fired queues, the driver's round-end run after a
    # measurement session) in the same container.
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")

    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)

    args = sys.argv[1:]
    layout, impl, seg, fuse, mesh_str = "auto", "einsum", "scatter", True, None
    usage = ("usage: bench.py [--layout plain|blocked|fused|fused_stack|"
             "tiled|io|auto] "
             "[--impl pallas|einsum] [--seg scatter|cumsum|ell] "
             "[--fuse 0|1] [--mesh DxGxT]  "
             "(env: BENCH_REORDER, BENCH_AGG_DTYPE, BENCH_STACK_NODES, "
             "BENCH_IO_NODES, BENCH_IO_DEPTH)")
    if "--mesh" in args:
        i = args.index("--mesh")
        if i + 1 >= len(args) or not re.fullmatch(r"\d+x\d+x\d+",
                                                  args[i + 1].lower()):
            sys.exit(usage)
        mesh_str = args[i + 1].lower()
    if "--layout" in args:
        i = args.index("--layout")
        if i + 1 >= len(args) or args[i + 1] not in ("plain", "blocked", "fused",
                                                     "fused_stack", "tiled",
                                                     "io", "auto", "probe"):
            sys.exit(usage)
        layout = args[i + 1]
    if "--impl" in args:
        i = args.index("--impl")
        if i + 1 >= len(args) or args[i + 1] not in ("pallas", "einsum"):
            sys.exit(usage)
        impl = args[i + 1]
    if "--seg" in args:
        i = args.index("--seg")
        if i + 1 >= len(args) or args[i + 1] not in ("scatter", "cumsum", "ell"):
            sys.exit(usage)
        seg = args[i + 1]
    if "--fuse" in args:
        i = args.index("--fuse")
        if i + 1 >= len(args) or args[i + 1] not in ("0", "1"):
            sys.exit(usage)
        fuse = args[i + 1] == "1"

    if mesh_str is not None:
        # CPU runs (smoke tests) need the virtual devices provisioned BEFORE
        # the backend initializes; harmless no-op when it already is (the
        # RuntimeError path) or on real hardware.
        if plat == "cpu" or os.environ.get("JAX_PLATFORMS") == "cpu":
            import jax

            need = int(np.prod([int(v) for v in mesh_str.split("x")]))
            try:
                jax.config.update("jax_num_cpu_devices", max(need, 1))
            except (RuntimeError, AttributeError):
                # older jax: the XLA flag is read at backend init, which has
                # not happened yet on this path
                if "--xla_force_host_platform_device_count" not in \
                        os.environ.get("XLA_FLAGS", ""):
                    os.environ["XLA_FLAGS"] = (
                        os.environ.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count={need}")
        _emit_bench(measure_mesh(mesh_str, seg, fuse))
        return

    edge_block = _env_int("BENCH_EDGE_BLOCK", 256)
    if layout == "probe":
        # Tiny round-trip (matmul + host fetch). On a wedged tunnel this
        # blocks in acquire without ever claiming the device, so the parent's
        # timeout-kill is safe (same contract as scripts/tpu_probe.sh).
        import jax
        import jax.numpy as jnp

        x = jnp.ones((256, 256))
        print("PROBE_OK", jax.devices()[0].platform, float((x @ x).sum()))
        return
    if layout == "fused":
        # fused edge pipeline: kernel constraints pin the block (>= 512 and a
        # multiple of it); BENCH_FUSED_BLOCK overrides for VMEM-window sweeps
        fb = _env_int("BENCH_FUSED_BLOCK", 512)
        _emit_bench(measure(fb, impl, seg, fuse, edge_impl="fused"))
        return
    if layout == "fused_stack":
        # Cross-layer megakernel: the whole L-layer stack must be VMEM-
        # resident, and the flagship 113k shape exceeds the 16 MiB budget by
        # design (ops/layer_pipeline.check_stack_vmem would raise its typed
        # error at trace time). Self-cap to the largest padded shape that
        # fits at Fluid113K density rather than fail-record the leg; the
        # resulting number is an A/B vs --layout fused at the SAME node
        # count, and official/vs_baseline is already None off-workload.
        global N_NODES
        cap = _env_int("BENCH_STACK_NODES", 1536)
        if N_NODES > cap:
            print(f"bench: fused_stack leg capped at N={cap} "
                  f"(VMEM-resident stack; N={N_NODES} exceeds the "
                  f"default 16 MiB budget)", file=sys.stderr)
            N_NODES = cap
        fb = _env_int("BENCH_FUSED_BLOCK", 512)
        _emit_bench(measure(fb, impl, seg, fuse, edge_impl="fused_stack"))
        return
    if layout == "tiled":
        # giant-scene serving leg (tile executor nodes/sec + halo/stall
        # gauges); an inference number, never the training headline.
        # BENCH_TILED_DEVICES>1 on CPU needs virtual devices provisioned
        # BEFORE the backend initializes (same contract as the mesh leg);
        # harmless no-op on real hardware.
        dneed = _env_int("BENCH_TILED_DEVICES", 0)
        if dneed > 1 and (plat == "cpu"
                          or os.environ.get("JAX_PLATFORMS") == "cpu"):
            import jax

            try:
                jax.config.update("jax_num_cpu_devices", dneed)
            except (RuntimeError, AttributeError):
                if "--xla_force_host_platform_device_count" not in \
                        os.environ.get("XLA_FLAGS", ""):
                    os.environ["XLA_FLAGS"] = (
                        os.environ.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count={dneed}")
        _emit_bench(measure_tiled())
        return
    if layout == "io":
        # input-pipeline A/B (prefetch vs blocking put over streamed shards);
        # reports graphs/s + stall fractions, never the training headline
        _emit_bench(measure_io())
        return
    if layout in ("plain", "blocked"):
        _emit_bench(measure(edge_block if layout == "blocked" else 0,
                            impl, seg, fuse))
        return

    # auto: probe-gate, then measure the candidate lowerings, each in a CHILD
    # process (so a compiler surprise on new hardware can't take down the
    # bench), and report the fastest real measurement. Candidates:
    # plain-cumsum (scatter-free prefix-sum aggregation), plain-ell
    # (fixed-degree chained gathers) and plain-scatter. The blocked layouts
    # are excluded after losing on hardware twice (BASELINE.md round-2
    # status: pallas 1067.7 ms vs plain 712-773; einsum 2462.7 vs plain
    # 1653.5 in the same degraded-tunnel session) — measure them explicitly
    # with --layout blocked if revisiting.
    t_start = time.monotonic()

    def remaining():
        return TOTAL_BUDGET_S - (time.monotonic() - t_start)

    def fail_record(reason):
        return {
            "metric": "largefluid_train_nodes_per_sec_per_chip",
            "value": 0.0,
            "unit": f"MEASUREMENT FAILED: {reason[:400]}",
            "vs_baseline": 0.0,
        }

    self_path = os.path.abspath(__file__)
    repo_dir = os.path.dirname(self_path)


    def persist_race(records, fails, probe_ok, platform, on_hardware):
        # Tracked artifact with EVERY child's record, not just the winner:
        # the race IS the in-session A/B control (cross-session tunnel
        # variance is 2.2x — BASELINE.md), so the per-lowering table is only
        # meaningful as a unit. Written even on failure so a dead-tunnel
        # round still leaves evidence of what was attempted. CPU (dev-box)
        # races go to a SEPARATE artifact so a local run can never clobber
        # committed hardware evidence; platform and the real probe outcome
        # are recorded top-level (ADVICE r3, medium). probe_ok=None means
        # the probe was skipped (explicit CPU run / delegated probe).
        try:
            os.makedirs(os.path.join(repo_dir, "docs", "artifacts"), exist_ok=True)
            # Routing: hardware measurements AND attempted-hardware probe
            # failures (probe_ok is False — the honest dead-tunnel record)
            # belong in the tracked hardware artifact; anything that actually
            # ran on CPU goes to the CPU file. A probe failure only counts as
            # a hardware attempt on a machine that actually has the axon TPU
            # plugin — on a plugin-less dev box a failed/overloaded probe
            # must not clobber committed hardware evidence (code-review r4).
            hardware_rig = os.path.exists("/root/.axon_site")
            to_main = on_hardware or (probe_ok is False and hardware_rig)
            path = os.path.join(
                repo_dir, RACE_ARTIFACT if to_main else RACE_ARTIFACT_CPU)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"DO_NOT_CITE": "rolling file, overwritten by "
                                          "every race — cite the dated "
                                          "docs/artifacts/bench_*_<stamp> "
                                          "archives instead",
                           "probe_ok": probe_ok, "platform": platform,
                           "on_hardware": on_hardware, "n_nodes": N_NODES,
                           "note": "single-session race; values comparable "
                                   "only within this record (2.2x "
                                   "cross-session tunnel variance)",
                           "results": records, "failures": fails}, f, indent=1)
            os.replace(tmp, path)
        except OSError as e:
            print(f"bench: artifact write failed: {e!r}", file=sys.stderr)

    # Probe first (round 2 lost its end-of-round number to a wedged tunnel
    # that hung the measurement children past the driver's budget). On a
    # dead tunnel this prints the honest-failure JSON in <2 min total.
    on_hardware = False  # proven non-CPU backend -> pause competitors
    probe_ok = None      # None = probe skipped (explicit CPU / delegated)
    probed_plat = plat   # best knowledge of the backend for the artifact
    if os.environ.get("BENCH_PROBE", "1") != "0" and plat != "cpu":
        # Hard-timeout probe with ONE auto-retry: a probe fired into a slow
        # claim release fails once on a healthy tunnel, and an unretried
        # probe failure records nothing (the BENCH_r02-r05 wipeout mode).
        reason = ""
        for attempt in (1, 2):
            try:
                out = subprocess.run(
                    [sys.executable, self_path, "--layout", "probe"],
                    capture_output=True, text=True,
                    timeout=PROBE_TIMEOUT_S, cwd=repo_dir)
                probe_ok = out.returncode == 0 and "PROBE_OK" in out.stdout
                reason = f"rc={out.returncode}, stderr tail: {out.stderr[-200:]}"
                if probe_ok:
                    # Parse the PROBE_OK line itself ("PROBE_OK <platform>
                    # <val>") and derive BOTH provenance fields from it —
                    # scanning the whole stdout could let a stray diagnostic
                    # token disagree with the on_hardware test (code-review
                    # r4).
                    for line in out.stdout.splitlines():
                        toks = line.split()
                        if toks and toks[0] == "PROBE_OK" and len(toks) > 1:
                            probed_plat = toks[1]
                            break
                    on_hardware = probed_plat is not None and probed_plat != "cpu"
            except subprocess.TimeoutExpired:
                probe_ok, reason = False, f"probe timed out after {PROBE_TIMEOUT_S}s"
            if probe_ok or attempt == 2:
                break
            print(f"bench: probe attempt 1 failed ({reason}); retrying once "
                  f"after {PROBE_RETRY_SPACING_S}s", file=sys.stderr)
            time.sleep(PROBE_RETRY_SPACING_S)
        if not probe_ok:
            rec = fail_record(f"device probe failed (wedged TPU tunnel?): {reason}")
            persist_race([], [f"probe: {reason}"], False,
                         platform="unreachable", on_hardware=False)
            _emit_bench(rec)
            return
        # Claim release after a client exits takes >25 s on this tunnel; a
        # child started immediately can hang in acquire even when healthy.
        time.sleep(30)
    elif os.environ.get("BENCH_PROBE") == "0" and plat != "cpu":
        # Probe delegated to the caller (hw_session.sh run()). Trust it ONLY
        # with an explicit attestation of what the caller's probe saw —
        # BENCH_PROBE=0 alone on a CPU dev box must not stamp hardware
        # evidence or freeze unrelated local work (code-review r4).
        caller_plat = os.environ.get("BENCH_CALLER_PROBED", "")
        if caller_plat:
            # honest provenance either way; only a non-cpu attestation makes
            # this a hardware measurement
            on_hardware = caller_plat != "cpu"
            probed_plat = f"{caller_plat} (probe delegated to caller)"
        else:
            # nothing verified the backend — record that, NOT the requested
            # platform (BENCH_PLATFORM is a wish, not a measurement)
            probed_plat = "unverified (BENCH_PROBE=0, no attestation)"

    # Pause provably-CPU-pinned competitors for the measurement window
    # (resumed in the finally below; a driver SIGTERM also resumes them via
    # the handler — otherwise a killed bench would leave them frozen
    # forever). BENCH_PAUSE=0 disables (hw_session.sh pauses for the whole
    # queue itself); the probe's reported platform gates it off entirely on
    # CPU-only machines so a dev-box bench never freezes unrelated work.
    paused, ambiguous = [], []
    if on_hardware and os.environ.get("BENCH_PAUSE", "1") != "0":
        paused, ambiguous = cpu_competitors()
    if paused:
        # Ledger FIRST, SIGSTOP second: if the bench is SIGKILLed mid-
        # measurement (driver hard-timeout / OOM — the round-2 scenario) the
        # finally/handler resume never runs, and tpu_watch.sh CONTs the
        # leftover stopped PIDs from this file on startup (ADVICE r3).
        # MERGE with any existing ledger: a prior SIGKILLed bench's frozen
        # PIDs are skipped by cpu_competitors (state T), so overwriting
        # would erase the only record of them (code-review r4).
        try:
            prior = []
            if os.path.exists(PAUSED_PIDS_FILE):
                with open(PAUSED_PIDS_FILE) as f:
                    prior = [int(l) for l in f.read().split() if l.isdigit()]
            ledger = sorted(set(paused) | set(prior))
            with open(PAUSED_PIDS_FILE, "w") as f:
                f.write("\n".join(str(p) for p in ledger) + "\n")
        except (OSError, ValueError) as e:
            print(f"bench: paused-pid ledger write failed: {e!r}", file=sys.stderr)
    for p in paused:
        try:
            os.kill(p, signal.SIGSTOP)
        except OSError:
            pass

    def _resume(signum=None, frame=None):
        for p in paused:
            try:
                os.kill(p, signal.SIGCONT)
            except OSError:
                pass
        # Clean resume -> drop OUR pids from the ledger, but preserve any
        # merged-in entries from a previously killed bench that are still
        # frozen (they are not ours to CONT mid-queue; the watcher recovers
        # them). Remove the file only when nothing is left.
        try:
            if paused and os.path.exists(PAUSED_PIDS_FILE):
                with open(PAUSED_PIDS_FILE) as f:
                    ledger = {int(l) for l in f.read().split() if l.isdigit()}
                leftover = []
                for p in ledger - set(paused):
                    try:
                        with open(f"/proc/{p}/stat") as f:
                            if f.read().split(") ")[-1].split()[0] == "T":
                                leftover.append(p)
                    except OSError:
                        pass
                if leftover:
                    with open(PAUSED_PIDS_FILE, "w") as f:
                        f.write("\n".join(str(p) for p in sorted(leftover)) + "\n")
                else:
                    os.remove(PAUSED_PIDS_FILE)
        except (OSError, ValueError):
            pass
        if signum is not None:
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

    if paused:
        signal.signal(signal.SIGTERM, _resume)
        signal.signal(signal.SIGINT, _resume)

    best, records, fails, measured = None, [], [], []
    first = True
    try:
        # Race order lives in RACE_ORDER (module top) so the CPU trace test
        # and hw_session.sh stage the exact legs this loop runs.
        for child_args, child_env in RACE_ORDER:
            # Skip rather than admit a child that could only finish by being
            # timeout-killed: a timeout SIGKILLs a LIVE client
            # mid-measurement, which strands the remote claim (the
            # tunnel-wedging hazard). The slowest observed degraded-session
            # child is ~360 s; require enough budget that the clamped
            # timeout stays comfortably above that.
            leg = " ".join(child_args) + (
                " " + " ".join(f"{k}={v}" for k, v in child_env.items())
                if child_env else "")
            if remaining() < 480:
                fails.append(f"{leg}: skipped (wall budget "
                             f"{TOTAL_BUDGET_S}s nearly spent)")
                continue
            if not first:
                time.sleep(30)  # claim-release spacing between TPU clients
            first = False
            try:
                out = subprocess.run(
                    [sys.executable, self_path] + child_args,
                    capture_output=True, text=True,
                    # per-leg budget: one wedged leg may not eat the race
                    timeout=min(CHILD_TIMEOUT_S, LEG_BUDGET_S,
                                remaining() - 60),
                    cwd=repo_dir,
                    env=(dict(os.environ, **child_env) if child_env else None),
                )
                rec = None
                if out.returncode == 0:
                    for line in out.stdout.strip().splitlines():
                        try:
                            parsed = json.loads(line)
                        except json.JSONDecodeError:
                            continue
                        if isinstance(parsed, dict) and parsed.get("metric"):
                            rec = parsed
                if rec is None:
                    fails.append(f"{leg}: rc={out.returncode}, "
                                 f"stderr tail: {out.stderr[-300:]}")
                else:
                    records.append(rec)
                    measured.append(leg)
                    # only the training headline contends for best: the io
                    # leg's graphs/s lives on a different scale and must
                    # never displace a nodes/sec/chip measurement
                    if rec.get("metric") == \
                            "largefluid_train_nodes_per_sec_per_chip" and (
                            best is None or rec["value"] > best["value"]):
                        best = rec
            except subprocess.TimeoutExpired:
                fails.append(f"{leg}: timed out (leg budget "
                             f"{min(CHILD_TIMEOUT_S, LEG_BUDGET_S)}s)")
            except Exception as e:
                fails.append(f"{leg}: {e!r}")
            # Persist INCREMENTALLY: a bench killed mid-race (driver budget,
            # tunnel wedge hanging a later child) must not lose the legs
            # that already finished — each completed child updates the
            # artifact with a partial=True stamp the final write clears.
            persist_race(records, fails + ["partial: race still running"],
                         probe_ok, platform=probed_plat,
                         on_hardware=on_hardware)
            # Un-losable headline (VERDICT r4 #1): print the best-so-far JSON
            # line after EVERY finished leg and flush. The driver parses the
            # LAST parseable line of the captured tail, so killing this
            # process at any point after >=1 finished leg still yields an
            # official number — round 4 finished 4 legs and recorded nothing
            # because the only print sat after the whole race.
            if best is not None:
                _emit_bench(best, flush=True)
    finally:
        _resume()
    if ambiguous:
        # measuring happened next to a possibly-live TPU client — don't let
        # the number be silently trusted
        note = (f"CONTENTION: possibly-live TPU client(s) pid {ambiguous} "
                "ran during the race")
        print(f"bench: {note}", file=sys.stderr)
        fails.append(note)
        if best is not None:
            best = dict(best, unit=best["unit"] + f"; {note}")
    for f in fails:
        print(f"bench: child failed ({f})", file=sys.stderr)
    persist_race(records, fails, probe_ok, platform=probed_plat,
                 on_hardware=on_hardware)
    if best is not None:
        if fails:
            # Degraded-mode line: SOME legs died/were skipped. Name exactly
            # which legs produced the number so a partial race reads as
            # partial — BENCH_r02-r05 recorded nothing and left no per-leg
            # record of what had been attempted.
            best = dict(best,
                        unit=best["unit"] + (
                            f"; DEGRADED: measured {len(measured)}/"
                            f"{len(RACE_ORDER)} legs [{', '.join(measured)}]"),
                        legs_measured=measured,
                        legs_failed=[f.split(":", 1)[0] for f in fails])
        _emit_bench(best)
    else:
        # All children failed — almost certainly unreachable hardware (a
        # wedged axon tunnel). Do NOT fall back to an in-process measurement:
        # on a wedged tunnel that blocks forever at the first device op, and
        # a hung bench records nothing at all. Emit an honest failure line
        # that still names every attempted leg.
        rec = fail_record(
            f"all bench children died (wedged TPU tunnel?): {'; '.join(fails)}")
        rec["legs_measured"] = []
        rec["legs_failed"] = [f.split(":", 1)[0] for f in fails]
        _emit_bench(rec)


if __name__ == "__main__":
    main()
