"""Benchmark: LargeFluid-scale training-step throughput, nodes/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload (BASELINE.md protocol): Fluid113K shape — 113,140 nodes, ~1.7M
radius-0.075 edges, batch 1, FastEGNN hidden 64 / 4 layers / C=3 with MMD
(sigma 3, w 0.01, n 50) and grad clip 0.3 — the largefluid_distegnn.yaml
configuration on one chip. vs_baseline divides by the round-1 TPU v5e anchor
measured with this same script, so the number tracks our own progress
(the reference publishes no GPU throughput; see BASELINE.md)."""

from __future__ import annotations

import json
import time

import numpy as np

# Round-1 anchor: first measurement of this script on the single TPU v5e chip
# (2026-07-29, step 166.9ms at N=113140/E=1639080).
BASELINE_NODES_PER_SEC = 677_764.7

N_NODES = 113_140
RADIUS = 0.075
TARGET_EDGES_PER_NODE = 15.0
HIDDEN, LAYERS, CHANNELS = 64, 4, 3
WARMUP, STEPS = 3, 10


def make_fluid_batch(rng):
    """Synthetic fluid-like particle cloud at Fluid113K density."""
    from distegnn_tpu.ops.graph import pad_graphs
    from distegnn_tpu.ops.radius import radius_graph_np

    vol = N_NODES * (4.0 / 3.0) * np.pi * RADIUS**3 / TARGET_EDGES_PER_NODE
    side = vol ** (1.0 / 3.0)
    loc = rng.uniform(0, side, size=(N_NODES, 3)).astype(np.float32)
    vel = rng.normal(size=(N_NODES, 3)).astype(np.float32) * 0.01
    edge_index = radius_graph_np(loc, RADIUS)
    dist = np.linalg.norm(loc[edge_index[0]] - loc[edge_index[1]], axis=1)
    graph = {
        "node_feat": np.concatenate(
            [np.linalg.norm(vel, axis=1, keepdims=True), vel[:, :2]], axis=1
        ).astype(np.float32),                       # 3 features (largefluid config)
        "node_attr": np.ones((N_NODES, 2), np.float32),  # viscosity, mass
        "loc": loc,
        "vel": vel,
        "target": loc + vel * 0.05,
        "loc_mean": loc.mean(axis=0),
        "edge_index": edge_index.astype(np.int32),
        "edge_attr": np.repeat(dist[:, None], 2, axis=1).astype(np.float32),
    }
    return pad_graphs([graph]), edge_index.shape[1]


def main():
    import jax

    from distegnn_tpu.models.fast_egnn import FastEGNN
    from distegnn_tpu.train import TrainState, make_optimizer, make_train_step

    rng = np.random.default_rng(0)
    batch, n_edges = make_fluid_batch(rng)

    model = FastEGNN(node_feat_nf=3, node_attr_nf=2, edge_attr_nf=2,
                     hidden_nf=HIDDEN, virtual_channels=CHANNELS, n_layers=LAYERS)
    params = model.init(jax.random.PRNGKey(0), batch)
    tx = make_optimizer(5e-4, weight_decay=1e-12, clip_norm=0.3)
    state = TrainState.create(params, tx)
    step = jax.jit(make_train_step(model, tx, mmd_weight=0.01, mmd_sigma=3.0,
                                   mmd_samples=50), donate_argnums=0)

    for i in range(WARMUP):
        state, metrics = step(state, batch, jax.random.PRNGKey(i))
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for i in range(STEPS):
        state, metrics = step(state, batch, jax.random.PRNGKey(100 + i))
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    nodes_per_sec = N_NODES * STEPS / dt
    vs = nodes_per_sec / BASELINE_NODES_PER_SEC
    print(json.dumps({
        "metric": "largefluid_train_nodes_per_sec_per_chip",
        "value": round(nodes_per_sec, 1),
        "unit": f"nodes/sec/chip (N={N_NODES}, E={n_edges}, step={dt / STEPS * 1e3:.1f}ms)",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
