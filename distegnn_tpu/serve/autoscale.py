"""SLO-driven replica autoscaler — the fleet operates itself.

One :class:`ReplicaAutoscaler` watches every model in a
:class:`~distegnn_tpu.serve.registry.ModelRegistry` and grows/shrinks each
model's :class:`~distegnn_tpu.serve.replica.ReplicaSet` LIVE, reading the
same windowed numbers ``GET /metrics`` exports (the SLOMonitor's rolling
window plus the per-model queue depth):

  scale UP    when queued work per healthy replica exceeds ``queue_high``,
              the window shed rate exceeds ``shed_high``, or (optionally)
              the windowed predict p99 exceeds ``p99_high_ms`` — bounded by
              ``max_replicas`` and ``scale_up_cooldown_s``
  scale DOWN  after ``idle_rounds`` consecutive calm evaluations (depth per
              replica under ``queue_low``, zero window shed, no up-trigger)
              — bounded by ``min_replicas`` and ``scale_down_cooldown_s``

New replicas come from the registry entry's ``replica_factory`` (thread or
process workers through the exact supervisor/breaker machinery static
replicas use — the supervisor's tick iterates the live list, so an added
replica is supervised from its next tick). Retirement goes through
``ReplicaSet.retire_replica``: the victim first stops being choosable, its
in-flight set drains, then its queue stops — at-most-once is never
sacrificed for elasticity.

Every decision lands on the obs stream as ``gateway/scale_up`` /
``gateway/scale_down`` / ``gateway/scale_blocked`` carrying the triggering
gauge values, and ``gateway/autoscale_<model>_replicas`` / ``..._target``
gauges ride every metrics render. The control loop is a plain thread;
``tick(now=...)`` is public and synchronous so tests drive the whole
decision table with a synthetic clock, exactly like the supervisor's.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from distegnn_tpu import obs

# knob defaults — kept in lockstep with config._DEFAULTS["serve"]["autoscale"]
# (scripts/check_config_keys.py asserts the config side; this dict is the
# in-code fallback for hand-built configs)
_DEFAULTS: Dict[str, Any] = {
    "enable": False,
    "min_replicas": 1,
    "max_replicas": 4,
    "interval_s": 0.5,
    "scale_up_cooldown_s": 2.0,
    "scale_down_cooldown_s": 10.0,
    "step": 1,
    "queue_high": 4.0,
    "shed_high": 0.01,
    "p99_high_ms": None,
    "queue_low": 0.5,
    "idle_rounds": 3,
    "drain_timeout_s": 30.0,
}


class _ModelState:
    """Per-model control-loop memory (cooldowns + calm streak)."""

    __slots__ = ("last_up_at", "last_down_at", "calm_rounds")

    def __init__(self):
        self.last_up_at = float("-inf")
        self.last_down_at = float("-inf")
        self.calm_rounds = 0


class ReplicaAutoscaler:
    """Per-model scale control loop over a live registry + SLO window.

    Args:
      registry: the ModelRegistry whose entries scale.
      monitor: the gateway's SLOMonitor (``window_snapshot`` source); None
        disables the shed/p99 triggers (depth still drives decisions).
      config: the ``serve.autoscale`` mapping (missing keys take defaults).
      metrics_registry: obs MetricsRegistry for the replica-count gauges
        (None skips gauge export).
    """

    def __init__(self, registry, monitor=None, *,
                 config: Optional[dict] = None, metrics_registry=None):
        knobs = dict(_DEFAULTS)
        knobs.update(dict(config or {}))
        self.enable = bool(knobs["enable"])
        self.min_replicas = max(1, int(knobs["min_replicas"]))
        self.max_replicas = max(self.min_replicas, int(knobs["max_replicas"]))
        self.interval_s = float(knobs["interval_s"])
        self.up_cooldown_s = float(knobs["scale_up_cooldown_s"])
        self.down_cooldown_s = float(knobs["scale_down_cooldown_s"])
        self.step = max(1, int(knobs["step"]))
        self.queue_high = float(knobs["queue_high"])
        self.shed_high = float(knobs["shed_high"])
        self.p99_high_ms = (None if knobs["p99_high_ms"] is None
                            else float(knobs["p99_high_ms"]))
        self.queue_low = float(knobs["queue_low"])
        self.idle_rounds = max(1, int(knobs["idle_rounds"]))
        self.drain_timeout_s = float(knobs["drain_timeout_s"])
        self.registry = registry
        self.monitor = monitor
        self._reg = metrics_registry
        self._states: Dict[str, _ModelState] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()  # one tick at a time (loop vs tests)

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> "ReplicaAutoscaler":
        if self._thread is not None or not self.enable:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-autoscale", daemon=True)
        self._thread.start()
        return self

    def stop(self, join_timeout_s: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=join_timeout_s)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as exc:  # the loop must outlive any one model
                obs.log(f"autoscale: tick failed: {exc!r}")
            self._stop.wait(self.interval_s)

    # ---- the control loop body -------------------------------------------
    def tick(self, now: Optional[float] = None) -> None:
        """One synchronous evaluation of every model. ``now`` overrides the
        clock for the cooldown/calm bookkeeping AND the window snapshot —
        tests drive the full decision table deterministically with it."""
        with self._lock:
            t = time.monotonic() if now is None else float(now)
            snap = (self.monitor.window_snapshot(now=now)
                    if self.monitor is not None else {})
            for name, entry in self.registry.items():
                try:
                    self._tick_model(name, entry, snap, t)
                except Exception as exc:
                    obs.log(f"autoscale: {name}: {exc!r}")

    def _tick_model(self, name: str, entry, snap: Dict[str, float],
                    t: float) -> None:
        st = self._states.setdefault(name, _ModelState())
        rset = entry.replicas
        current = len(rset.replicas)
        healthy = rset.available()
        depth = int(entry.queue.depth())
        per_rep = depth / max(healthy, 1)
        shed = float(snap.get("shed_rate", 0.0))
        p99 = snap.get("predict_p99_ms")
        gauges = dict(depth=depth, healthy=healthy,
                      per_replica_depth=round(per_rep, 3),
                      shed_rate=round(shed, 6),
                      predict_p99_ms=(None if p99 is None else round(p99, 3)))

        reasons = []
        if per_rep > self.queue_high:
            reasons.append("queue_depth")
        if shed > self.shed_high:
            reasons.append("shed_rate")
        if (self.p99_high_ms is not None and p99 is not None
                and p99 > self.p99_high_ms):
            reasons.append("p99")

        target = current
        if reasons:
            st.calm_rounds = 0
            target = min(current + self.step, self.max_replicas)
            if current >= self.max_replicas:
                obs.event("gateway/scale_blocked", model=name,
                          direction="up", reason="max_replicas",
                          replicas=current, triggers=reasons, **gauges)
            elif t - st.last_up_at < self.up_cooldown_s:
                obs.event("gateway/scale_blocked", model=name,
                          direction="up", reason="cooldown",
                          replicas=current, triggers=reasons, **gauges)
            elif entry.replica_factory is None:
                obs.event("gateway/scale_blocked", model=name,
                          direction="up", reason="no_factory",
                          replicas=current, triggers=reasons, **gauges)
            else:
                added = self._grow(name, entry, target - current)
                if added:
                    st.last_up_at = t
                    obs.event("gateway/scale_up", model=name,
                              from_replicas=current,
                              to_replicas=current + added,
                              triggers=reasons, **gauges)
        else:
            calm = per_rep < self.queue_low and shed == 0.0
            st.calm_rounds = st.calm_rounds + 1 if calm else 0
            if (st.calm_rounds >= self.idle_rounds
                    and current > self.min_replicas):
                target = max(current - self.step, self.min_replicas)
                if t - max(st.last_down_at, st.last_up_at) \
                        < self.down_cooldown_s:
                    obs.event("gateway/scale_blocked", model=name,
                              direction="down", reason="cooldown",
                              replicas=current,
                              calm_rounds=st.calm_rounds, **gauges)
                else:
                    removed = self._shrink(entry, current - target)
                    if removed:
                        st.last_down_at = t
                        st.calm_rounds = 0
                        obs.event("gateway/scale_down", model=name,
                                  from_replicas=current,
                                  to_replicas=current - removed,
                                  calm_rounds=self.idle_rounds, **gauges)
        if self._reg is not None:
            self._reg.gauge(f"gateway/autoscale_{name}_replicas").set(
                len(rset.replicas))
            self._reg.gauge(f"gateway/autoscale_{name}_target").set(target)

    def _grow(self, name: str, entry, count: int) -> int:
        added = 0
        # warm the already-warmed rungs BEFORE the new replica becomes
        # choosable (add_replica's warm_sizes contract), so a mid-spike
        # scale-up never routes live traffic into a compile storm; warmup
        # failure is non-fatal (lazy compile on first traffic)
        sizes = [(b.n, b.e) for b in entry.warmed]
        for _ in range(count):
            try:
                # entry.add_replica (not the raw set) so a blue/green swap
                # racing the build cannot leave the new replica on the
                # retired version — it re-pins under the swap lock
                entry.add_replica(warm_sizes=sizes)
            except Exception as exc:
                obs.event("gateway/scale_blocked", model=name,
                          direction="up", reason="spawn_failed",
                          error=repr(exc)[:300])
                break
            added += 1
        return added

    def _shrink(self, entry, count: int) -> int:
        removed = 0
        for _ in range(count):
            victim = entry.replicas.retire_replica(
                drain_timeout_s=self.drain_timeout_s)
            if victim is None:
                break
            removed += 1
        return removed

    # ---- health surface ---------------------------------------------------
    def status(self) -> Dict[str, dict]:
        """Per-model scale state for /readyz."""
        out: Dict[str, dict] = {}
        for name, entry in self.registry.items():
            st = self._states.get(name)
            out[name] = {
                "replicas": len(entry.replicas.replicas),
                "available": entry.replicas.available(),
                "min": self.min_replicas,
                "max": self.max_replicas,
                "calm_rounds": 0 if st is None else st.calm_rounds,
            }
        return out


__all__ = ["ReplicaAutoscaler"]
