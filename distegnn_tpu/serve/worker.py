"""Out-of-process serving worker — IPC child + parent-side handle.

``serve.workers: process`` moves each replica's engine out of the gateway
process: a crashed device call, an OOM kill, or a GIL-holding wedge takes
down ONE child, not the fleet. This module is both halves of that boundary:

  - **child** (``python -m distegnn_tpu.serve.worker --fd N``): builds its
    own engine from the model config — the registry's deterministic recipe
    via :func:`distegnn_tpu.serve.engine_with_params_from_config`, so params
    are bitwise-identical to the parent's — and serves predict / rollout /
    warmup / swap ops over the inherited socket. A heartbeat thread beats
    every ``heartbeat_s`` and doubles as the parent-death watchdog
    (``getppid`` flip or a dead pipe → ``os._exit``; no orphans).
  - **parent** (:class:`WorkerHandle`): spawns the child with ``spawn``
    semantics (fresh interpreter via ``sys.executable -m``, no forked JAX
    state), speaks the framed protocol with per-message deadlines, tracks
    heartbeat age for the supervisor's staleness check, and escalates
    SIGTERM → SIGKILL with zombie reaping on ``terminate()``.

Framing: ``!2sBIII`` header (magic ``DW``, frame kind, sequence number,
payload length, CRC32) + a pickled payload. Every failure mode is a typed
error — :class:`FrameError` (corruption), :class:`WorkerClosedError` (dead
pipe / EOF), :class:`WorkerTimeoutError` (deadline), :class:`WorkerSpawnError`
(exec/handshake/digest failure) — never a hang: a caller blocked on a dead
child is released by the reader thread failing its pending slot.

Module-level imports are STDLIB ONLY (enforced by
``scripts/check_worker_imports.py``): the child must stay a thin engine
host, so transport/registry/supervisor code can never ride into the
isolated process.
"""

from __future__ import annotations

import argparse
import atexit
import os
import pickle
import signal
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time
import traceback
import zlib
from typing import Any, Dict, List, Optional

_MAGIC = b"DW"
_HEADER = struct.Struct("!2sBIII")  # magic, kind, seq, length, crc32
FRAME_REQUEST = 1
FRAME_RESPONSE = 2
FRAME_HEARTBEAT = 3


class WorkerError(RuntimeError):
    """Base of every typed worker-IPC failure."""


class FrameError(WorkerError):
    """Corrupt framing: bad magic or a checksum mismatch. The channel is
    unusable after this — the reader marks the worker lost."""


class WorkerClosedError(WorkerError):
    """The IPC channel is dead (EOF, reset, or the worker was reaped)."""


class WorkerTimeoutError(WorkerError):
    """A framed call exceeded its per-message deadline. The child may still
    be computing — the caller decides whether to kill it."""


class WorkerSpawnError(WorkerError):
    """The child failed to exec, initialize, or match the parent's params
    digest. The replica layer degrades to an in-process queue on this."""


class WorkerRemoteError(WorkerError):
    """The child executed the op but raised an exception the parent has no
    richer type for; carries the remote type name + message."""


# ---- framing ----------------------------------------------------------------

def send_frame(sock: socket.socket, lock: threading.Lock, kind: int,
               seq: int, obj: Any) -> None:
    """Serialize + frame + send one message under the channel write lock
    (the child's heartbeat thread and op loop share one socket)."""
    payload = pickle.dumps(obj, protocol=4)
    header = _HEADER.pack(_MAGIC, kind, seq, len(payload),
                          zlib.crc32(payload) & 0xFFFFFFFF)
    try:
        with lock:
            sock.sendall(header + payload)
    except OSError as exc:
        raise WorkerClosedError(f"worker channel write failed: {exc}") from None


def _recv_exact(sock: socket.socket, n: int,
                deadline: Optional[float]) -> bytes:
    chunks: List[bytes] = []
    got = 0
    while got < n:
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise WorkerTimeoutError("worker channel read deadline passed")
            sock.settimeout(remaining)
        else:
            sock.settimeout(None)
        try:
            chunk = sock.recv(n - got)
        except socket.timeout:
            raise WorkerTimeoutError(
                "worker channel read deadline passed") from None
        except OSError as exc:
            raise WorkerClosedError(
                f"worker channel read failed: {exc}") from None
        if not chunk:
            raise WorkerClosedError("worker channel closed (EOF)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket,
               deadline: Optional[float] = None) -> tuple:
    """Read one frame; returns (kind, seq, payload object). ``deadline`` is
    absolute ``time.monotonic()`` seconds (None = block forever — the
    parent's dedicated reader thread relies on EOF instead)."""
    header = _recv_exact(sock, _HEADER.size, deadline)
    magic, kind, seq, length, crc = _HEADER.unpack(header)
    if magic != _MAGIC:
        raise FrameError(f"bad frame magic {magic!r}")
    payload = _recv_exact(sock, length, deadline)
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise FrameError(f"frame checksum mismatch (seq {seq})")
    return kind, seq, pickle.loads(payload)


def current_matmul_precision() -> Optional[str]:
    """The parent's jax_default_matmul_precision, forwarded to the child at
    init so cross-process predictions stay bitwise-identical."""
    try:
        import jax

        v = jax.config.jax_default_matmul_precision
        return None if v is None else str(v)
    except Exception:
        return None


def _obs_event(name: str, **attrs) -> None:
    """Best-effort obs event (lazy import keeps module-level stdlib-only)."""
    try:
        from distegnn_tpu import obs

        obs.event(name, **attrs)
    except Exception:
        pass


# ---- parent side ------------------------------------------------------------

_LIVE: "set[WorkerHandle]" = set()
_LIVE_LOCK = threading.Lock()


def reap_live_workers(join_timeout_s: float = 10.0) -> int:
    """Terminate (SIGTERM → SIGKILL) every worker this process still holds a
    live handle to; bounded overall by ``join_timeout_s``. The test-suite
    orphan reaper and the atexit sweep both call this — no child survives
    its parent. Returns how many handles were reaped."""
    deadline = time.monotonic() + max(float(join_timeout_s), 0.1)
    with _LIVE_LOCK:
        handles = list(_LIVE)
    for h in handles:
        h.terminate(grace_s=max(min(0.5, deadline - time.monotonic()), 0.05))
    return len(handles)


@atexit.register
def _reap_at_exit() -> None:
    try:
        reap_live_workers(join_timeout_s=5.0)
    except Exception:
        pass


class WorkerHandle:
    """Parent-side handle to one worker child: spawn, framed calls with
    deadlines, heartbeat-age tracking, and SIGTERM→SIGKILL teardown.

    A dedicated reader thread owns every read on the channel: responses are
    routed to their callers by sequence number, heartbeats refresh
    ``heartbeat_age()``, and EOF/corruption fails every pending call with
    :class:`WorkerClosedError` — a dead child never strands a caller.
    """

    def __init__(self, proc: subprocess.Popen, sock: socket.socket,
                 model: str, idx: int, log_path: Optional[str],
                 kill_grace_s: float, log_file=None):
        self.proc = proc
        self.pid = proc.pid
        self.model = model
        self.idx = idx
        self.log_path = log_path
        self.kill_grace_s = float(kill_grace_s)
        self.ready: Dict[str, Any] = {}
        self.checkpoint: Optional[str] = None  # set by spawn()
        self._sock = sock
        self._log_file = log_file
        self._wlock = threading.Lock()
        self._plock = threading.Lock()
        self._pending: Dict[int, list] = {}  # seq -> [Event, response|None]
        self._seq = 0
        self._lost: Optional[str] = None
        self._closed = False
        # terminate() is serialized: the supervisor's kill and a dispatcher's
        # WorkerLostError path can race it, and the thread that escalated to
        # SIGKILL must be the one whose story the worker_exit event tells
        self._term_lock = threading.Lock()
        self._escalated = False
        self._last_frame = time.monotonic()
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"worker-io-{model}-{idx}")
        self._reader.start()

    # ---- spawn -----------------------------------------------------------
    @classmethod
    def spawn(cls, cfg_dict: dict, model: str, idx: int, *,
              checkpoint: Optional[str] = None,
              warm_sizes: Optional[List] = None,
              obs_dir: Optional[str] = None,
              spawn_timeout_s: float = 120.0,
              heartbeat_s: float = 0.5,
              kill_grace_s: float = 3.0,
              expect_digest: Optional[str] = None,
              matmul_precision: Optional[str] = None) -> "WorkerHandle":
        """Launch ``python -m distegnn_tpu.serve.worker`` over a socketpair
        and run the init handshake (config + checkpoint + warm sizes) within
        ``spawn_timeout_s``. Child stderr/stdout land in
        ``<obs_dir>/worker_<model>_<idx>.log`` (a tempdir when tracing is
        off). Any exec/handshake failure — including a params-digest
        mismatch against ``expect_digest``, which would silently break
        cross-process parity — tears the child down and raises
        :class:`WorkerSpawnError`."""
        parent_sock, child_sock = socket.socketpair()
        log_dir = obs_dir or os.path.join(tempfile.gettempdir(),
                                          "distegnn_tpu_workers")
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"worker_{model}_{idx}.log")
        log_f = open(log_path, "ab")
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else pkg_root)
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "distegnn_tpu.serve.worker",
                 "--fd", str(child_sock.fileno())],
                pass_fds=(child_sock.fileno(),),
                stdin=subprocess.DEVNULL, stdout=log_f,
                stderr=subprocess.STDOUT, env=env, close_fds=True)
        except Exception as exc:
            parent_sock.close()
            child_sock.close()
            log_f.close()
            raise WorkerSpawnError(
                f"failed to exec worker {model}/{idx}: {exc}") from exc
        child_sock.close()
        handle = cls(proc, parent_sock, model, idx, log_path, kill_grace_s,
                     log_file=log_f)
        init = {"config": cfg_dict, "model": model, "idx": idx,
                "heartbeat_s": float(heartbeat_s),
                "checkpoint": checkpoint,
                "warm_sizes": [list(s) for s in (warm_sizes or [])],
                "matmul_precision": matmul_precision,
                "obs": {"dir": obs_dir} if obs_dir else {}}
        try:
            ready = handle.call("init", init, timeout_s=spawn_timeout_s)
        except WorkerError as exc:
            handle.terminate(grace_s=0.5)
            raise WorkerSpawnError(
                f"worker {model}/{idx} failed to initialize: {exc} "
                f"(child log: {log_path})") from exc
        if expect_digest and ready.get("params_digest") != expect_digest:
            handle.terminate(grace_s=0.5)
            raise WorkerSpawnError(
                f"worker {model}/{idx} params digest "
                f"{ready.get('params_digest')} != parent {expect_digest} — "
                f"non-deterministic init or env drift would break parity")
        handle.ready = dict(ready or {})
        # which version this child came up on — WorkerReplica.start_queue
        # compares it against current_checkpoint to catch a hot-swap that
        # deferred WHILE this spawn was in flight (the child captured the
        # pre-swap checkpoint seconds ago)
        handle.checkpoint = checkpoint
        with _LIVE_LOCK:
            _LIVE.add(handle)
        _obs_event("gateway/worker_spawn", model=model, replica=idx,
                   pid=handle.pid, params_digest=ready.get("params_digest"),
                   warmed=ready.get("warmed"))
        return handle

    # ---- channel ---------------------------------------------------------
    def _read_loop(self) -> None:
        try:
            while True:
                kind, seq, obj = recv_frame(self._sock, None)
                self._last_frame = time.monotonic()
                if kind == FRAME_RESPONSE:
                    with self._plock:
                        slot = self._pending.pop(seq, None)
                    if slot is not None:
                        slot[1] = obj
                        slot[0].set()
                # FRAME_HEARTBEAT only refreshes _last_frame
        except WorkerError as exc:
            self._mark_lost(str(exc))

    def _mark_lost(self, reason: str) -> None:
        if self._lost is None:
            self._lost = reason
        with self._plock:
            pending, self._pending = self._pending, {}
        for slot in pending.values():
            slot[0].set()  # slot[1] stays None -> WorkerClosedError

    @property
    def lost_reason(self) -> Optional[str]:
        return self._lost

    def call(self, op: str, payload: Optional[dict] = None,
             timeout_s: float = 60.0):
        """One framed request/response round-trip with a hard deadline.
        Raises :class:`WorkerClosedError` (dead channel),
        :class:`WorkerTimeoutError` (deadline), or the remote error mapped
        back to its serve type when the child executed but failed."""
        if self._lost is not None:
            raise WorkerClosedError(
                f"worker {self.model}/{self.idx} (pid {self.pid}) channel "
                f"lost: {self._lost}")
        with self._plock:
            self._seq += 1
            seq = self._seq
            slot = [threading.Event(), None]
            self._pending[seq] = slot
        msg = {"op": op}
        if payload:
            msg.update(payload)
        try:
            send_frame(self._sock, self._wlock, FRAME_REQUEST, seq, msg)
        except WorkerError as exc:
            with self._plock:
                self._pending.pop(seq, None)
            self._mark_lost(str(exc))
            raise WorkerClosedError(
                f"worker {self.model}/{self.idx} (pid {self.pid}) channel "
                f"lost: {exc}") from None
        if not slot[0].wait(max(float(timeout_s), 0.001)):
            with self._plock:
                self._pending.pop(seq, None)
            raise WorkerTimeoutError(
                f"worker {self.model}/{self.idx} (pid {self.pid}) op "
                f"{op!r} exceeded its {float(timeout_s):.1f} s deadline")
        resp = slot[1]
        if resp is None:
            raise WorkerClosedError(
                f"worker {self.model}/{self.idx} (pid {self.pid}) channel "
                f"lost: {self._lost}")
        if not resp.get("ok"):
            raise self._remote_error(op, resp)
        return resp.get("result")

    def _remote_error(self, op: str, resp: dict) -> Exception:
        etype = str(resp.get("etype", "Exception"))
        emsg = str(resp.get("error", ""))
        known: Dict[str, type] = {"ValueError": ValueError}
        try:
            from distegnn_tpu.serve import buckets as _bk
            from distegnn_tpu.serve import engine as _eng

            known.update({
                "RolloutOverflowError": _eng.RolloutOverflowError,
                "MixedRolloutStepsError": _eng.MixedRolloutStepsError,
                "CanaryError": _eng.CanaryError,
                "BucketOverflowError": _bk.BucketOverflowError,
            })
        except Exception:
            pass
        cls = known.get(etype)
        prefix = f"worker {self.model}/{self.idx} op {op!r}: "
        if cls is not None:
            return cls(prefix + emsg)
        return WorkerRemoteError(prefix + f"{etype}: {emsg}")

    # ---- liveness --------------------------------------------------------
    def proc_alive(self) -> bool:
        return self.proc.poll() is None

    def heartbeat_age(self) -> float:
        """Seconds since the LAST frame of any kind arrived. A SIGSTOPped
        (or truly GIL-wedged) child stops beating; the supervisor reads this
        through WorkerQueue.heartbeat_age for staleness-based wedge
        detection."""
        return time.monotonic() - self._last_frame

    # ---- chaos (testing/serve_faults.py) ---------------------------------
    def kill9(self) -> None:
        """SIGKILL the child outright — the crash the isolation exists for."""
        try:
            os.kill(self.pid, signal.SIGKILL)
        except OSError:
            pass

    def sigstop(self) -> None:
        """SIGSTOP the child: heartbeats stop, the process stays alive — a
        true wedge only staleness detection can see."""
        try:
            os.kill(self.pid, signal.SIGSTOP)
        except OSError:
            pass

    def sigcont(self) -> None:
        try:
            os.kill(self.pid, signal.SIGCONT)
        except OSError:
            pass

    # ---- teardown --------------------------------------------------------
    def terminate(self, grace_s: Optional[float] = None) -> Optional[int]:
        """SIGTERM → bounded wait → SIGKILL → reap. Idempotent; always reaps
        the zombie (``proc.wait``) and closes the channel + log file.
        SIGKILL also takes down SIGSTOPped children (pending SIGTERM never
        delivers to a stopped process). Returns the child's returncode."""
        grace = self.kill_grace_s if grace_s is None else float(grace_s)
        with self._term_lock:
            if self.proc.poll() is None:
                try:
                    self.proc.terminate()
                except OSError:
                    pass
                try:
                    self.proc.wait(timeout=max(grace, 0.05))
                except subprocess.TimeoutExpired:
                    self._escalated = True
                    try:
                        self.proc.kill()
                    except OSError:
                        pass
                    try:
                        self.proc.wait(timeout=10.0)
                    except subprocess.TimeoutExpired:
                        pass
            else:
                try:
                    self.proc.wait(timeout=0.1)  # reap the zombie
                except subprocess.TimeoutExpired:
                    pass
            self._mark_lost("terminated")
            first = not self._closed
            self._closed = True
            if first:
                try:
                    self._sock.close()
                except OSError:
                    pass
                if self._log_file is not None:
                    try:
                        self._log_file.close()
                    except OSError:
                        pass
                with _LIVE_LOCK:
                    _LIVE.discard(self)
                _obs_event("gateway/worker_exit", model=self.model,
                           replica=self.idx, pid=self.pid,
                           returncode=self.proc.returncode,
                           escalated=self._escalated)
        return self.proc.returncode


# ---- child side -------------------------------------------------------------

def _child_dispatch(engine, op: str, msg: dict, state: dict):
    if op == "ping":
        return {"pid": os.getpid()}
    if op == "predict":
        from distegnn_tpu.serve.buckets import Bucket

        b = msg.get("bucket")
        return engine.predict_batch(
            msg["graphs"], bucket=Bucket(*b) if b else None,
            request_ids=msg.get("request_ids") or None)
    if op == "rollout":
        return engine.rollout_batch(
            msg["scenes"], request_ids=msg.get("request_ids") or None)
    if op == "warmup":
        warmed = engine.warmup([tuple(s) for s in msg.get("sizes") or []])
        return [[b.n, b.e] for b in warmed]
    if op == "swap":
        # blue/green unit, child side: checksummed restore against the LIVE
        # params tree, canary on the warmed rungs, then the atomic flip;
        # the pre-swap params stay held for swap_rollback
        from distegnn_tpu.serve.buckets import Bucket
        from distegnn_tpu.train.checkpoint import restore_params

        new_params = restore_params(str(msg["checkpoint"]), engine.params)
        rungs = [Bucket(*r) for r in msg.get("rungs") or []]
        checked = engine.canary(new_params, rungs)
        state["prev_params"] = engine.params
        engine.params = new_params
        return {"rungs": checked, "params_digest": engine.params_digest()}
    if op == "swap_rollback":
        if state.get("prev_params") is not None:
            engine.params = state.pop("prev_params")
        return {"params_digest": engine.params_digest()}
    if op == "shutdown":
        return {"pid": os.getpid()}
    raise ValueError(f"unknown worker op {op!r}")


def _child_serve(sock: socket.socket) -> int:
    parent_pid = os.getppid()
    wlock = threading.Lock()
    # the parent-controlled drain governs shutdown: a Ctrl-C delivered to
    # the whole process group must not race it, and SIGTERM (the parent's
    # escalation step 1) exits cleanly so obs buffers flush
    signal.signal(signal.SIGINT, signal.SIG_IGN)
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))

    # The parent-death watchdog starts BEFORE the init handshake: the init
    # window (jax import + engine build) can run for tens of seconds, and a
    # parent that dies during it must still take the child down promptly —
    # "no orphans" cannot wait for the init recv deadline to expire. The
    # same thread upgrades to the heartbeat sender once init completes.
    stop_beat = threading.Event()
    beat = {"interval_s": 0.5, "send": False}

    def _beat() -> None:
        while not stop_beat.wait(beat["interval_s"]):
            if os.getppid() != parent_pid:
                os._exit(3)  # parent died: never orphan
            if beat["send"]:
                try:
                    send_frame(sock, wlock, FRAME_HEARTBEAT, 0,
                               {"ts": time.time()})
                except Exception:
                    os._exit(3)

    threading.Thread(target=_beat, daemon=True,
                     name="worker-heartbeat").start()

    kind, seq, init = recv_frame(sock, deadline=time.monotonic() + 300.0)
    if kind != FRAME_REQUEST or init.get("op") != "init":
        sys.stderr.write(f"worker: expected init frame, got {init!r}\n")
        return 1
    model_name = str(init.get("model", "default"))
    idx = int(init.get("idx", 0))
    heartbeat_s = max(float(init.get("heartbeat_s", 0.5)), 0.01)

    try:
        prec = init.get("matmul_precision")
        if prec:
            import jax

            jax.config.update("jax_default_matmul_precision", prec)
        obs_cfg = init.get("obs") or {}
        if obs_cfg.get("dir"):
            from distegnn_tpu.obs import trace as _trace

            _trace.configure(
                log_dir=obs_cfg["dir"], enable=True,
                filename=f"events_worker_{model_name}_{idx}.jsonl",
                tags={"worker": f"{model_name}/{idx}"})
        from distegnn_tpu.config import ConfigDict
        from distegnn_tpu.serve import engine_with_params_from_config

        cfg = ConfigDict(init["config"])
        _model, engine, _queue, _params = engine_with_params_from_config(
            cfg, checkpoint=init.get("checkpoint"))
        warm_sizes = [tuple(s) for s in init.get("warm_sizes") or []]
        warmed = engine.warmup(warm_sizes) if warm_sizes else []
        send_frame(sock, wlock, FRAME_RESPONSE, seq,
                   {"ok": True,
                    "result": {"pid": os.getpid(),
                               "params_digest": engine.params_digest(),
                               "warmed": [[b.n, b.e] for b in warmed]}})
    except Exception as exc:
        sys.stderr.write("worker: init failed\n" + traceback.format_exc())
        try:
            send_frame(sock, wlock, FRAME_RESPONSE, seq,
                       {"ok": False, "etype": type(exc).__name__,
                        "error": str(exc)[:2000]})
        except WorkerError:
            pass
        return 1

    beat["interval_s"] = heartbeat_s
    beat["send"] = True

    state: dict = {}
    try:
        while True:
            try:
                kind, seq, msg = recv_frame(sock, None)
            except WorkerClosedError:
                return 0  # parent closed the channel: clean exit
            if kind != FRAME_REQUEST:
                continue
            op = str(msg.get("op"))
            try:
                result = _child_dispatch(engine, op, msg, state)
                send_frame(sock, wlock, FRAME_RESPONSE, seq,
                           {"ok": True, "result": result})
            except Exception as exc:
                sys.stderr.write(f"worker: op {op!r} failed\n"
                                 + traceback.format_exc())
                try:
                    send_frame(sock, wlock, FRAME_RESPONSE, seq,
                               {"ok": False, "etype": type(exc).__name__,
                                "error": str(exc)[:2000]})
                except WorkerError:
                    return 1
            if op == "shutdown":
                return 0
    finally:
        stop_beat.set()
        try:
            from distegnn_tpu import obs

            obs.flush()
        except Exception:
            pass


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="distegnn_tpu.serve.worker",
        description="Serving worker child (spawned by WorkerHandle; not a "
                    "user-facing entry point)")
    parser.add_argument("--fd", type=int, required=True,
                        help="inherited socketpair fd (the IPC channel)")
    args = parser.parse_args(argv)
    sock = socket.socket(fileno=args.fd)
    return _child_serve(sock)


if __name__ == "__main__":
    sys.exit(main())
