"""distegnn_tpu.serve — bucketed-batching inference (docs/SERVING.md).

Request path: HTTP gateway (transport.py) -> ModelRegistry route ->
RequestQueue.submit(graph) -> bucket ladder -> micro-batcher ->
InferenceEngine per-bucket compile cache -> ServeFuture result. All
components of one model share one ServeMetrics snapshot; the gateway adds
process-wide admission/latency series and a /metrics scrape endpoint.
"""

from distegnn_tpu.serve.buckets import (Bucket, BucketLadder,
                                        BucketOverflowError, synthetic_graph)
from distegnn_tpu.serve.engine import (InferenceEngine,
                                       MixedRolloutStepsError,
                                       RolloutOverflowError)
from distegnn_tpu.serve.metrics import ServeMetrics
from distegnn_tpu.serve.prep import PrepPlan, PrepResult, SessionPrepCache
from distegnn_tpu.serve.queue import (DispatcherCrashError, QueueFullError,
                                      RequestQueue, RequestTimeoutError,
                                      ServeFuture, WorkerLostError)
from distegnn_tpu.serve.replica import (ModelUnavailableError, Replica,
                                        ReplicaSet, WorkerQueue,
                                        WorkerReplica)
from distegnn_tpu.serve.supervisor import ReplicaSupervisor
from distegnn_tpu.serve.tiled import TiledExecutor, TiledOverflowError

__all__ = [
    "Bucket", "BucketLadder", "BucketOverflowError", "synthetic_graph",
    "InferenceEngine", "MixedRolloutStepsError", "RolloutOverflowError",
    "ServeMetrics", "PrepPlan", "PrepResult", "SessionPrepCache",
    "QueueFullError", "RequestQueue", "RequestTimeoutError", "ServeFuture",
    "DispatcherCrashError", "WorkerLostError", "ModelUnavailableError",
    "Replica", "ReplicaSet", "WorkerQueue", "WorkerReplica",
    "ReplicaSupervisor", "SwapError", "SwapInProgressError",
    "TiledExecutor", "TiledOverflowError",
    "engine_from_config", "engine_with_params_from_config", "Gateway",
    "ModelEntry", "ModelRegistry", "PayloadError",
]


def __getattr__(name):
    # transport/registry import lazily: the in-process serve stack must not
    # pay for (or depend on) the HTTP layer, and registry->engine_from_config
    # would otherwise be a load-time cycle through this package __init__
    if name in ("Gateway", "PayloadError"):
        from distegnn_tpu.serve import transport

        return getattr(transport, name)
    if name in ("ModelEntry", "ModelRegistry", "SwapError",
                "SwapInProgressError"):
        from distegnn_tpu.serve import registry

        return getattr(registry, name)
    raise AttributeError(name)


def engine_from_config(cfg, model, params, metrics=None):
    """Build (InferenceEngine, RequestQueue) from a config's ``serve:``
    section (distegnn_tpu.config defaults; queue NOT started)."""
    s = cfg.serve
    ladder = BucketLadder(
        node_floor=s.node_floor, edge_floor=s.edge_floor, growth=s.growth,
        node_multiple=s.node_multiple, edge_multiple=s.edge_multiple,
        max_nodes=s.max_nodes, max_edges=s.max_edges)
    metrics = metrics or ServeMetrics()
    layout = None
    if cfg.get("model") and cfg.model.get("edge_impl") in ("fused",
                                                             "fused_stack"):
        # fused/fused_stack models only consume blocked split_remote batches
        layout = dict(edge_block=int(cfg.data.edge_block),
                      split_remote=True)
    engine = InferenceEngine(
        model, params, ladder=ladder, max_batch=s.max_batch,
        cache_size=s.cache_size, donate=s.donate, metrics=metrics,
        rollout_opts=(s.rollout.to_dict() if s.get("rollout") else None),
        layout_opts=layout,
        session_cache=int(s.get("session_cache", 0) or 0),
        session_cache_bytes=int(s.get("session_cache_bytes", 0) or 0),
        tiled=(s.tiled.to_dict() if s.get("tiled")
               and s.tiled.get("enable") else None))
    q = RequestQueue(
        engine, batch_deadline_ms=s.batch_deadline_ms,
        queue_capacity=s.queue_capacity,
        request_timeout_ms=s.request_timeout_ms,
        result_margin_s=float(s.get("result_margin_s", 30.0)),
        metrics=metrics)
    return engine, q


def engine_with_params_from_config(cfg, metrics=None, checkpoint=None):
    """The registry's full deterministic model+engine+params recipe, shared
    with the process-worker child (serve/worker.py) so BOTH sides of the
    IPC boundary hold bitwise-identical params: seeded ``model.init`` on a
    ladder-padded synthetic graph, then an optional checksummed checkpoint
    restore. ``checkpoint`` overrides ``cfg.model.checkpoint`` — the worker
    respawn path after a hot-swap, where the child must come back up on the
    SWAPPED version, not the config's original. Returns
    ``(model, engine, queue, params)``; the queue is NOT started."""
    import jax

    from distegnn_tpu.models.registry import get_model

    model = get_model(cfg.model, dataset_name=cfg.data.dataset_name)
    metrics = metrics or ServeMetrics()
    engine, queue = engine_from_config(cfg, model, params=None,
                                       metrics=metrics)
    feat_nf = int(cfg.model.node_feat_nf)
    edge_nf = int(cfg.model.edge_attr_nf)
    seed = int(cfg.get("seed", 0) or 0)
    g = synthetic_graph(2, seed=seed, feat_nf=feat_nf, edge_attr_nf=edge_nf)
    b0 = engine.ladder.bucket_of_graph(g)
    init_batch, _ = engine.ladder.pad_batch([g], b0, 1,
                                            **engine._layout_opts)
    params = model.init(jax.random.PRNGKey(seed), init_batch)
    ckpt = checkpoint if checkpoint is not None else cfg.model.get("checkpoint")
    if ckpt:
        from distegnn_tpu.train.checkpoint import restore_params

        params = restore_params(str(ckpt), params)
    engine.params = params
    return model, engine, queue, params
