"""Replica supervisor — heartbeat, wedge detection, backoff, breaker.

One daemon thread per :class:`~distegnn_tpu.serve.replica.ReplicaSet` ticks
every ``heartbeat_s`` and drives each replica's state machine:

  - **crash**: the dispatcher thread is gone (``queue.alive()`` False while
    the replica is supposed to be running). The queue's own crash budget
    already failed its futures; the supervisor claims anything still
    tracked, fails it over to survivors, and schedules a restart.
  - **wedge**: the dispatcher is alive but making no batch progress
    (``queue.depth() > 0`` and ``queue.last_progress`` older than
    ``wedge_timeout_s`` — a stuck device call). The supervisor claims the
    in-flight work for failover FIRST (at-most-once: claims are
    compare-and-pop), then ``kill()``s the queue so any straggler future
    fails typed instead of hanging, and schedules a restart. The abandoned
    thread dies at its next kill-flag check; its late results are dropped
    by the outer futures' first-wins resolution.
  - **restart**: after an exponential backoff (``backoff_base_s`` doubling
    per consecutive failure, capped at ``backoff_max_s``) the replica gets
    a fresh RequestQueue on its existing warmed engine. ``breaker_threshold``
    consecutive failures open the per-replica circuit breaker: the replica
    sits out ``breaker_cooldown_s`` before the next (half-open) attempt.
    A replica that stays healthy for ``healthy_reset_s`` gets its failure
    count cleared (breaker closes).

Process-backed replicas (``serve.workers: process``) run under the SAME
state machine with two additions: liveness also covers the child process
(``WorkerQueue.alive()`` folds in a process poll, so a SIGKILL'd child is
a plain **crash**), and a second wedge signal — heartbeat staleness. A
SIGSTOPped or truly GIL-wedged child stops beating even when the queue is
idle, which ``depth() > 0`` progress tracking can never see; when
``heartbeat_age()`` (duck-typed, None for thread replicas) exceeds
``worker_heartbeat_timeout_s`` the replica is marked down as a wedge.
Every mark-down of a process replica kills its queue, which escalates
SIGTERM → SIGKILL with zombie reaping (``WorkerQueue.kill``) — SIGKILL is
what actually fells a stopped child. The respawn path then goes through
the same backoff/breaker math; a spawn failure degrades to an in-process
queue (``gateway/worker_degraded``) inside ``restart_queue`` rather than
shedding the model.

Every transition emits a ``gateway/replica_*`` obs event. ``tick()`` is
public so tests drive the state machine deterministically with synthetic
clocks instead of sleeping through real heartbeats.

Defaults are deliberately conservative (wedge_timeout 60 s ≫ the default
request_timeout + result_margin 31 s), so single-replica deployments keep
their existing hard-deadline 504 semantics unless tuned tighter.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from distegnn_tpu import obs


class ReplicaSupervisor:
    def __init__(self, replica_set, *,
                 heartbeat_s: float = 0.25,
                 wedge_timeout_s: float = 60.0,
                 worker_heartbeat_timeout_s: float = 10.0,
                 backoff_base_s: float = 0.5,
                 backoff_max_s: float = 30.0,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 30.0,
                 healthy_reset_s: float = 60.0):
        self.rset = replica_set
        self.heartbeat_s = float(heartbeat_s)
        self.wedge_timeout_s = float(wedge_timeout_s)
        self.worker_heartbeat_timeout_s = float(worker_heartbeat_timeout_s)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.healthy_reset_s = float(healthy_reset_s)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> "ReplicaSupervisor":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"replica-supervisor-{self.rset.model}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            try:
                self.tick()
            except Exception as exc:  # supervision must never die silently
                obs.log(f"serve: supervisor tick failed for "
                        f"{self.rset.model}: {exc!r}")

    # ---- state machine ----------------------------------------------------
    def tick(self, now: Optional[float] = None) -> None:
        """One heartbeat pass (public: tests call it with synthetic clocks)."""
        if not self.rset._supervised:
            return  # set is stopping/stopped — nothing to supervise
        now = time.perf_counter() if now is None else now
        for r in self.rset.replicas:
            if r.state == "running":
                if not r.queue.alive():
                    self._mark_down(r, "crash", now)
                elif self._heartbeat_stale(r):
                    self._mark_down(r, "wedge", now)
                elif (r.queue.depth() > 0
                      and now - r.queue.last_progress > self.wedge_timeout_s):
                    self._mark_down(r, "wedge", now)
                elif r.failures and now - r.started_at >= self.healthy_reset_s:
                    r.failures = 0
                    obs.event("gateway/replica_breaker_close",
                              model=self.rset.model, replica=r.idx)
                if r.state == "running":
                    # process replicas: heal a worker left on a stale
                    # checkpoint by a swap that raced its respawn
                    rec = getattr(r, "reconcile_checkpoint", None)
                    if callable(rec):
                        rec()
            elif r.state in ("backoff", "broken"):
                if now >= r.next_restart_at:
                    self._restart(r, now)

    def _heartbeat_stale(self, r) -> bool:
        """True when a process-backed replica's child has stopped beating
        (SIGSTOP / hard GIL wedge). Duck-typed: thread queues have no
        heartbeat_age and return None here. Ages are real ``monotonic``
        seconds — synthetic test clocks don't apply to this signal."""
        fn = getattr(r.queue, "heartbeat_age", None)
        if not callable(fn):
            return False
        age = fn()
        return age is not None and age > self.worker_heartbeat_timeout_s

    def _mark_down(self, r, reason: str, now: float) -> None:
        r.last_reason = reason
        r.failures += 1
        broken = r.failures >= self.breaker_threshold
        r.state = "broken" if broken else "backoff"
        delay = (self.breaker_cooldown_s if broken else
                 min(self.backoff_base_s * (2 ** (r.failures - 1)),
                     self.backoff_max_s))
        r.next_restart_at = now + delay
        obs.event(f"gateway/replica_{reason}", model=self.rset.model,
                  replica=r.idx, failures=r.failures, state=r.state,
                  restart_in_s=round(delay, 3))
        if broken:
            obs.event("gateway/replica_breaker_open", model=self.rset.model,
                      replica=r.idx, failures=r.failures,
                      cooldown_s=self.breaker_cooldown_s)
        # claim in-flight work for failover BEFORE poisoning the queue, so
        # each record is claimed exactly once (supervisor vs done-callback);
        # per-request gateway/replica_failover events carry the detail —
        # obs.log would pollute stdout-contract scripts (traffic_gen)
        self.rset.fail_over_replica(r, reason=reason)
        if reason == "wedge" or getattr(r.queue, "backend", "thread") == "process":
            # wedge: poison stragglers so no future hangs. Process backend:
            # ALWAYS kill — WorkerQueue.kill escalates SIGTERM → SIGKILL
            # (the only signal a SIGSTOPped child honors) and reaps the
            # zombie, so a dead child never lingers between restarts.
            r.queue.kill(reason=f"marked down ({reason}) by supervisor")

    def _restart(self, r, now: float) -> None:
        r.restarts += 1
        self.rset.metrics.replica_restarted()
        try:
            r.restart_queue()
        except Exception as exc:
            # counts as another failure: breaker math applies unchanged
            obs.log(f"serve: {self.rset.model} replica {r.idx} restart "
                    f"failed: {exc!r}")
            self._mark_down(r, "restart_failed", now)
            return
        if not self.rset._supervised:
            # a stop() raced us while restart_queue was blocked (a worker
            # spawn can take seconds): never revive a queue after drain
            # has begun
            r.queue.stop(drain=False, join_timeout_s=2.0)
            r.state = "stopped"
            obs.event("gateway/replica_restart_aborted",
                      model=self.rset.model, replica=r.idx)
            return
        r.state = "running"
        r.started_at = now
        obs.event("gateway/replica_restart", model=self.rset.model,
                  replica=r.idx, attempt=r.restarts, failures=r.failures)
