"""Shape bucketing — map an incoming graph's (N, E) to a small padded ladder.

XLA compiles one program per shape, so a serving layer admitting arbitrary
graphs must quantize sizes or it compiles forever. The training pipeline
already solves this with linear buckets (`data.node_bucket`/`edge_bucket`,
ops/graph.pad_graphs); serving traffic spans orders of magnitude, so the
ladder here is GEOMETRIC: rung k holds

    n_k = round_up(floor_n * growth^k, node_multiple)
    e_k = round_up(floor_e * growth^k, edge_multiple)

with N and E bucketed INDEPENDENTLY (a dense small graph and a sparse big one
should not share a program that pads both axes to the max). Worst-case pad
waste per axis is the growth factor; the rung count is logarithmic in the
admitted size range, which bounds both compile time and compile-cache size.

Padding itself reuses `ops/graph.pad_graphs` — the exact layout the models
are trained and tested on (padded edges point at node N-1, row-sorted masks),
so a served response is numerically the model's answer on the unpadded graph
(padding invariance is asserted in tests/test_models.py and test_serve.py).
"""

from __future__ import annotations

import math
from typing import List, NamedTuple, Sequence, Tuple

import numpy as np

from distegnn_tpu.ops.graph import GraphBatch, pad_graphs


class Bucket(NamedTuple):
    """One rung of the ladder: the padded (nodes, edges) of a compiled shape."""

    n: int
    e: int


class BucketOverflowError(ValueError):
    """Request exceeds the largest admitted shape — surfaced, never truncated."""


class BucketLadder:
    """Geometric (N, E) ladder with linear rounding at each rung.

    Args:
      node_floor/edge_floor: size of rung 0 (smallest compiled shape).
      growth: geometric step between rungs (> 1). 2.0 halves the rung count
        of 1.5 at the price of up to 2x pad waste on each axis.
      node_multiple/edge_multiple: every rung rounds up to these (the
        training bucket quanta — keeps rungs aligned with loader shapes).
      max_nodes/max_edges: admission bound; larger requests raise
        BucketOverflowError instead of compiling an unbounded shape.
    """

    def __init__(self, node_floor: int = 64, edge_floor: int = 256,
                 growth: float = 2.0, node_multiple: int = 8,
                 edge_multiple: int = 128, max_nodes: int = 65536,
                 max_edges: int = 1 << 20):
        if growth <= 1.0:
            raise ValueError(f"growth must be > 1 (got {growth})")
        if node_floor < 1 or edge_floor < 1:
            raise ValueError("node_floor/edge_floor must be >= 1")
        self.node_floor = int(node_floor)
        self.edge_floor = int(edge_floor)
        self.growth = float(growth)
        self.node_multiple = int(node_multiple)
        self.edge_multiple = int(edge_multiple)
        self.max_nodes = int(max_nodes)
        self.max_edges = int(max_edges)

    def _rung(self, size: int, floor: int, multiple: int, cap: int,
              axis: str) -> int:
        if size > cap:
            raise BucketOverflowError(
                f"request {axis}={size} exceeds the ladder cap {cap}; raise "
                f"serve.max_{axis}, enable the tiled executor (serve.tiled, "
                f"serves any node count through fixed-shape tiles), or "
                f"shard the request")
        k = max(0, math.ceil(math.log(max(size, 1) / floor, self.growth)))
        # float log can land one rung low on exact powers — fix up locally
        while floor * self.growth ** k < size:
            k += 1
        r = int(math.ceil(floor * self.growth ** k))
        r = ((r + multiple - 1) // multiple) * multiple
        return min(r, ((cap + multiple - 1) // multiple) * multiple)

    def bucket_for(self, n_nodes: int, n_edges: int) -> Bucket:
        """Smallest rung admitting an (n_nodes, n_edges) graph."""
        return Bucket(
            self._rung(n_nodes, self.node_floor, self.node_multiple,
                       self.max_nodes, "nodes"),
            self._rung(n_edges, self.edge_floor, self.edge_multiple,
                       self.max_edges, "edges"),
        )

    def bucket_of_graph(self, graph: dict) -> Bucket:
        """Bucket for a pad_graphs-style graph dict."""
        return self.bucket_for(int(graph["loc"].shape[0]),
                               int(graph["edge_index"].shape[1]))

    def ladder(self, upto_nodes: int, upto_edges: int) -> List[Bucket]:
        """All distinct rungs admitting sizes up to the given bounds —
        the warmup enumeration."""
        out: List[Bucket] = []
        n = e = 1
        ns, es = [], []
        while True:
            r = self._rung(n, self.node_floor, self.node_multiple,
                           self.max_nodes, "nodes")
            if not ns or r != ns[-1]:
                ns.append(r)
            if r >= min(upto_nodes, self.max_nodes):
                break
            n = r + 1
        while True:
            r = self._rung(e, self.edge_floor, self.edge_multiple,
                           self.max_edges, "edges")
            if not es or r != es[-1]:
                es.append(r)
            if r >= min(upto_edges, self.max_edges):
                break
            e = r + 1
        for rn in ns:
            for re in es:
                out.append(Bucket(rn, re))
        return out

    # ---- padding ---------------------------------------------------------
    def pad_batch(self, graphs: Sequence[dict], bucket: Bucket,
                  batch_pad: int, *, edge_block: int = 0, edge_tile: int = 512,
                  split_remote: bool = False) -> Tuple[GraphBatch, int]:
        """Pack ``graphs`` (all admitted by ``bucket``) into one GraphBatch
        of EXACTLY (batch_pad, bucket.n, bucket.e).

        The batch axis is padded by replicating the first graph — replicas
        are valid graphs (no NaN hazards from empty-graph means) and their
        outputs are simply discarded; returns (batch, n_real).

        ``edge_block > 0`` emits the BLOCKED layout instead (the fused edge
        pipeline's input; ``split_remote`` adds the compact out-of-window
        list). Node count snaps up from bucket.n to a block multiple;
        edges_per_block and the remote width auto-derive per batch — a
        serving layer has no dataset to scan, so the ENGINE keys its compile
        cache on the resulting batch shapes rather than on the rung alone.
        """
        n_real = len(graphs)
        if n_real == 0:
            raise ValueError("pad_batch: empty batch")
        if n_real > batch_pad:
            raise ValueError(f"pad_batch: {n_real} graphs > batch_pad {batch_pad}")
        filled = list(graphs) + [graphs[0]] * (batch_pad - n_real)
        if edge_block:
            nb = (bucket.n + edge_block - 1) // edge_block
            if split_remote:
                nb = max(nb, 3)  # fused kernel's VMEM window spans 3 blocks
            batch = pad_graphs(filled, max_nodes=nb * edge_block,
                               edge_block=edge_block, edge_tile=edge_tile,
                               compute_pair=False, split_remote=split_remote)
        else:
            batch = pad_graphs(filled, max_nodes=bucket.n, max_edges=bucket.e,
                               node_bucket=1, edge_bucket=1)
        return batch, n_real


def synthetic_graph(n: int, radius: float = 0.35, seed: int = 0,
                    feat_nf: int = 1, edge_attr_nf: int = 2) -> dict:
    """A random radius graph in pad_graphs dict form — shared by the serve
    tests and the bench harness (kept here so both draw the same workload)."""
    from distegnn_tpu.ops.radius import radius_graph_np

    rng = np.random.default_rng(seed)
    loc = rng.uniform(0, 1, size=(n, 3)).astype(np.float32)
    vel = (rng.normal(size=(n, 3)) * 0.05).astype(np.float32)
    ei = radius_graph_np(loc, radius)
    if ei.shape[1] == 0:  # guarantee at least one edge (self-loop-free pair)
        ei = np.array([[0, 1], [1, 0]], np.int32).T.reshape(2, 2)
    d = np.linalg.norm(loc[ei[0]] - loc[ei[1]], axis=1)[:, None]
    feat = np.linalg.norm(vel, axis=1, keepdims=True).astype(np.float32)
    feat = np.repeat(feat, feat_nf, axis=1)[:, :feat_nf]
    return {
        "node_feat": feat,
        "loc": loc, "vel": vel, "target": loc,
        "edge_index": ei.astype(np.int32),
        "edge_attr": np.repeat(d, edge_attr_nf, axis=1).astype(np.float32),
    }
