"""HTTP transport front-end — the serving stack's network edge.

A stdlib-only gateway (``http.server.ThreadingHTTPServer``, zero new
dependencies) over one or more :class:`~distegnn_tpu.serve.queue.RequestQueue`
instances routed by a :class:`~distegnn_tpu.serve.registry.ModelRegistry`:

  POST /v1/models/<name>/predict   JSON graph -> prediction (+ bucket,
                                   queue_ms, compute_ms, batch_filled);
                                   an optional ``session_id`` routes graph
                                   prep through the engine's session cache
  POST /v1/models/<name>/rollout   JSON scene (positions, steps, optional
                                   velocities/node_mask) -> K-step
                                   trajectory; 501 unless the model was
                                   built with serve.rollout
  GET  /v1/models                  routing table: rungs, warmup state, depth
  GET  /metrics                    Prometheus text: the process-wide obs
                                   MetricsRegistry + each model's serve
                                   registry (per-model name prefix)
  GET  /healthz                    process up (always 200)
  GET  /readyz                     200 only when accepting AND every model
                                   is warmed with a live dispatcher

Admission control is layered: a gateway-level ``max_inflight`` gate sheds
(429) BEFORE a request touches a queue; a full ingress maps
``QueueFullError`` -> 429; an oversize graph maps ``BucketOverflowError``
-> 413; a queued-deadline or hard-deadline expiry maps
``RequestTimeoutError`` -> 504. Every error body is JSON
(``{"error": str, "type": str}``) — a client never sees a hung socket or an
HTML traceback.

Graceful drain (the PR-3 preemption contract, at the serving edge): SIGTERM
flips ``/readyz`` to 503 and stops admitting predicts (503), drains every
queue via ``RequestQueue.stop(drain=True)`` so EVERY accepted request
resolves with a real status (200/429/504), waits for in-flight handlers,
then stops the accept loop — the process exits 0.

Elasticity and streaming (the SLO-driven elasticity PR):
``POST .../rollout?stream=1`` answers with HTTP chunked transfer — one
NDJSON line per ``chunk_steps``-step trajectory slice, so step 1 arrives
while step 500 is still computing, and a client disconnect cancels the
remaining compute at the next chunk boundary. Admission is priority-aware:
predicts are ``interactive``, rollouts are ``bulk`` (header-overridable);
bulk is capped at ``bulk_max_inflight_frac`` of the slots and deferred
outright while the rolling SLO window is degraded. A
:class:`~distegnn_tpu.serve.autoscale.ReplicaAutoscaler` (opt-in via
``serve.autoscale.enable``) grows/shrinks each model's replica fleet live
from the same window.

Every request runs inside an obs span (``serve/http`` with route/status
attrs) and lands in per-route latency reservoirs plus shed/timeout counters
in the metrics registry (the process-global obs registry by default), so
``GET /metrics`` is the live scrape endpoint ROADMAP's obs item asked for.
"""

from __future__ import annotations

import base64
import json
import queue as _pyqueue
import signal
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlsplit

import numpy as np

from distegnn_tpu import obs
from distegnn_tpu.obs.metrics import MetricsRegistry, _prom_name
from distegnn_tpu.serve.autoscale import ReplicaAutoscaler
from distegnn_tpu.serve.buckets import BucketOverflowError
from distegnn_tpu.serve.engine import RolloutOverflowError
from distegnn_tpu.serve.queue import (QueueFullError, RequestTimeoutError,
                                      StreamSink)
from distegnn_tpu.serve.registry import (ModelRegistry, SwapError,
                                         SwapInProgressError)
from distegnn_tpu.serve.replica import ModelUnavailableError


class PayloadError(ValueError):
    """Malformed request body — the transport's 400."""


_RID_MAX_LEN = 64


def mint_request_id(supplied: Optional[str] = None) -> str:
    """Return the request id for one HTTP request: the client's
    ``X-Request-Id`` when it is a sane token, else a fresh one. Client ids
    are clamped to printable non-space ASCII so they can round-trip through
    headers and the JSONL event stream unescaped."""
    if supplied:
        rid = "".join(c for c in str(supplied).strip()
                      if c.isprintable() and not c.isspace())
        if rid:
            return rid[:_RID_MAX_LEN]
    return uuid.uuid4().hex[:16]


# ---- payload <-> graph dict -------------------------------------------------

def decode_array(spec, dtype: str, name: str) -> np.ndarray:
    """JSON array spec -> numpy: nested lists, or ``{"b64": <base64 of
    little-endian raw bytes>, "shape": [...]}`` for dense payloads."""
    if spec is None:
        raise PayloadError(f"missing '{name}'")
    if isinstance(spec, dict):
        if "b64" not in spec:
            raise PayloadError(f"'{name}': object form needs 'b64' "
                               f"(+ optional 'shape')")
        try:
            raw = base64.b64decode(spec["b64"], validate=True)
        except Exception:
            raise PayloadError(f"'{name}': invalid base64") from None
        try:
            arr = np.frombuffer(raw, dtype=np.dtype(dtype))
            shape = spec.get("shape")
            if shape is not None:
                arr = arr.reshape([int(s) for s in shape])
        except Exception as exc:
            raise PayloadError(f"'{name}': {exc}") from None
        return arr.copy()           # frombuffer views are read-only
    try:
        return np.asarray(spec, dtype=np.dtype(dtype))
    except Exception:
        raise PayloadError(f"'{name}': not a numeric array") from None


def encode_array(arr: np.ndarray, encoding: str):
    if encoding == "b64":
        a = np.ascontiguousarray(arr, dtype="<f4")
        return {"b64": base64.b64encode(a.tobytes()).decode("ascii"),
                "shape": list(a.shape)}
    return np.asarray(arr, dtype=np.float64).tolist()


def graph_from_payload(payload: dict, feat_nf: int,
                       edge_attr_nf: int) -> dict:
    """Validate a predict body and build the pad_graphs-style graph dict
    the queue consumes. Required: ``positions`` [n,3] and either
    ``edge_index`` [2,E] or a ``radius`` (server-side radius graph).
    Optional: ``velocities`` (default zeros), ``node_feat`` (default |v|
    replicated to the model's width), ``edge_attr`` (default pairwise
    distances replicated)."""
    if not isinstance(payload, dict):
        raise PayloadError("body must be a JSON object")
    loc = decode_array(payload.get("positions", payload.get("loc")),
                       "<f4", "positions")
    if loc.ndim != 2 or loc.shape[1] != 3 or loc.shape[0] < 1:
        raise PayloadError(f"'positions' must be [n, 3] "
                           f"(got {list(loc.shape)})")
    n = int(loc.shape[0])
    vel_spec = payload.get("velocities", payload.get("vel"))
    if vel_spec is None:
        vel = np.zeros((n, 3), np.float32)
    else:
        vel = decode_array(vel_spec, "<f4", "velocities")
        if vel.shape != loc.shape:
            raise PayloadError(f"'velocities' must match positions shape "
                               f"(got {list(vel.shape)})")
    ei_spec = payload.get("edge_index")
    if ei_spec is not None:
        ei = decode_array(ei_spec, "<i4", "edge_index")
        if ei.ndim != 2 or ei.shape[0] != 2 or ei.shape[1] < 1:
            raise PayloadError(f"'edge_index' must be [2, E], E >= 1 "
                               f"(got {list(ei.shape)})")
        if int(ei.min()) < 0 or int(ei.max()) >= n:
            raise PayloadError("'edge_index' references nodes outside "
                               f"[0, {n})")
    elif payload.get("radius") is not None:
        from distegnn_tpu.ops.radius import radius_graph_np

        ei = radius_graph_np(loc, float(payload["radius"]))
        if ei.shape[1] == 0:
            if n < 2:
                raise PayloadError("radius graph is empty and n < 2; "
                                   "send 'edge_index' explicitly")
            ei = np.array([[0, 1], [1, 0]], np.int32).T.reshape(2, 2)
    else:
        raise PayloadError("provide 'edge_index' or 'radius'")
    ei = ei.astype(np.int32)

    feat_spec = payload.get("node_feat")
    if feat_spec is None:
        feat = np.linalg.norm(vel, axis=1, keepdims=True).astype(np.float32)
        feat = np.repeat(feat, max(feat_nf, 1), axis=1)[:, :max(feat_nf, 1)]
    else:
        feat = decode_array(feat_spec, "<f4", "node_feat")
        if feat.ndim != 2 or feat.shape[0] != n or feat.shape[1] != feat_nf:
            raise PayloadError(f"'node_feat' must be [{n}, {feat_nf}] "
                               f"(got {list(feat.shape)})")
    attr_spec = payload.get("edge_attr")
    if attr_spec is None:
        d = np.linalg.norm(loc[ei[0]] - loc[ei[1]], axis=1)[:, None]
        attr = np.repeat(d, max(edge_attr_nf, 1),
                         axis=1).astype(np.float32)[:, :max(edge_attr_nf, 1)]
    else:
        attr = decode_array(attr_spec, "<f4", "edge_attr")
        if (attr.ndim != 2 or attr.shape[0] != ei.shape[1]
                or attr.shape[1] != edge_attr_nf):
            raise PayloadError(
                f"'edge_attr' must be [{ei.shape[1]}, {edge_attr_nf}] "
                f"(got {list(attr.shape)})")
    return {"node_feat": feat.astype(np.float32),
            "loc": loc.astype(np.float32), "vel": vel.astype(np.float32),
            "target": loc.astype(np.float32), "edge_index": ei,
            "edge_attr": attr.astype(np.float32)}


def scene_from_payload(payload: dict) -> dict:
    """Validate a rollout body and build the scene dict
    ``RequestQueue.submit_rollout`` consumes. Required: ``positions`` [n,3]
    and ``steps`` (int >= 1). Optional: ``velocities`` (default zeros) and
    ``node_mask`` [n] (default all ones). No edge topology: the rollout
    rebuilds its radius graph on device every step."""
    if not isinstance(payload, dict):
        raise PayloadError("body must be a JSON object")
    loc = decode_array(payload.get("positions", payload.get("loc")),
                       "<f4", "positions")
    if loc.ndim != 2 or loc.shape[1] != 3 or loc.shape[0] < 1:
        raise PayloadError(f"'positions' must be [n, 3] "
                           f"(got {list(loc.shape)})")
    n = int(loc.shape[0])
    try:
        steps = int(payload.get("steps"))
    except (TypeError, ValueError):
        raise PayloadError("'steps' must be an integer >= 1") from None
    if steps < 1:
        raise PayloadError(f"'steps' must be >= 1 (got {steps})")
    vel_spec = payload.get("velocities", payload.get("vel"))
    if vel_spec is None:
        vel = np.zeros((n, 3), np.float32)
    else:
        vel = decode_array(vel_spec, "<f4", "velocities")
        if vel.shape != loc.shape:
            raise PayloadError(f"'velocities' must match positions shape "
                               f"(got {list(vel.shape)})")
    scene = {"loc": loc.astype(np.float32), "vel": vel.astype(np.float32),
             "steps": steps}
    mask_spec = payload.get("node_mask")
    if mask_spec is not None:
        mask = decode_array(mask_spec, "<f4", "node_mask")
        if mask.shape != (n,):
            raise PayloadError(f"'node_mask' must be [{n}] "
                               f"(got {list(mask.shape)})")
        scene["node_mask"] = mask.astype(np.float32)
    return scene


# ---- the gateway ------------------------------------------------------------

_GATEWAY_COUNTERS = (
    "requests_total", "predict_ok", "rollout_ok", "shed_inflight",
    "shed_bulk", "shed_queue_full", "timeouts", "bad_requests",
    "unknown_model", "overflow_rejected", "draining_rejected",
    "rollout_overflow", "model_unavailable", "swap_ok", "swap_failed",
    "stream_ok", "stream_cancelled",
    "errors",
)

# priority classes: interactive (predicts — a human is waiting) outranks
# bulk (rollouts — batch trajectory generation). Clients override with the
# priority header (serve.priority.header, default X-Priority).
_PRIORITY_CLASSES = ("interactive", "bulk")

_PRIORITY_DEFAULTS = {
    "enable": True,
    "header": "X-Priority",
    "bulk_max_inflight_frac": 0.75,
    "degrade_shed_rate": 0.05,
    "degrade_p99_ms": None,
    "bulk_retry_factor": 4.0,
    # predicts whose request body is at least this many bytes default to the
    # bulk class (million-node tiled scenes hold an executor for seconds —
    # they must not starve interactive traffic); 0 disables the heuristic
    "bulk_content_bytes": 4_194_304,
}


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    # 0.0.0.0 binds are deliberate (serve.gateway.host); rebinding a
    # lingering TIME_WAIT port must not fail a restart
    allow_reuse_address = True

    def handle_error(self, request, client_address):
        # socketserver's default prints a traceback to stderr; keep the
        # event stream as the error surface instead
        obs.event("gateway/socket_error", client=str(client_address))


class Gateway:
    """The HTTP front-end: routing, admission, drain, metrics.

    Handler logic lives on this class (the request handler is a thin
    dispatcher) so tests can drive pieces without sockets.
    """

    def __init__(self, registry: ModelRegistry, *, host: str = "127.0.0.1",
                 port: int = 0, max_inflight: int = 64,
                 drain_grace_s: float = 10.0,
                 metrics_registry: Optional[MetricsRegistry] = None,
                 slo_window_s: float = 60.0,
                 autoscale: Optional[dict] = None,
                 priority: Optional[dict] = None,
                 stream_chunk_steps: int = 8,
                 promote: Optional[dict] = None):
        from distegnn_tpu.obs.slo import SLOMonitor

        self.registry = registry
        self.max_inflight = int(max_inflight)
        self.drain_grace_s = float(drain_grace_s)
        self._reg = metrics_registry or obs.get_registry()
        # rolling-window SLO gauges (slo/window_*): fed per inference
        # request, exported on every GET /metrics render
        self.slo_monitor = SLOMonitor(window_s=slo_window_s)
        self._c = {n: self._reg.counter("gateway/" + n)
                   for n in _GATEWAY_COUNTERS}
        self._inflight_gauge = self._reg.gauge("gateway/inflight")
        self._ready_gauge = self._reg.gauge("gateway/ready")
        self._inflight = 0
        self._inflight_bulk = 0
        self._inflight_lock = threading.Lock()
        self._accepting = True
        self._draining = False
        self._drain_lock = threading.Lock()
        # priority admission: bulk (rollouts) is capped at a fraction of
        # max_inflight so interactive predicts always find headroom, and is
        # deferred outright while the SLO window is degraded
        pk = dict(_PRIORITY_DEFAULTS)
        pk.update(dict(priority or {}))
        self.priority_enable = bool(pk["enable"])
        self.priority_header = str(pk["header"])
        frac = float(pk["bulk_max_inflight_frac"])
        self.bulk_max_inflight = max(1, int(self.max_inflight * frac))
        self.degrade_shed_rate = float(pk["degrade_shed_rate"])
        self.degrade_p99_ms = (None if pk["degrade_p99_ms"] is None
                               else float(pk["degrade_p99_ms"]))
        self.bulk_retry_factor = float(pk["bulk_retry_factor"])
        self.bulk_content_bytes = int(pk["bulk_content_bytes"] or 0)
        self._degraded_cache = (0.0, False)   # (checked_at, degraded)
        self._degraded_lock = threading.Lock()
        # streaming rollouts: server-side chunk size (per-request
        # "chunk_steps" in the body overrides)
        self.stream_chunk_steps = max(1, int(stream_chunk_steps))
        # the elasticity control loop (no-op thread unless autoscale.enable)
        self.autoscaler = ReplicaAutoscaler(
            registry, self.slo_monitor, config=autoscale,
            metrics_registry=self._reg)
        self.autoscaler.start()
        # the promotion conveyor's serving end (no-op unless promote.enable):
        # watches the candidate directory, canaries on a quarantined replica,
        # and reads its shadow sample off this gateway's predict hot path
        from distegnn_tpu.promote.promoter import Promoter
        self.promoter = Promoter(
            registry, self.slo_monitor, config=promote,
            metrics_registry=self._reg)
        self.promoter.start()
        self.httpd = _Server((host, int(port)), _make_handler(self))

    # ---- addresses -------------------------------------------------------
    @property
    def address(self) -> Tuple[str, int]:
        host, port = self.httpd.server_address[:2]
        return str(host), int(port)

    @property
    def port(self) -> int:
        return self.address[1]

    def url(self, path: str = "") -> str:
        host, port = self.address
        return f"http://{host}:{port}{path}"

    # ---- lifecycle -------------------------------------------------------
    def serve_forever(self) -> None:
        self._ready_gauge.set(1.0 if self.ready() else 0.0)
        self.httpd.serve_forever(poll_interval=0.1)

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT -> graceful drain. The handler only spawns the
        drain thread (queue.stop joins a thread — never block the main
        thread's serve loop from its own signal frame)."""
        def _on_signal(signum, frame):
            obs.event("gateway/signal", signum=int(signum))
            threading.Thread(target=self.drain, name="gateway-drain",
                             daemon=True).start()

        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)

    def drain(self) -> None:
        """Stop accepting, flush every queue, wait for in-flight responses,
        then stop the accept loop. Idempotent."""
        with self._drain_lock:
            if self._draining:
                return
            self._draining = True
        self._accepting = False
        self._ready_gauge.set(0.0)
        # the autoscaler must not grow/shrink a fleet that is draining, and
        # the promoter must not start (or hold) a canary across the drain
        self.autoscaler.stop()
        self.promoter.stop()
        obs.event("gateway/drain_begin", inflight=self._inflight)
        # every admitted future resolves; models drain CONCURRENTLY, each
        # bounded by the grace budget (registry.stop). Signature-aware so a
        # wrapped/monkeypatched stop(drain=...) still works.
        stop_kwargs = {"drain": True}
        try:
            import inspect

            if "grace_s" in inspect.signature(self.registry.stop).parameters:
                stop_kwargs["grace_s"] = self.drain_grace_s
        except (TypeError, ValueError):
            pass
        self.registry.stop(**stop_kwargs)
        deadline = time.monotonic() + self.drain_grace_s
        while time.monotonic() < deadline:
            with self._inflight_lock:
                if self._inflight == 0:
                    break
            time.sleep(0.02)
        obs.event("gateway/drain_done", inflight=self._inflight)
        self.httpd.shutdown()

    def close(self) -> None:
        self.autoscaler.stop()
        self.promoter.stop()
        self.httpd.server_close()

    def ready(self) -> bool:
        return self._accepting and self.registry.ready()

    # ---- request handling ------------------------------------------------
    def _route_name(self, method: str, path: str) -> str:
        if method == "POST" and path.startswith("/v1/models/"):
            if path.endswith("/predict"):
                return "predict"
            if path.endswith("/rollout"):
                return "rollout"
            if path.endswith("/swap"):
                return "swap"
        return {"/v1/models": "models", "/metrics": "metrics",
                "/healthz": "healthz", "/readyz": "readyz"}.get(path,
                                                                "unknown")

    def dispatch(self, handler, method: str) -> None:
        path = handler.path.split("?", 1)[0]
        route = self._route_name(method, path)
        # every request gets an id at the edge: echoed back as X-Request-Id
        # and attached to every span/event the request touches downstream
        rid = mint_request_id(handler.headers.get("X-Request-Id"))
        handler.request_id = rid
        self._c["requests_total"].add(1)
        t0 = time.perf_counter()
        with obs.span("serve/http", route=route, method=method,
                      request_id=rid) as sp:
            try:
                status = self._handle(handler, method, path, route)
            except PayloadError as exc:
                self._c["bad_requests"].add(1)
                status = self._send_json(handler, 400, {
                    "error": str(exc), "type": "PayloadError"})
            except ConnectionError:
                status = 499        # client went away mid-response
            except Exception as exc:
                self._c["errors"].add(1)
                obs.event("gateway/handler_error", route=route,
                          error=repr(exc))
                status = self._send_json(handler, 500, {
                    "error": repr(exc), "type": type(exc).__name__})
            sp.set(status=status)
        ms = (time.perf_counter() - t0) * 1e3
        self._reg.reservoir(f"gateway/http_{route}_ms").record(ms)
        self.slo_monitor.observe_http(route, ms, status)

    def _handle(self, h, method: str, path: str, route: str) -> int:
        if route in ("predict", "rollout"):
            if method != "POST":
                return self._send_json(h, 405, {"error": "POST only",
                                                "type": "MethodNotAllowed"})
            return self._infer(h, path, route)
        if route == "swap":
            if method != "POST":
                return self._send_json(h, 405, {"error": "POST only",
                                                "type": "MethodNotAllowed"})
            if not self._accepting:
                self._c["draining_rejected"].add(1)
                return self._send_json(h, 503, {
                    "error": "gateway draining", "type": "Draining"},
                    retry_after=self.drain_grace_s)
            return self._swap(h, path)
        if method != "GET":
            return self._send_json(h, 405, {"error": "GET only",
                                            "type": "MethodNotAllowed"})
        if route == "healthz":
            return self._send_json(h, 200, {"status": "ok"})
        if route == "readyz":
            fully_ready = self.ready()
            self._ready_gauge.set(1.0 if fully_ready else 0.0)
            if not self._accepting:
                return self._send_json(h, 503, {
                    "ready": False, "reason": "draining"},
                    retry_after=self.drain_grace_s)
            health = self.registry.health()
            scale = (self.autoscaler.status()
                     if self.autoscaler.enable else None)
            promo = (self.promoter.status()
                     if self.promoter.enable else None)
            if fully_ready:
                body = {"ready": True, "models": health}
                if scale is not None:
                    body["autoscale"] = scale
                if promo is not None:
                    body["promote"] = promo
                return self._send_json(h, 200, body)
            if self.registry.any_ready():
                # degraded: the broken model 503s on its own routes while
                # every ready model keeps serving — report which is which
                body = {"ready": True, "degraded": True, "models": health}
                if scale is not None:
                    body["autoscale"] = scale
                if promo is not None:
                    body["promote"] = promo
                return self._send_json(h, 200, body)
            return self._send_json(h, 503, {
                "ready": False,
                "reason": "models not warmed or dispatcher down",
                "models": health}, retry_after=1.0)
        if route == "metrics":
            return self._send_text(h, 200, self.render_metrics(),
                                   content_type="text/plain; version=0.0.4")
        if route == "models":
            return self._send_json(h, 200, self.registry.describe())
        return self._send_json(h, 404, {"error": f"no route {path}",
                                        "type": "NotFound"})

    def _priority_of(self, h, route: str) -> str:
        """Admission class for one inference request: the priority header
        when present and sane, else predicts are interactive (a caller is
        blocked on the answer) and rollouts are bulk (batch trajectory
        generation that can wait). Always interactive when priority
        admission is disabled."""
        if not self.priority_enable:
            return "interactive"
        supplied = h.headers.get(self.priority_header)
        if supplied:
            val = str(supplied).strip().lower()
            if val in _PRIORITY_CLASSES:
                return val
        if route == "rollout":
            return "bulk"
        if self.bulk_content_bytes:
            # giant predicts (million-node tiled scenes) ride the bulk class:
            # they hold an executor for seconds and must not crowd out
            # latency-sensitive traffic
            try:
                clen = int(h.headers.get("Content-Length") or 0)
            except (TypeError, ValueError):
                clen = 0
            if clen >= self.bulk_content_bytes:
                return "bulk"
        return "interactive"

    def _window_degraded(self) -> bool:
        """True while the rolling SLO window says the gateway is hurting
        (shed rate or predict p99 past the priority thresholds). Cached for
        250ms — admission is on the hot path, the window math is not."""
        now = time.monotonic()
        with self._degraded_lock:
            checked_at, val = self._degraded_cache
            if now - checked_at < 0.25:
                return val
        snap = self.slo_monitor.window_snapshot()
        deg = snap.get("shed_rate", 0.0) > self.degrade_shed_rate
        if not deg and self.degrade_p99_ms is not None:
            p99 = snap.get("predict_p99_ms")
            deg = p99 is not None and p99 > self.degrade_p99_ms
        with self._degraded_lock:
            self._degraded_cache = (now, deg)
        return deg

    def _infer(self, h, path: str, route: str) -> int:
        name = path[len("/v1/models/"):-(len(route) + 1)]
        pri = self._priority_of(h, route)
        if pri == "bulk" and self._window_degraded():
            # the window says interactive traffic is hurting: defer bulk
            # outright so every freed slot goes to interactive work
            self._c["shed_bulk"].add(1)
            return self._send_json(h, 429, {
                "error": "SLO window degraded; bulk work deferred — retry "
                         "with backoff", "type": "BulkDeferred",
                "priority": "bulk"},
                retry_after=1.0 * self.bulk_retry_factor)
        if not self._try_acquire(pri):
            if pri == "bulk":
                # interactive still has headroom; only the bulk share is
                # spoken for — back bulk clients off harder
                self._c["shed_bulk"].add(1)
                return self._send_json(h, 429, {
                    "error": f"bulk admission at "
                             f"bulk_max_inflight={self.bulk_max_inflight}; "
                             "retry with backoff", "type": "Overloaded",
                    "priority": "bulk"},
                    retry_after=0.5 * self.bulk_retry_factor)
            self._c["shed_inflight"].add(1)
            return self._send_json(h, 429, {
                "error": f"gateway at max_inflight={self.max_inflight}; "
                         "retry with backoff", "type": "Overloaded"},
                retry_after=0.5)
        try:
            if not self._accepting:
                self._c["draining_rejected"].add(1)
                return self._send_json(h, 503, {
                    "error": "gateway draining", "type": "Draining"},
                    retry_after=self.drain_grace_s)
            try:
                entry = self.registry.get(name)
            except KeyError:
                self._c["unknown_model"].add(1)
                return self._send_json(h, 404, {
                    "error": f"unknown model {name!r}; "
                             f"see GET /v1/models", "type": "UnknownModel"})
            if entry.state == "failed":
                # per-model shed: THIS model failed warmup; every other
                # model keeps serving (see /readyz degraded detail)
                self._c["model_unavailable"].add(1)
                return self._send_json(h, 503, {
                    "error": f"model {name!r} failed warmup: {entry.error}",
                    "type": "ModelFailed"}, retry_after=30.0)
            if route == "rollout":
                return self._rollout_admitted(h, name, entry)
            return self._predict_admitted(h, name, entry)
        finally:
            self._release(pri)

    def _submit_guarded(self, h, submit_fn, entry=None):
        """Run one queue submit, mapping the admission errors to their HTTP
        statuses. Returns (future, None) or (None, status)."""
        try:
            return submit_fn(), None
        except QueueFullError as exc:
            self._c["shed_queue_full"].add(1)
            return None, self._send_json(
                h, 429, {"error": str(exc), "type": "QueueFull"},
                retry_after=self._queue_retry_after(entry))
        except BucketOverflowError as exc:
            self._c["overflow_rejected"].add(1)
            return None, self._send_json(h, 413, {"error": str(exc),
                                                  "type": "BucketOverflow"})
        except ModelUnavailableError as exc:
            # all replicas of THIS model are down; others keep serving
            self._c["model_unavailable"].add(1)
            return None, self._send_json(
                h, 503, {"error": str(exc), "type": "ModelUnavailable",
                         "model": exc.model},
                retry_after=exc.retry_after_s)
        except RuntimeError as exc:       # queue stopped under our feet
            self._c["draining_rejected"].add(1)
            return None, self._send_json(h, 503, {"error": str(exc),
                                                  "type": "Draining"},
                                         retry_after=1.0)

    @staticmethod
    def _queue_retry_after(entry) -> Optional[float]:
        """429 Retry-After hint from the model's backlog (replica sets
        estimate drain time from queue depth; plain queues get a floor)."""
        if entry is None:
            return 1.0
        hint = getattr(entry.queue, "queue_retry_after_s", None)
        return hint() if callable(hint) else 1.0

    def _predict_admitted(self, h, name: str, entry) -> int:
        payload = self._read_json(h)
        graph = graph_from_payload(payload, entry.feat_nf,
                                   entry.edge_attr_nf)
        encoding = str(payload.get("encoding", "list"))
        if encoding not in ("list", "b64"):
            raise PayloadError("'encoding' must be 'list' or 'b64'")
        t0 = time.perf_counter()
        rid = getattr(h, "request_id", None)
        if (int(graph["loc"].shape[0]) > entry.engine.ladder.max_nodes
                and getattr(entry.engine, "tiled_enabled", False)):
            # above the ladder cap: serve through the tiled executor (one
            # fixed-shape tile program) instead of 413-rejecting. Branch
            # BEFORE session prep — the monolithic prepare would raise
            # BucketOverflowError while bucketing the plan.
            return self._predict_tiled(h, name, entry, payload, graph,
                                       encoding, rid, t0)
        session = None
        bucket = perm = None
        session_id = payload.get("session_id")
        cache = getattr(entry.engine, "prep_cache", None)
        if session_id is not None and cache is not None:
            prepped = cache.prepare(str(session_id), graph, request_id=rid)
            graph, bucket, perm = prepped.graph, prepped.bucket, prepped.perm
            session = {"id": str(session_id), "hit": prepped.hit,
                       "prep_ms": round((time.perf_counter() - t0) * 1e3, 3)}
        fut, status = self._submit_guarded(
            h, lambda: entry.queue.submit(graph, bucket=bucket,
                                          request_id=rid), entry)
        if fut is None:
            return status
        try:
            out = fut.result()            # bounded by the hard deadline
        except RequestTimeoutError as exc:
            self._c["timeouts"].add(1)
            return self._send_json(h, 504, {"error": str(exc),
                                            "type": "RequestTimeout"})
        except ModelUnavailableError as exc:
            # admitted, then every replica (and failover) died under it
            self._c["model_unavailable"].add(1)
            return self._send_json(
                h, 503, {"error": str(exc), "type": "ModelUnavailable",
                         "model": exc.model},
                retry_after=exc.retry_after_s)
        if self.promoter.enable:
            # promotion shadow tee: mirror this (graph, live output) pair to
            # the canary replica. Sampled + bounded inside tee, and the
            # shadow response never reaches this client — the live `out` is
            # already in hand and is what gets encoded below.
            self.promoter.tee(name, graph, bucket, rid, out)
        if perm is not None:
            # the session plan served the model a Morton-relabeled graph;
            # answer in the client's original node order
            unperm = np.empty_like(out)
            unperm[perm] = out
            out = unperm
        meta = dict(fut.meta)
        self._c["predict_ok"].add(1)
        body = {
            "request_id": rid,
            "model": name,
            "n": int(graph["loc"].shape[0]),
            "prediction": encode_array(out, encoding),
            "bucket": {"n": meta.get("bucket_n"), "e": meta.get("bucket_e")},
            "queue_ms": meta.get("queue_ms"),
            "compute_ms": meta.get("compute_ms"),
            "batch_filled": meta.get("batch_filled"),
            "total_ms": round((time.perf_counter() - t0) * 1e3, 3),
        }
        if session is not None:
            body["session"] = session
        return self._send_json(h, 200, body)

    # ---- tiled predicts (above the ladder cap) ---------------------------
    @staticmethod
    def _tiled_stats(out: dict) -> dict:
        stats = {
            "tiles": out.get("tiles"),
            "layers": out.get("layers"),
            "devices": out.get("devices", 1),
            "rounds": out.get("rounds"),
            "padded_nodes": out.get("padded_nodes"),
            "halo_fraction": round(float(out.get("halo_fraction", 0.0)), 6),
            "work_imbalance": round(float(out.get("work_imbalance", 0.0)), 4),
            "stall_fraction": round(float(out.get("stall_fraction", 0.0)), 6),
            "prep_ms": out.get("prep_ms"),
            "compute_ms": out.get("total_ms"),
        }
        # mesh-round extras (serve/mesh_tiled.py) when devices > 1
        for key in ("round_ms", "halo_gather_ms", "round_imbalance"):
            if key in out:
                stats[key] = round(float(out[key]), 4)
        return stats

    def _predict_tiled(self, h, name: str, entry, payload: dict, graph: dict,
                       encoding: str, rid, t0) -> int:
        """Predict for a scene above the ladder cap: tile plan (session-
        cached), tiled executor, buffered JSON — or NDJSON per-tile progress
        on ``?stream=1``."""
        engine = entry.engine
        session = None
        session_id = payload.get("session_id")
        cache = getattr(engine, "prep_cache", None)
        if session_id is not None and cache is not None:
            plan, hit = cache.prepare_tile(
                str(session_id), graph,
                lambda: engine.tiled.plan(graph), request_id=rid)
            graph["_tile_plan"] = plan
            session = {"id": str(session_id), "hit": hit,
                       "prep_ms": round((time.perf_counter() - t0) * 1e3, 3)}
        stream = self._wants_stream(h)
        supports = getattr(entry.queue, "supports_streaming", None)
        if stream and callable(supports) and not supports():
            # no in-process replica to push progress chunks: fall back to a
            # buffered response (same result, no per-tile lines)
            stream = False
        if not stream:
            fut, status = self._submit_guarded(
                h, lambda: entry.queue.submit_tiled(graph, request_id=rid),
                entry)
            if fut is None:
                return status
            try:
                out = fut.result()        # bounded by the scaled deadline
            except RequestTimeoutError as exc:
                self._c["timeouts"].add(1)
                return self._send_json(h, 504, {"error": str(exc),
                                                "type": "RequestTimeout"})
            except ModelUnavailableError as exc:
                self._c["model_unavailable"].add(1)
                return self._send_json(
                    h, 503, {"error": str(exc), "type": "ModelUnavailable",
                             "model": exc.model},
                    retry_after=exc.retry_after_s)
            meta = dict(fut.meta)
            self._c["predict_ok"].add(1)
            body = {
                "request_id": rid,
                "model": name,
                "n": int(out["n"]),
                "prediction": encode_array(out["prediction"], encoding),
                "tiled": self._tiled_stats(out),
                "queue_ms": meta.get("queue_ms"),
                "compute_ms": meta.get("compute_ms"),
                "total_ms": round((time.perf_counter() - t0) * 1e3, 3),
            }
            if session is not None:
                body["session"] = session
            return self._send_json(h, 200, body)
        return self._tiled_streamed(h, name, entry, graph, encoding, rid,
                                    t0, session)

    def _tiled_streamed(self, h, name: str, entry, graph: dict,
                        encoding: str, rid, t0, session) -> int:
        """``POST .../predict?stream=1`` above the ladder cap: one NDJSON
        progress line per completed tile (sequential) or per completed
        ROUND of D tiles (serve.tiled.devices > 1), then a final line
        carrying the prediction. A client disconnect cancels the executor
        at the next tile/round boundary."""
        sink = StreamSink()
        fut, status = self._submit_guarded(
            h, lambda: entry.queue.submit_tiled(graph, request_id=rid,
                                                stream=sink), entry)
        if fut is None:
            return status
        tiled = getattr(entry.engine, "tiled", None)
        factor = max(float(getattr(tiled, "timeout_factor", 1.0) or 1.0), 1.0)
        deadline = time.monotonic() + factor * (
            float(getattr(entry.queue, "request_timeout", 30.0))
            + float(getattr(entry.queue, "result_margin", 5.0)))
        self._begin_chunked(h, rid)
        err_line = None
        try:
            while True:
                try:
                    kind, a, b = sink.next(timeout=0.25)
                except _pyqueue.Empty:
                    if time.monotonic() > deadline:
                        sink.cancel()
                        self._c["timeouts"].add(1)
                        err_line = {"error": "tiled stream timed out",
                                    "type": "RequestTimeout"}
                        break
                    continue
                if kind == "chunk":
                    # per-tile lines from the sequential executor carry
                    # "tile"; per-ROUND lines from the mesh executor
                    # (serve.tiled.devices > 1) carry "round"/"n_rounds"
                    info = dict(b or {})
                    self._write_chunk(h, json.dumps(
                        {k: info[k] for k in
                         ("layer", "tile", "round", "n_layers", "n_tiles",
                          "n_rounds") if k in info}) + "\n")
                elif kind == "done":
                    out = a or {}
                    pred = out.get("prediction")
                    self._c["predict_ok"].add(1)
                    self._c["stream_ok"].add(1)
                    line = {
                        "done": True, "request_id": rid, "model": name,
                        "n": out.get("n"),
                        "prediction": (encode_array(pred, encoding)
                                       if pred is not None else None),
                        "tiled": self._tiled_stats(out),
                        "cancelled": bool(out.get("cancelled", False)),
                        "total_ms": round((time.perf_counter() - t0) * 1e3,
                                          3),
                    }
                    if session is not None:
                        line["session"] = session
                    self._write_chunk(h, json.dumps(line) + "\n")
                    break
                else:           # ("error", exc, None)
                    self._count_stream_error(a)
                    err_line = {"error": str(a), "type": type(a).__name__}
                    break
            if err_line is not None:
                err_line["request_id"] = rid
                self._write_chunk(h, json.dumps(err_line) + "\n")
            self._end_chunked(h)
        except ConnectionError:
            sink.cancel()
            self._c["stream_cancelled"].add(1)
            raise
        return 200

    def _rollout_admitted(self, h, name: str, entry) -> int:
        if not entry.engine.rollout_enabled:
            return self._send_json(h, 501, {
                "error": f"model {name!r} was built without serve.rollout; "
                         "set serve.rollout in its config to enable the "
                         "endpoint", "type": "RolloutDisabled"})
        payload = self._read_json(h)
        scene = scene_from_payload(payload)
        encoding = str(payload.get("encoding", "list"))
        if encoding not in ("list", "b64"):
            raise PayloadError("'encoding' must be 'list' or 'b64'")
        t0 = time.perf_counter()
        rid = getattr(h, "request_id", None)
        if self._wants_stream(h):
            return self._rollout_streamed(h, name, entry, payload, scene,
                                          encoding, rid, t0)
        fut, status = self._submit_guarded(
            h, lambda: entry.queue.submit_rollout(scene, request_id=rid),
            entry)
        if fut is None:
            return status
        try:
            traj = fut.result()           # bounded by the hard deadline
        except RequestTimeoutError as exc:
            self._c["timeouts"].add(1)
            return self._send_json(h, 504, {"error": str(exc),
                                            "type": "RequestTimeout"})
        except ModelUnavailableError as exc:
            self._c["model_unavailable"].add(1)
            return self._send_json(
                h, 503, {"error": str(exc), "type": "ModelUnavailable",
                         "model": exc.model},
                retry_after=exc.retry_after_s)
        except RolloutOverflowError as exc:
            # a well-formed request whose scene outgrew the model's static
            # neighbor capacity — the client's to fix, not a server error
            self._c["rollout_overflow"].add(1)
            return self._send_json(h, 422, {"error": str(exc),
                                            "type": "RolloutOverflow"})
        meta = dict(fut.meta)
        self._c["rollout_ok"].add(1)
        return self._send_json(h, 200, {
            "request_id": rid,
            "model": name,
            "n": int(scene["loc"].shape[0]),
            "steps": int(scene["steps"]),
            "trajectory": encode_array(traj, encoding),
            "bucket": {"n": meta.get("bucket_n")},
            "queue_ms": meta.get("queue_ms"),
            "compute_ms": meta.get("compute_ms"),
            "batch_filled": meta.get("batch_filled"),
            "total_ms": round((time.perf_counter() - t0) * 1e3, 3),
        })

    # ---- chunked streaming rollouts --------------------------------------
    @staticmethod
    def _wants_stream(h) -> bool:
        """``?stream=1`` on the rollout URL (dispatch strips the query
        before routing; the raw handler path still carries it)."""
        vals = parse_qs(urlsplit(h.path).query).get("stream")
        return bool(vals) and vals[-1].lower() in ("1", "true", "yes", "on")

    def _stream_chunk(self, payload: dict) -> int:
        chunk = payload.get("chunk_steps")
        if chunk is None:
            return self.stream_chunk_steps
        try:
            chunk = int(chunk)
        except (TypeError, ValueError):
            raise PayloadError("'chunk_steps' must be an integer >= 1") \
                from None
        if chunk < 1:
            raise PayloadError(f"'chunk_steps' must be >= 1 (got {chunk})")
        return chunk

    def _rollout_streamed(self, h, name: str, entry, payload: dict,
                          scene: dict, encoding: str, rid, t0) -> int:
        """``POST .../rollout?stream=1``: HTTP chunked transfer, one NDJSON
        line per trajectory chunk so step 1 arrives while step 500 is still
        computing, then a summary line. A client disconnect (detected at the
        next chunk write) cancels the remaining compute at the next chunk
        boundary and frees the admission slot."""
        chunk = self._stream_chunk(payload)
        scene = dict(scene)
        scene["chunk_steps"] = chunk
        supports = getattr(entry.queue, "supports_streaming", None)
        if callable(supports) and not supports():
            # process-worker replicas can't push chunks over the IPC
            # channel: serve one buffered rollout re-chunked at the edge —
            # same wire contract, just without the early first chunk
            return self._rollout_stream_fallback(h, name, entry, scene,
                                                 encoding, rid, t0, chunk)
        sink = StreamSink()
        fut, status = self._submit_guarded(
            h, lambda: entry.queue.submit_rollout(scene, request_id=rid,
                                                  stream=sink), entry)
        if fut is None:
            return status
        # admitted: from here the response is chunked NDJSON. Bound the
        # consumer loop by the queue's own hard deadline so a wedged
        # replica can't hold the socket forever.
        deadline = time.monotonic() \
            + float(getattr(entry.queue, "request_timeout", 30.0)) \
            + float(getattr(entry.queue, "result_margin", 5.0))
        self._begin_chunked(h, rid)
        steps_done = 0
        err_line = None
        try:
            while True:
                try:
                    kind, a, b = sink.next(timeout=0.25)
                except _pyqueue.Empty:
                    if time.monotonic() > deadline:
                        sink.cancel()
                        self._c["timeouts"].add(1)
                        err_line = {"error": "stream timed out",
                                    "type": "RequestTimeout"}
                        break
                    continue
                if kind == "chunk":
                    start, traj = int(a), b
                    self._write_chunk(h, json.dumps({
                        "start_step": start,
                        "steps": int(traj.shape[0]),
                        "chunk": encode_array(traj, encoding)}) + "\n")
                    steps_done = start + int(traj.shape[0])
                elif kind == "done":
                    summary = a or {}
                    self._c["rollout_ok"].add(1)
                    self._c["stream_ok"].add(1)
                    self._write_chunk(h, json.dumps({
                        "done": True, "request_id": rid, "model": name,
                        "n": int(scene["loc"].shape[0]),
                        "steps": int(summary.get("steps_done", steps_done)),
                        "steps_total": int(summary.get("steps_total",
                                                       scene["steps"])),
                        "cancelled": bool(summary.get("cancelled", False)),
                        "total_ms": round((time.perf_counter() - t0) * 1e3,
                                          3)}) + "\n")
                    break
                else:           # ("error", exc, None)
                    self._count_stream_error(a)
                    err_line = {"error": str(a), "type": type(a).__name__}
                    break
            if err_line is not None:
                err_line["request_id"] = rid
                self._write_chunk(h, json.dumps(err_line) + "\n")
            self._end_chunked(h)
        except ConnectionError:
            # the client went away mid-stream (EPIPE or RST, depending on
            # timing): flag the sink so the engine stops at the next chunk
            # boundary (it emits serve/stream_cancelled with the
            # skipped-step count), free the slot, and let dispatch record
            # the 499
            sink.cancel()
            self._c["stream_cancelled"].add(1)
            raise
        return 200

    def _rollout_stream_fallback(self, h, name: str, entry, scene: dict,
                                 encoding: str, rid, t0, chunk: int) -> int:
        """Streaming contract over a non-streaming backend: run the buffered
        rollout, then replay it as NDJSON chunks. Bitwise-identical chunk
        lines, no early first chunk (the backend can't provide one)."""
        fut, status = self._submit_guarded(
            h, lambda: entry.queue.submit_rollout(scene, request_id=rid),
            entry)
        if fut is None:
            return status
        try:
            traj = fut.result()
        except RequestTimeoutError as exc:
            self._c["timeouts"].add(1)
            return self._send_json(h, 504, {"error": str(exc),
                                            "type": "RequestTimeout"})
        except ModelUnavailableError as exc:
            self._c["model_unavailable"].add(1)
            return self._send_json(
                h, 503, {"error": str(exc), "type": "ModelUnavailable",
                         "model": exc.model},
                retry_after=exc.retry_after_s)
        except RolloutOverflowError as exc:
            self._c["rollout_overflow"].add(1)
            return self._send_json(h, 422, {"error": str(exc),
                                            "type": "RolloutOverflow"})
        steps = int(traj.shape[0])
        self._begin_chunked(h, rid)
        try:
            done = 0
            while done < steps:
                c = min(chunk, steps - done)
                self._write_chunk(h, json.dumps({
                    "start_step": done, "steps": c,
                    "chunk": encode_array(traj[done:done + c],
                                          encoding)}) + "\n")
                done += c
            self._c["rollout_ok"].add(1)
            self._c["stream_ok"].add(1)
            self._write_chunk(h, json.dumps({
                "done": True, "request_id": rid, "model": name,
                "n": int(scene["loc"].shape[0]), "steps": steps,
                "steps_total": steps, "cancelled": False,
                "total_ms": round((time.perf_counter() - t0) * 1e3,
                                  3)}) + "\n")
            self._end_chunked(h)
        except ConnectionError:
            self._c["stream_cancelled"].add(1)
            raise
        return 200

    def _count_stream_error(self, exc) -> None:
        if isinstance(exc, RequestTimeoutError):
            self._c["timeouts"].add(1)
        elif isinstance(exc, RolloutOverflowError):
            self._c["rollout_overflow"].add(1)
        elif isinstance(exc, ModelUnavailableError):
            self._c["model_unavailable"].add(1)
        else:
            self._c["errors"].add(1)

    @staticmethod
    def _begin_chunked(h, rid) -> None:
        h.send_response(200)
        h.send_header("Content-Type", "application/x-ndjson")
        h.send_header("Transfer-Encoding", "chunked")
        if rid is not None:
            h.send_header("X-Request-Id", rid)
        h.end_headers()

    @staticmethod
    def _write_chunk(h, text: str) -> None:
        data = text.encode("utf-8")
        h.wfile.write(f"{len(data):x}\r\n".encode("ascii"))
        h.wfile.write(data)
        h.wfile.write(b"\r\n")
        h.wfile.flush()

    @staticmethod
    def _end_chunked(h) -> None:
        h.wfile.write(b"0\r\n\r\n")
        h.wfile.flush()

    # ---- blue/green hot-swap --------------------------------------------
    def _swap(self, h, path: str) -> int:
        """POST /v1/models/<name>/swap {"checkpoint": <path>} — blue/green
        params swap under load (registry.swap: checksummed restore, per-rung
        canary, one-at-a-time replica flips, auto-rollback)."""
        name = path[len("/v1/models/"):-len("/swap")]
        try:
            entry = self.registry.get(name)
        except KeyError:
            self._c["unknown_model"].add(1)
            return self._send_json(h, 404, {
                "error": f"unknown model {name!r}; see GET /v1/models",
                "type": "UnknownModel"})
        payload = self._read_json(h)
        ckpt = payload.get("checkpoint")
        if not ckpt or not isinstance(ckpt, str):
            raise PayloadError("'checkpoint' (a path string) is required")
        try:
            info = entry.swap(ckpt)
        except SwapInProgressError as exc:
            self._c["swap_failed"].add(1)
            return self._send_json(h, 409, {"error": str(exc),
                                            "type": "SwapInProgress"},
                                   retry_after=1.0)
        except SwapError as exc:
            # the swap REJECTED the checkpoint and rolled back — serving
            # params are unchanged; the client's checkpoint is the problem
            self._c["swap_failed"].add(1)
            return self._send_json(h, 422, {
                "error": str(exc), "type": "SwapFailed",
                "stage": exc.stage, "rolled_back": exc.rolled_back})
        self._c["swap_ok"].add(1)
        info["request_id"] = getattr(h, "request_id", None)
        return self._send_json(h, 200, info)

    # ---- metrics ---------------------------------------------------------
    def render_metrics(self) -> str:
        """Prometheus text: the gateway/process-wide registry, then each
        model's serve registry under a per-model name prefix (distinct
        names instead of labels — the renderer is label-free)."""
        with self._inflight_lock:
            self._inflight_gauge.set(self._inflight)
        self._ready_gauge.set(1.0 if self.ready() else 0.0)
        self.slo_monitor.export(self._reg, self.registry)
        if self.promoter.enable:
            self.promoter.export()   # conveyor + drift gauges stay fresh
        # per-replica health gauges: 1 = running with a live dispatcher
        for name, entry in self.registry.items():
            for rh in entry.replicas.health():
                up = 1.0 if (rh["state"] == "running" and rh["alive"]) else 0.0
                self._reg.gauge(
                    f"gateway/replica_{name}_{rh['replica']}_up").set(up)
                if rh.get("backend") == "process":
                    # per-worker liveness detail: a climbing heartbeat age
                    # is the early-warning signal for a wedging child
                    age = rh.get("heartbeat_age_s")
                    self._reg.gauge(
                        f"gateway/worker_{name}_{rh['replica']}_"
                        f"heartbeat_age_s").set(
                            float(age) if age is not None else -1.0)
                    self._reg.gauge(
                        f"gateway/worker_{name}_{rh['replica']}_"
                        f"restarts").set(float(rh.get("restarts", 0)))
            self._reg.gauge(f"gateway/replicas_{name}_available").set(
                entry.replicas.available())
        parts = [self._reg.render_prometheus(prefix="distegnn")]
        for name, entry in self.registry.items():
            parts.append(entry.engine.metrics.registry.render_prometheus(
                prefix=_prom_name(f"distegnn_model_{name}")))
        return "".join(parts)

    # ---- plumbing --------------------------------------------------------
    def _try_acquire(self, priority: str = "interactive") -> bool:
        bulk = self.priority_enable and priority == "bulk"
        with self._inflight_lock:
            if self._inflight >= self.max_inflight:
                return False
            if bulk and self._inflight_bulk >= self.bulk_max_inflight:
                return False
            self._inflight += 1
            if bulk:
                self._inflight_bulk += 1
            return True

    def _release(self, priority: str = "interactive") -> None:
        bulk = self.priority_enable and priority == "bulk"
        with self._inflight_lock:
            self._inflight -= 1
            if bulk:
                self._inflight_bulk -= 1

    @staticmethod
    def _read_json(h) -> dict:
        try:
            length = int(h.headers.get("Content-Length", 0))
        except (TypeError, ValueError):
            raise PayloadError("bad Content-Length") from None
        if length <= 0:
            raise PayloadError("empty body (Content-Length required)")
        body = h.rfile.read(length)
        try:
            return json.loads(body)
        except json.JSONDecodeError as exc:
            raise PayloadError(f"invalid JSON: {exc}") from None

    @staticmethod
    def _send_text(h, status: int, text: str,
                   content_type: str = "text/plain",
                   retry_after: Optional[float] = None) -> int:
        body = text.encode("utf-8")
        h.send_response(status)
        h.send_header("Content-Type", content_type)
        h.send_header("Content-Length", str(len(body)))
        rid = getattr(h, "request_id", None)
        if rid is not None:
            h.send_header("X-Request-Id", rid)
        if retry_after is not None:
            # decimal seconds (spec allows integers; our client and most
            # libraries parse floats) — derived from queue depth / breaker
            # cooldown so clients back off instead of hammering a shed
            h.send_header("Retry-After", str(round(max(retry_after, 0.1), 3)))
        h.end_headers()
        h.wfile.write(body)
        return status

    @classmethod
    def _send_json(cls, h, status: int, obj,
                   retry_after: Optional[float] = None) -> int:
        return cls._send_text(h, status, json.dumps(obj),
                              content_type="application/json",
                              retry_after=retry_after)


def _make_handler(gateway: Gateway):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "distegnn-gateway"

        def log_message(self, format, *args):
            pass    # access logging is the serve/http span, not stderr

        def do_GET(self):
            gateway.dispatch(self, "GET")

        def do_POST(self):
            gateway.dispatch(self, "POST")

    return Handler
