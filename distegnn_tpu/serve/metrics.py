"""Serving metrics — thread-safe counters + a JSON-able snapshot.

One `ServeMetrics` instance is shared by the engine (compile cache, execute
latencies) and the batcher (queue depth, fill ratio, rejections). Everything
is a plain counter or a bounded latency reservoir guarded by one lock — the
serving hot path adds microseconds, never blocks on I/O.

Snapshot schema (docs/SERVING.md "Metrics"): every field is a number, so the
snapshot is directly a Prometheus-style scrape body or one BENCH JSON line.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile on an ascending list (0 <= q <= 100)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class ServeMetrics:
    """Counters for the serving path. All methods are thread-safe.

    Latencies are recorded in milliseconds into a bounded reservoir (the most
    recent ``reservoir`` samples) — p50/p99 are computed at snapshot time, so
    the record path is O(1).
    """

    def __init__(self, reservoir: int = 8192):
        self._lock = threading.Lock()
        self._reservoir = int(reservoir)
        self._t0 = time.perf_counter()
        self._lat_ms: List[float] = []
        self._queue_ms: List[float] = []
        self.requests_submitted = 0
        self.requests_completed = 0
        self.requests_failed = 0      # engine/model errors surfaced on futures
        self.requests_timeout = 0     # deadline passed while queued
        self.requests_rejected = 0    # bounded-queue backpressure (submit fails)
        self.requests_retried = 0     # re-executed individually after a batch failure
        self.requests_poison = 0      # failed even alone (the bad graph itself)
        self.worker_restarts = 0      # dispatcher thread died and was restarted
        self.batches_executed = 0
        self.batch_slots_total = 0    # sum of padded batch capacity over batches
        self.batch_slots_filled = 0   # sum of real requests over batches
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_evictions = 0
        self.queue_depth = 0          # gauge, set by the batcher

    # ---- recorders -------------------------------------------------------
    def submitted(self, n: int = 1) -> None:
        with self._lock:
            self.requests_submitted += n

    def rejected(self, n: int = 1) -> None:
        with self._lock:
            self.requests_rejected += n

    def timed_out(self, n: int = 1) -> None:
        with self._lock:
            self.requests_timeout += n

    def failed(self, n: int = 1) -> None:
        with self._lock:
            self.requests_failed += n

    def retried(self, n: int = 1) -> None:
        with self._lock:
            self.requests_retried += n

    def poison(self, n: int = 1) -> None:
        with self._lock:
            self.requests_poison += n

    def worker_restarted(self, n: int = 1) -> None:
        with self._lock:
            self.worker_restarts += n

    def batch_done(self, filled: int, capacity: int,
                   latencies_ms: List[float],
                   queue_ms_each: Optional[List[float]] = None) -> None:
        """One executed micro-batch: ``filled`` real requests padded to
        ``capacity`` slots, with one end-to-end latency per request."""
        with self._lock:
            self.batches_executed += 1
            self.batch_slots_total += capacity
            self.batch_slots_filled += filled
            self.requests_completed += filled
            self._lat_ms.extend(latencies_ms)
            if queue_ms_each:
                self._queue_ms.extend(queue_ms_each)
            del self._lat_ms[:-self._reservoir]
            del self._queue_ms[:-self._reservoir]

    def cache_event(self, hit: bool, evicted: int = 0) -> None:
        with self._lock:
            if hit:
                self.cache_hits += 1
            else:
                self.cache_misses += 1
            self.cache_evictions += evicted

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = depth

    # ---- export ----------------------------------------------------------
    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            lat = sorted(self._lat_ms)
            qms = sorted(self._queue_ms)
            elapsed = max(time.perf_counter() - self._t0, 1e-9)
            fill = (self.batch_slots_filled / self.batch_slots_total
                    if self.batch_slots_total else 0.0)
            return {
                "uptime_s": round(elapsed, 3),
                "requests_submitted": self.requests_submitted,
                "requests_completed": self.requests_completed,
                "requests_failed": self.requests_failed,
                "requests_timeout": self.requests_timeout,
                "requests_rejected": self.requests_rejected,
                "requests_retried": self.requests_retried,
                "requests_poison": self.requests_poison,
                "worker_restarts": self.worker_restarts,
                "requests_per_sec": round(self.requests_completed / elapsed, 3),
                "batches_executed": self.batches_executed,
                "batch_fill_ratio": round(fill, 4),
                "latency_p50_ms": round(_percentile(lat, 50), 3),
                "latency_p99_ms": round(_percentile(lat, 99), 3),
                "queue_wait_p50_ms": round(_percentile(qms, 50), 3),
                "queue_wait_p99_ms": round(_percentile(qms, 99), 3),
                "cache_hits": self.cache_hits,
                "cache_misses": self.cache_misses,
                "cache_evictions": self.cache_evictions,
                "queue_depth": self.queue_depth,
            }

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)
