"""Serving metrics — thread-safe counters + a JSON-able snapshot.

One `ServeMetrics` instance is shared by the engine (compile cache, execute
latencies) and the batcher (queue depth, fill ratio, rejections). Since the
obs subsystem landed (docs/OBSERVABILITY.md) this is a thin facade over the
shared ``distegnn_tpu.obs.metrics`` primitives — ``Counter`` / ``Gauge`` /
``LatencyReservoir`` in a private ``MetricsRegistry`` — so the serving hot
path still adds microseconds and never blocks on I/O, and the same registry
renders Prometheus text via :meth:`ServeMetrics.render_prometheus`.

Snapshot schema (docs/SERVING.md "Metrics") is unchanged: every field is a
number, so the snapshot is directly one BENCH JSON line.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional

from distegnn_tpu.obs.metrics import MetricsRegistry
from distegnn_tpu.obs.metrics import percentile as _percentile  # noqa: F401
# _percentile is re-exported for back-compat: this module used to own the
# nearest-rank implementation; obs.metrics.percentile is now THE one

_COUNTERS = (
    "requests_submitted", "requests_completed", "requests_failed",
    "requests_timeout", "requests_rejected", "requests_retried",
    "requests_poison", "worker_restarts", "requests_failed_over",
    "replica_restarts", "batches_executed",
    "batch_slots_total", "batch_slots_filled",
    "cache_hits", "cache_misses", "cache_evictions",
    "session_hits", "session_misses", "session_evictions",
)


class ServeMetrics:
    """Counters for the serving path. All methods are thread-safe.

    Latencies are recorded in milliseconds into a bounded reservoir (the most
    recent ``reservoir`` samples) — p50/p99 are computed at snapshot time, so
    the record path is O(1). Counter values stay readable as plain int
    attributes (``metrics.requests_submitted``) for existing callers.
    """

    def __init__(self, reservoir: int = 8192):
        self._registry = MetricsRegistry()
        self._t0 = time.perf_counter()
        self._c = {name: self._registry.counter("serve/" + name)
                   for name in _COUNTERS}
        self._qdepth = self._registry.gauge("serve/queue_depth")
        self._lat = self._registry.reservoir("serve/latency_ms",
                                             size=int(reservoir))
        self._queue = self._registry.reservoir("serve/queue_wait_ms",
                                               size=int(reservoir))

    def __getattr__(self, name: str):
        # attribute back-compat: counters/gauge read as plain numbers
        c = self.__dict__.get("_c") or {}
        if name in c:
            return int(c[name].value)
        if name == "queue_depth":
            return int(self.__dict__["_qdepth"].value)
        raise AttributeError(name)

    @property
    def registry(self) -> MetricsRegistry:
        return self._registry

    # ---- recorders -------------------------------------------------------
    def submitted(self, n: int = 1) -> None:
        self._c["requests_submitted"].add(n)

    def rejected(self, n: int = 1) -> None:
        self._c["requests_rejected"].add(n)

    def timed_out(self, n: int = 1) -> None:
        self._c["requests_timeout"].add(n)

    def failed(self, n: int = 1) -> None:
        self._c["requests_failed"].add(n)

    def retried(self, n: int = 1) -> None:
        self._c["requests_retried"].add(n)

    def poison(self, n: int = 1) -> None:
        self._c["requests_poison"].add(n)

    def worker_restarted(self, n: int = 1) -> None:
        self._c["worker_restarts"].add(n)

    def failed_over(self, n: int = 1) -> None:
        """A dead replica's in-flight request was re-dispatched to a
        survivor (the replica layer's at-most-once failover)."""
        self._c["requests_failed_over"].add(n)

    def replica_restarted(self, n: int = 1) -> None:
        """The supervisor restarted a crashed/wedged replica (distinct from
        ``worker_restarts``, the in-queue dispatcher crash containment)."""
        self._c["replica_restarts"].add(n)

    def batch_done(self, filled: int, capacity: int,
                   latencies_ms: List[float],
                   queue_ms_each: Optional[List[float]] = None) -> None:
        """One executed micro-batch: ``filled`` real requests padded to
        ``capacity`` slots, with one end-to-end latency per request."""
        self._c["batches_executed"].add(1)
        self._c["batch_slots_total"].add(capacity)
        self._c["batch_slots_filled"].add(filled)
        self._c["requests_completed"].add(filled)
        self._lat.record_many(latencies_ms)
        if queue_ms_each:
            self._queue.record_many(queue_ms_each)

    def cache_event(self, hit: bool, evicted: int = 0) -> None:
        self._c["cache_hits" if hit else "cache_misses"].add(1)
        if evicted:
            self._c["cache_evictions"].add(evicted)

    def session_event(self, hit: bool, evicted: int = 0) -> None:
        """One session-affinity prep-cache lookup (distinct from the compile
        cache tracked by :meth:`cache_event`)."""
        self._c["session_hits" if hit else "session_misses"].add(1)
        if evicted:
            self._c["session_evictions"].add(evicted)

    def set_queue_depth(self, depth: int) -> None:
        self._qdepth.set(depth)

    # ---- export ----------------------------------------------------------
    def snapshot(self) -> Dict[str, float]:
        c = {name: int(cnt.value) for name, cnt in self._c.items()}
        elapsed = max(time.perf_counter() - self._t0, 1e-9)
        fill = (c["batch_slots_filled"] / c["batch_slots_total"]
                if c["batch_slots_total"] else 0.0)
        return {
            "uptime_s": round(elapsed, 3),
            "requests_submitted": c["requests_submitted"],
            "requests_completed": c["requests_completed"],
            "requests_failed": c["requests_failed"],
            "requests_timeout": c["requests_timeout"],
            "requests_rejected": c["requests_rejected"],
            "requests_retried": c["requests_retried"],
            "requests_poison": c["requests_poison"],
            "worker_restarts": c["worker_restarts"],
            "requests_failed_over": c["requests_failed_over"],
            "replica_restarts": c["replica_restarts"],
            "requests_per_sec": round(c["requests_completed"] / elapsed, 3),
            "batches_executed": c["batches_executed"],
            "batch_fill_ratio": round(fill, 4),
            "latency_p50_ms": round(self._lat.percentile(50), 3),
            "latency_p99_ms": round(self._lat.percentile(99), 3),
            "queue_wait_p50_ms": round(self._queue.percentile(50), 3),
            "queue_wait_p99_ms": round(self._queue.percentile(99), 3),
            "cache_hits": c["cache_hits"],
            "cache_misses": c["cache_misses"],
            "cache_evictions": c["cache_evictions"],
            "session_hits": c["session_hits"],
            "session_misses": c["session_misses"],
            "session_evictions": c["session_evictions"],
            "queue_depth": int(self._qdepth.value),
        }

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)

    def render_prometheus(self, prefix: str = "distegnn") -> str:
        """Prometheus text exposition of the underlying registry (the obs
        subsystem's renderer; docs/SERVING.md "Metrics")."""
        return self._registry.render_prometheus(prefix=prefix)
