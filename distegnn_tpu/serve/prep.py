"""Session-affinity graph-prep cache — skip re-layout for repeat topologies.

Interactive clients (MD front-ends, trajectory viewers) stream many requests
for the SAME scene: positions move every frame, but the edge topology — and
therefore everything expensive about graph prep (Morton relabel, blocked
re-pack, remote-edge classification, bucket assignment) — is identical or
changes rarely. The serve path previously redid that work per request.

`SessionPrepCache` is a per-model LRU keyed on the client-supplied
``session_id``. Each entry holds a `PrepPlan`: the topology-only layout
artifacts (`ops.blocked.RepackPlan`, the remote selection indices, the
ladder bucket). A hit re-applies the plan to the fresh per-request arrays
with fancy-index gathers only — no sort, no classify, no bucket math — and
the produced dict carries the ``_blockified`` stamp so
`prepare_blocked_graph` inside `pad_graphs` is a no-op.

Correctness contract:
  - The plan is validated against a topology fingerprint (n, e, digest of
    edge_index bytes). A session whose topology changed gets a clean MISS
    (rebuild), never a stale layout.
  - Hit and miss paths produce bitwise-identical prepared dicts (tested in
    tests/test_serve_prep.py) — the cache changes latency, never results.
  - The Morton perm is computed from the positions seen at plan-build time.
    Later frames of the same session reuse it: any permutation is CORRECT
    (it is inverted before responding), the relabel just drifts from the
    spatially-optimal one as the scene evolves — locality degrades
    gracefully, results do not.

Plan arrays are shared across requests and never mutated in place: the
apply path allocates fresh per-request payload arrays, and the recovery
path in `prepare_blocked_graph` (epb mismatch when co-batched with a denser
peer) rebinds dict keys to new arrays rather than writing through.

Metrics: hits/misses/evictions are recorded on the engine's `ServeMetrics`
(``session_hits`` / ``session_misses`` / ``session_evictions``) and land in
``GET /metrics`` through the shared obs registry.

Capacity is bounded two ways: an entry-count LRU (``serve.session_cache``)
and, independently, a BYTE bound (``serve.session_cache_bytes``) accounted
with :func:`nbytes_of` over each stored plan — a 64-entry LRU of
million-node tile plans (serve/tiled.py, stored here under ``tile:<sid>``
keys) is multi-GB host RSS, so the entry count alone is a poor proxy.
Inserts evict-to-fit from the LRU tail; the live total is exported as the
``serve/session_cache_bytes`` gauge on /metrics.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import NamedTuple, Optional

import numpy as np

from distegnn_tpu import obs
from distegnn_tpu.ops.blocked import (RepackPlan, max_block_degree,
                                      repack_blocked)
from distegnn_tpu.serve.buckets import Bucket, BucketLadder
from distegnn_tpu.serve.metrics import ServeMetrics


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def nbytes_of(obj) -> int:
    """Recursive host-memory estimate of a cached plan: every numpy array's
    ``nbytes``, walked through tuples/NamedTuples/lists/dicts. Scalars and
    tiny metadata round to 0 — arrays are what dominate a plan."""
    if isinstance(obj, np.ndarray):
        return int(obj.nbytes)
    if isinstance(obj, dict):
        return sum(nbytes_of(v) for v in obj.values())
    if isinstance(obj, (tuple, list)):
        return sum(nbytes_of(v) for v in obj)
    return 0


def topology_fingerprint(edge_index: np.ndarray, n_nodes: int) -> tuple:
    """(n, e, digest) — positions excluded on purpose: a session's frames
    move, its topology (usually) doesn't."""
    ei = np.ascontiguousarray(edge_index)
    digest = hashlib.blake2b(ei.tobytes(), digest_size=16).digest()
    return (int(n_nodes), int(ei.shape[1]), ei.dtype.str, digest)


class PrepPlan(NamedTuple):
    """Topology-only prep artifacts for one session (one cache entry)."""

    fingerprint: tuple
    bucket: Bucket                   # from the RAW (n, e) — the submit rung
    repack: Optional[RepackPlan]     # blocked layouts; None for plain
    remote_sel: Optional[np.ndarray]  # row-sorted remote slot indices
    sort: Optional[np.ndarray]       # plain layouts: row-sort of raw edges
    edge_index: Optional[np.ndarray]  # plain layouts: the sorted edge list

    @property
    def perm(self) -> Optional[np.ndarray]:
        return self.repack.perm if self.repack is not None else None


class PrepResult(NamedTuple):
    graph: dict
    bucket: Bucket
    perm: Optional[np.ndarray]       # perm[new] = old; None for plain plans
    hit: bool


class SessionPrepCache:
    """LRU of `PrepPlan`s keyed by session id. Thread-safe (HTTP handlers
    call `prepare` concurrently); plan building runs outside the lock, so a
    slow build never blocks other sessions — two racing builds of the same
    session are both correct and the later insert wins."""

    def __init__(self, capacity: int, *, ladder: BucketLadder,
                 layout_opts: Optional[dict] = None,
                 metrics: Optional[ServeMetrics] = None, bits: int = 16,
                 max_bytes: int = 0):
        if capacity < 1:
            raise ValueError("SessionPrepCache: capacity must be >= 1")
        self.capacity = int(capacity)
        self.max_bytes = int(max_bytes)   # 0 = entry-count bound only
        self.ladder = ladder
        self.metrics = metrics
        self.bits = int(bits)
        opts = dict(layout_opts or {})
        self.edge_block = int(opts.get("edge_block", 0))
        self.edge_tile = int(opts.get("edge_tile", 512))
        self.split_remote = bool(opts.get("split_remote", False))
        self._plans: "OrderedDict[str, object]" = OrderedDict()
        self._sizes: dict = {}
        self._bytes = 0
        self._g_bytes = (metrics.registry.gauge("serve/session_cache_bytes")
                         if metrics is not None else None)
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._plans)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def _insert(self, key: str, plan) -> int:
        """LRU insert with byte accounting: frees the key's old entry (a
        same-session replace is not an eviction), then evicts from the LRU
        tail until both the entry-count and byte bounds admit the new plan.
        Returns the number of OTHER entries evicted."""
        size = nbytes_of(plan)
        with self._lock:
            if key in self._plans:
                self._bytes -= self._sizes.pop(key, 0)
                self._plans.pop(key)
            evicted = 0
            while self._plans and (
                    len(self._plans) >= self.capacity
                    or (self.max_bytes
                        and self._bytes + size > self.max_bytes)):
                k, _ = self._plans.popitem(last=False)
                self._bytes -= self._sizes.pop(k, 0)
                evicted += 1
            self._plans[key] = plan
            self._sizes[key] = size
            self._bytes += size
            if self._g_bytes is not None:
                self._g_bytes.set(self._bytes)
        return evicted

    # ---- plan building ---------------------------------------------------
    def _build(self, graph: dict, fp: tuple) -> PrepPlan:
        ei = np.asarray(graph["edge_index"])
        n = int(graph["loc"].shape[0])
        bucket = self.ladder.bucket_for(n, int(ei.shape[1]))
        if not self.edge_block:
            # plain layout: stable row-sort keeps pad_graphs on the
            # sorted-scatter lowering; nothing else is topology-derived
            sort = np.argsort(ei[0], kind="stable")
            return PrepPlan(fingerprint=fp, bucket=bucket, repack=None,
                            remote_sel=None, sort=sort,
                            edge_index=np.ascontiguousarray(ei[:, sort]))
        # blocked layout: mirror pad_batch's node snap exactly, then relabel
        # along the Morton curve and derive epb from the RELABELED rows (the
        # perm moves edges between blocks, so degree must be measured after)
        from distegnn_tpu.ops.order import morton_perm

        nb = -(-bucket.n // self.edge_block)
        if self.split_remote:
            nb = max(nb, 3)  # fused kernel's VMEM window spans 3 blocks
        N = nb * self.edge_block
        perm = morton_perm(np.asarray(graph["loc"]), bits=self.bits)
        inv = np.empty_like(perm)
        inv[perm] = np.arange(n, dtype=perm.dtype)
        ei2 = inv[ei.astype(np.int64, copy=False)]
        deg = max_block_degree(np.sort(ei2[0]), N, self.edge_block)
        epb = _round_up(max(deg, 1), self.edge_tile)
        plan = repack_blocked(ei2, None, n_nodes_padded=N, epb=epb,
                              block=self.edge_block)._replace(perm=perm)
        remote_sel = None
        if self.split_remote:
            from distegnn_tpu.ops.edge_pipeline import remote_selection

            remote_sel = remote_selection(plan.edge_index,
                                          block=self.edge_block, n_nodes=N)
        return PrepPlan(fingerprint=fp, bucket=bucket, repack=plan,
                        remote_sel=remote_sel, sort=None, edge_index=None)

    # ---- plan application ------------------------------------------------
    def _apply(self, graph: dict, plan: PrepPlan) -> dict:
        g = dict(graph)
        loc = np.asarray(graph["loc"])
        # loc_mean is permutation-invariant; pin it before reordering so the
        # prepared dict never falls back to a mean over permuted copies
        if g.get("loc_mean") is None:
            g["loc_mean"] = loc.mean(axis=0)
        if plan.repack is None:
            g["edge_index"] = plan.edge_index
            if graph.get("edge_attr") is not None:
                g["edge_attr"] = np.ascontiguousarray(
                    np.asarray(graph["edge_attr"])[plan.sort])
            return g
        p = plan.repack
        for key in ("node_feat", "loc", "vel", "target", "node_attr"):
            if graph.get(key) is not None:
                g[key] = np.ascontiguousarray(np.asarray(graph[key])[p.perm])
        ea = graph.get("edge_attr")
        if ea is None:
            ea = np.zeros((graph["edge_index"].shape[1], 0), np.float32)
        g["edge_index"] = p.edge_index
        g["edge_attr"] = p.apply_edge_attr(np.asarray(ea))
        g["_edge_mask"] = p.edge_mask
        g["_edge_pair"] = None       # serve batches run compute_pair=False
        g["_blockified"] = p.stamp
        if plan.remote_sel is not None:
            g["_remote_sel"] = plan.remote_sel
        return g

    # ---- the entry point -------------------------------------------------
    def prepare(self, session_id: str, graph: dict,
                request_id: Optional[str] = None) -> PrepResult:
        """Lay out ``graph`` for the serve path, reusing the session's plan
        when its topology fingerprint still matches. ``request_id`` (the
        gateway's trace id) tags the ``serve/prep`` event so the waterfall
        stitcher sees the prep leg of a traced request."""
        t0 = time.perf_counter()
        fp = topology_fingerprint(graph["edge_index"], graph["loc"].shape[0])
        with self._lock:
            plan = self._plans.get(session_id)
            if plan is not None and plan.fingerprint == fp:
                self._plans.move_to_end(session_id)
                hit, evicted = True, 0
            else:
                plan = None
        if plan is None:
            plan = self._build(graph, fp)
            evicted = self._insert(session_id, plan)
            hit = False
        if self.metrics is not None:
            self.metrics.session_event(hit=hit, evicted=evicted)
        result = PrepResult(graph=self._apply(graph, plan),
                            bucket=plan.bucket, perm=plan.perm, hit=hit)
        attrs = {"request_id": request_id} if request_id is not None else {}
        obs.event("serve/prep", session=str(session_id), hit=hit,
                  dur_s=round(time.perf_counter() - t0, 6), **attrs)
        return result

    # ---- tiled giant-scene plans (serve/tiled.py) ------------------------
    def prepare_tile(self, session_id: str, graph: dict, build,
                     request_id: Optional[str] = None):
        """Session-cached tile plan for a giant scene: same fingerprint
        contract and metrics as :meth:`prepare`, stored in the SAME LRU +
        byte budget under a ``tile:`` key (tile plans are the entries the
        byte bound exists for). ``build`` is a zero-arg plan builder (the
        tiled executor's ``plan``); returns ``(plan, hit)``."""
        t0 = time.perf_counter()
        fp = topology_fingerprint(graph["edge_index"], graph["loc"].shape[0])
        key = "tile:" + str(session_id)
        with self._lock:
            ent = self._plans.get(key)
            if ent is not None and ent[0] == fp:
                self._plans.move_to_end(key)
                plan, hit, evicted = ent[1], True, 0
            else:
                plan = None
        if plan is None:
            plan = build()
            evicted = self._insert(key, (fp, plan))
            hit = False
        if self.metrics is not None:
            self.metrics.session_event(hit=hit, evicted=evicted)
        attrs = {"request_id": request_id} if request_id is not None else {}
        obs.event("serve/prep", session=str(session_id), hit=hit,
                  plan_kind="tile_plan",
                  dur_s=round(time.perf_counter() - t0, 6), **attrs)
        return plan, hit
