"""Device-parallel tile rounds — multi-chip serving for million-node scenes.

The sequential tiled executor (serve/tiled.py) walks a scene's tiles one at
a time on ONE device, so a TPU slice serves a giant scene no faster than a
single chip. Because `plan_tiles` quantizes every tile to one shared padded
shape (``TilePlan.shape_key``), tiles stack cleanly on a leading device
axis: this module groups them into *rounds* of D (``ops/tiling.plan_rounds``
— LPT over the plan's work model) and runs each round through ONE pmapped
per-tile EGCL executable across D devices. The compile-cache key extends the
sequential ``("tile_layer",) + shape_key`` tuple with D — exactly one
executable regardless of tile count or scene size, same as the sequential
invariant.

What stays the same, per the exactness argument of ops/tiling.py:

  - Every tile reads LAYER-INPUT state (h/x snapshots + the layer-input
    virtual X/Hv), so tiles of one layer commute — running D of them
    simultaneously is the same sum in a different order.
  - The halo exchange stays a host-side gather between layers; it is merely
    staged per-round, with round k+1's per-device ``device_put`` overlapping
    round k's compute (the double-buffering of the sequential path, widened
    to D transfers). Device residency stays bounded by TWO staged rounds.
  - The virtual-node closure is exact: each round psums its slots' masked
    partials across the device axis (``models/fast_egnn.reduce_tile_
    partials``), the host accumulates round sums across rounds, and
    ``tiled_virtual_update`` closes the layer once — identical numerators
    and denominator as the sequential accumulation.

Ragged last round (``T % D != 0``): free slots carry a zero-filled filler
tile whose node_mask is all-zero AND a 0.0 validity flag, so they
contribute exactly nothing to the psums and their outputs are discarded.

The schedule itself is device-count-agnostic state-free planning: a
``TilePlan`` built (or session-cached) at ``devices: 1`` serves at any D
without a rebuild — ``plan_rounds`` derives rounds from the plan on the
fly. Everything here is CPU-testable on 8 virtual devices via
``--xla_force_host_platform_device_count`` (tests/test_tiled_mesh.py);
measured multi-chip speedups land through the ``bench_tiled_mesh``
hw_session leg per the ROADMAP evidence rule.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distegnn_tpu import obs
from distegnn_tpu.ops.tiling import TilePlan, plan_rounds

#: pmap axis name for one round's device dimension
ROUND_AXIS = "tile_round"


def resolve_devices(spec, n_tiles: Optional[int] = None) -> int:
    """Resolve the ``serve.tiled.devices`` knob to a usable device count.

    ``"auto"`` takes every local device; an int is clamped (with an obs
    event, never an error — a config written for a 4-chip slice must still
    serve on 1) to what this process actually has. Returns 1 when there is
    nothing to parallelize over (``n_tiles`` <= 1 included: a one-tile
    scene has no round structure worth a pmap dispatch)."""
    avail = jax.local_device_count()
    if spec == "auto":
        d = avail
    else:
        d = int(spec)
        if d > avail:
            obs.event("serve/tiled_devices_clamped", requested=d,
                      available=avail)
            d = avail
    if n_tiles is not None and n_tiles <= 1:
        return 1
    return max(1, d)


def _round_executable(ex, plan: TilePlan, devices) -> Callable:
    """THE round executable: one EGCL layer over D same-shape tiles, one
    per device, partials psum-closed across the round axis. Reuses the
    sequential executor's un-jitted single-tile callable unchanged; the
    compile-cache key is the sequential key extended with D, so every round
    of every layer of every same-rung scene shares this one program."""
    from distegnn_tpu.models.fast_egnn import reduce_tile_partials

    model = ex.engine.model
    fn = ex._layer_callable(plan)
    D = len(devices)

    def mapped(gcl_params, h, x, batch, X, Hv, cm, valid):
        h2, x2, tx, vf, ct = fn(gcl_params, h, x, batch, X, Hv, cm)
        tx, vf, ct = reduce_tile_partials(tx, vf, ct, valid, ROUND_AXIS)
        return h2, x2, tx, vf, ct

    key = ("tile_layer",) + plan.shape_key + (
        ex.edge_impl, int(model.hidden_nf), int(model.virtual_channels), D)
    return ex.engine._compiled(
        key, lambda: jax.pmap(
            mapped, axis_name=ROUND_AXIS,
            in_axes=(None, 0, 0, 0, None, None, None, 0),
            devices=devices))


def run_rounds(ex, plan: TilePlan, batches, h_full: np.ndarray,
               x_full: np.ndarray, X, Hv, gcls, n_layers: int, virt_fn,
               progress: Optional[Callable] = None, n_devices: int = 2):
    """Execute all layers of one tiled scene as device-parallel rounds.

    Mirrors the sequential layer loop of ``TiledExecutor.predict`` (same
    host-side halo gather, same double-buffered staging, same virtual
    closure) with the tile axis folded into rounds of ``n_devices``.
    ``progress(layer=..., round=..., n_layers=..., n_rounds=...,
    n_tiles=...)`` fires after each ROUND; returning False cancels the
    remaining compute at the next round boundary (the NDJSON disconnect
    contract, at round granularity). Returns ``(h_full, x_full, stats,
    cancelled)`` with stats carrying rounds/devices/round_imbalance plus
    the stall, halo-gather, and per-round timing gauge feeds."""
    devices = jax.local_devices()[:n_devices]
    D = len(devices)
    sched = plan_rounds(plan, D)
    rounds = sched.rounds
    R = sched.n_rounds
    L = int(n_layers)
    tn = plan.tile_nodes
    H = h_full.shape[1]
    C = int(X.shape[2])
    nd = int(np.asarray(batches[0].node_mask).shape[1])
    round_fn = _round_executable(ex, plan, devices)

    # ragged-round filler: zero inputs + an all-zero node_mask clone of tile
    # 0's batch (finite math, zero masked partials) + a 0.0 validity flag
    pad_batch = batches[0].replace(
        node_mask=np.zeros_like(np.asarray(batches[0].node_mask)))
    zeros_h = np.zeros((1, nd, H), np.float32)
    zeros_x = np.zeros((1, nd, 3), np.float32)
    valid_1 = np.asarray(1.0, np.float32)
    valid_0 = np.asarray(0.0, np.float32)

    halo_gather_s = 0.0

    def stage_round(ri: int, h_src: np.ndarray, x_src: np.ndarray):
        """Gather round ri's tile inputs from the layer-input snapshot and
        start their per-device H2D; returns sharded device handles (the
        transfers proceed async under the previous round's compute)."""
        nonlocal halo_gather_s
        t0 = time.perf_counter()
        shards = []
        tiles_r = rounds[ri]
        for slot in range(D):
            if slot < len(tiles_r):
                s = plan.tiles[tiles_r[slot]]
                h_t = np.zeros((1, nd, H), np.float32)
                x_t = np.zeros((1, nd, 3), np.float32)
                h_t[0, :s.n_own] = h_src[s.start:s.stop]
                x_t[0, :s.n_own] = x_src[s.start:s.stop]
                hh = int(s.halo.shape[0])
                if hh:
                    h_t[0, tn:tn + hh] = h_src[s.halo]
                    x_t[0, tn:tn + hh] = x_src[s.halo]
                shards.append((h_t, x_t, batches[tiles_r[slot]], valid_1))
            else:
                shards.append((zeros_h, zeros_x, pad_batch, valid_0))
        halo_gather_s += time.perf_counter() - t0
        return jax.device_put_sharded(shards, devices)

    stall_s = 0.0
    round_s = 0.0
    rounds_done = 0
    cancelled = False
    t_loop = time.perf_counter()
    for li in range(L):
        # scene-global coordinate mean of the layer input (psum #1),
        # identical to the sequential path
        cm = jnp.asarray(x_full.mean(axis=0, dtype=np.float64)
                         .astype(np.float32)[None])
        h_next = np.empty_like(h_full)
        x_next = np.empty_like(x_full)
        tx_l = np.zeros((1, 3, C), np.float32)
        vf_l = np.zeros((1, C, H), np.float32)
        ct_l = np.zeros((1,), np.float32)
        staged = stage_round(0, h_full, x_full)
        for ri, tiles_r in enumerate(rounds):
            t_round = time.perf_counter()
            tb = time.perf_counter()
            jax.block_until_ready(staged)   # residual un-hidden H2D
            stall_s += time.perf_counter() - tb
            h_d, x_d, b_d, v_d = staged
            out = round_fn(gcls[li], h_d, x_d, b_d, X, Hv, cm, v_d)
            # double buffer: round ri+1's D transfers overlap this compute.
            # Later rounds read h_full/x_full (the LAYER INPUT), never
            # h_next — the same invariant that makes tiling exact.
            staged = (stage_round(ri + 1, h_full, x_full)
                      if ri + 1 < R else None)
            h_o = np.asarray(out[0])        # [D, 1, nd, H] — syncs compute
            x_o = np.asarray(out[1])
            for slot, t in enumerate(tiles_r):
                s = plan.tiles[t]
                h_next[s.start:s.stop] = h_o[slot, 0, :s.n_own]
                x_next[s.start:s.stop] = x_o[slot, 0, :s.n_own]
            # the psum'd partials are identical on every device: take slot 0
            tx_l += np.asarray(out[2])[0]
            vf_l += np.asarray(out[3])[0]
            ct_l += np.asarray(out[4])[0]
            round_s += time.perf_counter() - t_round
            rounds_done += 1
            if progress is not None:
                ok = progress(layer=li, round=ri, n_layers=L, n_rounds=R,
                              n_tiles=plan.n_tiles)
                if ok is False:
                    cancelled = True
                    break
        if cancelled:
            break
        h_full, x_full = h_next, x_next
        # close the layer's virtual state from the accumulated round psums
        Hv, X = virt_fn(gcls[li], Hv, X, jnp.asarray(tx_l),
                        jnp.asarray(vf_l), jnp.asarray(ct_l))
    loop_s = max(time.perf_counter() - t_loop, 1e-9)
    stats = {
        "devices": D,
        "rounds": R,
        "round_imbalance": sched.round_imbalance,
        "stall_fraction": min(stall_s / loop_s, 1.0),
        "round_ms": round_s / max(rounds_done, 1) * 1e3,
        "halo_gather_ms": halo_gather_s * 1e3,
    }
    return h_full, x_full, stats, cancelled
