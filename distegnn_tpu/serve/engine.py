"""InferenceEngine — per-bucket jit compile cache over a model's apply fn.

The training loop compiles ONE program per run (static shapes, data/loader).
Serving sees heterogeneous graphs, so the engine quantizes every request to a
`BucketLadder` rung and keeps one compiled executable per rung in a bounded
LRU — the GSPMD serving recipe (arXiv:2105.04663): a small set of padded
shapes amortizes XLA compilation across all traffic.

Two entry points:
  - ``predict_batch`` — one model step over up to ``max_batch`` same-bucket
    graphs. The batch axis is ALWAYS padded to ``max_batch`` (replicating a
    real graph), so a bucket owns exactly one executable regardless of how
    full its micro-batches run — compile count == rung count, and the
    batch-fill ratio is a metrics problem, not a compile-cache problem.
  - ``rollout`` — K autoregressive steps via `rollout.make_rollout_fn`
    (radius graph rebuilt on device each step); per-step capacity overflow
    flags are checked after the scan and surfaced as RolloutOverflowError,
    never silently dropped (the rollout.py contract).

Donation: on TPU the padded input batch is donated to the executable
(``donate_argnums``) so XLA reuses its buffers for the outputs — the steady
state allocates nothing per request. CPU ignores donation (and warns), so
``donate='auto'`` enables it only when the backend is a TPU.
"""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distegnn_tpu import obs
from distegnn_tpu.obs import jaxprobe
from distegnn_tpu.serve.buckets import Bucket, BucketLadder
from distegnn_tpu.serve.metrics import ServeMetrics


def _rid_attrs(request_ids: Optional[Sequence[str]]) -> dict:
    """serve/execute span attrs for a batch's trace ids; {} when the batch
    carries none (in-proc callers) so untraced events stay compact."""
    ids = [r for r in (request_ids or []) if r is not None]
    return {"request_ids": ids} if ids else {}


class RolloutOverflowError(RuntimeError):
    """A rollout step overflowed the static radius-graph capacity bounds
    (max_per_cell / max_degree) — results would silently drop edges."""


class CanaryError(RuntimeError):
    """The blue/green canary forward pass rejected candidate params
    (non-finite outputs or a shape mismatch) — the swap must roll back."""


class MixedRolloutStepsError(ValueError):
    """A rollout micro-batch mixed different steps-K. The scan length is
    static (part of the compiled executable), so scenes with different K can
    never share a batch — the batcher keys on (rung, steps) to prevent this;
    hitting it through ``rollout_batch`` directly is a caller bug."""


class InferenceEngine:
    """Bucketed, compile-cached inference over one model + params.

    Args:
      model: a flax module whose ``apply(params, GraphBatch)`` returns a
        tuple with predicted positions ``[B, N, 3]`` first (the registry
        contract), or pass ``apply_fn`` explicitly.
      params: the model params pytree.
      ladder: BucketLadder (default: serving defaults).
      max_batch: fixed padded batch of every compiled program.
      cache_size: max live executables; least-recently-used rungs are
        evicted (and recompiled on return — counted in metrics).
      donate: True | False | 'auto' (TPU only).
      rollout_opts: kwargs forwarded to make_rollout_fn (radius, max_degree,
        max_per_cell, edge_block, ...) — required for ``rollout``.
      layout_opts: kwargs forwarded to ``ladder.pad_batch`` (edge_block,
        edge_tile, split_remote) — a model with ``edge_impl='fused'`` needs
        ``{'edge_block': 512, 'split_remote': True}`` so every served batch
        carries the blocked layout + remote tail.
      session_cache: capacity of the session-affinity prep cache
        (serve/prep.py) exposed as ``engine.prep_cache``; 0 (default)
        disables it.
      session_cache_bytes: byte bound on the prep cache's stored plans
        (evict-to-fit; 0 = entry-count bound only). Million-node tile
        plans make the entry count a poor proxy for host RSS.
      tiled: ``serve.tiled:`` config dict — builds the tiled executor
        (serve/tiled.py) for scenes above the ladder cap; None disables.
    """

    def __init__(self, model, params, *, ladder: Optional[BucketLadder] = None,
                 max_batch: int = 8, cache_size: int = 32,
                 donate: Any = "auto", metrics: Optional[ServeMetrics] = None,
                 apply_fn: Optional[Callable] = None,
                 rollout_opts: Optional[dict] = None,
                 layout_opts: Optional[dict] = None,
                 session_cache: int = 0,
                 session_cache_bytes: int = 0,
                 tiled: Optional[dict] = None):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if cache_size < 1:
            raise ValueError("cache_size must be >= 1")
        self.model = model
        self.params = params
        self.ladder = ladder or BucketLadder()
        self.max_batch = int(max_batch)
        self.cache_size = int(cache_size)
        self.metrics = metrics or ServeMetrics()
        self._apply_fn = apply_fn or (
            lambda p, batch: model.apply(p, batch)[0])
        self._rollout_opts = dict(rollout_opts or {})
        self._layout_opts = dict(layout_opts or {})
        # session-affinity prep cache (serve/prep.py): 0 disables. Created
        # here so the transport finds it on the engine and its hit/miss
        # counters share this engine's metrics registry.
        if session_cache:
            from distegnn_tpu.serve.prep import SessionPrepCache

            self.prep_cache: Optional[SessionPrepCache] = SessionPrepCache(
                int(session_cache), ladder=self.ladder,
                layout_opts=self._layout_opts, metrics=self.metrics,
                max_bytes=int(session_cache_bytes))
        else:
            self.prep_cache = None
        # tiled executor (serve/tiled.py): scenes above the ladder cap run
        # as a scan over fixed-shape tiles instead of 413-rejecting
        if tiled is not None:
            from distegnn_tpu.serve.tiled import TiledExecutor

            self.tiled: Optional["TiledExecutor"] = TiledExecutor(self, tiled)
        else:
            self.tiled = None
        if donate == "auto":
            donate = jax.default_backend() == "tpu"
        self._donate = bool(donate)
        # the executable's identity includes the model's edge path and, for
        # fused_stack, the stack depth: one multi-layer kernel per (rung, L).
        # A blue/green swap to a different depth must not reuse the old one.
        _impl = str(getattr(model, "edge_impl", "plain") or "plain")
        self._stack_key: Tuple = (
            _impl, int(getattr(model, "n_layers", 0) or 0)
            if _impl == "fused_stack" else 0)
        self._cache: "OrderedDict[Tuple, Callable]" = OrderedDict()
        # one lock for the cache; device execution itself is serialized by
        # the runtime, and the batcher calls from a single dispatch thread
        self._lock = threading.Lock()

    # ---- compile cache ---------------------------------------------------
    def _compiled(self, key: Tuple, build: Callable[[], Callable]) -> Callable:
        with self._lock:
            fn = self._cache.get(key)
            if fn is not None:
                self._cache.move_to_end(key)
                self.metrics.cache_event(hit=True)
                return fn
            evicted = 0
            while len(self._cache) >= self.cache_size:
                self._cache.popitem(last=False)
                evicted += 1
            fn = build()
            self._cache[key] = fn
            self.metrics.cache_event(hit=False, evicted=evicted)
            # cache misses land on the event stream: a miss AFTER warmup is
            # either an un-warmed rung (fine, once) or an eviction storm
            obs.event("serve/cache_miss", key=repr(key), evicted=evicted)
            return fn

    def cache_stats(self) -> Dict[str, int]:
        with self._lock:
            live = len(self._cache)
        snap = self.metrics.snapshot()
        return {"live": live, "hits": int(snap["cache_hits"]),
                "misses": int(snap["cache_misses"]),
                "evictions": int(snap["cache_evictions"])}

    # ---- one-step prediction --------------------------------------------
    def _build_predict(self, bucket: Bucket) -> Callable:
        donate = (1,) if self._donate else ()
        jitted = jax.jit(self._apply_fn, donate_argnums=donate)
        return jitted

    def predict_batch(self, graphs: Sequence[dict],
                      bucket: Optional[Bucket] = None,
                      request_ids: Optional[Sequence[str]] = None,
                      ) -> List[np.ndarray]:
        """Run one model step over same-bucket graphs; returns the UNPADDED
        per-graph predicted positions ``[n_i, 3]`` (numpy, host-synced).
        ``request_ids`` (gateway trace ids, position-aligned with ``graphs``)
        are stamped on the ``serve/execute`` span."""
        if not graphs:
            return []
        if len(graphs) > self.max_batch:
            raise ValueError(f"{len(graphs)} graphs > max_batch {self.max_batch}")
        if bucket is None:
            bs = [self.ladder.bucket_of_graph(g) for g in graphs]
            # elementwise max: the rung admitting every graph on BOTH axes
            bucket = Bucket(max(b.n for b in bs), max(b.e for b in bs))
        batch, n_real = self.ladder.pad_batch(graphs, bucket, self.max_batch,
                                              **self._layout_opts)
        # key on the RESULTING shapes, not the rung: blocked layouts derive
        # edges_per_block / remote width per batch, and two rungs that pad to
        # the same shapes may share one executable (plain layout keys reduce
        # to the old (bucket.n, bucket.e, max_batch) triple)
        rpad = (batch.remote_edge_mask.shape[-1]
                if batch.remote_edge_mask is not None else 0)
        fn = self._compiled(("predict", batch.max_nodes, batch.max_edges,
                             batch.edge_block, rpad, self.max_batch)
                            + self._stack_key,
                            lambda: self._build_predict(bucket))
        with obs.span("serve/execute", n=batch.max_nodes, e=batch.max_edges,
                      filled=n_real, capacity=self.max_batch,
                      **_rid_attrs(request_ids)):
            x = np.asarray(fn(self.params, batch))       # [max_batch, N, 3]
        return [x[i, : graphs[i]["loc"].shape[0]].copy()
                for i in range(n_real)]

    def predict(self, graph: dict) -> np.ndarray:
        """Single-graph convenience wrapper over ``predict_batch``."""
        return self.predict_batch([graph])[0]

    def warmup(self, sizes: Sequence[Tuple[int, int]]) -> List[Bucket]:
        """Pre-compile the rungs admitting the given (n_nodes, n_edges)
        sizes (distinct rungs only). Returns the warmed buckets."""
        from distegnn_tpu.serve.buckets import synthetic_graph

        jaxprobe.set_phase("serve_warmup")
        warmed: List[Bucket] = []
        with obs.span("serve/warmup", rungs=0) as sp:
            for n, e in sizes:
                b = self.ladder.bucket_for(n, e)
                if b in warmed:
                    continue
                # a tiny probe graph: the compiled shape is fixed by (bucket,
                # max_batch) alone, and padding admits any graph under the rung
                g = synthetic_graph(2, seed=0,
                                    feat_nf=self._probe_feat_nf(),
                                    edge_attr_nf=self._probe_edge_attr_nf())
                self.predict_batch([g], bucket=b)
                warmed.append(b)
            sp.set(rungs=len(warmed))
        jaxprobe.set_phase("serve")
        return warmed

    def _probe_feat_nf(self) -> int:
        return int(getattr(self.model, "node_feat_nf", 1) or 1)

    def _probe_edge_attr_nf(self) -> int:
        return int(getattr(self.model, "edge_attr_nf", 2) or 0)

    # ---- tiled giant-scene path (serve/tiled.py) ------------------------
    @property
    def tiled_enabled(self) -> bool:
        """True when scenes above the ladder cap dispatch to the tiled
        executor instead of 413-rejecting."""
        return self.tiled is not None and self.tiled.enable

    def predict_tiled(self, graph: dict,
                      request_id: Optional[str] = None,
                      progress: Optional[Callable] = None) -> dict:
        """One giant scene through the tile executor. The transport stashes
        a session-cached plan on the graph as ``_tile_plan``; absent (or
        built for a different layout) the executor replans inline. Plans
        carry no device count, so the same cached plan serves sequentially
        or as device-parallel rounds (``serve.tiled.devices``,
        serve/mesh_tiled.py) unchanged."""
        if self.tiled is None:
            raise RuntimeError(
                "engine built without serve.tiled config; giant scenes "
                "cannot be served")
        plan = graph.pop("_tile_plan", None)
        return self.tiled.predict(graph, plan=plan, request_id=request_id,
                                  progress=progress)

    @property
    def rollout_enabled(self) -> bool:
        """True when the engine was built with rollout_opts — the public
        capability flag the registry/transport consult instead of reaching
        into ``_rollout_opts``."""
        return bool(self._rollout_opts)

    def params_digest(self) -> str:
        """16-byte blake2b over the flattened param leaves (shape- and
        dtype-tagged, in canonical tree order) — the cross-process parity
        fingerprint. The parent compares its own digest against a worker
        child's at the spawn handshake, so silently divergent init (seed or
        environment drift) becomes a typed WorkerSpawnError instead of a
        wrong answer. Both sides build params through
        ``engine_with_params_from_config``, so the treedefs (and hence the
        leaf order) match by construction."""
        import hashlib

        h = hashlib.blake2b(digest_size=16)
        for leaf in jax.tree_util.tree_leaves(self.params):
            arr = np.asarray(leaf)
            h.update(str(arr.shape).encode())
            h.update(str(arr.dtype).encode())
            h.update(np.ascontiguousarray(arr).tobytes())
        return h.hexdigest()

    # ---- blue/green canary ----------------------------------------------
    def canary(self, params, buckets: Sequence[Bucket]) -> int:
        """Forward CANDIDATE params through each bucket's compiled
        executable on a synthetic graph, without flipping ``self.params``.

        Reuses the exact predict compile-cache keys, so canarying warmed
        rungs compiles nothing new. Raises :class:`CanaryError` on
        non-finite outputs or a shape mismatch; returns the number of rungs
        checked. Used by the registry's blue/green swap before a replica is
        flipped to new params.
        """
        from distegnn_tpu.serve.buckets import synthetic_graph

        g = synthetic_graph(2, seed=0, feat_nf=self._probe_feat_nf(),
                            edge_attr_nf=self._probe_edge_attr_nf())
        checked = 0
        for b in buckets or [self.ladder.bucket_of_graph(g)]:
            batch, _ = self.ladder.pad_batch([g], b, self.max_batch,
                                             **self._layout_opts)
            rpad = (batch.remote_edge_mask.shape[-1]
                    if batch.remote_edge_mask is not None else 0)
            fn = self._compiled(("predict", batch.max_nodes, batch.max_edges,
                                 batch.edge_block, rpad, self.max_batch)
                                + self._stack_key,
                                lambda: self._build_predict(b))
            out = np.asarray(fn(params, batch))
            if out.shape != (self.max_batch, batch.max_nodes, 3):
                raise CanaryError(
                    f"canary output shape {out.shape} != expected "
                    f"{(self.max_batch, batch.max_nodes, 3)} on rung {b}")
            n_real = int(g["loc"].shape[0])
            if not np.isfinite(out[0, :n_real]).all():
                raise CanaryError(
                    f"canary produced non-finite outputs on rung {b} "
                    f"(candidate params are poisoned)")
            checked += 1
        return checked

    # ---- K-step rollout --------------------------------------------------
    def _rollout_fn_opts(self) -> dict:
        """rollout_opts resolved against the MODEL's feature widths: the
        rollout defaults (speed [N,1], distance-twice [E,2]) only fit models
        with those exact widths, so when the config doesn't pin a
        feature_fn/edge_attr_fn, replicate the defaults to match."""
        opts = dict(self._rollout_opts)
        nf = self._probe_feat_nf()
        if "feature_fn" not in opts and nf != 1:
            opts["feature_fn"] = lambda v: jnp.repeat(
                jnp.linalg.norm(v, axis=-1, keepdims=True), nf, axis=-1)
        ef = self._probe_edge_attr_nf()
        if "edge_attr_fn" not in opts and ef != 2:
            def edge_attr_fn(x, ei, em, _ef=max(ef, 1)):
                d = jnp.linalg.norm(x[ei[0]] - x[ei[1]], axis=-1,
                                    keepdims=True)
                return jnp.repeat(d, _ef, axis=-1) * em[:, None]

            opts["edge_attr_fn"] = edge_attr_fn
        return opts

    def rollout_rung(self, n: int) -> int:
        """Padded node count the rollout path compiles for a scene of ``n``
        nodes: the node-ladder rung rounded up to a multiple of the rollout
        edge_block. The batcher groups rollout requests on this value (plus
        steps) so same-rung scenes share one executable."""
        if not self._rollout_opts:
            raise ValueError("engine built without rollout_opts; pass "
                             "rollout_opts={'radius': ..., 'max_degree': ...}")
        edge_block = int(self._rollout_opts.get("edge_block", 256))
        rung = self.ladder._rung(n, self.ladder.node_floor,
                                 self.ladder.node_multiple,
                                 self.ladder.max_nodes, "nodes")
        return -(-max(rung, edge_block) // edge_block) * edge_block

    def rollout(self, loc0: np.ndarray, vel0: np.ndarray, steps: int,
                node_mask: Optional[np.ndarray] = None) -> np.ndarray:
        """K-step autoregressive rollout of one graph; returns the UNPADDED
        trajectory [steps, n, 3]. Raises RolloutOverflowError if any step
        overflowed the static neighbor-capacity bounds."""
        from distegnn_tpu.rollout import make_rollout_fn

        n = int(loc0.shape[0])
        n_pad = self.rollout_rung(n)
        opts = self._rollout_fn_opts()
        loc_p = np.zeros((n_pad, 3), np.float32)
        vel_p = np.zeros((n_pad, 3), np.float32)
        mask = np.zeros((n_pad,), np.float32)
        loc_p[:n], vel_p[:n] = loc0, vel0
        mask[:n] = (node_mask if node_mask is not None else np.ones(n)).astype(np.float32)

        def build():
            ro = make_rollout_fn(self.model, **opts)
            return jax.jit(functools.partial(ro, steps=int(steps)))

        fn = self._compiled(("rollout", n_pad, int(steps)) + self._stack_key,
                            build)
        traj, over = fn(self.params, jnp.asarray(loc_p), jnp.asarray(vel_p),
                        jnp.asarray(mask))
        if bool(np.asarray(over).any()):
            self.metrics.failed()
            raise RolloutOverflowError(
                f"rollout overflowed radius-graph capacity at steps "
                f"{np.nonzero(np.asarray(over))[0].tolist()}; raise "
                f"max_degree/max_per_cell in rollout_opts")
        return np.asarray(traj)[:, :n]

    def rollout_batch(self, scenes: Sequence[dict],
                      request_ids: Optional[Sequence[str]] = None,
                      ) -> List[np.ndarray]:
        """Batched K-step rollout over same-rung scenes.

        Each scene dict carries ``loc`` [n, 3], ``vel`` [n, 3], ``steps``
        (int), and optionally ``node_mask`` [n]. All scenes MUST share the
        same ``steps`` (the scan length is compiled in) — mixing raises
        :class:`MixedRolloutStepsError`. Scenes are padded to one common
        node rung and the scene axis to ``max_batch`` (replicating scene 0,
        copies discarded), so a (rung, steps) pair owns exactly one
        executable — the predict-path batching contract, applied to
        rollouts. Returns per-scene UNPADDED trajectories [steps, n_i, 3].
        """
        if not scenes:
            return []
        if len(scenes) > self.max_batch:
            raise ValueError(f"{len(scenes)} scenes > max_batch {self.max_batch}")
        from distegnn_tpu.rollout import make_batched_rollout_fn

        steps_set = {int(s["steps"]) for s in scenes}
        if len(steps_set) != 1:
            raise MixedRolloutStepsError(
                f"rollout batch mixes steps {sorted(steps_set)}; scenes with "
                f"different K cannot share a compiled scan")
        steps = steps_set.pop()
        ns = [int(s["loc"].shape[0]) for s in scenes]
        n_pad = max(self.rollout_rung(n) for n in ns)
        B = self.max_batch
        loc_p = np.zeros((B, n_pad, 3), np.float32)
        vel_p = np.zeros((B, n_pad, 3), np.float32)
        mask = np.zeros((B, n_pad), np.float32)
        for i, (s, n) in enumerate(zip(scenes, ns)):
            loc_p[i, :n], vel_p[i, :n] = s["loc"], s["vel"]
            nm = s.get("node_mask")
            mask[i, :n] = (nm if nm is not None else np.ones(n)).astype(np.float32)
        # fill pad slots with scene 0 so the replicated work is well-posed
        # (an all-zero scene would collapse every node into one radius cell)
        for i in range(len(scenes), B):
            loc_p[i], vel_p[i], mask[i] = loc_p[0], vel_p[0], mask[0]

        opts = self._rollout_fn_opts()

        def build():
            ro = make_batched_rollout_fn(self.model, **opts)
            return jax.jit(functools.partial(ro, steps=steps))

        fn = self._compiled(("rollout_batch", n_pad, steps, B)
                            + self._stack_key, build)
        with obs.span("serve/execute", n=n_pad, e=0, filled=len(scenes),
                      capacity=B, workload="rollout", steps=steps,
                      **_rid_attrs(request_ids)):
            traj, over = fn(self.params, jnp.asarray(loc_p),
                            jnp.asarray(vel_p), jnp.asarray(mask))
            traj = np.asarray(traj)                      # [B, steps, n_pad, 3]
        over = np.asarray(over)[: len(scenes)]           # replicas don't count
        if bool(over.any()):
            self.metrics.failed()
            bad = [(int(i), np.nonzero(over[i])[0].tolist())
                   for i in np.nonzero(over.any(axis=1))[0]]
            raise RolloutOverflowError(
                f"batched rollout overflowed radius-graph capacity "
                f"(scene, steps): {bad}; raise max_degree/max_per_cell in "
                f"rollout_opts")
        return [traj[i, :, :n].copy() for i, n in enumerate(ns)]

    def rollout_stream(self, scene: dict, emit,
                       request_id: Optional[str] = None) -> dict:
        """Chunked K-step rollout of ONE scene, delivering the trajectory
        incrementally through ``emit`` (a :class:`~distegnn_tpu.serve.queue.
        StreamSink`-shaped object: ``put_chunk(start_step, traj)`` plus a
        ``cancelled`` flag polled between chunks).

        The steps axis is executed as successive ``chunk_steps``-length
        compiled scans with the (loc, vel) carry threaded between them
        host-side — the same per-step update as one long scan (the carry
        rule mirrors rollout.py: ``v_next = (x_next - x) * velocity_scale``
        when ``velocity_from_delta``), so the first chunk arrives after
        ~chunk/K of the work and a client disconnect stops the remaining
        compute at the next chunk boundary. The compile-cache key is the
        single-scene ``("rollout", n_pad, chunk)`` rung, shared with the
        unbatched path. Returns a summary dict (steps_total / steps_done /
        cancelled / chunk_steps)."""
        from distegnn_tpu.rollout import make_rollout_fn

        steps = int(scene["steps"])
        chunk = max(1, int(scene.get("chunk_steps", 8) or 8))
        n = int(scene["loc"].shape[0])
        n_pad = self.rollout_rung(n)
        opts = self._rollout_fn_opts()
        vel_from_delta = bool(opts.get("velocity_from_delta", True))
        vscale = float(opts.get("velocity_scale", 1.0))
        loc_p = np.zeros((n_pad, 3), np.float32)
        vel_p = np.zeros((n_pad, 3), np.float32)
        mask = np.zeros((n_pad,), np.float32)
        loc_p[:n], vel_p[:n] = scene["loc"], scene["vel"]
        nm = scene.get("node_mask")
        mask[:n] = (nm if nm is not None else np.ones(n)).astype(np.float32)

        done = 0
        while done < steps:
            if getattr(emit, "cancelled", False):
                break
            c = min(chunk, steps - done)

            def build(_c=c):
                ro = make_rollout_fn(self.model, **opts)
                return jax.jit(functools.partial(ro, steps=_c))

            fn = self._compiled(("rollout", n_pad, c) + self._stack_key,
                                build)
            with obs.span("serve/execute", n=n_pad, e=0, filled=1,
                          capacity=1, workload="rollout_stream", steps=c,
                          **_rid_attrs([request_id])):
                traj, over = fn(self.params, jnp.asarray(loc_p),
                                jnp.asarray(vel_p), jnp.asarray(mask))
                traj = np.asarray(traj)                  # [c, n_pad, 3]
            if bool(np.asarray(over).any()):
                self.metrics.failed()
                raise RolloutOverflowError(
                    f"streamed rollout overflowed radius-graph capacity at "
                    f"steps {(done + np.nonzero(np.asarray(over))[0]).tolist()}"
                    f"; raise max_degree/max_per_cell in rollout_opts")
            # thread the carry exactly as the scan body would have
            prev = loc_p if c == 1 else traj[c - 2]
            new_loc = traj[c - 1].copy()
            if vel_from_delta:
                vel_p = ((new_loc - prev) * vscale).astype(np.float32)
            loc_p = new_loc
            emit.put_chunk(done, traj[:, :n].copy())
            done += c
        return {"steps_total": steps, "steps_done": done,
                "cancelled": done < steps, "chunk_steps": chunk}
