"""Request queue + micro-batcher — coalesce same-bucket requests.

One dispatcher thread owns the serving loop: it drains a BOUNDED ingress
queue into per-bucket pending lists and flushes a bucket as a micro-batch
when it reaches the batch cap OR its oldest request has waited the batching
deadline — the classic latency/throughput knob (deadline 0 = no batching,
larger = fuller batches, +deadline worst-case added latency).

Failure surfaces (never silent, matching the overflow-flag contract in
rollout.py):
  - ingress full            -> QueueFullError raised AT SUBMIT (backpressure)
  - graph exceeds ladder    -> BucketOverflowError raised at submit
  - deadline passed queued  -> RequestTimeoutError set on the future
  - engine/model exception  -> each request of the batch is RETRIED ALONE
    once (one poison graph must not take down co-batched neighbors); only
    requests that fail solo get the exception (counted as ``poison``)
  - dispatcher thread crash -> restarted up to ``_MAX_WORKER_RESTARTS``
    times (pending requests survive), then every outstanding future fails
    with the crash error and submit() raises — never a silent hang

Device execution runs inline in the dispatcher thread: the accelerator is a
serial resource, so a thread pool would only add queueing ambiguity. The
GIL is released inside XLA execution, so submitters keep running.
"""

from __future__ import annotations

import queue as _pyqueue
import threading
import time
from typing import Dict, List, Optional

from distegnn_tpu import obs
from distegnn_tpu.serve.buckets import Bucket, BucketLadder, BucketOverflowError
from distegnn_tpu.serve.engine import InferenceEngine
from distegnn_tpu.serve.metrics import ServeMetrics


class QueueFullError(RuntimeError):
    """Bounded ingress queue is full — shed load at the edge."""


class RequestTimeoutError(RuntimeError):
    """The request's deadline passed before a batch picked it up."""


class ServeFuture:
    """Minimal one-shot future (no asyncio dependency in the serving core)."""

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._exc: Optional[BaseException] = None

    def set_result(self, value) -> None:
        self._result = value
        self._event.set()

    def set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("serve future not ready")
        if self._exc is not None:
            raise self._exc
        return self._result


class _Request:
    __slots__ = ("graph", "bucket", "future", "t_submit", "deadline")

    def __init__(self, graph: dict, bucket: Bucket, deadline: float):
        self.graph = graph
        self.bucket = bucket
        self.future = ServeFuture()
        self.t_submit = time.perf_counter()
        self.deadline = deadline


_STOP = object()

# dispatcher crash tolerance: a crashing _loop (a BUG, not an engine error —
# those are caught per-batch) restarts this many times before the queue
# declares itself dead and fails everything outstanding
_MAX_WORKER_RESTARTS = 3


class RequestQueue:
    """Bounded ingress + per-bucket micro-batcher over an InferenceEngine.

    Args:
      engine: the compiled-shape executor (its ladder buckets requests).
      batch_deadline_ms: max time the OLDEST pending request of a bucket
        waits for co-batchable traffic before the bucket flushes.
      queue_capacity: ingress bound; submits beyond it raise QueueFullError.
      request_timeout_ms: per-request deadline (queued time only — an
        admitted request that starts executing always completes).
    """

    def __init__(self, engine: InferenceEngine, *,
                 batch_deadline_ms: float = 5.0, queue_capacity: int = 256,
                 request_timeout_ms: float = 1000.0,
                 metrics: Optional[ServeMetrics] = None):
        self.engine = engine
        self.metrics = metrics or engine.metrics
        self.batch_deadline = batch_deadline_ms / 1e3
        self.request_timeout = request_timeout_ms / 1e3
        self._ingress: "_pyqueue.Queue" = _pyqueue.Queue(maxsize=queue_capacity)
        self._pending: Dict[Bucket, List[_Request]] = {}
        self._thread: Optional[threading.Thread] = None
        self._started = False
        self._restarts = 0

    @property
    def ladder(self) -> BucketLadder:
        return self.engine.ladder

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> "RequestQueue":
        if self._started:
            return self
        self._started = True
        self._thread = threading.Thread(target=self._run,
                                        name="serve-dispatch", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop the dispatcher. ``drain=True`` flushes everything already
        admitted; False fails pending futures with RequestTimeoutError."""
        if not self._started:
            return
        self._ingress.put((_STOP, drain))
        self._thread.join(timeout=30.0)
        self._started = False
        # a submit racing the final drain check could leave a request in the
        # ingress after the dispatcher exited — fail it, never strand it
        self._fail_all(RequestTimeoutError("server stopped"))

    def __enter__(self) -> "RequestQueue":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- submission ------------------------------------------------------
    def submit(self, graph: dict) -> ServeFuture:
        """Admit one pad_graphs-style graph dict; returns a ServeFuture
        resolving to the predicted positions [n, 3] (numpy)."""
        if not self._started:
            raise RuntimeError("RequestQueue not started (use start() or a "
                               "with-block)")
        bucket = self.ladder.bucket_of_graph(graph)  # BucketOverflowError here
        req = _Request(graph, bucket,
                       deadline=time.perf_counter() + self.request_timeout)
        try:
            self._ingress.put_nowait(req)
        except _pyqueue.Full:
            self.metrics.rejected()
            raise QueueFullError(
                f"ingress queue full ({self._ingress.maxsize}); retry with "
                f"backoff or raise serve.queue_capacity") from None
        self.metrics.submitted()
        return req.future

    def depth(self) -> int:
        return self._ingress.qsize() + sum(len(v) for v in self._pending.values())

    # ---- dispatcher ------------------------------------------------------
    def _run(self) -> None:
        """Thread target: _loop with crash containment. Engine errors are
        handled per-batch inside _execute; anything escaping _loop is a bug —
        restart the loop (pending state survives on the instance) a bounded
        number of times, then fail everything outstanding and mark the queue
        dead so submit() raises instead of hanging until timeout."""
        while True:
            try:
                self._loop()
                return  # clean exit (stop/drain)
            except Exception as exc:
                self._restarts += 1
                self.metrics.worker_restarted()
                if self._restarts > _MAX_WORKER_RESTARTS:
                    obs.log(f"serve: dispatcher died permanently after "
                            f"{_MAX_WORKER_RESTARTS} restarts: {exc!r}")
                    self._fail_all(RuntimeError(
                        f"serve dispatcher crashed: {exc!r}"))
                    self._started = False
                    return
                obs.log(f"serve: dispatcher crashed ({exc!r}); restarting "
                        f"({self._restarts}/{_MAX_WORKER_RESTARTS})")

    def _next_flush_deadline(self) -> Optional[float]:
        ts = [rs[0].t_submit + self.batch_deadline
              for rs in self._pending.values() if rs]
        return min(ts) if ts else None

    def _absorb(self, item) -> bool:
        """Move one ingress item into pending; returns True on _STOP."""
        if isinstance(item, tuple) and item[0] is _STOP:
            if not item[1]:  # drain=False: fail everything outstanding
                self._fail_all(RequestTimeoutError("server stopped"))
            return True
        self._pending.setdefault(item.bucket, []).append(item)
        return False

    def _loop(self) -> None:
        draining = False
        while True:
            now = time.perf_counter()
            flush_at = self._next_flush_deadline()
            timeout = None if flush_at is None else max(flush_at - now, 0.0)
            if not draining:
                try:
                    item = self._ingress.get(timeout=timeout)
                except _pyqueue.Empty:
                    item = None
                # absorb everything already arrived in one pass (no sleep);
                # a _STOP flips to draining but this round still flushes
                while item is not None:
                    draining = self._absorb(item) or draining
                    try:
                        item = self._ingress.get_nowait()
                    except _pyqueue.Empty:
                        item = None
            else:
                while True:  # drain mode: empty the ingress, then flush all
                    try:
                        self._absorb(self._ingress.get_nowait())
                    except _pyqueue.Empty:
                        break
            self.metrics.set_queue_depth(self.depth())

            now = time.perf_counter()
            for bucket in list(self._pending):
                reqs = self._pending[bucket]
                self._expire(bucket, reqs, now)
                while len(reqs) >= self.engine.max_batch:
                    self._execute(bucket, reqs[: self.engine.max_batch])
                    del reqs[: self.engine.max_batch]
                if reqs and (draining or
                             now - reqs[0].t_submit >= self.batch_deadline):
                    self._execute(bucket, reqs)
                    reqs.clear()
                if not reqs:
                    del self._pending[bucket]
            self.metrics.set_queue_depth(self.depth())
            if draining and not self._pending and self._ingress.empty():
                return

    def _expire(self, bucket: Bucket, reqs: List[_Request], now: float) -> None:
        alive = [r for r in reqs if r.deadline > now]
        for r in reqs:
            if r.deadline <= now:
                self.metrics.timed_out()
                r.future.set_exception(RequestTimeoutError(
                    f"request waited > {self.request_timeout * 1e3:.0f} ms "
                    f"in bucket {bucket}"))
        reqs[:] = alive

    def _execute(self, bucket: Bucket, reqs: List[_Request]) -> None:
        t_start = time.perf_counter()
        try:
            outs = self.engine.predict_batch([r.graph for r in reqs],
                                             bucket=bucket)
        except Exception:
            # one bad graph fails the whole padded batch — retry each request
            # ALONE once, so a poison graph only takes down itself
            self._retry_individually(bucket, reqs)
            return
        now = time.perf_counter()
        lats = [(now - r.t_submit) * 1e3 for r in reqs]
        qms = [(t_start - r.t_submit) * 1e3 for r in reqs]
        self.metrics.batch_done(len(reqs), self.engine.max_batch, lats, qms)
        obs.event("serve/batch", n=bucket.n, e=bucket.e, filled=len(reqs),
                  capacity=self.engine.max_batch,
                  dur_s=round(now - t_start, 6))
        for r, out in zip(reqs, outs):
            r.future.set_result(out)

    def _retry_individually(self, bucket: Bucket, reqs: List[_Request]) -> None:
        self.metrics.retried(len(reqs))
        for r in reqs:
            t_start = time.perf_counter()
            try:
                out = self.engine.predict_batch([r.graph], bucket=bucket)[0]
            except Exception as solo_exc:  # fails even alone: the poison graph
                self.metrics.poison()
                self.metrics.failed()
                r.future.set_exception(solo_exc)
                continue
            now = time.perf_counter()
            self.metrics.batch_done(1, self.engine.max_batch,
                                    [(now - r.t_submit) * 1e3],
                                    [(t_start - r.t_submit) * 1e3])
            r.future.set_result(out)

    def _fail_all(self, exc: BaseException) -> None:
        for reqs in self._pending.values():
            for r in reqs:
                r.future.set_exception(exc)
        self._pending.clear()
        while True:
            try:
                item = self._ingress.get_nowait()
            except _pyqueue.Empty:
                return
            if not (isinstance(item, tuple) and item[0] is _STOP):
                item.future.set_exception(exc)
