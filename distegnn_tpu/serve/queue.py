"""Request queue + micro-batcher — coalesce same-bucket requests.

One dispatcher thread owns the serving loop: it drains a BOUNDED ingress
queue into per-bucket pending lists and flushes a bucket as a micro-batch
when it reaches the batch cap OR its oldest request has waited the batching
deadline — the classic latency/throughput knob (deadline 0 = no batching,
larger = fuller batches, +deadline worst-case added latency).

Rollout requests (``submit_rollout``) ride the SAME machinery: they share
the ingress, deadlines, poison isolation, and restart containment, but
coalesce per (node rung, steps) — the compiled scan length is static, so
scenes with a different K land in a different pending list and can never
co-batch (engine.rollout_batch additionally raises MixedRolloutStepsError
as the typed backstop).

Failure surfaces (never silent, matching the overflow-flag contract in
rollout.py):
  - ingress full            -> QueueFullError raised AT SUBMIT (backpressure)
  - graph exceeds ladder    -> BucketOverflowError raised at submit
  - deadline passed queued  -> RequestTimeoutError set on the future
  - engine/model exception  -> each request of the batch is RETRIED ALONE
    once (one poison graph must not take down co-batched neighbors); only
    requests that fail solo get the exception (counted as ``poison``)
  - dispatcher thread crash -> restarted with exponential backoff, up to
    ``_MAX_WORKER_RESTARTS`` times within ``_RESTART_WINDOW_S`` (the budget
    replenishes after a healthy interval, so transient crashes spread over
    hours never exhaust it); past the budget every outstanding future fails
    with :class:`DispatcherCrashError` and submit() raises — never a silent
    hang

The queue also carries the serving chaos surface (``kill`` / ``wedge`` /
``inject_latency``) used by the replica supervisor and the fault-injection
harness, plus a ``last_progress`` heartbeat timestamp the supervisor reads
to detect wedged dispatchers (depth > 0 with no batch progress).

Device execution runs inline in the dispatcher thread: the accelerator is a
serial resource, so a thread pool would only add queueing ambiguity. The
GIL is released inside XLA execution, so submitters keep running.
"""

from __future__ import annotations

import queue as _pyqueue
import threading
import time
from typing import Dict, List, Optional

from distegnn_tpu import obs
from distegnn_tpu.serve.buckets import Bucket, BucketLadder, BucketOverflowError
from distegnn_tpu.serve.engine import InferenceEngine
from distegnn_tpu.serve.metrics import ServeMetrics


class QueueFullError(RuntimeError):
    """Bounded ingress queue is full — shed load at the edge."""


class RequestTimeoutError(RuntimeError):
    """The request's deadline passed before a batch picked it up."""


class DispatcherCrashError(RuntimeError):
    """The dispatcher died permanently (crash budget exhausted, or killed by
    chaos / the replica supervisor). Outstanding futures carry this error so
    the replica layer can tell a dead dispatcher (fail over the request)
    from a per-request failure (propagate to the caller)."""


class WorkerLostError(RuntimeError):
    """The out-of-process worker executing this queue's batches is gone
    (dead pipe, SIGKILL'd child, missed deadline). Unlike a per-request
    failure this poisons the WHOLE queue: the dispatcher kills itself so
    every pending future carries :class:`DispatcherCrashError` and the
    replica layer fails the work over — a lost child must never be
    retried request-by-request against the same dead channel."""


class _KilledError(Exception):
    """Internal control flow: the dispatcher observed its kill flag."""


class ServeFuture:
    """Minimal one-shot future (no asyncio dependency in the serving core).

    ``hard_deadline`` (absolute ``time.perf_counter()`` seconds) is the
    belt-and-suspenders bound the queue stamps on every request: a
    ``result()`` call with no explicit timeout waits at most until then, so
    a wedged dispatcher surfaces as :class:`RequestTimeoutError` (the
    gateway's 504) instead of a hung caller. ``meta`` is filled by the
    dispatcher before resolution (queue_ms / compute_ms / batch_filled /
    bucket) for transports that report per-request timing.

    Resolution is FIRST-WINS: once resolved, later ``set_result`` /
    ``set_exception`` calls are ignored (and return False). The replica
    layer relies on this for at-most-once failover — a late result from an
    abandoned wedged replica can't clobber the failover's answer.
    """

    def __init__(self, hard_deadline: Optional[float] = None):
        self._event = threading.Event()
        self._result = None
        self._exc: Optional[BaseException] = None
        self._hard_deadline = hard_deadline
        self._lock = threading.Lock()
        self._callbacks: List = []
        self.meta: dict = {}

    def _resolve(self, value, exc: Optional[BaseException]) -> bool:
        with self._lock:
            if self._event.is_set():
                return False  # first resolution wins
            self._result = value
            self._exc = exc
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            try:
                fn(self)
            except Exception as cb_exc:
                obs.log(f"serve: future callback raised: {cb_exc!r}")
        return True

    def set_result(self, value) -> bool:
        return self._resolve(value, None)

    def set_exception(self, exc: BaseException) -> bool:
        return self._resolve(None, exc)

    def add_done_callback(self, fn) -> None:
        """Run ``fn(self)`` when the future resolves (immediately if it
        already has). Callbacks fire in the resolving thread; exceptions are
        logged, never propagated into the dispatcher."""
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        try:
            fn(self)
        except Exception as cb_exc:
            obs.log(f"serve: future callback raised: {cb_exc!r}")

    def done(self) -> bool:
        return self._event.is_set()

    def exception(self) -> Optional[BaseException]:
        """Non-blocking peek at the resolved exception (None if pending or
        resolved with a result)."""
        return self._exc if self._event.is_set() else None

    def result(self, timeout: Optional[float] = None):
        if timeout is None and self._hard_deadline is not None:
            remaining = max(self._hard_deadline - time.perf_counter(), 0.0)
            if not self._event.wait(remaining):
                raise RequestTimeoutError(
                    "request passed its hard deadline with no dispatcher "
                    "progress (dispatcher wedged or overloaded past the "
                    "result margin)")
        elif not self._event.wait(timeout):
            raise TimeoutError("serve future not ready")
        if self._exc is not None:
            raise self._exc
        return self._result


class StreamSink:
    """Chunk conduit between the dispatcher (producer) and a streaming
    consumer (the gateway's chunked-transfer writer) for ONE rollout.

    The producer calls ``put_chunk`` per chunk, then exactly one of
    ``finish`` / ``fail``; the consumer iterates ``next`` and calls
    ``cancel()`` when its client disconnects — the producer polls
    ``cancelled`` between chunk computations and stops, so remaining
    compute is skipped at the next chunk boundary. Thread-safe; items are
    ``("chunk", start_step, traj)``, ``("done", summary, None)``, or
    ``("error", exc, None)``."""

    def __init__(self):
        self._q: "_pyqueue.Queue" = _pyqueue.Queue()
        self._cancelled = threading.Event()

    def put_chunk(self, start_step: int, traj) -> None:
        self._q.put(("chunk", int(start_step), traj))

    def finish(self, summary: dict) -> None:
        self._q.put(("done", summary, None))

    def fail(self, exc: BaseException) -> None:
        self._q.put(("error", exc, None))

    def cancel(self) -> None:
        self._cancelled.set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def next(self, timeout: Optional[float] = None):
        """Blocking pop of the next item; raises ``queue.Empty`` on
        timeout (the consumer's poll loop re-checks the future then)."""
        return self._q.get(timeout=timeout)


class _Request:
    __slots__ = ("graph", "bucket", "kind", "steps", "future", "t_submit",
                 "deadline", "request_id", "stream")

    def __init__(self, graph: dict, bucket: Bucket, deadline: float,
                 hard_deadline: Optional[float] = None,
                 kind: str = "predict", steps: Optional[int] = None,
                 request_id: Optional[str] = None,
                 stream: Optional[StreamSink] = None):
        self.graph = graph
        self.bucket = bucket
        self.kind = kind        # "predict" | "rollout" | "rollout_stream" | "tiled"
        self.steps = steps      # rollout scan length (None for predicts)
        self.future = ServeFuture(hard_deadline=hard_deadline)
        self.t_submit = time.perf_counter()
        self.deadline = deadline
        self.request_id = request_id  # gateway trace id (None off-gateway)
        self.stream = stream    # StreamSink for kind "rollout_stream"

    @property
    def key(self):
        """Micro-batch coalescing key: same-rung predicts batch together as
        before; rollouts additionally key on steps (the compiled scan length)
        so mixed-K scenes never co-batch."""
        return (self.kind, self.bucket, self.steps)


def _request_ids(reqs: List["_Request"]) -> List[Optional[str]]:
    """Trace ids for a micro-batch, POSITION-ALIGNED with the batch members
    (so the i-th queue_ms in the batch event belongs to the i-th id). All
    non-gateway traffic (in-proc bench submits) has no ids: return [] so
    those events stay compact."""
    ids = [r.request_id for r in reqs]
    return ids if any(i is not None for i in ids) else []


_STOP = object()
_KILL = object()  # chaos/supervisor kill marker — wakes a blocked ingress.get

# dispatcher crash tolerance: a crashing _loop (a BUG, not an engine error —
# those are caught per-batch) restarts with exponential backoff; only crashes
# within _RESTART_WINDOW_S count against the budget, so the budget replenishes
# after a healthy interval and 3 transient crashes spread over hours never
# kill the queue — but a tight crash loop still dies after
# _MAX_WORKER_RESTARTS + 1 total crashes instead of spinning forever
_MAX_WORKER_RESTARTS = 3
_RESTART_WINDOW_S = 60.0
_RESTART_BACKOFF_BASE_S = 0.05
_RESTART_BACKOFF_MAX_S = 2.0


class RequestQueue:
    """Bounded ingress + per-bucket micro-batcher over an InferenceEngine.

    Args:
      engine: the compiled-shape executor (its ladder buckets requests).
      batch_deadline_ms: max time the OLDEST pending request of a bucket
        waits for co-batchable traffic before the bucket flushes.
      queue_capacity: ingress bound; submits beyond it raise QueueFullError.
      request_timeout_ms: per-request deadline (queued time only — an
        admitted request that starts executing always completes).
      result_margin_s: execute-time headroom added on top of the queued
        deadline to form each future's HARD deadline — a no-timeout
        ``ServeFuture.result()`` never waits longer than
        ``request_timeout + result_margin``, so a wedged dispatcher is a
        typed RequestTimeoutError, not a hang.
    """

    backend = "thread"  # WorkerQueue overrides: the supervisor branches on it

    def __init__(self, engine: InferenceEngine, *,
                 batch_deadline_ms: float = 5.0, queue_capacity: int = 256,
                 request_timeout_ms: float = 1000.0,
                 result_margin_s: float = 30.0,
                 metrics: Optional[ServeMetrics] = None):
        self.engine = engine
        self.metrics = metrics or engine.metrics
        self.batch_deadline = batch_deadline_ms / 1e3
        self.request_timeout = request_timeout_ms / 1e3
        self.result_margin = float(result_margin_s)
        self._ingress: "_pyqueue.Queue" = _pyqueue.Queue(maxsize=queue_capacity)
        # keyed on _Request.key = (kind, bucket, steps): predicts coalesce
        # per rung exactly as before; rollouts per (rung, steps)
        self._pending: Dict[tuple, List[_Request]] = {}
        self._thread: Optional[threading.Thread] = None
        self._started = False
        self._restarts = 0             # lifetime crash count (informational)
        self._crash_times: List[float] = []  # windowed restart budget
        # chaos / supervision surface
        self._kill_reason: Optional[str] = None
        self._wedge_until = 0.0
        self._inject_latency_s = 0.0
        self.last_progress = time.perf_counter()
        # stop() coordination: idempotent and signal-safe — any number of
        # threads (SIGTERM handler, bench atexit, with-block) may race it
        self._stop_lock = threading.Lock()
        self._stop_begun = False

    @property
    def ladder(self) -> BucketLadder:
        return self.engine.ladder

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> "RequestQueue":
        if self._started:
            return self
        with self._stop_lock:
            self._stop_begun = False
        self._started = True
        self._thread = threading.Thread(target=self._run,
                                        name="serve-dispatch", daemon=True)
        self._thread.start()
        return self

    def alive(self) -> bool:
        """True while the dispatcher thread is accepting and running."""
        t = self._thread
        return bool(self._started and t is not None and t.is_alive())

    def stop(self, drain: bool = True, join_timeout_s: float = 30.0) -> None:
        """Stop the dispatcher. ``drain=True`` flushes everything already
        admitted; False fails pending futures with RequestTimeoutError.
        ``join_timeout_s`` bounds the wait for the dispatcher thread — the
        registry's concurrent drain passes its per-model grace slice so one
        wedged queue can't eat every model's budget.

        Idempotent and signal-safe: double-stop, stop-before-start, and
        concurrent stops (the gateway's SIGTERM drain racing a bench's
        with-block exit) never raise, block indefinitely, or strand a
        future. Only the first caller delivers the STOP; later callers just
        wait for the dispatcher to finish.
        """
        with self._stop_lock:
            first = not self._stop_begun
            self._stop_begun = True
            thread = self._thread
        if thread is None:
            # stop before start: nothing is running and nothing was admitted
            self._started = False
            return
        if first:
            self._started = False   # reject new submits while stopping
            # never block forever handing over the STOP: a full ingress with
            # a live dispatcher drains; a dead dispatcher can't take it
            while thread.is_alive():
                try:
                    self._ingress.put((_STOP, drain), timeout=0.05)
                    break
                except _pyqueue.Full:
                    continue
        thread.join(timeout=join_timeout_s)
        if first:
            # a submit racing the final drain check could leave a request in
            # the ingress after the dispatcher exited — fail it, never
            # strand it
            self._fail_all(RequestTimeoutError("server stopped"))

    def __enter__(self) -> "RequestQueue":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- submission ------------------------------------------------------
    def submit(self, graph: dict, bucket: Optional[Bucket] = None,
               request_id: Optional[str] = None) -> ServeFuture:
        """Admit one pad_graphs-style graph dict; returns a ServeFuture
        resolving to the predicted positions [n, 3] (numpy). ``bucket``
        overrides the ladder assignment — the session prep cache passes the
        rung it computed from the RAW topology, since a prepared (blocked)
        dict's inflated edge count would otherwise re-bucket it.
        ``request_id`` tags the request's batch/execute spans in the event
        stream (the gateway passes its X-Request-Id)."""
        if not self._started:
            raise RuntimeError("RequestQueue not started (use start() or a "
                               "with-block)")
        if bucket is None:
            bucket = self.ladder.bucket_of_graph(graph)  # BucketOverflowError
        now = time.perf_counter()
        req = _Request(graph, bucket, deadline=now + self.request_timeout,
                       hard_deadline=(now + self.request_timeout
                                      + self.result_margin),
                       request_id=request_id)
        return self._enqueue(req)

    def submit_rollout(self, scene: dict,
                       request_id: Optional[str] = None,
                       stream: Optional[StreamSink] = None) -> ServeFuture:
        """Admit one rollout scene dict (``loc`` [n,3], ``vel`` [n,3],
        ``steps`` int, optional ``node_mask``); resolves to the trajectory
        [steps, n, 3]. Same deadline/backpressure semantics as ``submit`` —
        rollouts share the ingress, deadlines, and restart containment; they
        coalesce per (node rung, steps), so same-shape same-K scenes fill one
        compiled scan exactly like predicts fill a padded batch.

        With ``stream`` (a :class:`StreamSink`), the scene runs as a CHUNKED
        stream instead: the trajectory arrives on the sink chunk by chunk
        (``engine.rollout_stream``), the future resolves to the run summary,
        and a ``stream.cancel()`` stops the remaining chunks. Streams never
        co-batch with buffered rollouts and never enter the solo-retry path
        after partial emission — a failed chunk fails the sink, once."""
        if not self._started:
            raise RuntimeError("RequestQueue not started (use start() or a "
                               "with-block)")
        steps = int(scene.get("steps", 0))
        if steps < 1:
            raise ValueError(f"rollout steps must be >= 1, got {steps}")
        n_pad = self.engine.rollout_rung(int(scene["loc"].shape[0]))
        now = time.perf_counter()
        req = _Request(scene, Bucket(n_pad, 0),
                       deadline=now + self.request_timeout,
                       hard_deadline=(now + self.request_timeout
                                      + self.result_margin),
                       kind="rollout" if stream is None else "rollout_stream",
                       steps=steps, request_id=request_id, stream=stream)
        return self._enqueue(req)

    def submit_tiled(self, graph: dict,
                     request_id: Optional[str] = None,
                     stream: Optional[StreamSink] = None) -> ServeFuture:
        """Admit one GIANT scene for the tiled executor (serve/tiled.py) —
        the path for ``n_nodes`` above the ladder cap, so it bypasses the
        rung assignment entirely (``Bucket(0, 0)`` keys these requests into
        their own dispatch group). Resolves to the executor's result dict
        (prediction + tiling stats). Deadlines scale by
        ``serve.tiled.timeout_factor``: a tiled scene is tens of tile
        invocations, not one padded batch, and the queued-time deadline
        must admit sitting behind another giant scene.

        With ``stream`` (a :class:`StreamSink`), per-tile progress arrives
        on the sink as ``(layer, tile)`` chunks — or per-ROUND
        ``(layer, round)`` chunks when the executor runs device-parallel
        rounds (``serve.tiled.devices`` > 1, serve/mesh_tiled.py) — and a
        ``stream.cancel()`` stops the remaining compute at the next
        tile/round boundary (the streamed-rollout disconnect contract)."""
        if not self._started:
            raise RuntimeError("RequestQueue not started (use start() or a "
                               "with-block)")
        tiled = getattr(self.engine, "tiled", None)
        if tiled is None:
            raise RuntimeError("engine built without serve.tiled config; "
                               "giant scenes cannot be served")
        tiled.check_admit(int(graph["loc"].shape[0]))  # TiledOverflowError
        factor = max(float(tiled.timeout_factor), 1.0)
        now = time.perf_counter()
        req = _Request(graph, Bucket(0, 0),
                       deadline=now + self.request_timeout * factor,
                       hard_deadline=(now + self.request_timeout * factor
                                      + self.result_margin * factor),
                       kind="tiled", request_id=request_id, stream=stream)
        return self._enqueue(req)

    def _enqueue(self, req: _Request) -> ServeFuture:
        try:
            self._ingress.put_nowait(req)
        except _pyqueue.Full:
            self.metrics.rejected()
            raise QueueFullError(
                f"ingress queue full ({self._ingress.maxsize}); retry with "
                f"backoff or raise serve.queue_capacity") from None
        self.metrics.submitted()
        return req.future

    def depth(self) -> int:
        return self._ingress.qsize() + sum(len(v) for v in self._pending.values())

    # ---- chaos / supervision hooks ---------------------------------------
    def kill(self, reason: str = "killed") -> None:
        """Abruptly and permanently kill the dispatcher (chaos harness; also
        how the supervisor abandons a wedged replica). Every outstanding
        future fails with :class:`DispatcherCrashError` immediately — even if
        the dispatcher thread is stuck inside a device call — and the queue
        rejects further submits. No restart budget applies: a killed queue
        stays dead; the replica supervisor builds a fresh one."""
        self._kill_reason = str(reason)
        self._started = False
        self._fail_all(DispatcherCrashError(
            f"dispatcher killed: {self._kill_reason}"))
        # after the drain so _fail_all can't consume the wake-up marker
        try:
            self._ingress.put_nowait(_KILL)  # wake a blocked ingress.get
        except _pyqueue.Full:
            pass

    def wedge(self, duration_s: float) -> None:
        """Chaos: make the dispatcher sit without batch progress for
        ``duration_s`` — admitted requests pile up and ``last_progress``
        goes stale, exactly what a stuck device call looks like to the
        supervisor."""
        self._wedge_until = time.perf_counter() + float(duration_s)

    def inject_latency(self, seconds: float) -> None:
        """Chaos: add a fixed sleep before every batch execute (0 clears)."""
        self._inject_latency_s = max(float(seconds), 0.0)

    # ---- dispatcher ------------------------------------------------------
    def _run(self) -> None:
        """Thread target: _loop with crash containment. Engine errors are
        handled per-batch inside _execute; anything escaping _loop is a bug —
        restart the loop (pending state survives on the instance) with
        exponential backoff, budgeted over a sliding window (the budget
        replenishes after a healthy interval), then fail everything
        outstanding and mark the queue dead so submit() raises instead of
        hanging until timeout."""
        while True:
            try:
                self._loop()
                return  # clean exit (stop/drain)
            except _KilledError:
                self._die(DispatcherCrashError(
                    f"dispatcher killed: {self._kill_reason}"))
                return
            except Exception as exc:
                now = time.perf_counter()
                self._crash_times = [t for t in self._crash_times
                                     if now - t < _RESTART_WINDOW_S]
                self._crash_times.append(now)
                self._restarts += 1
                self.metrics.worker_restarted()
                burst = len(self._crash_times)
                if burst > _MAX_WORKER_RESTARTS:
                    obs.log(f"serve: dispatcher died permanently after "
                            f"{_MAX_WORKER_RESTARTS} restarts in "
                            f"{_RESTART_WINDOW_S:.0f} s: {exc!r}")
                    self._die(DispatcherCrashError(
                        f"serve dispatcher crashed: {exc!r}"))
                    return
                backoff = min(_RESTART_BACKOFF_BASE_S * (2 ** (burst - 1)),
                              _RESTART_BACKOFF_MAX_S)
                obs.log(f"serve: dispatcher crashed ({exc!r}); restart "
                        f"{burst}/{_MAX_WORKER_RESTARTS} in "
                        f"{backoff * 1e3:.0f} ms")
                time.sleep(backoff)

    def _die(self, exc: BaseException) -> None:
        self._fail_all(exc)
        self._started = False

    def _next_flush_deadline(self) -> Optional[float]:
        ts = [rs[0].t_submit + self.batch_deadline
              for rs in self._pending.values() if rs]
        return min(ts) if ts else None

    def _absorb(self, item) -> bool:
        """Move one ingress item into pending; returns True on _STOP."""
        if item is _KILL:
            raise _KilledError()
        if isinstance(item, tuple) and item[0] is _STOP:
            if not item[1]:  # drain=False: fail everything outstanding
                self._fail_all(RequestTimeoutError("server stopped"))
            return True
        self._pending.setdefault(item.key, []).append(item)
        return False

    def _loop(self) -> None:
        draining = False
        while True:
            if self._kill_reason is not None:
                raise _KilledError()
            now = time.perf_counter()
            if now < self._wedge_until:
                # chaos wedge: no absorption, no flush, no progress stamp —
                # depth grows while last_progress goes stale
                time.sleep(min(0.05, self._wedge_until - now))
                continue
            self.last_progress = now
            flush_at = self._next_flush_deadline()
            timeout = None if flush_at is None else max(flush_at - now, 0.0)
            if not draining:
                try:
                    item = self._ingress.get(timeout=timeout)
                except _pyqueue.Empty:
                    item = None
                # absorb everything already arrived in one pass (no sleep);
                # a _STOP flips to draining but this round still flushes
                while item is not None:
                    draining = self._absorb(item) or draining
                    try:
                        item = self._ingress.get_nowait()
                    except _pyqueue.Empty:
                        item = None
            else:
                while True:  # drain mode: empty the ingress, then flush all
                    try:
                        self._absorb(self._ingress.get_nowait())
                    except _pyqueue.Empty:
                        break
            self.metrics.set_queue_depth(self.depth())

            now = time.perf_counter()
            for key in list(self._pending):
                # a concurrent kill()'s _fail_all may clear pending under us:
                # tolerate vanished keys instead of crashing the loop (the
                # kill flag ends it at the next iteration)
                reqs = self._pending.get(key)
                if not reqs:
                    self._pending.pop(key, None)
                    continue
                self._expire(key, reqs, now)
                while len(reqs) >= self.engine.max_batch:
                    self._execute(key, reqs[: self.engine.max_batch])
                    del reqs[: self.engine.max_batch]
                if reqs and (draining or
                             now - reqs[0].t_submit >= self.batch_deadline):
                    self._execute(key, reqs)
                    reqs.clear()
                if not reqs:
                    self._pending.pop(key, None)
            self.metrics.set_queue_depth(self.depth())
            if draining and not self._pending and self._ingress.empty():
                return

    def _expire(self, key, reqs: List[_Request], now: float) -> None:
        alive = [r for r in reqs if r.deadline > now]
        for r in reqs:
            if r.deadline <= now:
                self.metrics.timed_out()
                exc = RequestTimeoutError(
                    f"request waited > {self.request_timeout * 1e3:.0f} ms "
                    f"in bucket {key[1]}")
                r.future.set_exception(exc)
                if r.stream is not None:
                    r.stream.fail(exc)
        reqs[:] = alive

    def _run_batch(self, key, reqs: List[_Request]) -> List:
        """One engine call for a coalesced micro-batch; dispatch on kind."""
        kind, bucket, _steps = key
        graphs = [r.graph for r in reqs]
        rids = _request_ids(reqs)
        if kind == "rollout_stream":
            return [self._run_stream(r) for r in reqs]
        if kind == "tiled":
            return [self._run_tiled(r) for r in reqs]
        if kind == "rollout":
            return self.engine.rollout_batch(graphs, request_ids=rids)
        return self.engine.predict_batch(graphs, bucket=bucket,
                                         request_ids=rids)

    def _run_stream(self, r: _Request) -> dict:
        """Execute ONE streamed rollout scene. Exceptions stay inside: a
        failed chunk fails the request's sink and future directly — a
        partially-emitted stream must never re-run through the solo-retry
        path (the client already consumed its prefix)."""
        sink = r.stream
        try:
            summary = self.engine.rollout_stream(r.graph, sink,
                                                 request_id=r.request_id)
        except Exception as exc:
            self.metrics.failed()
            sink.fail(exc)
            r.future.set_exception(exc)
            return {"error": repr(exc)}
        if summary.get("cancelled"):
            # client went away mid-stream: the remaining steps were skipped
            # at the chunk boundary — the freed-compute audit trail
            obs.event("serve/stream_cancelled",
                      request_id=r.request_id,
                      steps_done=summary["steps_done"],
                      steps_total=summary["steps_total"],
                      steps_skipped=(summary["steps_total"]
                                     - summary["steps_done"]))
        sink.finish(summary)
        return summary

    def _run_tiled(self, r: _Request) -> dict:
        """Execute ONE giant scene through the tiled executor. Same
        containment shape as :meth:`_run_stream`: failures resolve the
        request's sink and future directly and never reach the solo-retry
        path (a tiled request already IS solo, and its progress stream may
        have partially emitted)."""
        sink = r.stream
        progress = None
        if sink is not None:
            seq = [0]

            def progress(**info):
                if sink.cancelled:
                    return False        # client gone: stop at tile boundary
                sink.put_chunk(seq[0], info)
                seq[0] += 1
                return True

        try:
            out = self.engine.predict_tiled(r.graph,
                                            request_id=r.request_id,
                                            progress=progress)
        except Exception as exc:
            self.metrics.failed()
            if sink is not None:
                sink.fail(exc)
            r.future.set_exception(exc)
            return {"error": repr(exc)}
        if out.get("cancelled"):
            obs.event("serve/tiled_cancelled", request_id=r.request_id,
                      tiles=out["tiles"], layers=out["layers"])
        if sink is not None:
            sink.finish(out)
        return out

    def _execute(self, key, reqs: List[_Request]) -> None:
        kind, bucket, steps = key
        if self._inject_latency_s > 0:
            time.sleep(self._inject_latency_s)  # chaos: slow device
        t_start = time.perf_counter()
        try:
            outs = self._run_batch(key, reqs)
        except WorkerLostError as exc:
            # the executor itself is gone, not one bad graph: kill the queue
            # (futures fail typed, the replica layer claims them for
            # failover) and let the dispatcher die at its kill check
            self.kill(reason=str(exc))
            raise _KilledError() from None
        except Exception:
            # one bad graph fails the whole padded batch — retry each request
            # ALONE once, so a poison graph only takes down itself
            self._retry_individually(key, reqs)
            return
        now = time.perf_counter()
        self.last_progress = now  # batch progress heartbeat for the supervisor
        lats = [(now - r.t_submit) * 1e3 for r in reqs]
        qms = [(t_start - r.t_submit) * 1e3 for r in reqs]
        self.metrics.batch_done(len(reqs), self.engine.max_batch, lats, qms)
        obs.event("serve/batch", n=bucket.n, e=bucket.e, filled=len(reqs),
                  capacity=self.engine.max_batch, workload=kind,
                  dur_s=round(now - t_start, 6),
                  request_ids=_request_ids(reqs),
                  queue_ms=[round(q, 3) for q in qms])
        compute_ms = round((now - t_start) * 1e3, 3)
        for r, out, q_ms in zip(reqs, outs, qms):
            r.future.meta.update(queue_ms=round(q_ms, 3),
                                 compute_ms=compute_ms,
                                 batch_filled=len(reqs),
                                 bucket_n=bucket.n, bucket_e=bucket.e,
                                 request_id=r.request_id)
            r.future.set_result(out)

    def _retry_individually(self, key, reqs: List[_Request]) -> None:
        kind, bucket, _steps = key
        self.metrics.retried(len(reqs))
        for r in reqs:
            t_start = time.perf_counter()
            try:
                out = self._run_batch(key, [r])[0]
            except WorkerLostError as exc:
                self.kill(reason=str(exc))
                raise _KilledError() from None
            except Exception as solo_exc:  # fails even alone: the poison graph
                self.metrics.poison()
                self.metrics.failed()
                r.future.set_exception(solo_exc)
                continue
            now = time.perf_counter()
            q_ms = (t_start - r.t_submit) * 1e3
            self.metrics.batch_done(1, self.engine.max_batch,
                                    [(now - r.t_submit) * 1e3], [q_ms])
            obs.event("serve/batch", n=bucket.n, e=bucket.e, filled=1,
                      capacity=self.engine.max_batch, workload=kind,
                      dur_s=round(now - t_start, 6), retry=True,
                      request_ids=_request_ids([r]),
                      queue_ms=[round(q_ms, 3)])
            r.future.meta.update(
                queue_ms=round(q_ms, 3),
                compute_ms=round((now - t_start) * 1e3, 3),
                batch_filled=1, bucket_n=bucket.n, bucket_e=bucket.e,
                request_id=r.request_id)
            r.future.set_result(out)

    def _fail_all(self, exc: BaseException) -> None:
        # list() copies: kill() calls this from a foreign thread while the
        # dispatcher may still be mutating _pending; futures are first-wins
        # so double resolution is harmless
        for reqs in list(self._pending.values()):
            for r in list(reqs):
                r.future.set_exception(exc)
                if r.stream is not None:
                    r.stream.fail(exc)
        self._pending.clear()
        while True:
            try:
                item = self._ingress.get_nowait()
            except _pyqueue.Empty:
                return
            if item is _KILL:
                continue
            if not (isinstance(item, tuple) and item[0] is _STOP):
                item.future.set_exception(exc)
                if item.stream is not None:
                    item.stream.fail(exc)
