"""Multi-model routing — name -> (replica set, warmup state).

One gateway process fronts N independently-configured models (the
``serve.models:`` config list): each :class:`ModelEntry` owns a
:class:`~distegnn_tpu.serve.replica.ReplicaSet` of ``serve.replicas``
shared-nothing (engine, queue) pairs — every replica has its own
InferenceEngine (compile cache) and RequestQueue (micro-batcher), all
sharing one ServeMetrics — plus warmup state. One model's traffic, compile
storm, or total replica loss never perturbs another model's entries: the
registry reports per-model health and the transport sheds ONLY the broken
model (typed 503 + Retry-After).

The registry is the routing table the HTTP transport resolves
``/v1/models/<name>/...`` against, and the single lifecycle handle the
gateway's SIGTERM drain walks. ``stop(drain=True)`` drains every model
CONCURRENTLY, each bounded by the grace budget, so one wedged queue can't
eat every other model's drain window.

Params come from ``model.checkpoint`` when set (verified restore via
``train/checkpoint.restore_params``); otherwise the entry initializes
random params from the config seed — the synthetic-load/bench path.
:meth:`ModelRegistry.swap` is the blue/green path for retrained models:
checksummed restore, per-rung canary forward pass, one-replica-at-a-time
atomic flips, auto-rollback on any failure — without dropping the queue.
"""

from __future__ import annotations

import copy
import threading
import time
from typing import Dict, List, Optional, Sequence

from distegnn_tpu import obs
from distegnn_tpu.serve.buckets import Bucket, synthetic_graph
from distegnn_tpu.serve.engine import InferenceEngine
from distegnn_tpu.serve.queue import RequestQueue
from distegnn_tpu.serve.replica import ReplicaSet


class SwapError(RuntimeError):
    """A blue/green swap failed (restore or canary stage). ``rolled_back``
    is True when serving params are back to the pre-swap version — the
    gateway reports it so operators know nothing is half-flipped."""

    def __init__(self, msg: str, stage: str, rolled_back: bool):
        super().__init__(msg)
        self.stage = stage
        self.rolled_back = bool(rolled_back)


class SwapInProgressError(RuntimeError):
    """A swap is already running for this model (one at a time)."""


class ModelEntry:
    """One served model: a replica set + warmup/swap state, owned by a name.

    ``engine`` is the PRIMARY replica's engine — the stable handle for
    feature widths, the session prep cache, and capability flags (engines
    survive replica restarts; only queues are rebuilt). ``queue`` is the
    replica set itself, which duck-types RequestQueue, so all pre-replica
    callers (transport routes, benches, tests) work unchanged.
    """

    def __init__(self, name: str, engine: InferenceEngine,
                 queue: RequestQueue, feat_nf: int, edge_attr_nf: int,
                 config=None, extra_replicas: Sequence = (),
                 supervisor_opts: Optional[dict] = None,
                 replica_objs: Optional[Sequence] = None):
        self.name = name
        self.engine = engine
        if replica_objs is not None:
            # process backend: pre-built WorkerReplica objects; ``engine``
            # is the parent-side reference handle they all share
            members = list(replica_objs)
        else:
            members = [(engine, queue)] + list(extra_replicas)
        self.replicas = ReplicaSet(name, members,
                                   supervisor_opts=supervisor_opts)
        self.feat_nf = int(feat_nf)
        self.edge_attr_nf = int(edge_attr_nf)
        self.config = config
        self.warmed: List[Bucket] = []
        self.state = "cold"            # cold -> ready | failed
        self.error: Optional[str] = None
        self.checkpoint: Optional[str] = None
        self.params_version = 0
        self._swap_lock = threading.Lock()
        # ``replica_factory(idx) -> Replica``: the autoscaler's scale-up
        # recipe, set by _build_entry (None for hand-built entries — those
        # sets are not elastically growable). Reads the entry's CURRENT
        # params/checkpoint at call time, so replicas added after a
        # blue/green swap come up on the live version.
        self.replica_factory = None

    @property
    def queue(self) -> ReplicaSet:
        return self.replicas

    def start(self) -> None:
        self.replicas.start()

    def stop(self, drain: bool = True, join_timeout_s: float = 30.0) -> None:
        self.replicas.stop(drain=drain, join_timeout_s=join_timeout_s)

    def warmup(self, nodes: Sequence[int]) -> None:
        """Pre-compile the rungs admitting synthetic graphs of the given
        node counts on EVERY replica engine; flips state to 'ready' (or
        'failed', kept servable so /v1/models can show WHY readiness is
        down)."""
        try:
            sizes = []
            for n in nodes:
                g = synthetic_graph(int(n), seed=0, feat_nf=self.feat_nf,
                                    edge_attr_nf=self.edge_attr_nf)
                sizes.append((int(g["loc"].shape[0]),
                              int(g["edge_index"].shape[1])))
            for r in self.replicas.replicas:
                warmed = r.warmup(sizes)
            self.warmed = warmed
            self.state = "ready"
        except Exception as exc:
            self.state, self.error = "failed", repr(exc)
            obs.event("gateway/warmup_failed", model=self.name,
                      error=repr(exc))

    def alive(self) -> bool:
        return self.replicas.alive()

    def add_replica(self, warm_sizes=None):
        """The autoscaler's scale-up unit. ``replica_factory`` reads the
        entry's params/checkpoint at build time, so a blue/green swap racing
        the build could hand the new replica a snapshot the flip loop
        already retired — and never revisit it (the loop iterates the list
        as it was while the replica was still unappended). The post-append
        re-pin below runs under the swap lock, where the live version is
        stable, closing that window for every interleaving."""
        if self.replica_factory is None:
            raise RuntimeError(f"model '{self.name}' has no replica "
                               f"factory; its set is not growable")
        replica = self.replicas.add_replica(self.replica_factory,
                                            warm_sizes=warm_sizes)
        with self._swap_lock:   # blocks until any in-flight swap lands
            ck = getattr(replica, "current_checkpoint", None)
            if ck is not None or getattr(replica, "_ckpt_lock",
                                         None) is not None:
                stale = str(ck) != str(self.checkpoint)
            else:
                eng = getattr(replica, "engine", None)
                stale = (eng is not None
                         and eng.params is not self.engine.params)
            if stale:
                replica.swap_params(str(self.checkpoint),
                                    self.engine.params, list(self.warmed))
                obs.event("gateway/scale_up_repin", model=self.name,
                          replica=replica.idx,
                          version=self.params_version)
        return replica

    @property
    def rollout_enabled(self) -> bool:
        return self.engine.rollout_enabled

    # ---- blue/green hot-swap ---------------------------------------------
    def swap(self, checkpoint) -> dict:
        """Swap serving params to ``checkpoint`` under load, blue/green:

        1. checksummed params-only restore (``restore_params``) — corrupt
           or shape-mismatched checkpoints fail HERE, params untouched;
        2. per-replica canary: forward the CANDIDATE params through every
           warmed rung's compiled executable on a synthetic graph
           (NaN/shape check) before that replica flips;
        3. atomic one-at-a-time flips (params are a runtime argument of the
           shape-keyed executables — no recompile, the queue never drops);
        4. any canary failure rolls every already-flipped replica back to
           the old params and raises :class:`SwapError` (rolled_back=True).
        """
        from distegnn_tpu.train.checkpoint import restore_params

        if not self._swap_lock.acquire(blocking=False):
            raise SwapInProgressError(
                f"a swap is already in progress for model '{self.name}'")
        try:
            obs.event("gateway/swap_begin", model=self.name,
                      path=str(checkpoint))
            old_params = self.engine.params
            try:
                new_params = restore_params(str(checkpoint), old_params)
            except Exception as exc:
                obs.event("gateway/swap_rollback", model=self.name,
                          stage="restore", flipped=0, error=repr(exc)[:300])
                raise SwapError(
                    f"swap restore failed for '{self.name}': {exc}",
                    stage="restore", rolled_back=True) from exc
            rungs = list(self.warmed)
            flipped: List = []
            try:
                for r in self.replicas.replicas:
                    # per-replica blue/green unit: the replica canaries and
                    # flips its OWN executor (local engine, or the worker
                    # child over IPC — a down worker defers to its respawn)
                    checked = r.swap_params(str(checkpoint), new_params,
                                            rungs)
                    obs.event("gateway/swap_canary", model=self.name,
                              replica=r.idx, rungs=checked)
                    flipped.append(r)
                    obs.event("gateway/swap_flip", model=self.name,
                              replica=r.idx)
            except Exception as exc:
                for r in flipped:
                    r.swap_rollback(old_params)
                obs.event("gateway/swap_rollback", model=self.name,
                          stage="canary", flipped=len(flipped),
                          error=repr(exc)[:300])
                raise SwapError(
                    f"swap canary failed for '{self.name}': {exc}; rolled "
                    f"back {len(flipped)} flipped replica(s)",
                    stage="canary", rolled_back=True) from exc
            # the parent reference handle tracks the live version: it is the
            # digest source for worker respawns and the params source for
            # degraded fallbacks (no-op for thread replica 0, same engine)
            self.engine.params = new_params
            self.checkpoint = str(checkpoint)
            self.params_version += 1
            obs.event("gateway/swap_done", model=self.name,
                      path=str(checkpoint), version=self.params_version,
                      replicas=len(self.replicas.replicas),
                      rungs_canaried=len(rungs))
            return {"model": self.name, "checkpoint": str(checkpoint),
                    "version": self.params_version,
                    "replicas": len(self.replicas.replicas),
                    "rungs_canaried": len(rungs)}
        finally:
            self._swap_lock.release()

    def describe(self) -> dict:
        snap = self.engine.metrics.snapshot()
        return {
            "name": self.name,
            "state": self.state,
            "error": self.error,
            "dispatcher_alive": self.alive(),
            "warmed_rungs": [[b.n, b.e] for b in self.warmed],
            "max_batch": self.engine.max_batch,
            "ladder": {"max_nodes": self.engine.ladder.max_nodes,
                       "max_edges": self.engine.ladder.max_edges},
            "queue_depth": self.replicas.depth(),
            "requests_completed": snap["requests_completed"],
            # clients (scripts/traffic_gen.py) read this to know whether
            # rollout traffic is servable or would 501
            "rollout": self.rollout_enabled,
            "replicas": self.replicas.health(),
            "replicas_available": self.replicas.available(),
            "params_version": self.params_version,
            "checkpoint": self.checkpoint,
        }


class ModelRegistry:
    """name -> ModelEntry routing table + one lifecycle handle."""

    def __init__(self, entries: Dict[str, ModelEntry]):
        if not entries:
            raise ValueError("ModelRegistry needs at least one model entry")
        self._entries = dict(entries)

    # ---- construction ----------------------------------------------------
    @classmethod
    def from_config(cls, cfg, default_name: str = "default") -> "ModelRegistry":
        """Build from a config: the ``serve.models:`` list (each item a
        mapping with ``name`` + optional ``config_path``/``overrides``), or
        — when the list is absent — ONE entry from the config itself."""
        from distegnn_tpu.config import (ConfigDict, _merge, load_config,
                                         validate_config)

        models = cfg.serve.get("models") or None
        entries: Dict[str, ModelEntry] = {}
        if not models:
            entries[default_name] = cls._build_entry(default_name, cfg)
            return cls(entries)
        for item in models:
            name = str(item["name"])
            if item.get("config_path"):
                m_cfg = load_config(str(item["config_path"]))
            else:
                m_cfg = ConfigDict(copy.deepcopy(cfg.to_dict()))
            overrides = item.get("overrides")
            if overrides:
                m_cfg = ConfigDict(_merge(m_cfg.to_dict(),
                                          dict(overrides)))
                validate_config(m_cfg)
            entries[name] = cls._build_entry(name, m_cfg)
        return cls(entries)

    @staticmethod
    def _build_entry(name: str, cfg) -> ModelEntry:
        from distegnn_tpu.serve import (engine_from_config,
                                        engine_with_params_from_config)
        from distegnn_tpu.serve.metrics import ServeMetrics
        from distegnn_tpu.serve.replica import WorkerReplica

        n_replicas = max(1, int(cfg.serve.get("replicas", 1) or 1))
        backend = str(cfg.serve.get("workers", "thread") or "thread")
        metrics = ServeMetrics()  # shared by every replica of this model
        # the deterministic recipe (seeded init -> optional checksummed
        # restore) is SHARED with the worker child, which rebuilds params
        # from the same config — the spawn-handshake digest check pins the
        # two sides bitwise-identical
        model, engine, queue, params = engine_with_params_from_config(
            cfg, metrics=metrics)
        feat_nf = int(cfg.model.node_feat_nf)
        edge_nf = int(cfg.model.edge_attr_nf)
        ckpt = cfg.model.get("checkpoint")
        if ckpt:
            obs.event("gateway/params_restored", model=name, path=str(ckpt))
        supervisor_opts = dict(cfg.serve.get("supervisor") or {})
        if backend == "process":
            s = cfg.serve
            queue_kw = dict(
                batch_deadline_ms=s.batch_deadline_ms,
                queue_capacity=s.queue_capacity,
                request_timeout_ms=s.request_timeout_ms,
                result_margin_s=float(s.get("result_margin_s", 30.0)),
                metrics=metrics)
            cfg_dict = copy.deepcopy(cfg.to_dict())
            worker_opts = dict(cfg.serve.get("worker") or {})

            def fallback_factory(_cfg=cfg, _model=model, _engine=engine,
                                 _metrics=metrics):
                # spawn-failure degradation: a fresh in-process pair serving
                # the parent handle's CURRENT params (post-swap correct),
                # sharing the prep cache so sessions keep their hit rate
                eng_i, q_i = engine_from_config(_cfg, _model,
                                                params=_engine.params,
                                                metrics=_metrics)
                eng_i.prep_cache = _engine.prep_cache
                return eng_i, q_i

            replica_objs = [
                WorkerReplica(i, engine, model=name, queue_kw=queue_kw,
                              worker_opts=worker_opts, cfg_dict=cfg_dict,
                              fallback_factory=fallback_factory,
                              checkpoint=(str(ckpt) if ckpt else None))
                for i in range(n_replicas)]
            entry = ModelEntry(name, engine, None, feat_nf, edge_nf,
                               config=cfg, replica_objs=replica_objs,
                               supervisor_opts=supervisor_opts)
        else:
            extra = []
            for _ in range(n_replicas - 1):
                eng_i, q_i = engine_from_config(cfg, model, params=params,
                                                metrics=metrics)
                # the prep-plan cache is engine-agnostic (pure layout plans):
                # share it so a failed-over session keeps its prep hit rate
                eng_i.prep_cache = engine.prep_cache
                extra.append((eng_i, q_i))
            entry = ModelEntry(name, engine, queue, feat_nf, edge_nf,
                               config=cfg, extra_replicas=extra,
                               supervisor_opts=supervisor_opts)
        if ckpt:
            entry.checkpoint = str(ckpt)
        if backend == "process":
            def replica_factory(idx, _entry=entry, _queue_kw=queue_kw,
                                _worker_opts=worker_opts, _cfg_dict=cfg_dict,
                                _fallback=fallback_factory):
                return WorkerReplica(
                    idx, _entry.engine, model=_entry.name,
                    queue_kw=_queue_kw, worker_opts=_worker_opts,
                    cfg_dict=_cfg_dict, fallback_factory=_fallback,
                    checkpoint=_entry.checkpoint)
        else:
            def replica_factory(idx, _cfg=cfg, _model=model, _entry=entry,
                                _metrics=metrics):
                from distegnn_tpu.serve.replica import Replica

                # fresh engine + queue serving the entry's CURRENT params
                # (post-swap correct), sharing the primary's prep cache so
                # failed-over sessions keep their hit rate
                eng_i, q_i = engine_from_config(_cfg, _model,
                                                params=_entry.engine.params,
                                                metrics=_metrics)
                eng_i.prep_cache = _entry.engine.prep_cache
                return Replica(idx, eng_i, q_i)
        entry.replica_factory = replica_factory
        return entry

    @classmethod
    def single(cls, name: str, engine: InferenceEngine, queue: RequestQueue,
               feat_nf: int = 1, edge_attr_nf: int = 2) -> "ModelRegistry":
        """Wrap one pre-built engine/queue pair (the bench's http mode and
        the transport tests)."""
        return cls({name: ModelEntry(name, engine, queue, feat_nf,
                                     edge_attr_nf)})

    # ---- routing ---------------------------------------------------------
    def get(self, name: str) -> ModelEntry:
        return self._entries[name]      # KeyError -> the transport's 404

    def names(self) -> List[str]:
        return sorted(self._entries)

    def items(self):
        return sorted(self._entries.items())

    def __len__(self) -> int:
        return len(self._entries)

    # ---- blue/green hot-swap ---------------------------------------------
    def swap(self, name: str, checkpoint) -> dict:
        """Blue/green swap one model's params under load (KeyError -> the
        transport's 404; see :meth:`ModelEntry.swap`)."""
        return self._entries[name].swap(checkpoint)

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> "ModelRegistry":
        for _, e in self.items():
            e.start()
        return self

    def warmup(self, nodes: Sequence[int]) -> None:
        for _, e in self.items():
            e.warmup(nodes)

    def stop(self, drain: bool = True,
             grace_s: Optional[float] = None) -> None:
        """Stop every model CONCURRENTLY (idempotent; safe from a SIGTERM
        handler thread racing other shutdown paths). Each model drains in
        parallel bounded by ``grace_s`` (default 30 s), so one wedged
        queue can't consume every other model's drain window."""
        budget = 30.0 if grace_s is None else max(float(grace_s), 0.1)
        entries = self.items()
        # phase 1 for EVERY model before any drain: stop the supervisors so
        # an in-flight restart can't revive a queue / spawn a worker after
        # its drain begins (the supervisor also rechecks _supervised after
        # any blocking claim, covering a restart already past the flag)
        for _, e in entries:
            e.replicas.begin_stop()
        if len(entries) == 1:
            entries[0][1].stop(drain=drain, join_timeout_s=budget)
            return
        threads = []
        for _, e in entries:
            t = threading.Thread(target=e.stop, name=f"drain-{e.name}",
                                 kwargs=dict(drain=drain,
                                             join_timeout_s=budget),
                                 daemon=True)
            t.start()
            threads.append(t)
        deadline = time.monotonic() + budget + 5.0
        for t in threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.1))

    # ---- health -----------------------------------------------------------
    def ready(self) -> bool:
        """All models warmed and their dispatcher threads alive."""
        return all(e.state == "ready" and e.alive()
                   for e in self._entries.values())

    def any_ready(self) -> bool:
        """At least one model is servable — the gateway keeps routing in
        degraded mode instead of flipping the whole fleet to 503."""
        return any(e.state == "ready" and e.alive()
                   for e in self._entries.values())

    def health(self) -> Dict[str, dict]:
        """Per-model readiness detail for /readyz's degraded reporting."""
        out: Dict[str, dict] = {}
        for name, e in self.items():
            out[name] = {
                "state": e.state,
                "ready": e.state == "ready" and e.alive(),
                "error": e.error,
                "replicas_available": e.replicas.available(),
                "replicas_total": len(e.replicas.replicas),
                # per-worker detail (pid/heartbeat for process backends;
                # threads report backend only) — /readyz surfaces this and
                # /metrics derives the heartbeat-age gauges from it
                "workers": [
                    {"replica": h["replica"],
                     "backend": h.get("backend", "thread"),
                     "pid": h.get("pid"),
                     "heartbeat_age_s": h.get("heartbeat_age_s"),
                     "restarts": h["restarts"],
                     "degraded": h.get("degraded", False)}
                    for h in e.replicas.health()],
            }
        return out

    def describe(self) -> dict:
        return {"models": [e.describe() for _, e in self.items()]}
