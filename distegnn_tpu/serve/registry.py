"""Multi-model routing — name -> (engine, queue, warmup state).

One gateway process fronts N independently-configured models (the
``serve.models:`` config list): each :class:`ModelEntry` owns its own
InferenceEngine (compile cache, ladder), RequestQueue (micro-batcher,
admission), ServeMetrics, and warmup state, so one model's traffic or
compile storm never perturbs another's rungs. The registry is the routing
table the HTTP transport (``serve/transport.py``) resolves
``/v1/models/<name>/...`` against, and the single lifecycle handle the
gateway's SIGTERM drain walks (start all -> warm all -> stop(drain=True)
all — queue.stop is idempotent, so a bench or atexit racing the drain is
harmless).

Params come from ``model.checkpoint`` when set (verified restore via
``train/checkpoint.restore_params``); otherwise the entry initializes
random params from the config seed — the synthetic-load/bench path.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Sequence

from distegnn_tpu import obs
from distegnn_tpu.serve.buckets import Bucket, synthetic_graph
from distegnn_tpu.serve.engine import InferenceEngine
from distegnn_tpu.serve.queue import RequestQueue


class ModelEntry:
    """One served model: engine + queue + warmup state, owned by a name."""

    def __init__(self, name: str, engine: InferenceEngine,
                 queue: RequestQueue, feat_nf: int, edge_attr_nf: int,
                 config=None):
        self.name = name
        self.engine = engine
        self.queue = queue
        self.feat_nf = int(feat_nf)
        self.edge_attr_nf = int(edge_attr_nf)
        self.config = config
        self.warmed: List[Bucket] = []
        self.state = "cold"            # cold -> ready | failed
        self.error: Optional[str] = None

    def warmup(self, nodes: Sequence[int]) -> None:
        """Pre-compile the rungs admitting synthetic graphs of the given
        node counts; flips state to 'ready' (or 'failed', kept servable so
        /v1/models can show WHY readiness is down)."""
        try:
            sizes = []
            for n in nodes:
                g = synthetic_graph(int(n), seed=0, feat_nf=self.feat_nf,
                                    edge_attr_nf=self.edge_attr_nf)
                sizes.append((int(g["loc"].shape[0]),
                              int(g["edge_index"].shape[1])))
            self.warmed = self.engine.warmup(sizes)
            self.state = "ready"
        except Exception as exc:
            self.state, self.error = "failed", repr(exc)
            obs.event("gateway/warmup_failed", model=self.name,
                      error=repr(exc))

    def alive(self) -> bool:
        return self.queue.alive()

    def describe(self) -> dict:
        snap = self.engine.metrics.snapshot()
        return {
            "name": self.name,
            "state": self.state,
            "error": self.error,
            "dispatcher_alive": self.alive(),
            "warmed_rungs": [[b.n, b.e] for b in self.warmed],
            "max_batch": self.engine.max_batch,
            "ladder": {"max_nodes": self.engine.ladder.max_nodes,
                       "max_edges": self.engine.ladder.max_edges},
            "queue_depth": self.queue.depth(),
            "requests_completed": snap["requests_completed"],
            # clients (scripts/traffic_gen.py) read this to know whether
            # rollout traffic is servable or would 501
            "rollout": bool(getattr(self.engine, "_rollout_opts", None)),
        }


class ModelRegistry:
    """name -> ModelEntry routing table + one lifecycle handle."""

    def __init__(self, entries: Dict[str, ModelEntry]):
        if not entries:
            raise ValueError("ModelRegistry needs at least one model entry")
        self._entries = dict(entries)

    # ---- construction ----------------------------------------------------
    @classmethod
    def from_config(cls, cfg, default_name: str = "default") -> "ModelRegistry":
        """Build from a config: the ``serve.models:`` list (each item a
        mapping with ``name`` + optional ``config_path``/``overrides``), or
        — when the list is absent — ONE entry from the config itself."""
        from distegnn_tpu.config import (ConfigDict, _merge, load_config,
                                         validate_config)

        models = cfg.serve.get("models") or None
        entries: Dict[str, ModelEntry] = {}
        if not models:
            entries[default_name] = cls._build_entry(default_name, cfg)
            return cls(entries)
        for item in models:
            name = str(item["name"])
            if item.get("config_path"):
                m_cfg = load_config(str(item["config_path"]))
            else:
                m_cfg = ConfigDict(copy.deepcopy(cfg.to_dict()))
            overrides = item.get("overrides")
            if overrides:
                m_cfg = ConfigDict(_merge(m_cfg.to_dict(),
                                          dict(overrides)))
                validate_config(m_cfg)
            entries[name] = cls._build_entry(name, m_cfg)
        return cls(entries)

    @staticmethod
    def _build_entry(name: str, cfg) -> ModelEntry:
        import jax

        from distegnn_tpu.models.registry import get_model
        from distegnn_tpu.serve import engine_from_config

        model = get_model(cfg.model, dataset_name=cfg.data.dataset_name)
        engine, queue = engine_from_config(cfg, model, params=None)
        feat_nf = int(cfg.model.node_feat_nf)
        edge_nf = int(cfg.model.edge_attr_nf)
        seed = int(cfg.get("seed", 0) or 0)
        g = synthetic_graph(2, seed=seed, feat_nf=feat_nf,
                            edge_attr_nf=edge_nf)
        b0 = engine.ladder.bucket_of_graph(g)
        init_batch, _ = engine.ladder.pad_batch([g], b0, 1,
                                                **engine._layout_opts)
        params = model.init(jax.random.PRNGKey(seed), init_batch)
        ckpt = cfg.model.get("checkpoint")
        if ckpt:
            from distegnn_tpu.train.checkpoint import restore_params

            params = restore_params(ckpt, params)
            obs.event("gateway/params_restored", model=name, path=str(ckpt))
        engine.params = params
        return ModelEntry(name, engine, queue, feat_nf, edge_nf, config=cfg)

    @classmethod
    def single(cls, name: str, engine: InferenceEngine, queue: RequestQueue,
               feat_nf: int = 1, edge_attr_nf: int = 2) -> "ModelRegistry":
        """Wrap one pre-built engine/queue pair (the bench's http mode and
        the transport tests)."""
        return cls({name: ModelEntry(name, engine, queue, feat_nf,
                                     edge_attr_nf)})

    # ---- routing ---------------------------------------------------------
    def get(self, name: str) -> ModelEntry:
        return self._entries[name]      # KeyError -> the transport's 404

    def names(self) -> List[str]:
        return sorted(self._entries)

    def items(self):
        return sorted(self._entries.items())

    def __len__(self) -> int:
        return len(self._entries)

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> "ModelRegistry":
        for _, e in self.items():
            e.queue.start()
        return self

    def warmup(self, nodes: Sequence[int]) -> None:
        for _, e in self.items():
            e.warmup(nodes)

    def stop(self, drain: bool = True) -> None:
        """Stop every queue (idempotent; safe from a SIGTERM handler thread
        racing other shutdown paths)."""
        for _, e in self.items():
            e.queue.stop(drain=drain)

    def ready(self) -> bool:
        """All models warmed and their dispatcher threads alive."""
        return all(e.state == "ready" and e.alive()
                   for e in self._entries.values())

    def describe(self) -> dict:
        return {"models": [e.describe() for _, e in self.items()]}
