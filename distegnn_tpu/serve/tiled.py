"""Tiled inference executor — million-node scenes through ONE compiled
fixed-shape tile program with host-side halo exchange.

Scenes above the bucket ladder's cap used to be hard 413s (serve/buckets.py).
Here they serve as a *scan over tiles* of a Morton-ordered plan
(ops/tiling.py): every layer runs the SAME jitted single-tile EGCL program
over every tile, reading cross-tile sender (halo) features from the
layer-input snapshot held on the host, and the virtual-node state (X, Hv) —
the paper's only global coupling — is closed once per layer from per-tile
masked partial sums (models/fast_egnn.py ``tile_partials`` mode +
``tiled_virtual_update``). That is exactly the monolithic forward in a
different summation order: every cross-node quantity in the EGCL layer
derives from LAYER-INPUT state, so parity holds to float-accumulation
order (tests/test_tiled.py, 1e-5 scale-normalized).

Why this is the right shape for giant scenes:

  - ONE executable per tile rung (``TilePlan.shape_key``), regardless of
    scene size: tile axes are quantized to geometric rungs, so the whole
    fleet of giant scenes shares a handful of compiled programs, cached in
    the engine's existing compile-cache LRU.
  - Device residency is bounded by TWO staged tiles plus the tiny virtual
    state, not O(N): tile k+1's inputs are ``device_put`` while tile k
    computes (double buffering), and the non-overlapped H2D remainder is
    measured and exported as the stall fraction.
  - Halo exchange is a host-side gather between tile invocations — no
    device-side cross-tile addressing, no ragged shapes, no recompiles.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distegnn_tpu import obs
from distegnn_tpu.ops.graph import GraphBatch, pad_graphs
from distegnn_tpu.ops.tiling import TilePlan, plan_tiles
from distegnn_tpu.serve.buckets import BucketOverflowError

#: serve.tiled: config defaults (config.py mirrors these; keep in sync)
TILED_DEFAULTS = {
    "enable": True,
    "max_nodes": 4_194_304,     # TiledOverflowError beyond this
    "tile_nodes": 65536,        # own-node slots per tile
    "halo_floor": 1024,         # halo rung floor (geometric growth above)
    "edge_floor": 8192,         # plain-layout edge rung floor
    "growth": 2.0,              # rung growth factor (matches the ladder)
    "timeout_factor": 8.0,      # tiled deadline = factor * request_timeout
    "devices": 1,               # 'auto'|N: device-parallel tile rounds
                                # (serve/mesh_tiled.py); 1 = sequential
}


class TiledOverflowError(BucketOverflowError):
    """The scene exceeds even the tiled executor's bound
    (``serve.tiled.max_nodes``). Subclasses BucketOverflowError so the
    gateway's existing 413 mapping applies unchanged."""


class TiledExecutor:
    """Runs one engine's model over a :class:`~distegnn_tpu.ops.tiling.
    TilePlan`, sharing the engine's params, compile cache, and metrics.

    Built by :class:`~distegnn_tpu.serve.engine.InferenceEngine` when a
    ``serve.tiled:`` config block is present; the engine dispatches
    ``n_nodes > ladder.max_nodes`` requests here (serve/transport.py routes
    them under bulk-priority admission).
    """

    def __init__(self, engine, cfg: Optional[dict] = None):
        c = dict(TILED_DEFAULTS)
        c.update(cfg or {})
        self.engine = engine
        self.enable = bool(c["enable"])
        self.max_nodes = int(c["max_nodes"])
        self.tile_nodes = int(c["tile_nodes"])
        self.halo_floor = int(c["halo_floor"])
        self.edge_floor = int(c["edge_floor"])
        self.growth = float(c["growth"])
        self.timeout_factor = float(c["timeout_factor"])
        # 'auto' | int: device-parallel tile rounds (serve/mesh_tiled.py).
        # Resolved per predict against the live device count — plans and
        # shape_key are device-count-independent, so the same (possibly
        # session-cached) plan serves at any setting.
        self.devices = c["devices"] if c["devices"] == "auto" \
            else int(c["devices"])
        layout = dict(getattr(engine, "_layout_opts", {}) or {})
        model = engine.model
        impl = str(getattr(model, "edge_impl", "plain") or "plain")
        # fused_stack lowers to the per-layer fused path (identical params);
        # the megakernel's whole-loop grid cannot host a per-tile scan
        self.edge_impl = "fused" if impl in ("fused", "fused_stack") else "plain"
        self.edge_block = (int(layout.get("edge_block", 512) or 512)
                           if self.edge_impl == "fused" else 0)
        self.edge_tile = int(layout.get("edge_tile", 512) or 512)
        g = self.engine.metrics.registry.gauge
        self._g_tiles = g("serve/tiled_tiles")
        self._g_halo = g("serve/tiled_halo_fraction")
        self._g_stall = g("serve/tiled_stall_fraction")
        # mesh-round gauges (serve/mesh_tiled.py): devices used by the last
        # tiled predict, mean compute ms per round, host halo-gather ms
        self._g_devices = g("serve/tiled_devices")
        self._g_round_ms = g("serve/tiled_round_ms")
        self._g_halo_gather = g("serve/tiled_halo_gather_ms")

    # ---- admission -------------------------------------------------------
    def check_admit(self, n: int) -> None:
        if int(n) > self.max_nodes:
            raise TiledOverflowError(
                f"request nodes={int(n)} exceeds the tiled serving bound "
                f"{self.max_nodes}; raise serve.tiled.max_nodes or shard "
                f"the request")

    # ---- planning --------------------------------------------------------
    def plan(self, graph: dict) -> TilePlan:
        """Morton tile plan for one scene (ops/tiling.plan_tiles with this
        engine's layout). Cacheable per session (serve/prep.py)."""
        return plan_tiles(
            np.asarray(graph["edge_index"]), np.asarray(graph["loc"]),
            np.asarray(graph["edge_attr"]) if graph.get("edge_attr") is not None else None,
            tile_nodes=self.tile_nodes, halo_floor=self.halo_floor,
            edge_floor=self.edge_floor, growth=self.growth,
            edge_block=self.edge_block, edge_tile=self.edge_tile)

    def _plan_ok(self, plan: TilePlan, n: int) -> bool:
        """A cached plan is reusable only if it was built for this layout
        and scene size (a blue/green swap can change the edge impl)."""
        return (plan.n_nodes == n and plan.edge_block == self.edge_block
                and plan.tile_nodes == self.tile_nodes)

    # ---- tile batch construction ----------------------------------------
    def _tile_batch(self, plan: TilePlan, spec, loc, vel, feat, node_attr,
                    loc_mean) -> GraphBatch:
        """One tile's padded GraphBatch: own nodes at [0, n_own), halo
        senders at [tile_nodes, tile_nodes + h), node_mask OWN-ONLY so the
        tile's psum partials count each scene node exactly once."""
        nd = plan.tile_nodes + plan.halo_pad
        n_own, halo = spec.n_own, spec.halo
        d_feat = np.zeros((nd, feat.shape[1]), np.float32)
        d_loc = np.zeros((nd, 3), np.float32)
        d_vel = np.zeros((nd, 3), np.float32)
        d_feat[:n_own] = feat[spec.start:spec.stop]
        d_loc[:n_own] = loc[spec.start:spec.stop]
        d_vel[:n_own] = vel[spec.start:spec.stop]
        h = int(halo.shape[0])
        if h:
            d_feat[plan.tile_nodes:plan.tile_nodes + h] = feat[halo]
            d_loc[plan.tile_nodes:plan.tile_nodes + h] = loc[halo]
            d_vel[plan.tile_nodes:plan.tile_nodes + h] = vel[halo]
        d = {"node_feat": d_feat, "loc": d_loc, "vel": d_vel,
             "edge_index": spec.edge_index, "edge_attr": spec.edge_attr,
             "loc_mean": loc_mean}
        if node_attr is not None:
            d_attr = np.zeros((nd, node_attr.shape[1]), np.float32)
            d_attr[:n_own] = node_attr[spec.start:spec.stop]
            if h:
                d_attr[plan.tile_nodes:plan.tile_nodes + h] = node_attr[halo]
            d["node_attr"] = d_attr
        if plan.edge_block:
            batch = pad_graphs([d], max_nodes=plan.padded_nodes,
                               edge_block=plan.edge_block,
                               edges_per_block=plan.edges_per_block,
                               edge_tile=plan.edge_tile, compute_pair=False,
                               split_remote=True, remote_pad=plan.remote_pad)
        else:
            batch = pad_graphs([d], max_nodes=plan.padded_nodes,
                               max_edges=plan.edge_pad, node_bucket=1,
                               edge_bucket=1)
        own = np.zeros((1, batch.node_mask.shape[1]), np.float32)
        own[0, :n_own] = 1.0
        return batch.replace(node_mask=own)

    # ---- compiled pieces -------------------------------------------------
    def _embed_fn(self, feat_nf: int):
        from distegnn_tpu.models.common import TorchDense

        H = int(self.engine.model.hidden_nf)
        tn = self.tile_nodes

        def build():
            dense = TorchDense(H)
            return jax.jit(lambda p, f: dense.apply({"params": p}, f))

        return self.engine._compiled(("tile_embed", tn, feat_nf, H), build)

    def _layer_callable(self, plan: TilePlan):
        """The un-jitted single-tile layer fn: one EGCL layer over one
        tile's padded batch, returning (h', x', transX_partial,
        vef_partial, count). Shared verbatim by the sequential executable
        (``_layer_fn`` jits it) and the device-parallel round executable
        (serve/mesh_tiled.py pmaps it over a round of D tiles)."""
        from distegnn_tpu.models.fast_egnn import EGCLVel
        from distegnn_tpu.ops.blocked import blocked_slot_inv_deg
        from distegnn_tpu.ops.edge_pipeline import build_edge_blocks

        model = self.engine.model
        impl = self.edge_impl
        blocked_impl = str(getattr(model, "blocked_impl", "einsum"))
        gravity = (jnp.asarray(model.gravity, jnp.float32)
                   if getattr(model, "gravity", None) is not None else None)
        layer = EGCLVel(
            hidden_nf=int(model.hidden_nf),
            virtual_channels=int(model.virtual_channels),
            node_attr_nf=int(getattr(model, "node_attr_nf", 0) or 0),
            edge_attr_nf=int(getattr(model, "edge_attr_nf", 0) or 0),
            residual=bool(getattr(model, "residual", True)),
            attention=bool(getattr(model, "attention", False)),
            normalize=bool(getattr(model, "normalize", False)),
            tanh=bool(getattr(model, "tanh", False)),
            has_gravity=gravity is not None,
            axis_name=None, tensor_axis=None,
            compute_dtype=getattr(model, "compute_dtype", None),
            hoist_edge_mlp=bool(getattr(model, "hoist_edge_mlp", True)),
            seg_impl=str(getattr(model, "segment_impl", "scatter")),
            fuse_agg=bool(getattr(model, "fuse_agg", True)),
            agg_dtype=getattr(model, "agg_dtype", None),
            edge_impl=impl)

        def fn(gcl_params, h, x, batch, X, Hv, cm):
            slot, inv_deg, oh = blocked_slot_inv_deg(batch, blocked_impl)
            fused_arrs = None
            if impl == "fused":
                fused_arrs = jax.vmap(
                    lambda r, c, ea, em: build_edge_blocks(
                        r, c, ea, em, block=batch.edge_block,
                        n_nodes=batch.max_nodes)
                )(batch.row, batch.col, batch.edge_attr, batch.edge_mask)
            return layer.apply(
                {"params": gcl_params}, h, x, batch.vel, X, Hv, batch,
                gravity=gravity, slot=slot, inv_deg=inv_deg, oh=oh,
                fused_arrs=fused_arrs, tile_coord_mean=cm,
                tile_partials=True)

        return fn

    def _layer_fn(self, plan: TilePlan):
        """THE sequential tile executable: one EGCL layer over one tile.
        Keyed on the plan's shape rung + the model's layer config — every
        tile of every layer of every scene on the same rung shares this one
        program (the round executable extends this key with D)."""
        model = self.engine.model
        key = ("tile_layer",) + plan.shape_key + (
            self.edge_impl, int(model.hidden_nf),
            int(model.virtual_channels))
        return self.engine._compiled(
            key, lambda: jax.jit(self._layer_callable(plan)))

    def _virtual_fn(self):
        from distegnn_tpu.models.fast_egnn import tiled_virtual_update

        model = self.engine.model
        residual = bool(getattr(model, "residual", True))
        cdt = getattr(model, "compute_dtype", None)

        def build():
            return jax.jit(lambda p, Hv, X, tx, vf, c: tiled_virtual_update(
                p, Hv, X, tx, vf, c, residual=residual, compute_dtype=cdt))

        key = ("tile_virtual", int(model.hidden_nf),
               int(model.virtual_channels))
        return self.engine._compiled(key, build)

    # ---- execution -------------------------------------------------------
    def predict(self, graph: dict, *, plan: Optional[TilePlan] = None,
                request_id: Optional[str] = None,
                progress: Optional[Callable[..., Optional[bool]]] = None,
                ) -> dict:
        """Serve one giant scene. Returns a dict with the UNPADDED predicted
        positions (original node order) plus the tiling stats the BENCH leg
        and the NDJSON progress stream report.

        ``progress(layer=..., tile=..., n_layers=..., n_tiles=...)`` is
        called after each tile completes; returning False cancels the
        remaining compute at the next tile boundary (the streamed-rollout
        disconnect contract, applied to tiles).
        """
        engine = self.engine
        model = engine.model
        n = int(graph["loc"].shape[0])
        self.check_admit(n)
        t0 = time.perf_counter()
        if plan is None or not self._plan_ok(plan, n):
            plan = self.plan(graph)
        L = int(getattr(model, "n_layers", 1) or 1)
        T = plan.n_tiles
        H = int(model.hidden_nf)
        C = int(model.virtual_channels)
        params = engine.params["params"]
        gcls = [params[f"gcl_{i}"] for i in range(L)]

        # scene arrays in Morton order (plan.perm[new] = old)
        p = plan.perm
        loc = np.ascontiguousarray(np.asarray(graph["loc"], np.float32)[p])
        vel = np.ascontiguousarray(np.asarray(graph["vel"], np.float32)[p])
        feat = np.ascontiguousarray(
            np.asarray(graph["node_feat"], np.float32)[p])
        na = graph.get("node_attr")
        node_attr = (np.ascontiguousarray(np.asarray(na, np.float32)[p])
                     if na is not None and np.asarray(na).size else None)
        loc_mean = np.asarray(graph["loc"], np.float32).mean(axis=0)[None]

        with obs.span("serve/tiled", n=n, tiles=T, layers=L,
                      padded_nodes=plan.padded_nodes,
                      halo_fraction=round(plan.halo_fraction, 4),
                      work_imbalance=round(plan.work_imbalance, 4),
                      request_id=request_id or "") as sp:
            batches = [self._tile_batch(plan, s, loc, vel, feat, node_attr,
                                        loc_mean) for s in plan.tiles]
            prep_ms = (time.perf_counter() - t0) * 1e3

            # bootstrap: h0 = embedding(node_feat) tile-by-tile (fixed shape)
            emb_fn = self._embed_fn(feat.shape[1])
            emb_p = params["embedding_in"]
            h_full = np.empty((n, H), np.float32)
            buf = np.zeros((self.tile_nodes, feat.shape[1]), np.float32)
            for s in plan.tiles:
                buf[:] = 0.0
                buf[:s.n_own] = feat[s.start:s.stop]
                h_full[s.start:s.stop] = np.asarray(emb_fn(emb_p, buf))[:s.n_own]
            x_full = loc.copy()
            X = jnp.repeat(jnp.asarray(loc_mean)[:, :, None], C, axis=2)
            Hv = jnp.asarray(params["virtual_node_feat"])          # [1, H, C]

            virt_fn = self._virtual_fn()

            # device-parallel tile rounds (serve/mesh_tiled.py): D same-
            # shape tiles at once across D devices, behind the same plan,
            # session cache, and queue/gateway contracts
            from distegnn_tpu.serve import mesh_tiled

            D = mesh_tiled.resolve_devices(self.devices, n_tiles=T)
            mesh_stats = None
            if D > 1:
                h_full, x_full, mesh_stats, cancelled = mesh_tiled.run_rounds(
                    self, plan, batches, h_full, x_full, X, Hv, gcls, L,
                    virt_fn, progress=progress, n_devices=D)
                stall_frac = mesh_stats["stall_fraction"]
                rounds = mesh_stats["rounds"]
                sp.set(stall_fraction=round(stall_frac, 4),
                       cancelled=cancelled, devices=D, rounds=rounds,
                       round_ms=round(mesh_stats["round_ms"], 3))
            else:
                h_full, x_full, stall_frac, cancelled = self._run_sequential(
                    plan, batches, h_full, x_full, X, Hv, gcls, L, T, H, C,
                    virt_fn, progress)
                rounds = T      # each sequential tile is its own round
                sp.set(stall_fraction=round(stall_frac, 4),
                       cancelled=cancelled)

        self._g_tiles.set(T)
        self._g_halo.set(round(plan.halo_fraction, 6))
        self._g_stall.set(round(stall_frac, 6))
        self._g_devices.set(D)
        if mesh_stats is not None:
            self._g_round_ms.set(round(mesh_stats["round_ms"], 3))
            self._g_halo_gather.set(round(mesh_stats["halo_gather_ms"], 3))
        out = None
        if not cancelled:
            out = np.ascontiguousarray(x_full[plan.inv_perm])
        result = {
            "prediction": out,
            "n": n,
            "tiles": T,
            "layers": L,
            "devices": D,
            "rounds": rounds,
            "padded_nodes": plan.padded_nodes,
            "halo_fraction": plan.halo_fraction,
            "work_imbalance": plan.work_imbalance,
            "stall_fraction": stall_frac,
            "prep_ms": prep_ms,
            "total_ms": (time.perf_counter() - t0) * 1e3,
            "cancelled": cancelled,
        }
        if mesh_stats is not None:
            result["round_ms"] = mesh_stats["round_ms"]
            result["halo_gather_ms"] = mesh_stats["halo_gather_ms"]
            result["round_imbalance"] = mesh_stats["round_imbalance"]
        return result

    def _run_sequential(self, plan: TilePlan, batches, h_full, x_full,
                        X, Hv, gcls, L: int, T: int, H: int, C: int,
                        virt_fn, progress):
        """The single-device tile loop: one tile at a time through the
        jitted layer executable, double-buffered H2D, per-tile progress.
        Kept verbatim from the pre-mesh executor — ``devices: 1`` and the
        D=1 mesh resolution both land here, so nothing changes for
        single-chip serving."""
        layer_fn = self._layer_fn(plan)

        def stage(t: int, h_src: np.ndarray, x_src: np.ndarray):
            """Gather tile t's layer inputs and start their H2D; returns
            device handles (transfer proceeds async under compute)."""
            s = plan.tiles[t]
            nd = batches[t].node_mask.shape[1]
            h_t = np.zeros((1, nd, H), np.float32)
            x_t = np.zeros((1, nd, 3), np.float32)
            h_t[0, :s.n_own] = h_src[s.start:s.stop]
            x_t[0, :s.n_own] = x_src[s.start:s.stop]
            hh = int(s.halo.shape[0])
            if hh:
                h_t[0, plan.tile_nodes:plan.tile_nodes + hh] = h_src[s.halo]
                x_t[0, plan.tile_nodes:plan.tile_nodes + hh] = x_src[s.halo]
            return jax.device_put((h_t, x_t, batches[t]))

        stall_s = 0.0
        cancelled = False
        t_loop = time.perf_counter()
        for li in range(L):
            # psum #1 host-side: the SCENE-global coordinate mean of the
            # layer input (a tile-local mean would be wrong)
            cm = jnp.asarray(x_full.mean(axis=0, dtype=np.float64)
                             .astype(np.float32)[None])
            h_next = np.empty_like(h_full)
            x_next = np.empty_like(x_full)
            tx_l = np.zeros((1, 3, C), np.float32)
            vf_l = np.zeros((1, C, H), np.float32)
            ct_l = np.zeros((1,), np.float32)
            staged = stage(0, h_full, x_full)
            for ti, s in enumerate(plan.tiles):
                tb = time.perf_counter()
                jax.block_until_ready(staged)   # residual un-hidden H2D
                stall_s += time.perf_counter() - tb
                h_d, x_d, b_d = staged
                out = layer_fn(gcls[li], h_d, x_d, b_d, X, Hv, cm)
                # double buffer: tile ti+1's H2D overlaps this compute.
                # Later tiles read h_full/x_full (the LAYER INPUT), never
                # h_next — that is what makes tiling exact.
                staged = (stage(ti + 1, h_full, x_full)
                          if ti + 1 < T else None)
                h_o, x_o, tx_p, vf_p, ct_p = [np.asarray(o) for o in out]
                h_next[s.start:s.stop] = h_o[0, :s.n_own]
                x_next[s.start:s.stop] = x_o[0, :s.n_own]
                tx_l += tx_p
                vf_l += vf_p
                ct_l += ct_p
                if progress is not None:
                    ok = progress(layer=li, tile=ti, n_layers=L,
                                  n_tiles=T)
                    if ok is False:
                        cancelled = True
                        break
            if cancelled:
                break
            h_full, x_full = h_next, x_next
            # close the layer's virtual state from the tile partials —
            # the scene-wide psums #2/#3, applied exactly once
            Hv, X = virt_fn(gcls[li], Hv, X, jnp.asarray(tx_l),
                            jnp.asarray(vf_l), jnp.asarray(ct_l))
        loop_s = max(time.perf_counter() - t_loop, 1e-9)
        return h_full, x_full, min(stall_s / loop_s, 1.0), cancelled
