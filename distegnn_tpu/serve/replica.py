"""Shared-nothing engine replicas behind one admission front.

A :class:`ReplicaSet` owns N (engine, RequestQueue) pairs for one model and
duck-types the single RequestQueue the transport used to hold: ``submit`` /
``submit_rollout`` / ``depth`` / ``alive`` / ``start`` / ``stop`` keep their
signatures, so every existing consumer (gateway routes, serve_bench,
``ModelRegistry.single``-based tests) works unchanged with ``replicas: 1``.

What changes with N > 1:

  - admission picks a HEALTHY replica round-robin; the caller gets an OUTER
    :class:`~distegnn_tpu.serve.queue.ServeFuture` wired to the replica's
    inner future via ``add_done_callback``
  - if the chosen replica's dispatcher dies with the request in flight
    (inner future resolves with :class:`DispatcherCrashError`), the request
    FAILS OVER to a survivor — at most once per replica, tracked in the
    record's ``tried`` set, so a poison batch that kills whoever runs it
    can't ping-pong forever
  - when no replica is available AND the set is supervised, admission raises
    :class:`ModelUnavailableError` carrying a ``retry_after_s`` hint derived
    from the earliest scheduled restart — the gateway maps it to a typed 503
    + ``Retry-After`` for THIS model only; other models keep serving
  - an unsupervised set (never ``start()``-ed, e.g. tests poking the raw
    queue) passes through to replica 0 so the queue's own admission errors
    (not-started RuntimeError, QueueFullError) surface exactly as before

Failover is AT-MOST-ONCE per delivery: in-flight records are claimed either
by the inner future's done-callback or by the supervisor's drain — never
both — via ``Replica.untrack``'s compare-and-pop, and the outer future's
first-wins resolution drops any late result from an abandoned replica.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Set

from distegnn_tpu import obs
from distegnn_tpu.serve import worker as worker_mod
from distegnn_tpu.serve.buckets import Bucket
from distegnn_tpu.serve.queue import (DispatcherCrashError, RequestQueue,
                                      ServeFuture, WorkerLostError,
                                      _request_ids)


class ModelUnavailableError(RuntimeError):
    """Every replica of one model is down (crashed/broken/restarting).

    ``retry_after_s`` is the serving hint for the gateway's ``Retry-After``
    header: time until the earliest scheduled replica restart, floored so
    clients never busy-spin.
    """

    def __init__(self, model: str, retry_after_s: float = 1.0):
        super().__init__(
            f"model '{model}' has no live replicas (all crashed, wedged, or "
            f"in breaker cooldown); retry after {retry_after_s:.1f} s")
        self.model = model
        self.retry_after_s = float(retry_after_s)


class _Tracked:
    """One admitted request: the outer future handed to the caller plus
    everything needed to re-dispatch it to a survivor."""

    __slots__ = ("kind", "payload", "bucket", "request_id", "outer", "tried",
                 "stream")

    def __init__(self, kind: str, payload: dict, bucket, request_id,
                 outer: ServeFuture, stream=None):
        self.kind = kind            # "predict" | "rollout"
        self.payload = payload
        self.bucket = bucket        # predict-only override (may be None)
        self.request_id = request_id
        self.outer = outer
        self.tried: Set[int] = set()  # replica indices that saw this request
        self.stream = stream        # StreamSink: streamed rollouts only


class Replica:
    """One engine + its current dispatcher queue, plus supervision state.

    The ENGINE is stable across restarts (its per-rung compile cache is the
    expensive part); only the RequestQueue — the crashed thread and its
    poisoned pending state — is rebuilt.

    States: ``init`` (built, not started) → ``running`` → ``backoff``
    (crashed/wedged, restart scheduled) → ``broken`` (circuit breaker open,
    long cooldown) → ``running`` again, or → ``stopped`` (clean shutdown).
    """

    backend = "thread"

    def __init__(self, idx: int, engine, queue: RequestQueue):
        self.idx = idx
        self.engine = engine
        self.queue = queue
        self.state = "init"
        self.failures = 0        # consecutive supervised failures (breaker)
        self.restarts = 0        # lifetime supervised restarts
        self.started_at = 0.0
        self.next_restart_at = 0.0
        self.last_reason: Optional[str] = None
        self._inflight: Dict[int, _Tracked] = {}
        self._lock = threading.Lock()

    def healthy(self) -> bool:
        return self.state == "running" and self.queue.alive()

    # ---- in-flight tracking (at-most-once claim protocol) ----------------
    def track(self, rec: _Tracked) -> None:
        with self._lock:
            self._inflight[id(rec)] = rec

    def untrack(self, rec: _Tracked) -> bool:
        """Claim one record; True for exactly one of the competing claimers
        (inner-future callback vs supervisor drain)."""
        with self._lock:
            return self._inflight.pop(id(rec), None) is not None

    def drain_inflight(self) -> List[_Tracked]:
        with self._lock:
            recs = list(self._inflight.values())
            self._inflight.clear()
        return recs

    def inflight_count(self) -> int:
        with self._lock:
            return len(self._inflight)

    def fresh_queue(self) -> RequestQueue:
        """Replacement RequestQueue cloned from the dead one's knobs; the
        warmed engine (and its compile cache) is reused as-is."""
        old = self.queue
        self.queue = RequestQueue(
            self.engine,
            batch_deadline_ms=old.batch_deadline * 1e3,
            queue_capacity=old._ingress.maxsize,
            request_timeout_ms=old.request_timeout * 1e3,
            result_margin_s=old.result_margin,
            metrics=old.metrics)
        return self.queue

    # ---- backend lifecycle (WorkerReplica overrides) ---------------------
    def start_queue(self) -> None:
        """Start the current queue (ReplicaSet.start / supervisor restart).
        WorkerReplica's override spawns the child and degrades to an
        in-process queue on spawn failure."""
        self.queue.start()

    def restart_queue(self) -> None:
        """Supervisor restart: fresh queue (fresh worker for the process
        backend), then start it."""
        self.fresh_queue()
        self.start_queue()

    def warmup(self, sizes) -> List[Bucket]:
        """Warm this replica's EXECUTOR — the local engine here, the worker
        child for the process backend."""
        return self.engine.warmup(sizes)

    def swap_params(self, checkpoint: str, new_params, rungs) -> int:
        """Blue/green unit, one replica: canary CANDIDATE params on this
        replica's executor, then flip atomically. Returns rungs checked;
        raises (CanaryError, ...) without flipping on failure."""
        checked = self.engine.canary(new_params, rungs)
        self.engine.params = new_params
        return checked

    def swap_rollback(self, old_params) -> None:
        """Undo a flip this swap already applied to this replica."""
        self.engine.params = old_params

    def backend_detail(self) -> dict:
        """Extra per-replica health fields (pid/heartbeat/degraded for the
        process backend; empty for threads)."""
        return {}


def _obs_run_dir() -> Optional[str]:
    """Directory of the live obs sink (``<run>/obs``) — worker children put
    their stderr logs and per-process event files next to the parent's
    events.jsonl. None when tracing is off (worker stderr then lands in a
    tempdir so it is never lost)."""
    try:
        from distegnn_tpu.obs.trace import get_tracer

        w = get_tracer().writer
        if w is not None and getattr(w, "path", None):
            return os.path.dirname(os.path.abspath(str(w.path)))
    except Exception:
        pass
    return None


class WorkerQueue(RequestQueue):
    """RequestQueue whose micro-batches execute in an out-of-process worker
    child over the checksummed IPC channel (serve/worker.py).

    Inherits ALL of the parent-side machinery — bounded ingress, per-bucket
    coalescing, deadlines, poison retry, kill/wedge chaos, crash budget —
    and overrides only the batch-execution hop: ``_run_batch`` becomes one
    framed call with a hard deadline, and a dead channel surfaces as
    :class:`~distegnn_tpu.serve.queue.WorkerLostError` so the dispatcher
    poisons itself and the replica layer fails the work over. The
    parent-side ``engine`` stays the model's reference handle (ladder math,
    prep cache, params for digest/fallback); it never executes this queue's
    traffic.
    """

    backend = "process"

    def __init__(self, engine, *, spawn_fn, model: str = "default",
                 idx: int = 0, kill_grace_s: float = 3.0, **queue_kw):
        super().__init__(engine, **queue_kw)
        self._spawn_fn = spawn_fn  # () -> WorkerHandle; may raise WorkerSpawnError
        self.model = model
        self.idx = idx
        self.kill_grace_s = float(kill_grace_s)
        self.worker: Optional[worker_mod.WorkerHandle] = None

    def start(self):
        if self.worker is None:
            self.worker = self._spawn_fn()  # WorkerSpawnError propagates
        return super().start()

    def alive(self) -> bool:
        w = self.worker
        return (super().alive() and w is not None
                and w.lost_reason is None and w.proc_alive())

    def heartbeat_age(self) -> Optional[float]:
        """Seconds since the child's last frame (the supervisor's staleness
        wedge signal); None before the worker exists."""
        w = self.worker
        return None if w is None else w.heartbeat_age()

    @property
    def pid(self) -> Optional[int]:
        w = self.worker
        return None if w is None else w.pid

    def _run_batch(self, key, reqs) -> List:
        kind, bucket, _steps = key
        if kind == "rollout_stream":
            # the IPC channel is one framed call per batch — there is no
            # chunk conduit to a child. The ReplicaSet routes streams to
            # thread replicas; this is the typed backstop for direct callers.
            exc = RuntimeError(
                f"streamed rollouts are not supported over the "
                f"process-worker IPC channel ({self.model}/{self.idx}); "
                f"route to a thread-backend replica")
            for r in reqs:
                if r.stream is not None:
                    r.stream.fail(exc)
                r.future.set_exception(exc)
            return [{"error": "stream-unsupported"}] * len(reqs)
        w = self.worker
        if w is None:
            raise WorkerLostError(
                f"worker {self.model}/{self.idx} never spawned")
        rids = _request_ids(reqs)
        timeout = self.request_timeout + self.result_margin
        try:
            if kind == "rollout":
                return w.call("rollout",
                              {"scenes": [r.graph for r in reqs],
                               "request_ids": rids}, timeout_s=timeout)
            return w.call("predict",
                          {"graphs": [r.graph for r in reqs],
                           "bucket": list(bucket) if bucket else None,
                           "request_ids": rids}, timeout_s=timeout)
        except (worker_mod.WorkerClosedError,
                worker_mod.WorkerTimeoutError) as exc:
            raise WorkerLostError(
                f"worker {self.model}/{self.idx} (pid {self.pid}) lost "
                f"mid-batch: {exc}") from exc

    def kill(self, reason: str = "killed") -> None:
        super().kill(reason)
        self.ensure_worker_dead()

    def ensure_worker_dead(self) -> None:
        """SIGTERM → SIGKILL the child and reap the zombie (idempotent)."""
        w = self.worker
        if w is not None:
            w.terminate(grace_s=self.kill_grace_s)

    def stop(self, drain: bool = True, join_timeout_s: float = 30.0) -> None:
        super().stop(drain=drain, join_timeout_s=join_timeout_s)
        w = self.worker
        if w is not None and w.lost_reason is None and w.proc_alive():
            try:
                # polite shutdown flushes the child's obs buffers
                w.call("shutdown", timeout_s=min(float(join_timeout_s), 5.0))
            except worker_mod.WorkerError:
                pass
        self.ensure_worker_dead()


class WorkerReplica(Replica):
    """Replica whose dispatcher queue executes in a worker child process
    (``serve.workers: process``).

    The ``engine`` attribute stays the PARENT-side reference handle: it
    holds the canonical params (digest source for the spawn handshake,
    fallback source for degradation) and the shared prep/session caches,
    but never runs this replica's traffic. In-flight tracking lives in the
    base class — in the parent — which is what makes at-most-once failover
    survive a SIGKILL'd child.

    Degradation: a spawn failure (exec error, init crash, digest mismatch)
    falls back to a fresh in-process queue with a ``gateway/worker_degraded``
    event — the model keeps serving without isolation, and the next
    supervised restart attempts a real worker again.
    """

    backend = "process"

    def __init__(self, idx: int, engine, *, model: str, queue_kw: dict,
                 worker_opts: dict, cfg_dict: dict, fallback_factory,
                 checkpoint: Optional[str] = None):
        super().__init__(idx, engine, None)
        self.model_name = model
        self.degraded = False
        self.current_checkpoint = checkpoint  # tracks swaps for respawn
        self.warm_sizes: List = []
        self._queue_kw = dict(queue_kw)
        self._worker_opts = dict(worker_opts or {})
        self._cfg_dict = cfg_dict
        self._fallback_factory = fallback_factory
        self._spawn_fail_next = 0  # chaos: forced spawn failures
        self._swap_prev_ckpt: Optional[str] = None
        # orders deferred-swap bookkeeping against the post-spawn catch-up
        # check in start_queue (a swap can defer WHILE a respawn is in
        # flight; whichever side runs second must see the other's write)
        self._ckpt_lock = threading.Lock()
        self.queue = self._make_worker_queue()

    # ---- spawn -----------------------------------------------------------
    def _make_worker_queue(self) -> WorkerQueue:
        return WorkerQueue(
            self.engine, spawn_fn=self._spawn_worker, model=self.model_name,
            idx=self.idx,
            kill_grace_s=float(self._worker_opts.get("kill_grace_s", 3.0)),
            **self._queue_kw)

    def _spawn_worker(self) -> worker_mod.WorkerHandle:
        if self._spawn_fail_next > 0:
            self._spawn_fail_next -= 1
            raise worker_mod.WorkerSpawnError(
                f"injected spawn failure (chaos) for "
                f"{self.model_name}/{self.idx}")
        opts = self._worker_opts
        return worker_mod.WorkerHandle.spawn(
            self._cfg_dict, self.model_name, self.idx,
            checkpoint=self.current_checkpoint,
            warm_sizes=list(self.warm_sizes),
            obs_dir=_obs_run_dir(),
            spawn_timeout_s=float(opts.get("spawn_timeout_s", 120.0)),
            heartbeat_s=float(opts.get("heartbeat_s", 0.5)),
            kill_grace_s=float(opts.get("kill_grace_s", 3.0)),
            expect_digest=self.engine.params_digest(),
            matmul_precision=worker_mod.current_matmul_precision())

    def fail_next_spawns(self, n: int = 1) -> None:
        """Chaos hook (testing/serve_faults.py): the next ``n`` spawn
        attempts raise WorkerSpawnError, exercising degradation."""
        self._spawn_fail_next = int(n)

    # ---- lifecycle -------------------------------------------------------
    def start_queue(self) -> None:
        try:
            self.queue.start()
            if isinstance(self.queue, WorkerQueue):
                self.degraded = False
                self._catch_up_checkpoint()
        except worker_mod.WorkerSpawnError as exc:
            obs.event("gateway/worker_degraded", model=self.model_name,
                      replica=self.idx, error=str(exc)[:300])
            if isinstance(self.queue, WorkerQueue):
                # a failed catch-up swap leaves a RUNNING queue over a
                # stale child: poison the dispatcher and kill the child
                self.queue.kill(reason="stale-checkpoint catch-up failed")
            _eng, q = self._fallback_factory()
            self.queue = q
            self.queue.start()
            self.degraded = True

    def _catch_up_checkpoint(self) -> None:
        """Close the in-flight-spawn swap window: a spawn takes seconds
        (child jax import), and a hot-swap that arrives in that window
        defers — but the child already captured the PRE-swap checkpoint,
        so without this it would come up serving stale params and the
        deferral would never reach it. Compare what the child actually
        loaded against ``current_checkpoint`` under the same lock the
        deferred branch writes it, and swap the fresh worker over IPC if
        they diverge. A failure here is a spawn failure (the child is
        unusable on the wrong version) → WorkerSpawnError → degradation,
        whose fallback serves the parent handle's post-swap params."""
        w = self.queue.worker
        if w is None:
            return
        with self._ckpt_lock:
            want = self.current_checkpoint
            if not want or getattr(w, "checkpoint", None) == want:
                return
            try:
                w.call("swap", {"checkpoint": want, "rungs": []},
                       timeout_s=float(
                           self._worker_opts.get("spawn_timeout_s", 120.0)))
            except worker_mod.WorkerError as exc:
                raise worker_mod.WorkerSpawnError(
                    f"worker {self.model_name}/{self.idx} spawned on a "
                    f"stale checkpoint and the catch-up swap to {want!r} "
                    f"failed: {exc}") from exc
            w.checkpoint = want
            obs.event("gateway/swap_catchup", model=self.model_name,
                      replica=self.idx, path=want)

    def reconcile_checkpoint(self) -> None:
        """Supervisor-tick safety net for the last swap/respawn race window
        (a deferral landing between the post-spawn catch-up check and the
        replica being marked up): if a healthy worker is serving a version
        other than ``current_checkpoint``, catch it up now; if that fails,
        kill the queue so the normal restart path reloads the right
        version. Normal ticks cost one attribute compare."""
        if self.degraded or not isinstance(self.queue, WorkerQueue):
            return
        w = self.queue.worker
        if (w is None or not self.current_checkpoint
                or getattr(w, "checkpoint", None) == self.current_checkpoint):
            return
        try:
            self._catch_up_checkpoint()
        except worker_mod.WorkerSpawnError:
            self.queue.kill(reason="checkpoint reconcile failed")

    def fresh_queue(self) -> WorkerQueue:
        old = self.queue
        if isinstance(old, WorkerQueue):
            old.ensure_worker_dead()
        # ALWAYS retry the worker backend, even off a degraded fallback:
        # degradation is temporary by construction
        self.queue = self._make_worker_queue()
        return self.queue

    def warmup(self, sizes) -> List[Bucket]:
        self.warm_sizes = [tuple(s) for s in sizes]
        if not isinstance(self.queue, WorkerQueue):
            return self.queue.engine.warmup(sizes)
        w = self.queue.worker
        if w is None:
            raise RuntimeError(
                f"worker {self.model_name}/{self.idx} not spawned — start "
                f"the replica set before warmup")
        rungs = w.call(
            "warmup", {"sizes": [list(s) for s in self.warm_sizes]},
            timeout_s=float(self._worker_opts.get("spawn_timeout_s", 120.0)))
        return [Bucket(*r) for r in rungs]

    # ---- blue/green ------------------------------------------------------
    def swap_params(self, checkpoint: str, new_params, rungs) -> int:
        self._swap_prev_ckpt = self.current_checkpoint
        if not isinstance(self.queue, WorkerQueue):
            # degraded fallback executes in-process: flip its engine
            checked = self.queue.engine.canary(new_params, rungs)
            self.queue.engine.params = new_params
            self.current_checkpoint = str(checkpoint)
            return checked
        w = self.queue.worker
        if w is None or not self.healthy():
            # down / mid-restart: adopt the new version at the next respawn
            # instead of failing the whole swap. Under _ckpt_lock so a
            # respawn already past its catch-up check can't miss this write
            # (the catch-up re-reads current_checkpoint under the same lock).
            with self._ckpt_lock:
                self.current_checkpoint = str(checkpoint)
            obs.event("gateway/swap_deferred", model=self.model_name,
                      replica=self.idx, path=str(checkpoint))
            return 0
        res = w.call(
            "swap", {"checkpoint": str(checkpoint),
                     "rungs": [[b.n, b.e] for b in rungs]},
            timeout_s=float(self._worker_opts.get("spawn_timeout_s", 120.0)))
        self.current_checkpoint = str(checkpoint)
        return int(res.get("rungs", 0))

    def swap_rollback(self, old_params) -> None:
        self.current_checkpoint = self._swap_prev_ckpt
        if not isinstance(self.queue, WorkerQueue):
            self.queue.engine.params = old_params
            return
        w = self.queue.worker
        if w is not None and self.healthy():
            try:
                w.call("swap_rollback", timeout_s=30.0)
            except worker_mod.WorkerError:
                pass  # child is dying; its respawn loads _swap_prev_ckpt

    # ---- health ----------------------------------------------------------
    def backend_detail(self) -> dict:
        q = self.queue
        if self.degraded or not isinstance(q, WorkerQueue):
            return {"backend": "thread", "degraded": self.degraded,
                    "pid": None, "heartbeat_age_s": None}
        age = q.heartbeat_age()
        return {"backend": "process", "degraded": False, "pid": q.pid,
                "heartbeat_age_s": None if age is None else round(age, 3)}


class ReplicaSet:
    """N shared-nothing replicas of one model behind one admission front.

    Duck-types RequestQueue for the transport/registry (submit,
    submit_rollout, depth, alive, start, stop), adds the failover and
    health surface, and owns the :class:`ReplicaSupervisor`.
    """

    def __init__(self, model: str, pairs, *, supervisor_opts: Optional[dict] = None):
        if not pairs:
            raise ValueError("ReplicaSet needs at least one (engine, queue)")
        self.model = model
        # members are (engine, queue) pairs or pre-built Replica objects
        # (the registry hands in WorkerReplicas for the process backend)
        self.replicas: List[Replica] = []
        for i, item in enumerate(pairs):
            if isinstance(item, Replica):
                item.idx = i
                self.replicas.append(item)
            else:
                eng, q = item
                self.replicas.append(Replica(i, eng, q))
        self.metrics = self.replicas[0].queue.metrics
        self.request_timeout = self.replicas[0].queue.request_timeout
        self.result_margin = self.replicas[0].queue.result_margin
        self._rr = 0
        self._lock = threading.Lock()
        self._supervised = False
        # replica indices pinned OUT of live round-robin (the promotion
        # conveyor's canary slice): still supervised, still restartable,
        # but _choose never routes live traffic to them — shadow traffic is
        # submitted straight to the replica's own queue
        self._quarantined: Set[int] = set()
        # monotonic index source for replicas added LIVE (autoscaler
        # scale-up): indices are never renumbered or reused, so per-replica
        # gauges and health rows keyed on idx can't alias across a
        # grow/shrink cycle
        self._next_idx = len(self.replicas)
        from distegnn_tpu.serve.supervisor import ReplicaSupervisor
        self.supervisor = ReplicaSupervisor(self, **(supervisor_opts or {}))

    # ---- RequestQueue-compatible surface ---------------------------------
    @property
    def engine(self):
        """Primary replica's engine — the registry's width/session-cache/
        capability handle (stable across restarts)."""
        return self.replicas[0].engine

    @property
    def ladder(self):
        return self.replicas[0].engine.ladder

    def start(self) -> "ReplicaSet":
        now = time.perf_counter()
        for r in self.replicas:
            r.start_queue()
            r.state = "running"
            r.started_at = now
        self._supervised = True
        self.supervisor.start()
        return self

    def begin_stop(self) -> None:
        """Phase 1 of shutdown: drop the supervised flag and stop the
        supervisor BEFORE any queue drains, so an in-flight restart can
        never revive a queue (or spawn a worker) after drain has begun —
        the supervisor's _restart rechecks ``_supervised`` after its
        blocking claim and aborts. Idempotent; ModelRegistry.stop calls it
        for EVERY model before draining any of them."""
        self._supervised = False
        self.supervisor.stop()

    def stop(self, drain: bool = True, join_timeout_s: float = 30.0) -> None:
        self.begin_stop()
        for r in self.replicas:
            r.queue.stop(drain=drain, join_timeout_s=join_timeout_s)
            r.state = "stopped"

    def alive(self) -> bool:
        return any(r.queue.alive() for r in self.replicas)

    def depth(self) -> int:
        return sum(r.queue.depth() for r in self.replicas)

    def submit(self, graph: dict, bucket=None,
               request_id: Optional[str] = None) -> ServeFuture:
        return self._admit("predict", graph, bucket, request_id)

    def submit_rollout(self, scene: dict,
                       request_id: Optional[str] = None,
                       stream=None) -> ServeFuture:
        return self._admit("rollout", scene, None, request_id, stream=stream)

    def submit_tiled(self, graph: dict,
                     request_id: Optional[str] = None,
                     stream=None) -> ServeFuture:
        """Above-ladder predict through the tiled executor. Runs only on
        in-process replicas (the host-side halo exchange loop can't cross
        the worker IPC channel). ``serve.tiled.devices`` > 1 parallelizes
        WITHIN one request (device-parallel tile rounds, serve/
        mesh_tiled.py) — orthogonal to replica-level parallelism across
        requests, which keeps giant scenes on dedicated engines."""
        return self._admit("tiled", graph, None, request_id, stream=stream)

    # ---- elastic membership (autoscaler surface) -------------------------
    def add_replica(self, build_fn, warm_sizes=None) -> Replica:
        """Grow the set LIVE by one replica built by ``build_fn(idx) ->
        Replica`` (the registry's per-model factory). The new replica gets a
        fresh monotonic index, is started AND warmed at ``warm_sizes``
        BEFORE it becomes visible (so admission never picks a half-built
        member, and a mid-spike scale-up never routes live traffic into a
        compile storm) — warmup failure is non-fatal, the replica just
        compiles lazily on first traffic. Because the supervisor's tick
        iterates the live list, the new member is supervised from its next
        tick with no extra wiring. Raises whatever the factory or queue
        start raises; nothing is appended on failure."""
        with self._lock:
            idx = self._next_idx
            self._next_idx += 1
        replica = build_fn(idx)
        replica.idx = idx
        replica.start_queue()
        if warm_sizes:
            try:
                replica.warmup(warm_sizes)
            except Exception as exc:
                obs.log(f"replica {idx}: pre-visibility warmup failed "
                        f"({exc!r}); compiling lazily on first traffic")
        replica.state = "running"
        replica.started_at = time.perf_counter()
        with self._lock:
            self.replicas.append(replica)
        return replica

    def retire_replica(self, drain_timeout_s: float = 30.0
                       ) -> Optional[Replica]:
        """Shrink the set LIVE by one replica, preserving at-most-once: the
        victim (the newest running replica; replica 0 — the registry's
        engine handle — is never retired) first stops being choosable
        (state ``retiring`` fails ``healthy()``), then its in-flight set
        and queue depth drain (bounded by ``drain_timeout_s``), then its
        queue stops with drain and the replica leaves the list. Returns
        the retired replica, or None when only one running replica
        remains."""
        with self._lock:
            running = [r for r in self.replicas if r.state == "running"
                       and r.idx not in self._quarantined]
            if len(running) <= 1:
                return None
            victim = running[-1]
            if victim is self.replicas[0]:
                return None
            victim.state = "retiring"
        deadline = time.perf_counter() + float(drain_timeout_s)
        while time.perf_counter() < deadline:
            if victim.inflight_count() == 0 and victim.queue.depth() == 0:
                break
            time.sleep(0.01)
        # claim whatever the drain window could not flush (a wedged
        # dispatcher) BEFORE stopping the queue — the supervisor's ordering:
        # stop would fail the stragglers' inner futures, and the done
        # callback passes a non-crash error straight to the client; a claim
        # is compare-and-pop, so a result that races in still wins exactly
        # once
        self.fail_over_replica(victim, reason="retired with work in flight")
        victim.queue.stop(drain=True, join_timeout_s=float(drain_timeout_s))
        victim.state = "stopped"
        with self._lock:
            if victim in self.replicas:
                self.replicas.remove(victim)
        return victim

    # ---- canary quarantine (promotion conveyor surface) ------------------
    def quarantine(self, idx: int) -> bool:
        """Pin replica ``idx`` out of live round-robin (the promotion
        canary slice). Refused (returns False) when it would leave no other
        healthy live replica — a single-replica fleet has no slice to
        spare. Idempotent; the replica stays supervised throughout."""
        with self._lock:
            target = next((r for r in self.replicas if r.idx == idx), None)
            if target is None:
                return False
            others = [r for r in self.replicas
                      if r.idx != idx and r.idx not in self._quarantined
                      and r.healthy()]
            if not others:
                return False
            self._quarantined.add(idx)
            return True

    def release(self, idx: int) -> None:
        """Return a quarantined replica to live rotation. Idempotent."""
        with self._lock:
            self._quarantined.discard(idx)

    def quarantined(self) -> Set[int]:
        with self._lock:
            return set(self._quarantined)

    def supports_streaming(self) -> bool:
        """True when some member executes in-process (a plain RequestQueue)
        — the chunk conduit can't cross the worker IPC channel, so the
        gateway falls back to buffered rollouts when this is False."""
        with self._lock:
            return any(not isinstance(r.queue, WorkerQueue)
                       for r in self.replicas)

    # ---- dispatch / failover ---------------------------------------------
    def _admit(self, kind: str, payload: dict, bucket, request_id,
               stream=None) -> ServeFuture:
        now = time.perf_counter()
        factor = 1.0
        if kind == "tiled":
            # a tiled predict runs L x n_tiles fixed-shape invocations; its
            # inner deadline is scaled by serve.tiled.timeout_factor, so the
            # outer safety net must stretch by the same factor
            tiled = getattr(self.replicas[0].engine, "tiled", None)
            factor = max(float(getattr(tiled, "timeout_factor", 1.0) or 1.0),
                         1.0)
        outer = ServeFuture(
            hard_deadline=now + (self.request_timeout + self.result_margin)
            * factor)
        rec = _Tracked(kind, payload, bucket, request_id, outer,
                       stream=stream)
        self._dispatch(rec, admission=True)
        return outer

    def _choose(self, exclude: Set[int],
                thread_only: bool = False) -> Optional[Replica]:
        with self._lock:
            cands = [r for r in self.replicas
                     if r.idx not in exclude and r.healthy()
                     and r.idx not in self._quarantined
                     and not (thread_only and isinstance(r.queue,
                                                         WorkerQueue))]
            if not cands:
                return None
            self._rr += 1
            return cands[self._rr % len(cands)]

    def _dispatch(self, rec: _Tracked, admission: bool) -> None:
        # streams need an in-process executor: the chunk conduit can't
        # cross the worker IPC channel; tiled predicts likewise — the halo
        # exchange loop lives on the gateway host
        replica = self._choose(rec.tried,
                               thread_only=(rec.stream is not None
                                            or rec.kind == "tiled"))
        if replica is None:
            if not self._supervised and not rec.tried:
                # legacy pass-through: an unstarted/unsupervised set surfaces
                # replica 0's own admission errors (RuntimeError not-started,
                # QueueFullError) exactly as the single-queue gateway did
                replica = self.replicas[0]
            else:
                exc = ModelUnavailableError(self.model,
                                            retry_after_s=self.retry_after_s())
                if admission:
                    raise exc
                rec.outer.set_exception(exc)
                if rec.stream is not None:
                    rec.stream.fail(exc)
                return
        rec.tried.add(replica.idx)
        try:
            if rec.kind == "rollout":
                inner = replica.queue.submit_rollout(
                    rec.payload, request_id=rec.request_id,
                    stream=rec.stream)
            elif rec.kind == "tiled":
                inner = replica.queue.submit_tiled(
                    rec.payload, request_id=rec.request_id,
                    stream=rec.stream)
            else:
                inner = replica.queue.submit(
                    rec.payload, bucket=rec.bucket, request_id=rec.request_id)
        except Exception:
            if admission:
                raise  # typed 4xx/5xx mapping happens at the gateway
            # survivor couldn't admit (full / just died): try the next one;
            # recursion is bounded by the growing tried set
            self._dispatch(rec, admission=False)
            return
        replica.track(rec)
        inner.add_done_callback(
            lambda fut, rec=rec, rep=replica: self._on_inner_done(rec, rep, fut))

    def _on_inner_done(self, rec: _Tracked, replica: Replica,
                       inner: ServeFuture) -> None:
        if not replica.untrack(rec):
            return  # supervisor already claimed it (drained for failover)
        exc = inner.exception()
        if isinstance(exc, DispatcherCrashError) and rec.stream is None:
            # streams are deliberately NOT failed over: the client may have
            # already consumed a chunk prefix, and a re-dispatch would
            # replay it from step 0 — the sink carries the typed error and
            # the client retries the whole request instead
            self._fail_over(rec, replica, reason=str(exc))
            return
        rec.outer.meta.update(inner.meta)
        rec.outer.meta["replica"] = replica.idx
        if exc is not None:
            rec.outer.set_exception(exc)
        else:
            rec.outer.set_result(inner._result)

    def _fail_over(self, rec: _Tracked, dead: Replica, reason: str) -> None:
        if rec.stream is not None:
            # no stream failover (see _on_inner_done): surface the typed
            # error on both the future and the sink so the consumer ends
            exc = DispatcherCrashError(
                f"streamed rollout lost its replica ({reason[:160]}); "
                f"streams are not failed over — retry the request")
            rec.outer.set_exception(exc)
            rec.stream.fail(exc)
            return
        self.metrics.failed_over()
        obs.event("gateway/replica_failover", model=self.model,
                  replica=dead.idx, request_id=rec.request_id,
                  tried=sorted(rec.tried), reason=reason[:160])
        self._dispatch(rec, admission=False)

    def fail_over_replica(self, replica: Replica, reason: str) -> int:
        """Supervisor entry point: claim and re-dispatch everything in
        flight on a dead/wedged replica. Returns how many moved."""
        recs = replica.drain_inflight()
        for rec in recs:
            self._fail_over(rec, replica, reason=reason)
        return len(recs)

    # ---- health / hints ---------------------------------------------------
    def available(self) -> int:
        with self._lock:
            return sum(1 for r in self.replicas
                       if r.healthy() and r.idx not in self._quarantined)

    def health(self) -> List[dict]:
        rows = []
        for r in self.replicas:
            row = {"replica": r.idx, "state": r.state,
                   "alive": r.queue.alive(), "failures": r.failures,
                   "restarts": r.restarts, "inflight": r.inflight_count(),
                   "depth": r.queue.depth(), "last_reason": r.last_reason,
                   "quarantined": r.idx in self._quarantined,
                   "backend": r.backend}
            row.update(r.backend_detail())  # may downgrade backend: degraded
            rows.append(row)
        return rows

    def retry_after_s(self) -> float:
        """Hint for 503 Retry-After: time to the earliest scheduled replica
        restart (floored at 0.1 s so clients never busy-spin)."""
        now = time.perf_counter()
        waits = [r.next_restart_at - now for r in self.replicas
                 if r.state in ("backoff", "broken")]
        if not waits:
            return 1.0
        return round(max(min(waits), 0.1), 3)

    def queue_retry_after_s(self) -> float:
        """Hint for 429 Retry-After: roughly how long the current backlog
        takes to drain (one batch deadline per max_batch queued requests),
        clamped to [0.1, 5] s."""
        per_batch = max(self.replicas[0].queue.batch_deadline, 0.01)
        max_batch = max(int(getattr(self.engine, "max_batch", 1)), 1)
        est = per_batch * (1.0 + self.depth() / max_batch)
        return round(min(max(est, 0.1), 5.0), 3)
