"""Shared-nothing engine replicas behind one admission front.

A :class:`ReplicaSet` owns N (engine, RequestQueue) pairs for one model and
duck-types the single RequestQueue the transport used to hold: ``submit`` /
``submit_rollout`` / ``depth`` / ``alive`` / ``start`` / ``stop`` keep their
signatures, so every existing consumer (gateway routes, serve_bench,
``ModelRegistry.single``-based tests) works unchanged with ``replicas: 1``.

What changes with N > 1:

  - admission picks a HEALTHY replica round-robin; the caller gets an OUTER
    :class:`~distegnn_tpu.serve.queue.ServeFuture` wired to the replica's
    inner future via ``add_done_callback``
  - if the chosen replica's dispatcher dies with the request in flight
    (inner future resolves with :class:`DispatcherCrashError`), the request
    FAILS OVER to a survivor — at most once per replica, tracked in the
    record's ``tried`` set, so a poison batch that kills whoever runs it
    can't ping-pong forever
  - when no replica is available AND the set is supervised, admission raises
    :class:`ModelUnavailableError` carrying a ``retry_after_s`` hint derived
    from the earliest scheduled restart — the gateway maps it to a typed 503
    + ``Retry-After`` for THIS model only; other models keep serving
  - an unsupervised set (never ``start()``-ed, e.g. tests poking the raw
    queue) passes through to replica 0 so the queue's own admission errors
    (not-started RuntimeError, QueueFullError) surface exactly as before

Failover is AT-MOST-ONCE per delivery: in-flight records are claimed either
by the inner future's done-callback or by the supervisor's drain — never
both — via ``Replica.untrack``'s compare-and-pop, and the outer future's
first-wins resolution drops any late result from an abandoned replica.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set

from distegnn_tpu import obs
from distegnn_tpu.serve.queue import (DispatcherCrashError, RequestQueue,
                                      ServeFuture)


class ModelUnavailableError(RuntimeError):
    """Every replica of one model is down (crashed/broken/restarting).

    ``retry_after_s`` is the serving hint for the gateway's ``Retry-After``
    header: time until the earliest scheduled replica restart, floored so
    clients never busy-spin.
    """

    def __init__(self, model: str, retry_after_s: float = 1.0):
        super().__init__(
            f"model '{model}' has no live replicas (all crashed, wedged, or "
            f"in breaker cooldown); retry after {retry_after_s:.1f} s")
        self.model = model
        self.retry_after_s = float(retry_after_s)


class _Tracked:
    """One admitted request: the outer future handed to the caller plus
    everything needed to re-dispatch it to a survivor."""

    __slots__ = ("kind", "payload", "bucket", "request_id", "outer", "tried")

    def __init__(self, kind: str, payload: dict, bucket, request_id,
                 outer: ServeFuture):
        self.kind = kind            # "predict" | "rollout"
        self.payload = payload
        self.bucket = bucket        # predict-only override (may be None)
        self.request_id = request_id
        self.outer = outer
        self.tried: Set[int] = set()  # replica indices that saw this request


class Replica:
    """One engine + its current dispatcher queue, plus supervision state.

    The ENGINE is stable across restarts (its per-rung compile cache is the
    expensive part); only the RequestQueue — the crashed thread and its
    poisoned pending state — is rebuilt.

    States: ``init`` (built, not started) → ``running`` → ``backoff``
    (crashed/wedged, restart scheduled) → ``broken`` (circuit breaker open,
    long cooldown) → ``running`` again, or → ``stopped`` (clean shutdown).
    """

    def __init__(self, idx: int, engine, queue: RequestQueue):
        self.idx = idx
        self.engine = engine
        self.queue = queue
        self.state = "init"
        self.failures = 0        # consecutive supervised failures (breaker)
        self.restarts = 0        # lifetime supervised restarts
        self.started_at = 0.0
        self.next_restart_at = 0.0
        self.last_reason: Optional[str] = None
        self._inflight: Dict[int, _Tracked] = {}
        self._lock = threading.Lock()

    def healthy(self) -> bool:
        return self.state == "running" and self.queue.alive()

    # ---- in-flight tracking (at-most-once claim protocol) ----------------
    def track(self, rec: _Tracked) -> None:
        with self._lock:
            self._inflight[id(rec)] = rec

    def untrack(self, rec: _Tracked) -> bool:
        """Claim one record; True for exactly one of the competing claimers
        (inner-future callback vs supervisor drain)."""
        with self._lock:
            return self._inflight.pop(id(rec), None) is not None

    def drain_inflight(self) -> List[_Tracked]:
        with self._lock:
            recs = list(self._inflight.values())
            self._inflight.clear()
        return recs

    def inflight_count(self) -> int:
        with self._lock:
            return len(self._inflight)

    def fresh_queue(self) -> RequestQueue:
        """Replacement RequestQueue cloned from the dead one's knobs; the
        warmed engine (and its compile cache) is reused as-is."""
        old = self.queue
        self.queue = RequestQueue(
            self.engine,
            batch_deadline_ms=old.batch_deadline * 1e3,
            queue_capacity=old._ingress.maxsize,
            request_timeout_ms=old.request_timeout * 1e3,
            result_margin_s=old.result_margin,
            metrics=old.metrics)
        return self.queue


class ReplicaSet:
    """N shared-nothing replicas of one model behind one admission front.

    Duck-types RequestQueue for the transport/registry (submit,
    submit_rollout, depth, alive, start, stop), adds the failover and
    health surface, and owns the :class:`ReplicaSupervisor`.
    """

    def __init__(self, model: str, pairs, *, supervisor_opts: Optional[dict] = None):
        if not pairs:
            raise ValueError("ReplicaSet needs at least one (engine, queue)")
        self.model = model
        self.replicas = [Replica(i, eng, q) for i, (eng, q) in enumerate(pairs)]
        self.metrics = self.replicas[0].queue.metrics
        self.request_timeout = self.replicas[0].queue.request_timeout
        self.result_margin = self.replicas[0].queue.result_margin
        self._rr = 0
        self._lock = threading.Lock()
        self._supervised = False
        from distegnn_tpu.serve.supervisor import ReplicaSupervisor
        self.supervisor = ReplicaSupervisor(self, **(supervisor_opts or {}))

    # ---- RequestQueue-compatible surface ---------------------------------
    @property
    def engine(self):
        """Primary replica's engine — the registry's width/session-cache/
        capability handle (stable across restarts)."""
        return self.replicas[0].engine

    @property
    def ladder(self):
        return self.replicas[0].engine.ladder

    def start(self) -> "ReplicaSet":
        now = time.perf_counter()
        for r in self.replicas:
            r.queue.start()
            r.state = "running"
            r.started_at = now
        self._supervised = True
        self.supervisor.start()
        return self

    def stop(self, drain: bool = True, join_timeout_s: float = 30.0) -> None:
        self._supervised = False
        self.supervisor.stop()
        for r in self.replicas:
            r.queue.stop(drain=drain, join_timeout_s=join_timeout_s)
            r.state = "stopped"

    def alive(self) -> bool:
        return any(r.queue.alive() for r in self.replicas)

    def depth(self) -> int:
        return sum(r.queue.depth() for r in self.replicas)

    def submit(self, graph: dict, bucket=None,
               request_id: Optional[str] = None) -> ServeFuture:
        return self._admit("predict", graph, bucket, request_id)

    def submit_rollout(self, scene: dict,
                       request_id: Optional[str] = None) -> ServeFuture:
        return self._admit("rollout", scene, None, request_id)

    # ---- dispatch / failover ---------------------------------------------
    def _admit(self, kind: str, payload: dict, bucket, request_id) -> ServeFuture:
        now = time.perf_counter()
        outer = ServeFuture(
            hard_deadline=now + self.request_timeout + self.result_margin)
        rec = _Tracked(kind, payload, bucket, request_id, outer)
        self._dispatch(rec, admission=True)
        return outer

    def _choose(self, exclude: Set[int]) -> Optional[Replica]:
        with self._lock:
            cands = [r for r in self.replicas
                     if r.idx not in exclude and r.healthy()]
            if not cands:
                return None
            self._rr += 1
            return cands[self._rr % len(cands)]

    def _dispatch(self, rec: _Tracked, admission: bool) -> None:
        replica = self._choose(rec.tried)
        if replica is None:
            if not self._supervised and not rec.tried:
                # legacy pass-through: an unstarted/unsupervised set surfaces
                # replica 0's own admission errors (RuntimeError not-started,
                # QueueFullError) exactly as the single-queue gateway did
                replica = self.replicas[0]
            else:
                exc = ModelUnavailableError(self.model,
                                            retry_after_s=self.retry_after_s())
                if admission:
                    raise exc
                rec.outer.set_exception(exc)
                return
        rec.tried.add(replica.idx)
        try:
            if rec.kind == "rollout":
                inner = replica.queue.submit_rollout(
                    rec.payload, request_id=rec.request_id)
            else:
                inner = replica.queue.submit(
                    rec.payload, bucket=rec.bucket, request_id=rec.request_id)
        except Exception:
            if admission:
                raise  # typed 4xx/5xx mapping happens at the gateway
            # survivor couldn't admit (full / just died): try the next one;
            # recursion is bounded by the growing tried set
            self._dispatch(rec, admission=False)
            return
        replica.track(rec)
        inner.add_done_callback(
            lambda fut, rec=rec, rep=replica: self._on_inner_done(rec, rep, fut))

    def _on_inner_done(self, rec: _Tracked, replica: Replica,
                       inner: ServeFuture) -> None:
        if not replica.untrack(rec):
            return  # supervisor already claimed it (drained for failover)
        exc = inner.exception()
        if isinstance(exc, DispatcherCrashError):
            self._fail_over(rec, replica, reason=str(exc))
            return
        rec.outer.meta.update(inner.meta)
        rec.outer.meta["replica"] = replica.idx
        if exc is not None:
            rec.outer.set_exception(exc)
        else:
            rec.outer.set_result(inner._result)

    def _fail_over(self, rec: _Tracked, dead: Replica, reason: str) -> None:
        self.metrics.failed_over()
        obs.event("gateway/replica_failover", model=self.model,
                  replica=dead.idx, request_id=rec.request_id,
                  tried=sorted(rec.tried), reason=reason[:160])
        self._dispatch(rec, admission=False)

    def fail_over_replica(self, replica: Replica, reason: str) -> int:
        """Supervisor entry point: claim and re-dispatch everything in
        flight on a dead/wedged replica. Returns how many moved."""
        recs = replica.drain_inflight()
        for rec in recs:
            self._fail_over(rec, replica, reason=reason)
        return len(recs)

    # ---- health / hints ---------------------------------------------------
    def available(self) -> int:
        return sum(1 for r in self.replicas if r.healthy())

    def health(self) -> List[dict]:
        return [{"replica": r.idx, "state": r.state,
                 "alive": r.queue.alive(), "failures": r.failures,
                 "restarts": r.restarts, "inflight": r.inflight_count(),
                 "depth": r.queue.depth(), "last_reason": r.last_reason}
                for r in self.replicas]

    def retry_after_s(self) -> float:
        """Hint for 503 Retry-After: time to the earliest scheduled replica
        restart (floored at 0.1 s so clients never busy-spin)."""
        now = time.perf_counter()
        waits = [r.next_restart_at - now for r in self.replicas
                 if r.state in ("backoff", "broken")]
        if not waits:
            return 1.0
        return round(max(min(waits), 0.1), 3)

    def queue_retry_after_s(self) -> float:
        """Hint for 429 Retry-After: roughly how long the current backlog
        takes to drain (one batch deadline per max_batch queued requests),
        clamped to [0.1, 5] s."""
        per_batch = max(self.replicas[0].queue.batch_deadline, 0.01)
        max_batch = max(int(getattr(self.engine, "max_batch", 1)), 1)
        est = per_batch * (1.0 + self.depth() / max_batch)
        return round(min(max(est, 0.1), 5.0), 3)
