"""distegnn_tpu.obs — unified observability (docs/OBSERVABILITY.md).

One substrate for every runtime:
  - ``obs.span("name")`` / ``obs.event`` / ``obs.log`` — structured tracing
    into ``<log_dir>/obs/events.jsonl`` (``obs/trace.py``), near-zero-cost
    no-ops until :func:`configure` binds a sink (and always under
    ``obs.enable: false``);
  - ``Counter`` / ``Gauge`` / ``LatencyReservoir`` / ``MetricsRegistry`` —
    reusable run metrics with a JSON snapshot and a Prometheus-text renderer
    (``obs/metrics.py``; the serve stack's ``ServeMetrics`` is built on
    these);
  - JAX-runtime probes (``obs/jaxprobe.py``): the compile watcher that
    catches recompiles-after-warmup, device memory stats, and host<->device
    transfer byte counters;
  - declarative SLOs (``obs/slo.py``): :class:`SLOSpec` thresholds scored
    against the event stream or a live ``GET /metrics`` scrape, plus the
    :class:`SLOMonitor` rolling-window gauges the gateway exports.

Render a run: ``python scripts/obs_report.py <log_dir>/obs/events.jsonl``.
"""

from distegnn_tpu.obs.metrics import (Counter, Gauge, LatencyReservoir,
                                      MetricsRegistry, REGISTRY, get_registry,
                                      percentile)
from distegnn_tpu.obs.slo import SLOMonitor, SLOSpec
from distegnn_tpu.obs.trace import (EventWriter, Tracer, configure,
                                    configure_from_config, event, flush,
                                    get_tracer, log, span)

__all__ = [
    "Counter", "Gauge", "LatencyReservoir", "MetricsRegistry", "REGISTRY",
    "get_registry", "percentile",
    "EventWriter", "Tracer", "configure", "configure_from_config",
    "event", "flush", "get_tracer", "log", "span",
    "SLOMonitor", "SLOSpec",
]
