"""Low-overhead structured tracing: spans + events -> buffered JSONL.

One process-global :class:`Tracer` (swap it with :func:`configure`) serves
every runtime — trainer, serve stack, loaders, checkpointing. When no sink is
configured (the default until a run calls :func:`configure`, and always under
``obs.enable: false``) every call is a near-zero-cost no-op: ``span()``
returns a shared null context manager and ``event()`` returns immediately, so
instrumentation can stay in the hot paths unconditionally.

Event schema (docs/OBSERVABILITY.md): one JSON object per line,
  {"ts": <unix seconds>, "kind": "span"|"event"|"log", "name": str,
   "proc": <process_index>, "host": <hostname>, ["dur_s": float], ...attrs}

Writing is buffered (``buffer_events`` lines or ``flush_interval_s`` seconds,
whichever first) behind one lock, appended to ``<dir>/events.jsonl``. By
default only process 0 writes (params/metrics are replicated, and one file
per run is what the report tooling wants); ``per_host=True`` gives every
process its own ``events_p<i>.jsonl`` for load-imbalance hunts.

``log()`` is the host-prefixed structured logger replacing bare ``print``:
stdout stays line-compatible (the message text is unchanged; a ``[p<i>] ``
prefix appears only on processes > 0), always flushed, and — when a sink is
live — the same message lands in events.jsonl as a ``log`` event.
"""

from __future__ import annotations

import atexit
import json
import os
import socket
import threading
import time
from typing import Any, Dict, Optional


def _process_index() -> int:
    """jax.process_index() if the backend is importable, else 0. Kept lazy so
    importing obs never forces backend initialization."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


class EventWriter:
    """Thread-safe buffered JSONL appender with time/size-based flushing."""

    def __init__(self, path: str, buffer_events: int = 256,
                 flush_interval_s: float = 2.0):
        self.path = path
        self.buffer_events = max(int(buffer_events), 1)
        self.flush_interval_s = float(flush_interval_s)
        self._lock = threading.Lock()
        self._buf: list[str] = []
        self._last_flush = time.monotonic()
        self._closed = False
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # truncate: one writer per run dir, and a re-configured run (tests,
        # resumed processes reusing a dir) must not interleave with old events
        with open(path, "w"):
            pass
        # every writer flushes at interpreter exit, not just the one the
        # global tracer happens to hold — a bench that buffers its tail and
        # calls sys.exit must still leave a parseable file behind
        atexit.register(self.close)

    def write(self, record: Dict[str, Any]) -> None:
        line = json.dumps(record, separators=(",", ":"), default=repr)
        with self._lock:
            if self._closed:
                return
            self._buf.append(line)
            if (len(self._buf) >= self.buffer_events
                    or time.monotonic() - self._last_flush >= self.flush_interval_s):
                self._flush_locked()

    def _flush_locked(self) -> None:
        if self._buf:
            with open(self.path, "a") as f:
                f.write("\n".join(self._buf) + "\n")
            self._buf.clear()
        self._last_flush = time.monotonic()

    def flush(self) -> None:
        with self._lock:
            if not self._closed:
                self._flush_locked()

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                self._flush_locked()
                self._closed = True
        atexit.unregister(self.close)


class _NullSpan:
    """Shared no-op context manager — the disabled-tracer fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> "_NullSpan":
        return self


_NULL_SPAN = _NullSpan()


class _Span:
    """Times a with-block and writes one ``span`` record at exit. Extra
    attributes can be attached mid-flight via ``set(**attrs)``."""

    __slots__ = ("_tracer", "name", "attrs", "_t0")

    def __init__(self, tracer: "Tracer", name: str, attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs

    def set(self, **attrs) -> "_Span":
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur = time.perf_counter() - self._t0
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        self._tracer._emit("span", self.name, dur_s=round(dur, 6), **self.attrs)
        return False


class Tracer:
    """Span/event/log emitter over an optional :class:`EventWriter` sink."""

    def __init__(self, writer: Optional[EventWriter] = None,
                 tags: Optional[Dict[str, Any]] = None,
                 process_index: int = 0):
        self.writer = writer
        self.tags = dict(tags or {})
        self.process_index = int(process_index)

    @property
    def enabled(self) -> bool:
        return self.writer is not None

    def _emit(self, kind: str, name: str, **attrs) -> None:
        w = self.writer
        if w is None:
            return
        rec = {"ts": round(time.time(), 6), "kind": kind, "name": name}
        rec.update(self.tags)
        rec.update(attrs)
        w.write(rec)

    def span(self, name: str, **attrs):
        """Context manager timing a block; no-op when no sink is live."""
        if self.writer is None:
            return _NULL_SPAN
        return _Span(self, name, attrs)

    def event(self, name: str, **attrs) -> None:
        self._emit("event", name, **attrs)

    def log(self, msg: str, **attrs) -> None:
        """Structured logger replacing bare ``print``: stdout-line-compatible
        (identical text on process 0 / single-process; ``[p<i>] `` prefix on
        other processes), always flushed, mirrored into the event stream."""
        prefix = f"[p{self.process_index}] " if self.process_index else ""
        print(prefix + msg, flush=True)  # noqa: obs-print (the logger itself)
        self._emit("log", "log", msg=msg, **attrs)

    def flush(self) -> None:
        if self.writer is not None:
            self.writer.flush()

    def close(self) -> None:
        if self.writer is not None:
            self.writer.close()


# ---- process-global tracer --------------------------------------------------

_tracer = Tracer()          # disabled until configure() runs
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    return _tracer


def configure(log_dir: Optional[str] = None, enable: bool = True,
              per_host: bool = False, buffer_events: int = 256,
              flush_interval_s: float = 2.0,
              tags: Optional[Dict[str, Any]] = None,
              filename: Optional[str] = None) -> Tracer:
    """(Re)bind the global tracer.

    ``enable=False`` or ``log_dir=None`` installs a sinkless tracer: spans and
    events become no-ops and NO file is created (the ``obs.enable: false``
    kill switch); ``log()`` keeps printing either way. Default sink layout:
    process 0 writes ``<log_dir>/events.jsonl``; with ``per_host`` every
    process writes ``<log_dir>/events_p<i>.jsonl``. Every record is tagged
    ``proc``/``host`` (plus any extra ``tags``) so multi-host streams merge
    unambiguously.

    ``filename`` overrides the sink file name outright and always writes
    (no process-0 gating) — the serving worker children reuse this per-host
    machinery with worker-scoped names (``events_worker_<model>_<idx>.jsonl``
    next to the parent's ``events.jsonl``), so obs_report can stitch one
    request waterfall across the process boundary.
    """
    global _tracer
    pidx = _process_index()
    writer = None
    if enable and log_dir is not None and (per_host or filename is not None
                                           or pidx == 0):
        name = filename or (f"events_p{pidx}.jsonl" if per_host
                            else "events.jsonl")
        writer = EventWriter(os.path.join(log_dir, name),
                             buffer_events=buffer_events,
                             flush_interval_s=flush_interval_s)
    all_tags = {"proc": pidx, "host": socket.gethostname()}
    all_tags.update(tags or {})
    with _tracer_lock:
        old, _tracer = _tracer, Tracer(writer, tags=all_tags,
                                       process_index=pidx)
        old.close()
    return _tracer


def configure_from_config(config, exp_dir: str, enabled_here: bool = True,
                          tags: Optional[Dict[str, Any]] = None) -> Tracer:
    """Wire the tracer from a run config's ``obs:`` section (absent section =
    defaults = on). ``enabled_here`` gates non-logging invocations (e.g.
    ``train(log=False)`` test runs must not leave event files around).
    Returns the tracer; also installs the compile watcher when
    ``obs.jax_probe`` is on."""
    o = config.get("obs") if hasattr(config, "get") else None
    get = (lambda k, d: o.get(k, d) if o is not None else d)
    enable = bool(get("enable", True)) and enabled_here
    tracer = configure(
        log_dir=os.path.join(exp_dir, "obs") if enable else None,
        enable=enable,
        per_host=bool(get("per_host", False)),
        buffer_events=int(get("buffer_events", 256)),
        flush_interval_s=float(get("flush_interval_s", 2.0)),
        tags=tags)
    if enable and bool(get("jax_probe", True)):
        from distegnn_tpu.obs.jaxprobe import install_compile_watcher

        install_compile_watcher(tracer)
    return tracer


# module-level conveniences — stable call sites that always hit the CURRENT
# global tracer (configure() may rebind it mid-process, e.g. across tests)

def span(name: str, **attrs):
    return _tracer.span(name, **attrs)


def event(name: str, **attrs) -> None:
    _tracer.event(name, **attrs)


def log(msg: str, **attrs) -> None:
    _tracer.log(msg, **attrs)


def flush() -> None:
    _tracer.flush()


@atexit.register
def _flush_at_exit() -> None:
    try:
        _tracer.close()
    except Exception:
        pass
