"""JAX-runtime probes: compile (recompile!) watcher, device memory, transfers.

The #1 silent perf bug on a shape-laddered TPU stack is a recompile after
warmup — a shape drifting past its bucket, a weak_type flip, a donated buffer
changing layout — which shows up only as a mysteriously slow step. XLA's
compiles are invisible to user code EXCEPT through ``jax.monitoring``: every
backend compile records a ``/jax/core/compile/backend_compile_duration``
event. :class:`CompileWatcher` hooks that stream, attributes each compile to
the phase the runtime declared (``warmup``, ``epoch<N>``, ``serve``, ...) and
counts compiles-after-warmup separately so ``scripts/obs_report.py --check``
can fail a run on them.

Listener lifetime: ``jax.monitoring`` listeners cannot portably be removed,
so ONE module-level listener is registered (idempotently) and dispatches to
the currently-active watcher — re-configuring a run (or running many tests in
one process) swaps the watcher, never stacks listeners.

Also here: ``device_memory_stats()`` (``memory_stats()`` of local device 0,
when the backend exposes it — TPU/GPU yes, CPU None) and
:class:`TransferMeter` host->device byte accounting for loader/donation
boundaries.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from distegnn_tpu.obs import metrics as _metrics
from distegnn_tpu.obs import trace as _trace

# the jax.monitoring event marking one real backend (XLA) compile
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_listener_installed = False
_install_lock = threading.Lock()
_active: Optional["CompileWatcher"] = None


def _on_duration_event(event: str, duration_secs: float, **kwargs) -> None:
    w = _active
    if w is not None and event == _COMPILE_EVENT:
        w._record_compile(duration_secs)


class CompileWatcher:
    """Counts XLA compiles and attributes them to runtime-declared phases.

    Counters (global registry): ``jax/compiles`` (total),
    ``jax/compiles_after_warmup`` (the alarm), ``jax/compile_s`` (time spent
    compiling). Each compile also lands in the event stream as a
    ``jax/compile`` event with its phase, so the report can render a
    recompile table.
    """

    def __init__(self, tracer: Optional[_trace.Tracer] = None,
                 registry: Optional[_metrics.MetricsRegistry] = None):
        self.tracer = tracer or _trace.get_tracer()
        self.registry = registry or _metrics.get_registry()
        self._lock = threading.Lock()
        self.phase = "warmup"
        self.warmup_done = False
        self.compiles = 0
        self.compiles_after_warmup = 0

    def set_phase(self, phase: str) -> None:
        with self._lock:
            self.phase = phase

    def mark_warmup_done(self) -> None:
        """Declare steady state: every compile from here on is a recompile —
        the silent perf bug obs_report's --check gate exists to catch."""
        with self._lock:
            self.warmup_done = True

    def _record_compile(self, duration_secs: float) -> None:
        with self._lock:
            self.compiles += 1
            after = self.warmup_done
            if after:
                self.compiles_after_warmup += 1
            phase = self.phase
        self.registry.counter("jax/compiles").add(1)
        self.registry.counter("jax/compile_s").add(duration_secs)
        if after:
            self.registry.counter("jax/compiles_after_warmup").add(1)
        self.tracer.event("jax/compile", phase=phase,
                          dur_s=round(duration_secs, 6),
                          after_warmup=after)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"compiles": self.compiles,
                    "compiles_after_warmup": self.compiles_after_warmup,
                    "phase": self.phase, "warmup_done": self.warmup_done}


def install_compile_watcher(tracer: Optional[_trace.Tracer] = None,
                            registry: Optional[_metrics.MetricsRegistry] = None
                            ) -> CompileWatcher:
    """Install (or re-target) THE process compile watcher. The underlying
    jax.monitoring listener registers once per process; the active watcher —
    the one counting — is swapped atomically."""
    global _active, _listener_installed
    watcher = CompileWatcher(tracer, registry)
    with _install_lock:
        if not _listener_installed:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(
                _on_duration_event)
            _listener_installed = True
        _active = watcher
    return watcher


def get_compile_watcher() -> Optional[CompileWatcher]:
    return _active


def deactivate_compile_watcher() -> None:
    """Stop counting (the listener stays registered but dispatches nowhere)."""
    global _active
    _active = None


def set_phase(phase: str) -> None:
    """Phase declaration on the active watcher; no-op when none is live, so
    runtimes can declare phases unconditionally."""
    w = _active
    if w is not None:
        w.set_phase(phase)


def mark_warmup_done() -> None:
    w = _active
    if w is not None:
        w.mark_warmup_done()


# ---- device memory ---------------------------------------------------------

def device_memory_stats() -> Dict[str, Any]:
    """``memory_stats()`` of local device 0 when the backend exposes it
    (TPU/GPU); {} on CPU or pre-initialization failure. Keys are
    backend-defined (e.g. ``bytes_in_use``, ``peak_bytes_in_use``)."""
    try:
        import jax

        dev = jax.local_devices()[0]
        stats = dev.memory_stats() if hasattr(dev, "memory_stats") else None
        return dict(stats) if stats else {}
    except Exception:
        return {}


def emit_memory_event(tracer: Optional[_trace.Tracer] = None,
                      name: str = "jax/memory", **attrs) -> Dict[str, Any]:
    """Snapshot device memory into the event stream (no-op payload on CPU —
    the event still lands, so the report can say 'no memory stats here')."""
    t = tracer or _trace.get_tracer()
    stats = device_memory_stats()
    t.event(name, **{**attrs, **{k: stats[k] for k in
                                 ("bytes_in_use", "peak_bytes_in_use",
                                  "largest_alloc_size")
                                 if k in stats}})
    return stats


def record_memory_gauges(tag: str,
                         registry: Optional[_metrics.MetricsRegistry] = None,
                         ) -> Dict[str, Any]:
    """Per-chip HBM footprint as registry gauges (``mem/<tag>/<key>``) —
    the obs_report/snapshot view that pairs with :func:`emit_memory_event`'s
    events.jsonl view. The 3D-mesh sizing question this answers: does the
    T-way hidden-dim shard actually shrink ``peak_bytes_in_use`` per chip?
    Empty dict (no gauges) on CPU, where the backend reports no stats."""
    reg = registry or _metrics.get_registry()
    stats = device_memory_stats()
    for k in ("bytes_in_use", "peak_bytes_in_use", "largest_alloc_size"):
        if k in stats:
            reg.gauge(f"mem/{tag}/{k}").set(float(stats[k]))
    return stats


# ---- host<->device transfer accounting -------------------------------------

def tree_nbytes(tree) -> int:
    """Total nbytes of the array leaves of a pytree (numpy or jax arrays)."""
    try:
        import jax

        leaves = jax.tree.leaves(tree)
    except Exception:
        leaves = [tree]
    return sum(int(getattr(l, "nbytes", 0)) for l in leaves)


class TransferMeter:
    """Byte counters around the host<->device boundary. The loaders/putters
    call ``h2d(batch)`` on everything they hand to the device; fetches of
    results call ``d2h``. Counters live in the global registry
    (``xfer/h2d_bytes``, ``xfer/d2h_bytes``) so they appear in every
    snapshot without plumbing."""

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None):
        reg = registry or _metrics.get_registry()
        self._h2d = reg.counter("xfer/h2d_bytes")
        self._d2h = reg.counter("xfer/d2h_bytes")

    def h2d(self, tree) -> int:
        n = tree_nbytes(tree)
        self._h2d.add(n)
        return n

    def d2h(self, tree) -> int:
        n = tree_nbytes(tree)
        self._d2h.add(n)
        return n
