"""Turn an ``events.jsonl`` stream into a human-readable run report.

Consumed by ``scripts/obs_report.py``. Pure functions over parsed events so
tests can drive them without a filesystem:

  - :func:`load_events` — parse a JSONL file, tolerating (and counting)
    garbage lines (a crashed run can tear the final line);
  - :func:`summarize` — the numbers: step-time percentiles, stall fraction,
    recompile table by phase, checkpoint/fault/serve activity, per-proc
    event counts (load-imbalance smell at pod scale);
  - :func:`render_text` — the report itself;
  - :func:`check` — CI gate: failures on a zero-event stream or any
    recompile after warmup (the silent shape-ladder bug);
  - :func:`stitch_request` / :func:`render_request` — the per-request
    waterfall: every span/event carrying a gateway ``request_id`` (directly
    or via a batch's ``request_ids`` membership list), stitched into the
    queue -> batch -> compute timeline (``obs_report.py --request <id>``).
"""

from __future__ import annotations

import json
from collections import Counter as _CCounter
from collections import defaultdict
from typing import Any, Dict, List, Tuple

from distegnn_tpu.obs.metrics import percentile

# fault-timeline event names, in the order a reader wants them labeled
_FAULT_EVENTS = ("train/divergence", "train/rollback", "train/preempt",
                 "train/resume", "ckpt/corrupt")


def load_events(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """Parse one JSONL file -> (events, n_bad_lines). A torn final line (the
    writer died mid-append) is counted, not fatal."""
    events, bad = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            if isinstance(rec, dict):
                events.append(rec)
            else:
                bad += 1
    return events, bad


def load_run_events(path: str) -> Tuple[List[Dict[str, Any]], int,
                                        List[str]]:
    """Load one run's FULL stream: the named file plus any sibling
    ``events_worker_*.jsonl`` files (serving worker children write their
    own sinks next to the parent's — docs/SERVING.md "Worker processes"),
    merged and ts-sorted so a request that crossed the process boundary
    stitches into one waterfall. Returns (events, n_bad_lines, files)."""
    import glob
    import os

    files = [path]
    sibling_glob = os.path.join(os.path.dirname(path) or ".",
                                "events_worker_*.jsonl")
    files.extend(sorted(p for p in glob.glob(sibling_glob) if p != path))
    events: List[Dict[str, Any]] = []
    bad = 0
    for p in files:
        evs, b = load_events(p)
        events.extend(evs)
        bad += b
    events.sort(key=lambda e: float(e.get("ts", 0.0)))
    return events, bad, files


def _named(events, name):
    return [e for e in events if e.get("name") == name]


def summarize(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    steps = _named(events, "train/step")
    epochs = _named(events, "train/epoch")
    compiles = _named(events, "jax/compile")
    saves = _named(events, "ckpt/save")
    restores = _named(events, "ckpt/restore")
    serve_batches = _named(events, "serve/batch")

    step_s = sorted(float(e["dur_s"]) for e in steps if "dur_s" in e)
    # stall fraction: time blocked waiting on the loader over total
    # (stall + step) time. Host-loop step events carry their own stall;
    # scan-epoch runs have no step events — fall back to the per-epoch
    # aggregates the trainer emits.
    stall_s = sum(float(e.get("stall_s", 0.0)) for e in steps)
    busy_s = sum(step_s) + stall_s
    if not steps and epochs:
        stall_s = sum(float(e.get("stall_s", 0.0)) for e in epochs)
        busy_s = sum(float(e.get("dur_s", 0.0)) for e in epochs)

    by_phase: Dict[str, Dict[str, float]] = defaultdict(
        lambda: {"count": 0, "dur_s": 0.0, "after_warmup": 0})
    for c in compiles:
        row = by_phase[str(c.get("phase", "?"))]
        row["count"] += 1
        row["dur_s"] += float(c.get("dur_s", 0.0))
        row["after_warmup"] += bool(c.get("after_warmup"))
    recompiles = sum(r["after_warmup"] for r in by_phase.values())

    faults = sorted((e for e in events if e.get("name") in _FAULT_EVENTS),
                    key=lambda e: e.get("ts", 0.0))

    serve_exec_ms = sorted(1e3 * float(e["dur_s"])
                           for e in serve_batches if "dur_s" in e)

    return {
        "n_events": len(events),
        "by_kind": dict(_CCounter(e.get("kind", "?") for e in events)),
        "by_proc": dict(_CCounter(int(e.get("proc", 0)) for e in events)),
        "steps": {
            "count": len(step_s),
            "p50_ms": round(1e3 * percentile(step_s, 50), 3),
            "p99_ms": round(1e3 * percentile(step_s, 99), 3),
            "total_s": round(sum(step_s), 4),
        },
        "epochs": {
            "count": len(epochs),
            "time_p50_s": round(percentile(
                sorted(float(e.get("dur_s", 0.0)) for e in epochs), 50), 4),
            "last_loss_train": (epochs[-1].get("loss_train")
                                if epochs else None),
        },
        "stall": {
            "stall_s": round(stall_s, 4),
            "fraction": round(stall_s / busy_s, 6) if busy_s > 0 else 0.0,
        },
        "compiles": {
            "total": len(compiles),
            "after_warmup": int(recompiles),
            "by_phase": {k: {"count": int(v["count"]),
                             "dur_s": round(v["dur_s"], 4),
                             "after_warmup": int(v["after_warmup"])}
                         for k, v in sorted(by_phase.items())},
        },
        "checkpoints": {
            "saves": len(saves),
            "save_bytes": int(sum(int(e.get("bytes", 0)) for e in saves)),
            "save_s": round(sum(float(e.get("dur_s", 0.0)) for e in saves), 4),
            "restores": len(restores),
        },
        "serve": {
            "batches": len(serve_batches),
            "exec_p50_ms": round(percentile(serve_exec_ms, 50), 3),
            "exec_p99_ms": round(percentile(serve_exec_ms, 99), 3),
        },
        "faults": [{k: e.get(k) for k in
                    ("ts", "name", "epoch", "step", "msg", "reason",
                     "lr_scale", "path") if k in e} for e in faults],
    }


def render_text(summary: Dict[str, Any], source: str = "",
                bad_lines: int = 0) -> str:
    s = summary
    lines = []
    lines.append(f"== obs run report{' — ' + source if source else ''} ==")
    lines.append(f"events: {s['n_events']} "
                 f"({', '.join(f'{k}={v}' for k, v in sorted(s['by_kind'].items()))})"
                 + (f", {bad_lines} unparseable line(s)" if bad_lines else ""))
    if len(s["by_proc"]) > 1:
        lines.append("per-process events: " + ", ".join(
            f"p{k}={v}" for k, v in sorted(s["by_proc"].items())))
    st = s["steps"]
    if st["count"]:
        lines.append(f"steps: {st['count']}  p50 {st['p50_ms']} ms  "
                     f"p99 {st['p99_ms']} ms  (host-observed dispatch)")
    ep = s["epochs"]
    if ep["count"]:
        lines.append(f"epochs: {ep['count']}  median {ep['time_p50_s']} s"
                     + (f"  last train loss {ep['last_loss_train']}"
                        if ep["last_loss_train"] is not None else ""))
    lines.append(f"data stall: {s['stall']['stall_s']} s "
                 f"({100 * s['stall']['fraction']:.2f}% of busy time)")
    c = s["compiles"]
    lines.append(f"compiles: {c['total']} total, "
                 f"{c['after_warmup']} AFTER WARMUP"
                 + (" <-- recompile bug, see table" if c["after_warmup"] else ""))
    if c["by_phase"]:
        lines.append("  phase                     compiles  after-warmup  compile-time")
        for phase, row in c["by_phase"].items():
            lines.append(f"  {phase:<25} {row['count']:>8}  "
                         f"{row['after_warmup']:>12}  {row['dur_s']:>10.3f} s")
    ck = s["checkpoints"]
    if ck["saves"] or ck["restores"]:
        lines.append(f"checkpoints: {ck['saves']} save(s) "
                     f"({ck['save_bytes']} B, {ck['save_s']} s), "
                     f"{ck['restores']} restore(s)")
    sv = s["serve"]
    if sv["batches"]:
        lines.append(f"serve: {sv['batches']} batch(es)  "
                     f"exec p50 {sv['exec_p50_ms']} ms  "
                     f"p99 {sv['exec_p99_ms']} ms")
    if s["faults"]:
        lines.append("fault timeline:")
        t0 = s["faults"][0].get("ts") or 0.0
        for f in s["faults"]:
            extra = ", ".join(f"{k}={v}" for k, v in f.items()
                              if k not in ("ts", "name") and v is not None)
            lines.append(f"  +{(f.get('ts') or 0.0) - t0:8.2f}s  "
                         f"{f.get('name')}" + (f"  ({extra})" if extra else ""))
    else:
        lines.append("fault timeline: clean (no divergence/preempt/corrupt events)")
    return "\n".join(lines) + "\n"


def _touches(rec: Dict[str, Any], request_id: str) -> bool:
    """True when a record belongs to the request: its own ``request_id``
    attr (http span, prep event) or membership in a batch-level
    ``request_ids`` list (serve/batch, serve/execute)."""
    if rec.get("request_id") == request_id:
        return True
    ids = rec.get("request_ids")
    return isinstance(ids, (list, tuple)) and request_id in ids


def request_ids_seen(events: List[Dict[str, Any]]) -> List[str]:
    """All distinct request ids in the stream, in first-seen order."""
    seen: Dict[str, None] = {}
    for e in events:
        rid = e.get("request_id")
        if isinstance(rid, str):
            seen.setdefault(rid)
        for rid in (e.get("request_ids") or []):
            if isinstance(rid, str):
                seen.setdefault(rid)
    return list(seen)


def stitch_request(events: List[Dict[str, Any]],
                   request_id: str) -> Dict[str, Any]:
    """Reconstruct one request's life from the event stream alone.

    Returns records (ts-sorted), per-phase durations, and the stitched
    total. ``queue_ms`` comes out of the serve/batch event's per-member
    list (position-aligned with ``request_ids``); the stitched total is
    prep + queue-wait + batch compute, which the transport's reported
    ``total_ms`` upper-bounds (it adds response encode + thread wakeup).
    ``complete`` is True when the queue -> batch -> compute chain is all
    present (http span + batch event with a queue slot + execute span).
    """
    recs = sorted((e for e in events if _touches(e, request_id)),
                  key=lambda e: float(e.get("ts", 0.0)))
    http = next((e for e in recs if e.get("name") == "serve/http"), None)
    batches = [e for e in recs if e.get("name") == "serve/batch"]
    execs = [e for e in recs if e.get("name") == "serve/execute"]
    preps = [e for e in recs if e.get("name") == "serve/prep"]
    queue_ms = None
    for b in batches:
        ids = b.get("request_ids") or []
        qs = b.get("queue_ms") or []
        if request_id in ids and len(qs) == len(ids):
            queue_ms = float(qs[ids.index(request_id)])
            break
    prep_ms = round(sum(1e3 * float(e.get("dur_s", 0.0)) for e in preps), 3)
    compute_ms = round(sum(1e3 * float(e.get("dur_s", 0.0))
                           for e in batches), 3)
    execute_ms = round(sum(1e3 * float(e.get("dur_s", 0.0))
                           for e in execs), 3)
    http_ms = (round(1e3 * float(http.get("dur_s", 0.0)), 3)
               if http is not None else None)
    stitched_ms = round((queue_ms or 0.0) + prep_ms + compute_ms, 3)
    return {
        "request_id": request_id,
        "records": recs,
        "phases": {"prep_ms": prep_ms if preps else None,
                   "queue_ms": queue_ms, "compute_ms": compute_ms,
                   "execute_ms": execute_ms, "http_ms": http_ms},
        "stitched_ms": stitched_ms,
        "complete": bool(http is not None and queue_ms is not None
                         and batches and execs),
    }


def render_request(stitched: Dict[str, Any], source: str = "") -> str:
    """The waterfall: one row per record the request touched, offsets
    relative to the earliest span start (span ts is emitted at EXIT, so
    start = ts - dur_s), plus a synthetic queue-wait row ahead of the
    batch it resolved in."""
    rid = stitched["request_id"]
    recs = stitched["records"]
    lines = [f"== request {rid} — queue -> batch -> compute waterfall"
             f"{' — ' + source if source else ''} =="]
    if not recs:
        lines.append("no spans or events carry this request id")
        return "\n".join(lines) + "\n"
    http = next((e for e in recs if e.get("name") == "serve/http"), None)
    if http is not None:
        lines.append(f"route={http.get('route')} method={http.get('method')} "
                     f"status={http.get('status')} proc={http.get('proc')}")

    def _start(rec):
        return float(rec.get("ts", 0.0)) - float(rec.get("dur_s", 0.0))

    rows = []
    for rec in recs:
        detail = ", ".join(
            f"{k}={rec[k]}" for k in ("route", "status", "session", "hit",
                                      "filled", "capacity", "n", "e",
                                      "workload", "steps", "retry")
            if rec.get(k) is not None)
        rows.append((_start(rec), rec.get("name", "?"),
                     1e3 * float(rec.get("dur_s", 0.0)), detail))
        if rec.get("name") == "serve/batch":
            ids = rec.get("request_ids") or []
            qs = rec.get("queue_ms") or []
            if rid in ids and len(qs) == len(ids):
                q = float(qs[ids.index(rid)])
                rows.append((_start(rec) - q / 1e3, "[queue wait]", q, ""))
    rows.sort(key=lambda r: r[0])
    t0 = rows[0][0]
    lines.append(f"  {'offset':>12}  {'span/event':<16} {'dur':>11}  detail")
    for start, name, dur_ms, detail in rows:
        lines.append(f"  {1e3 * (start - t0):>+9.3f} ms  {name:<16} "
                     f"{dur_ms:>8.3f} ms" + (f"  {detail}" if detail else ""))
    ph = stitched["phases"]
    parts = [f"queue {ph['queue_ms']} ms" if ph["queue_ms"] is not None
             else "queue ?"]
    if ph["prep_ms"] is not None:
        parts.insert(0, f"prep {ph['prep_ms']} ms")
    parts.append(f"compute {ph['compute_ms']} ms")
    lines.append(f"stitched: {' + '.join(parts)} = {stitched['stitched_ms']}"
                 f" ms" + (f"  (http span {ph['http_ms']} ms)"
                           if ph["http_ms"] is not None else ""))
    lines.append("status: " + ("complete (queue -> batch -> compute all "
                               "reconstructed)" if stitched["complete"]
                               else "INCOMPLETE — a leg is missing from the "
                               "stream (shed/timeout, or obs was disabled "
                               "in part of the stack)"))
    return "\n".join(lines) + "\n"


def check(summary: Dict[str, Any]) -> List[str]:
    """CI-gate failures (empty list = pass)."""
    fails = []
    if summary["n_events"] == 0:
        fails.append("zero events: the run produced no telemetry "
                     "(obs disabled, or the instrumented paths never ran)")
    after = summary["compiles"]["after_warmup"]
    if after:
        fails.append(f"{after} recompile(s) after warmup — a shape/dtype "
                     "drifted past its compiled bucket (see the recompile "
                     "table; recompiles silently eat step time)")
    return fails
