"""Reusable run-metrics primitives (docs/OBSERVABILITY.md).

One ``Counter``/``Gauge``/``LatencyReservoir`` vocabulary shared by every
runtime: the serve stack's ``ServeMetrics`` is a thin facade over these, the
loader records data-stall time into them, and the trainer snapshots them into
per-epoch events. Everything is O(1) on the record path and guarded by a
per-primitive lock — metrics must never serialize a hot path on I/O.

Exports:
  - :func:`percentile` — THE nearest-rank percentile implementation (the one
    previously duplicated as ``serve/metrics._percentile``);
  - :class:`MetricsRegistry` — name -> primitive, with a flat JSON-able
    ``snapshot()`` and a Prometheus-text ``render_prometheus()``;
  - ``REGISTRY`` / :func:`get_registry` — the process-global default registry
    (the sink ``data/loader.py`` and ``obs/jaxprobe.py`` record into).
"""

from __future__ import annotations

import json
import re
import threading
import time
from typing import Dict, List, Optional, Union


def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile on an ASCENDING list (0 <= q <= 100).

    Empty input returns 0.0; q is clamped to [0, 100] by construction of the
    index. This is the single implementation — ``serve/metrics`` imports it.
    """
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class Counter:
    """Monotonic add-only counter (thread-safe)."""

    kind = "counter"

    def __init__(self, name: str = ""):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def add(self, n: Union[int, float] = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-value-wins gauge (thread-safe)."""

    kind = "gauge"

    def __init__(self, name: str = ""):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: Union[int, float]) -> None:
        with self._lock:
            self._value = float(v)

    def add(self, n: Union[int, float] = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class LatencyReservoir:
    """Bounded reservoir of the most recent ``size`` samples (milliseconds by
    convention); percentiles are computed at snapshot time so the record path
    stays O(1) amortized."""

    kind = "reservoir"

    def __init__(self, name: str = "", size: int = 8192):
        self.name = name
        self.size = int(size)
        self._lock = threading.Lock()
        self._vals: List[float] = []
        self._count = 0          # total ever recorded (reservoir is bounded)
        self._sum = 0.0

    def record(self, v: float) -> None:
        with self._lock:
            self._vals.append(float(v))
            self._count += 1
            self._sum += float(v)
            del self._vals[:-self.size]

    def record_many(self, vs: List[float]) -> None:
        with self._lock:
            self._vals.extend(float(v) for v in vs)
            self._count += len(vs)
            self._sum += sum(vs)
            del self._vals[:-self.size]

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def total(self) -> float:
        with self._lock:
            return self._sum

    def values(self) -> List[float]:
        """Sorted copy of the current reservoir contents."""
        with self._lock:
            return sorted(self._vals)

    def percentile(self, q: float) -> float:
        return percentile(self.values(), q)


_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """Metric name -> Prometheus-legal name (slashes/dots/dashes -> '_')."""
    out = _PROM_BAD.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


class MetricsRegistry:
    """Name -> primitive map with get-or-create accessors.

    ``snapshot()`` flattens everything into one {str: number} dict (reservoirs
    contribute ``<name>_p50``/``<name>_p99``/``<name>_count``/``<name>_sum``),
    which is directly a JSON line; ``render_prometheus()`` emits the same data
    in Prometheus text exposition format.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, name: str, cls, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as "
                                f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def reservoir(self, name: str, size: int = 8192) -> LatencyReservoir:
        return self._get_or_create(name, LatencyReservoir, size=size)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            items = list(self._metrics.items())
        out: Dict[str, float] = {}
        for name, m in items:
            if isinstance(m, LatencyReservoir):
                vals = m.values()
                out[f"{name}_count"] = m.count
                out[f"{name}_sum"] = round(m.total, 6)
                out[f"{name}_p50"] = round(percentile(vals, 50), 6)
                out[f"{name}_p99"] = round(percentile(vals, 99), 6)
            else:
                out[name] = m.value
        return out

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)

    def render_prometheus(self, prefix: str = "distegnn") -> str:
        """Prometheus text exposition (v0.0.4): ``# TYPE`` line + one sample
        per metric. Reservoirs render as a summary (quantile labels + _count
        and _sum samples)."""
        with self._lock:
            items = sorted(self._metrics.items())
        lines: List[str] = []
        for name, m in items:
            pname = _prom_name(f"{prefix}_{name}") if prefix else _prom_name(name)
            if isinstance(m, LatencyReservoir):
                vals = m.values()
                lines.append(f"# TYPE {pname} summary")
                for q in (50, 99):
                    lines.append(f'{pname}{{quantile="0.{q}"}} '
                                 f"{percentile(vals, q):g}")
                lines.append(f"{pname}_sum {m.total:g}")
                lines.append(f"{pname}_count {m.count}")
            else:
                lines.append(f"# TYPE {pname} {m.kind}")
                lines.append(f"{pname} {m.value:g}")
        return "\n".join(lines) + ("\n" if lines else "")


# process-global default registry: cross-cutting recorders (loader stall,
# jaxprobe compile counts) land here so the trainer/report can read them
# without threading a registry through every constructor
REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY
