"""Declarative SLO specs, evaluated wherever the numbers already are.

One spec (the ``slo:`` config section, or a YAML/JSON file handed to
``obs_report.py --slo``) names the service-level objectives of the serving
stack: per-route latency ceilings, error- and shed-rate ceilings, batch-fill
and session-hit floors. Evaluation is a pure function over a flat
``stats`` dict, so the same spec can be scored against

  - the EVENT STREAM (:func:`stats_from_events` — what ``obs_report --slo``
    does offline, from ``events.jsonl`` alone),
  - a live ``GET /metrics`` scrape (:func:`stats_from_prometheus` — what
    ``scripts/traffic_gen.py`` does against a running gateway),
  - any caller-built dict (the traffic generator merges its client-observed
    latencies in; an autoscaler would read the registry directly).

Stat keys (every producer speaks this vocabulary; missing = NO DATA, which
is reported but never a breach):

  ``<route>_p50_ms`` / ``<route>_p99_ms``  successful-response latency per
                                           route (predict / rollout)
  ``error_rate``       5xx fraction of inference requests (incl. 504)
  ``shed_rate``        429 fraction of inference requests
  ``batch_fill``       filled / capacity slots over executed micro-batches
  ``session_hit_rate`` session prep-cache hits / lookups

:class:`SLOMonitor` is the live half: a rolling window of gateway
observations exported as ``slo/window_*`` gauges on every ``GET /metrics``
render, so shed/autoscale logic and humans read the same numbers the
offline verdict uses.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from typing import Any, Dict, List, NamedTuple, Optional

from distegnn_tpu.obs.metrics import MetricsRegistry, _prom_name, percentile

# routes the SLO vocabulary covers: inference traffic only — operational
# scrapes (healthz/metrics/models) would dilute every rate
SLO_ROUTES = ("predict", "rollout")


class SLORule(NamedTuple):
    """One objective: ``stat`` must stay on the right side of ``threshold``
    (``bound`` is 'max' for ceilings, 'min' for floors)."""

    name: str
    stat: str
    bound: str          # "max" | "min"
    threshold: float


class SLOResult(NamedTuple):
    rule: SLORule
    observed: Optional[float]   # None = stat absent from stats (NO DATA)

    @property
    def ok(self) -> Optional[bool]:
        if self.observed is None:
            return None
        if self.rule.bound == "max":
            return self.observed <= self.rule.threshold
        return self.observed >= self.rule.threshold


class SLOSpec:
    """The declarative spec: thresholds only, no measurement."""

    def __init__(self, *, window_s: float = 60.0,
                 routes: Optional[Dict[str, Dict[str, float]]] = None,
                 error_rate_max: Optional[float] = None,
                 shed_rate_max: Optional[float] = None,
                 batch_fill_min: Optional[float] = None,
                 session_hit_min: Optional[float] = None):
        if window_s <= 0:
            raise ValueError(f"slo.window_s must be > 0 (got {window_s})")
        self.window_s = float(window_s)
        self.routes: Dict[str, Dict[str, float]] = {}
        for route, ceilings in (routes or {}).items():
            if route not in SLO_ROUTES:
                raise ValueError(f"slo.routes: unknown route {route!r} "
                                 f"(expected one of {SLO_ROUTES})")
            if not isinstance(ceilings, dict):
                raise ValueError(f"slo.routes.{route} must be a mapping of "
                                 f"p50_ms/p99_ms ceilings")
            for k, v in ceilings.items():
                if k not in ("p50_ms", "p99_ms"):
                    raise ValueError(f"slo.routes.{route}: unknown ceiling "
                                     f"{k!r} (expected p50_ms or p99_ms)")
                if v is not None and float(v) <= 0:
                    raise ValueError(f"slo.routes.{route}.{k} must be > 0 "
                                     f"(got {v})")
            self.routes[route] = {k: (None if v is None else float(v))
                                  for k, v in ceilings.items()}
        for label, v, lo, hi in (("error_rate_max", error_rate_max, 0, 1),
                                 ("shed_rate_max", shed_rate_max, 0, 1),
                                 ("batch_fill_min", batch_fill_min, 0, 1),
                                 ("session_hit_min", session_hit_min, 0, 1)):
            if v is not None and not (lo <= float(v) <= hi):
                raise ValueError(f"slo.{label} must be in [{lo}, {hi}] "
                                 f"(got {v})")
        self.error_rate_max = error_rate_max
        self.shed_rate_max = shed_rate_max
        self.batch_fill_min = batch_fill_min
        self.session_hit_min = session_hit_min

    @classmethod
    def from_mapping(cls, d: Dict[str, Any]) -> "SLOSpec":
        """Build from the ``slo:`` config section (or an equivalent dict);
        a nested ``{"slo": {...}}`` wrapper is unwrapped. Unknown keys are
        errors — a typo'd ceiling must not silently never fire."""
        if "slo" in d and isinstance(d["slo"], dict):
            d = d["slo"]
        known = {"enable", "window_s", "routes", "error_rate_max",
                 "shed_rate_max", "batch_fill_min", "session_hit_min"}
        extra = set(d) - known
        if extra:
            raise ValueError(f"slo: unknown key(s) {sorted(extra)} "
                             f"(known: {sorted(known)})")
        return cls(window_s=float(d.get("window_s", 60.0)),
                   routes=d.get("routes") or {},
                   error_rate_max=d.get("error_rate_max"),
                   shed_rate_max=d.get("shed_rate_max"),
                   batch_fill_min=d.get("batch_fill_min"),
                   session_hit_min=d.get("session_hit_min"))

    @classmethod
    def from_file(cls, path: str) -> "SLOSpec":
        """Load a YAML (or JSON — valid YAML) spec file."""
        import yaml

        with open(path) as f:
            d = yaml.safe_load(f)
        if not isinstance(d, dict):
            raise ValueError(f"SLO spec {path}: expected a mapping, "
                             f"got {type(d).__name__}")
        return cls.from_mapping(d)

    def rules(self) -> List[SLORule]:
        out: List[SLORule] = []
        for route in sorted(self.routes):
            for q in ("p50_ms", "p99_ms"):
                thr = self.routes[route].get(q)
                if thr is not None:
                    out.append(SLORule(f"{route}_{q} <= {thr:g}",
                                       f"{route}_{q}", "max", thr))
        if self.error_rate_max is not None:
            out.append(SLORule(f"error_rate <= {self.error_rate_max:g}",
                               "error_rate", "max",
                               float(self.error_rate_max)))
        if self.shed_rate_max is not None:
            out.append(SLORule(f"shed_rate <= {self.shed_rate_max:g}",
                               "shed_rate", "max", float(self.shed_rate_max)))
        if self.batch_fill_min is not None:
            out.append(SLORule(f"batch_fill >= {self.batch_fill_min:g}",
                               "batch_fill", "min",
                               float(self.batch_fill_min)))
        if self.session_hit_min is not None:
            out.append(SLORule(f"session_hit_rate >= "
                               f"{self.session_hit_min:g}",
                               "session_hit_rate", "min",
                               float(self.session_hit_min)))
        return out


# ---- stat producers ---------------------------------------------------------

def stats_from_events(events: List[Dict[str, Any]]) -> Dict[str, float]:
    """The SLO stats vocabulary, computed from ``events.jsonl`` alone.

    Latency percentiles use SUCCESSFUL (status < 400) inference responses;
    error/shed rates are fractions of ALL inference requests. Keys with no
    underlying traffic are omitted (NO DATA), never zero-filled.
    """
    stats: Dict[str, float] = {}
    infer = [e for e in events if e.get("name") == "serve/http"
             and e.get("route") in SLO_ROUTES]
    for route in SLO_ROUTES:
        lat = sorted(1e3 * float(e.get("dur_s", 0.0)) for e in infer
                     if e.get("route") == route
                     and int(e.get("status") or 0) < 400)
        if lat:
            stats[f"{route}_p50_ms"] = round(percentile(lat, 50), 3)
            stats[f"{route}_p99_ms"] = round(percentile(lat, 99), 3)
    if infer:
        statuses = [int(e.get("status") or 0) for e in infer]
        stats["error_rate"] = round(
            sum(s >= 500 for s in statuses) / len(statuses), 6)
        stats["shed_rate"] = round(
            sum(s == 429 for s in statuses) / len(statuses), 6)
    batches = [e for e in events if e.get("name") == "serve/batch"]
    slots = sum(int(e.get("capacity", 0)) for e in batches)
    if slots:
        stats["batch_fill"] = round(
            sum(int(e.get("filled", 0)) for e in batches) / slots, 6)
    preps = [e for e in events if e.get("name") == "serve/prep"]
    if preps:
        stats["session_hit_rate"] = round(
            sum(bool(e.get("hit")) for e in preps) / len(preps), 6)
    return stats


_PROM_LINE = re.compile(r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
                        r'(?:\{([^}]*)\})?\s+([^\s]+)$')


def parse_prometheus(text: str) -> Dict[str, float]:
    """Prometheus text -> {name or name{labels}: value} (comments skipped).
    Tolerates unparseable lines — a scrape is diagnostics, not input."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        if not m:
            continue
        name, labels, val = m.groups()
        try:
            fval = float(val)
        except ValueError:
            continue
        out[f"{name}{{{labels}}}" if labels else name] = fval
    return out


def stats_from_prometheus(text: str,
                          models: Optional[List[str]] = None
                          ) -> Dict[str, float]:
    """The SLO stats vocabulary, from a live ``GET /metrics`` scrape.

    Uses the gateway's per-route reservoirs (all responses — the scrape has
    no per-status latency split) and counters; fill and session hits are
    summed over the per-model serve registries (``models`` limits which;
    default: every ``distegnn_model_*`` present).
    """
    vals = parse_prometheus(text)
    stats: Dict[str, float] = {}
    for route in SLO_ROUTES:
        base = f"distegnn_gateway_http_{route}_ms"
        if vals.get(f"{base}_count", 0.0) > 0:
            stats[f"{route}_p50_ms"] = vals.get(f'{base}{{quantile="0.50"}}',
                                                0.0)
            stats[f"{route}_p99_ms"] = vals.get(f'{base}{{quantile="0.99"}}',
                                                0.0)
    total = vals.get("distegnn_gateway_requests_total", 0.0)
    if total > 0:
        errors = (vals.get("distegnn_gateway_errors", 0.0)
                  + vals.get("distegnn_gateway_timeouts", 0.0))
        sheds = (vals.get("distegnn_gateway_shed_inflight", 0.0)
                 + vals.get("distegnn_gateway_shed_queue_full", 0.0))
        stats["error_rate"] = round(errors / total, 6)
        stats["shed_rate"] = round(sheds / total, 6)
    prefixes = ([f"distegnn_model_{_prom_name(m)}" for m in models]
                if models is not None else
                sorted({k.split("_serve_")[0] for k in vals
                        if k.startswith("distegnn_model_")
                        and "_serve_" in k}))
    filled = slots = hits = misses = 0.0
    for p in prefixes:
        filled += vals.get(f"{p}_serve_batch_slots_filled", 0.0)
        slots += vals.get(f"{p}_serve_batch_slots_total", 0.0)
        hits += vals.get(f"{p}_serve_session_hits", 0.0)
        misses += vals.get(f"{p}_serve_session_misses", 0.0)
    if slots > 0:
        stats["batch_fill"] = round(filled / slots, 6)
    if hits + misses > 0:
        stats["session_hit_rate"] = round(hits / (hits + misses), 6)
    return stats


# ---- evaluation -------------------------------------------------------------

def evaluate(spec: SLOSpec, stats: Dict[str, float]) -> List[SLOResult]:
    return [SLOResult(rule, (float(stats[rule.stat])
                             if rule.stat in stats else None))
            for rule in spec.rules()]


def breached(results: List[SLOResult]) -> bool:
    return any(r.ok is False for r in results)


def verdict_table(results: List[SLOResult], source: str = "") -> str:
    lines = [f"== SLO verdict{' — ' + source if source else ''} =="]
    if not results:
        lines.append("spec declares no objectives (all thresholds null)")
        return "\n".join(lines) + "\n"
    lines.append(f"  {'objective':<34} {'observed':>10}  verdict")
    n_breach = n_nodata = 0
    for r in results:
        if r.ok is None:
            verdict, obs_s = "NO DATA", "-"
            n_nodata += 1
        elif r.ok:
            verdict, obs_s = "OK", f"{r.observed:g}"
        else:
            verdict, obs_s = "BREACH", f"{r.observed:g}"
            n_breach += 1
        lines.append(f"  {r.rule.name:<34} {obs_s:>10}  {verdict}")
    overall = "FAIL" if n_breach else "PASS"
    lines.append(f"overall: {overall} ({len(results)} objective(s), "
                 f"{n_breach} breached, {n_nodata} without data)")
    return "\n".join(lines) + "\n"


def results_json(results: List[SLOResult]) -> Dict[str, Any]:
    """The verdict as a JSON-able dict (embedded in traffic_gen's BENCH
    line)."""
    return {
        "pass": not breached(results),
        "rules": len(results),
        "breached": [r.rule.name for r in results if r.ok is False],
        "no_data": [r.rule.name for r in results if r.ok is None],
    }


# ---- the live half: rolling-window gauges on GET /metrics -------------------

class SLOMonitor:
    """Rolling window over gateway observations, exported as gauges.

    The gateway feeds one ``observe_http`` per inference request;
    ``export`` (called from every ``render_metrics``) prunes the window and
    sets ``slo/window_*`` gauges — windowed p50/p99 per route, error and
    shed rates, and per-model queue depth + windowed batch fill (computed
    by differencing each model's cumulative slot counters across the
    window). Thread-safe; O(1) per observation.
    """

    def __init__(self, window_s: float = 60.0, max_samples: int = 8192):
        self.window_s = float(window_s)
        self.max_samples = int(max_samples)
        self._lock = threading.Lock()
        self._http: deque = deque(maxlen=self.max_samples)  # (t, route, ms, status)
        self._fills: Dict[str, deque] = {}  # model -> (t, filled, slots)

    def observe_http(self, route: str, ms: float, status: int,
                     now: Optional[float] = None) -> None:
        if route not in SLO_ROUTES:
            return
        t = time.monotonic() if now is None else now
        with self._lock:
            self._http.append((t, route, float(ms), int(status)))

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._http and self._http[0][0] < cutoff:
            self._http.popleft()
        for dq in self._fills.values():
            # keep one sample older than the window as the diff baseline
            while len(dq) > 1 and dq[1][0] < cutoff:
                dq.popleft()

    def export(self, registry: MetricsRegistry,
               model_registry=None, now: Optional[float] = None) -> None:
        t = time.monotonic() if now is None else now
        with self._lock:
            self._prune(t)
            samples = list(self._http)
        registry.gauge("slo/window_requests").set(len(samples))
        for route in SLO_ROUTES:
            lat = sorted(ms for (_, r, ms, s) in samples
                         if r == route and s < 400)
            if lat:
                registry.gauge(f"slo/window_{route}_p50_ms").set(
                    percentile(lat, 50))
                registry.gauge(f"slo/window_{route}_p99_ms").set(
                    percentile(lat, 99))
        if samples:
            statuses = [s for (_, _, _, s) in samples]
            registry.gauge("slo/window_error_rate").set(
                sum(s >= 500 for s in statuses) / len(statuses))
            registry.gauge("slo/window_shed_rate").set(
                sum(s == 429 for s in statuses) / len(statuses))
        if model_registry is None:
            return
        for name, entry in model_registry.items():
            registry.gauge(f"slo/model_{name}_queue_depth").set(
                entry.queue.depth())
            # cumulative slot counters -> windowed fill by differencing
            filled = float(entry.engine.metrics.batch_slots_filled)
            slots = float(entry.engine.metrics.batch_slots_total)
            with self._lock:
                dq = self._fills.setdefault(name, deque())
                if dq and (filled < dq[-1][1] or slots < dq[-1][2]):
                    # a replica restart reset the cumulative counters — the
                    # old samples can't be differenced against the new line;
                    # restart the window baseline at the reset point
                    dq.clear()
                dq.append((t, filled, slots))
                if len(dq) > self.max_samples:
                    dq.popleft()
                t0, f0, s0 = dq[0]
            if slots > s0:
                registry.gauge(f"slo/window_model_{name}_fill").set(
                    max((filled - f0) / (slots - s0), 0.0))


    def window_snapshot(self, now: Optional[float] = None
                        ) -> Dict[str, float]:
        """The window as a flat stats dict (same vocabulary the offline
        verdict speaks, ``window_requests`` added) — the shared input for
        the autoscaler and priority admission, so scale/shed decisions read
        exactly the numbers ``/metrics`` exports."""
        t = time.monotonic() if now is None else now
        with self._lock:
            self._prune(t)
            samples = list(self._http)
        out: Dict[str, float] = {"window_requests": float(len(samples))}
        for route in SLO_ROUTES:
            lat = sorted(ms for (_, r, ms, s) in samples
                         if r == route and s < 400)
            if lat:
                out[f"{route}_p50_ms"] = percentile(lat, 50)
                out[f"{route}_p99_ms"] = percentile(lat, 99)
        if samples:
            statuses = [s for (_, _, _, s) in samples]
            out["error_rate"] = sum(s >= 500 for s in statuses) / len(statuses)
            out["shed_rate"] = sum(s == 429 for s in statuses) / len(statuses)
        return out


def bench_verdict(spec: SLOSpec, stats: Dict[str, float]) -> Dict[str, Any]:
    """One-call convenience: evaluate + JSON verdict (the traffic_gen
    embedding)."""
    return results_json(evaluate(spec, stats))


__all__ = [
    "SLO_ROUTES", "SLORule", "SLOResult", "SLOSpec", "SLOMonitor",
    "stats_from_events", "stats_from_prometheus", "parse_prometheus",
    "evaluate", "breached", "verdict_table", "results_json", "bench_verdict",
]
