"""distegnn_tpu — a TPU-native framework for fast & distributed equivariant GNNs.

A from-scratch JAX/XLA/pjit implementation of the capabilities of
GLAD-RUC/DistEGNN ("Fast and Distributed Equivariant Graph Neural Networks by
Virtual Node Learning", arXiv:2506.19482). Compute path is JAX (jit/shard_map/
Pallas); graphs are dense batched arrays with static shapes; distribution is a
`jax.sharding.Mesh` with a `graph` (spatial-partition) axis and XLA collectives
instead of NCCL.

Layer map (mirrors reference SURVEY.md §1, redesigned TPU-first):
  L6 CLI/config       distegnn_tpu.config, main.py
  L5 Training runtime distegnn_tpu.train
  L4 Models           distegnn_tpu.models
  L3 Distributed comm distegnn_tpu.parallel (mesh + psum collectives)
  L2 Data/partition   distegnn_tpu.data
  L1 Dataset gen      distegnn_tpu.datagen (offline)
"""

__version__ = "0.1.0"

from distegnn_tpu.ops.graph import GraphBatch  # noqa: F401
