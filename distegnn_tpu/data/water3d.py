"""Water-3D pipeline (reference process_water3d_cutoff,
datasets/process_dataset.py:225-297, and process_water_3d_dist, :308-438).

Input: ``{split}.h5`` files (DeepMind learning_to_simulate trajectories
converted by dataset_generation/Water-3D/tfrecord_to_h5.py) — per trajectory
key: ``particle_type`` [N], ``position`` [T, N, 3]. Per trajectory, 15 random
frames from the first 250 form (frame -> frame+delta_t) prediction pairs;
velocity is the one-step difference. The reference draws frames with an
UNSEEDED random.randint (process_dataset.py:241) — here the draw is seeded so
shards and reruns are reproducible.

Cutoff mode writes one pickle per split; distribute mode partitions every
frame with the chosen split_mode and writes per-rank shard files (the
reference's rank-0 flow)."""

from __future__ import annotations

import os
import pickle
import zlib
from typing import List, Optional

import numpy as np


def _split_seed(seed: int, split: str) -> list:
    """Deterministic RNG stream per (seed, split) — crc32, NOT Python's
    per-process-salted hash()."""
    return [seed, zlib.crc32(split.encode())]

from distegnn_tpu.data.distribute import write_partitioned_split
from distegnn_tpu.ops.radius import cutoff_edges_np, radius_graph_np

FRAME_RANGE = 250   # reference: "15 random frames from former 250"
FRAMES_PER_TRAJ = 15


def build_water3d_graph(loc_0, vel_0, particle_type, target, radius: float,
                        cutoff_rate: float = 0.0, with_edges: bool = True) -> dict:
    """node_feat = [|v|, type/max type]; node_attr = type; distance edge_attr
    (reference process_dataset.py:258-277)."""
    loc_0 = np.asarray(loc_0, np.float32)
    vel_0 = np.asarray(vel_0, np.float32)
    ptype = np.asarray(particle_type, np.float32).reshape(-1, 1)

    if with_edges:
        edge_index = radius_graph_np(loc_0, radius)
        edge_index = cutoff_edges_np(edge_index, loc_0, cutoff_rate)
    else:
        edge_index = np.zeros((2, 0), np.int64)
    dist = np.linalg.norm(loc_0[edge_index[0]] - loc_0[edge_index[1]], axis=1)

    speed = np.linalg.norm(vel_0, axis=1, keepdims=True)
    node_feat = np.concatenate([speed, ptype / max(ptype.max(), 1e-12)], axis=1)
    return {
        "node_feat": node_feat.astype(np.float32),
        "node_attr": ptype,
        "loc": loc_0,
        "vel": vel_0,
        "target": np.asarray(target, np.float32),
        "loc_mean": loc_0.mean(axis=0),
        "edge_index": edge_index.astype(np.int32),
        "edge_attr": np.repeat(dist[:, None], 2, axis=1).astype(np.float32),
    }


def _iter_frames(h5file, max_samples: int, delta_t: int, rng: np.random.Generator):
    """Yield (loc_0, vel_0, particle_type, target) tuples, <= max_samples."""
    import h5py  # C-backed IO; fine on TPU hosts (SURVEY.md §2.9)

    count = 0
    with h5py.File(h5file, "r") as f:
        for key in sorted(f.keys()):
            if count >= max_samples:
                break
            ptype = np.asarray(f[key]["particle_type"])
            pos = np.asarray(f[key]["position"])
            n = min(FRAMES_PER_TRAJ, max_samples - count)
            hi = min(FRAME_RANGE, pos.shape[0] - delta_t - 1)
            if hi <= 0:
                continue  # trajectory too short for this delta_t
            for frame in rng.integers(0, hi, size=n):
                yield (pos[frame], pos[frame + 1] - pos[frame], ptype, pos[frame + delta_t])
                count += 1


def process_water3d_cutoff(data_dir: str, dataset_name: str, max_samples: int,
                           radius: float, delta_t: int, cutoff_rate: float,
                           seed: int = 0) -> List[str]:
    base = os.path.join(data_dir, dataset_name)
    processed_dir = os.path.join(base, "processed")
    os.makedirs(processed_dir, exist_ok=True)
    paths = []
    for split in ("train", "valid", "test"):
        out = os.path.join(
            processed_dir,
            f"{dataset_name}_{split}_{radius}_{cutoff_rate:.3f}_{max_samples}_{delta_t}_s{seed}.pkl")
        paths.append(out)
        if os.path.exists(out):
            continue
        rng = np.random.default_rng(_split_seed(seed, split))
        graphs = [
            build_water3d_graph(l, v, p, t, radius, cutoff_rate)
            for l, v, p, t in _iter_frames(os.path.join(base, f"{split}.h5"),
                                           max_samples, delta_t, rng)
        ]
        with open(out, "wb") as f:
            pickle.dump(graphs, f, protocol=pickle.HIGHEST_PROTOCOL)
    return paths


def process_water3d_distribute(data_dir: str, dataset_name: str, world_size: int,
                               max_samples: int, inner_radius: float,
                               outer_radius: Optional[float], split_mode: str,
                               delta_t: int, seed: int = 0) -> List[List[str]]:
    """Distribute mode (reference process_water_3d_dist): every frame is
    partitioned into world_size shards; returns per-split lists of per-rank
    paths."""
    base = os.path.join(data_dir, dataset_name)
    processed_dir = os.path.join(base, "processed")
    os.makedirs(processed_dir, exist_ok=True)
    out = []
    for split in ("train", "valid", "test"):
        key = (f"{dataset_name}_{split_mode}_{split}_o{outer_radius}_i{inner_radius}"
               f"_{max_samples}_{delta_t}_s{seed}")
        rng = np.random.default_rng(_split_seed(seed, split))
        shard_paths = [os.path.join(processed_dir, f"{key}_{p}-{world_size}.pkl")
                       for p in range(world_size)]
        if not all(os.path.exists(p) for p in shard_paths):
            graphs = [
                build_water3d_graph(l, v, p, t, inner_radius, with_edges=False)
                for l, v, p, t in _iter_frames(os.path.join(base, f"{split}.h5"),
                                               max_samples, delta_t, rng)
            ]
            write_partitioned_split(graphs, processed_dir, key, world_size,
                                    split_mode, inner_radius, outer_radius, seed=seed)
        out.append(shard_paths)
    return out
