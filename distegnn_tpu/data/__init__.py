"""Data layer (L1+L2): offline n-body simulator, per-dataset preprocessing
pipelines, static-shape loaders, and the out-of-core streamed shard pipeline
(reference dataset_generation/** and datasets/process_dataset.py)."""

from distegnn_tpu.data.loader import GraphDataset, GraphLoader, ShardedGraphLoader
from distegnn_tpu.data.nbody import build_nbody_graph, process_nbody_cutoff
from distegnn_tpu.data.nbody_sim import (
    ChargedSystem,
    generate_nbody_files,
    simulate_trajectory,
)
from distegnn_tpu.data.stream import (
    PrefetchCrashError,
    PrefetchLoader,
    ShardChecksumError,
    StreamedGraphDataset,
    open_dataset,
    write_shards,
)

__all__ = [
    "ChargedSystem",
    "GraphDataset",
    "GraphLoader",
    "PrefetchCrashError",
    "PrefetchLoader",
    "ShardChecksumError",
    "ShardedGraphLoader",
    "StreamedGraphDataset",
    "build_nbody_graph",
    "generate_nbody_files",
    "open_dataset",
    "process_nbody_cutoff",
    "simulate_trajectory",
    "write_shards",
]
