"""Data layer (L1+L2): offline n-body simulator, per-dataset preprocessing
pipelines, and static-shape loaders (reference dataset_generation/** and
datasets/process_dataset.py)."""

from distegnn_tpu.data.loader import GraphDataset, GraphLoader, ShardedGraphLoader
from distegnn_tpu.data.nbody import build_nbody_graph, process_nbody_cutoff
from distegnn_tpu.data.nbody_sim import (
    ChargedSystem,
    generate_nbody_files,
    simulate_trajectory,
)

__all__ = [
    "ChargedSystem",
    "GraphDataset",
    "GraphLoader",
    "ShardedGraphLoader",
    "build_nbody_graph",
    "generate_nbody_files",
    "process_nbody_cutoff",
    "simulate_trajectory",
]
