"""Charged n-body simulator with rigid constraints (offline dataset generation).

TPU-native rebuild of the reference generator (reference
dataset_generation/nbody/system.py + physical_objects.py +
generate_dataset.py): charged particles under a softened Coulomb force,
integrated with symplectic Euler; optional rigid Sticks (2 balls) and Hinges
(3 balls, rigid beams to a pivot) whose constraint-preserving updates evolve a
persistent rigid-body state. The reference organizes this as a class hierarchy
of per-object Python updates; here one vectorized ``ChargedSystem`` carries
array state plus per-constraint records, and `check()` asserts the same
invariants (stick length, matched along-beam velocity projections, eps=1e-6,
reference physical_objects.py:135-145,229-243).

Physics parity notes (all behaviors, none of the code, from the reference):
  - force F_i = k * sum_j c_i c_j (x_i - x_j)/r^3, elementwise-clipped to
    +-max_F with max_F = 0.1/dt (system.py:16,107-135)
  - loc_std grows with ball count: std*(n/5)^(1/3)+0.1 (system.py:23)
  - initial speeds normalized to vel_norm (system.py:59-61)
  - multi-cluster initial placement for the large-graph configs
    (system.py:41-56; run.sh uses 100K nodes / 10 clusters)
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

EPS = 1e-6


def _rotation_matrix(theta: float, axis: np.ndarray) -> np.ndarray:
    """Rodrigues rotation by angle theta about unit vector axis."""
    K = np.array([
        [0.0, -axis[2], axis[1]],
        [axis[2], 0.0, -axis[0]],
        [-axis[1], axis[0], 0.0],
    ])
    return np.eye(3) + np.sin(theta) * K + (1.0 - np.cos(theta)) * (K @ K)


def _proj(v: np.ndarray, d: np.ndarray) -> np.ndarray:
    """Component of v along d."""
    return (v @ d) / (d @ d) * d


class ChargedSystem:
    """Charged balls with optional rigid sticks/hinges.

    Public state: X [n,3], V [n,3], charges [n,1], edges [n,n] (charge
    products — the n-body 'edges' arrays the pipeline loads), sticks/hinges
    (lists of dicts with "idx"/"length*" plus integrator state).
    """

    def __init__(
        self,
        rng: np.random.Generator,
        n_isolated: int = 0,
        n_stick: int = 0,
        n_hinge: int = 0,
        delta_t: float = 0.001,
        clusters: int = 1,
        box_size: Optional[float] = None,
        loc_std: float = 1.0,
        vel_norm: float = 0.5,
        interaction_strength: float = 1.0,
        charge_types=(1.0, -1.0),
    ):
        self.rng = rng
        self.delta_t = delta_t
        self.max_F = 0.1 / delta_t
        self.box_size = box_size
        self.interaction_strength = interaction_strength
        n = self.n_balls = n_isolated + 2 * n_stick + 3 * n_hinge
        self.loc_std = loc_std * (float(n) / 5.0) ** (1.0 / 3.0) + 0.1

        self.charges = rng.choice(np.asarray(charge_types, float), size=(n, 1))
        self.edges = self.charges @ self.charges.T

        # initial placement: each ball joins a random Gaussian cluster
        if clusters == 1:
            centers = np.zeros((1, 3))
        else:
            scale = 10.0 * clusters if clusters == 3 else 3.0 * clusters
            centers = rng.uniform(-scale, scale, size=(clusters, 3))
        which = rng.integers(0, clusters, size=n)
        self.X = rng.standard_normal((n, 3)) * self.loc_std + centers[which]
        V = rng.standard_normal((n, 3))
        self.V = V / np.linalg.norm(V, axis=1, keepdims=True) * vel_norm

        # constraint membership: random disjoint index groups
        perm = rng.permutation(n)
        self.isolated = perm[:n_isolated].copy()
        self.sticks: List[dict] = []
        self.hinges: List[dict] = []
        at = n_isolated
        for _ in range(n_stick):
            self.sticks.append({"idx": (int(perm[at]), int(perm[at + 1]))})
            at += 2
        for _ in range(n_hinge):
            self.hinges.append({"idx": (int(perm[at]), int(perm[at + 1]), int(perm[at + 2]))})
            at += 3

        for s in self.sticks:
            self._init_stick(s)
        for h in self.hinges:
            self._init_hinge(h)

    # -- constraint initialization: make velocities consistent with rigidity --

    def _init_stick(self, s: dict) -> None:
        i0, i1 = s["idx"]
        x0, x1 = self.X[i0], self.X[i1]
        v0, v1 = self.V[i0], self.V[i1]
        d = x1 - x0
        # both endpoints must share the along-stick velocity component
        p0, p1 = _proj(v0, d), _proj(v1, d)
        shared = 0.5 * (p0 + p1)
        v0, v1 = v0 - p0 + shared, v1 - p1 + shared
        self.V[i0], self.V[i1] = v0, v1

        xc, vc = 0.5 * (x0 + x1), 0.5 * (v0 + v1)
        r0 = x0 - xc
        s["length"] = float(np.linalg.norm(d))
        s["xc"], s["vc"] = xc, vc
        s["wc"] = np.cross(r0, v0 - vc) / (r0 @ r0)

    def _init_hinge(self, h: dict) -> None:
        i0, i1, i2 = h["idx"]
        x0, x1, x2 = self.X[i0], self.X[i1], self.X[i2]
        v0 = self.V[i0]
        d1, d2 = x1 - x0, x2 - x0
        # each arm keeps its own transverse velocity but inherits the pivot's
        # along-beam component
        v1 = _proj(v0, d1) + (self.V[i1] - _proj(self.V[i1], d1))
        v2 = _proj(v0, d2) + (self.V[i2] - _proj(self.V[i2], d2))
        self.V[i1], self.V[i2] = v1, v2
        h["length1"], h["length2"] = float(np.linalg.norm(d1)), float(np.linalg.norm(d2))
        h["w1"] = np.cross(d1, v1 - v0) / (d1 @ d1)
        h["w2"] = np.cross(d2, v2 - v0) / (d2 @ d2)

    # -- dynamics --

    def _forces(self) -> np.ndarray:
        diff = self.X[:, None, :] - self.X[None, :, :]  # x_i - x_j
        r2 = np.sum(diff * diff, axis=-1)
        np.fill_diagonal(r2, np.inf)
        k = self.interaction_strength * self.edges / np.power(r2, 1.5)
        F = np.sum(k[:, :, None] * diff, axis=1)
        return np.clip(F, -self.max_F, self.max_F)

    def step(self) -> None:
        dt = self.delta_t
        F = self._forces()

        # free balls: symplectic Euler (unit mass)
        iso = self.isolated
        if iso.size:
            self.V[iso] += F[iso] * dt
            self.X[iso] += self.V[iso] * dt

        for s in self.sticks:
            self._step_stick(s, F, dt)
        for h in self.hinges:
            self._step_hinge(h, F, dt)

    def _step_stick(self, s: dict, F: np.ndarray, dt: float) -> None:
        i0, i1 = s["idx"]
        f0, f1 = F[i0], F[i1]
        xc, vc, wc = s["xc"], s["vc"], s["wc"]
        r0, r1 = self.X[i0] - xc, self.X[i1] - xc

        vc = vc + 0.5 * (f0 + f1) * dt
        xc = xc + vc * dt

        # torque about the COM drives the angular velocity
        J = r0 @ r0 + r1 @ r1
        wc = wc + (np.cross(r0, f0) + np.cross(r1, f1)) / J * dt

        w = float(np.linalg.norm(wc))
        if w > 1e-12:
            R = _rotation_matrix(w * dt, wc / w)
            r0, r1 = R @ r0, R @ r1
        self.X[i0], self.X[i1] = xc + r0, xc + r1
        self.V[i0], self.V[i1] = vc + np.cross(wc, r0), vc + np.cross(wc, r1)
        s["xc"], s["vc"], s["wc"] = xc, vc, wc

    def _step_hinge(self, h: dict, F: np.ndarray, dt: float) -> None:
        i0, i1, i2 = h["idx"]
        x0, x1, x2 = self.X[i0], self.X[i1], self.X[i2]
        v0, v1, v2 = self.V[i0], self.V[i1], self.V[i2]
        f0, f1, f2 = F[i0], F[i1], F[i2]
        w1, w2 = h["w1"], h["w2"]
        r01, r02 = x1 - x0, x2 - x0
        e1 = np.outer(r01, r01) / (r01 @ r01)
        e2 = np.outer(r02, r02) / (r02 @ r02)

        # pivot acceleration from the rigid-beam constraint solve
        A = np.eye(3) + e1 + e2
        rhs = (
            (f0 + f1 + f2)
            - np.cross(w1, v1 - v0)
            - np.cross(w2, v2 - v0)
            - (np.eye(3) - e1) @ f1
            - (np.eye(3) - e2) @ f2
        )
        a0 = np.linalg.solve(A, rhs)

        v0 = v0 + a0 * dt
        x0 = x0 + v0 * dt

        w1 = w1 + np.cross(r01, f1 - a0) / (r01 @ r01) * dt
        w2 = w2 + np.cross(r02, f2 - a0) / (r02 @ r02) * dt

        for (i, r, w) in ((i1, r01, w1), (i2, r02, w2)):
            wn = float(np.linalg.norm(w))
            rr = _rotation_matrix(wn * dt, w / wn) @ r if wn > 1e-12 else r
            self.X[i] = x0 + rr
            self.V[i] = v0 + np.cross(w, rr)
        self.X[i0], self.V[i0] = x0, v0
        h["w1"], h["w2"] = w1, w2

    # -- invariants (reference physical_objects.py check() methods) --

    def check(self) -> None:
        for s in self.sticks:
            i0, i1 = s["idx"]
            d = self.X[i1] - self.X[i0]
            assert abs(np.linalg.norm(d) - s["length"]) < EPS, "stick length drifted"
            p0, p1 = _proj(self.V[i0], d), _proj(self.V[i1], d)
            assert np.sum(np.abs(p0 - p1)) < EPS, "stick endpoints shear apart"
        for h in self.hinges:
            i0, i1, i2 = h["idx"]
            for i, key in ((i1, "length1"), (i2, "length2")):
                d = self.X[i] - self.X[i0]
                assert abs(np.linalg.norm(d) - h[key]) < EPS, "hinge beam length drifted"
                p0, pi = _proj(self.V[i0], d), _proj(self.V[i], d)
                assert np.sum(np.abs(p0 - pi)) < EPS, "hinge beam shears apart"

    def is_valid(self) -> bool:
        if self.box_size is None:
            return True
        return bool(np.all(np.abs(self.X) <= self.box_size))


def simulate_trajectory(
    rng: np.random.Generator,
    length: int,
    sample_freq: int,
    n_isolated: int = 0,
    n_stick: int = 0,
    n_hinge: int = 0,
    clusters: int = 1,
    delta_t: float = 0.001,
    box_size: Optional[float] = None,
):
    """One trajectory, sampled every ``sample_freq`` steps (reference
    generate_dataset.py:55-70). Returns (loc [T,N,3], vel [T,N,3],
    charges [N,1], edges [N,N]); regenerates on box escape."""
    while True:
        sys_ = ChargedSystem(
            rng, n_isolated=n_isolated, n_stick=n_stick, n_hinge=n_hinge,
            clusters=clusters, delta_t=delta_t, box_size=box_size,
        )
        loc, vel = [], []
        for t in range(length):
            sys_.step()
            if t % sample_freq == 0:
                loc.append(sys_.X.copy())
                vel.append(sys_.V.copy())
        sys_.check()
        if sys_.is_valid():
            return np.asarray(loc), np.asarray(vel), sys_.charges.copy(), sys_.edges.copy()


def simulate_trajectories_batched(
    rng: np.random.Generator,
    num: int,
    length: int,
    sample_freq: int,
    n_isolated: int,
    clusters: int = 1,
    delta_t: float = 0.001,
    loc_std: float = 1.0,
    vel_norm: float = 0.5,
    interaction_strength: float = 1.0,
    charge_types=(1.0, -1.0),
    dtype: str = "float64",
):
    """Batched isolated-only fast path: ``num`` trajectories integrated at
    once with one jitted lax.scan (any backend; ~2 orders of magnitude over
    the per-trajectory Python loop on a single host core).

    Same physics as ChargedSystem (reference system.py:16,107-135): softened
    Coulomb forces elementwise-clipped to +-0.1/dt, symplectic Euler, samples
    at t % sample_freq == 0 of the reference's step loop
    (generate_dataset.py:55-70) — i.e. one step, sample, then
    (sample_freq steps, sample) x (T-1); the reference's trailing
    sample_freq-1 unsampled steps are skipped. RNG draws differ in ORDER from
    the serial path, so a given seed yields a statistically identical but not
    bitwise-equal dataset; constraints (sticks/hinges) and box_size need the
    serial path.

    ``dtype``: 'float64' (default; the serial path's precision — integrated
    under jax's local enable_x64 so no global config leaks) or 'float32'
    (half the memory/time; fine for training data, which the pipelines cast
    to f32 anyway, but 5000 chaotic Coulomb steps DIVERGE pointwise from an
    f64 integration — only the distribution matches). TPU backends have no
    native f64; use float32 there.

    Returns (loc [num,T,N,3], vel [num,T,N,3], charges [num,N,1],
    edges [num,N,N]); loc/vel in ``dtype``.
    """
    import jax
    import jax.numpy as jnp

    n = n_isolated
    T = (length + sample_freq - 1) // sample_freq
    max_F = 0.1 / delta_t
    std = loc_std * (float(n) / 5.0) ** (1.0 / 3.0) + 0.1

    charges = rng.choice(np.asarray(charge_types, float), size=(num, n, 1))
    edges = charges @ np.swapaxes(charges, 1, 2)
    if clusters == 1:
        centers = np.zeros((num, 1, 3))
    else:
        scale = 10.0 * clusters if clusters == 3 else 3.0 * clusters
        centers = rng.uniform(-scale, scale, size=(num, clusters, 3))
    which = rng.integers(0, clusters, size=(num, n))
    X0 = rng.standard_normal((num, n, 3)) * std + np.take_along_axis(
        centers, which[:, :, None], axis=1)
    V0 = rng.standard_normal((num, n, 3))
    V0 = V0 / np.linalg.norm(V0, axis=2, keepdims=True) * vel_norm

    eye = jnp.eye(n, dtype=bool)

    def force(X, E):
        diff = X[:, :, None, :] - X[:, None, :, :]
        r2 = jnp.sum(diff * diff, axis=-1)
        r2 = jnp.where(eye, jnp.inf, r2)
        k = interaction_strength * E / jnp.power(r2, 1.5)
        F = jnp.einsum("bij,bijd->bid", k, diff)
        return jnp.clip(F, -max_F, max_F)

    def one_step(carry):
        X, V, E = carry
        F = force(X, E)
        V = V + F * delta_t
        X = X + V * delta_t
        return X, V, E

    @jax.jit
    def run(X, V, E):
        def sample_block(carry, _):
            carry = jax.lax.fori_loop(0, sample_freq, lambda _, c: one_step(c), carry)
            return carry, (carry[0], carry[1])

        carry = one_step((X, V, E))  # reference samples first at t == 0, after one step
        first = (carry[0], carry[1])
        _, rest = jax.lax.scan(sample_block, carry, None, length=T - 1)
        loc = jnp.concatenate([first[0][None], rest[0]], axis=0)
        vel = jnp.concatenate([first[1][None], rest[1]], axis=0)
        return jnp.swapaxes(loc, 0, 1), jnp.swapaxes(vel, 0, 1)  # [num, T, N, 3]

    if dtype == "float64":
        with jax.enable_x64(True):
            loc, vel = run(jnp.asarray(X0, jnp.float64),
                           jnp.asarray(V0, jnp.float64),
                           jnp.asarray(edges, jnp.float64))
            loc, vel = np.asarray(loc), np.asarray(vel)
    else:
        loc, vel = run(jnp.asarray(X0, jnp.float32), jnp.asarray(V0, jnp.float32),
                       jnp.asarray(edges, jnp.float32))
        loc, vel = np.asarray(loc), np.asarray(vel)
    return loc, vel, charges, edges


def generate_nbody_files(
    path: str,
    n_isolated: int = 0,
    n_stick: int = 0,
    n_hinge: int = 0,
    clusters: int = 1,
    num_train: int = 0,
    num_valid: int = 0,
    num_test: int = 0,
    length: int = 5000,
    sample_freq: int = 100,
    seed: int = 42,
    suffix: str = "",
    box_size: Optional[float] = None,
) -> str:
    """Write the reference's .npy file layout (generate_dataset.py:86-118):
    ``{loc,vel,charges,edges}_{split}_charged{iso}_{stick}_{hinge}_{clusters}{suffix}.npy``
    with loc/vel [num, T, N, 3], charges [num, N, 1], edges [num, N, N].
    Returns the tag (the part after the first underscore of the split)."""
    tag = f"charged{n_isolated}_{n_stick}_{n_hinge}_{clusters}{suffix}"
    os.makedirs(path, exist_ok=True)
    rng = np.random.default_rng(seed)
    fast = n_stick == 0 and n_hinge == 0 and box_size is None
    for split, num in (("train", num_train), ("valid", num_valid), ("test", num_test)):
        if fast and num:
            # accelerator-friendly batched integrator, chunked to bound memory
            locs, vels, chgs, edgs = [], [], [], []
            chunk = 512
            for at in range(0, num, chunk):
                loc, vel, charges, edges = simulate_trajectories_batched(
                    rng, min(chunk, num - at), length, sample_freq,
                    n_isolated=n_isolated, clusters=clusters,
                )
                locs.append(loc)
                vels.append(vel)
                chgs.append(charges)
                edgs.append(edges)
            locs, vels = np.concatenate(locs), np.concatenate(vels)
            chgs, edgs = np.concatenate(chgs), np.concatenate(edgs)
        else:
            locs, vels, chgs, edgs = [], [], [], []
            for _ in range(num):
                loc, vel, charges, edges = simulate_trajectory(
                    rng, length, sample_freq, n_isolated=n_isolated, n_stick=n_stick,
                    n_hinge=n_hinge, clusters=clusters, box_size=box_size,
                )
                locs.append(loc)
                vels.append(vel)
                chgs.append(charges)
                edgs.append(edges)
            locs, vels = np.asarray(locs), np.asarray(vels)
            chgs, edgs = np.asarray(chgs), np.asarray(edgs)
        np.save(os.path.join(path, f"loc_{split}_{tag}.npy"), locs)
        np.save(os.path.join(path, f"vel_{split}_{tag}.npy"), vels)
        np.save(os.path.join(path, f"charges_{split}_{tag}.npy"), chgs)
        np.save(os.path.join(path, f"edges_{split}_{tag}.npy"), edgs)
    return tag
