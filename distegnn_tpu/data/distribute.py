"""Distribute-mode preprocessing: partition every graph into world_size shards
and cache one file per (split, partition-rank) — the reference's rank-0
preprocessing + per-rank shard files flow (reference
datasets/process_dataset.py:308-578: rank 0 partitions all frames, writes
``..._{rank}-{world_size}.pt``, other ranks wait at a barrier).

Here one host process drives all chips, so "rank 0 does the work" is simply
the only code path; multi-host pods reuse the same cache through a shared
filesystem exactly like the reference.

The reference wires this mode only for Water-3D / Fluid113K; the n-body
variant below exists because it makes the distributed path testable and
benchmarkable from generated data alone (same partition+shard flow)."""

from __future__ import annotations

import os
import pickle
from typing import List, Optional

import numpy as np

from distegnn_tpu.data.nbody import _find_tag, build_nbody_graph
from distegnn_tpu.data.partition import split_graph


def _shard_paths(processed_dir: str, key: str, world_size: int) -> List[str]:
    return [os.path.join(processed_dir, f"{key}_{p}-{world_size}.pkl") for p in range(world_size)]


def write_partitioned_split(
    graphs: List[dict],
    processed_dir: str,
    key: str,
    world_size: int,
    split_mode: str,
    inner_radius: float,
    outer_radius: Optional[float],
    seed: int = 0,
) -> List[str]:
    """Partition each graph into world_size parts; write shard p's list of
    partition-p dicts to its own file. Asserts equal shard lengths (reference
    process_dataset.py:430-431,570-571)."""
    paths = _shard_paths(processed_dir, key, world_size)
    if all(os.path.exists(p) for p in paths):
        return paths
    shards: List[List[dict]] = [[] for _ in range(world_size)]
    for i, g in enumerate(graphs):
        parts = split_graph(
            g, world_size, split_mode, inner_radius,
            outer_radius=outer_radius, seed=seed + i,
        )
        for p in range(world_size):
            shards[p].append(parts[p])
    assert len({len(s) for s in shards}) == 1, "unequal shard lengths"
    os.makedirs(processed_dir, exist_ok=True)
    for p, path in enumerate(paths):
        # tmp + atomic rename: a reader (another host on shared storage, or a
        # crashed run's leftovers) never sees a truncated pickle
        with open(path + ".tmp", "wb") as f:
            pickle.dump(shards[p], f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(path + ".tmp", path)
    return paths


def process_nbody_distribute(
    data_dir: str,
    dataset_name: str,
    world_size: int,
    max_samples: int,
    inner_radius: float,
    outer_radius: Optional[float],
    split_mode: str,
    frame_0: int,
    frame_T: int,
    seed: int = 0,
    tag: Optional[str] = None,
) -> List[List[str]]:
    """N-body distribute mode: whole graphs (full connectivity dropped — each
    partition rebuilds inner_radius edges) split into world_size shards.
    Returns [train_paths, valid_paths, test_paths], each world_size long."""
    base = os.path.join(data_dir, dataset_name)
    processed_dir = os.path.join(base, "processed")
    os.makedirs(processed_dir, exist_ok=True)

    out = []
    for split in ("train", "valid", "test"):
        key = (
            f"{dataset_name}_{split}_dist_{split_mode}_o{outer_radius}_i{inner_radius}"
            f"_{max_samples}_{frame_0}_{frame_T}_s{seed}"
        )
        paths = _shard_paths(processed_dir, key, world_size)
        if not all(os.path.exists(p) for p in paths):
            t = tag if tag is not None else _find_tag(base, split)
            loc = np.load(os.path.join(base, f"loc_{split}_{t}.npy"))[:max_samples]
            vel = np.load(os.path.join(base, f"vel_{split}_{t}.npy"))[:max_samples]
            charges = np.load(os.path.join(base, f"charges_{split}_{t}.npy"))[:max_samples]
            graphs = [
                build_nbody_graph(loc[k, frame_0], vel[k, frame_0], charges[k],
                                  loc[k, frame_T], with_edges=False)
                for k in range(loc.shape[0])
            ]
            write_partitioned_split(
                graphs, processed_dir, key, world_size, split_mode,
                inner_radius, outer_radius, seed=seed,
            )
        out.append(paths)
    return out
