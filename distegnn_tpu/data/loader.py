"""Static-shape graph loaders.

The reference wraps processed lists in a PyG DataLoader with drop_last=True and
a seeded RandomSampler so every rank draws the same graph order
(reference main.py:184-190, datasets/process_dataset.py:582-596). Here loaders
collate into padded ``GraphBatch``es with dataset-wide N/E maxima fixed at
construction, so every batch of an epoch shares ONE compiled XLA program —
the TPU-first replacement for ragged PyG batching.
"""

from __future__ import annotations

import pickle
from typing import List, Sequence, Union

import numpy as np
import jax

from distegnn_tpu.ops.graph import GraphBatch, _round_up, pad_graphs


class GraphDataset:
    """A list of graph dicts, from a processed pickle file or in memory
    (reference DatasetWrapper, datasets/process_dataset.py:582-596)."""

    def __init__(self, source: Union[str, Sequence[dict]]):
        if isinstance(source, str):
            with open(source, "rb") as f:
                self.graphs: List[dict] = pickle.load(f)
        else:
            self.graphs = list(source)

    def __len__(self) -> int:
        return len(self.graphs)

    def __getitem__(self, i: int) -> dict:
        return self.graphs[i]

    def size_maxima(self):
        n = max(g["loc"].shape[0] for g in self.graphs)
        e = max(g["edge_index"].shape[1] for g in self.graphs)
        return n, e


class GraphLoader:
    """Deterministic batching: permutation from (seed, epoch) only, so every
    host draws identical order (the invariant the reference checks per step
    with an all_gather, utils/train.py:55-61 — here it holds by construction).
    drop_last always (reference main.py:186)."""

    def __init__(
        self,
        dataset: GraphDataset,
        batch_size: int,
        shuffle: bool = False,
        seed: int = 0,
        node_bucket: int = 8,
        edge_bucket: int = 128,
        max_nodes: int = None,
        max_edges: int = None,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        if max_nodes is None or max_edges is None:
            n, e = dataset.size_maxima()
            max_nodes = max_nodes if max_nodes is not None else _round_up(n, node_bucket)
            max_edges = max_edges if max_edges is not None else _round_up(e, edge_bucket)
        self.max_nodes, self.max_edges = max_nodes, max_edges
        if len(self) == 0:
            raise ValueError(
                f"batch_size {batch_size} > dataset size {len(dataset)}: "
                "drop_last leaves zero batches"
            )

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return len(self.dataset) // self.batch_size

    def _order(self) -> np.ndarray:
        if not self.shuffle:
            return np.arange(len(self.dataset))
        return np.random.default_rng([self.seed, self.epoch]).permutation(len(self.dataset))

    def __iter__(self):
        order = self._order()
        for b in range(len(self)):
            idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            yield pad_graphs(
                [self.dataset[int(i)] for i in idx],
                max_nodes=self.max_nodes, max_edges=self.max_edges,
            )


class ShardedGraphLoader:
    """Lockstep loaders over per-partition shards, stacked on a leading
    partition axis [P, B, ...] — the layout shard_map consumes with the P axis
    sharded over the mesh's ``graph`` axis. Mirrors the reference's per-rank
    shard files + identical seeded order (main.py:182-190); shards share one
    N/E maximum so the stack is rectangular.

    ``data_parallel=D`` activates the mesh's second axis: each step draws
    D*batch_size graphs per partition shard and emits [D, P, B, ...], the D
    axis sharding over DATA_AXIS (different graphs per data shard — true data
    parallelism, which the reference lacks: its ranks all see the same batch,
    SURVEY.md §2.10)."""

    def __init__(
        self,
        datasets: Sequence[GraphDataset],
        batch_size: int,
        shuffle: bool = False,
        seed: int = 0,
        node_bucket: int = 8,
        edge_bucket: int = 128,
        data_parallel: int = 1,
    ):
        sizes = {len(d) for d in datasets}
        if len(sizes) != 1:
            raise ValueError(f"shards must be equal length, got {sorted(sizes)}")
        maxima = [d.size_maxima() for d in datasets]
        n = max(m[0] for m in maxima)
        e = max(m[1] for m in maxima)
        self.data_parallel = data_parallel
        self.loaders = [
            GraphLoader(
                d, batch_size * data_parallel, shuffle=shuffle, seed=seed,
                max_nodes=_round_up(n, node_bucket), max_edges=_round_up(e, edge_bucket),
            )
            for d in datasets
        ]

    @property
    def num_partitions(self) -> int:
        return len(self.loaders)

    def set_epoch(self, epoch: int) -> None:
        for l in self.loaders:
            l.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.loaders[0])

    def __iter__(self):
        D = self.data_parallel
        for parts in zip(*self.loaders):
            stacked = jax.tree.map(lambda *xs: np.stack(xs, axis=0), *parts)
            if D > 1:
                # [P, D*B, ...] -> [D, P, B, ...]
                stacked = jax.tree.map(
                    lambda x: x.reshape(x.shape[0], D, x.shape[1] // D,
                                        *x.shape[2:]).swapaxes(0, 1),
                    stacked,
                )
            yield stacked
