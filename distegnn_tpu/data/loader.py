"""Static-shape graph loaders.

The reference wraps processed lists in a PyG DataLoader with drop_last=True and
a seeded RandomSampler so every rank draws the same graph order
(reference main.py:184-190, datasets/process_dataset.py:582-596). Here loaders
collate into padded ``GraphBatch``es with dataset-wide N/E maxima fixed at
construction, so every batch of an epoch shares ONE compiled XLA program —
the TPU-first replacement for ragged PyG batching.
"""

from __future__ import annotations

import contextlib
import pickle
import threading
import time
import zipfile
from typing import Callable, List, Optional, Sequence, Union

import numpy as np
import jax

from distegnn_tpu import obs
from distegnn_tpu.ops.graph import GraphBatch, _round_up, pad_graphs

# module-level open hook: the fault-injection harness (testing/faults.py
# flaky_open / truncated_read) swaps this to exercise the retry path without
# touching a real filesystem fault
_file_open = open

# bounded retry around dataset file reads: epoch-start reads off NFS/GCS see
# transient ESTALE/EIO-style hiccups, and a multi-hour unattended session
# (scripts/convergence_session.sh) must not die to one
_OPEN_ATTEMPTS = 3
_OPEN_BACKOFF_S = 0.1

# What a transiently-broken read surfaces as: open/read syscall errors
# (OSError), a pickle cut mid-payload (EOFError / UnpicklingError), a
# truncated .npz (BadZipFile), and numpy's header parse on garbage bytes
# (ValueError). A file broken the same way on every attempt still fails
# hard after the last retry.
_READ_ERRORS = (OSError, EOFError, pickle.UnpicklingError,
                zipfile.BadZipFile, ValueError)


def _open_with_retry(path: str, mode: str = "rb"):
    """``open`` with ``_OPEN_ATTEMPTS`` tries and exponential backoff
    (0.1s, 0.2s, ...); each retry is logged. The final failure propagates —
    a genuinely missing/unreadable file is still a hard error.

    NOTE: this only guards the ``open()`` syscall. Dataset loads must use
    :func:`_read_with_retry`, which covers the FULL payload read — a
    truncated NFS read succeeds at open() and dies inside ``pickle.load``."""
    for attempt in range(_OPEN_ATTEMPTS):
        try:
            return _file_open(path, mode)
        except OSError as e:
            if attempt == _OPEN_ATTEMPTS - 1:
                raise
            delay = _OPEN_BACKOFF_S * (2 ** attempt)
            obs.log(f"loader: open {path} failed ({e!r}); retry "
                    f"{attempt + 1}/{_OPEN_ATTEMPTS - 1} in {delay:.1f}s")
            time.sleep(delay)


def _read_with_retry(path: str, reader: Callable, what: str = "dataset",
                     retry_on: tuple = ()):
    """Open ``path`` and run ``reader(file)`` with the bounded retry covering
    the WHOLE read, not just ``open()``: a truncated NFS read hands back a
    short payload that only explodes inside ``pickle.load``/``np.load``, and
    before this existed such a failure escaped the retry and killed a
    multi-hour convergence session. ``retry_on`` adds caller-typed errors
    (e.g. a shard checksum mismatch) to the retryable set; the final failure
    always propagates."""
    errors = _READ_ERRORS + tuple(retry_on)
    for attempt in range(_OPEN_ATTEMPTS):
        try:
            with _file_open(path, "rb") as f:
                return reader(f)
        except errors as e:
            if attempt == _OPEN_ATTEMPTS - 1:
                raise
            delay = _OPEN_BACKOFF_S * (2 ** attempt)
            obs.log(f"loader: {what} read {path} failed ({e!r}); retry "
                    f"{attempt + 1}/{_OPEN_ATTEMPTS - 1} in {delay:.1f}s")
            time.sleep(delay)


# Stall attribution: the trainer reads per-step deltas of ``data/stall_s``,
# so that counter must mean "time the TRAINER was blocked on data". When the
# prefetch producer (data/stream.PrefetchLoader) drives a loader from its
# background thread, the collate/put work overlaps compute and is NOT a
# stall — the producer redirects its thread's accounting to
# ``data/produce_s`` via this thread-local, and only the consumer's real
# wait lands on ``data/stall_s``.
_STALL_TLS = threading.local()


def _stall_counter():
    name = getattr(_STALL_TLS, "name", None) or "data/stall_s"
    return obs.get_registry().counter(name)


@contextlib.contextmanager
def stall_attribution(name: str):
    """Redirect this THREAD's loader stall accounting to ``name``."""
    prev = getattr(_STALL_TLS, "name", None)
    _STALL_TLS.name = name
    try:
        yield
    finally:
        _STALL_TLS.name = prev


def graphs_nbytes(graphs: Sequence[dict]) -> int:
    """Resident bytes of a list of graph dicts (numpy payload only)."""
    total = 0
    for g in graphs:
        for v in g.values():
            if isinstance(v, np.ndarray):
                total += v.nbytes
    return total


def _log_host_bytes(nbytes: int, what: str) -> None:
    """Account dataset host residency on the ``data/host_bytes`` gauge (the
    RSS a training process pays to hold its datasets — the number the
    out-of-core streamed loader exists to bound)."""
    obs.get_registry().gauge("data/host_bytes").add(nbytes)
    obs.log(f"loader: {what} resident {nbytes / 2**20:.1f} MiB "
            f"(data/host_bytes)")


class GraphDataset:
    """A list of graph dicts, from a processed pickle file or in memory
    (reference DatasetWrapper, datasets/process_dataset.py:582-596)."""

    def __init__(self, source: Union[str, Sequence[dict]],
                 node_order: str = "none"):
        if isinstance(source, str):
            # retry covers the FULL pickle read: a truncated NFS payload dies
            # inside pickle.load, not at open()
            self.graphs: List[dict] = _read_with_retry(
                source, pickle.load, what="pickle")
        elif isinstance(source, list):
            # already-materialized list: adopt it as-is. list(source) here
            # used to double the transient footprint of the outer container
            # for zero benefit (the graph dicts were shared either way).
            self.graphs = source
        else:
            self.graphs = list(source)
        # 'morton': relabel nodes along a Z curve of their positions — static
        # locality preprocessing for the gather/aggregation hot loop
        # (ops/order.py; VERDICT r3 #1). Permutation-equivariant models see
        # an identical problem with cache-friendly edge indices.
        if node_order == "morton":
            from distegnn_tpu.ops.order import morton_reorder_graph

            if self.graphs is source:
                # shallow outer copy (pointers only) so the caller's list is
                # never mutated by the per-slot reorder below
                self.graphs = list(self.graphs)
            # per-slot replacement so peak payload residency stays one
            # dataset + one graph, not two full array sets
            for i in range(len(self.graphs)):
                self.graphs[i] = morton_reorder_graph(self.graphs[i])
        elif node_order not in ("none", None):
            raise ValueError(f"GraphDataset: unknown node_order {node_order!r}")
        _log_host_bytes(graphs_nbytes(self.graphs),
                        f"GraphDataset[{len(self.graphs)} graphs]")

    def __len__(self) -> int:
        return len(self.graphs)

    def __getitem__(self, i: int) -> dict:
        return self.graphs[i]

    def size_maxima(self):
        n = max(g["loc"].shape[0] for g in self.graphs)
        e = max(g["edge_index"].shape[1] for g in self.graphs)
        return n, e


class GraphLoader:
    """Deterministic batching: permutation from (seed, epoch) only, so every
    host draws identical order (the invariant the reference checks per step
    with an all_gather, utils/train.py:55-61 — here it holds by construction).
    drop_last always (reference main.py:186)."""

    def __init__(
        self,
        dataset: GraphDataset,
        batch_size: int,
        shuffle: bool = False,
        seed: int = 0,
        node_bucket: int = 8,
        edge_bucket: int = 128,
        max_nodes: int = None,
        max_edges: int = None,
        edge_block: int = 0,
        edges_per_block: int = None,
        edge_tile: int = 512,
        pairing: Optional[bool] = None,  # None=auto (blocked: symmetry scan; plain: off)
        cache_bytes: int = 2 << 30,
        max_in_degree: Optional[int] = None,  # plain+pairing: dataset-stable ELL D
        split_remote: bool = False,  # fused edge pipeline: carry compact remote list
        remote_pad: Optional[int] = None,  # None=auto (dataset scan, run-stable R)
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.edge_block, self.edge_tile = edge_block, edge_tile
        self.pairing = False
        self._prepared_cache = None
        if split_remote and not edge_block:
            raise ValueError("GraphLoader: split_remote requires edge_block > 0 "
                             "(the fused pipeline's window is defined on the "
                             "blocked layout)")
        self.split_remote, self.remote_pad = bool(split_remote), remote_pad
        if edge_block:
            # dataset-stable blocked layout: ONE edges_per_block and ONE
            # pairing decision for every batch (single scan up front), so the
            # whole run keeps a single pytree structure / compiled program
            from distegnn_tpu.ops.blocked import scan_dataset_for_blocking

            if max_edges is not None:
                raise ValueError("GraphLoader: max_edges is unsupported with "
                                 "edge_block; pass edges_per_block instead")
            n, _ = dataset.size_maxima()
            self.max_nodes = _round_up(max(max_nodes or 0, n, 1), edge_block)
            if split_remote:
                # fused kernel's 3-block VMEM window needs nb >= 3; small
                # graphs pay two all-padding blocks rather than failing
                self.max_nodes = max(self.max_nodes, 3 * edge_block)
            if edges_per_block is None or pairing is None:
                deg, sym = scan_dataset_for_blocking(
                    dataset, self.max_nodes, edge_block)
                if edges_per_block is None:
                    edges_per_block = _round_up(deg, edge_tile)
                pairing = sym if pairing is None else pairing
            self.pairing = pairing
            self.edges_per_block = edges_per_block
            self.max_edges = (self.max_nodes // edge_block) * edges_per_block
            if self.split_remote and self.remote_pad is None:
                # run-stable remote width: scan raw edge lists once (blockify
                # never adds out-of-window edges — its padding slots sit
                # inside their own block), pad to a lane multiple
                from distegnn_tpu.ops.edge_pipeline import count_remote_edges

                er = max(count_remote_edges(dataset[i]["edge_index"],
                                            block=edge_block,
                                            n_nodes=self.max_nodes)
                         for i in range(len(dataset)))
                self.remote_pad = max(_round_up(er, 128), 128)
            # cache prepared (blockified) graphs across epochs when affordable:
            # per-graph blocked edge payload ~ E * (2 idx + attrs + mask + pair)
            d0 = dataset[0].get("edge_attr")
            per = self.max_edges * (8 + 4 + 8 + (d0.shape[1] * 4 if d0 is not None else 0))
            if per * len(dataset) <= cache_bytes:
                self._prepared_cache = {}
            else:
                obs.log(f"GraphLoader: blockify cache OFF "
                        f"({per * len(dataset) / 2**30:.1f} GiB > "
                        f"{cache_bytes / 2**30:.1f} GiB budget) — every epoch re-lays "
                        f"edges on host; raise cache_bytes if RAM allows")
        else:
            self.edges_per_block = None
            # plain layout: pairing=True attaches the reverse-edge involution
            # to every batch (segment_impl='cumsum' uses it for scatter-free
            # col-gather backwards). In-tree edge builders emit symmetric
            # radius/full graphs, so the all-or-nothing per-batch pairing
            # stays structurally stable across the run.
            self.pairing = bool(pairing)
            if max_nodes is None or max_edges is None:
                n, e = dataset.size_maxima()
                max_nodes = max_nodes if max_nodes is not None else _round_up(n, node_bucket)
                max_edges = max_edges if max_edges is not None else _round_up(e, edge_bucket)
            self.max_nodes, self.max_edges = max_nodes, max_edges
            # GraphBatch.max_in_degree is STATIC: a per-batch value would
            # retrace the jitted step whenever it crossed a bucket boundary,
            # so scan the dataset once for a run-stable D (same rationale as
            # the blocked path's edges_per_block scan above)
            if self.pairing and max_in_degree is None:
                deg = max(int(np.bincount(dataset[i]["edge_index"][0],
                                          minlength=1).max())
                          for i in range(len(dataset)))
                max_in_degree = _round_up(max(deg, 1), 8)
            self.max_in_degree = max_in_degree
        if len(self) == 0:
            raise ValueError(
                f"batch_size {batch_size} > dataset size {len(dataset)}: "
                "drop_last leaves zero batches"
            )

    def pad_kwargs(self) -> dict:
        """kwargs that make pad_graphs emit this loader's (stable) layout."""
        if self.edge_block:
            return dict(edge_block=self.edge_block, edge_tile=self.edge_tile,
                        edges_per_block=self.edges_per_block,
                        max_nodes=self.max_nodes, compute_pair=self.pairing,
                        split_remote=self.split_remote,
                        remote_pad=self.remote_pad)
        return dict(max_nodes=self.max_nodes, max_edges=self.max_edges,
                    compute_pair=self.pairing, max_in_degree=self.max_in_degree)

    def _graph(self, i: int) -> dict:
        """Fetch graph i, blockified (and cached) when edge_block is on."""
        if not self.edge_block:
            return self.dataset[i]
        if self._prepared_cache is not None and i in self._prepared_cache:
            return self._prepared_cache[i]
        from distegnn_tpu.ops.blocked import prepare_blocked_graph

        g = prepare_blocked_graph(self.dataset[i], self.max_nodes,
                                  self.edges_per_block, self.edge_block,
                                  compute_pair=self.pairing)
        if self._prepared_cache is not None:
            self._prepared_cache[i] = g
        return g

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return len(self.dataset) // self.batch_size

    def _order(self) -> np.ndarray:
        if not self.shuffle:
            return np.arange(len(self.dataset))
        return np.random.default_rng([self.seed, self.epoch]).permutation(len(self.dataset))

    def __iter__(self):
        order = self._order()
        # collation time is data-stall by definition (iteration is
        # synchronous: the trainer blocks on this generator) — unless this
        # thread runs under stall_attribution (prefetch producer), in which
        # case the same work overlaps compute and lands on data/produce_s
        stall = _stall_counter()
        for b in range(len(self)):
            t0 = time.perf_counter()
            idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            batch = pad_graphs(
                [self._graph(int(i)) for i in idx], **self.pad_kwargs(),
            )
            stall.add(time.perf_counter() - t0)
            yield batch


class ShardedGraphLoader:
    """Lockstep loaders over per-partition shards, stacked on a leading
    partition axis [P, B, ...] — the layout shard_map consumes with the P axis
    sharded over the mesh's ``graph`` axis. Mirrors the reference's per-rank
    shard files + identical seeded order (main.py:182-190); shards share one
    N/E maximum so the stack is rectangular.

    ``data_parallel=D`` activates the mesh's second axis: each step draws
    D*batch_size graphs per partition shard and emits [D, P, B, ...], the D
    axis sharding over DATA_AXIS (different graphs per data shard — true data
    parallelism, which the reference lacks: its ranks all see the same batch,
    SURVEY.md §2.10)."""

    def __init__(
        self,
        datasets: Sequence[GraphDataset],
        batch_size: int,
        shuffle: bool = False,
        seed: int = 0,
        node_bucket: int = 8,
        edge_bucket: int = 128,
        data_parallel: int = 1,
        edge_block: int = 0,
        edge_tile: int = 512,
        pairing: Optional[bool] = None,  # None=auto (blocked: AND over shard scans; plain: off)
        split_remote: bool = False,
    ):
        sizes = {len(d) for d in datasets}
        if len(sizes) != 1:
            raise ValueError(f"shards must be equal length, got {sorted(sizes)}")
        maxima = [d.size_maxima() for d in datasets]
        n = max(m[0] for m in maxima)
        e = max(m[1] for m in maxima)
        self.data_parallel = data_parallel
        if edge_block:
            # one blocked layout across ALL shards so the [P, B, ...] stack is
            # rectangular: common N, common edges_per_block, and ONE pairing
            # decision (max/AND over shards)
            from distegnn_tpu.ops.blocked import scan_dataset_for_blocking

            N = _round_up(n, edge_block)
            if split_remote:
                # fused kernel's 3-block VMEM window needs nb >= 3 (same
                # clamp as GraphLoader's single-shard blocked branch)
                N = max(N, 3 * edge_block)
            scans = [scan_dataset_for_blocking(d, N, edge_block) for d in datasets]
            epb = _round_up(max(s[0] for s in scans), edge_tile)
            if pairing is None:
                pairing = all(s[1] for s in scans)
            rp = None
            if split_remote:
                # one remote width across ALL shards (same rectangular-stack
                # argument as epb above)
                from distegnn_tpu.ops.edge_pipeline import count_remote_edges

                er = max(count_remote_edges(d[i]["edge_index"],
                                            block=edge_block, n_nodes=N)
                         for d in datasets for i in range(len(d)))
                rp = max(_round_up(er, 128), 128)
            self.loaders = [
                GraphLoader(
                    d, batch_size * data_parallel, shuffle=shuffle, seed=seed,
                    max_nodes=N, edge_block=edge_block, edge_tile=edge_tile,
                    edges_per_block=epb, pairing=pairing,
                    split_remote=split_remote, remote_pad=rp,
                )
                for d in datasets
            ]
        else:
            if split_remote:
                raise ValueError("ShardedGraphLoader: split_remote requires "
                                 "edge_block > 0")
            # one static max_in_degree across ALL shards so the stacked
            # [P, B, ...] batches share a single pytree identity
            mid = None
            if pairing:
                deg = max(int(np.bincount(d[i]["edge_index"][0], minlength=1).max())
                          for d in datasets for i in range(len(d)))
                mid = _round_up(max(deg, 1), 8)
            self.loaders = [
                GraphLoader(
                    d, batch_size * data_parallel, shuffle=shuffle, seed=seed,
                    max_nodes=_round_up(n, node_bucket), max_edges=_round_up(e, edge_bucket),
                    pairing=pairing, max_in_degree=mid,
                )
                for d in datasets
            ]

    @property
    def num_partitions(self) -> int:
        return len(self.loaders)

    def set_epoch(self, epoch: int) -> None:
        for l in self.loaders:
            l.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.loaders[0])

    def __iter__(self):
        D = self.data_parallel
        # the per-shard loaders already count their collation time; only the
        # stack/reshape work on top of them is added here (same thread-local
        # attribution as GraphLoader.__iter__)
        stall = _stall_counter()
        for parts in zip(*self.loaders):
            t0 = time.perf_counter()
            if any(p.edge_pair is None for p in parts):
                # pairing must be all-or-nothing for a rectangular stack
                parts = [p.replace(edge_pair=None) for p in parts]
            stacked = jax.tree.map(lambda *xs: np.stack(xs, axis=0), *parts)
            if D > 1:
                # [P, D*B, ...] -> [D, P, B, ...]
                stacked = jax.tree.map(
                    lambda x: x.reshape(x.shape[0], D, x.shape[1] // D,
                                        *x.shape[2:]).swapaxes(0, 1),
                    stacked,
                )
            stall.add(time.perf_counter() - t0)
            yield stacked
