"""Out-of-core streamed graph pipeline: sharded on-disk datasets + async
double-buffered prefetch.

Two independent single-host ceilings fall here (ROADMAP "million-node
graphs" item, streaming rationale per arXiv:1906.11786):

1. **Residency** — ``GraphDataset`` pickles the whole dataset into host RAM.
   :func:`write_shards` lays a processed dataset out as a directory of
   fixed-schema ``.npz`` shards plus a JSON manifest (per-shard N/E maxima,
   dataset maxima, CRC32 checksums), and :class:`StreamedGraphDataset` serves
   the same ``__getitem__``/``size_maxima`` protocol while holding only a
   bounded LRU of decoded shards — host RSS is O(cache_shards · shard_bytes),
   not O(dataset).

2. **Stall** — the old ``_PuttingLoader`` blocked the trainer on every
   synchronous collate + host→device put. :class:`PrefetchLoader` moves that
   work to a bounded background thread (``data.prefetch_depth`` deep, default
   2) so disk read + collate + put overlap the previous step's compute;
   ``data/stall_s`` then measures only true starvation, with the overlapped
   producer time visible separately as ``data/produce_s`` and the consumer
   wait as ``data/prefetch_stall_s``.

Determinism is untouched: epoch order lives entirely in
``GraphLoader._order()`` (seeded permutation), the shard format round-trips
arrays bitwise (npz is lossless), and the prefetch queue is strictly FIFO —
so a streamed, prefetched epoch is bitwise-identical to the in-memory
blocking epoch (tests/test_stream.py asserts this end to end).
"""

from __future__ import annotations

import collections
import json
import os
import queue
import threading
import time
import zlib
from typing import Callable, Optional, Sequence

import numpy as np

from distegnn_tpu import obs
from distegnn_tpu.data.loader import (
    GraphDataset, _read_with_retry, stall_attribution,
)
from distegnn_tpu.obs.jaxprobe import TransferMeter

FORMAT = "distegnn-shards-v1"
MANIFEST = "manifest.json"

# graph-dict fields along the node axis / edge axis / per-graph, in the order
# they are concatenated into a shard. Optional fields must be uniformly
# present or absent across the WHOLE dataset (the loaders' static-shape
# contract: one pytree structure per run).
_NODE_FIELDS = ("node_feat", "node_attr", "loc", "vel", "target")
_EDGE_FIELDS = ("edge_attr",)
_OPTIONAL = frozenset({"node_attr", "target", "edge_attr"})


class ShardChecksumError(RuntimeError):
    """A shard's bytes do not match the manifest CRC32 (bit rot, torn write,
    or a partially-synced copy). Retried a bounded number of times — a
    transient short read off NFS heals; persistent corruption propagates."""


class PrefetchCrashError(RuntimeError):
    """The prefetch producer thread died. The original exception is chained
    as ``__cause__`` — the trainer gets a typed, immediate failure instead of
    a silent hang on an empty queue."""


def _crc32(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def _check_uniform_fields(graphs: Sequence[dict]):
    """Which optional fields are present — uniformly, or it's an error."""
    present = {}
    for name in _NODE_FIELDS + _EDGE_FIELDS:
        if name in _OPTIONAL:
            have = [g.get(name) is not None for g in graphs]
            if any(have) and not all(have):
                raise ValueError(
                    f"write_shards: field {name!r} present in some graphs but "
                    "not others; the static-shape loaders need one schema for "
                    "the whole dataset")
            present[name] = bool(have and have[0])
        else:
            present[name] = True
    return present


def write_shards(graphs: Sequence[dict], out_dir: str, shard_size: int = 64,
                 node_order: str = "none") -> dict:
    """Write ``graphs`` as ``out_dir/shard_%05d.npz`` + ``manifest.json``.

    Shard schema (fixed): ``node_ptr``/``edge_ptr`` int64 prefix offsets over
    the shard's graphs, node-axis fields concatenated on axis 0, edge fields
    on their edge axis (``edge_index`` is [2, Etot] with LOCAL per-graph node
    ids — slicing by ``edge_ptr`` recovers each graph exactly), ``loc_mean``
    stacked [g, 3]. Writes are atomic (tmp + rename) and each shard's CRC32
    goes in the manifest so a torn read is detected at load, not at loss=NaN.

    Returns the manifest dict.
    """
    if shard_size < 1:
        raise ValueError(f"write_shards: shard_size must be >= 1, got {shard_size}")
    graphs = list(graphs)
    if not graphs:
        raise ValueError("write_shards: empty dataset")
    if node_order == "morton":
        from distegnn_tpu.ops.order import morton_reorder_graph

        graphs = [morton_reorder_graph(g) for g in graphs]
    elif node_order not in ("none", None):
        raise ValueError(f"write_shards: unknown node_order {node_order!r}")
    present = _check_uniform_fields(graphs)
    os.makedirs(out_dir, exist_ok=True)

    shards = []
    for s0 in range(0, len(graphs), shard_size):
        chunk = graphs[s0:s0 + shard_size]
        arrays = {
            "node_ptr": np.cumsum(
                [0] + [g["loc"].shape[0] for g in chunk], dtype=np.int64),
            "edge_ptr": np.cumsum(
                [0] + [g["edge_index"].shape[1] for g in chunk], dtype=np.int64),
            "edge_index": np.concatenate(
                [g["edge_index"] for g in chunk], axis=1),
            "loc_mean": np.stack(
                [g["loc_mean"] if g.get("loc_mean") is not None
                 else g["loc"].mean(axis=0) for g in chunk], axis=0),
        }
        for name in _NODE_FIELDS:
            if present[name]:
                arrays[name] = np.concatenate([g[name] for g in chunk], axis=0)
        for name in _EDGE_FIELDS:
            if present[name]:
                arrays[name] = np.concatenate([g[name] for g in chunk], axis=0)
        import io

        buf = io.BytesIO()
        np.savez(buf, **arrays)
        payload = buf.getvalue()
        fname = f"shard_{len(shards):05d}.npz"
        tmp = os.path.join(out_dir, fname + ".tmp")
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(out_dir, fname))
        shards.append({
            "file": fname,
            "n_graphs": len(chunk),
            "max_nodes": max(g["loc"].shape[0] for g in chunk),
            "max_edges": max(g["edge_index"].shape[1] for g in chunk),
            "crc32": _crc32(payload),
            "bytes": len(payload),
        })

    manifest = {
        "format": FORMAT,
        "n_graphs": len(graphs),
        "shard_size": shard_size,
        "node_order": node_order or "none",
        "fields": present,
        "max_nodes": max(s["max_nodes"] for s in shards),
        "max_edges": max(s["max_edges"] for s in shards),
        "shards": shards,
    }
    tmp = os.path.join(out_dir, MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(out_dir, MANIFEST))
    obs.log(f"write_shards: {len(graphs)} graphs -> {len(shards)} shards in "
            f"{out_dir} ({sum(s['bytes'] for s in shards) / 2**20:.1f} MiB)")
    return manifest


def is_shard_dir(path) -> bool:
    return (isinstance(path, str) and os.path.isdir(path)
            and os.path.exists(os.path.join(path, MANIFEST)))


class StreamedGraphDataset:
    """Out-of-core ``GraphDataset`` drop-in over a :func:`write_shards`
    directory: same ``__len__``/``__getitem__``/``size_maxima`` protocol, so
    ``GraphLoader``/``ShardedGraphLoader`` (and their dataset-wide blocking /
    degree scans) work unchanged — but only ``cache_shards`` decoded shards
    are resident at any time (LRU), keeping host RSS bounded regardless of
    dataset size.

    Honest residency note: npz members are zip-compressed streams, so shards
    cannot be OS-mmapped page-by-page; a shard's arrays are materialized when
    it enters the cache (one sequential read + CRC32 verify, O(shard) not
    O(dataset)) and every ``__getitem__`` serves zero-copy views into those
    arrays. The LRU bound — not mmap — is what keeps RSS flat.
    """

    def __init__(self, shard_dir: str, node_order: str = "none",
                 cache_shards: int = 4, verify: bool = True):
        if cache_shards < 1:
            raise ValueError(
                f"StreamedGraphDataset: cache_shards must be >= 1, got {cache_shards}")
        self.shard_dir = shard_dir
        self.cache_shards = cache_shards
        self.verify = verify
        self.manifest = _read_with_retry(
            os.path.join(shard_dir, MANIFEST),
            lambda f: json.loads(f.read().decode("utf-8")),
            what="manifest")
        if self.manifest.get("format") != FORMAT:
            raise ValueError(
                f"StreamedGraphDataset: {shard_dir} manifest format "
                f"{self.manifest.get('format')!r} != {FORMAT!r}")
        if node_order in ("none", None):
            self._reorder = None
        elif node_order == "morton":
            if self.manifest.get("node_order") == "morton":
                # already baked into the shards at write time — don't pay a
                # per-access reorder for an identity permutation
                self._reorder = None
            else:
                from distegnn_tpu.ops.order import morton_reorder_graph

                self._reorder = morton_reorder_graph
        else:
            raise ValueError(
                f"StreamedGraphDataset: unknown node_order {node_order!r}")
        self._starts = np.cumsum(
            [0] + [s["n_graphs"] for s in self.manifest["shards"]])
        self._cache = collections.OrderedDict()  # shard idx -> dict of arrays
        self._cache_bytes = 0
        self._host_gauge = obs.get_registry().gauge("data/host_bytes")

    def __len__(self) -> int:
        return int(self.manifest["n_graphs"])

    @property
    def open_shards(self) -> int:
        """Decoded shards currently resident (the RSS proxy tests bound)."""
        return len(self._cache)

    def size_maxima(self):
        return int(self.manifest["max_nodes"]), int(self.manifest["max_edges"])

    def _load_shard(self, si: int) -> dict:
        meta = self.manifest["shards"][si]
        path = os.path.join(self.shard_dir, meta["file"])

        def _reader(f):
            payload = f.read()
            if self.verify and _crc32(payload) != meta["crc32"]:
                raise ShardChecksumError(
                    f"{path}: crc32 {_crc32(payload):#010x} != manifest "
                    f"{meta['crc32']:#010x} ({len(payload)} bytes read, "
                    f"{meta['bytes']} expected)")
            import io

            with np.load(io.BytesIO(payload)) as z:
                return {k: z[k] for k in z.files}

        # a short/torn read shows up as a CRC mismatch — retryable; a shard
        # corrupted the same way on every attempt still fails hard
        return _read_with_retry(path, _reader, what="shard",
                                retry_on=(ShardChecksumError,))

    def _shard(self, si: int) -> dict:
        hit = self._cache.get(si)
        if hit is not None:
            self._cache.move_to_end(si)
            return hit
        arrays = self._load_shard(si)
        nbytes = sum(a.nbytes for a in arrays.values())
        self._cache[si] = arrays
        self._cache_bytes += nbytes
        while len(self._cache) > self.cache_shards:
            _, old = self._cache.popitem(last=False)
            self._cache_bytes -= sum(a.nbytes for a in old.values())
        self._host_gauge.set(self._cache_bytes)
        return arrays

    def __getitem__(self, i: int) -> dict:
        if i < 0:
            i += len(self)
        if not 0 <= i < len(self):
            raise IndexError(f"graph index {i} out of range [0, {len(self)})")
        si = int(np.searchsorted(self._starts, i, side="right")) - 1
        l = i - int(self._starts[si])
        sh = self._shard(si)
        n0, n1 = int(sh["node_ptr"][l]), int(sh["node_ptr"][l + 1])
        e0, e1 = int(sh["edge_ptr"][l]), int(sh["edge_ptr"][l + 1])
        fields = self.manifest["fields"]
        g = {
            "edge_index": sh["edge_index"][:, e0:e1],
            "loc_mean": sh["loc_mean"][l],
        }
        for name in _NODE_FIELDS:
            g[name] = sh[name][n0:n1] if fields.get(name) else None
        for name in _EDGE_FIELDS:
            g[name] = sh[name][e0:e1] if fields.get(name) else None
        if self._reorder is not None:
            g = self._reorder(g)
        return g


def open_dataset(source, node_order: str = "none", cache_shards: int = 4):
    """One constructor for both residency models: a :func:`write_shards`
    directory streams (:class:`StreamedGraphDataset`); a pickle path or
    in-memory list materializes (:class:`GraphDataset`). launch.py routes
    every dataset path through here, so switching a run out-of-core is a
    data-path change, not a code change."""
    if is_shard_dir(source):
        return StreamedGraphDataset(source, node_order=node_order,
                                    cache_shards=cache_shards)
    return GraphDataset(source, node_order=node_order)


class PrefetchLoader:
    """Async replacement for the blocking put-wrapper (`_PuttingLoader`): a
    bounded background thread runs the inner loader's disk read + collate +
    host→device ``put`` up to ``depth`` batches ahead, overlapping the
    previous step's compute.

    Accounting contract (trainer reads per-step deltas of ``data/stall_s``):
    the producer thread runs under ``stall_attribution("data/produce_s")`` so
    the overlapped collate work no longer pollutes the stall counter; only
    the consumer's real wait on the queue lands on ``data/stall_s`` (and,
    disaggregated, ``data/prefetch_stall_s``). ``data/prefetch_depth`` gauge
    reports the configured depth. ``depth=0`` degrades to the old fully
    synchronous behavior (useful for A/B: bench.py --layout io runs both).

    Failure contract: a producer crash propagates as
    :class:`PrefetchCrashError` (original chained as ``__cause__``) on the
    consumer's next ``__next__`` — never a hang. Abandoning iteration
    mid-epoch stops and joins the thread (generator ``finally``).
    """

    def __init__(self, loader, put: Optional[Callable] = None, depth: int = 2):
        if depth < 0:
            raise ValueError(f"PrefetchLoader: depth must be >= 0, got {depth}")
        self.loader, self.put, self.depth = loader, put, depth
        self._meter = TransferMeter()

    def set_epoch(self, epoch: int) -> None:
        self.loader.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.loader)

    def _produce_one(self, batch):
        self._meter.h2d(batch)
        return self.put(batch) if self.put is not None else batch

    def __iter__(self):
        reg = obs.get_registry()
        reg.gauge("data/prefetch_depth").set(self.depth)
        if self.depth == 0:
            # synchronous path: put time is trainer stall by definition
            stall = reg.counter("data/stall_s")
            for batch in self.loader:
                t0 = time.perf_counter()
                out = self._produce_one(batch)
                stall.add(time.perf_counter() - t0)
                yield out
            return

        q = queue.Queue(maxsize=self.depth)
        stop = threading.Event()

        def _offer(msg) -> bool:
            # bounded-queue put that never deadlocks a dead consumer: give up
            # as soon as the consumer signalled stop
            while not stop.is_set():
                try:
                    q.put(msg, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def _producer():
            try:
                with stall_attribution("data/produce_s"):
                    for batch in self.loader:
                        if not _offer(("item", self._produce_one(batch))):
                            return
                _offer(("done", None))
            except BaseException as e:  # must reach the consumer, whatever it is
                _offer(("err", e))

        t = threading.Thread(target=_producer, daemon=True,
                             name="distegnn-prefetch")
        t.start()
        stall = reg.counter("data/stall_s")
        pf_stall = reg.counter("data/prefetch_stall_s")
        try:
            while True:
                t0 = time.perf_counter()
                while True:
                    try:
                        kind, val = q.get(timeout=1.0)
                        break
                    except queue.Empty:
                        if not t.is_alive():
                            raise PrefetchCrashError(
                                "prefetch producer thread died without "
                                "reporting (queue empty, thread dead)")
                waited = time.perf_counter() - t0
                stall.add(waited)
                pf_stall.add(waited)
                if kind == "done":
                    return
                if kind == "err":
                    raise PrefetchCrashError(
                        f"prefetch producer crashed: {val!r}") from val
                yield val
        finally:
            stop.set()
            while True:  # unblock a producer parked on a full queue
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=10.0)
