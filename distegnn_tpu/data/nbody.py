"""N-body preprocessing pipeline (reference process_nbody_cutoff,
datasets/process_dataset.py:61-125): load raw trajectory .npy files, pick
(frame_0 -> frame_T) prediction pairs, build (radius or full) graphs with the
edge cutoff, cache to disk keyed by every parameter.

Graphs are plain numpy dicts (the schema pad_graphs consumes); serialized
lists are pickled (the reference torch.save()s PyG Data lists,
process_dataset.py:114-115)."""

from __future__ import annotations

import glob
import os
import pickle
from typing import List, Optional

import numpy as np

from distegnn_tpu.ops.radius import cutoff_edges_np, full_graph_np, radius_graph_np


def build_nbody_graph(
    loc: np.ndarray,
    vel: np.ndarray,
    charges: np.ndarray,
    target: Optional[np.ndarray],
    radius: float = -1.0,
    cutoff_rate: float = 0.0,
    with_edges: bool = True,
) -> dict:
    """One sample -> graph dict (reference process_key,
    process_dataset.py:90-115): full graph when radius == -1 else radius
    graph; drop the longest cutoff_rate fraction; edge_attr = distance
    duplicated to 2 channels; node_feat = [|v|, q / max q]; node_attr = q;
    loc_mean = mean position (the virtual-node seed).

    with_edges=False skips edge construction (empty edge list) — for
    distribute mode, which drops whole-graph edges and rebuilds per-partition
    inner_radius edges anyway (building the O(n^2) full set would be waste)."""
    loc = np.asarray(loc, np.float32)
    vel = np.asarray(vel, np.float32)
    charges = np.asarray(charges, np.float32)
    n = loc.shape[0]

    if with_edges:
        edge_index = full_graph_np(n) if radius == -1 else radius_graph_np(loc, radius)
        edge_index = cutoff_edges_np(edge_index, loc, cutoff_rate)
    else:
        edge_index = np.zeros((2, 0), np.int64)
    dist = np.linalg.norm(loc[edge_index[0]] - loc[edge_index[1]], axis=1)
    edge_attr = np.repeat(dist[:, None], 2, axis=1).astype(np.float32)

    speed = np.linalg.norm(vel, axis=1, keepdims=True)
    node_feat = np.concatenate([speed, charges / charges.max()], axis=1).astype(np.float32)

    return {
        "node_feat": node_feat,
        "node_attr": charges,
        "loc": loc,
        "vel": vel,
        "target": None if target is None else np.asarray(target, np.float32),
        "loc_mean": loc.mean(axis=0),
        "edge_index": edge_index.astype(np.int32),
        "edge_attr": edge_attr,
    }


def _find_tag(base: str, split: str) -> str:
    hits = sorted(glob.glob(os.path.join(base, f"loc_{split}_*.npy")))
    if not hits:
        raise FileNotFoundError(f"no loc_{split}_*.npy under {base} — run scripts/generate_nbody.py first")
    name = os.path.basename(hits[0])
    return name[len(f"loc_{split}_"):-len(".npy")]


def process_nbody_cutoff(
    data_dir: str,
    dataset_name: str,
    max_samples: int,
    radius: float,
    frame_0: int,
    frame_T: int,
    cutoff_rate: float,
    tag: Optional[str] = None,
) -> List[str]:
    """Process train/valid/test splits; returns the three processed file paths.
    Cached: an existing file (same parameter key in its name) is reused
    untouched (reference process_dataset.py:66-72)."""
    base = os.path.join(data_dir, dataset_name)
    processed_dir = os.path.join(base, "processed")
    os.makedirs(processed_dir, exist_ok=True)

    paths = []
    for split in ("train", "valid", "test"):
        out = os.path.join(
            processed_dir,
            f"{dataset_name}_{split}_{radius}_{cutoff_rate:.3f}_{max_samples}_{frame_0}_{frame_T}.pkl",
        )
        paths.append(out)
        if os.path.exists(out):
            continue

        t = tag if tag is not None else _find_tag(base, split)
        loc = np.load(os.path.join(base, f"loc_{split}_{t}.npy"))[:max_samples]
        vel = np.load(os.path.join(base, f"vel_{split}_{t}.npy"))[:max_samples]
        charges = np.load(os.path.join(base, f"charges_{split}_{t}.npy"))[:max_samples]

        graphs = [
            build_nbody_graph(
                loc[k, frame_0], vel[k, frame_0], charges[k], loc[k, frame_T],
                radius=radius, cutoff_rate=cutoff_rate,
            )
            for k in range(loc.shape[0])
        ]
        with open(out, "wb") as f:
            pickle.dump(graphs, f, protocol=pickle.HIGHEST_PROTOCOL)
    return paths
