"""Protein (AdK equilibrium) pipeline (reference process_protein_cutoff,
datasets/process_dataset.py:128-222).

Two stages, split so the heavy native dependency is isolated:
  1. extract_adk_npz — fetch the MDAnalysisData AdK trajectory, select
     backbone (or all) atoms, dump positions [T, N, 3] + charges [N] into one
     npz cache. Requires MDAnalysis/MDAnalysisData (gated import: absent in
     this image — run this stage wherever those are installed, or place the
     npz directly; the reference has the same implicit requirement).
  2. process_protein_cutoff — pure numpy from the npz: per frame t,
     vel = pos[t+1] - pos[t], target = pos[t+delta_t]; contact-matrix edges
     at ``radius`` Angstrom (the reference's scipy contact_matrix == a radius
     graph); fixed split 2481/827/863; optional test-split rotation /
     translation injection (test_rot/test_trans — the reference's empirical
     equivariance eval, process_dataset.py:162-174)."""

from __future__ import annotations

import os
import pickle
from typing import List

import numpy as np

from distegnn_tpu.ops.radius import cutoff_edges_np, radius_graph_np
from distegnn_tpu.utils.rotate import random_rotate

TRAIN_VALID_TEST = {"train": (0, 2481), "valid": (2481, 3308), "test": (3308, 4171)}
NPZ_NAME = "adk_{sel}.npz"


def extract_adk_npz(data_dir: str, backbone: bool = True) -> str:
    """Stage 1: MDAnalysis fetch + selection -> npz cache. Returns the path."""
    sel = "backbone" if backbone else "all"
    out = os.path.join(data_dir, NPZ_NAME.format(sel=sel))
    if os.path.exists(out):
        return out
    try:
        import MDAnalysis
        import MDAnalysisData
    except ImportError as e:
        raise NotImplementedError(
            f"protein extraction needs MDAnalysis/MDAnalysisData (not in this "
            f"image). Run extract_adk_npz where they are available, or place "
            f"{out} (positions [T,N,3] float32, charges [N] float32) manually."
        ) from e

    adk = MDAnalysisData.datasets.fetch_adk_equilibrium(data_home=data_dir)
    u = MDAnalysis.Universe(adk.topology, adk.trajectory)
    ag = u.select_atoms("backbone") if backbone else u.atoms
    charges = np.asarray(u.atoms[ag.ix].charges, np.float32)
    positions = np.stack([ts.positions[ag.ix].copy() for ts in u.trajectory]
                         ).astype(np.float32)
    # box dimensions scale the test_trans injection (reference
    # process_dataset.py:173 uses ts.dimensions[:3] / 2)
    dims = np.asarray(u.dimensions[:3], np.float32) if u.dimensions is not None else None
    if dims is not None:
        np.savez_compressed(out, positions=positions, charges=charges, dimensions=dims)
    else:
        np.savez_compressed(out, positions=positions, charges=charges)
    return out


def build_protein_graph(loc_0, vel_0, charges, target, radius: float,
                        cutoff_rate: float) -> dict:
    loc_0 = np.asarray(loc_0, np.float32)
    charges = np.asarray(charges, np.float32).reshape(-1, 1)
    edge_index = radius_graph_np(loc_0, radius)
    edge_index = cutoff_edges_np(edge_index, loc_0, cutoff_rate)
    dist = np.linalg.norm(loc_0[edge_index[0]] - loc_0[edge_index[1]], axis=1)
    speed = np.linalg.norm(vel_0, axis=1, keepdims=True)
    node_feat = np.concatenate([speed, charges / charges.max()], axis=1)
    return {
        "node_feat": node_feat.astype(np.float32),
        "node_attr": charges,
        "loc": loc_0,
        "vel": np.asarray(vel_0, np.float32),
        "target": np.asarray(target, np.float32),
        "loc_mean": loc_0.mean(axis=0),
        "edge_index": edge_index.astype(np.int32),
        "edge_attr": np.repeat(dist[:, None], 2, axis=1).astype(np.float32),
    }


def process_protein_cutoff(data_dir: str, dataset_name: str, max_samples: int,
                           radius: float, delta_t: int, cutoff_rate: float,
                           backbone: bool = True, test_rot: bool = False,
                           test_trans: bool = False, seed: int = 0) -> List[str]:
    base = os.path.join(data_dir, dataset_name)
    processed_dir = os.path.join(base, "processed")
    os.makedirs(processed_dir, exist_ok=True)

    npz_path = os.path.join(base, NPZ_NAME.format(sel="backbone" if backbone else "all"))
    if not os.path.exists(npz_path):
        npz_path = extract_adk_npz(base, backbone=backbone)
    data = np.load(npz_path)
    positions, charges = data["positions"], data["charges"]
    # translation scale: box dimensions when the npz carries them (reference
    # semantics), else the coordinate span as a fallback for bare npz caches
    trans_scale = (np.asarray(data["dimensions"], np.float32)
                   if "dimensions" in data.files
                   else np.abs(positions).max(axis=(0, 1)))
    rng = np.random.default_rng(seed)

    paths = []
    for split, (lo, hi) in TRAIN_VALID_TEST.items():
        out = os.path.join(
            processed_dir,
            f"{dataset_name}_{split}_{radius}_{cutoff_rate:.3f}_{max_samples}_{delta_t}"
            f"_rot{int(test_rot)}_trans{int(test_trans)}_s{seed}.pkl")
        paths.append(out)
        if os.path.exists(out):
            continue
        hi = min(hi, positions.shape[0] - delta_t - 1, lo + max_samples)
        graphs = []
        for t in range(lo, hi):
            loc_0 = positions[t]
            vel_0 = positions[t + 1] - loc_0
            target = positions[t + delta_t]
            if split == "test" and test_rot:
                R = random_rotate(rng).astype(np.float32)
                loc_0, vel_0, target = loc_0 @ R, vel_0 @ R, target @ R
            if split == "test" and test_trans:
                tr = (rng.standard_normal(3) * trans_scale / 2).astype(np.float32)
                loc_0, target = loc_0 + tr, target + tr
            graphs.append(build_protein_graph(loc_0, vel_0, charges, target,
                                              radius, cutoff_rate))
        with open(out, "wb") as f:
            pickle.dump(graphs, f, protocol=pickle.HIGHEST_PROTOCOL)
    return paths
