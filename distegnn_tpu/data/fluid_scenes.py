"""Fluid113K offline scene generation — the in-tree port of the reference's
SPlisHSPlasH pipeline (dataset_generation/Fluid113K/create_physics_scenes.py
:1-497 and create_physics_records.py:1-148).

The reference synthesizes random fluid scenes (randomly rotated/scaled fluid
volumes dropped into a box, random viscosity/density), writes a SPlisHSPlasH
scene description (JSON + bgeo particle files), runs the external
``DynamicBoundarySimulator`` C++ binary, and packs the exported frames into
the ``sim_XXXX_YY.msgpack.zst`` shards the training pipeline reads. This
module reproduces that flow with two deliberate re-designs for a
dependency-light TPU host:

- mesh volume/surface sampling is done in-tree with numpy (parity ray casts
  and area-weighted surface draws) instead of the ``VolumeSampling`` binary
  and open3d Poisson-disk sampling (create_physics_scenes.py:120-145);
- the O(grid^3 * window^3) Python placement scan
  (find_valid_fluid_start_positions, create_physics_scenes.py:183-224) is an
  FFT cross-correlation plus a first-valid-per-column reduction.

Only the physics simulation itself stays external: ``run_simulator`` drives
any SPlisHSPlasH build via subprocess exactly like the reference
(create_physics_scenes.py:225-231); without the binary the synthesized scene
directories are still complete and portable to a machine that has one.
"""

from __future__ import annotations

import copy
import json
import os
import subprocess
from typing import Dict, List, Optional, Tuple

import numpy as np

from distegnn_tpu.data.bgeo import (list_partio_frames, numpy_from_bgeo,
                                    write_bgeo_from_numpy)

PARTICLE_RADIUS = 0.025
MAX_FLUID_START_VELOCITY_XZ = 4.0
MAX_FLUID_START_VELOCITY_Y = 1.0

# SPlisHSPlasH scene-file parameter blocks (simulator API configuration;
# values per reference create_physics_scenes.py:36-90).
DEFAULT_CONFIGURATION = {
    "pause": False, "stopAt": 4.0, "particleRadius": 0.025,
    "numberOfStepsPerRenderUpdate": 1, "density0": 1000, "simulationMethod": 4,
    "gravitation": [0, -9.81, 0], "cflMethod": 0, "cflFactor": 1,
    "cflMaxTimeStepSize": 0.005, "maxIterations": 100, "maxError": 0.01,
    "maxIterationsV": 100, "maxErrorV": 0.1, "stiffness": 50000, "exponent": 7,
    "velocityUpdateMethod": 0, "enableDivergenceSolver": True,
    "enablePartioExport": True, "enableRigidBodyExport": True,
    "particleFPS": 50.0, "partioAttributes": "density;velocity",
}
DEFAULT_SIMULATION = {"contactTolerance": 0.0125}
DEFAULT_FLUID = {
    "surfaceTension": 0.2, "surfaceTensionMethod": 0, "viscosity": 0.01,
    "viscosityMethod": 3, "viscoMaxIter": 200, "viscoMaxError": 0.05,
}
DEFAULT_RIGIDBODY = {
    "translation": [0, 0, 0], "rotationAxis": [0, 1, 0], "rotationAngle": 0,
    "scale": [1.0, 1.0, 1.0], "color": [0.1, 0.4, 0.6, 1.0], "isDynamic": False,
    "isWall": True, "restitution": 0.6, "friction": 0.0,
    "collisionObjectType": 5, "collisionObjectScale": [1.0, 1.0, 1.0],
    "invertSDF": True,
}


# ---------------------------------------------------------------- meshes ---

def box_mesh(size=(5.0, 10.0, 5.0), base_y: float = 0.0):
    """Axis-aligned box triangle mesh: the reference's Box.obj is a 5x10x5
    container with its floor at y=0, Fluid.obj a 2.5^3 cube about the origin
    (dataset_generation/Fluid113K/models/)."""
    sx, sy, sz = size
    xs, ys, zs = (-sx / 2, sx / 2), (base_y, base_y + sy), (-sz / 2, sz / 2)
    verts = np.array([[x, y, z] for x in xs for y in ys for z in zs], np.float64)
    # 12 triangles, outward-facing winding
    quads = [(0, 1, 3, 2), (4, 6, 7, 5),  # x- x+
             (0, 4, 5, 1), (2, 3, 7, 6),  # z- z+  (indices: bit order x,y,z)
             (0, 2, 6, 4), (1, 5, 7, 3)]  # y- y+
    tris = []
    for a, b, c, d in quads:
        tris += [(a, b, c), (a, c, d)]
    return verts, np.array(tris, np.int32)


def fluid_mesh():
    return box_mesh(size=(2.5, 2.5, 2.5), base_y=-1.25)


def load_obj(path: str):
    """Minimal OBJ reader (v/f lines, fan-triangulated) so user meshes can
    replace the procedural defaults."""
    verts, tris = [], []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            if parts[0] == "v":
                verts.append([float(x) for x in parts[1:4]])
            elif parts[0] == "f":
                idx = [int(p.split("/")[0]) - 1 for p in parts[1:]]
                for i in range(1, len(idx) - 1):
                    tris.append((idx[0], idx[i], idx[i + 1]))
    return np.asarray(verts, np.float64), np.asarray(tris, np.int32)


def write_obj(path: str, verts: np.ndarray, tris: np.ndarray) -> None:
    with open(path, "w") as f:
        for v in verts:
            f.write(f"v {v[0]:.6f} {v[1]:.6f} {v[2]:.6f}\n")
        for t in tris:
            f.write(f"f {t[0] + 1} {t[1] + 1} {t[2] + 1}\n")


def _triangle_geometry(verts, tris):
    a, b, c = verts[tris[:, 0]], verts[tris[:, 1]], verts[tris[:, 2]]
    cross = np.cross(b - a, c - a)
    area2 = np.linalg.norm(cross, axis=1)
    normals = cross / np.maximum(area2, 1e-30)[:, None]
    return a, b, c, area2 / 2.0, normals


def points_inside_mesh(points: np.ndarray, verts: np.ndarray,
                       tris: np.ndarray) -> np.ndarray:
    """Parity test: count +x ray/triangle crossings (vectorized
    Moller-Trumbore) — replaces the external VolumeSampling binary's inside
    test for watertight meshes."""
    rng = np.random.default_rng(0)
    d = np.array([1.0, 0.0, 0.0]) + rng.normal(scale=1e-4, size=3)  # dodge edges
    d /= np.linalg.norm(d)
    a, b, c, _, _ = _triangle_geometry(verts, tris)
    e1, e2 = b - a, c - a                                      # [T,3]
    pvec = np.cross(d, e2)                                     # [T,3]
    det = np.einsum("tk,tk->t", e1, pvec)                      # [T]
    ok = np.abs(det) > 1e-12
    inv = np.where(ok, 1.0 / np.where(ok, det, 1.0), 0.0)
    hits = np.zeros(points.shape[0], np.int64)
    for t in np.nonzero(ok)[0]:                                # few triangles
        tvec = points - a[t]
        u = tvec @ pvec[t] * inv[t]
        qvec = np.cross(tvec, e1[t])
        v = qvec @ d * inv[t]
        w = qvec @ e2[t] * inv[t]
        hits += ((u >= 0) & (v >= 0) & (u + v <= 1) & (w > 0)).astype(np.int64)
    return hits % 2 == 1


def sample_volume(verts: np.ndarray, tris: np.ndarray, scale: float = 1.0,
                  radius: float = PARTICLE_RADIUS) -> np.ndarray:
    """Particles on a 2r grid filling the (scaled) mesh interior — the role
    of ``obj_volume_to_particles`` (create_physics_scenes.py:120-132)."""
    verts = verts * scale
    lo, hi = verts.min(0) + radius, verts.max(0) - radius
    axes = [np.arange(lo[k], hi[k] + 1e-9, 2 * radius) for k in range(3)]
    grid = np.stack(np.meshgrid(*axes, indexing="ij"), -1).reshape(-1, 3)
    return grid[points_inside_mesh(grid, verts, tris)].astype(np.float32)


def sample_surface(verts: np.ndarray, tris: np.ndarray,
                   radius: float = PARTICLE_RADIUS):
    """(points, inward_normals) covering the mesh surface at SPlisHSPlasH
    boundary density: 1.9 * area / (pi r^2) samples (the open3d Poisson-disk
    count, create_physics_scenes.py:134-145), drawn area-weighted and thinned
    on a hash grid to approximate the Poisson-disk spacing."""
    a, b, c, area, normals = _triangle_geometry(verts, tris)
    target = max(int(1.9 * area.sum() / (np.pi * radius**2)), 1)
    rng = np.random.default_rng(1)
    tri_idx = rng.choice(len(area), size=3 * target, p=area / area.sum())
    r1, r2 = rng.random(3 * target), rng.random(3 * target)
    flip = r1 + r2 > 1
    r1, r2 = np.where(flip, 1 - r1, r1), np.where(flip, 1 - r2, r2)
    pts = a[tri_idx] + r1[:, None] * (b - a)[tri_idx] + r2[:, None] * (c - a)[tri_idx]
    nrm = normals[tri_idx]

    spacing = np.sqrt(area.sum() / target) * 0.72
    cell = np.floor(pts / spacing).astype(np.int64)
    _, keep = np.unique(cell, axis=0, return_index=True)
    keep = np.sort(keep)[:target]
    return pts[keep].astype(np.float32), -nrm[keep].astype(np.float32)


def random_rotation_matrix(rng: np.random.Generator, strength: float = 1.0):
    """Uniform random rotation (Arvo's method, as the reference uses at
    create_physics_scenes.py:93-120)."""
    x = rng.random(3)
    theta, phi, z = x[0] * 2 * np.pi * strength, x[1] * 2 * np.pi, x[2] * strength
    r = np.sqrt(z)
    V = np.array([np.sin(phi) * r, np.cos(phi) * r, np.sqrt(2.0 - z)])
    st, ct = np.sin(theta), np.cos(theta)
    Rz = np.array([[ct, st, 0], [-st, ct, 0], [0, 0, 1]])
    return ((np.outer(V, V) - np.eye(3)) @ Rz).astype(np.float32)


# ---------------------------------------------------- placement rasters ---

def rasterize_points(points: np.ndarray, voxel_size: float,
                     particle_radius: float):
    """(grid_origin_index, voxel_size, occupancy) — each particle marks the
    voxels its 8 radius-offset corners land in (reference rasterize_points,
    create_physics_scenes.py:147-180)."""
    if not voxel_size > 2 * particle_radius:
        raise ValueError(f"voxel_size {voxel_size} must exceed 2*{particle_radius}")
    arr_min = np.floor_divide(points.min(0) - particle_radius, voxel_size).astype(np.int32)
    arr_max = np.floor_divide(points.max(0) + particle_radius, voxel_size).astype(np.int32) + 1
    arr = np.zeros(arr_max - arr_min, dtype=bool)
    for sx in (-1, 1):
        for sy in (-1, 1):
            for sz in (-1, 1):
                off = np.array([sx, sy, sz]) * particle_radius
                idx = np.floor_divide(points + off, voxel_size).astype(np.int32) - arr_min
                arr[idx[:, 0], idx[:, 1], idx[:, 2]] = True
    return arr_min, voxel_size, arr


def find_valid_fluid_start_positions(box_raster, fluid_raster,
                                     rng: np.random.Generator) -> np.ndarray:
    """Pick a random placement of the fluid occupancy inside the box's free
    space, preferring the lowest feasible y per column, and carve the chosen
    volume out of the free space (mutates ``box_raster``'s occupancy).
    Same contract as the reference's triple loop
    (create_physics_scenes.py:183-224), computed as one FFT correlation."""
    from scipy.signal import fftconvolve

    b_min, voxel, box = box_raster
    _, _, fluid = fluid_raster
    fs, bs = np.array(fluid.shape), np.array(box.shape)
    if np.any(fs > bs):
        raise ValueError("fluid volume larger than box free space")
    # window at p is feasible iff no fluid voxel overlaps a blocked voxel:
    # correlate blocked-space with the fluid mask and demand an exact zero
    overlap = fftconvolve((~box).astype(np.float32),
                          fluid[::-1, ::-1, ::-1].astype(np.float32), mode="valid")
    feasible = overlap < 0.5
    # keep only the lowest feasible y in each (x, z) column (reference keeps
    # idx where nothing below it in the column is feasible)
    lowest = np.zeros_like(feasible)
    first = np.argmax(feasible, axis=1)
    any_f = feasible.any(axis=1)
    ii, kk = np.nonzero(any_f)
    lowest[ii, first[ii, kk], kk] = True
    valid = np.stack(np.nonzero(lowest), axis=-1)
    if valid.shape[0] == 0:
        raise RuntimeError("no valid fluid start position")
    pos = valid[rng.integers(valid.shape[0])]
    sl = tuple(slice(p, p + s) for p, s in zip(pos, fs))
    box[sl] &= ~fluid
    return (pos + b_min).astype(np.float32) * voxel


# ------------------------------------------------------- scene synthesis ---

def synthesize_scene(output_dir: str, seed: int, *,
                     radius: float = PARTICLE_RADIUS,
                     num_objects: int = 0,
                     uniform_viscosity: bool = False,
                     log10_uniform_viscosity: bool = False,
                     default_viscosity: bool = False,
                     default_density: bool = False,
                     const_fluid_particles: int = 0,
                     max_fluid_particles: int = 0,
                     min_fluid_particles: int = 100_000,
                     box_size=(5.0, 10.0, 5.0),
                     fluid_size=(2.5, 2.5, 2.5)) -> str:
    """Create ``sim_{seed:04d}/`` with scene.json + box/fluid bgeo files —
    the full behavior of the reference's create_fluid_data
    (create_physics_scenes.py:233-437): 1-3 randomly rotated/scaled fluid
    volumes placed without overlap in the eroded free space of the box,
    exponential/uniform/log10 viscosity, density U(500, 2000), random start
    velocities, trimming to an exact particle budget when requested."""
    from scipy.ndimage import binary_erosion

    rng = np.random.default_rng(seed)
    n_obj = int(num_objects) if num_objects > 0 else int(rng.choice([1, 2, 3]))

    box_v, box_t = box_mesh(box_size)
    fl_v, fl_t = box_mesh(fluid_size, base_y=-fluid_size[1] / 2)
    bb_pts, bb_nrm = sample_surface(box_v, box_t, radius)
    bb_vol = sample_volume(box_v, box_t, radius=radius)

    b_min, voxel, occ = rasterize_points(
        np.concatenate([bb_vol, bb_pts], 0), 2.01 * radius, radius)
    occ = binary_erosion(occ, structure=np.ones((3, 3, 3)), iterations=3)
    box_raster = (b_min, voxel, occ)

    objects = []
    for _ in range(n_obj):
        for _attempt in range(10):
            try:
                fluid = sample_volume(fl_v, fl_t, scale=rng.uniform(0.90, 1.00),
                                      radius=radius)
                fluid = fluid @ random_rotation_matrix(rng)
                fl_raster = rasterize_points(fluid, 2.01 * radius, radius)
                sel = find_valid_fluid_start_positions(box_raster, fl_raster, rng)
                fluid = fluid + (sel - fl_raster[0] * fl_raster[1])

                vel = np.zeros_like(fluid)
                vel[:, 0] = rng.uniform(-MAX_FLUID_START_VELOCITY_XZ,
                                        MAX_FLUID_START_VELOCITY_XZ)
                vel[:, 2] = rng.uniform(-MAX_FLUID_START_VELOCITY_XZ,
                                        MAX_FLUID_START_VELOCITY_XZ)
                vel[:, 1] = rng.uniform(-MAX_FLUID_START_VELOCITY_Y,
                                        MAX_FLUID_START_VELOCITY_Y)

                density = 1000.0 if default_density else rng.uniform(500, 2000)
                if default_viscosity:
                    viscosity = 0.01
                elif uniform_viscosity:
                    viscosity = rng.uniform(0.01, 0.3)
                elif log10_uniform_viscosity:
                    viscosity = 0.01 * 10 ** rng.uniform(0.0, 1.5)
                else:
                    viscosity = rng.exponential(scale=1 / 20) + 0.01
                objects.append({"positions": fluid, "velocities": vel,
                                "density": float(density),
                                "viscosity": float(viscosity)})
                break
            except (RuntimeError, ValueError):
                continue

    def total():
        return sum(o["positions"].shape[0] for o in objects)

    if const_fluid_particles:
        if const_fluid_particles > total():
            raise RuntimeError(f"scene has {total()} < {const_fluid_particles} particles")
        while total() != const_fluid_particles:
            diff = total() - const_fluid_particles
            smallest = min(range(len(objects)),
                           key=lambda i: objects[i]["positions"].shape[0])
            if objects[smallest]["positions"].shape[0] < diff:
                del objects[smallest]
            else:
                for k in ("positions", "velocities"):
                    objects[smallest][k] = objects[smallest][k][:-diff]
    if max_fluid_particles and total() > max_fluid_particles:
        raise RuntimeError(f"scene has {total()} > {max_fluid_particles} particles")
    if total() < min_fluid_particles:
        raise RuntimeError(f"scene has only {total()} fluid particles")

    sim_dir = os.path.join(output_dir, f"sim_{seed:04d}")
    os.makedirs(sim_dir, exist_ok=False)

    scene = {"Configuration": dict(DEFAULT_CONFIGURATION,
                                   particleRadius=radius),
             "Simulation": dict(DEFAULT_SIMULATION),
             "RigidBodies": [], "FluidModels": []}

    write_bgeo_from_numpy(os.path.join(sim_dir, "box.bgeo"), bb_pts, bb_nrm)
    write_obj(os.path.join(sim_dir, "box.obj"), box_v, box_t)
    rigid = copy.deepcopy(DEFAULT_RIGIDBODY)
    rigid.update(id=1, geometryFile="box.obj", resolutionSDF=[64, 64, 64])
    scene["RigidBodies"].append(rigid)

    for i, obj in enumerate(objects):
        fid = f"fluid{i}"
        scene[fid] = dict(DEFAULT_FLUID, viscosity=obj["viscosity"],
                          density0=obj["density"])
        write_bgeo_from_numpy(os.path.join(sim_dir, f"{fid}.bgeo"),
                              obj["positions"], obj["velocities"])
        scene["FluidModels"].append({"translation": [0.0, 0.0, 0.0],
                                     "scale": [1.0, 1.0, 1.0], "id": fid,
                                     "particleFile": f"{fid}.bgeo"})

    with open(os.path.join(sim_dir, "scene.json"), "w") as f:
        json.dump(scene, f, indent=4)
    return sim_dir


def run_simulator(simulator_bin: str, scene_dir: str) -> int:
    """Drive an external SPlisHSPlasH DynamicBoundarySimulator on a scene
    directory (reference run_simulator, create_physics_scenes.py:225-231);
    frame exports land in ``<scene_dir>/partio/``."""
    scene = os.path.abspath(os.path.join(scene_dir, "scene.json"))
    proc = subprocess.run([simulator_bin, "--no-cache", "--no-gui",
                           "--no-initial-pause", "--output-dir",
                           os.path.abspath(scene_dir), scene])
    return proc.returncode


# --------------------------------------------------------- record packing ---

def pack_scene_records(scene_dir: str, scene_id: str, out_prefix: str,
                       splits: int = 16,
                       radius: float = PARTICLE_RADIUS) -> List[str]:
    """Partio frame exports -> ``<out_prefix>_YY.msgpack.zst`` shards in the
    training format (reference create_scene_files,
    create_physics_records.py:14-97): frames split evenly over ``splits``
    files; the box surface only on each shard's first frame; per-particle
    mass = density0 * (2r)^3; particles id-sorted for cross-frame stability."""
    import msgpack
    import zstandard as zstd

    with open(os.path.join(scene_dir, "scene.json")) as f:
        scene = json.load(f)
    box, box_normals = numpy_from_bgeo(os.path.join(scene_dir, "box.bgeo"))
    frames_by_fluid = list_partio_frames(os.path.join(scene_dir, "partio"))
    if not frames_by_fluid:
        raise FileNotFoundError(f"no partio exports under {scene_dir}/partio "
                                "(run the simulator first)")
    counts = {len(v) for v in frames_by_fluid.values()}
    if len(counts) != 1:
        raise ValueError(f"fluids exported different frame counts: {counts}")

    def encode_np(o):
        if isinstance(o, np.ndarray):
            return {b"nd": True, b"type": o.dtype.str.encode(),
                    b"shape": list(o.shape), b"data": o.tobytes()}
        return o

    n_frames = counts.pop()
    sublists = np.array_split(np.arange(n_frames), splits)
    cctx = zstd.ZstdCompressor(level=22)
    written = []
    for s, sub in enumerate(sublists):
        out_path = f"{out_prefix}_{s:02d}.msgpack.zst"
        written.append(out_path)
        if os.path.isfile(out_path):
            continue
        data = []
        for frame_i in sub:
            feat: Dict = {}
            if frame_i == sub[0]:
                feat["box"] = box.astype(np.float32)
                feat["box_normals"] = box_normals.astype(np.float32)
            feat["frame_id"] = int(frame_i)
            feat["scene_id"] = scene_id
            pos, vel, mass, visc = [], [], [], []
            for fid, paths in frames_by_fluid.items():
                p, v = numpy_from_bgeo(paths[frame_i])
                pos.append(p)
                vel.append(v if v is not None else np.zeros_like(p))
                visc.append(np.full(p.shape[0], scene[fid]["viscosity"], np.float32))
                mass.append(np.full(p.shape[0], scene[fid]["density0"], np.float32))
            feat["pos"] = np.concatenate(pos, 0).astype(np.float32)
            feat["vel"] = np.concatenate(vel, 0).astype(np.float32)
            feat["m"] = (np.concatenate(mass, 0) * (2 * radius) ** 3).astype(np.float32)
            feat["viscosity"] = np.concatenate(visc, 0).astype(np.float32)
            data.append(feat)
        with open(out_path, "wb") as f:
            f.write(cctx.compress(msgpack.packb(data, default=encode_np)))
    return written
