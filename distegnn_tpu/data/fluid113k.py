"""Fluid113K (LargeFluid) pipeline (reference process_large_fluid_dist,
datasets/process_dataset.py:441-578).

Input: SPlisHSPlasH scenes packed as 16 zstd+msgpack shards per simulation
(``sim_XXXX_YY.msgpack.zst``; each frame dict has 'pos', 'vel', and scene
constants 'viscosity', 'm' — written by
dataset_generation/Fluid113K/create_physics_records.py with msgpack-numpy).
Simulation splits: train 1-100, valid 101-120, test 121-140; 16 random frames
from the first 50 per sim; node_attr = [viscosity, mass],
node_feat = [viscosity, mass, |v|] (3 features — largefluid config's
node_feat_nf=3/node_attr_nf=2).

msgpack-numpy's array encoding is decoded with a local hook (the library
isn't in this image): {b'nd': True, b'type': .., b'shape': .., b'data': ..}
-> np.ndarray."""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from distegnn_tpu.data.distribute import write_partitioned_split
from distegnn_tpu.data.water3d import _split_seed

SIM_SPLITS = {"train": (1, 101), "valid": (101, 121), "test": (121, 141)}
SHARDS_PER_SIM = 16
FRAMES_PER_SIM = 16
FRAME_RANGE = 50


def _mn_decode(obj):
    """msgpack-numpy decode hook (format of msgpack_numpy.encode)."""
    if isinstance(obj, dict):
        if obj.get(b"nd") is True:
            return np.frombuffer(obj[b"data"], dtype=np.dtype(obj[b"type"].decode())
                                 ).reshape(obj[b"shape"])
        if obj.get("nd") is True:
            return np.frombuffer(obj["data"], dtype=np.dtype(obj["type"])
                                 ).reshape(obj["shape"])
    return obj


def read_sim(data_dir: str, dataset_name: str, idx: int):
    """Read one simulation's 16 shards -> (pos [T,N,3], vel [T,N,3],
    viscosity [N], mass [N]) (reference process_key, process_dataset.py:480-498)."""
    import msgpack
    import zstandard as zstd

    position, vel = [], []
    viscosity = mass = None
    dctx = zstd.ZstdDecompressor()
    for i in range(SHARDS_PER_SIM):
        path = os.path.join(data_dir, dataset_name, f"sim_{idx:04d}_{i:02d}.msgpack.zst")
        with open(path, "rb") as f:
            raw = msgpack.unpackb(dctx.decompress(f.read()), raw=False,
                                  object_hook=_mn_decode, strict_map_key=False)
        for frame in raw:
            position.append(np.asarray(frame["pos"]))
            vel.append(np.asarray(frame["vel"]))
        if raw:  # tolerate empty shards (short simulations)
            viscosity = np.asarray(raw[0]["viscosity"])
            mass = np.asarray(raw[0]["m"])
    return (np.stack(position).astype(np.float32), np.stack(vel).astype(np.float32),
            viscosity.astype(np.float32), mass.astype(np.float32))


def write_fluid_sim(data_dir: str, dataset_name: str, idx: int,
                    pos: np.ndarray, vel: np.ndarray,
                    viscosity: np.ndarray, mass: np.ndarray) -> None:
    """Write one simulation in the exact on-disk format ``read_sim`` consumes
    (16 zstd+msgpack shards with msgpack-numpy array encoding — the layout of
    reference dataset_generation/Fluid113K/create_physics_records.py:1-148).

    pos/vel: [T, N, 3]; T frames are split evenly over the 16 shards. Used by
    scripts/generate_fluid_synthetic.py (format-identical synthetic data for
    pipeline validation at any scale) and the end-to-end tests; real
    SPlisHSPlasH data is the supported production path (docs/DATASETS.md)."""
    import msgpack
    import zstandard as zstd

    def encode_np(o):
        if isinstance(o, np.ndarray):
            return {b"nd": True, b"type": o.dtype.str.encode(),
                    b"shape": list(o.shape), b"data": o.tobytes()}
        return o

    base = os.path.join(data_dir, dataset_name)
    os.makedirs(base, exist_ok=True)
    T = pos.shape[0]
    # np.array_split balance: every shard non-empty for T >= SHARDS_PER_SIM
    bounds = np.linspace(0, T, SHARDS_PER_SIM + 1).astype(int)
    cctx = zstd.ZstdCompressor()
    viscosity = np.asarray(viscosity, np.float32)
    mass = np.asarray(mass, np.float32)
    for s in range(SHARDS_PER_SIM):
        frames = [
            {"pos": np.asarray(pos[t], np.float32),
             "vel": np.asarray(vel[t], np.float32),
             "viscosity": viscosity, "m": mass}
            for t in range(bounds[s], bounds[s + 1])
        ]
        packed = msgpack.packb(frames, default=encode_np)
        with open(os.path.join(base, f"sim_{idx:04d}_{s:02d}.msgpack.zst"), "wb") as f:
            f.write(cctx.compress(packed))


def build_fluid_graph(loc_0, vel_0, viscosity, mass, target) -> dict:
    """Whole-graph dict, no edges — Fluid113K runs distribute-mode only and
    partitions rebuild inner_radius edges (reference builds edges only inside
    split_large_graph_*)."""
    loc_0 = np.asarray(loc_0, np.float32)
    vel_0 = np.asarray(vel_0, np.float32)
    node_attr = np.stack([np.broadcast_to(viscosity, loc_0[:, 0].shape),
                          np.broadcast_to(mass, loc_0[:, 0].shape)], axis=-1)
    speed = np.linalg.norm(vel_0, axis=1, keepdims=True)
    node_feat = np.concatenate([node_attr, speed], axis=1)
    return {
        "node_feat": node_feat.astype(np.float32),
        "node_attr": node_attr.astype(np.float32),
        "loc": loc_0,
        "vel": vel_0,
        "target": np.asarray(target, np.float32),
        "loc_mean": loc_0.mean(axis=0),
        "edge_index": np.zeros((2, 0), np.int32),
        "edge_attr": np.zeros((0, 2), np.float32),
    }


def process_large_fluid_distribute(data_dir: str, dataset_name: str, world_size: int,
                                   max_samples: int, inner_radius: float,
                                   outer_radius: Optional[float], split_mode: str,
                                   delta_t: int, seed: int = 0) -> List[List[str]]:
    base = os.path.join(data_dir, dataset_name)
    processed_dir = os.path.join(base, "processed")
    os.makedirs(processed_dir, exist_ok=True)
    out = []
    for split, (lo, hi) in SIM_SPLITS.items():
        key = (f"{dataset_name}_{split_mode}_{split}_o{outer_radius}_i{inner_radius}"
               f"_{max_samples}_{delta_t}_s{seed}")
        shard_paths = [os.path.join(processed_dir, f"{key}_{p}-{world_size}.pkl")
                       for p in range(world_size)]
        out.append(shard_paths)
        if all(os.path.exists(p) for p in shard_paths):
            continue
        rng = np.random.default_rng(_split_seed(seed, split))
        graphs = []
        for idx in range(lo, hi):
            if len(graphs) >= max_samples:
                break
            pos, vel, viscosity, mass = read_sim(data_dir, dataset_name, idx)
            n = min(FRAMES_PER_SIM, max_samples - len(graphs))
            hi_f = min(FRAME_RANGE, pos.shape[0] - delta_t - 1)
            if hi_f <= 0:
                continue  # simulation too short for this delta_t
            for frame in rng.integers(0, hi_f, size=n):
                graphs.append(build_fluid_graph(pos[frame], vel[frame], viscosity,
                                                mass, pos[frame + delta_t]))
        write_partitioned_split(graphs, processed_dir, key, world_size,
                                split_mode, inner_radius, outer_radius, seed=seed)
    return out
