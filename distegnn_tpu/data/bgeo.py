"""Pure-Python codec for the classic Houdini BGEO v5 particle format as
written/read by Disney's partio library.

The reference pipeline moves particles between its scene generator and
SPlisHSPlasH as ``.bgeo`` files through the partio Python module
(dataset_generation/Fluid113K/physics_data_helper.py:28-82); SPlisHSPlasH
itself reads fluid ``particleFile``s and writes per-frame ``ParticleData``
exports with partio. partio is not in this image, so this module implements
the same on-disk layout directly:

  header (big-endian): int32 magic "Bgeo", char 'V', int32 version=5,
    int32 nPoints nPrims nPointGroups nPrimGroups,
    int32 nPointAttrib nVertexAttrib nPrimAttrib nDetailAttrib
  per point attribute (position is implicit, never listed):
    uint16 name-length + name bytes, int32 size, int32 houdini-type
    (0=float, 1=int, 5=vector; 4=indexed-string with its string table),
    then ``size`` int32 default-value slots
  per point: 4 float32 (x, y, z, w=1) then each attribute's payload
  trailer: bytes 0x00 0xff (no primitives)

Files gzipped by partio (``.bgeo.gz`` or transparently compressed) are
detected by magic and decompressed on read.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

_MAGIC = 0x4267656F  # "Bgeo"
_HTYPE_FLOAT, _HTYPE_INT, _HTYPE_STRING, _HTYPE_VECTOR = 0, 1, 4, 5


def write_bgeo(path: str, position: np.ndarray,
               attributes: Optional[Dict[str, np.ndarray]] = None) -> None:
    """Write particles. ``attributes`` maps name -> [N] or [N, k] arrays;
    float arrays with k==3 are declared VECTOR (partio's convention for
    velocity), other float widths FLOAT, integer arrays INT."""
    position = np.asarray(position, np.float32)
    if position.ndim != 2 or position.shape[1] != 3:
        raise ValueError(f"position must be [N, 3], got {position.shape}")
    n = position.shape[0]
    attributes = dict(attributes or {})

    spec: List[Tuple[str, np.ndarray, int]] = []
    for name, arr in attributes.items():
        arr = np.asarray(arr)
        if arr.ndim == 1:
            arr = arr[:, None]
        if arr.shape[0] != n:
            raise ValueError(f"attribute {name}: {arr.shape[0]} rows != {n} points")
        if np.issubdtype(arr.dtype, np.integer):
            spec.append((name, arr.astype(">i4"), _HTYPE_INT))
        else:
            htype = _HTYPE_VECTOR if arr.shape[1] == 3 else _HTYPE_FLOAT
            spec.append((name, arr.astype(">f4"), htype))

    out = bytearray()
    out += struct.pack(">i", _MAGIC)
    out += b"V"
    out += struct.pack(">i", 5)
    out += struct.pack(">4i", n, 0, 0, 0)
    out += struct.pack(">4i", len(spec), 0, 0, 0)
    for name, arr, htype in spec:
        nb = name.encode()
        out += struct.pack(">H", len(nb)) + nb
        out += struct.pack(">2i", arr.shape[1], htype)
        out += struct.pack(f">{arr.shape[1]}i", *([0] * arr.shape[1]))

    # interleave: position as homogeneous 4-float + attribute payloads
    row = np.empty((n, 4 + sum(a.shape[1] for _, a, _ in spec)), dtype=">f4")
    row[:, :3] = position
    row[:, 3] = 1.0
    col = 4
    for _, arr, htype in spec:
        k = arr.shape[1]
        # int payloads are stored bit-exact in the f4-typed staging buffer
        row[:, col:col + k] = arr.view(">f4") if htype == _HTYPE_INT else arr
        col += k
    out += row.tobytes()
    out += b"\x00\xff"

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wb") as f:
        f.write(bytes(out))


def read_bgeo(path: str) -> Dict[str, np.ndarray]:
    """Read particles -> {'position': [N,3], <attr>: [N,k]...} (k==1 squeezed)."""
    with open(path, "rb") as f:
        head = f.read(2)
        f.seek(0)
        data = f.read()
    if head == b"\x1f\x8b":
        data = gzip.decompress(data)

    off = 0

    def take(fmt):
        nonlocal off
        vals = struct.unpack_from(fmt, data, off)
        off += struct.calcsize(fmt)
        return vals

    (magic,) = take(">i")
    if magic != _MAGIC:
        raise ValueError(f"{path}: not a BGEO file (magic {magic:#x})")
    (vchar,) = take("c")
    (version,) = take(">i")
    if vchar != b"V" or version != 5:
        raise ValueError(f"{path}: unsupported BGEO version {vchar!r}{version}")
    n, _nprims, _npg, _nprg = take(">4i")
    nattr, _nva, _npa, _nda = take(">4i")

    names, sizes, htypes = [], [], []
    for _ in range(nattr):
        (ln,) = take(">H")
        names.append(data[off:off + ln].decode())
        off += ln
        size, htype = take(">2i")
        if htype in (_HTYPE_FLOAT, _HTYPE_INT, _HTYPE_VECTOR):
            take(f">{size}i")  # defaults
        elif htype == _HTYPE_STRING:
            (nidx,) = take(">i")
            for _ in range(nidx):
                (sl,) = take(">H")
                off += sl
        else:
            raise ValueError(f"{path}: unsupported attribute type {htype}")
        sizes.append(size)
        htypes.append(htype)

    width = 4 + sum(sizes)
    raw = np.frombuffer(data, dtype=">f4", count=n * width, offset=off)
    raw = raw.reshape(n, width)
    out: Dict[str, np.ndarray] = {"position": raw[:, :3].astype(np.float32)}
    col = 4
    for name, size, htype in zip(names, sizes, htypes):
        block = raw[:, col:col + size]
        if htype == _HTYPE_INT or htype == _HTYPE_STRING:
            arr = block.view(">i4").astype(np.int64)
        else:
            arr = block.astype(np.float32)
        out[name] = arr[:, 0] if size == 1 else arr
        col += size
    return out


def numpy_from_bgeo(path: str) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """(position, velocity-or-None), sorted by the 'id'/'trackid' attribute
    when present — the contract of the reference's partio-backed
    numpy_from_bgeo (physics_data_helper.py:28-60), which SPlisHSPlasH frame
    exports need because particle order is not stable across frames."""
    d = read_bgeo(path)
    pos = d["position"]
    vel = d.get("velocity") if d.get("velocity") is not None else d.get("v")
    ids = d.get("trackid")
    if ids is None:
        ids = d.get("id")
    if ids is not None:
        order = np.argsort(np.asarray(ids).reshape(-1), kind="stable")
        pos = pos[order]
        vel = vel[order] if vel is not None else None
    return pos, vel


def write_bgeo_from_numpy(path: str, pos: np.ndarray, vel: np.ndarray) -> None:
    """Positions + a 3-vector attribute named 'velocity' (the generator also
    stores surface normals under this name for box.bgeo, mirroring
    create_physics_scenes.py:400-401)."""
    pos = np.asarray(pos, np.float32)
    vel = np.asarray(vel, np.float32)
    if pos.shape != vel.shape or pos.ndim != 2 or pos.shape[1] != 3:
        raise ValueError(f"invalid shapes {pos.shape} / {vel.shape}")
    write_bgeo(path, pos, {"velocity": vel})


def list_partio_frames(partio_dir: str) -> Dict[str, List[str]]:
    """SPlisHSPlasH export dir -> {fluid_id: frame-ordered bgeo paths}
    (reference get_fluid_ids_from_partio_dir / get_fluid_bgeo_files,
    physics_data_helper.py:8-25). Files are named
    ``ParticleData_<fluid>_<frame>.bgeo``."""
    import re

    pat = re.compile(r"ParticleData_(.+)_(\d+)\.bgeo(\.gz)?$")
    by_id: Dict[str, List[Tuple[int, str]]] = {}
    for fn in os.listdir(partio_dir):
        m = pat.match(fn)
        if m:
            by_id.setdefault(m.group(1), []).append(
                (int(m.group(2)), os.path.join(partio_dir, fn)))
    return {k: [p for _, p in sorted(v)] for k, v in sorted(by_id.items())}
