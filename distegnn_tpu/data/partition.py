"""Spatial graph partitioners — shard one large graph across the mesh's
``graph`` axis (reference datasets/distribute_graphs.py: random / METIS /
spectral / kmeans splitters).

Contract (reference distribute_graphs.py:17-143): a partitioner assigns every
node to one of P parts, then each part keeps ONLY its own nodes, rebuilds
edges locally with ``inner_radius`` (inter-partition edges are dropped, not
haloed — global coupling flows exclusively through the virtual nodes), and
records the GLOBAL position mean as ``loc_mean`` so every partition seeds the
same replicated virtual-node coordinates.

Methods:
  random   — seeded permutation chunks (distribute_graphs.py:17-51)
  kmeans   — sklearn KMeans on positions (:118-143,188-198)
  spectral — sklearn SpectralClustering, RBF affinity with median-distance
             sigma over a <=2000-node subsample (:90-115,201-223)
  metis    — edge-cut-minimizing topological partition of the outer_radius
             graph. The reference calls C++ libmetis through torch-sparse
             (:151-185); here the preferred path is the in-tree C++
             multilevel partitioner (native/partition.cpp: HEM coarsening +
             weighted FM + k-way refinement, ctypes-bound, built lazily) —
             measured cut 0.0298 vs kmeans 0.0360 at 113k/8-way — with a
             pure-numpy BFS recursive bisection as the compiler-less
             fallback. Same interface and balance guarantee either way.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from distegnn_tpu.ops.radius import radius_graph_np


def random_labels(n: int, n_parts: int, rng: np.random.Generator) -> np.ndarray:
    """Random chunks of a node permutation, balanced to +-1 (the reference
    dumps the division remainder into the last chunk, distribute_graphs.py:
    27-29; spreading it keeps shard padding minimal)."""
    labels = np.empty(n, np.int32)
    for p, chunk in enumerate(np.array_split(rng.permutation(n), n_parts)):
        labels[chunk] = p
    return labels


def kmeans_labels(pos: np.ndarray, n_parts: int, seed: int = 0) -> np.ndarray:
    from sklearn.cluster import KMeans

    km = KMeans(n_clusters=n_parts, random_state=seed, n_init="auto")
    return km.fit_predict(np.asarray(pos, np.float32)).astype(np.int32)


def spectral_labels(pos: np.ndarray, n_parts: int, seed: int = 0,
                    sigma: Optional[float] = None) -> np.ndarray:
    from sklearn.cluster import SpectralClustering

    X = np.asarray(pos, np.float32)
    n = X.shape[0]
    if sigma is None:
        m = min(n, 2000)
        idx = np.random.RandomState(seed).choice(n, size=m, replace=False)
        D = np.linalg.norm(X[idx, None, :] - X[None, idx, :], axis=2)
        sigma = float(np.median(D[D > 0])) + 1e-12
    sc = SpectralClustering(
        n_clusters=n_parts, affinity="rbf", gamma=1.0 / (2.0 * sigma * sigma),
        assign_labels="kmeans", random_state=seed, eigen_solver="arpack",
    )
    return sc.fit_predict(X).astype(np.int32)


def _bfs_bisect(adj_indptr: np.ndarray, adj_indices: np.ndarray,
                nodes: np.ndarray, take: int, rng: np.random.Generator) -> np.ndarray:
    """Grow a connected region of exactly ``take`` nodes from a random seed by
    BFS over the induced subgraph; returns a bool mask over ``nodes``."""
    n = nodes.shape[0]
    local = {int(g): i for i, g in enumerate(nodes)}
    picked = np.zeros(n, bool)
    frontier = [int(rng.integers(n))]
    picked[frontier[0]] = True
    count = 1
    qi = 0
    while count < take:
        if qi >= len(frontier):
            # disconnected remainder: jump to an unpicked node
            rest = np.nonzero(~picked)[0]
            frontier.append(int(rest[0]))
            picked[rest[0]] = True
            count += 1
            continue
        u = frontier[qi]
        qi += 1
        gu = nodes[u]
        for gv in adj_indices[adj_indptr[gu]:adj_indptr[gu + 1]]:
            lv = local.get(int(gv))
            if lv is not None and not picked[lv] and count < take:
                picked[lv] = True
                frontier.append(lv)
                count += 1
    return picked


def _csr_from_edges(edge_index: np.ndarray, n: int):
    order = np.argsort(edge_index[0], kind="stable")
    row, col = edge_index[0][order], edge_index[1][order]
    indptr = np.zeros(n + 1, np.int64)
    np.add.at(indptr, row + 1, 1)
    return np.cumsum(indptr), col.astype(np.int64)


def metis_labels(pos: np.ndarray, n_parts: int, outer_radius: float,
                 seed: int = 0) -> np.ndarray:
    """Topological balanced partition of the outer_radius graph (the
    reference's libmetis call, distribute_graphs.py:151-185).

    Prefers the in-tree C++ partitioner (native/partition.cpp: recursive
    bisection with BFS region growing + FM boundary refinement, ctypes-bound,
    built lazily); falls back to the pure-numpy BFS bisection below when no
    compiler is available."""
    pos = np.asarray(pos)
    n = pos.shape[0]
    if n_parts <= 1:
        return np.zeros(n, np.int32)
    edge_index = radius_graph_np(pos, outer_radius)
    indptr, col = _csr_from_edges(edge_index, n)

    from distegnn_tpu.native import native_partition

    labels = native_partition(indptr, col, n_parts, seed=seed)
    if labels is not None:
        return labels
    rng = np.random.default_rng(seed)

    labels = np.zeros(n, np.int32)

    def recurse(nodes: np.ndarray, parts: int, base: int):
        if parts == 1:
            labels[nodes] = base
            return
        if nodes.shape[0] <= parts:
            # degenerate region: one node per part, surplus parts stay empty
            # (random_labels has the same silent-empty behavior for n < P)
            for i, g in enumerate(nodes):
                labels[g] = base + i
            return
        left = parts // 2
        take = int(round(nodes.shape[0] * left / parts))
        picked = _bfs_bisect(indptr, col, nodes, take, rng)
        recurse(nodes[picked], left, base)
        recurse(nodes[~picked], parts - left, base + left)

    recurse(np.arange(n), n_parts, 0)
    return labels


def assign_partitions(pos: np.ndarray, n_parts: int, method: str,
                      outer_radius: Optional[float] = None, seed: int = 0) -> np.ndarray:
    """Node -> partition labels [n] by the chosen split_mode."""
    if method == "random":
        return random_labels(pos.shape[0], n_parts, np.random.default_rng(seed))
    if method == "kmeans":
        return kmeans_labels(pos, n_parts, seed)
    if method == "spectral":
        return spectral_labels(pos, n_parts, seed)
    if method == "metis":
        if outer_radius is None:
            raise ValueError("metis split needs outer_radius")
        return metis_labels(pos, n_parts, outer_radius, seed)
    raise NotImplementedError(f"split_mode {method!r}")


def split_graph(
    graph: dict,
    n_parts: int,
    method: str,
    inner_radius: float,
    outer_radius: Optional[float] = None,
    seed: int = 0,
) -> List[dict]:
    """Partition one graph dict into P partition dicts (reference
    split_large_graph_*, distribute_graphs.py:17-143): per-part node subset,
    local inner_radius edges with distance edge_attr (2 channels), GLOBAL
    loc_mean on every part."""
    pos = graph["loc"]
    labels = assign_partitions(pos, n_parts, method, outer_radius=outer_radius, seed=seed)
    loc_mean = pos.mean(axis=0).astype(np.float32)

    parts = []
    for p in range(n_parts):
        sel = labels == p
        pos_p = pos[sel]
        edge_index = radius_graph_np(pos_p, inner_radius)
        dist = np.linalg.norm(pos_p[edge_index[0]] - pos_p[edge_index[1]], axis=1)
        parts.append({
            "node_feat": graph["node_feat"][sel],
            "node_attr": None if graph.get("node_attr") is None else graph["node_attr"][sel],
            "loc": pos_p.astype(np.float32),
            "vel": graph["vel"][sel],
            "target": None if graph.get("target") is None else graph["target"][sel],
            "loc_mean": loc_mean,
            "edge_index": edge_index.astype(np.int32),
            "edge_attr": np.repeat(dist[:, None], 2, axis=1).astype(np.float32),
        })
    return parts
