"""Spatial graph partitioners — shard one large graph across the mesh's
``graph`` axis (reference datasets/distribute_graphs.py: random / METIS /
spectral / kmeans splitters).

Contract (reference distribute_graphs.py:17-143): a partitioner assigns every
node to one of P parts, then each part keeps ONLY its own nodes, rebuilds
edges locally with ``inner_radius`` (inter-partition edges are dropped, not
haloed — global coupling flows exclusively through the virtual nodes), and
records the GLOBAL position mean as ``loc_mean`` so every partition seeds the
same replicated virtual-node coordinates.

Methods:
  random   — seeded permutation chunks (distribute_graphs.py:17-51)
  kmeans   — sklearn KMeans on positions (:118-143,188-198)
  spectral — sklearn SpectralClustering, RBF affinity with median-distance
             sigma over a <=2000-node subsample (:90-115,201-223)
  metis    — edge-cut-minimizing topological partition of the outer_radius
             graph. The reference calls C++ libmetis through torch-sparse
             (:151-185); here the preferred path is the in-tree C++
             multilevel partitioner (native/partition.cpp: HEM coarsening +
             weighted FM + k-way refinement, ctypes-bound, built lazily) —
             measured cut 0.0298 vs kmeans 0.0360 at 113k/8-way — with a
             pure-numpy BFS recursive bisection as the compiler-less
             fallback. Same interface and balance guarantee either way.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from distegnn_tpu import obs
from distegnn_tpu.ops.radius import radius_graph_np


def random_labels(n: int, n_parts: int, rng: np.random.Generator) -> np.ndarray:
    """Random chunks of a node permutation, balanced to +-1 (the reference
    dumps the division remainder into the last chunk, distribute_graphs.py:
    27-29; spreading it keeps shard padding minimal)."""
    labels = np.empty(n, np.int32)
    for p, chunk in enumerate(np.array_split(rng.permutation(n), n_parts)):
        labels[chunk] = p
    return labels


def kmeans_labels(pos: np.ndarray, n_parts: int, seed: int = 0) -> np.ndarray:
    from sklearn.cluster import KMeans

    km = KMeans(n_clusters=n_parts, random_state=seed, n_init="auto")
    return km.fit_predict(np.asarray(pos, np.float32)).astype(np.int32)


def spectral_labels(pos: np.ndarray, n_parts: int, seed: int = 0,
                    sigma: Optional[float] = None) -> np.ndarray:
    from sklearn.cluster import SpectralClustering

    X = np.asarray(pos, np.float32)
    n = X.shape[0]
    if sigma is None:
        m = min(n, 2000)
        idx = np.random.RandomState(seed).choice(n, size=m, replace=False)
        D = np.linalg.norm(X[idx, None, :] - X[None, idx, :], axis=2)
        sigma = float(np.median(D[D > 0])) + 1e-12
    sc = SpectralClustering(
        n_clusters=n_parts, affinity="rbf", gamma=1.0 / (2.0 * sigma * sigma),
        assign_labels="kmeans", random_state=seed, eigen_solver="arpack",
    )
    return sc.fit_predict(X).astype(np.int32)


def _bfs_bisect(adj_indptr: np.ndarray, adj_indices: np.ndarray,
                nodes: np.ndarray, take: int, rng: np.random.Generator) -> np.ndarray:
    """Grow a connected region of exactly ``take`` nodes from a random seed by
    BFS over the induced subgraph; returns a bool mask over ``nodes``."""
    n = nodes.shape[0]
    local = {int(g): i for i, g in enumerate(nodes)}
    picked = np.zeros(n, bool)
    frontier = [int(rng.integers(n))]
    picked[frontier[0]] = True
    count = 1
    qi = 0
    while count < take:
        if qi >= len(frontier):
            # disconnected remainder: jump to an unpicked node
            rest = np.nonzero(~picked)[0]
            frontier.append(int(rest[0]))
            picked[rest[0]] = True
            count += 1
            continue
        u = frontier[qi]
        qi += 1
        gu = nodes[u]
        for gv in adj_indices[adj_indptr[gu]:adj_indptr[gu + 1]]:
            lv = local.get(int(gv))
            if lv is not None and not picked[lv] and count < take:
                picked[lv] = True
                frontier.append(lv)
                count += 1
    return picked


def _csr_from_edges(edge_index: np.ndarray, n: int):
    order = np.argsort(edge_index[0], kind="stable")
    row, col = edge_index[0][order], edge_index[1][order]
    indptr = np.zeros(n + 1, np.int64)
    np.add.at(indptr, row + 1, 1)
    return np.cumsum(indptr), col.astype(np.int64)


def metis_labels(pos: np.ndarray, n_parts: int, outer_radius: float,
                 seed: int = 0) -> np.ndarray:
    """Topological balanced partition of the outer_radius graph (the
    reference's libmetis call, distribute_graphs.py:151-185).

    Prefers the in-tree C++ partitioner (native/partition.cpp: recursive
    bisection with BFS region growing + FM boundary refinement, ctypes-bound,
    built lazily); falls back to the pure-numpy BFS bisection below when no
    compiler is available."""
    pos = np.asarray(pos)
    n = pos.shape[0]
    if n_parts <= 1:
        return np.zeros(n, np.int32)
    edge_index = radius_graph_np(pos, outer_radius)
    indptr, col = _csr_from_edges(edge_index, n)

    from distegnn_tpu.native import native_partition

    labels = native_partition(indptr, col, n_parts, seed=seed)
    if labels is not None:
        return labels
    rng = np.random.default_rng(seed)

    labels = np.zeros(n, np.int32)

    def recurse(nodes: np.ndarray, parts: int, base: int):
        if parts == 1:
            labels[nodes] = base
            return
        if nodes.shape[0] <= parts:
            # degenerate region: one node per part, surplus parts stay empty
            # (random_labels has the same silent-empty behavior for n < P)
            for i, g in enumerate(nodes):
                labels[g] = base + i
            return
        left = parts // 2
        take = int(round(nodes.shape[0] * left / parts))
        picked = _bfs_bisect(indptr, col, nodes, take, rng)
        recurse(nodes[picked], left, base)
        recurse(nodes[~picked], parts - left, base + left)

    recurse(np.arange(n), n_parts, 0)
    return labels


def assign_partitions(pos: np.ndarray, n_parts: int, method: str,
                      outer_radius: Optional[float] = None, seed: int = 0) -> np.ndarray:
    """Node -> partition labels [n] by the chosen split_mode."""
    if method == "random":
        return random_labels(pos.shape[0], n_parts, np.random.default_rng(seed))
    if method == "kmeans":
        return kmeans_labels(pos, n_parts, seed)
    if method == "spectral":
        return spectral_labels(pos, n_parts, seed)
    if method == "metis":
        if outer_radius is None:
            raise ValueError("metis split needs outer_radius")
        return metis_labels(pos, n_parts, outer_radius, seed)
    raise NotImplementedError(f"split_mode {method!r}")


# ---------------------------------------------------------------------------
# Skew-balanced load pass.
#
# The spatial partitioners above balance NODE counts; per-step cost on a chip
# is closer to a·nodes + b·edges, and physical datasets are dense exactly
# where interesting (a fluid splash region can carry 10x the mean degree). A
# dense cluster then makes one graph-axis shard the step's critical path
# while the rest idle — padded static shapes mean EVERY chip waits for the
# hottest one. The pass below scores per-node work from the inner_radius
# degree and, when the measured max/mean ratio exceeds a threshold, reassigns
# Morton-ordered contiguous chunks greedily (LPT) so no shard owns the hot
# spot while chunks stay spatially compact (Z-curve segments). NeutronTP
# (arXiv:2412.20379) reaches the same balance by sharding the TENSOR axis
# instead; on our 3D mesh both levers exist — see docs/PERFORMANCE.md.
# ---------------------------------------------------------------------------

# default per-node / per-edge work weights: one node visit plus one unit per
# incident inner-radius edge (message+aggregate dominate the EGCL step)
WORK_NODE_COST = 1.0
WORK_EDGE_COST = 1.0


def node_work(pos: np.ndarray, inner_radius: float,
              a: float = WORK_NODE_COST, b: float = WORK_EDGE_COST,
              edge_index: Optional[np.ndarray] = None) -> np.ndarray:
    """Per-node work score ``a + b*degree(inner_radius graph)`` [n]. The
    degree is measured on the FULL graph — a proxy for the local edges each
    partition rebuilds (cross-partition pairs drop, so this upper-bounds the
    dense region's true local work: conservative in the right direction)."""
    pos = np.asarray(pos)
    if edge_index is None:
        edge_index = radius_graph_np(pos, inner_radius)
    deg = np.bincount(edge_index[0], minlength=pos.shape[0])
    return a + b * deg.astype(np.float64)


def partition_work(labels: np.ndarray, work: np.ndarray,
                   n_parts: int) -> np.ndarray:
    """Summed work per partition [P]."""
    return np.bincount(labels, weights=work, minlength=n_parts)


def imbalance_ratio(part_work: np.ndarray) -> float:
    """max/mean partition work — 1.0 is perfect, the step-time multiplier a
    static-shape mesh pays for its hottest shard."""
    pw = np.asarray(part_work, np.float64)
    return float(pw.max() / pw.mean())


def rebalance_morton(pos: np.ndarray, work: np.ndarray, n_parts: int,
                     chunks_per_part: int = 32) -> np.ndarray:
    """Greedy work-balanced labels from Morton-ordered contiguous chunks.

    Nodes are sorted along the Z curve, cut into ``n_parts*chunks_per_part``
    contiguous chunks (each a compact curve segment, so spatial locality
    survives), then chunks go to the currently-lightest partition in
    decreasing-work order (LPT). LPT's bound gives max/mean <= 1 + 1/m per
    chunk granule; with 32 chunks/part the measured ratio on the skewed
    synthetic benchmark sits well under the 1.15 gate."""
    from distegnn_tpu.ops.order import morton_perm

    pos = np.asarray(pos)
    n = pos.shape[0]
    perm = morton_perm(pos)
    n_chunks = min(n, n_parts * max(1, chunks_per_part))
    chunks = np.array_split(perm, n_chunks)
    chunk_work = np.array([work[c].sum() for c in chunks])
    labels = np.empty(n, np.int32)
    load = np.zeros(n_parts, np.float64)
    for ci in np.argsort(chunk_work, kind="stable")[::-1]:
        p = int(np.argmin(load))
        labels[chunks[ci]] = p
        load[p] += chunk_work[ci]
    return labels


def balance_partitions(
    pos: np.ndarray,
    labels: np.ndarray,
    n_parts: int,
    inner_radius: float,
    balance_ratio: float = 1.15,
    chunks_per_part: int = 32,
    edge_index: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, float, float]:
    """Apply the skew-balance pass when the measured imbalance exceeds
    ``balance_ratio``. Returns (labels, ratio_before, ratio_after) and emits
    a ``partition/balance`` obs event either way, so every run records how
    skewed its graph-axis shards actually are."""
    work = node_work(pos, inner_radius, edge_index=edge_index)
    before = imbalance_ratio(partition_work(labels, work, n_parts))
    after = before
    rebalanced = False
    if before > balance_ratio and n_parts > 1:
        new = rebalance_morton(pos, work, n_parts,
                               chunks_per_part=chunks_per_part)
        after = imbalance_ratio(partition_work(new, work, n_parts))
        # never trade a better split away: keep the original if the greedy
        # pass somehow did worse (tiny graphs, degenerate chunk counts)
        if after < before:
            labels, rebalanced = new, True
        else:
            after = before
    obs.event("partition/balance", n_parts=n_parts,
              ratio_before=round(before, 4), ratio_after=round(after, 4),
              rebalanced=rebalanced, threshold=balance_ratio)
    if rebalanced:
        obs.log(f"partition: work imbalance {before:.3f} -> {after:.3f} "
                f"(max/mean over {n_parts} parts, threshold {balance_ratio})")
    return labels, before, after


def split_graph(
    graph: dict,
    n_parts: int,
    method: str,
    inner_radius: float,
    outer_radius: Optional[float] = None,
    seed: int = 0,
    balance: bool = False,
    balance_ratio: float = 1.15,
) -> List[dict]:
    """Partition one graph dict into P partition dicts (reference
    split_large_graph_*, distribute_graphs.py:17-143): per-part node subset,
    local inner_radius edges with distance edge_attr (2 channels), GLOBAL
    loc_mean on every part. ``balance=True`` adds the skew-balance pass:
    when a·nodes+b·edges work imbalance exceeds ``balance_ratio``, labels are
    rebuilt from Morton chunks via greedy LPT (see balance_partitions)."""
    pos = graph["loc"]
    labels = assign_partitions(pos, n_parts, method, outer_radius=outer_radius, seed=seed)
    if balance:
        labels, _, _ = balance_partitions(
            pos, labels, n_parts, inner_radius, balance_ratio=balance_ratio)
    loc_mean = pos.mean(axis=0).astype(np.float32)

    parts = []
    for p in range(n_parts):
        sel = labels == p
        pos_p = pos[sel]
        edge_index = radius_graph_np(pos_p, inner_radius)
        dist = np.linalg.norm(pos_p[edge_index[0]] - pos_p[edge_index[1]], axis=1)
        parts.append({
            "node_feat": graph["node_feat"][sel],
            "node_attr": None if graph.get("node_attr") is None else graph["node_attr"][sel],
            "loc": pos_p.astype(np.float32),
            "vel": graph["vel"][sel],
            "target": None if graph.get("target") is None else graph["target"][sel],
            "loc_mean": loc_mean,
            "edge_index": edge_index.astype(np.int32),
            "edge_attr": np.repeat(dist[:, None], 2, axis=1).astype(np.float32),
        })
    return parts
