"""Device mesh construction — the TPU replacement for the reference's
process-group world (reference main.py:143,159-163 hard-wires single-node NCCL
with rank = LOCAL_RANK; SURVEY.md §2.10 flags multi-host as a gap to fill).

Axes:
  GRAPH_AXIS ('graph') — spatial graph partitions; carries the per-layer
      virtual-node psums (the only cross-partition traffic, constant-size).
      Lay this axis over ICI: it communicates every layer.
  DATA_AXIS  ('data')  — batch data parallelism; gradient psum once per step.
      May span DCN on multi-host pods.
  TENSOR_AXIS ('tensor') — tensor parallelism over the EGCL hidden dimension
      (NeutronTP-style feature split): each chip computes a 1/T hidden slice
      per edge/node block with exactly one gather-or-psum per MLP at the layer
      boundary. Placed minor-most (innermost ICI ring) because it communicates
      the most often. T=1 (the default) is bitwise-identical to the 2D mesh.

Multi-host: ``main.py --multihost`` calls jax.distributed.initialize(), then
this same code builds the mesh from the GLOBAL jax.devices() — shard_map over
a global mesh handles cross-host collectives; there is no rank-conditional
code anywhere in the framework (rank-0-style work like checkpoint writes keys
off ``jax.process_index() == 0``). Exercised for real by
tests/test_multihost.py (two OS processes, 8-device world, gloo CPU
collectives); pod recipe in docs/MULTIHOST.md.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

GRAPH_AXIS = "graph"
DATA_AXIS = "data"
TENSOR_AXIS = "tensor"


def make_mesh(
    n_graph: int = 1,
    n_data: int = 1,
    n_tensor: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a (data, graph, tensor) mesh over the available devices.

    n_data * n_graph * n_tensor must equal the device count used. The tensor
    axis is placed minor (fastest-varying) so the per-MLP hidden-dim
    collectives run over the innermost ICI ring; the graph axis comes next so
    partitions of one graph stay ICI-adjacent and the per-layer psums stay off
    DCN. The mesh always carries all three axis names — a T=1 tensor axis is
    size-1 and every collective over it is an identity, so existing 2D configs
    are bitwise-unchanged.
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_graph * n_data * n_tensor != len(devices):
        raise ValueError(
            f"mesh {n_data}x{n_graph}x{n_tensor} (data x graph x tensor) "
            f"!= {len(devices)} devices"
        )
    arr = np.asarray(devices).reshape(n_data, n_graph, n_tensor)
    return Mesh(arr, (DATA_AXIS, GRAPH_AXIS, TENSOR_AXIS))
