from distegnn_tpu.parallel.collectives import (  # noqa: F401
    pweighted_mean,
    global_node_mean,
    global_node_sum,
)
from distegnn_tpu.parallel.mesh import make_mesh, GRAPH_AXIS, DATA_AXIS  # noqa: F401
