"""Differentiable cross-partition reductions — the TPU-native replacement for
the reference's custom NCCL autograd collective.

The reference hand-writes a differentiable all_reduce (``_AllReduce``,
reference models/FastEGNN.py:10-43: forward = all_reduce(SUM), backward =
all_reduce(grad)) and composes it into ``weighted_average_reduce`` (reference
models/FastEGNN.py:310-319: data*=w; allreduce(data); allreduce(w); data/=w)
to turn per-partition means into exact global means.

In JAX none of that machinery is needed: ``jax.lax.psum`` inside ``shard_map``
is differentiable by construction (its reverse-mode rule IS the
backward-allreduce the reference implements by hand), runs over ICI as an XLA
collective, and fuses into the surrounding jitted step. Per-graph node counts
come from mask sums as traced ops — replacing the reference's per-step Python
``.item()`` loops (models/FastEGNN.py:196,226,260), its known hot-loop wart.

Every function takes ``axis_name=None`` meaning "not distributed" so the same
model code runs single-chip and on a mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from distegnn_tpu.ops.segment import masked_sum


def _psum(x, axis_name):
    return jax.lax.psum(x, axis_name) if axis_name is not None else x


def pweighted_mean(data: jnp.ndarray, weight: jnp.ndarray, axis_name: Optional[str] = None):
    """Exact global weighted mean across mesh partitions.

    Parity with reference weighted_average_reduce (models/FastEGNN.py:310-319):
    multiply by weight, SUM-reduce data and weight across the axis, divide.
    ``weight`` broadcasts against ``data`` (e.g. [B,1,1] node counts vs [B,3,C]).
    """
    num = _psum(data * weight, axis_name)
    den = _psum(weight, axis_name)
    return num / jnp.maximum(den, 1e-30)


def global_node_sum(data: jnp.ndarray, mask: jnp.ndarray, axis_name: Optional[str] = None):
    """Masked sum over the node axis (axis=1 of [B, N, ...]), then summed across
    mesh partitions. Returns ([B, ...] sum, [B] count)."""
    s = _psum(masked_sum(data, mask, axis=1), axis_name)
    c = _psum(jnp.sum(mask.astype(data.dtype), axis=1), axis_name)
    return s, c


# ---------------------------------------------------------------------------
# Tensor-parallel collectives (hidden-dim sharding over TENSOR_AXIS).
#
# The EGCL MLPs are Megatron-split: the first dense is column-parallel (each
# chip computes a contiguous 1/T slice of the hidden dim), the second is
# row-parallel (each chip contracts its slice, then one psum restores the full
# output). Params stay FULL and replicated on every chip — slicing happens at
# compute time inside the model (see models/common.py) — so checkpoints,
# optimizer state, and the DDP gradient psum over (data, graph) are untouched.
#
# That replication makes the naive autodiff of psum/all_gather wrong: the loss
# is computed once per tensor rank, so transposed collectives double-count
# gradients by T. These custom VJPs implement the "loss counted once" rules
# (each is the transpose of its partner):
#
#   tp_copy    fwd identity          bwd psum      (entering a sharded region)
#   tp_reduce  fwd psum              bwd identity  (row-parallel contraction)
#   tp_gather  fwd all_gather(tiled) bwd slice     (column-parallel collection)
#   tp_slice   fwd slice             bwd all_gather(tiled)
#
# With these, every param gradient comes out tensor-replicated, so the train
# step's gradient psum over (data, graph) needs no change for T>1.
# ---------------------------------------------------------------------------


def _tp_slice_bounds(full_dim: int, axis_name: str):
    """(per-rank width, this rank's start offset) for a contiguous 1/T slice."""
    t = jax.lax.psum(1, axis_name)
    if full_dim % t != 0:
        raise ValueError(f"hidden dim {full_dim} not divisible by tensor size {t}")
    width = full_dim // t
    return width, jax.lax.axis_index(axis_name) * width


def tp_copy(x, axis_name: Optional[str] = None):
    """Identity forward; psums the cotangent over the tensor axis.

    Use where a tensor-replicated activation enters a sharded computation: the
    forward value is the same on every rank, but each rank contributes an
    independent gradient that must be summed.
    """
    if axis_name is None:
        return x

    @jax.custom_vjp
    def _copy(v):
        return v

    _copy.defvjp(lambda v: (v, None), lambda _, g: (jax.lax.psum(g, axis_name),))
    return _copy(x)


def tp_reduce(x, axis_name: Optional[str] = None):
    """psum forward (row-parallel contraction back to model dim); identity bwd."""
    if axis_name is None:
        return x

    @jax.custom_vjp
    def _reduce(v):
        return jax.lax.psum(v, axis_name)

    _reduce.defvjp(lambda v: (jax.lax.psum(v, axis_name), None), lambda _, g: (g,))
    return _reduce(x)


def tp_gather(x, axis_name: Optional[str] = None):
    """all_gather slices along the last dim forward; slice the cotangent bwd."""
    if axis_name is None:
        return x

    @jax.custom_vjp
    def _gather(v):
        return jax.lax.all_gather(v, axis_name, axis=v.ndim - 1, tiled=True)

    def _fwd(v):
        return _gather(v), v.shape[-1]

    def _bwd(width, g):
        start = jax.lax.axis_index(axis_name) * width
        return (jax.lax.dynamic_slice_in_dim(g, start, width, axis=g.ndim - 1),)

    _gather.defvjp(_fwd, _bwd)
    return _gather(x)


def tp_slice(x, axis_name: Optional[str] = None):
    """This rank's contiguous 1/T slice of the last dim fwd; all_gather bwd.

    Used to carve a rank-local column block out of a FULL replicated param at
    compute time (the param tree itself stays mesh-shape independent).
    """
    if axis_name is None:
        return x
    width, start = _tp_slice_bounds(x.shape[-1], axis_name)

    @jax.custom_vjp
    def _slice(v):
        return jax.lax.dynamic_slice_in_dim(v, start, width, axis=v.ndim - 1)

    def _bwd(_, g):
        return (jax.lax.all_gather(g, axis_name, axis=g.ndim - 1, tiled=True),)

    _slice.defvjp(lambda v: (_slice(v), None), _bwd)
    return _slice(x)


def tp_once(x, axis_name: Optional[str] = None):
    """Identity forward; divides the cotangent by T. Zero communication.

    For values computed redundantly (bitwise-identically) on every tensor rank
    from replicated inputs — e.g. the fused kernel's ef_sum/count outputs,
    which come from the replicated phi_e weights while the same kernel call's
    trans_sum output is a per-rank partial. Inputs feeding such a kernel are
    wrapped in tp_copy (bwd psum), which would count the replicated outputs'
    cotangent T times; tp_once pre-divides so the psum counts it exactly once.
    Exact (not just approximate) when T is a power of two.
    """
    if axis_name is None:
        return x
    t = jax.lax.psum(1, axis_name)

    @jax.custom_vjp
    def _once(v):
        return v

    _once.defvjp(lambda v: (v, None), lambda _, g: (jax.tree.map(lambda a: a / t, g),))
    return _once(x)


def tp_slice_rows(x, axis_name: Optional[str] = None):
    """Row-block analogue of tp_slice: 1/T slice of axis 0 (row-parallel W2)."""
    if axis_name is None:
        return x
    width, start = _tp_slice_bounds(x.shape[0], axis_name)

    @jax.custom_vjp
    def _slice(v):
        return jax.lax.dynamic_slice_in_dim(v, start, width, axis=0)

    def _bwd(_, g):
        return (jax.lax.all_gather(g, axis_name, axis=0, tiled=True),)

    _slice.defvjp(lambda v: (_slice(v), None), _bwd)
    return _slice(x)


def global_node_mean(data: jnp.ndarray, mask: jnp.ndarray, axis_name: Optional[str] = None):
    """Exact GLOBAL mean over real nodes of each graph, across all partitions.

    Single device: equals the reference's global_mean_pool. Distributed: equals
    global_mean_pool followed by weighted_average_reduce with per-partition
    node counts (reference models/FastEGNN.py:258-261) — computed here in one
    fused step: psum(masked node sum) / psum(node count).
    """
    s, c = global_node_sum(data, mask, axis_name)
    c = jnp.maximum(c, 1.0).reshape(c.shape + (1,) * (s.ndim - c.ndim))
    return s / c
