"""Differentiable cross-partition reductions — the TPU-native replacement for
the reference's custom NCCL autograd collective.

The reference hand-writes a differentiable all_reduce (``_AllReduce``,
reference models/FastEGNN.py:10-43: forward = all_reduce(SUM), backward =
all_reduce(grad)) and composes it into ``weighted_average_reduce`` (reference
models/FastEGNN.py:310-319: data*=w; allreduce(data); allreduce(w); data/=w)
to turn per-partition means into exact global means.

In JAX none of that machinery is needed: ``jax.lax.psum`` inside ``shard_map``
is differentiable by construction (its reverse-mode rule IS the
backward-allreduce the reference implements by hand), runs over ICI as an XLA
collective, and fuses into the surrounding jitted step. Per-graph node counts
come from mask sums as traced ops — replacing the reference's per-step Python
``.item()`` loops (models/FastEGNN.py:196,226,260), its known hot-loop wart.

Every function takes ``axis_name=None`` meaning "not distributed" so the same
model code runs single-chip and on a mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from distegnn_tpu.ops.segment import masked_sum


def _psum(x, axis_name):
    return jax.lax.psum(x, axis_name) if axis_name is not None else x


def pweighted_mean(data: jnp.ndarray, weight: jnp.ndarray, axis_name: Optional[str] = None):
    """Exact global weighted mean across mesh partitions.

    Parity with reference weighted_average_reduce (models/FastEGNN.py:310-319):
    multiply by weight, SUM-reduce data and weight across the axis, divide.
    ``weight`` broadcasts against ``data`` (e.g. [B,1,1] node counts vs [B,3,C]).
    """
    num = _psum(data * weight, axis_name)
    den = _psum(weight, axis_name)
    return num / jnp.maximum(den, 1e-30)


def global_node_sum(data: jnp.ndarray, mask: jnp.ndarray, axis_name: Optional[str] = None):
    """Masked sum over the node axis (axis=1 of [B, N, ...]), then summed across
    mesh partitions. Returns ([B, ...] sum, [B] count)."""
    s = _psum(masked_sum(data, mask, axis=1), axis_name)
    c = _psum(jnp.sum(mask.astype(data.dtype), axis=1), axis_name)
    return s, c


def global_node_mean(data: jnp.ndarray, mask: jnp.ndarray, axis_name: Optional[str] = None):
    """Exact GLOBAL mean over real nodes of each graph, across all partitions.

    Single device: equals the reference's global_mean_pool. Distributed: equals
    global_mean_pool followed by weighted_average_reduce with per-partition
    node counts (reference models/FastEGNN.py:258-261) — computed here in one
    fused step: psum(masked node sum) / psum(node count).
    """
    s, c = global_node_sum(data, mask, axis_name)
    c = jnp.maximum(c, 1.0).reshape(c.shape + (1,) * (s.ndim - c.ndim))
    return s / c
