"""Distributed consistency checks (SURVEY §5.2; reference main.py:40-55).

The reference broadcasts rank-0 weights and asserts allclose on every rank at
startup, and all_gathers the per-rank graph signature each step
(utils/train.py:55-61) — its defenses against rank divergence, the main
"race" in that design. Here replication is by construction (one program,
psum-synced grads), so divergence indicates a real bug (donation aliasing,
sharding mistake, non-deterministic collective order, host data drift).
These checks make the invariant EXECUTABLE rather than assumed:

- :func:`assert_replicated` — every addressable shard of every param is
  bitwise identical, and (multi-host) every process holds the same
  fingerprint. This spans ALL mesh axes, including the tensor axis: tensor
  parallelism slices activations at compute time but keeps the param TREE
  full and replicated (models/common.py), so a tensor rank holding diverged
  weights is exactly as much a bug as a diverged data rank.
- :func:`batch_fingerprint` — the per-step data-order invariant: hosts must
  feed identical logical batches; compare fingerprints across processes.

Cheap enough to run at checkpoint epochs; wired behind
``log.check_consistency`` in the trainer.
"""

from __future__ import annotations

import hashlib

import jax
import numpy as np


def _leaf_digest(x: np.ndarray) -> bytes:
    return hashlib.blake2b(np.ascontiguousarray(x).tobytes(), digest_size=16).digest()


def _leaf_host_view(leaf):
    """Host bytes of a leaf, or None for leaves no single process can see.

    A multi-host REPLICATED array is not fully addressable but every process
    holds a complete copy (its first addressable shard); a genuinely
    cross-process-sharded leaf has no process-local full view -> skipped,
    matching the per-device check's tolerance of distinct-index shards."""
    if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
        if leaf.sharding.is_fully_replicated:
            return np.asarray(leaf.addressable_shards[0].data)
        return None
    return np.asarray(leaf)


def tree_fingerprint(tree) -> bytes:
    """16-byte digest of every (process-visible) leaf's bytes."""
    h = hashlib.blake2b(digest_size=16)
    for leaf in jax.tree.leaves(tree):
        view = _leaf_host_view(leaf)
        if view is not None:
            h.update(_leaf_digest(view))
    return h.digest()


def assert_replicated(tree, name: str = "params") -> None:
    """Raise if any device or process holds a diverged copy of ``tree``.

    Per-device: compares every addressable shard of replicated arrays
    bitwise. Per-process (multi-host): allgathers a fingerprint and compares.
    """
    # cross-process compare FIRST: every process reaches the collective, so a
    # divergence raise below cannot strand peers inside the allgather
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        fp = np.frombuffer(tree_fingerprint(tree), dtype=np.uint8)
        all_fp = np.asarray(multihost_utils.process_allgather(fp))
        if not (all_fp == all_fp[0]).all():
            raise AssertionError(
                f"{name} fingerprint differs across processes "
                f"(process {jax.process_index()} of {jax.process_count()})")

    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if not hasattr(leaf, "addressable_shards"):
            continue
        # compare only shards covering the SAME global slice (replicas);
        # distinct-index shards are genuine shards, not copies
        by_index = {}
        for s in leaf.addressable_shards:
            key = tuple((sl.start, sl.stop) for sl in s.index)
            ref = by_index.setdefault(key, s)
            if ref is s:
                continue
            if not np.array_equal(np.asarray(ref.data), np.asarray(s.data),
                                  equal_nan=True):
                raise AssertionError(
                    f"{name}{jax.tree_util.keystr(path)} diverged between "
                    f"devices {ref.device} and {s.device}")


def batch_fingerprint(batch) -> bytes:
    """Digest of a host batch — the analog of the reference's per-step graph
    signature all_gather (utils/train.py:55-61). Hosts feeding a lockstep
    loader must produce identical fingerprints for the same step."""
    return tree_fingerprint(batch)
