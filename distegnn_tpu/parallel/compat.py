"""jax version compatibility for shard_map.

Newer jax exposes ``jax.shard_map(..., check_vma=...)``; older releases only
have ``jax.experimental.shard_map.shard_map(..., check_rep=...)``. The
framework calls through this one wrapper so every distributed entry point
(launch.py, scan_epoch.py, dryrun parity, tensor-parallel tests) runs on
either API without version-conditional code at the call sites.
"""

from __future__ import annotations

import jax

try:  # jax >= 0.6 style
    _shard_map_new = jax.shard_map  # type: ignore[attr-defined]
except AttributeError:
    _shard_map_new = None

if _shard_map_new is None:
    from jax.experimental.shard_map import shard_map as _shard_map_old
else:
    _shard_map_old = None


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """Portable shard_map: replication checking off by default (the manual
    tensor-axis collectives intentionally produce unreplicated intermediates).
    """
    if _shard_map_new is not None:
        return _shard_map_new(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )
    return _shard_map_old(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
    )
