"""Distributed (DistEGNN) execution: one jitted shard_map'd train step over the
mesh's ``graph`` axis.

Replaces the reference's torchrun + NCCL + DDP stack (reference
main.py:159-229): there, one OS process per GPU runs the same Python loop and
synchronizes through process-group collectives; here ONE program traces the
step once, shard_map lays the partition axis over devices, and the three
per-layer virtual-node psums plus the node-count loss psum are XLA collectives
riding ICI. Gradient sync is an explicit psum of per-partition gradients
inside the step (see distegnn_tpu/train/step.py) — the DDP-sum pattern — so
every device applies the identical optimizer update and weights stay
replicated by construction (the invariant the reference checks with
broadcast+allclose at startup, main.py:40-55).

Multi-host: ``main.py --multihost`` calls ``jax.distributed.initialize()``;
``run_distributed`` then builds the mesh from the GLOBAL ``jax.devices()``
(all processes), host batches become global jax.Arrays via
``global_batch_putter`` (each host materializes only its addressable shards),
and the same shard_map spans the global mesh with XLA routing the collectives
over ICI/DCN. See docs/MULTIHOST.md for the pod launch recipe and
tests/test_multihost.py for a real two-process CPU test.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distegnn_tpu import obs
from distegnn_tpu.parallel.compat import shard_map
from distegnn_tpu.parallel.mesh import DATA_AXIS, GRAPH_AXIS, TENSOR_AXIS, make_mesh
from distegnn_tpu.train import (
    TrainState,
    make_eval_step,
    make_optimizer,
    make_train_step,
    needs_grad_clip,
    restore_checkpoint,
    train,
)
from distegnn_tpu.train.checkpoint import (
    adopt_resume_seed,
    resolve_resume,
    verify_resume_consensus,
)


def batch_layout(n_data: int):
    """The single source of truth for the batch array layout: (PartitionSpec
    for the leading shard axes, per-device strip function). 1-D mesh:
    [P, B, ...] sharded P(GRAPH_AXIS); 2-D: [D, P, B, ...] sharded
    P(DATA_AXIS, GRAPH_AXIS)."""
    if n_data > 1:
        return P(DATA_AXIS, GRAPH_AXIS), (lambda x: x[0, 0])
    return P(GRAPH_AXIS), (lambda x: x[0])


def make_device_steps(model, tx, mesh, mmd_weight: float, mmd_sigma: float,
                      mmd_samples: int):
    """The PER-DEVICE (axis-bound, un-shard_mapped) train/eval callables —
    the single source of step semantics for both distributed paths: the
    per-step loop (make_distributed_steps) and the scanned epoch
    (train.scan_epoch.DistributedScanRunner)."""
    n_data = mesh.shape[DATA_AXIS]
    data_axis = DATA_AXIS if n_data > 1 else None
    step = make_train_step(model, tx, mmd_weight=mmd_weight, mmd_sigma=mmd_sigma,
                           mmd_samples=mmd_samples, axis_name=GRAPH_AXIS,
                           data_axis_name=data_axis)
    ev = make_eval_step(model, axis_name=GRAPH_AXIS, data_axis_name=data_axis)
    return step, ev


def make_distributed_steps(model, tx, mesh, mmd_weight: float, mmd_sigma: float,
                           mmd_samples: int):
    """Build jitted (train_step, eval_step) running under shard_map.

    1-D mesh (data axis size 1): batch arrays arrive [P, B, ...]
    (ShardedGraphLoader layout); the leading axis shards over GRAPH_AXIS so
    each device sees its partition's [B, ...] slice.

    2-D mesh: batch arrives [D, P, B, ...]; the leading axes shard over
    (DATA_AXIS, GRAPH_AXIS). Loss node-weighting and the gradient psum span
    both axes; the model's virtual-node psums stay on GRAPH_AXIS (the data
    axis holds different graphs). State and PRNG key are replicated; outputs
    (replicated state, psum'd scalars) come back as single copies.
    """
    n_data = mesh.shape[DATA_AXIS]
    step, ev = make_device_steps(model, tx, mesh, mmd_weight, mmd_sigma,
                                 mmd_samples)
    batch_spec, strip = batch_layout(n_data)

    def _step_one(state, batch, key):
        # strip the leading shard axes (size 1 per device under shard_map)
        b = jax.tree.map(strip, batch)
        return step(state, b, key)

    def _eval_one(params, batch):
        return ev(params, jax.tree.map(strip, batch))

    train_step = jax.jit(shard_map(
        _step_one, mesh=mesh,
        in_specs=(P(), batch_spec, P()),
        out_specs=(P(), P()),
        check_vma=False,
    ))
    eval_step = jax.jit(shard_map(
        _eval_one, mesh=mesh,
        in_specs=(P(), batch_spec),
        out_specs=P(),
        check_vma=False,
    ))
    return train_step, eval_step


def global_batch_putter(mesh):
    """Host numpy batch -> global jax.Array laid out for make_distributed_steps.

    Single-process this is equivalent to an implicit device_put; multi-host it
    is REQUIRED: each process holds the full logical batch in host RAM but
    materializes only its addressable shards (jax.make_array_from_callback
    invokes the callback per addressable shard index only) — the TPU analog of
    the reference's per-rank shard files (reference main.py:182-190)."""
    batch_spec, _ = batch_layout(mesh.shape[DATA_AXIS])

    def put(batch):
        def _mk(x):
            x = np.asarray(x)
            sharding = NamedSharding(mesh, batch_spec)
            return jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])

        return jax.tree.map(_mk, batch)

    return put


# the blocking put-wrapper (_PuttingLoader) lives on as
# data/stream.PrefetchLoader(depth=0); depth>0 (config data.prefetch_depth,
# default 2) overlaps collate + put with the previous step's compute


def _dispatch_preprocess(config, ws: int):
    """Per-dataset distribute-mode preprocessing (reference
    process_dataset_distribute, datasets/process_dataset.py:48-58). Idempotent:
    results are cached shard files keyed by config, so any process may call it
    and later callers hit the cache."""
    from distegnn_tpu.data.distribute import process_nbody_distribute

    d = config.data
    name = d.dataset_name
    if name.startswith("nbody"):
        return process_nbody_distribute(
            d.data_dir, name, ws, d.max_samples, d.inner_radius, d.outer_radius,
            d.split_mode, d.frame_0, d.frame_T, seed=config.seed,
        )
    if name == "Water-3D":
        try:
            from distegnn_tpu.data.water3d import process_water3d_distribute
        except ImportError as e:
            raise NotImplementedError("Water-3D pipeline not built yet (SURVEY.md §7.2 stage 8)") from e

        return process_water3d_distribute(
            d.data_dir, name, ws, d.max_samples, d.inner_radius, d.outer_radius,
            d.split_mode, d.delta_t, seed=config.seed,
        )
    if name in ("Fluid113K", "LargeFluid"):
        try:
            from distegnn_tpu.data.fluid113k import process_large_fluid_distribute
        except ImportError as e:
            raise NotImplementedError("Fluid113K pipeline not built yet (SURVEY.md §7.2 stage 8)") from e

        return process_large_fluid_distribute(
            d.data_dir, name, ws, d.max_samples, d.inner_radius, d.outer_radius,
            d.split_mode, d.delta_t, seed=config.seed,
        )
    raise NotImplementedError(f"{name} has no distribute-mode processor")


def run_distributed(config):
    """Distribute-mode entry (reference main.py distribute flow): partitioned
    shards -> ShardedGraphLoader -> shard_map'd jitted step -> shared outer
    training loop."""
    from distegnn_tpu.config import derive_runtime_fields
    from distegnn_tpu.data import PrefetchLoader, ShardedGraphLoader, open_dataset
    from distegnn_tpu.models.registry import get_model
    from distegnn_tpu.utils.seed import fix_seed

    # world_size = graph partitions (reference semantics); data_parallel adds
    # the second mesh axis and parallel.mesh.tensor the third, so ws * dp * tp
    # devices are used. Multi-host: after jax.distributed.initialize()
    # (main.py --multihost) jax.devices() is the GLOBAL device list, so the
    # mesh spans all processes with no extra code.
    pmesh = (config.get("parallel") or {}).get("mesh") or {}
    tp = int(pmesh.get("tensor") or 1)
    dp = int(pmesh.get("data") or config.data.get("data_parallel") or 1)
    ws = (pmesh.get("graph") or config.data.get("world_size")
          or len(jax.devices()) // (dp * tp))
    ws = int(ws)
    if ws < 1 or ws * dp * tp > len(jax.devices()):
        raise ValueError(
            f"mesh data={dp} x graph={ws} x tensor={tp} does not fit the "
            f"{len(jax.devices())} available devices")
    derive_runtime_fields(config, world_size=ws)
    adopt_resume_seed(config)
    fix_seed(config.seed)
    mesh = make_mesh(n_graph=ws, n_data=dp, n_tensor=tp,
                     devices=jax.devices()[:ws * dp * tp])
    # record the resolved shape so downstream consumers (checkpoint metadata,
    # per-chip memory gauges) tag artifacts with the actual mesh
    config.parallel = {"mesh": {"data": dp, "graph": ws, "tensor": tp}}

    d = config.data
    name = d.dataset_name

    def preprocess():
        return _dispatch_preprocess(config, ws)

    if jax.process_count() > 1:
        # preprocessing runs on process 0 only, everyone else waits at a
        # barrier then reads the cache — the reference's rank-0 + dist.barrier
        # flow (reference main.py:171-177, process_dataset.py:462-463)
        from jax.experimental import multihost_utils

        if jax.process_index() == 0:
            split_paths = preprocess()
        multihost_utils.sync_global_devices("distegnn_preprocess")
        if jax.process_index() != 0:
            split_paths = preprocess()  # cache hit: shard files exist
    else:
        split_paths = preprocess()

    put = global_batch_putter(mesh)
    loaders = []
    for split_idx, paths in enumerate(split_paths):
        # open_dataset streams shard directories (scripts/shard_dataset.py
        # output) out-of-core and materializes pickle paths as before
        datasets = [open_dataset(p, node_order=d.node_order,
                                 cache_shards=int(d.get("stream_shard_cache", 4)))
                    for p in paths]
        loaders.append(PrefetchLoader(ShardedGraphLoader(
            datasets, d.batch_size, shuffle=(split_idx == 0), seed=config.seed,
            node_bucket=d.node_bucket, edge_bucket=d.edge_bucket,
            data_parallel=dp, edge_block=d.edge_block,
            split_remote=(config.model.get("edge_impl")
                          in ("fused", "fused_stack")),
            # cumsum aggregation wants the reverse-edge pairing attached to
            # plain batches (scatter-free col-gather backward, ops/segment.py)
            pairing=(True if (not d.edge_block and
                              config.model.get("segment_impl") in ("cumsum", "ell"))
                     else None),
        ), put, depth=int(d.get("prefetch_depth", 2))))
    loader_train, loader_valid, loader_test = loaders
    obs.log(f"Data ready: {len(loader_train.loader.loaders[0].dataset)} graphs x "
            f"{ws} partitions x {dp} data shards")

    model = get_model(config.model, world_size=ws, dataset_name=name,
                      axis_name=GRAPH_AXIS,
                      tensor_axis=(TENSOR_AXIS if tp > 1 else None))
    # init outside shard_map on the raw HOST batch (the axis names are unbound
    # there, and the param tree is identical either way — axis_name only
    # routes psums, and tensor_axis slices the SAME full params at compute
    # time); a global jax.Array can't be indexed on one host
    sample = next(iter(loader_train.loader))
    _, strip0 = batch_layout(dp)
    init_model = (model.copy(axis_name=None, tensor_axis=None) if tp > 1
                  else model.copy(axis_name=None))
    params = init_model.init(
        jax.random.PRNGKey(config.seed), jax.tree.map(strip0, sample))
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    obs.log(f"Model: {config.model.model_name}, {n_params} parameters, "
            f"mesh data={dp} graph={ws} tensor={tp}")

    total_steps = config.train.epochs * len(loader_train) // config.train.accumulation_steps
    clip = 0.3 if needs_grad_clip(config) else None

    def build_tx(lr_scale: float = 1.0):
        return make_optimizer(
            config.train.learning_rate * lr_scale,
            weight_decay=config.train.weight_decay,
            clip_norm=clip, accumulation_steps=config.train.accumulation_steps,
            total_steps=total_steps, scheduler=str(config.train.scheduler),
        )

    tx = build_tx()
    state = TrainState.create(params, tx)
    start_epoch, start_step_in_epoch = 0, 0
    resumed = resolve_resume(config, state)
    if resumed is not None:
        state, start_epoch = resumed.state, resumed.epoch
        start_step_in_epoch = resumed.step_in_epoch
        obs.log(f"resume: restored {resumed.path} (epoch {start_epoch} + "
                f"{start_step_in_epoch} step(s) applied)")
    elif config.model.checkpoint:
        state, start_epoch, _ = restore_checkpoint(
            config.model.checkpoint, state, config=config)
        obs.log(f"Checkpoint loaded from {config.model.checkpoint} (epoch {start_epoch})")
    # coordinated-restore barrier: every host must have adopted the same
    # resume coordinates before any psum'd step runs (no-op single-process);
    # the local path rides the typed error so a consensus failure names a
    # concrete checkpoint to diff against the lagging hosts
    verify_resume_consensus(
        start_epoch, start_step_in_epoch,
        path=(resumed.path if resumed is not None
              else (config.model.checkpoint or None)))

    is_fast = config.model.model_name.startswith("Fast")
    mmd_w = config.train.mmd.weight if is_fast else 0.0

    def step_factory(lr_scale: float):
        """(shard_mapped step, per-device step) at a scaled LR — divergence
        recovery rolls back and retries at a decayed LR; the opt-state tree
        is LR-independent so the rolled-back state loads unchanged. The
        device step feeds DistributedScanRunner.with_train_step."""
        tx2 = build_tx(lr_scale)
        tstep, _ = make_distributed_steps(
            model, tx2, mesh, mmd_weight=mmd_w,
            mmd_sigma=config.train.mmd.sigma,
            mmd_samples=config.train.mmd.samples)
        dstep, _ = make_device_steps(
            model, tx2, mesh, mmd_weight=mmd_w,
            mmd_sigma=config.train.mmd.sigma,
            mmd_samples=config.train.mmd.samples)
        return tstep, dstep

    train_step, eval_step = make_distributed_steps(
        model, tx, mesh, mmd_weight=mmd_w,
        mmd_sigma=config.train.mmd.sigma, mmd_samples=config.train.mmd.samples,
    )

    # scan_epochs for the distribute path too (VERDICT r2 weak #4: the
    # LargeFluid convergence run is distribute-mode and was paying per-batch
    # tunnel dispatch). Same flag + HBM-budget policy as main.py; the
    # per-DEVICE footprint is one partition's stacked dataset.
    scan_runner = None
    from distegnn_tpu.train.scan_epoch import (
        DistributedScanRunner,
        scan_enabled,
        sharded_dataset_nbytes,
    )

    total = sum(sharded_dataset_nbytes(l.loader) for l in loaders)
    if scan_enabled(config.train.scan_epochs, total):
        dstep, dev = make_device_steps(
            model, tx, mesh, mmd_weight=mmd_w,
            mmd_sigma=config.train.mmd.sigma,
            mmd_samples=config.train.mmd.samples)
        scan_runner = DistributedScanRunner(
            dstep, dev, mesh, loader_train.loader, config.seed,
            loader_valid=loader_valid.loader, loader_test=loader_test.loader)
        obs.log(f"scan_epochs: on ({total / 2**30:.2f} GiB device-resident "
                f"per chip)")

    state, best_state, best, log_dict = train(
        state, train_step, eval_step, loader_train, loader_valid, loader_test,
        config, start_epoch=start_epoch, scan_runner=scan_runner,
        start_step_in_epoch=start_step_in_epoch, step_factory=step_factory,
    )
    if best.get("preempted"):
        obs.log(f"Preempted (resumable). Best so far: {best}")
    else:
        obs.log(f"Done. Best: {best}")
    return best
