"""Autoregressive rollout, fully on device.

The reference evaluates one-step MSE only; its dataset generators produce
long trajectories offline with external simulators. This module closes the
loop TPU-natively: predict positions -> rebuild the radius graph
(ops/radius_dev.py, static shapes) -> next model step, all inside ONE
``lax.scan`` — zero host round-trips for the whole trajectory, and the
rebuilt edge list is already in the blocked layout the MXU aggregation
kernels consume (max_degree * edge_block slots per block).

Because capacity bounds are static, a step that overflows them (a cell
holding more than ``max_per_cell`` nodes, or a node with more than
``max_degree`` neighbors) silently drops edges; the per-step overflow flags
are returned stacked so callers can assert on them AFTER the scan (one host
sync for the whole rollout).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from distegnn_tpu.ops.graph import GraphBatch
from distegnn_tpu.ops.radius_dev import ell_to_edge_list, radius_graph_dev


def default_feature_fn(v: jnp.ndarray) -> jnp.ndarray:
    """[N, 3] velocity -> [N, 1] speed (the n-body convention)."""
    return jnp.linalg.norm(v, axis=-1, keepdims=True)


def default_edge_attr_fn(x, ei, em) -> jnp.ndarray:
    """Distance twice — the fluid pipelines' [d, d] edge attribute."""
    d = jnp.linalg.norm(x[ei[0]] - x[ei[1]], axis=-1, keepdims=True)
    return jnp.concatenate([d, d], axis=-1) * em[:, None]


def make_rollout_fn(
    model,
    radius: float,
    max_degree: int,
    max_per_cell: int = 16,
    feature_fn: Callable = default_feature_fn,
    edge_attr_fn: Callable = default_edge_attr_fn,
    node_attr: Optional[jnp.ndarray] = None,   # [N, A] static per-node attrs
    edge_block: int = 256,
    velocity_from_delta: bool = True,
    velocity_scale: float = 1.0,
):
    """Build jit-ready ``rollout(params, loc0, vel0, node_mask, steps)``.

    Returns (traj [steps, N, 3], overflow [steps] bool). N must be a multiple
    of ``edge_block`` and ``max_degree * edge_block`` a multiple of the kernel
    edge tile (512 at block 256 -> keep max_degree even) so every rebuilt
    graph is a legal blocked layout.
    """
    if (max_degree * edge_block) % 512:
        raise ValueError("max_degree * edge_block must be a multiple of 512")

    def one_step(params, x, v, node_mask, feat_args, attr_now):
        g = radius_graph_dev(x, radius, max_degree, max_per_cell,
                             node_mask=node_mask)
        ei, em = ell_to_edge_list(g)
        N = x.shape[0]
        nm = node_mask[:, None]
        loc_mean = (jnp.sum(x * nm, axis=0)
                    / jnp.maximum(jnp.sum(node_mask), 1.0))
        attr = (attr_now if attr_now is not None
                else jnp.zeros((N, 0), jnp.float32))
        batch = GraphBatch(
            node_feat=(feature_fn(v, *feat_args) * nm)[None],
            node_attr=(attr * nm)[None],
            loc=(x * nm)[None],
            vel=(v * nm)[None],
            target=jnp.zeros((1, N, 3), jnp.float32),
            loc_mean=loc_mean[None],
            node_mask=node_mask[None],
            edge_index=ei[None],
            edge_attr=edge_attr_fn(x, ei, em)[None],
            edge_mask=em[None],
            edges_sorted=True,
            edge_block=edge_block,
            edge_tile=512,
        )
        x_next, _ = model.apply(params, batch)
        x_next = x_next[0] * nm
        overflow = g.cell_overflow | g.degree_overflow
        return x_next, overflow

    def rollout(params, loc0, vel0, node_mask, steps: int, feat_args=(),
                node_attr_now: Optional[jnp.ndarray] = None,
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """``feat_args``: extra traced arrays forwarded to ``feature_fn(v,
        *feat_args)``; ``node_attr_now``: per-rollout static node attributes
        [N, A] (overrides the make-time ``node_attr``) — per-rollout constants
        passed as arguments instead of closures, so one jitted rollout serves
        many samples (jit with ``static_argnums=(4,)``)."""
        if loc0.shape[0] % edge_block:
            raise ValueError(f"N={loc0.shape[0]} must be a multiple of "
                             f"edge_block={edge_block} (pad loc0/node_mask)")
        attr_now = node_attr_now if node_attr_now is not None else node_attr

        def body(carry, _):
            x, v = carry
            x_next, overflow = one_step(params, x, v, node_mask, feat_args,
                                        attr_now)
            # velocity_scale: converts the per-rollout-step displacement into
            # the velocity convention the model was trained on (e.g. the
            # Water-3D pipeline's velocity is the ONE-frame delta while a
            # rollout step spans delta_t frames -> scale = 1/delta_t)
            v_next = ((x_next - x) * velocity_scale if velocity_from_delta
                      else v)
            return (x_next, v_next), (x_next, overflow)

        _, (traj, over) = jax.lax.scan(body, (loc0, vel0), None, length=steps)
        return traj, over

    return rollout


def make_batched_rollout_fn(
    model,
    radius: float,
    max_degree: int,
    max_per_cell: int = 16,
    feature_fn: Callable = default_feature_fn,
    edge_attr_fn: Callable = default_edge_attr_fn,
    node_attr: Optional[jnp.ndarray] = None,
    edge_block: int = 256,
    velocity_from_delta: bool = True,
    velocity_scale: float = 1.0,
):
    """Batched variant of :func:`make_rollout_fn`: a leading SCENE axis.

    ``rollout_batch(params, loc0 [B,N,3], vel0 [B,N,3], node_mask [B,N],
    steps)`` -> (traj [B, steps, N, 3], overflow [B, steps] bool).

    Structure: ONE ``lax.scan`` over steps whose body is the single-scene
    step ``vmap``-ed over scenes — every scene rebuilds its own radius graph
    per step, but all B scenes advance inside one executable, so the serve
    path amortizes dispatch/pad/sync over the whole micro-batch instead of
    paying it per scene (the B=1 throughput hole). All shapes are static, so
    the vmap is shape-preserving and the compile cache keys stay (n_pad,
    steps, B). Per-scene trajectories match B independent calls of the
    unbatched rollout (parity tested to 1e-6).
    """
    single = make_rollout_fn(
        model, radius, max_degree, max_per_cell=max_per_cell,
        feature_fn=feature_fn, edge_attr_fn=edge_attr_fn,
        node_attr=node_attr, edge_block=edge_block,
        velocity_from_delta=velocity_from_delta,
        velocity_scale=velocity_scale)

    def rollout_batch(params, loc0, vel0, node_mask, steps: int,
                      feat_args=(),
                      node_attr_now: Optional[jnp.ndarray] = None,
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        if loc0.ndim != 3:
            raise ValueError(f"rollout_batch expects loc0 [B, N, 3], got "
                             f"shape {tuple(loc0.shape)}")
        fn = lambda l, v, m: single(params, l, v, m, steps,
                                    feat_args=feat_args,
                                    node_attr_now=node_attr_now)
        return jax.vmap(fn)(loc0, vel0, node_mask)

    return rollout_batch
