"""SO(3) rotation helpers for equivariance tests and augmentation.

Same capabilities as reference utils/rotate.py:6-57 (rotx/roty/rotz,
random_rotate, random_rotate_y) — standard Euler rotation matrices,
implemented in numpy.
"""

from __future__ import annotations

import numpy as np


def rotx(theta: float) -> np.ndarray:
    c, s = np.cos(theta), np.sin(theta)
    return np.array([[1, 0, 0], [0, c, -s], [0, s, c]], dtype=np.float64)


def roty(theta: float) -> np.ndarray:
    c, s = np.cos(theta), np.sin(theta)
    return np.array([[c, 0, s], [0, 1, 0], [-s, 0, c]], dtype=np.float64)


def rotz(theta: float) -> np.ndarray:
    c, s = np.cos(theta), np.sin(theta)
    return np.array([[c, -s, 0], [s, c, 0], [0, 0, 1]], dtype=np.float64)


def random_rotate(rng: np.random.Generator | None = None) -> np.ndarray:
    """Random rotation composed from uniform Euler angles (as the reference's
    random_rotate does); adequate for equivariance checks."""
    rng = rng or np.random.default_rng()
    a, b, c = rng.uniform(0, 2 * np.pi, size=3)
    return rotx(a) @ roty(b) @ rotz(c)


def random_rotate_y(rng: np.random.Generator | None = None) -> np.ndarray:
    rng = rng or np.random.default_rng()
    return roty(rng.uniform(0, 2 * np.pi))
