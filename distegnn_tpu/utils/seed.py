"""Determinism. The reference seeds python/numpy/torch + cudnn-deterministic
(reference utils/seed.py:6-14). XLA is deterministic by default; JAX randomness
is explicit via PRNG keys, which also solves the reference's cross-rank RNG
discipline problem (SURVEY.md §7.4.7) — every host derives identical keys from
the config seed, so samplers agree by construction instead of by side effect.
"""

from __future__ import annotations

import random

import numpy as np
import jax


def fix_seed(seed: int = 43) -> jax.Array:
    """Seed host-side RNGs (python, numpy — used by data pipeline) and return
    the root JAX PRNG key for everything traced."""
    random.seed(seed)
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)
