from distegnn_tpu.utils.seed import fix_seed  # noqa: F401
from distegnn_tpu.utils import rotate  # noqa: F401
