"""ctypes bindings for the in-tree C++ components (native/).

The shared library is built lazily with g++ on first use and cached next to
the sources (``native/build/``). Pure-Python fallbacks exist for every native
entry point (distegnn_tpu/data/partition.py), so the framework runs even
where no compiler is available — mirroring how the reference degrades from
torch-sparse METIS to its other splitters."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libdistegnn_native.so")
_lock = threading.Lock()
_lib = None
_build_failed = False


_SOURCES = ("partition.cpp", "blockify.cpp")


def _build() -> Optional[str]:
    os.makedirs(os.path.dirname(_LIB_PATH), exist_ok=True)
    srcs = [os.path.join(_NATIVE_DIR, s) for s in _SOURCES]
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", *srcs, "-o", _LIB_PATH]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return _LIB_PATH
    except (subprocess.SubprocessError, FileNotFoundError):
        return None


def load_native() -> Optional[ctypes.CDLL]:
    """The native library, building it if needed; None if unavailable."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            newest = max(os.path.getmtime(os.path.join(_NATIVE_DIR, s))
                         for s in _SOURCES)
            if (not os.path.exists(_LIB_PATH)
                    or os.path.getmtime(_LIB_PATH) < newest):
                if _build() is None:
                    _build_failed = True
                    return None
            lib = _bind(ctypes.CDLL(_LIB_PATH))
        except (OSError, AttributeError):
            # stale/incompatible cached .so (load failure OR missing symbols
            # from an older build): rebuild once, else numpy fallback
            try:
                if _build() is None:
                    raise OSError
                lib = _bind(ctypes.CDLL(_LIB_PATH))
            except (OSError, AttributeError):
                _build_failed = True
                return None
        _lib = lib
        return _lib


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    """Declare every exported symbol's signature (raises AttributeError on a
    library built from older sources — caller rebuilds)."""
    lib.partition_graph.restype = ctypes.c_int
    lib.partition_graph.argtypes = [
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_int32, ctypes.c_uint64,
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
    ]
    lib.edge_cut.restype = ctypes.c_int64
    lib.edge_cut.argtypes = [
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
    ]
    lib.blockify_edges_native.restype = ctypes.c_int
    lib.blockify_edges_native.argtypes = [
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_void_p,  # attr (may be NULL)
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
    ]
    lib.pairing_perm_native.restype = ctypes.c_int
    lib.pairing_perm_native.argtypes = [
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
    ]
    return lib


def native_partition(indptr: np.ndarray, indices: np.ndarray, nparts: int,
                     seed: int = 0) -> Optional[np.ndarray]:
    """Balanced k-way partition labels [n] via the C++ partitioner, or None
    when the native library can't be built."""
    lib = load_native()
    if lib is None:
        return None
    n = indptr.shape[0] - 1
    labels = np.empty(n, np.int32)
    rc = lib.partition_graph(n, np.ascontiguousarray(indptr, np.int64),
                             np.ascontiguousarray(indices, np.int64),
                             np.int32(nparts), np.uint64(seed), labels)
    return labels if rc == 0 else None


def native_edge_cut(indptr: np.ndarray, indices: np.ndarray,
                    labels: np.ndarray) -> Optional[int]:
    lib = load_native()
    if lib is None:
        return None
    n = indptr.shape[0] - 1
    return int(lib.edge_cut(n, np.ascontiguousarray(indptr, np.int64),
                            np.ascontiguousarray(indices, np.int64),
                            np.ascontiguousarray(labels, np.int32)))


def native_blockify(edge_index: np.ndarray, edge_attr: Optional[np.ndarray],
                    n_nodes: int, epb: int, block: int):
    """Blocked edge re-layout via C++ (ops/blocked.blockify_edges semantics),
    or None when the native library can't be built / input is invalid."""
    lib = load_native()
    if lib is None:
        return None
    e = edge_index.shape[1]
    nb = n_nodes // block
    E = nb * epb
    d = edge_attr.shape[1] if edge_attr is not None else 0
    out_index = np.empty((2, E), np.int32)
    # d == 0: C++ never touches out_attr, a 1-element dummy satisfies ctypes
    out_attr = np.zeros((E, d) if d else (1, 1), np.float32)
    out_mask = np.empty((E,), np.float32)
    row = np.ascontiguousarray(edge_index[0], np.int64)
    col = np.ascontiguousarray(edge_index[1], np.int64)
    # keep the contiguous attr alive across the call (a bare .ctypes.data of
    # a temporary would dangle)
    attr_arr = np.ascontiguousarray(edge_attr, np.float32) if d else None
    rc = lib.blockify_edges_native(
        e, row, col, attr_arr.ctypes.data if d else None, d, n_nodes, block,
        epb, out_index, out_attr, out_mask)
    if rc != 0:
        return None
    return out_index, out_attr if d else np.zeros((E, 0), np.float32), out_mask


def native_pairing(edge_index: np.ndarray):
    """Reverse-edge involution via C++ (ops/blocked.pairing_perm semantics).

    Tri-state: ndarray (valid permutation) | False (definitively asymmetric)
    | None (native unavailable or ids out of packing range — use the numpy
    path). Prefer ops/blocked.pairing_perm_fast, which folds the dispatch."""
    lib = load_native()
    if lib is None:
        return None
    e = edge_index.shape[1]
    pair = np.empty((e,), np.int64)
    rc = lib.pairing_perm_native(
        e, np.ascontiguousarray(edge_index[0], np.int32),
        np.ascontiguousarray(edge_index[1], np.int32), pair)
    if rc == 0:
        return pair
    if rc == 1:
        return False           # definitively not symmetric
    return None                # out of packing range: caller uses numpy
