"""ctypes bindings for the in-tree C++ components (native/).

The shared library is built lazily with g++ on first use and cached next to
the sources (``native/build/``). Pure-Python fallbacks exist for every native
entry point (distegnn_tpu/data/partition.py), so the framework runs even
where no compiler is available — mirroring how the reference degrades from
torch-sparse METIS to its other splitters."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libdistegnn_native.so")
_lock = threading.Lock()
_lib = None
_build_failed = False


def _build() -> Optional[str]:
    os.makedirs(os.path.dirname(_LIB_PATH), exist_ok=True)
    src = os.path.join(_NATIVE_DIR, "partition.cpp")
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", src, "-o", _LIB_PATH]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return _LIB_PATH
    except (subprocess.SubprocessError, FileNotFoundError):
        return None


def load_native() -> Optional[ctypes.CDLL]:
    """The native library, building it if needed; None if unavailable."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            src = os.path.join(_NATIVE_DIR, "partition.cpp")
            if (not os.path.exists(_LIB_PATH)
                    or os.path.getmtime(_LIB_PATH) < os.path.getmtime(src)):
                if _build() is None:
                    _build_failed = True
                    return None
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            # stale/incompatible cached .so or missing source: rebuild once,
            # else fall back to the numpy partitioner
            try:
                if _build() is None:
                    raise OSError
                lib = ctypes.CDLL(_LIB_PATH)
            except OSError:
                _build_failed = True
                return None
        lib.partition_graph.restype = ctypes.c_int
        lib.partition_graph.argtypes = [
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            ctypes.c_int32, ctypes.c_uint64,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ]
        lib.edge_cut.restype = ctypes.c_int64
        lib.edge_cut.argtypes = [
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ]
        _lib = lib
        return _lib


def native_partition(indptr: np.ndarray, indices: np.ndarray, nparts: int,
                     seed: int = 0) -> Optional[np.ndarray]:
    """Balanced k-way partition labels [n] via the C++ partitioner, or None
    when the native library can't be built."""
    lib = load_native()
    if lib is None:
        return None
    n = indptr.shape[0] - 1
    labels = np.empty(n, np.int32)
    rc = lib.partition_graph(n, np.ascontiguousarray(indptr, np.int64),
                             np.ascontiguousarray(indices, np.int64),
                             np.int32(nparts), np.uint64(seed), labels)
    return labels if rc == 0 else None


def native_edge_cut(indptr: np.ndarray, indices: np.ndarray,
                    labels: np.ndarray) -> Optional[int]:
    lib = load_native()
    if lib is None:
        return None
    n = indptr.shape[0] - 1
    return int(lib.edge_cut(n, np.ascontiguousarray(indptr, np.int64),
                            np.ascontiguousarray(indices, np.int64),
                            np.ascontiguousarray(labels, np.int32)))
