"""Tiny deterministic CPU training run — the subprocess under test.

``python -m distegnn_tpu.testing.tiny_run --log-dir D ...`` trains a small
FastEGNN on a synthetic n-body set whose graphs depend only on a FIXED data
seed, so every invocation (control, victim, resumed) sees the identical
problem. The resilience tests (tests/test_resilience.py, preempt drill in
tests/test_cli_e2e.py, scripts/preempt_drill.sh) SIGKILL/SIGTERM it
mid-training and assert the resumed run reaches the same final train loss as
an uninterrupted control — which holds because per-step PRNG keys and loader
permutations derive from (seed, epoch, step) only (train/trainer.py).

Fault flags map to testing/faults.py injectors:
  --kill-at-step N     SIGKILL self after N train-step calls (abrupt death)
  --sigterm-at-step N  SIGTERM self after N calls (graceful preemption path)
  --poison-at-step N   NaN batch at global step N (divergence recovery path)

Exits 75 (EX_TEMPFAIL, main.py contract) when preempted-but-resumable.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys

import numpy as np

DATA_SEED = 1234  # fixed: the dataset must be identical across invocations


def build_graphs(n_graphs: int = 8, n: int = 10):
    from distegnn_tpu.data import build_nbody_graph

    rng = np.random.default_rng(DATA_SEED)
    graphs = []
    for _ in range(n_graphs):
        loc = rng.normal(size=(n, 3))
        vel = rng.normal(size=(n, 3))
        charges = rng.choice([1.0, -1.0], size=(n, 1))
        target = loc + 0.1 * vel
        graphs.append(build_nbody_graph(loc, vel, charges, target,
                                        radius=-1.0, cutoff_rate=0.0))
    return graphs


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(description="tiny resilience-test trainer")
    ap.add_argument("--log-dir", required=True)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--batch-size", type=int, default=2)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--interval-s", type=float, default=0.0,
                    help="train.checkpoint_interval_s (mid-epoch cadence)")
    ap.add_argument("--keep", type=int, default=3)
    ap.add_argument("--resume", default=None, help="'auto' or a checkpoint path")
    ap.add_argument("--retries", type=int, default=0,
                    help="train.divergence_retries")
    ap.add_argument("--kill-at-step", type=int, default=0)
    ap.add_argument("--sigterm-at-step", type=int, default=0)
    ap.add_argument("--poison-at-step", type=int, default=-1)
    args = ap.parse_args(argv)

    import jax

    from distegnn_tpu.config import ConfigDict
    from distegnn_tpu.data import GraphDataset, GraphLoader
    from distegnn_tpu.models.fast_egnn import FastEGNN
    from distegnn_tpu.testing.faults import inject_at_call, poison_nan_batches
    from distegnn_tpu.train import (TrainState, make_eval_step, make_optimizer,
                                    make_train_step, train)
    from distegnn_tpu.train.checkpoint import adopt_resume_seed, resolve_resume

    config = ConfigDict({
        "seed": args.seed,
        "train": {"epochs": args.epochs, "early_stop": 10_000,
                  "checkpoint_interval_s": args.interval_s,
                  "keep_checkpoints": args.keep,
                  "divergence_retries": args.retries,
                  "divergence_lr_decay": 0.5,
                  "resume": args.resume,
                  # scan_epochs stays off: the host loop is the code path
                  # under test (cadence saves + preemption checks live there)
                  "scan_epochs": False},
        "log": {"test_interval": 2, "log_dir": args.log_dir,
                "exp_name": "run",  # fixed (no timestamp): resume scans here
                "check_consistency": False,
                "wandb": {"enable": False}},
    })

    # a resumed run must adopt the original run's seed BEFORE the loaders /
    # model derive anything from it (same contract as main.py)
    adopt_resume_seed(config)
    seed = int(config.seed)

    graphs = build_graphs()
    mk = lambda shuffle: GraphLoader(GraphDataset(graphs), args.batch_size,
                                     shuffle=shuffle, seed=seed)
    loader_train, loader_valid, loader_test = mk(True), mk(False), mk(False)

    model = FastEGNN(node_feat_nf=2, hidden_nf=16, virtual_channels=3, n_layers=2)
    params = model.init(jax.random.PRNGKey(seed), next(iter(loader_train)))

    def build_tx(lr_scale: float = 1.0):
        return make_optimizer(args.lr * lr_scale)

    def step_factory(lr_scale: float):
        return jax.jit(make_train_step(model, build_tx(lr_scale),
                                       mmd_weight=0.0, mmd_sigma=1.5,
                                       mmd_samples=3))

    state = TrainState.create(params, build_tx())
    start_epoch, start_step_in_epoch = 0, 0
    resumed = resolve_resume(config, state)
    if resumed is not None:
        state, start_epoch = resumed.state, resumed.epoch
        start_step_in_epoch = resumed.step_in_epoch
        from distegnn_tpu import obs

        obs.log(f"resume: restored {resumed.path} (epoch {start_epoch} + "
                f"{start_step_in_epoch} step(s) applied)")

    train_step = step_factory(1.0)
    if args.kill_at_step > 0:
        train_step = inject_at_call(
            train_step, args.kill_at_step,
            lambda: os.kill(os.getpid(), signal.SIGKILL))
    elif args.sigterm_at_step > 0:
        train_step = inject_at_call(
            train_step, args.sigterm_at_step,
            lambda: signal.raise_signal(signal.SIGTERM))
    if args.poison_at_step >= 0:
        loader_train = poison_nan_batches(loader_train, args.poison_at_step)

    eval_step = jax.jit(make_eval_step(model))
    state, _, best, log_dict = train(
        state, train_step, eval_step, loader_train, loader_valid, loader_test,
        config, start_epoch=start_epoch,
        start_step_in_epoch=start_step_in_epoch, step_factory=step_factory)

    result = {
        "final_train_loss": log_dict["loss_train"][-1] if log_dict["loss_train"] else None,
        "start_epoch": start_epoch,
        "start_step_in_epoch": start_step_in_epoch,
        "epochs_logged": len(log_dict["loss_train"]),
        "divergence_events": len(log_dict["divergence_events"]),
        "preempted": bool(best.get("preempted")),
        "diverged": bool(best.get("diverged")),
    }
    # harness contract line (tests parse exactly this prefix on stdout):
    # stays a bare print — the obs event sink may already be closed here
    print("RESULT " + json.dumps(result), flush=True)  # noqa: obs-print
    return result


if __name__ == "__main__":
    _r = main()
    if _r.get("preempted"):
        sys.exit(75)
