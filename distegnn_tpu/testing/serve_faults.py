"""Serving-layer fault injectors (docs/ROBUSTNESS.md, serving chaos harness).

Each injector reproduces ONE serving failure mode at an exact point in a
LIVE in-process gateway (they reach through the ModelRegistry into the
replica pool), so tests/test_replica.py and ``scripts/traffic_gen.py
--chaos`` can prove the recovery path under real traffic:

  - :func:`kill_replica` — the dispatcher thread dies abruptly (a crashed
    device runtime / OOM-killed worker). The supervisor must detect the
    dead queue, fail in-flight work over to survivors, and restart it
    behind backoff.
  - :func:`wedge_replica` — the dispatcher stays alive but stops making
    batch progress for ``duration_s`` (a stuck collective / hung device
    call). Wedge detection must claim the in-flight work, abandon the
    queue, and restart.
  - :func:`inject_execute_latency` — every batch execute takes an extra
    ``seconds`` (slow device / thermal throttle), inflating queue delay
    without breaking anything: the SLO harness sees honest degradation.
  - :func:`corrupt_swap_checkpoint` — damage a checkpoint file that a
    blue/green swap is about to restore from (torn write / bit-rot); the
    swap must fail at the restore stage and roll back, never flipping a
    replica onto garbage params.

Process-level injectors (``serve.workers: process`` only — they target the
replica's worker CHILD, proving the process-isolation story end to end):

  - :func:`kill9_replica` — SIGKILL the child outright (the OOM killer, a
    segfaulting extension). The parent-side in-flight tracking must fail
    the work over to survivors; the supervisor respawns through backoff.
  - :func:`sigstop_replica` — freeze the child without killing it (a
    debugger attach, cgroup freezer, swap storm). Queue progress tracking
    can't see this when idle; heartbeat staleness must catch it and the
    supervisor must escalate to SIGKILL (the only signal a stopped
    process honors) before respawning.
  - :func:`spawn_failure` — arm the NEXT ``n`` respawn attempts to fail
    (exec failure, bad image, broken env). The replica must degrade to an
    in-process queue (``gateway/worker_degraded``) instead of shedding.

All injectors are process-local: they need the registry object, not a URL
(``traffic_gen --chaos`` therefore refuses to run against ``--url``).
"""

from __future__ import annotations

import os
import signal

from distegnn_tpu.testing.faults import corrupt_checkpoint


def _replica(registry, model: str, replica: int):
    entry = registry.get(model)
    rset = entry.replicas
    if not 0 <= int(replica) < len(rset.replicas):
        raise IndexError(
            f"model {model!r} has {len(rset.replicas)} replica(s); "
            f"no replica {replica}")
    return rset.replicas[int(replica)]


def kill_replica(registry, model: str, replica: int = 0) -> None:
    """Abruptly kill one replica's dispatcher: its queue fails all queued
    futures typed and the thread exits at its next wake-up. With
    ``serve.replicas >= 2`` the supervisor fails the in-flight work over to
    survivors; single-replica models shed with 503 until the restart."""
    _replica(registry, model, replica).queue.kill(
        reason=f"chaos: killed replica {replica}")


def wedge_replica(registry, model: str, duration_s: float,
                  replica: int = 0) -> None:
    """Freeze one replica's dispatcher for ``duration_s`` seconds without
    killing it — no batch progress, ``last_progress`` goes stale. Pick a
    duration beyond ``serve.supervisor.wedge_timeout_s`` to trigger wedge
    detection, or below it to exercise pure queueing delay."""
    _replica(registry, model, replica).queue.wedge(float(duration_s))


def inject_execute_latency(registry, model: str, seconds: float,
                           replica: int | None = None) -> None:
    """Add ``seconds`` of latency to every batch execute on one replica
    (or all replicas of the model when ``replica`` is None). Pass 0 to
    clear the injection."""
    entry = registry.get(model)
    targets = (entry.replicas.replicas if replica is None
               else [_replica(registry, model, replica)])
    for r in targets:
        r.queue.inject_latency(float(seconds))


def _worker_pid(r, model: str, replica: int, what: str) -> int:
    pid = getattr(r.queue, "pid", None)
    if pid is None:
        raise ValueError(
            f"{what} targets a worker child, but {model!r} replica "
            f"{replica} has no live worker process (thread backend or "
            f"degraded) — run under serve.workers: process")
    return int(pid)


def kill9_replica(registry, model: str, replica: int = 0) -> int:
    """SIGKILL one replica's worker child (the OOM killer's signature move).
    No cleanup runs in the child; the parent's reader thread sees EOF, fails
    in-flight work over to survivors, and the supervisor respawns the child
    behind backoff. Returns the pid killed."""
    r = _replica(registry, model, replica)
    pid = _worker_pid(r, model, replica, "kill9")
    os.kill(pid, signal.SIGKILL)
    return pid


def sigstop_replica(registry, model: str, replica: int = 0) -> int:
    """SIGSTOP one replica's worker child: the process stays alive but stops
    beating. Heartbeat staleness (``worker_heartbeat_timeout_s``) must mark
    it wedged; the supervisor's kill escalates SIGTERM → SIGKILL, which a
    stopped process does honor. Returns the pid stopped."""
    r = _replica(registry, model, replica)
    pid = _worker_pid(r, model, replica, "sigstop")
    os.kill(pid, signal.SIGSTOP)
    return pid


def spawn_failure(registry, model: str, n: int = 1,
                  replica: int = 0) -> None:
    """Arm the next ``n`` worker spawn attempts on one replica to fail
    (injected WorkerSpawnError). Combined with :func:`kill9_replica` this
    proves graceful degradation: the respawn fails, the replica falls back
    to an in-process queue with ``gateway/worker_degraded``, and the NEXT
    restart retries the worker backend."""
    r = _replica(registry, model, replica)
    fn = getattr(r, "fail_next_spawns", None)
    if fn is None:
        raise ValueError(
            f"spawn_failure needs a process-backed replica; {model!r} "
            f"replica {replica} is thread-backed")
    fn(int(n))


def corrupt_swap_checkpoint(path: str, mode: str = "garbage") -> None:
    """Damage the checkpoint a blue/green swap is about to load (modes as
    :func:`distegnn_tpu.testing.faults.corrupt_checkpoint`): the swap's
    checksummed restore must fail and the swap must report
    ``stage="restore", rolled_back=True`` without touching live params."""
    corrupt_checkpoint(path, mode=mode)
