"""Deterministic fault injection for the resilience layer (docs/ROBUSTNESS.md).

Everything here is test machinery: injectors that corrupt checkpoints, poison
batches, and fail file opens on demand (``faults``), serving chaos injectors
that kill/wedge replicas and sabotage hot-swaps in a live gateway
(``serve_faults``), plus a tiny subprocess training entry point
(``tiny_run``) the kill-and-resume tests drive.
"""

from distegnn_tpu.testing.faults import (
    corrupt_checkpoint,
    flaky_open,
    inject_at_call,
    poison_nan_batches,
    simulate_killed_save,
    truncated_read,
)
from distegnn_tpu.testing.serve_faults import (
    corrupt_swap_checkpoint,
    inject_execute_latency,
    kill9_replica,
    kill_replica,
    sigstop_replica,
    spawn_failure,
    wedge_replica,
)

__all__ = [
    "corrupt_checkpoint",
    "simulate_killed_save",
    "poison_nan_batches",
    "flaky_open",
    "truncated_read",
    "inject_at_call",
    "kill_replica",
    "kill9_replica",
    "sigstop_replica",
    "spawn_failure",
    "wedge_replica",
    "inject_execute_latency",
    "corrupt_swap_checkpoint",
]
