"""Deterministic fault injectors (docs/ROBUSTNESS.md fault-injection cookbook).

Each injector reproduces ONE real failure mode at an exact, controllable
point, so tests/test_resilience.py and tests/test_checkpoint.py can prove the
recovery path instead of hoping for it:

  - :func:`corrupt_checkpoint` — torn write / bit-rot on a checkpoint file
    (restore must skip past it to the previous valid state);
  - :func:`simulate_killed_save` — a save killed between tmp-write and rename
    (the ``*.tmp`` leftover must be swept, the real file stays valid);
  - :func:`poison_nan_batches` — one NaN batch at step N (divergence recovery
    must roll back and retry, not kill the run);
  - :func:`flaky_open` — transient ``OSError`` from the dataset loader
    (bounded-backoff retry in data/loader.py must absorb it);
  - :func:`inject_at_call` — run an arbitrary action (SIGKILL/SIGTERM to
    self) after exactly N train-step calls.
"""

from __future__ import annotations

import contextlib
import os
import pickle
from typing import Callable, Optional

import numpy as np


# ---- checkpoint faults -----------------------------------------------------

def corrupt_checkpoint(path: str, mode: str = "truncate") -> None:
    """Damage an existing checkpoint file in place.

    ``truncate``: cut the file to half its size (a torn write — the manifest
    size check catches it). ``garbage``: flip bytes in the middle keeping the
    size (bit-rot — the CRC32 check catches it). ``headerless``: replace the
    whole file with non-pickle bytes (no manifest entry needed to detect)."""
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "rb+") as f:
            f.truncate(max(1, size // 2))
    elif mode == "garbage":
        with open(path, "rb+") as f:
            f.seek(size // 2)
            chunk = f.read(64)
            f.seek(size // 2)
            f.write(bytes(b ^ 0xFF for b in chunk))
    elif mode == "headerless":
        with open(path, "wb") as f:
            f.write(b"not a pickle at all")
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")


def simulate_killed_save(ckpt_dir: str, name: str = "victim.ckpt") -> str:
    """Leave the debris of a save killed MID-WRITE: a partial ``<name>.tmp``
    that never reached its atomic rename. Returns the tmp path. The next
    save_checkpoint into the directory must sweep it; restore must never
    consider it (only ``*.ckpt`` files are scanned)."""
    os.makedirs(ckpt_dir, exist_ok=True)
    blob = pickle.dumps({"epoch": 0, "params_leaves": [np.zeros(3)]})
    tmp = os.path.join(ckpt_dir, name + ".tmp")
    with open(tmp, "wb") as f:
        f.write(blob[: len(blob) // 2])  # killed before the write finished
    return tmp


# ---- data faults -----------------------------------------------------------

class poison_nan_batches:
    """Loader wrapper yielding batch ``at_step`` (0-based, counted across
    epochs) with every floating leaf replaced by NaN — the classic corrupted
    shard / overflowed preprocessing record. Fires ``times`` times total, so
    a rolled-back retry of the same epoch sees the CLEAN batch and recovery
    can be proven deterministic."""

    def __init__(self, loader, at_step: int, times: int = 1):
        self.loader = loader
        self.at_step = int(at_step)
        self.times = int(times)
        self._count = 0
        self.fired = 0

    def set_epoch(self, epoch: int) -> None:
        self.loader.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.loader)

    @staticmethod
    def _nanify(x):
        arr = np.asarray(x)
        if np.issubdtype(arr.dtype, np.floating):
            return np.full_like(arr, np.nan)
        return x

    def __iter__(self):
        import jax

        for batch in self.loader:
            if self._count == self.at_step and self.fired < self.times:
                self.fired += 1
                batch = jax.tree.map(self._nanify, batch)
            self._count += 1
            yield batch


@contextlib.contextmanager
def flaky_open(fail_times: int, exc: Optional[OSError] = None):
    """Patch the data loader's open hook so the first ``fail_times`` opens
    raise a transient ``OSError`` (default: errno 5, the NFS/GCS hiccup
    shape), then defer to the real ``open``. Context manager; restores the
    hook on exit. Yields a dict with the observed call count."""
    from distegnn_tpu.data import loader as loader_mod

    err = exc if exc is not None else OSError(5, "injected transient I/O error")
    calls = {"n": 0}
    real = loader_mod._file_open

    def _open(path, mode="rb"):
        calls["n"] += 1
        if calls["n"] <= fail_times:
            raise type(err)(*err.args)
        return real(path, mode)

    loader_mod._file_open = _open
    try:
        yield calls
    finally:
        loader_mod._file_open = real


@contextlib.contextmanager
def truncated_read(fail_times: int, fraction: float = 0.5):
    """Patch the loader's open hook so the first ``fail_times`` opened files
    hand back only the leading ``fraction`` of their bytes — the torn-NFS
    shape where ``open()`` SUCCEEDS and the failure only surfaces inside the
    payload read (``pickle.load`` EOFError, npz BadZipFile, shard CRC
    mismatch). Exercises the full-read retry (`loader._read_with_retry`)
    that a plain open-retry cannot cover. Yields the observed call count."""
    import io

    from distegnn_tpu.data import loader as loader_mod

    calls = {"n": 0}
    real = loader_mod._file_open

    def _open(path, mode="rb"):
        calls["n"] += 1
        if calls["n"] <= fail_times:
            with real(path, "rb") as f:
                data = f.read()
            return io.BytesIO(data[:int(len(data) * fraction)])
        return real(path, mode)

    loader_mod._file_open = _open
    try:
        yield calls
    finally:
        loader_mod._file_open = real


# ---- process faults --------------------------------------------------------

def inject_at_call(step: Callable, n: int, action: Callable[[], None]) -> Callable:
    """Wrap a train step so ``action()`` runs immediately AFTER the ``n``-th
    call (1-based) returns — e.g. ``lambda: os.kill(os.getpid(),
    signal.SIGKILL)`` for an abrupt preemption, or ``signal.raise_signal``
    for a graceful one. The wrapped step is otherwise transparent."""
    count = {"i": 0}

    def wrapped(state, batch, key):
        out = step(state, batch, key)
        count["i"] += 1
        if count["i"] == n:
            action()
        return out

    return wrapped
