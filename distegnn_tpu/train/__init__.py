"""Training runtime (L5): jitted step, losses, checkpointing, outer loop."""

from distegnn_tpu.train.checkpoint import (
    CheckpointCorruptError,
    RestoredRun,
    ResumeConsensusError,
    find_resume_checkpoint,
    restore_checkpoint,
    restore_for_resume,
    save_checkpoint,
    verify_checkpoint,
)
from distegnn_tpu.train.loss import (
    masked_mse,
    mmd_loss,
    weighted_global_loss,
    weighted_local_loss,
)
from distegnn_tpu.train.step import (
    TrainState,
    make_eval_step,
    make_loss_fn,
    make_optimizer,
    make_train_step,
    needs_grad_clip,
)
from distegnn_tpu.train.trainer import run_epoch_eval, run_epoch_train, train

__all__ = [
    "TrainState",
    "make_optimizer",
    "make_loss_fn",
    "make_train_step",
    "make_eval_step",
    "needs_grad_clip",
    "masked_mse",
    "mmd_loss",
    "weighted_global_loss",
    "weighted_local_loss",
    "save_checkpoint",
    "restore_checkpoint",
    "restore_for_resume",
    "find_resume_checkpoint",
    "verify_checkpoint",
    "CheckpointCorruptError",
    "ResumeConsensusError",
    "RestoredRun",
    "train",
    "run_epoch_train",
    "run_epoch_eval",
]
