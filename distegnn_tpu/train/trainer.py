"""Outer training loop (reference train(), utils/train.py:171-289).

Epoch structure, best-model tracking on valid loss, early stopping, best/last
checkpointing, per-epoch log.json, optional wandb, wall-clock time_cost — all
preserved. Host-side logic keys off ``jax.process_index() == 0`` instead of
rank 0; there is no early-stop allreduce because every host computes the same
loop state deterministically (same losses via psum-inside-jit, same epochs) —
the reference needs the MAX-allreduce only because its flag is set on rank 0
alone (utils/train.py:261-267).

Resilience layer (docs/ROBUSTNESS.md):
  - wall-clock cadence checkpoints (``train.checkpoint_interval_s``) written
    MID-epoch as ``step_<n>.ckpt`` with rotation (``train.keep_checkpoints``),
    so a preemptible session never loses more than the cadence;
  - a SIGTERM/SIGINT guard that finishes the in-flight step, writes
    ``preempt_model.ckpt`` + a ``PREEMPTED`` marker, and returns with
    ``best['preempted']`` set (main.py exits 75 — resumable);
  - divergence recovery: a non-finite epoch loss rolls back to the last
    finite-loss state, decays the LR by ``train.divergence_lr_decay`` (when a
    ``step_factory`` is provided), and retries up to
    ``train.divergence_retries`` times before declaring the run dead in
    log.json — the old stop-on-NaN behavior is the retries=0 case.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distegnn_tpu import obs
from distegnn_tpu.obs import jaxprobe


def _fmt(loss: float) -> str:
    """Loss for humans: fixed-point at ordinary scales, scientific once the
    value would round to 0.00000 (e.g. tiny-displacement fluid targets)."""
    return f"{loss:.5f}" if loss >= 1e-4 else f"{loss:.3e}"


class PreemptionGuard:
    """Cooperative SIGTERM/SIGINT handling: the first signal sets a flag that
    the epoch loop checks AFTER each completed step (the in-flight step always
    finishes — its dispatch is already enqueued and the checkpoint fetch syncs
    on it); a second signal restores default handling so a stuck run can still
    be killed. Handlers only install from the main thread (signal.signal
    raises elsewhere — e.g. trainer invocations inside test harness threads),
    and the previous handlers are restored by :meth:`uninstall`.

    Multi-host: each process reacts to ITS OWN signal, but the stop decision
    is COORDINATED — :meth:`stop_agreed` allgathers the local flag at every
    step boundary, so a SIGTERM delivered to one host (preemption notices
    rarely reach all hosts in the same step) stops every host after the SAME
    completed step. The flag is armed by the signal handler and observed one
    step later at the shared boundary; hosts that never saw a signal adopt
    the remote request, so the (epoch, step_in_epoch) recorded in the
    preempt checkpoint is a single cross-host value — which resume then
    verifies with checkpoint.verify_resume_consensus. ``allgather`` is
    injectable for single-process drills (tests/test_tensor_parallel.py)."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, allgather=None):
        self.requested = False
        self.signum: Optional[int] = None
        self.interrupted = False   # set by run_epoch_train on a mid-epoch break
        self.steps_done = 0        # steps of the current epoch applied at break
        self._prev: dict = {}
        self._allgather = allgather  # None -> multihost_utils when multi-host

    def stop_agreed(self) -> bool:
        """The cross-host stop barrier, called between steps: True iff ANY
        process has a stop request. Single-process with no injected
        allgather this is the plain local flag (no collective)."""
        ag = self._allgather
        if ag is None:
            if jax.process_count() == 1:
                return self.requested
            from jax.experimental import multihost_utils

            def ag(x):
                return np.asarray(multihost_utils.process_allgather(x))

        flags = np.asarray(
            ag(np.asarray([1 if self.requested else 0], dtype=np.int32))
        ).reshape(-1)
        agreed = bool(flags.any())
        if agreed and not self.requested:
            # adopt the remote host's request so this host checkpoints the
            # same (epoch, step) coordinates and exits resumable too
            self.requested = True
            self.signum = self.signum or signal.SIGTERM
            obs.log("preemption: adopting a remote host's stop request at "
                    "the step barrier")
        return agreed

    def _handle(self, signum, frame):
        if self.requested:  # second signal: give up on the graceful path
            signal.signal(signum, self._prev.get(signum, signal.SIG_DFL))
            raise KeyboardInterrupt(f"second signal {signum} during preemption")
        self.requested = True
        self.signum = signum
        obs.log(f"preemption: caught signal {signum}; finishing the in-flight "
                "step and checkpointing", signal=signum)

    def install(self) -> "PreemptionGuard":
        if threading.current_thread() is not threading.main_thread():
            return self
        for sig in self.SIGNALS:
            try:
                self._prev[sig] = signal.signal(sig, self._handle)
            except (ValueError, OSError):
                pass
        return self

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._prev.clear()


class CadenceSaver:
    """Wall-clock mid-epoch checkpointing (``train.checkpoint_interval_s``):
    every ``interval_s`` seconds of training, write ``step_<n>.ckpt`` (epoch +
    step_in_epoch recorded so resume replays the schedule exactly) and rotate,
    keeping the newest ``keep``. interval_s <= 0 or enabled=False is a no-op
    saver, so the epoch loop never branches on configuration."""

    def __init__(self, ckpt_dir: str, interval_s: float, keep: int,
                 config: Optional[dict], seed: Optional[int],
                 enabled: bool = True, publisher=None):
        self.ckpt_dir = ckpt_dir
        self.interval_s = float(interval_s or 0)
        self.keep = max(int(keep), 1)
        self.config = config
        self.seed = seed
        self.enabled = enabled and self.interval_s > 0
        self._last = time.monotonic()
        self.saves = 0
        # promotion conveyor (promote.publish): after each save+rotation the
        # checkpoint is republished as a serving candidate. None = training
        # island only. Latest eval loss rides the candidate manifest so the
        # promoter can attribute a candidate to its validation quality.
        self.publisher = publisher
        self.last_val_loss: Optional[float] = None

    def maybe_save(self, state, completed_epoch: int, step_in_epoch: int) -> None:
        if not self.enabled or time.monotonic() - self._last < self.interval_s:
            return
        from distegnn_tpu.train.checkpoint import (rotate_checkpoints,
                                                   save_checkpoint,
                                                   step_checkpoint_name)

        path = os.path.join(self.ckpt_dir, step_checkpoint_name(int(state.step)))
        save_checkpoint(path, state, completed_epoch, config=self.config,
                        seed=self.seed, step_in_epoch=step_in_epoch)
        rotate_checkpoints(self.ckpt_dir, self.keep)
        self._last = time.monotonic()
        self.saves += 1
        if self.publisher is not None:
            try:
                self.publisher.publish(path, step=int(state.step),
                                       val_loss=self.last_val_loss,
                                       config=self.config)
            except Exception as exc:
                # the conveyor never stops training: a full/unwritable
                # watch_dir just delays promotion to the next rotation
                obs.log(f"promote: candidate publish failed for step "
                        f"{int(state.step)}: {exc!r}")


def run_epoch_train(train_step: Callable, state, loader, seed: int, epoch: int,
                    start_step: int = 0,
                    guard: Optional[PreemptionGuard] = None,
                    cadence: Optional[CadenceSaver] = None,
                    tracer=None, step_events: bool = False):
    """One training epoch. Returns (state, avg loss) — the average of the
    per-step node-weighted global MSE weighted by batch size (reference
    result['loss']/result['counter'], utils/train.py:29,112-114).

    The loss accumulates ON DEVICE (tiny scalar adds enqueued asynchronously);
    the single host fetch happens once per epoch. Round 1 called
    ``float(loss)`` per step, forcing a blocking device round-trip per
    micro-batch and defeating XLA async dispatch (VERDICT r1 weak #3).

    ``start_step``: skip the first N batches — they were already applied to
    the state held by the mid-epoch checkpoint being resumed (the loader
    order and per-step PRNG keys derive from (seed, epoch, step_idx) only, so
    skipping replays the exact schedule). The returned average then covers
    the resumed span only. ``guard``/``cadence`` hook preemption checks and
    wall-clock checkpointing between steps (docs/ROBUSTNESS.md).

    ``tracer``/``step_events``: emit one ``train/step`` event per step with
    the host-observed dispatch time and the loader-stall delta since the
    previous step (the loaders add their collation/put time to the global
    ``data/stall_s`` counter; reading the delta here attributes it per step
    without a second clock in the loader's hot path)."""
    loader.set_epoch(epoch)
    try:
        steps_total = len(loader)
    except TypeError:
        steps_total = None
    reg = obs.get_registry()
    stall_c = reg.counter("data/stall_s")
    step_res = reg.reservoir("train/step_ms")
    emit = step_events and tracer is not None and tracer.enabled
    stall_prev = stall_c.value
    total, counter, cons = None, 0.0, None
    for step_idx, batch in enumerate(loader):
        if step_idx < start_step:
            stall_prev = stall_c.value
            continue  # applied before the checkpoint this run resumed from
        key = jax.random.PRNGKey(seed)
        key = jax.random.fold_in(jax.random.fold_in(key, epoch), step_idx)
        t_step = time.perf_counter()
        state, metrics = train_step(state, batch, key)
        dt_step = time.perf_counter() - t_step
        step_res.record(1e3 * dt_step)
        if emit:
            stall_now = stall_c.value
            tracer.event("train/step", epoch=epoch, step=step_idx,
                         dur_s=round(dt_step, 6),
                         stall_s=round(stall_now - stall_prev, 6))
            stall_prev = stall_now
        bsz = batch.loc.shape[-3] if batch.loc.ndim == 4 else batch.loc.shape[0]
        contrib = metrics["loss"] * bsz
        total = contrib if total is None else total + contrib
        counter += bsz
        if "batch_consistency" in metrics:  # device-side max, no extra sync
            c = metrics["batch_consistency"]
            cons = c if cons is None else jnp.maximum(cons, c)
        if cadence is not None:
            if steps_total is not None and step_idx + 1 == steps_total:
                # the save lands ON the epoch boundary: record it as
                # (epoch, 0), not (epoch-1, full) — a resume then starts the
                # NEXT epoch instead of skip-replaying an empty remainder
                cadence.maybe_save(state, epoch, 0)
            else:
                cadence.maybe_save(state, epoch - 1, step_idx + 1)
        if guard is not None and guard.stop_agreed():
            guard.interrupted = True
            guard.steps_done = step_idx + 1
            break
    avg = float(total) / max(counter, 1.0) if total is not None else 0.0
    assert_batch_consistency(cons, epoch)
    return state, avg


def assert_batch_consistency(cons, epoch: int) -> None:
    """Host-side assert of the in-step loc_mean residual (train/step.py):
    every graph-axis rank must have fed the same logical batch — the
    reference's per-step all_gather check (utils/train.py:55-61) at the cost
    of one scalar fetch per epoch (the epoch's loss fetch already syncs)."""
    # NOT `> 0`: a corrupted shard can carry NaN, and NaN residuals must
    # fail too — only an exactly-zero residual proves bitwise-identical
    # loc_mean across ranks.
    if cons is not None and not float(cons) == 0.0:
        raise AssertionError(
            f"cross-rank batch mismatch at epoch {epoch}: loc_mean residual "
            f"{float(cons):g} != 0 — hosts/partitions fed different logical "
            "batches (loader order drift or corrupted shard data)")


def run_epoch_eval(eval_step: Callable, params, loader):
    total, counter = None, 0.0
    for batch in loader:
        loss = eval_step(params, batch)
        bsz = batch.loc.shape[-3] if batch.loc.ndim == 4 else batch.loc.shape[0]
        contrib = loss * bsz
        total = contrib if total is None else total + contrib
        counter += bsz
    return float(total) / max(counter, 1.0) if total is not None else 0.0


def train(
    state,
    train_step: Callable,
    eval_step: Callable,
    loader_train,
    loader_valid,
    loader_test,
    config,
    start_epoch: int = 0,
    log: bool = True,
    scan_runner=None,
    start_step_in_epoch: int = 0,
    step_factory: Optional[Callable] = None,
):
    """Full training run. Returns (state, best_log_dict, log_dict).

    ``scan_runner`` (train/scan_epoch.ScanEpochRunner) replaces the host-side
    epoch loops with one lax.scan dispatch per epoch — same permutation, same
    PRNG keys, same result; only the dispatch granularity changes.

    ``start_step_in_epoch``: steps of epoch ``start_epoch + 1`` already
    applied to ``state`` (a mid-epoch cadence/preempt checkpoint); the first
    epoch skips exactly those batches. ``step_factory(lr_scale)`` rebuilds
    the jitted train step with a scaled learning rate — divergence recovery
    uses it to retry from the last finite state at a decayed LR (without a
    factory, retries replay at the original LR, which still recovers
    transient NaN batches)."""
    train_cfg, log_cfg = config.train, config.log
    seed = config.seed
    is_main = jax.process_index() == 0

    # start_epoch is recorded so artifact tooling can place the per-epoch
    # arrays (loss_train, epoch_time — appended from epoch start_epoch+1 on)
    # at absolute epoch numbers when merging staged/resumed runs.
    log_dict = {"epochs": [], "loss": [], "loss_train": [], "epoch_time": [],
                "start_epoch": start_epoch, "divergence_events": []}
    # epoch_index starts at start_epoch (not 0) so a checkpoint-resumed run
    # past the early_stop horizon doesn't spuriously stop before its first eval
    best = {"epoch_index": start_epoch, "loss_valid": 1e8, "loss_test": 1e8,
            "loss_train": 1e8}
    best_state = state

    exp_dir = os.path.join(log_cfg.log_dir, log_cfg.get("exp_name", "run"))
    log_dir = os.path.join(exp_dir, "log")
    ckpt_dir = os.path.join(exp_dir, "state_dict")
    wandb_run = None
    if is_main and log:
        os.makedirs(log_dir, exist_ok=True)
        os.makedirs(ckpt_dir, exist_ok=True)
        if log_cfg.wandb.enable:
            wandb_run = _init_wandb(config, exp_dir)
    # observability (docs/OBSERVABILITY.md): bind the event sink under this
    # run's exp_dir and point the compile watcher at it. log=False runs
    # (tests, replay harnesses) stay sinkless — no files, no-op spans.
    obs_cfg = config.get("obs") or {}
    tracer = obs.configure_from_config(
        config, exp_dir, enabled_here=log,
        tags={"run": log_cfg.get("exp_name", "run")})
    step_events = bool(obs_cfg.get("step_events", True))
    stall_c = obs.get_registry().counter("data/stall_s")
    # mesh tag for the per-chip memory gauges: the (data, graph, tensor)
    # shape the run resolved (launch.py records it; single-device runs
    # default to 1x1x1), so HBM numbers are comparable ACROSS mesh shapes
    pmesh = (config.get("parallel") or {}).get("mesh") or {}
    mesh_tag = "x".join(str(int(pmesh.get(k) or 1))
                        for k in ("data", "graph", "tensor"))
    tracer.event("train/run_start", start_epoch=start_epoch,
                 epochs=int(train_cfg.epochs),
                 scan_epochs=scan_runner is not None,
                 devices=jax.device_count(), processes=jax.process_count(),
                 mesh=mesh_tag)
    jaxprobe.emit_memory_event(tracer, phase="run_start", mesh=mesh_tag)
    jaxprobe.record_memory_gauges("run_start")
    if start_epoch or start_step_in_epoch:
        tracer.event("train/resume", epoch=start_epoch,
                     step_in_epoch=int(start_step_in_epoch or 0))
    start = time.perf_counter()

    cfg_dict = config.to_dict() if hasattr(config, "to_dict") else dict(config)
    guard = PreemptionGuard().install()
    # trainer end of the promotion conveyor (docs/SERVING.md "Continuous
    # promotion"): every rotated cadence checkpoint is republished as a
    # candidate the serving gateway's promoter can canary. process 0 only —
    # same ownership rule as the checkpoints themselves.
    publisher = None
    pm_cfg = config.get("promote") or {}
    if (is_main and log and pm_cfg.get("publish")
            and str(pm_cfg.get("watch_dir", "")).strip()):
        from distegnn_tpu.promote.publish import CandidatePublisher

        publisher = CandidatePublisher(str(pm_cfg["watch_dir"]),
                                       history=int(pm_cfg.get("history", 4)))
    cadence = CadenceSaver(
        ckpt_dir, train_cfg.get("checkpoint_interval_s", 0),
        train_cfg.get("keep_checkpoints", 3), cfg_dict, seed,
        enabled=is_main and log, publisher=publisher)
    retries_left = int(train_cfg.get("divergence_retries", 0) or 0)
    lr_decay = float(train_cfg.get("divergence_lr_decay", 0.5) or 0.5)
    lr_scale = 1.0
    try:
        steps_per_epoch = len(loader_train)
    except TypeError:
        steps_per_epoch = None
    # last finite-loss state + the log lengths at that point, so a divergence
    # rollback also rewinds the curves (merge tooling maps loss_train[i] to
    # absolute epoch start_epoch+1+i — retried epochs must not double-append)
    finite_snap = (state, start_epoch, 0, 0)

    def _preempt_exit(completed_epoch: int, step_in_epoch: int) -> None:
        from distegnn_tpu.train.checkpoint import (save_checkpoint,
                                                   write_preempt_marker)

        name = "preempt_model.ckpt"
        if is_main and log:
            save_checkpoint(os.path.join(ckpt_dir, name), state,
                            completed_epoch, config=cfg_dict, seed=seed,
                            step_in_epoch=step_in_epoch)
            write_preempt_marker(ckpt_dir, name, completed_epoch, step_in_epoch)
            obs.log(f"PREEMPTED (signal {guard.signum}): checkpointed "
                    f"epoch {completed_epoch} + {step_in_epoch} step(s) to "
                    f"{os.path.join(ckpt_dir, name)}; resume with "
                    "train.resume: auto")
        tracer.event("train/preempt", epoch=completed_epoch,
                     step_in_epoch=step_in_epoch, signal=guard.signum)
        tracer.flush()
        best["preempted"] = {"epoch": completed_epoch,
                             "step_in_epoch": step_in_epoch,
                             "signal": guard.signum,
                             "checkpoint": os.path.join(ckpt_dir, name)}
        _write_log_json(log_dir, best, log_dict, config, start, is_main and log)

    try:
        epoch = start_epoch  # last COMPLETED epoch; the loop body runs epoch+1
        resume_step = int(start_step_in_epoch or 0)
        warmup_marked = False
        while epoch < train_cfg.epochs:
            epoch += 1
            jaxprobe.set_phase(f"epoch{epoch}")
            t_epoch = time.perf_counter()
            stall_e0 = stall_c.value
            # optional device trace of exactly one epoch (log.trace_epoch):
            # SURVEY §5.1 observability — the per-op timeline behind the
            # epoch_time numbers, viewable in TensorBoard/Perfetto
            tracing = is_main and log and log_cfg.get("trace_epoch", 0) == epoch
            if tracing:
                trace_dir = os.path.join(exp_dir, "trace")
                os.makedirs(trace_dir, exist_ok=True)
                jax.profiler.start_trace(trace_dir)
            guard.interrupted, guard.steps_done = False, 0
            # a mid-epoch resume replays the remainder through the host loop
            # (lax.scan can't skip applied steps); identical math — the scan
            # runner uses the same permutation and PRNG keys by construction
            if scan_runner is not None and resume_step == 0:
                state, loss_train = scan_runner.train_epoch(state, epoch)
                loss_train = float(loss_train)
            else:
                state, loss_train = run_epoch_train(
                    train_step, state, loader_train, seed, epoch,
                    start_step=resume_step, guard=guard, cadence=cadence,
                    tracer=tracer, step_events=step_events)
            resume_step = 0  # only the first resumed epoch skips steps
            if tracing:
                jax.profiler.stop_trace()
                obs.log(f"profiler trace of epoch {epoch} written to {trace_dir}")
            dt_epoch = time.perf_counter() - t_epoch

            # preemption mid-epoch: the state holds a PARTIAL epoch — checkpoint
            # it with its intra-epoch step count (resume replays the remainder)
            # and do NOT log the partial-span loss average as the epoch's loss
            if (guard.interrupted and (steps_per_epoch is None
                                       or guard.steps_done < steps_per_epoch)):
                _preempt_exit(epoch - 1, guard.steps_done)
                break

            log_dict["loss_train"].append(loss_train)
            # observability (SURVEY §5.1/§5.5): per-epoch wall time is recorded in
            # log.json; the fetch of loss_train above is the epoch's one host sync,
            # so dt_epoch covers the full device time of the epoch
            log_dict["epoch_time"].append(round(dt_epoch, 4))
            tracer.event(
                "train/epoch", epoch=epoch, dur_s=round(dt_epoch, 4),
                stall_s=round(stall_c.value - stall_e0, 4),
                loss_train=(loss_train if np.isfinite(loss_train)
                            else repr(loss_train)))

            # failure detection (SURVEY §5.3, beyond reference parity): a
            # diverged run never recovers on its own, and unattended hardware
            # sessions (scripts/convergence_session.sh) would otherwise burn the
            # whole tunnel window training on NaN. With divergence_retries left,
            # roll back to the last finite-loss state, decay the LR, and retry;
            # otherwise record the diagnosis in log.json and stop (the last good
            # checkpoint remains on disk for a manual lower-LR resume).
            if not np.isfinite(loss_train):
                if retries_left > 0:
                    retries_left -= 1
                    state, snap_epoch, n_tr, n_ev = finite_snap
                    if step_factory is not None:
                        lr_scale *= lr_decay
                        # factories may return (train_step, device_step): the
                        # distribute path scans a PER-DEVICE step while the
                        # host loop drives the shard_mapped one (launch.py)
                        new_step = step_factory(lr_scale)
                        train_step, dev_step = (
                            new_step if isinstance(new_step, tuple)
                            else (new_step, new_step))
                        if scan_runner is not None:
                            scan_runner = scan_runner.with_train_step(dev_step)
                    # rewind the curves to the snapshot so retried epochs keep
                    # their absolute-epoch alignment
                    del log_dict["loss_train"][n_tr:], log_dict["epoch_time"][n_tr:]
                    del log_dict["epochs"][n_ev:], log_dict["loss"][n_ev:]
                    log_dict["divergence_events"].append(
                        {"epoch": epoch, "loss_train": repr(loss_train),
                         "rolled_back_to": snap_epoch, "lr_scale": lr_scale,
                         "retries_left": retries_left})
                    tracer.event("train/divergence", epoch=epoch,
                                 loss_train=repr(loss_train),
                                 retries_left=retries_left)
                    tracer.event("train/rollback", epoch=epoch,
                                 rolled_back_to=snap_epoch,
                                 lr_scale=round(lr_scale, 6))
                    if is_main:
                        obs.log(f"DIVERGED at epoch {epoch}: train loss {loss_train}"
                                f"; rolling back to epoch {snap_epoch} state, "
                                f"lr_scale={lr_scale:g} ({retries_left} retries "
                                "left)")
                    epoch = snap_epoch
                    continue
                # repr(), not the float: json.dump would emit a bare NaN token,
                # which strict RFC-8259 consumers (jq, JSON.parse) reject
                best["diverged"] = {"epoch": epoch, "loss_train": repr(loss_train),
                                    "retries_exhausted":
                                        int(train_cfg.get("divergence_retries", 0) or 0)}
                tracer.event("train/divergence", epoch=epoch,
                             loss_train=repr(loss_train), fatal=True)
                if is_main:
                    obs.log(f"DIVERGED at epoch {epoch}: train loss {loss_train}; "
                            "stopping (divergence retries exhausted — resume from "
                            "the last checkpoint with a lower lr)")
                _write_log_json(log_dir, best, log_dict, config, start, is_main and log)
                break
            finite_snap = (state, epoch, len(log_dict["loss_train"]),
                           len(log_dict["epochs"]))

            # preemption at an epoch boundary (scan-runner epochs, or the signal
            # landed on the last step): checkpoint the completed epoch and exit
            # BEFORE eval — a SIGTERM grace window is seconds, not an eval epoch
            if guard.stop_agreed():
                _preempt_exit(epoch, 0)
                break

            if epoch % log_cfg.test_interval == 0:
                t_eval = time.perf_counter()
                if scan_runner is not None:
                    loss_valid = scan_runner.eval_epoch(state.params, "valid")
                    loss_test = scan_runner.eval_epoch(state.params, "test")
                else:
                    loss_valid = run_epoch_eval(eval_step, state.params, loader_valid)
                    loss_test = run_epoch_eval(eval_step, state.params, loader_test)
                tracer.event("train/eval", epoch=epoch,
                             dur_s=round(time.perf_counter() - t_eval, 4),
                             loss_valid=float(loss_valid),
                             loss_test=float(loss_test))
                if np.isfinite(loss_valid):
                    # candidates published after this eval carry this loss
                    cadence.last_val_loss = float(loss_valid)
                if not warmup_marked:
                    # eval_step compiles at the FIRST eval epoch — only once
                    # both train and eval programs have run is every further
                    # compile a true (alarm-worthy) recompile
                    warmup_marked = True
                    jaxprobe.mark_warmup_done()
                    # steady-state HBM snapshot: both compiled programs have
                    # run, so peak_bytes_in_use now covers the real footprint
                    # — paired with the run_start gauge, the delta is what a
                    # T-way tensor shard is supposed to shrink
                    jaxprobe.emit_memory_event(tracer, phase="post_warmup",
                                               mesh=mesh_tag)
                    jaxprobe.record_memory_gauges("post_warmup")
                if log_cfg.get("check_consistency", True):
                    from distegnn_tpu.parallel.checks import assert_replicated

                    assert_replicated(state.params)
                log_dict["epochs"].append(epoch)
                log_dict["loss"].append(loss_test)

                if loss_valid < best["loss_valid"]:
                    best = {"epoch_index": epoch, "loss_valid": loss_valid,
                            "loss_test": loss_test, "loss_train": loss_train}
                    best_state = state
                    if is_main and log:
                        _save(ckpt_dir, "best_model.ckpt", state, epoch, best, config)
                if is_main and log:
                    _save(ckpt_dir, "last_model.ckpt", state, epoch,
                          {"loss_train": loss_train, "loss_valid": loss_valid, "loss_test": loss_test},
                          config)
                    if wandb_run is not None:
                        wandb_run.log({"loss_train": loss_train, "loss_valid": loss_valid,
                                       "loss_test": loss_test, "epoch_time": dt_epoch},
                                      step=epoch)
                    obs.log(f"Epoch {epoch} | train {_fmt(loss_train)} | "
                            f"valid {_fmt(loss_valid)} | test {_fmt(loss_test)} | "
                            f"{dt_epoch:.2f}s/epoch")
                    obs.log(f"*** Best Valid Loss: {_fmt(best['loss_valid'])} | "
                            f"Best Test Loss: {_fmt(best['loss_test'])} | "
                            f"Best Epoch Index: {best['epoch_index']}")

            elif is_main and log and wandb_run is not None:
                wandb_run.log({"loss_train": loss_train, "epoch_time": dt_epoch},
                              step=epoch)

            # early stop is evaluated EVERY epoch, not only on eval epochs —
            # reference checks it at the bottom of each epoch (utils/train.py:261-267)
            if epoch - best["epoch_index"] >= train_cfg.early_stop:
                best["early_stop"] = epoch
                if is_main:
                    obs.log(f"Early stopped! Epoch: {epoch}")
                _write_log_json(log_dir, best, log_dict, config, start, is_main and log)
                break

            _write_log_json(log_dir, best, log_dict, config, start, is_main and log)

    finally:
        guard.uninstall()
        tracer.flush()
    if wandb_run is not None:
        wandb_run.log({"best_test_loss": best["loss_test"]})
        wandb_run.finish()
    return state, best_state, best, log_dict


def _save(ckpt_dir, name, state, epoch, losses, config):
    from distegnn_tpu.train.checkpoint import save_checkpoint

    cfg = config.to_dict() if hasattr(config, "to_dict") else dict(config)
    save_checkpoint(os.path.join(ckpt_dir, name), state, epoch, losses=losses,
                    config=cfg, seed=cfg.get("seed") if isinstance(cfg, dict) else None)


def _sanitize_nonfinite(log_dict):
    """Replace non-finite floats with None (json null): json.dump would emit
    bare NaN/Infinity tokens, which strict RFC-8259 consumers reject — and a
    diverged run DOES put NaN in the loss curves."""
    def fix(v):
        if isinstance(v, float) and not np.isfinite(v):
            return None
        return v

    return {k: [fix(v) for v in vals] if isinstance(vals, list) else vals
            for k, vals in log_dict.items()}


def _write_log_json(log_dir, best, log_dict, config, start, enabled):
    if not enabled:
        return
    best["time_cost"] = time.perf_counter() - start
    cfg = config.to_dict() if hasattr(config, "to_dict") else dict(config)
    with open(os.path.join(log_dir, "log.json"), "w") as f:
        json.dump([best, _sanitize_nonfinite(log_dict), cfg], f, indent=4)


def _init_wandb(config, exp_dir):
    """wandb init (reference utils/train.py:185-198): offline-capable, env-var
    API key, group = dataset name. Returns None if wandb isn't importable."""
    try:
        import wandb
    except ImportError:
        return None
    log_cfg = config.log
    if log_cfg.wandb.api_key:
        os.environ["WANDB_API_KEY"] = log_cfg.wandb.api_key
    if log_cfg.wandb.offline:
        os.environ["WANDB_MODE"] = "offline"
    cfg = config.to_dict() if hasattr(config, "to_dict") else dict(config)
    return wandb.init(
        config=cfg,
        project=log_cfg.wandb.project or None,
        entity=log_cfg.wandb.entity or None,
        group=f"{config.data.dataset_name}",
        name=log_cfg.exp_name,
        dir=exp_dir,
        reinit=True,
    )
