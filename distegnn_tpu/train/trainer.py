"""Outer training loop (reference train(), utils/train.py:171-289).

Epoch structure, best-model tracking on valid loss, early stopping, best/last
checkpointing, per-epoch log.json, optional wandb, wall-clock time_cost — all
preserved. Host-side logic keys off ``jax.process_index() == 0`` instead of
rank 0; there is no early-stop allreduce because every host computes the same
loop state deterministically (same losses via psum-inside-jit, same epochs) —
the reference needs the MAX-allreduce only because its flag is set on rank 0
alone (utils/train.py:261-267).
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


def _fmt(loss: float) -> str:
    """Loss for humans: fixed-point at ordinary scales, scientific once the
    value would round to 0.00000 (e.g. tiny-displacement fluid targets)."""
    return f"{loss:.5f}" if loss >= 1e-4 else f"{loss:.3e}"


def run_epoch_train(train_step: Callable, state, loader, seed: int, epoch: int):
    """One training epoch. Returns (state, avg loss) — the average of the
    per-step node-weighted global MSE weighted by batch size (reference
    result['loss']/result['counter'], utils/train.py:29,112-114).

    The loss accumulates ON DEVICE (tiny scalar adds enqueued asynchronously);
    the single host fetch happens once per epoch. Round 1 called
    ``float(loss)`` per step, forcing a blocking device round-trip per
    micro-batch and defeating XLA async dispatch (VERDICT r1 weak #3)."""
    loader.set_epoch(epoch)
    total, counter, cons = None, 0.0, None
    for step_idx, batch in enumerate(loader):
        key = jax.random.PRNGKey(seed)
        key = jax.random.fold_in(jax.random.fold_in(key, epoch), step_idx)
        state, metrics = train_step(state, batch, key)
        bsz = batch.loc.shape[-3] if batch.loc.ndim == 4 else batch.loc.shape[0]
        contrib = metrics["loss"] * bsz
        total = contrib if total is None else total + contrib
        counter += bsz
        if "batch_consistency" in metrics:  # device-side max, no extra sync
            c = metrics["batch_consistency"]
            cons = c if cons is None else jnp.maximum(cons, c)
    avg = float(total) / max(counter, 1.0) if total is not None else 0.0
    assert_batch_consistency(cons, epoch)
    return state, avg


def assert_batch_consistency(cons, epoch: int) -> None:
    """Host-side assert of the in-step loc_mean residual (train/step.py):
    every graph-axis rank must have fed the same logical batch — the
    reference's per-step all_gather check (utils/train.py:55-61) at the cost
    of one scalar fetch per epoch (the epoch's loss fetch already syncs)."""
    # NOT `> 0`: a corrupted shard can carry NaN, and NaN residuals must
    # fail too — only an exactly-zero residual proves bitwise-identical
    # loc_mean across ranks.
    if cons is not None and not float(cons) == 0.0:
        raise AssertionError(
            f"cross-rank batch mismatch at epoch {epoch}: loc_mean residual "
            f"{float(cons):g} != 0 — hosts/partitions fed different logical "
            "batches (loader order drift or corrupted shard data)")


def run_epoch_eval(eval_step: Callable, params, loader):
    total, counter = None, 0.0
    for batch in loader:
        loss = eval_step(params, batch)
        bsz = batch.loc.shape[-3] if batch.loc.ndim == 4 else batch.loc.shape[0]
        contrib = loss * bsz
        total = contrib if total is None else total + contrib
        counter += bsz
    return float(total) / max(counter, 1.0) if total is not None else 0.0


def train(
    state,
    train_step: Callable,
    eval_step: Callable,
    loader_train,
    loader_valid,
    loader_test,
    config,
    start_epoch: int = 0,
    log: bool = True,
    scan_runner=None,
):
    """Full training run. Returns (state, best_log_dict, log_dict).

    ``scan_runner`` (train/scan_epoch.ScanEpochRunner) replaces the host-side
    epoch loops with one lax.scan dispatch per epoch — same permutation, same
    PRNG keys, same result; only the dispatch granularity changes."""
    train_cfg, log_cfg = config.train, config.log
    seed = config.seed
    is_main = jax.process_index() == 0

    # start_epoch is recorded so artifact tooling can place the per-epoch
    # arrays (loss_train, epoch_time — appended from epoch start_epoch+1 on)
    # at absolute epoch numbers when merging staged/resumed runs.
    log_dict = {"epochs": [], "loss": [], "loss_train": [], "epoch_time": [],
                "start_epoch": start_epoch}
    # epoch_index starts at start_epoch (not 0) so a checkpoint-resumed run
    # past the early_stop horizon doesn't spuriously stop before its first eval
    best = {"epoch_index": start_epoch, "loss_valid": 1e8, "loss_test": 1e8,
            "loss_train": 1e8}
    best_state = state

    exp_dir = os.path.join(log_cfg.log_dir, log_cfg.get("exp_name", "run"))
    log_dir = os.path.join(exp_dir, "log")
    ckpt_dir = os.path.join(exp_dir, "state_dict")
    wandb_run = None
    if is_main and log:
        os.makedirs(log_dir, exist_ok=True)
        os.makedirs(ckpt_dir, exist_ok=True)
        if log_cfg.wandb.enable:
            wandb_run = _init_wandb(config, exp_dir)
    start = time.perf_counter()

    for epoch in range(1 + start_epoch, train_cfg.epochs + 1):
        t_epoch = time.perf_counter()
        # optional device trace of exactly one epoch (log.trace_epoch):
        # SURVEY §5.1 observability — the per-op timeline behind the
        # epoch_time numbers, viewable in TensorBoard/Perfetto
        tracing = is_main and log and log_cfg.get("trace_epoch", 0) == epoch
        if tracing:
            trace_dir = os.path.join(exp_dir, "trace")
            os.makedirs(trace_dir, exist_ok=True)
            jax.profiler.start_trace(trace_dir)
        if scan_runner is not None:
            state, loss_train = scan_runner.train_epoch(state, epoch)
            loss_train = float(loss_train)
        else:
            state, loss_train = run_epoch_train(train_step, state, loader_train, seed, epoch)
        if tracing:
            jax.profiler.stop_trace()
            print(f"profiler trace of epoch {epoch} written to {trace_dir}", flush=True)
        dt_epoch = time.perf_counter() - t_epoch
        log_dict["loss_train"].append(loss_train)
        # observability (SURVEY §5.1/§5.5): per-epoch wall time is recorded in
        # log.json; the fetch of loss_train above is the epoch's one host sync,
        # so dt_epoch covers the full device time of the epoch
        log_dict["epoch_time"].append(round(dt_epoch, 4))

        # failure detection (SURVEY §5.3, beyond reference parity): a
        # diverged run never recovers on its own, and unattended hardware
        # sessions (scripts/convergence_session.sh) would otherwise burn the
        # whole tunnel window training on NaN. Record the diagnosis in
        # log.json and stop; the last good checkpoint (last eval epoch)
        # remains on disk for a lower-LR resume.
        if not np.isfinite(loss_train):
            # repr(), not the float: json.dump would emit a bare NaN token,
            # which strict RFC-8259 consumers (jq, JSON.parse) reject
            best["diverged"] = {"epoch": epoch, "loss_train": repr(loss_train)}
            if is_main:
                print(f"DIVERGED at epoch {epoch}: train loss {loss_train}; "
                      "stopping (resume from the last checkpoint with a "
                      "lower lr)", flush=True)
            _write_log_json(log_dir, best, log_dict, config, start, is_main and log)
            break

        if epoch % log_cfg.test_interval == 0:
            if scan_runner is not None:
                loss_valid = scan_runner.eval_epoch(state.params, "valid")
                loss_test = scan_runner.eval_epoch(state.params, "test")
            else:
                loss_valid = run_epoch_eval(eval_step, state.params, loader_valid)
                loss_test = run_epoch_eval(eval_step, state.params, loader_test)
            if log_cfg.get("check_consistency", True):
                from distegnn_tpu.parallel.checks import assert_replicated

                assert_replicated(state.params)
            log_dict["epochs"].append(epoch)
            log_dict["loss"].append(loss_test)

            if loss_valid < best["loss_valid"]:
                best = {"epoch_index": epoch, "loss_valid": loss_valid,
                        "loss_test": loss_test, "loss_train": loss_train}
                best_state = state
                if is_main and log:
                    _save(ckpt_dir, "best_model.ckpt", state, epoch, best, config)
            if is_main and log:
                _save(ckpt_dir, "last_model.ckpt", state, epoch,
                      {"loss_train": loss_train, "loss_valid": loss_valid, "loss_test": loss_test},
                      config)
                if wandb_run is not None:
                    wandb_run.log({"loss_train": loss_train, "loss_valid": loss_valid,
                                   "loss_test": loss_test, "epoch_time": dt_epoch},
                                  step=epoch)
                print(f"Epoch {epoch} | train {_fmt(loss_train)} | "
                      f"valid {_fmt(loss_valid)} | test {_fmt(loss_test)} | "
                      f"{dt_epoch:.2f}s/epoch", flush=True)
                print(f"*** Best Valid Loss: {_fmt(best['loss_valid'])} | "
                      f"Best Test Loss: {_fmt(best['loss_test'])} | "
                      f"Best Epoch Index: {best['epoch_index']}", flush=True)

        elif is_main and log and wandb_run is not None:
            wandb_run.log({"loss_train": loss_train, "epoch_time": dt_epoch},
                          step=epoch)

        # early stop is evaluated EVERY epoch, not only on eval epochs —
        # reference checks it at the bottom of each epoch (utils/train.py:261-267)
        if epoch - best["epoch_index"] >= train_cfg.early_stop:
            best["early_stop"] = epoch
            if is_main:
                print(f"Early stopped! Epoch: {epoch}")
            _write_log_json(log_dir, best, log_dict, config, start, is_main and log)
            break

        _write_log_json(log_dir, best, log_dict, config, start, is_main and log)

    if wandb_run is not None:
        wandb_run.log({"best_test_loss": best["loss_test"]})
        wandb_run.finish()
    return state, best_state, best, log_dict


def _save(ckpt_dir, name, state, epoch, losses, config):
    from distegnn_tpu.train.checkpoint import save_checkpoint

    cfg = config.to_dict() if hasattr(config, "to_dict") else dict(config)
    save_checkpoint(os.path.join(ckpt_dir, name), state, epoch, losses=losses, config=cfg)


def _sanitize_nonfinite(log_dict):
    """Replace non-finite floats with None (json null): json.dump would emit
    bare NaN/Infinity tokens, which strict RFC-8259 consumers reject — and a
    diverged run DOES put NaN in the loss curves."""
    def fix(v):
        if isinstance(v, float) and not np.isfinite(v):
            return None
        return v

    return {k: [fix(v) for v in vals] if isinstance(vals, list) else vals
            for k, vals in log_dict.items()}


def _write_log_json(log_dir, best, log_dict, config, start, enabled):
    if not enabled:
        return
    best["time_cost"] = time.perf_counter() - start
    cfg = config.to_dict() if hasattr(config, "to_dict") else dict(config)
    with open(os.path.join(log_dir, "log.json"), "w") as f:
        json.dump([best, _sanitize_nonfinite(log_dict), cfg], f, indent=4)


def _init_wandb(config, exp_dir):
    """wandb init (reference utils/train.py:185-198): offline-capable, env-var
    API key, group = dataset name. Returns None if wandb isn't importable."""
    try:
        import wandb
    except ImportError:
        return None
    log_cfg = config.log
    if log_cfg.wandb.api_key:
        os.environ["WANDB_API_KEY"] = log_cfg.wandb.api_key
    if log_cfg.wandb.offline:
        os.environ["WANDB_MODE"] = "offline"
    cfg = config.to_dict() if hasattr(config, "to_dict") else dict(config)
    return wandb.init(
        config=cfg,
        project=log_cfg.wandb.project or None,
        entity=log_cfg.wandb.entity or None,
        group=f"{config.data.dataset_name}",
        name=log_cfg.exp_name,
        dir=exp_dir,
        reinit=True,
    )
