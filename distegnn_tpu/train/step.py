"""The jitted train/eval step — forward, loss (MSE + MMD), backward, clip,
optimizer, all in ONE traced program (SURVEY.md §7.1 item 2: the reference's
per-step Python work must become traced ops or disappear).

Distributed: the same step function runs under ``shard_map`` with
``axis_name='graph'``. Each device differentiates its OWN node-weighted loss
share (cross-partition terms arrive through the model's virtual-node psums),
then the step psums the parameter gradients across the axis — the DDP-sum
pattern (reference DDP allreduce + world_size rescale, main.py:196 +
utils/train.py:110). Do NOT seed the backward from the psum'd global loss
instead: psum's transpose is psum, which would scale every gradient by the
axis size.

Optimizer parity (reference main.py:197-202 + utils/train.py:150-158):
torch.Adam with L2 weight_decay folded into the gradient, optional
grad-clip-by-global-norm(0.3), loss/accumulation_steps with a step every k
micro-batches (optax.MultiSteps), optional cosine schedule over
epochs*len(loader)/accumulation_steps.

``model.edge_impl`` (plain vs fused Pallas edge pipeline) needs no branch
here: the flag lives on the model object and its extra batch fields
(``remote_edge_*``, built by loaders with ``split_remote=True``) ride the
GraphBatch pytree through jit/shard_map untouched. The step stays one
compiled program per (layout, model) pair either way.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct

from distegnn_tpu.ops.graph import GraphBatch
from distegnn_tpu.parallel.collectives import _psum
from distegnn_tpu.train.loss import (
    masked_mse,
    mmd_loss,
    weighted_global_loss,
    weighted_local_loss,
)


@struct.dataclass
class TrainState:
    params: dict
    opt_state: optax.OptState
    step: jnp.ndarray  # micro-batch counter

    @classmethod
    def create(cls, params, tx: optax.GradientTransformation) -> "TrainState":
        return cls(params=params, opt_state=tx.init(params), step=jnp.zeros((), jnp.int32))


def needs_grad_clip(config) -> bool:
    """Reference rule (utils/train.py:153-154): clip-by-norm 0.3 only when
    distributed or on the largest dataset, and only for FastEGNN."""
    dist = config.data.world_size > 1
    big = config.data.dataset_name in ("LargeFluid", "Fluid113K")
    return (dist or big) and config.model.model_name == "FastEGNN"


def make_optimizer(
    learning_rate: float,
    weight_decay: float = 0.0,
    clip_norm: Optional[float] = None,
    accumulation_steps: int = 1,
    total_steps: Optional[int] = None,
    scheduler: str = "None",
) -> optax.GradientTransformation:
    """torch-Adam-parity chain: [clip] -> +wd*p -> adam moments -> -lr [cosine]."""
    parts = []
    if clip_norm is not None:
        parts.append(optax.clip_by_global_norm(clip_norm))
    if weight_decay:
        # torch.Adam weight_decay: grad += wd * param BEFORE the moment update
        parts.append(optax.add_decayed_weights(weight_decay))
    parts.append(optax.scale_by_adam(b1=0.9, b2=0.999, eps=1e-8))
    if scheduler == "cosine":
        assert total_steps is not None, "cosine scheduler needs total_steps"
        lr = optax.cosine_decay_schedule(learning_rate, total_steps)
    else:
        lr = learning_rate
    parts.append(optax.scale_by_learning_rate(lr))
    tx = optax.chain(*parts)
    if accumulation_steps > 1:
        # MultiSteps averages micro-grads — same math as the reference's
        # loss/accumulation_steps + step-every-k (utils/train.py:150-158)
        tx = optax.MultiSteps(tx, every_k_schedule=accumulation_steps)
    return tx


def _reduce_axes(axis_name, data_axis_name):
    """All mesh axes the LOSS/GRADIENT reduce over: graph partitions and (when
    2-D) data-parallel shards. The model's virtual-node psums stay on
    ``axis_name`` alone — virtual nodes are per-graph objects, and the data
    axis holds *different* graphs.

    The TENSOR axis is deliberately absent: the TP collectives' custom VJPs
    (parallel/collectives.py) already hand every tensor rank the FULL
    parameter cotangent (tensor-replicated, each loss term counted once), so
    the loss is replicated across tensor ranks and this psum over
    (data, graph) is exact unchanged for any tensor degree. Adding the tensor
    axis here would T-fold double-count gradients."""
    axes = tuple(a for a in (data_axis_name, axis_name) if a is not None)
    return axes if axes else None


def make_loss_fn(model, mmd_weight: float, mmd_sigma: float, mmd_samples: int,
                 axis_name: Optional[str] = None,
                 data_axis_name: Optional[str] = None) -> Callable:
    """loss(params, batch, key) -> (local_loss_for_grad, logged_global_mse).

    The grad path carries only THIS partition's weighted share; the train step
    psums the resulting parameter gradients across the mesh (DDP-sum pattern —
    differentiating the psum'd global loss instead would scale gradients by
    the axis size, since psum's transpose is psum). logged_global_mse is the
    node-weighted global MSE the reference logs (total_loss_loc).

    With a 2-D (data x graph) mesh the node-count weighting spans BOTH axes:
    every device holds a partition of some graph of the global batch, and the
    global loss is the node-weighted sum over all of them — the natural
    generalization of reference utils/train.py:100-110, where the data axis is
    degenerate (every rank sees the same graphs)."""
    axes = _reduce_axes(axis_name, data_axis_name)

    def loss_fn(params, batch: GraphBatch, key):
        loc_pred, virtual_loc = model.apply(params, batch)
        mse_local = masked_mse(loc_pred, batch.target, batch.node_mask)
        loss = weighted_local_loss(mse_local, batch.node_mask, axes)
        logged = _psum(loss, axes)
        if mmd_weight:
            for a in axes or ():
                # independent sample draw per device (each rank samples its
                # own local nodes, reference utils/train.py:124-139)
                key = jax.random.fold_in(key, jax.lax.axis_index(a))
            lm = mmd_loss(virtual_loc, batch.target, batch.node_mask, key, mmd_sigma, mmd_samples)
            loss = loss + mmd_weight * weighted_local_loss(lm, batch.node_mask, axes)
        return loss, logged

    return loss_fn


def make_train_step(model, tx: optax.GradientTransformation, mmd_weight: float,
                    mmd_sigma: float, mmd_samples: int,
                    axis_name: Optional[str] = None,
                    data_axis_name: Optional[str] = None) -> Callable:
    """Returns step(state, batch, key) -> (state, metrics). Jit/shard_map it."""
    loss_fn = make_loss_fn(model, mmd_weight, mmd_sigma, mmd_samples,
                           axis_name, data_axis_name)
    axes = _reduce_axes(axis_name, data_axis_name)

    def step(state: TrainState, batch: GraphBatch, key):
        (loss, logged), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params, batch, key)
        if axes is not None:
            # DDP-style gradient sum over the WHOLE mesh: each device holds
            # the gradient of ITS shard's loss share (incl. cross-device terms
            # routed through the model's virtual-node psums); summing yields
            # the exact global gradient, identically on every device — weights
            # stay replicated.
            grads = jax.lax.psum(grads, axes)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(params=params, opt_state=opt_state, step=state.step + 1)
        metrics = {"loss": logged, "loss_with_mmd": _psum(loss, axes)}
        if axis_name is not None:
            # In-step cross-rank data-consistency check (reference
            # utils/train.py:55-61 all_gathers loc_mean and asserts it EVERY
            # step): every partition of a graph carries the graph's GLOBAL
            # loc_mean, so across the graph axis the values must be bitwise
            # identical. max|m - pmin(m)| pmax'd over the axis is exactly 0
            # iff all ranks fed the same logical batch. Traced into the step:
            # one [B,3] collective — free next to the per-layer psums; the
            # trainer asserts the scalar host-side once per eval interval.
            # pmin spans the graph axis only (the data axis holds DIFFERENT
            # graphs); the final pmax spans the whole mesh so every process
            # sees a nonzero residual even when the drift is on another
            # host's data row.
            m = batch.loc_mean
            resid = jnp.max(jnp.abs(m - jax.lax.pmin(m, axis_name)))
            metrics["batch_consistency"] = jax.lax.pmax(resid, axes)
        return new_state, metrics

    return step


def make_eval_step(model, axis_name: Optional[str] = None,
                   data_axis_name: Optional[str] = None) -> Callable:
    """Returns eval(params, batch) -> node-weighted global MSE (no MMD —
    reference eval epochs compute only total_loss_loc)."""
    axes = _reduce_axes(axis_name, data_axis_name)

    def eval_step(params, batch: GraphBatch):
        loc_pred, _ = model.apply(params, batch)
        mse_local = masked_mse(loc_pred, batch.target, batch.node_mask)
        return weighted_global_loss(mse_local, batch.node_mask, axes)

    return eval_step
