"""Durable checkpoint save/restore (reference utils/train.py:234-259, main.py:208-220).

Saves {epoch, params, opt_state, losses, config} — the same payload as the
reference's best_model.pth/last_model.pth. Written by process 0 only
(``jax.process_index() == 0``; params are replicated so any host's copy is the
global state — reference does the same with rank 0, SURVEY.md §5.4).

Format: pickle of numpy leaf lists + the pytree re-built from a template at
restore time (so saved files don't depend on optax's internal tree classes
being pickleable across versions). Unlike the reference (whose DDP-wrapped
state_dicts are not portable between world sizes, SURVEY.md §5.4), params here
carry no wrapper prefix — checkpoints are world-size-portable by construction.

Durability layer (docs/ROBUSTNESS.md):
  - every save is tmp-write + fsync + atomic rename, and records a CRC32 +
    size entry in a per-directory ``manifest.json`` (itself written
    atomically), so restore can prove a file intact before unpickling it;
  - truncated/corrupt files surface as a typed :class:`CheckpointCorruptError`
    naming the path, never a bare ``EOFError``/``UnpicklingError``;
  - ``save_checkpoint`` sweeps ``*.tmp`` leftovers of a previously killed
    write out of the directory before writing;
  - step-granular checkpoints (``step_<n>.ckpt``) rotate, keeping the last K
    alongside ``best_model.ckpt``/``last_model.ckpt``/``preempt_model.ckpt``;
  - ``find_resume_checkpoint`` scans a whole log dir, verifies checksums, and
    falls back past corrupt/incompatible files to the newest valid state —
    the ``train.resume: auto`` entry point.
"""

from __future__ import annotations

import glob
import json
import os
import pickle
import re
import zlib
from dataclasses import dataclass, field
from typing import Any, List, Optional

import jax
import numpy as np

from distegnn_tpu import obs

MANIFEST_NAME = "manifest.json"
PREEMPT_MARKER = "PREEMPTED"

# payload keys every intact checkpoint must carry (older checkpoints predate
# step_in_epoch/seed — those stay optional for back-compat)
_REQUIRED_KEYS = ("epoch", "params_leaves", "opt_state_leaves", "step")

# unpickle failure modes of a torn/garbled file — anything else (e.g. a
# genuine OSError opening the file) propagates untouched
_UNPICKLE_ERRORS = (EOFError, pickle.UnpicklingError, AttributeError,
                    ImportError, IndexError, MemoryError, TypeError,
                    ValueError)


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file failed verification (CRC/size mismatch against its
    manifest entry, truncated pickle, or missing payload keys)."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"corrupt checkpoint {path}: {reason}")
        self.path = path
        self.reason = reason
        # every detected corruption lands on the obs fault timeline (no-op
        # when no sink is configured) — raise sites are many, this is one
        obs.event("ckpt/corrupt", path=os.path.basename(path), reason=reason)


@dataclass
class RestoredRun:
    """Everything a resumed run needs to replay the schedule exactly: the
    train state, how many epochs completed, how many steps of the NEXT epoch
    already applied (mid-epoch cadence/preempt saves), and the seed the run
    was started with (PRNG keys derive from (seed, epoch, step), so carrying
    the seed lets resume detect a mismatched --seed override)."""

    state: Any
    epoch: int
    step_in_epoch: int = 0
    losses: dict = field(default_factory=dict)
    seed: Optional[int] = None
    path: Optional[str] = None


def _mesh_of(config) -> Optional[dict]:
    """The (data, graph, tensor) mesh shape recorded in a config, as plain
    ints, or None when the config predates / doesn't carry one. Tolerant of
    both ConfigDict and plain-dict payload configs."""
    if not isinstance(config, dict):
        return None
    mesh = (config.get("parallel") or {}).get("mesh")
    if not isinstance(mesh, dict):
        return None
    try:
        return {k: int(mesh.get(k) or 1) for k in ("data", "graph", "tensor")}
    except (TypeError, ValueError):
        return None


def check_mesh_restore_compat(payload: dict, config=None) -> None:
    """Cross-mesh restore gate: a checkpoint written under mesh A restores
    under mesh B. Params are saved FULL (never tensor-sliced — the TP layers
    slice replicated weights at compute time), so the param tree is invariant
    in the mesh shape and 'resharding' is a plain load. The one real
    constraint is that the RESTORING mesh's tensor degree must still divide
    the saved model's hidden width; violations raise a typed ValueError here
    instead of surfacing as a shape error deep inside shard_map."""
    saved_mesh = payload.get("mesh") or _mesh_of(payload.get("config"))
    target_mesh = _mesh_of(config)
    if target_mesh is None:
        return
    tp = target_mesh["tensor"]
    saved_cfg = payload.get("config") or {}
    model_cfg = saved_cfg.get("model") if isinstance(saved_cfg, dict) else None
    hidden = (model_cfg or {}).get("hidden_nf")
    if tp > 1 and hidden is not None and int(hidden) % tp != 0:
        raise ValueError(
            f"checkpoint incompatible with mesh: saved hidden_nf={hidden} is "
            f"not divisible by restoring parallel.mesh.tensor={tp}")
    if saved_mesh is not None and saved_mesh != target_mesh:
        obs.event("ckpt/reshard", saved=saved_mesh, target=target_mesh)
        obs.log(f"restore: resharding checkpoint saved under mesh {saved_mesh} "
                f"onto mesh {target_mesh} (params are full/replicated — "
                "plain load)")


class ResumeConsensusError(RuntimeError):
    """Multi-host resume diverged: hosts adopted different (epoch,
    step_in_epoch) coordinates from their local filesystem views. Carries
    enough structure for tooling (and the operator) to see WHO is behind:

    - ``coords``: [(epoch, step_in_epoch)] per process index;
    - ``lagging``: process indices whose coordinates trail the newest view
      (the hosts whose checkpoint directory is stale);
    - ``local_path``: the checkpoint THIS process resolved (one concrete
      path to diff against the lagging hosts' directories).
    """

    def __init__(self, coords, lagging, local_path=None):
        self.coords = [tuple(int(v) for v in row) for row in coords]
        self.lagging = sorted(int(i) for i in lagging)
        self.local_path = local_path
        latest = max(self.coords)
        views = ", ".join(
            f"process {i}: epoch={e} step_in_epoch={s}"
            for i, (e, s) in enumerate(self.coords))
        behind = ", ".join(f"process {i}" for i in self.lagging)
        where = (f" (this process resolved {local_path!r})"
                 if local_path else "")
        super().__init__(
            f"resume consensus failure: {behind} lag(s) behind the newest "
            f"view epoch={latest[0]} step_in_epoch={latest[1]} — a "
            f"half-propagated checkpoint directory on the lagging host(s) "
            f"is the usual cause. Views: {views}{where}. Propagate the "
            "same state_dict/ contents to every host, then relaunch.")


def verify_resume_consensus(epoch: int, step_in_epoch: int,
                            allgather=None, path: Optional[str] = None) -> None:
    """Multi-host coordinated-restore barrier (closes the docs/ROBUSTNESS.md
    'Known gap'): each process resolves its resume checkpoint independently
    from its own filesystem view, so a half-propagated checkpoint directory
    (NFS lag, partial rsync) can leave hosts resuming from DIFFERENT steps —
    silently corrupting gradient averaging, since psum assumes every host
    holds the same params. After restore, every process publishes the
    (epoch, step_in_epoch) it adopted; any disagreement fails loudly here,
    before a single step runs.

    ``allgather`` is injectable for single-process tests: a callable taking
    the local ``np.ndarray([epoch, step_in_epoch])`` and returning the
    [n_process, 2] stack. Default uses
    ``jax.experimental.multihost_utils.process_allgather``; single-process
    runs with the default are a no-op. ``path`` is the resume checkpoint
    THIS process resolved — it rides the typed error so the operator has a
    concrete path to diff against the lagging hosts."""
    if allgather is None:
        if jax.process_count() == 1:
            return
        from jax.experimental import multihost_utils

        def allgather(x):
            return np.asarray(multihost_utils.process_allgather(x))

    local = np.asarray([int(epoch), int(step_in_epoch)], dtype=np.int64)
    coords = np.asarray(allgather(local)).reshape(-1, 2)
    uniq = {tuple(int(v) for v in row) for row in coords}
    obs.event("resume/consensus", epoch=int(epoch),
              step_in_epoch=int(step_in_epoch), n_views=len(uniq))
    if len(uniq) > 1:
        latest = max(uniq)
        lagging = [i for i, row in enumerate(coords)
                   if (int(row[0]), int(row[1])) < latest]
        obs.event("resume/consensus_failure", lagging=lagging,
                  latest=list(latest),
                  views=[[int(v) for v in row] for row in coords])
        raise ResumeConsensusError(coords, lagging, local_path=path)


def _to_leaves(tree) -> list:
    return [np.asarray(x) for x in jax.tree.leaves(tree)]


def _from_leaves(template, leaves: list):
    treedef = jax.tree.structure(template)
    tmpl_leaves = jax.tree.leaves(template)
    leaves = [np.asarray(l) for l in leaves]
    if len(leaves) != len(tmpl_leaves):
        raise ValueError(
            f"checkpoint incompatible with model: {len(leaves)} saved arrays vs "
            f"{len(tmpl_leaves)} expected — was the checkpoint written by a "
            "different architecture/config (e.g. hoist_edge_mlp flipped)?")
    for i, (saved, want) in enumerate(zip(leaves, tmpl_leaves)):
        if tuple(saved.shape) != tuple(np.shape(want)):
            raise ValueError(
                f"checkpoint incompatible with model: array {i} has shape "
                f"{tuple(saved.shape)}, model expects {tuple(np.shape(want))} — "
                "was the checkpoint written by a different architecture/config "
                "(e.g. hoist_edge_mlp flipped)?")
    return jax.tree.unflatten(treedef, leaves)


# ---- manifest --------------------------------------------------------------

def _manifest_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, MANIFEST_NAME)


def read_manifest(ckpt_dir: str) -> dict:
    """{basename: {crc32, size, epoch, step, step_in_epoch, time}} — empty on
    a missing or unparseable manifest (the manifest is an integrity aid, not
    a dependency: restore still works without it)."""
    try:
        with open(_manifest_path(ckpt_dir)) as f:
            m = json.load(f)
        return m if isinstance(m, dict) else {}
    except (OSError, json.JSONDecodeError):
        return {}


def _write_manifest(ckpt_dir: str, manifest: dict) -> None:
    tmp = _manifest_path(ckpt_dir) + ".manifest.tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, _manifest_path(ckpt_dir))


def _sweep_stale_tmps(ckpt_dir: str) -> None:
    """Remove ``*.tmp`` leftovers of a previous killed write. Safe by
    construction: a live save holds no .tmp across calls (tmp → rename is one
    call), and process 0 is the only writer."""
    for stale in glob.glob(os.path.join(ckpt_dir, "*.tmp")):
        try:
            os.remove(stale)
            obs.log(f"checkpoint: removed stale partial write {stale}")
        except OSError:
            pass


# ---- save ------------------------------------------------------------------

def save_checkpoint(path: str, state, epoch: int, losses: Optional[dict] = None,
                    config: Optional[dict] = None, seed: Optional[int] = None,
                    step_in_epoch: int = 0) -> None:
    """Atomically write one checkpoint + its CRC manifest entry.

    ``epoch`` counts COMPLETED epochs; ``step_in_epoch`` counts steps of
    epoch ``epoch + 1`` already applied to ``state`` (0 = epoch boundary) —
    a resumed run replays the schedule from exactly there."""
    if jax.process_index() != 0:
        return
    import time as _time

    t0 = _time.perf_counter()
    payload = {
        "epoch": int(epoch),
        "params_leaves": _to_leaves(state.params),
        "opt_state_leaves": _to_leaves(state.opt_state),
        "step": int(state.step),
        "step_in_epoch": int(step_in_epoch),
        "seed": None if seed is None else int(seed),
        "losses": losses or {},
        "config": config,
        # the (data, graph, tensor) shape this run trained under — restore
        # under any other shape is legal (params are full), the metadata
        # feeds the reshard log + compat check (check_mesh_restore_compat)
        "mesh": _mesh_of(config),
    }
    ckpt_dir = os.path.dirname(path) or "."
    os.makedirs(ckpt_dir, exist_ok=True)
    _sweep_stale_tmps(ckpt_dir)
    blob = pickle.dumps(payload)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # atomic: a crash never leaves a torn checkpoint
    manifest = read_manifest(ckpt_dir)
    manifest[os.path.basename(path)] = {
        "crc32": zlib.crc32(blob) & 0xFFFFFFFF,
        "size": len(blob),
        "epoch": int(epoch),
        "step": int(state.step),
        "step_in_epoch": int(step_in_epoch),
        "time": _time.time(),
    }
    # drop entries whose files are gone (rotation, manual cleanup)
    manifest = {k: v for k, v in manifest.items()
                if os.path.exists(os.path.join(ckpt_dir, k))}
    _write_manifest(ckpt_dir, manifest)
    obs.event("ckpt/save", path=os.path.basename(path), epoch=int(epoch),
              bytes=len(blob), dur_s=round(_time.perf_counter() - t0, 6))


_STEP_RE = re.compile(r"^step_(\d+)\.ckpt$")


def step_checkpoint_name(step: int) -> str:
    return f"step_{int(step):010d}.ckpt"


def rotate_checkpoints(ckpt_dir: str, keep: int) -> List[str]:
    """Keep the newest ``keep`` step-granular checkpoints (by step number);
    ``best_model``/``last_model``/``preempt_model`` never rotate. Returns the
    removed paths. Manifest entries for removed files are dropped on the next
    save (see save_checkpoint's existence filter)."""
    if jax.process_index() != 0:
        return []
    steps = []
    for p in glob.glob(os.path.join(ckpt_dir, "step_*.ckpt")):
        m = _STEP_RE.match(os.path.basename(p))
        if m:
            steps.append((int(m.group(1)), p))
    steps.sort()
    removed = []
    for _, p in steps[:max(0, len(steps) - max(int(keep), 1))]:
        try:
            os.remove(p)
            removed.append(p)
        except OSError:
            pass
    if steps:
        # rotation was silent before the promotion conveyor landed; the
        # event makes publish latency attributable in obs_report waterfalls
        # (ckpt/save -> ckpt/rotate -> promote/publish)
        newest_step, newest_path = steps[-1]
        try:
            newest_bytes = os.path.getsize(newest_path)
        except OSError:
            newest_bytes = -1
        obs.event("ckpt/rotate", step=newest_step, bytes=newest_bytes,
                  kept=min(len(steps) - len(removed), max(int(keep), 1)),
                  removed=len(removed))
    return removed


# ---- verify + restore ------------------------------------------------------

def verify_checkpoint(path: str) -> dict:
    """Read + integrity-check one checkpoint file; returns the payload.
    Raises CheckpointCorruptError on CRC/size mismatch vs the directory
    manifest, torn pickle, or missing payload keys."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except FileNotFoundError:
        raise CheckpointCorruptError(path, "file missing") from None
    entry = read_manifest(os.path.dirname(path) or ".").get(os.path.basename(path))
    if entry is not None:
        if len(blob) != int(entry.get("size", -1)):
            raise CheckpointCorruptError(
                path, f"size {len(blob)} != manifest {entry.get('size')} "
                      "(truncated or partially-written file)")
        if (zlib.crc32(blob) & 0xFFFFFFFF) != int(entry.get("crc32", -1)):
            raise CheckpointCorruptError(
                path, "CRC32 mismatch vs manifest (bit-rot or torn write)")
    try:
        payload = pickle.loads(blob)
    except _UNPICKLE_ERRORS as e:
        raise CheckpointCorruptError(path, f"unpickle failed: {e!r}") from None
    if not isinstance(payload, dict) or any(k not in payload for k in _REQUIRED_KEYS):
        raise CheckpointCorruptError(path, "payload missing required keys")
    return payload


def _with_config_hint(payload, e: ValueError) -> ValueError:
    saved_cfg = payload.get("config") or {}
    model_cfg = saved_cfg.get("model") if isinstance(saved_cfg, dict) else None
    hint = (f"; the checkpoint was written with model config {model_cfg}"
            if model_cfg else "")
    return ValueError(f"{e}{hint}")


def restore_for_resume(path: str, state, config=None) -> RestoredRun:
    """Verified restore into the structure of ``state`` (a freshly-created
    TrainState), carrying the resume coordinates (epoch, step_in_epoch, seed).
    The optimizer configuration must match the one the checkpoint was written
    with (grad-accumulation wrapping changes the opt-state tree);
    evaluation-only consumers should use :func:`restore_params` instead.
    With ``config`` given, the checkpoint's recorded mesh is checked against
    the restoring mesh (:func:`check_mesh_restore_compat`)."""
    import time as _time

    t0 = _time.perf_counter()
    payload = verify_checkpoint(path)
    if config is not None:
        check_mesh_restore_compat(payload, config)
    from distegnn_tpu.train.step import TrainState

    try:
        restored = TrainState(
            params=_from_leaves(state.params, payload["params_leaves"]),
            opt_state=_from_leaves(state.opt_state, payload["opt_state_leaves"]),
            step=np.int32(payload["step"]),
        )
    except ValueError as e:
        raise _with_config_hint(payload, e) from None
    obs.event("ckpt/restore", path=os.path.basename(path),
              epoch=int(payload["epoch"]),
              bytes=int(os.path.getsize(path)) if os.path.exists(path) else 0,
              dur_s=round(_time.perf_counter() - t0, 6))
    return RestoredRun(
        state=restored,
        epoch=int(payload["epoch"]),
        step_in_epoch=int(payload.get("step_in_epoch", 0) or 0),
        losses=payload.get("losses", {}) or {},
        seed=payload.get("seed"),
        path=path,
    )


def restore_checkpoint(path: str, state, config=None) -> tuple[Any, int, dict]:
    """Back-compat wrapper over :func:`restore_for_resume`: returns
    (state, start_epoch, losses)."""
    r = restore_for_resume(path, state, config=config)
    return r.state, r.epoch, r.losses


def restore_params(path: str, params) -> Any:
    """Params-only restore for evaluation/rollout: ignores the saved
    optimizer state, so a checkpoint written with ANY optimizer wrapping
    (grad accumulation, schedules) loads into a bare model."""
    payload = verify_checkpoint(path)
    try:
        return _from_leaves(params, payload["params_leaves"])
    except ValueError as e:
        raise _with_config_hint(payload, e) from None


# ---- auto-resume scan ------------------------------------------------------

def scan_resume_candidates(log_dir: str) -> List[str]:
    """All checkpoints under ``<log_dir>/<exp>/state_dict/`` (and a bare
    ``<log_dir>/state_dict/``), newest first by mtime — exp dirs are
    timestamped per run, so a preemption's ``preempt_model.ckpt`` (written at
    death) naturally sorts first."""
    pats = [os.path.join(log_dir, "*", "state_dict", "*.ckpt"),
            os.path.join(log_dir, "state_dict", "*.ckpt")]
    hits = [p for pat in pats for p in glob.glob(pat)]
    return sorted(hits, key=lambda p: os.path.getmtime(p), reverse=True)


def peek_resume_seed(log_dir: str):
    """(seed, path) of the newest checksum-valid checkpoint under ``log_dir``,
    or (None, None). Called BEFORE the model/loaders exist — a resumed run
    must adopt the original run's seed before anything derives from it (loader
    permutations, PRNG folds), and the full architecture-checked restore can
    only happen once a template TrainState exists."""
    for path in scan_resume_candidates(log_dir):
        try:
            payload = verify_checkpoint(path)
        except CheckpointCorruptError:
            continue
        return payload.get("seed"), path
    return None, None


def find_resume_checkpoint(log_dir: str, state, config=None) -> Optional[RestoredRun]:
    """``train.resume: auto``: scan the experiment log dir, verify checksums,
    and restore the NEWEST valid checkpoint — falling back past corrupt /
    truncated / architecture-incompatible files with a printed diagnosis.
    Returns None when nothing under ``log_dir`` restores (fresh start)."""
    for path in scan_resume_candidates(log_dir):
        try:
            return restore_for_resume(path, state, config=config)
        except CheckpointCorruptError as e:
            obs.log(f"resume: skipping {path} ({e.reason})")
        except ValueError as e:
            obs.log(f"resume: skipping incompatible {path} ({e})")
    return None


def adopt_resume_seed(config) -> None:
    """With ``train.resume`` set, adopt the seed of the checkpoint we are
    about to resume BEFORE anything derives from ``config.seed`` (loader
    permutations and per-step PRNG keys fold (seed, epoch, step) — replaying
    the schedule exactly requires the original seed, not a drifted default)."""
    resume = config.train.get("resume")
    if not resume:
        return
    if resume == "auto":
        seed, path = peek_resume_seed(config.log.log_dir)
    else:
        try:
            seed, path = verify_checkpoint(resume).get("seed"), resume
        except CheckpointCorruptError:
            return  # resolve_resume raises the loud, typed error
    if seed is not None and int(seed) != int(config.seed):
        obs.log(f"resume: adopting seed {seed} from {path} (config had "
                f"{config.seed}) so the resumed run replays the schedule")
        config.seed = int(seed)


def resolve_resume(config, state) -> Optional[RestoredRun]:
    """The ``train.resume`` entry point (main.py / parallel/launch.py):
    'auto' scans ``log.log_dir`` and falls back past corrupt files; an
    explicit path fails loudly. Returns a RestoredRun or None (fresh start)."""
    resume = config.train.get("resume")
    if not resume:
        return None
    if resume == "auto":
        rr = find_resume_checkpoint(config.log.log_dir, state, config=config)
        if rr is None:
            obs.log("resume: auto found no valid checkpoint under "
                    f"{config.log.log_dir}; starting fresh")
        return rr
    return restore_for_resume(resume, state, config=config)


def write_preempt_marker(ckpt_dir: str, ckpt_name: str, epoch: int,
                         step_in_epoch: int) -> None:
    """Drop the resumable marker scripts key off (lib_resume_paused.sh
    newest_resumable_ckpt / convergence_session.sh): the run exited on
    purpose mid-training and the named checkpoint continues it."""
    if jax.process_index() != 0:
        return
    import time as _time

    tmp = os.path.join(ckpt_dir, PREEMPT_MARKER + ".tmp")
    with open(tmp, "w") as f:
        json.dump({"checkpoint": ckpt_name, "epoch": int(epoch),
                   "step_in_epoch": int(step_in_epoch),
                   "time": _time.time()}, f)
    os.replace(tmp, os.path.join(ckpt_dir, PREEMPT_MARKER))


def clear_preempt_marker(ckpt_dir: str) -> None:
    try:
        os.remove(os.path.join(ckpt_dir, PREEMPT_MARKER))
    except OSError:
        pass
