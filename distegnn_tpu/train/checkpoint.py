"""Checkpoint save/restore (reference utils/train.py:234-259, main.py:208-220).

Saves {epoch, params, opt_state, losses, config} — the same payload as the
reference's best_model.pth/last_model.pth. Written by process 0 only
(``jax.process_index() == 0``; params are replicated so any host's copy is the
global state — reference does the same with rank 0, SURVEY.md §5.4).

Format: pickle of numpy leaf lists + the pytree re-built from a template at
restore time (so saved files don't depend on optax's internal tree classes
being pickleable across versions). Unlike the reference (whose DDP-wrapped
state_dicts are not portable between world sizes, SURVEY.md §5.4), params here
carry no wrapper prefix — checkpoints are world-size-portable by construction.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional

import jax
import numpy as np


def _to_leaves(tree) -> list:
    return [np.asarray(x) for x in jax.tree.leaves(tree)]


def _from_leaves(template, leaves: list):
    treedef = jax.tree.structure(template)
    tmpl_leaves = jax.tree.leaves(template)
    leaves = [np.asarray(l) for l in leaves]
    if len(leaves) != len(tmpl_leaves):
        raise ValueError(
            f"checkpoint incompatible with model: {len(leaves)} saved arrays vs "
            f"{len(tmpl_leaves)} expected — was the checkpoint written by a "
            "different architecture/config (e.g. hoist_edge_mlp flipped)?")
    for i, (saved, want) in enumerate(zip(leaves, tmpl_leaves)):
        if tuple(saved.shape) != tuple(np.shape(want)):
            raise ValueError(
                f"checkpoint incompatible with model: array {i} has shape "
                f"{tuple(saved.shape)}, model expects {tuple(np.shape(want))} — "
                "was the checkpoint written by a different architecture/config "
                "(e.g. hoist_edge_mlp flipped)?")
    return jax.tree.unflatten(treedef, leaves)


def save_checkpoint(path: str, state, epoch: int, losses: Optional[dict] = None,
                    config: Optional[dict] = None) -> None:
    if jax.process_index() != 0:
        return
    payload = {
        "epoch": int(epoch),
        "params_leaves": _to_leaves(state.params),
        "opt_state_leaves": _to_leaves(state.opt_state),
        "step": int(state.step),
        "losses": losses or {},
        "config": config,
    }
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(tmp, "wb") as f:
        pickle.dump(payload, f)
    os.replace(tmp, path)  # atomic: a crash never leaves a torn checkpoint


def _with_config_hint(payload, e: ValueError) -> ValueError:
    saved_cfg = payload.get("config") or {}
    model_cfg = saved_cfg.get("model") if isinstance(saved_cfg, dict) else None
    hint = (f"; the checkpoint was written with model config {model_cfg}"
            if model_cfg else "")
    return ValueError(f"{e}{hint}")


def restore_checkpoint(path: str, state) -> tuple[Any, int, dict]:
    """Restore into the structure of ``state`` (a freshly-created TrainState).
    Returns (state, start_epoch, losses). The optimizer configuration must
    match the one the checkpoint was written with (grad-accumulation wrapping
    changes the opt-state tree); evaluation-only consumers should use
    :func:`restore_params` instead."""
    with open(path, "rb") as f:
        payload = pickle.load(f)
    from distegnn_tpu.train.step import TrainState

    try:
        restored = TrainState(
            params=_from_leaves(state.params, payload["params_leaves"]),
            opt_state=_from_leaves(state.opt_state, payload["opt_state_leaves"]),
            step=np.int32(payload["step"]),
        )
    except ValueError as e:
        raise _with_config_hint(payload, e) from None
    return restored, payload["epoch"], payload.get("losses", {})


def restore_params(path: str, params) -> Any:
    """Params-only restore for evaluation/rollout: ignores the saved
    optimizer state, so a checkpoint written with ANY optimizer wrapping
    (grad accumulation, schedules) loads into a bare model."""
    with open(path, "rb") as f:
        payload = pickle.load(f)
    try:
        return _from_leaves(params, payload["params_leaves"])
    except ValueError as e:
        raise _with_config_hint(payload, e) from None
