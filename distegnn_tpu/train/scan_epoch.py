"""Device-resident epochs: ONE dispatch per epoch via lax.scan.

The reference's epoch loop dispatches one CUDA launch sequence per minibatch
(utils/train.py:83-117); the round-1 port kept that host-driven loop. On a
tunneled TPU every dispatch pays O(100ms) host->device latency, so an n-body
epoch (20 train + 16 eval micro-batches of ~1ms compute) cost ~2 min of pure
round-trips. TPU-native fix: the whole (uniformly padded) dataset lives in
HBM as one stacked GraphBatch, the epoch is a ``lax.scan`` over minibatch
index slices, and the host sees exactly one dispatch + one scalar fetch per
epoch. The permutation is still drawn on host from (seed, epoch) — identical
to GraphLoader._order — and the per-step PRNG keys are fold_in(epoch, step),
identical to the host loop, so the scanned trajectory is step-for-step the
same training run (tests/test_scan_epoch.py proves parameter parity).

``ScanEpochRunner`` covers the single-process path (all four pipelines pad to
dataset-wide maxima already). ``DistributedScanRunner`` covers distribute
mode: the per-partition datasets live in HBM as ONE [P, G, ...] global array
sharded over the mesh's graph axis, and the epoch is a single
shard_map(lax.scan) dispatch — the per-layer virtual-node psums and the
gradient psum trace into the scan body as XLA collectives, so distribute-mode
training no longer pays the O(100ms) tunnel dispatch latency per micro-batch
(VERDICT r2 weak #4).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distegnn_tpu.data.loader import GraphLoader, ShardedGraphLoader
from distegnn_tpu.ops.graph import GraphBatch, pad_graphs
from distegnn_tpu.parallel.compat import shard_map
from distegnn_tpu.parallel.mesh import DATA_AXIS, GRAPH_AXIS


def scan_enabled(flag, total_nbytes: int) -> bool:
    """The scan_epochs policy, shared by main.py (single-process) and
    parallel.launch (distribute mode): 'auto' turns scan on when the backend
    has dispatch latency worth killing (i.e. not local CPU) AND the stacked
    dataset fits a conservative HBM budget; True forces it; False disables.

    ``total_nbytes`` is the PER-DEVICE resident footprint (all splits)."""
    if flag is not True and flag != "auto":
        return False
    if flag == "auto" and jax.default_backend() == "cpu":
        return False  # no dispatch latency locally; scan only adds compile
    # budget: ~40% of device memory (params/opt/activations need the rest);
    # memory_stats is unavailable on some backends -> assume 16 GB HBM
    stats = jax.local_devices()[0].memory_stats() or {}
    budget = int(stats.get("bytes_limit", 16 << 30) * 0.4)
    return flag is True or total_nbytes <= budget


def stack_dataset(loader: GraphLoader) -> GraphBatch:
    """Pad every graph of a loader's dataset to the loader's maxima and stack
    into one device-resident GraphBatch with leading axis [num_graphs].
    ``loader._graph`` (not ``loader.dataset[i]``) so edge_block loaders feed
    BLOCKIFIED graphs to pad_graphs, exactly as their __iter__ does."""
    batch = pad_graphs([loader._graph(i) for i in range(len(loader.dataset))],
                       **loader.pad_kwargs())
    return jax.device_put(batch)


def dataset_nbytes(loader: GraphLoader) -> int:
    """Rough device-memory footprint of stack_dataset (float32/int32 leaves)."""
    g0 = pad_graphs([loader._graph(0)], **loader.pad_kwargs())
    per = sum(np.asarray(x).nbytes for x in jax.tree.leaves(g0))
    return per * len(loader.dataset)


class ScanEpochRunner:
    """Scanned replacements for run_epoch_train / run_epoch_eval.

    train_step(state, batch, key) -> (state, metrics) and
    eval_step(params, batch) -> loss are the SAME jittable callables the host
    loop uses; here they are traced into one epoch-long XLA program.
    """

    def __init__(self, train_step: Callable, eval_step: Optional[Callable],
                 loader_train: GraphLoader, seed: int,
                 loader_valid: Optional[GraphLoader] = None,
                 loader_test: Optional[GraphLoader] = None):
        self.seed = seed
        self.loader = loader_train
        self.batch_size = loader_train.batch_size
        self.num_steps = len(loader_train)
        self.data_train = stack_dataset(loader_train)
        self.eval_sets = {}
        if eval_step is not None:
            for name, ld in (("valid", loader_valid), ("test", loader_test)):
                if ld is not None:
                    self.eval_sets[name] = (stack_dataset(ld), len(ld), ld.batch_size)

        self._compile(train_step, eval_step)

    def _compile(self, train_step: Callable, eval_step: Optional[Callable]):
        self._train_step, self._eval_step = train_step, eval_step

        def pick(data: GraphBatch, idx):
            return jax.tree.map(lambda a: a[idx], data)

        def run_train(state, data, perm, epoch_key):
            def body(st, inp):
                idx, k = inp
                st, metrics = train_step(st, pick(data, idx), k)
                return st, metrics["loss"]

            keys = jax.vmap(lambda i: jax.random.fold_in(epoch_key, i))(
                jnp.arange(self.num_steps))
            state, losses = jax.lax.scan(body, state, (perm, keys))
            # equal batch sizes (drop_last) -> plain mean == weighted average
            return state, jnp.mean(losses)

        def run_eval(params, data, perm):
            def body(_, idx):
                return None, eval_step(params, pick(data, idx))

            _, losses = jax.lax.scan(body, None, perm)
            return jnp.mean(losses)

        self._run_train = jax.jit(run_train)
        self._run_eval = jax.jit(run_eval) if eval_step is not None else None

    def with_train_step(self, train_step: Callable) -> "ScanEpochRunner":
        """A copy sharing the device-resident datasets but scanning a NEW
        train step — divergence recovery swaps in a decayed-LR step without
        re-staging HBM (trainer.py rollback path)."""
        import copy

        new = copy.copy(self)
        new._compile(train_step, self._eval_step)
        return new

    def _perm(self, loader: GraphLoader, epoch: int, steps: int, bsz: int):
        loader.set_epoch(epoch)
        order = loader._order()[: steps * bsz]
        return jnp.asarray(order.reshape(steps, bsz).astype(np.int32))

    def train_epoch(self, state, epoch: int):
        perm = self._perm(self.loader, epoch, self.num_steps, self.batch_size)
        epoch_key = jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch)
        state, loss = self._run_train(state, self.data_train, perm, epoch_key)
        return state, loss  # loss: device scalar; trainer fetches once

    def eval_epoch(self, params, split: str) -> float:
        data, steps, bsz = self.eval_sets[split]
        perm = jnp.arange(steps * bsz, dtype=jnp.int32).reshape(steps, bsz)
        return float(self._run_eval(params, data, perm))


_BATCH_ARRAY_FIELDS = ("node_feat", "node_attr", "loc", "vel", "target",
                       "loc_mean", "node_mask", "edge_index", "edge_attr",
                       "edge_mask", "edge_pair")


def stack_sharded_dataset(sharded: ShardedGraphLoader, mesh) -> GraphBatch:
    """All partitions' graphs, padded to the shared static layout and stacked
    into one global jax.Array tree with leaves [P, G, ...], sharded over
    GRAPH_AXIS (replicated over the data axis — the data axis picks different
    GRAPH INDICES per step, not different arrays).

    Streams ONE partition at a time: pad the partition's dataset in host RAM,
    device_put each field onto the devices holding that partition block, free
    the numpy, move on — peak host memory is one partition's padded dataset,
    not all of them (which is exactly the per-chip HBM budget the caller
    already checks). Multi-host: each process pads only its own partitions
    and contributes its addressable shards; a process owning no mesh devices
    contributes none.

    edge_pair is all-or-nothing ACROSS partitions (one pytree structure for
    the stack): if any partition's pairing failed (asymmetric edges — the
    same condition ShardedGraphLoader.__iter__ handles per step), the pair
    field is dropped from the whole stack instead of failing the run.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    loaders = sharded.loaders
    n_parts = len(loaders)
    n_graphs = len(loaders[0].dataset)
    sharding = NamedSharding(mesh, PartitionSpec(GRAPH_AXIS))
    proc = jax.process_index()
    # partition index -> the local devices holding its [1, G, ...] block
    part_devs: dict = {}
    for dev, idx in sharding.devices_indices_map((n_parts,)).items():
        if dev.process_index == proc:
            part_devs.setdefault(idx[0].indices(n_parts)[0], []).append(dev)

    # template (one padded graph): global leaf shapes + static fields, cheap
    # on every process including ones that own no partitions
    ld0 = loaders[0]
    template = pad_graphs([ld0._graph(0)], **ld0.pad_kwargs())

    shards: dict = {f: [] for f in _BATCH_ARRAY_FIELDS}
    all_have_pair = True
    for p, devs in sorted(part_devs.items()):
        ld = loaders[p]
        # ld._graph, not ld.dataset[i]: edge_block loaders blockify here
        batch = pad_graphs([ld._graph(i) for i in range(n_graphs)],
                           **ld.pad_kwargs())
        statics = (batch.edges_sorted, batch.edge_block, batch.edge_tile,
                   batch.max_in_degree)
        if statics != (template.edges_sorted, template.edge_block,
                       template.edge_tile, template.max_in_degree):
            raise ValueError(
                f"partition {p} static layout {statics} differs from the "
                "shared template — the loaders' dataset-stable scan failed")
        if batch.edge_pair is None:
            all_have_pair = False
        for f in _BATCH_ARRAY_FIELDS:
            leaf = getattr(batch, f)
            if leaf is None:
                continue
            piece = np.asarray(leaf)[None]  # [1, G, ...] partition block
            for dev in devs:
                shards[f].append((p, jax.device_put(piece, dev)))
        del batch  # free this partition's numpy before padding the next

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        all_have_pair = bool(np.all(multihost_utils.process_allgather(
            np.array(all_have_pair))))

    fields = {}
    for f in _BATCH_ARRAY_FIELDS:
        tmpl_leaf = getattr(template, f)
        if tmpl_leaf is None or (f == "edge_pair" and not all_have_pair):
            continue  # dropped pair shards are freed with the dict
        gshape = (n_parts, n_graphs) + np.asarray(tmpl_leaf).shape[1:]
        fields[f] = jax.make_array_from_single_device_arrays(
            gshape, sharding, [buf for _, buf in shards[f]])
    pair = fields.pop("edge_pair", None)
    return template.replace(**fields, edge_pair=pair)


def sharded_dataset_nbytes(sharded: ShardedGraphLoader) -> int:
    """PER-DEVICE footprint of stack_sharded_dataset: each device holds one
    partition's [G, ...] block (the partition axis is sharded; graphs within
    a partition share the static padded shape)."""
    ld = sharded.loaders[0]
    g0 = pad_graphs([ld._graph(0)], **ld.pad_kwargs())
    per = sum(np.asarray(x).nbytes for x in jax.tree.leaves(g0))
    return per * len(ld.dataset)


class DistributedScanRunner:
    """Scanned epochs over the distribute-mode mesh — same interface as
    ScanEpochRunner (train_epoch / eval_epoch), same permutation and PRNG
    discipline as the per-step path (tests/test_scan_epoch.py proves
    parameter parity for both runners).

    ``device_train_step`` / ``device_eval_step`` are the PER-DEVICE callables
    from parallel.launch.make_device_steps — axis-bound but not shard_mapped;
    here they trace into one shard_map(lax.scan) program per epoch.
    """

    def __init__(self, device_train_step: Callable,
                 device_eval_step: Optional[Callable], mesh,
                 loader_train: ShardedGraphLoader, seed: int,
                 loader_valid: Optional[ShardedGraphLoader] = None,
                 loader_test: Optional[ShardedGraphLoader] = None):
        self.seed = seed
        self.loader = loader_train
        self.dp = loader_train.data_parallel
        self.num_steps = len(loader_train)
        # per-partition graphs drawn per step (= batch_size * data_parallel)
        self.draw = loader_train.loaders[0].batch_size
        self.data_train = stack_sharded_dataset(loader_train, mesh)
        self.eval_sets = {}
        if device_eval_step is not None:
            for name, ld in (("valid", loader_valid), ("test", loader_test)):
                if ld is not None:
                    self.eval_sets[name] = (stack_sharded_dataset(ld, mesh),
                                            len(ld), ld.loaders[0].batch_size)
        self._mesh = mesh
        self._compile(device_train_step, device_eval_step)

    def _compile(self, device_train_step: Callable,
                 device_eval_step: Optional[Callable]):
        from jax.sharding import PartitionSpec as P

        self._device_train_step = device_train_step
        self._device_eval_step = device_eval_step
        mesh = self._mesh
        dp = self.dp
        data_spec = P(GRAPH_AXIS)
        # [S, B] replicated, or [S, D, B] with the D axis sharded over DATA:
        # each data shard picks ITS slice of the global batch's graph indices
        # (ShardedGraphLoader's [D, P, B] layout, loader.py)
        perm_spec = P(None, DATA_AXIS, None) if dp > 1 else P()

        def pick(data, idx):
            # local data leaves [1, G, ...] (this device's partition);
            # idx [B] (dp=1) or [1, B] (local slice of [S, D, B])
            return jax.tree.map(lambda a: a[0][idx.reshape(-1)], data)

        def run_train(state, data, perm, epoch_key):
            keys = jax.vmap(lambda i: jax.random.fold_in(epoch_key, i))(
                jnp.arange(perm.shape[0]))

            def body(st, inp):
                idx, k = inp
                st, metrics = device_train_step(st, pick(data, idx), k)
                return st, (metrics["loss"],
                            metrics.get("batch_consistency", jnp.float32(0)))

            state, (losses, cons) = jax.lax.scan(body, state, (perm, keys))
            # drop_last equal batch sizes -> plain mean == weighted average
            return state, jnp.mean(losses), jnp.max(cons)

        def run_eval(params, data, perm):
            def body(_, idx):
                return None, device_eval_step(params, pick(data, idx))

            _, losses = jax.lax.scan(body, None, perm)
            return jnp.mean(losses)

        self._run_train = jax.jit(shard_map(
            run_train, mesh=mesh,
            in_specs=(P(), data_spec, perm_spec, P()),
            out_specs=(P(), P(), P()), check_vma=False))
        self._run_eval = None
        if device_eval_step is not None:
            self._run_eval = jax.jit(shard_map(
                run_eval, mesh=mesh,
                in_specs=(P(), data_spec, perm_spec),
                out_specs=P(), check_vma=False))

    def with_train_step(self, device_train_step: Callable) -> "DistributedScanRunner":
        """A copy sharing the device-resident sharded datasets but scanning a
        NEW per-device train step — divergence recovery swaps in a decayed-LR
        step without re-staging HBM (trainer.py rollback path)."""
        import copy

        new = copy.copy(self)
        new._compile(device_train_step, self._device_eval_step)
        return new

    def _perm_array(self, order: np.ndarray, steps: int, draw: int):
        o = np.asarray(order[: steps * draw], dtype=np.int32)
        if self.dp > 1:
            # order[s*D*B + d*B + b] lands at [s, d, b] — exactly the
            # [P, D*B] -> [D, P, B] reshape ShardedGraphLoader applies
            return jnp.asarray(o.reshape(steps, self.dp, draw // self.dp))
        return jnp.asarray(o.reshape(steps, draw))

    def train_epoch(self, state, epoch: int):
        self.loader.set_epoch(epoch)
        # all partition loaders share (seed, epoch) -> one common order
        perm = self._perm_array(self.loader.loaders[0]._order(),
                                self.num_steps, self.draw)
        epoch_key = jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch)
        state, loss, cons = self._run_train(state, self.data_train, perm,
                                            epoch_key)
        from distegnn_tpu.train.trainer import assert_batch_consistency

        assert_batch_consistency(cons, epoch)
        return state, loss  # loss: device scalar; trainer fetches once

    def eval_epoch(self, params, split: str) -> float:
        data, steps, draw = self.eval_sets[split]
        perm = self._perm_array(np.arange(steps * draw), steps, draw)
        return float(self._run_eval(params, data, perm))
