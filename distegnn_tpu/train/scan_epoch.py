"""Device-resident epochs: ONE dispatch per epoch via lax.scan.

The reference's epoch loop dispatches one CUDA launch sequence per minibatch
(utils/train.py:83-117); the round-1 port kept that host-driven loop. On a
tunneled TPU every dispatch pays O(100ms) host->device latency, so an n-body
epoch (20 train + 16 eval micro-batches of ~1ms compute) cost ~2 min of pure
round-trips. TPU-native fix: the whole (uniformly padded) dataset lives in
HBM as one stacked GraphBatch, the epoch is a ``lax.scan`` over minibatch
index slices, and the host sees exactly one dispatch + one scalar fetch per
epoch. The permutation is still drawn on host from (seed, epoch) — identical
to GraphLoader._order — and the per-step PRNG keys are fold_in(epoch, step),
identical to the host loop, so the scanned trajectory is step-for-step the
same training run (tests/test_scan_epoch.py proves parameter parity).

Scope: single-process, uniform-shape datasets (all four pipelines pad to
dataset-wide maxima already). The distributed path keeps its per-step
dispatch — its batches are globally sharded jax.Arrays.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distegnn_tpu.data.loader import GraphLoader
from distegnn_tpu.ops.graph import GraphBatch, pad_graphs


def stack_dataset(loader: GraphLoader) -> GraphBatch:
    """Pad every graph of a loader's dataset to the loader's maxima and stack
    into one device-resident GraphBatch with leading axis [num_graphs]."""
    ds = loader.dataset
    batch = pad_graphs([ds[i] for i in range(len(ds))], **loader.pad_kwargs())
    return jax.device_put(batch)


def dataset_nbytes(loader: GraphLoader) -> int:
    """Rough device-memory footprint of stack_dataset (float32/int32 leaves)."""
    g0 = pad_graphs([loader.dataset[0]], **loader.pad_kwargs())
    per = sum(np.asarray(x).nbytes for x in jax.tree.leaves(g0))
    return per * len(loader.dataset)


class ScanEpochRunner:
    """Scanned replacements for run_epoch_train / run_epoch_eval.

    train_step(state, batch, key) -> (state, metrics) and
    eval_step(params, batch) -> loss are the SAME jittable callables the host
    loop uses; here they are traced into one epoch-long XLA program.
    """

    def __init__(self, train_step: Callable, eval_step: Optional[Callable],
                 loader_train: GraphLoader, seed: int,
                 loader_valid: Optional[GraphLoader] = None,
                 loader_test: Optional[GraphLoader] = None):
        self.seed = seed
        self.loader = loader_train
        self.batch_size = loader_train.batch_size
        self.num_steps = len(loader_train)
        self.data_train = stack_dataset(loader_train)
        self.eval_sets = {}
        if eval_step is not None:
            for name, ld in (("valid", loader_valid), ("test", loader_test)):
                if ld is not None:
                    self.eval_sets[name] = (stack_dataset(ld), len(ld), ld.batch_size)

        def pick(data: GraphBatch, idx):
            return jax.tree.map(lambda a: a[idx], data)

        def run_train(state, data, perm, epoch_key):
            def body(st, inp):
                idx, k = inp
                st, metrics = train_step(st, pick(data, idx), k)
                return st, metrics["loss"]

            keys = jax.vmap(lambda i: jax.random.fold_in(epoch_key, i))(
                jnp.arange(self.num_steps))
            state, losses = jax.lax.scan(body, state, (perm, keys))
            # equal batch sizes (drop_last) -> plain mean == weighted average
            return state, jnp.mean(losses)

        def run_eval(params, data, perm):
            def body(_, idx):
                return None, eval_step(params, pick(data, idx))

            _, losses = jax.lax.scan(body, None, perm)
            return jnp.mean(losses)

        self._run_train = jax.jit(run_train)
        self._run_eval = jax.jit(run_eval) if eval_step is not None else None

    def _perm(self, loader: GraphLoader, epoch: int, steps: int, bsz: int):
        loader.set_epoch(epoch)
        order = loader._order()[: steps * bsz]
        return jnp.asarray(order.reshape(steps, bsz).astype(np.int32))

    def train_epoch(self, state, epoch: int):
        perm = self._perm(self.loader, epoch, self.num_steps, self.batch_size)
        epoch_key = jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch)
        state, loss = self._run_train(state, self.data_train, perm, epoch_key)
        return state, loss  # loss: device scalar; trainer fetches once

    def eval_epoch(self, params, split: str) -> float:
        data, steps, bsz = self.eval_sets[split]
        perm = jnp.arange(steps * bsz, dtype=jnp.int32).reshape(steps, bsz)
        return float(self._run_eval(params, data, perm))
