"""Loss functions: node-weighted global MSE + MMD virtual-node regularizer.

Reference semantics (utils/train.py:98-147):
  - per-device MSE over its partition's nodes, scaled by node_cnt/total_node_cnt
    (allreduce SUM of counts), summed across devices — so gradients SUM over
    partitions (the reference multiplies by world_size to undo DDP's mean;
    here the psum expresses the sum directly).
  - MMD: RBF kernel exp(-d/(2 sigma^2)) on *Euclidean* distances between the C
    virtual-node locations and samples*C randomly-drawn target positions per
    graph; loss_mmd = l_vv - l_rv with the reference's exact normalizations
    (utils/train.py:119-147).

TPU deltas: the reference's per-graph Python loop with torch.randperm becomes
a vmapped draw over the padded node axis (SURVEY.md §7.4 item 4) — fully
traced, no host sync. When the padded node axis is no longer than samples*C
every real node is used exactly once (what randperm degenerates to), with no
sampling op at all; otherwise a uniform index draw over the real-node prefix
replaces round 1's Gumbel top-k, which ran an O(N)-wide top_k over the 113k
node axis every step (VERDICT r1 weak #2b).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from distegnn_tpu.ops.graph import GraphBatch
from distegnn_tpu.parallel.collectives import _psum


def masked_mse(pred: jnp.ndarray, target: jnp.ndarray, node_mask: jnp.ndarray) -> jnp.ndarray:
    """MSE over real nodes of the whole batch — nn.MSELoss on the flat node
    axis (mean over nodes*3), restricted to mask==1 rows."""
    err = (pred - target) ** 2 * node_mask[..., None]
    cnt = jnp.maximum(jnp.sum(node_mask), 1.0)
    return jnp.sum(err) / (cnt * pred.shape[-1])


def rbf_kernel_sum(x: jnp.ndarray, y: jnp.ndarray, sigma: float,
                   wx: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """sum_ij w_i * exp(-||x_i - y_j|| / (2 sigma^2)). Euclidean distance, NOT
    squared — parity with torch.cdist in reference kernel() (utils/train.py:11-14)."""
    d2 = jnp.sum((x[:, None, :] - y[None, :, :]) ** 2, axis=-1)
    d = jnp.sqrt(jnp.maximum(d2, 1e-24))
    k = jnp.exp(-d / (2.0 * sigma * sigma))
    if wx is not None:
        k = k * wx[:, None]
    return jnp.sum(k)


def mmd_loss(
    virtual_loc: jnp.ndarray,   # [B, 3, C]
    target: jnp.ndarray,        # [B, N, 3]
    node_mask: jnp.ndarray,     # [B, N]
    key: jax.Array,
    sigma: float,
    samples: int,
) -> jnp.ndarray:
    """loss_mmd = l_vv - l_rv (reference normalizations, utils/train.py:141-145:
    the l_rv denominator is ALWAYS samples*C, even when a graph has fewer real
    nodes — randperm(n)[:num_sample] just yields all n nodes then)."""
    B, N, _ = target.shape
    C = virtual_loc.shape[2]
    num_sample = samples * C
    V = jnp.swapaxes(virtual_loc, 1, 2)  # [B, C, 3]

    if N <= num_sample:
        # Every real node is drawn exactly once — what the reference's
        # randperm(n)[:num_sample] degenerates to. Deterministic, no sampling.
        def per_graph(target_b, mask_b, V_b):
            k_vv = rbf_kernel_sum(V_b, V_b, sigma)
            k_rv = rbf_kernel_sum(target_b, V_b, sigma, wx=mask_b)
            return k_vv, k_rv

        k_vv, k_rv = jax.vmap(per_graph)(target, node_mask, V)
    else:
        # Real nodes occupy the prefix of the padded axis (pad_graphs
        # contract), so a uniform draw over [0, n) is a plain randint — no
        # O(N) top_k. With-replacement vs the reference's without-replacement
        # is an unbiased delta (150 draws from >100k nodes); graphs with
        # n < num_sample are down-weighted by n/num_sample to keep the
        # reference's expectation exactly.
        def per_graph(key_b, target_b, mask_b, V_b):
            n = jnp.sum(mask_b)
            u = jax.random.uniform(key_b, (num_sample,))
            idx = jnp.minimum((u * n).astype(jnp.int32), N - 1)
            w = jnp.minimum(n, float(num_sample)) / num_sample
            k_vv = rbf_kernel_sum(V_b, V_b, sigma)
            k_rv = rbf_kernel_sum(target_b[idx], V_b, sigma) * w
            return k_vv, k_rv

        keys = jax.random.split(key, B)
        k_vv, k_rv = jax.vmap(per_graph)(keys, target, node_mask, V)
    l_vv = jnp.sum(k_vv) / B / C / C
    l_rv = 2.0 * jnp.sum(k_rv) / B / num_sample / C
    return l_vv - l_rv


def weighted_local_loss(
    local_loss: jnp.ndarray,
    node_mask: jnp.ndarray,
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    """This partition's node-weighted share of the global loss:
    local_loss * node_cnt / total_node_cnt (reference utils/train.py:100-110).
    NOT summed across partitions — differentiate THIS and psum the parameter
    gradients (the DDP-sum pattern): seeding each device's backward from the
    psum'd global loss instead would scale every cotangent by the axis size,
    because the transpose of psum is psum."""
    node_cnt = jnp.sum(node_mask)
    total = _psum(node_cnt, axis_name)
    return local_loss * node_cnt / jnp.maximum(total, 1.0)


def weighted_global_loss(
    local_loss: jnp.ndarray,
    node_mask: jnp.ndarray,
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    """Node-weighted global loss summed across partitions — the logged/eval
    quantity (reference total_loss_loc, utils/train.py:112-114). Single-device
    this is the identity."""
    return _psum(weighted_local_loss(local_loss, node_mask, axis_name), axis_name)
