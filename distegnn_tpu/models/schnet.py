"""SchNet baseline with equivariant coordinate updates, TPU-native.

Re-design of reference models/SchNet.py (a PyG SchNet fork, 362 LoC): per
interaction block the standard continuous-filter feature update PLUS an added
equivariant coordinate update ``pos += scatter_mean((pos_r - pos_c) *
Linear([gauss(d), h_r, h_c]))`` (reference SchNet.py:191-198). The feature
path keeps PyG's pieces: GaussianSmearing distance expansion, CFConv with
cosine cutoff window, ShiftedSoftplus, xavier/zero-bias inits
(SchNet.py:271-341). Embedding is a Linear over the 2 node features — the
reference replaces the atomic-number Embedding (SchNet.py:121-124).

Batched GraphBatch layout; every aggregation masked.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from distegnn_tpu.models.common import TorchDense, gather_nodes
from distegnn_tpu.ops.graph import GraphBatch
from distegnn_tpu.ops.segment import segment_mean, segment_sum

xavier = nn.initializers.xavier_uniform()


def shifted_softplus(x):
    return jax.nn.softplus(x) - float(np.log(2.0))


class GaussianSmearing(nn.Module):
    """exp(-gamma (d - mu_k)^2) distance expansion (reference SchNet.py:344-358)."""

    start: float = 0.0
    stop: float = 5.0
    num_gaussians: int = 50

    @nn.compact
    def __call__(self, dist):
        offset = jnp.linspace(self.start, self.stop, self.num_gaussians)
        coeff = -0.5 / float((self.stop - self.start) / (self.num_gaussians - 1)) ** 2
        return jnp.exp(coeff * (dist[..., None] - offset) ** 2)


class CFConv(nn.Module):
    """Continuous-filter conv: x_i' = lin2(sum_j lin1(x_j) * W(d_ij))
    with the cosine cutoff window (reference SchNet.py:305-341)."""

    hidden_channels: int
    num_filters: int
    cutoff: float

    @nn.compact
    def __call__(self, h, g: GraphBatch, edge_weight, edge_attr):
        W = nn.Dense(self.num_filters, kernel_init=xavier, bias_init=nn.initializers.zeros)(edge_attr)
        W = shifted_softplus(W)
        W = nn.Dense(self.num_filters, kernel_init=xavier, bias_init=nn.initializers.zeros)(W)
        C = 0.5 * (jnp.cos(edge_weight * jnp.pi / self.cutoff) + 1.0)
        W = W * C[..., None] * g.edge_mask[..., None]

        x = nn.Dense(self.num_filters, use_bias=False, kernel_init=xavier)(h)
        msg = gather_nodes(x, g.col) * W
        N = h.shape[1]
        agg = jax.vmap(lambda m, r: segment_sum(m, r, N))(msg, g.row)  # aggr='add'
        return nn.Dense(self.hidden_channels, kernel_init=xavier, bias_init=nn.initializers.zeros)(agg)


class InteractionBlock(nn.Module):
    """CFConv -> ShiftedSoftplus -> Linear (reference SchNet.py:271-302)."""

    hidden_channels: int
    num_filters: int
    cutoff: float

    @nn.compact
    def __call__(self, h, g: GraphBatch, edge_weight, edge_attr):
        x = CFConv(self.hidden_channels, self.num_filters, self.cutoff)(h, g, edge_weight, edge_attr)
        x = shifted_softplus(x)
        return nn.Dense(self.hidden_channels, kernel_init=xavier, bias_init=nn.initializers.zeros)(x)


class SchNet(nn.Module):
    """Baseline SchNet (reference factory: hidden_channels=hidden_nf, cutoff
    per dataset, defaults num_interactions=6 / filters=128 / gaussians=50,
    main.py:81 + SchNet.py:85-96). Returns (pos_pred, None)."""

    hidden_channels: int = 128
    num_filters: int = 128
    num_interactions: int = 6
    num_gaussians: int = 50
    cutoff: float = 10.0
    embed_input: bool = True

    @nn.compact
    def __call__(self, g: GraphBatch, h: Optional[jnp.ndarray] = None) -> Tuple[jnp.ndarray, None]:
        pos = g.loc
        if h is None:
            h = g.node_feat
        if self.embed_input:
            # torch-default init: the reference does not re-init its embedding
            # Linear (SchNet.py:121-124 is excluded from reset_parameters)
            h = TorchDense(self.hidden_channels, name="embedding")(h)
        pos, h = self.run_interactions(h, pos, g)
        return pos, None

    def run_interactions(self, h, pos, g: GraphBatch):
        """The interaction stack, reusable by FastSchNet's coordinate path
        (which feeds its own h and discards the feature update).

        Distances and their gaussian expansion come from the INITIAL positions
        only — the reference computes them once before the loop
        (SchNet.py:187-189); just the direction vector tracks updated pos."""
        N = pos.shape[1]
        row, col = g.row, g.col
        diff0 = gather_nodes(pos, row) - gather_nodes(pos, col)
        edge_weight = jnp.linalg.norm(diff0 + 1e-30, axis=-1)
        edge_attr = GaussianSmearing(0.0, self.cutoff, self.num_gaussians,
                                     name="smearing")(edge_weight)
        for i in range(self.num_interactions):
            diff = gather_nodes(pos, row) - gather_nodes(pos, col)
            # equivariant coordinate update (the reference's addition; its
            # coord_updates Linears keep torch default init, SchNet.py:137-139)
            gate = TorchDense(1, name=f"coord_update_{i}")(
                jnp.concatenate([edge_attr, gather_nodes(h, row), gather_nodes(h, col)], axis=-1))
            aggr = diff * gate
            upd = jax.vmap(lambda m, r, e: segment_mean(m, r, N, mask=e))(aggr, row, g.edge_mask)
            pos = pos + upd * g.node_mask[..., None]
            h = h + InteractionBlock(self.hidden_channels, self.num_filters, self.cutoff,
                                     name=f"interaction_{i}")(h, g, edge_weight, edge_attr)
            h = h * g.node_mask[..., None]
        return pos, h
