from distegnn_tpu.models.fast_egnn import FastEGNN, EGCLVel  # noqa: F401
from distegnn_tpu.models.registry import get_model  # noqa: F401
