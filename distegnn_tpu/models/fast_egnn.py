"""FastEGNN / DistEGNN — the paper's core model, TPU-native.

Re-design of reference models/FastEGNN.py (E_GCL_vel + FastEGNN, 336 LoC):
EGNN with C learnable *virtual nodes* per graph; in distributed (DistEGNN)
mode each device owns one spatial partition of the graph and the virtual-node
state is the only cross-partition channel — exactly three global weighted
means per layer (reference models/FastEGNN.py:258-261, 191-200, 220-234),
realized here as `psum` over the mesh 'graph' axis instead of NCCL allreduces.

Layout: dense batched GraphBatch ([B,N,...] + masks, see ops/graph.py). Every
MLP application is one large matmul over [B*N(*C), F] — MXU-shaped — and the
whole L-layer forward traces into a single XLA program with no host sync.

Shape legend: B graphs, N padded nodes (per partition), E padded edges,
H hidden, C virtual channels.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from distegnn_tpu.models.common import (
    MLP, CoordMLP, HoistedEdgeMLP, TorchDense, _TorchDenseParams,
    _torch_bias_init, coord_head_init, gather_nodes, resolve_dtype,
    torch_linear_init,
)
from distegnn_tpu.ops.blocked import EdgeOps, blocked_slot_inv_deg
from distegnn_tpu.ops.edge_pipeline import (EdgeWeights, build_edge_blocks,
                                            fused_edge_layer)
from distegnn_tpu.ops.layer_pipeline import (DEFAULT_STACK_VMEM_BUDGET,
                                             StackConfig, fused_egnn_stack)
from distegnn_tpu.ops.graph import GraphBatch
from distegnn_tpu.ops.segment import masked_sum
from distegnn_tpu.parallel.collectives import (
    global_node_mean, tp_copy, tp_gather, tp_once, tp_reduce, tp_slice,
)


class FusedEdgeParams(nn.Module):
    """Raw phi_e + phi_x parameters for ``edge_impl='fused'``.

    Same shapes and init variances as the hoisted plain path (HoistedEdgeMLP
    ``phi_e`` + CoordMLP ``phi_x``), declared as raw arrays because both the
    Pallas kernel (ops/edge_pipeline.EdgeWeights) and the compact remote tail
    consume the weights directly. Like ``hoist_edge_mlp``, flipping
    ``edge_impl`` changes the param tree — checkpoints are not compatible
    across the flag (tests/test_fused_model.py remaps between them)."""

    hidden_nf: int
    scalar_nf: int           # per-edge scalars: radial + edge_attr

    @nn.compact
    def __call__(self):
        H, S = self.hidden_nf, self.scalar_nf
        fan1 = 2 * H + S
        w1 = self.param("w1", torch_linear_init, (fan1, H), jnp.float32)
        b1 = self.param("b1", _torch_bias_init(fan1), (H,), jnp.float32)
        w2 = self.param("w2", torch_linear_init, (H, H), jnp.float32)
        b2 = self.param("b2", _torch_bias_init(H), (H,), jnp.float32)
        w3 = self.param("w3", torch_linear_init, (H, H), jnp.float32)
        b3 = self.param("b3", _torch_bias_init(H), (H,), jnp.float32)
        w4 = self.param("w4", coord_head_init, (H, 1), jnp.float32)
        return w1, b1, w2, b2, w3, b3, w4


class _MLPParams(nn.Module):
    """Parameter-only shadow of :class:`common.MLP` (non-TP path): declares
    the identical ``TorchDense_{i}/Dense_0/{kernel,bias}`` subtree — same
    names, shapes, and initializers — without the compute. Flax derives init
    RNG from the module PATH, so a checkpoint is bitwise interchangeable
    between this and the real MLP (the precedent is MLP's own tensor-parallel
    branch, which does the same with _TorchDenseParams). The fused_stack
    megakernel uses these to own the whole layer loop while keeping the
    param tree identical to the per-layer EGCLVel modules."""

    sizes: Tuple[int, ...]
    use_bias_last: bool = True
    kernel_init_last: Optional[object] = None

    @nn.compact
    def __call__(self, fan_in: int):
        outs = []
        f = fan_in
        for i, s in enumerate(self.sizes):
            last = i == len(self.sizes) - 1
            outs.append(_TorchDenseParams(
                s, use_bias=(self.use_bias_last if last else True),
                kernel_init=(self.kernel_init_last if last else None),
                name=f"TorchDense_{i}")(f))
            f = s
        return outs


class _CoordMLPParams(nn.Module):
    """Parameter-only shadow of :class:`common.CoordMLP` (``MLP_0`` subtree:
    Dense(H) + biasless coord-head Dense(1) with coord_head_init)."""

    hidden_nf: int

    @nn.compact
    def __call__(self, fan_in: int):
        return _MLPParams([self.hidden_nf, 1], use_bias_last=False,
                          kernel_init_last=coord_head_init,
                          name="MLP_0")(fan_in)


class _EGCLVelStackParams(nn.Module):
    """Parameter-only shadow of one fused-path EGCLVel layer, returned in the
    megakernel's flat weight layout (ops/layer_pipeline.stack_weight_shapes).

    Declares exactly the subtree EGCLVel's ``edge_impl='fused'`` branch
    declares — phi_e_fused raw arrays plus the phi_ev/phi_xv/phi_X/phi_v/
    phi_h/phi_hv (+phi_g) MLP stacks — so ``edge_impl: fused_stack`` shares
    checkpoints bitwise with ``fused``: the [L, a, b] stacking that
    fused_egnn_stack consumes is a runtime VIEW (stack/transpose/row-bias
    reshape), not a different tree."""

    hidden_nf: int
    virtual_channels: int
    node_attr_nf: int
    edge_attr_nf: int
    has_gravity: bool

    @nn.compact
    def __call__(self):
        H, C, A = self.hidden_nf, self.virtual_channels, self.node_attr_nf
        w1, b1, w2, b2, w3, b3, w4 = FusedEdgeParams(
            H, 1 + self.edge_attr_nf, name="phi_e_fused")()
        ev = _MLPParams([H, H], name="phi_ev")(2 * H + 1 + C)
        xv = _CoordMLPParams(H, name="phi_xv")(H)
        Xh = _CoordMLPParams(H, name="phi_X")(H)
        vv = _MLPParams([H, 1], name="phi_v")(H)
        hh = _MLPParams([H, H], name="phi_h")(3 * H + A)
        hv = _MLPParams([H, H], name="phi_hv")(2 * H)
        row = lambda b: b[None]                  # [F] bias -> [1, F] row view
        w = {"e_w1": w1, "e_b1": row(b1), "e_w2": w2, "e_b2": row(b2),
             "e_w3": w3, "e_b3": row(b3), "e_w4": w4.T,
             "ev_k0": ev[0][0], "ev_b0": row(ev[0][1]),
             "ev_k1": ev[1][0], "ev_b1": row(ev[1][1]),
             "xv_k0": xv[0][0], "xv_b0": row(xv[0][1]), "xv_k1": xv[1][0],
             "X_k0": Xh[0][0], "X_b0": row(Xh[0][1]), "X_k1": Xh[1][0],
             "v_k0": vv[0][0], "v_b0": row(vv[0][1]),
             "v_k1": vv[1][0], "v_b1": vv[1][1].reshape(1, 1),
             "h_k0": hh[0][0], "h_b0": row(hh[0][1]),
             "h_k1": hh[1][0], "h_b1": row(hh[1][1]),
             "hv_k0": hv[0][0], "hv_b0": row(hv[0][1]),
             "hv_k1": hv[1][0], "hv_b1": row(hv[1][1])}
        if self.has_gravity:
            gg = _MLPParams([H, 1], name="phi_g")(H)
            w.update({"g_k0": gg[0][0], "g_b0": row(gg[0][1]),
                      "g_k1": gg[1][0], "g_b1": gg[1][1].reshape(1, 1)})
        return w


class EGCLVel(nn.Module):
    """E(n)-equivariant conv layer with velocity + virtual-node channels.

    Mirrors reference E_GCL_vel (models/FastEGNN.py:46-276): MLPs phi_e,
    phi_ev, phi_x, phi_xv, phi_X, phi_v, phi_h, phi_hv (+ optional attention
    gates and gravity head), with the three distributed global means marked.
    """

    hidden_nf: int
    virtual_channels: int
    node_attr_nf: int = 0
    edge_attr_nf: int = 0
    residual: bool = True
    attention: bool = False
    normalize: bool = False
    coords_agg: str = "mean"
    tanh: bool = False
    has_gravity: bool = False
    axis_name: Optional[str] = None  # mesh axis of graph partitions ('graph') or None
    # mesh axis of the hidden-dim shards ('tensor') or None. When set, each
    # chip computes a 1/T hidden slice of phi_e/phi_x/phi_h per edge/node
    # block, with exactly one collective per MLP at the layer boundary:
    # phi_e — node-level tiled all-gather of the hoisted h@W products;
    # phi_x — partial per-edge scalars ride coord_diff and the segment sum
    #         to the node axis, then ONE psum of the [B,N,3] aggregate;
    # phi_h — Megatron column/row split closed by ONE psum of [B,N,H].
    # Virtual-node MLPs (C channels, tiny) stay replicated. Params stay FULL
    # on every chip — slicing happens at compute time — so the param tree,
    # checkpoints, and the (data, graph) gradient psum are unchanged.
    tensor_axis: Optional[str] = None
    epsilon: float = 1e-8
    # compute dtype of the invariant-message MLPs ('bf16' or None=f32). All
    # GEOMETRY (coord_diff, radial, coordinate updates, aggregations) stays
    # f32, so equivariance is exact at math level — bf16 only widens noise in
    # invariant channels. See tests/test_equivariance.py::test_bf16.
    compute_dtype: Optional[str] = None
    # evaluate phi_e's first Dense on the node axis (HoistedEdgeMLP): same
    # math, E/N x fewer matmul rows, no [E, 2H+S] concat. False restores the
    # reference-shaped concat MLP (different param tree — not ckpt-compatible)
    hoist_edge_mlp: bool = True
    seg_impl: str = "scatter"  # plain-layout aggregation lowering ('scatter'|'cumsum'|'ell')
    # one packed aggregation pass per layer (translations + edge features +
    # count ride a single segment sum — EdgeOps.agg_rows_pair) instead of
    # two aggregations and a count. Same math; accumulation is ALWAYS f32 in
    # the fused path, so under compute_dtype=bf16 it is slightly MORE
    # precise than the legacy two-call path (whose bf16 edge_feat
    # aggregation accumulated in bf16) — not bit-identical for bf16 models;
    # fuse_agg=False restores the legacy numerics exactly.
    fuse_agg: bool = True
    # stream dtype of the packed aggregation ('bf16' halves the [E,3+H] read
    # bytes; accumulation stays f32). bf16 ROUNDS THE COORDINATE
    # TRANSLATIONS — equivariance becomes approximate at bf16 noise level.
    # Measured opt-in (VERDICT r3 #1), None = f32.
    agg_dtype: Optional[str] = None
    # real-edge lowering: 'plain' = per-edge streams through EdgeOps (any
    # layout), 'fused' = ONE Pallas pass per layer over the blocked in-window
    # edges (ops/edge_pipeline) plus a dense remote tail — needs a blocked
    # batch built with split_remote=True and edge_block >= 512
    edge_impl: str = "plain"

    @nn.compact
    def __call__(
        self,
        h: jnp.ndarray,          # [B, N, H] node features
        x: jnp.ndarray,          # [B, N, 3] coordinates
        v: jnp.ndarray,          # [B, N, 3] velocities
        X: jnp.ndarray,          # [B, 3, C] virtual coordinates (global objects)
        Hv: jnp.ndarray,         # [B, H, C] virtual features (global objects)
        g: GraphBatch,
        gravity: Optional[jnp.ndarray] = None,  # [3]
        slot: Optional[jnp.ndarray] = None,     # [B, E] blocked-layout slots
        inv_deg: Optional[jnp.ndarray] = None,  # [B, N, 1] 1/max(in-degree, 1)
        oh: Optional[jnp.ndarray] = None,       # [B, nb, epb, block] einsum incidence
        fused_arrs: Optional[Tuple] = None,     # batched build_edge_blocks output
        # tiled serving (serve/tiled.py): the layer runs over ONE tile of a
        # larger scene. tile_coord_mean is the precomputed SCENE-global
        # coordinate mean (replaces psum #1 — a tile-local mean would be
        # wrong); tile_partials=True returns the tile's masked-sum
        # contributions to psums #2/#3 instead of applying them (the
        # executor closes X/Hv once per layer via tiled_virtual_update).
        # Correct because every cross-node quantity here (vcd, m_X, vef,
        # trans_X) is computed from LAYER-INPUT X/Hv/x.
        tile_coord_mean: Optional[jnp.ndarray] = None,  # [B, 3]
        tile_partials: bool = False,
    ) -> Tuple[jnp.ndarray, ...]:
        H, C = self.hidden_nf, self.virtual_channels
        dt = resolve_dtype(self.compute_dtype)
        node_mask = g.node_mask                      # [B, N]
        edge_mask = g.edge_mask                      # [B, E]
        nm = node_mask[..., None]
        ops = EdgeOps(g, slot, inv_deg, oh, seg_impl=self.seg_impl)

        # --- real-edge lowering: 'plain' materializes per-edge streams via
        # EdgeOps; 'fused' runs one Pallas pass over the blocked in-window
        # edges + a dense remote tail and yields aggregated [B, N, ...]
        # results directly (no per-edge intermediate ever touches HBM)
        if self.edge_impl not in ("plain", "fused"):
            raise ValueError(f"unknown edge_impl {self.edge_impl!r}")
        if self.coords_agg not in ("sum", "mean"):
            raise ValueError(f"Wrong coords_agg parameter {self.coords_agg!r}")
        fused = self.edge_impl == "fused"
        agg = agg_h_f = None
        if fused:
            if self.attention or self.normalize or self.tanh:
                raise ValueError(
                    "edge_impl='fused' supports the flagship EGCL only: "
                    "attention/normalize/tanh are baked out of the kernel — "
                    "use edge_impl='plain' with those heads")
            if self.edge_attr_nf != 2:
                raise ValueError(
                    f"edge_impl='fused' requires edge_attr_nf=2 (the kernel "
                    f"scalar lanes are [radial, attr0, attr1]); got "
                    f"{self.edge_attr_nf}")
            if fused_arrs is None or g.remote_edge_index is None:
                raise ValueError(
                    "edge_impl='fused' needs a blocked batch built with "
                    "split_remote=True plus the hoisted build_edge_blocks "
                    "arrays (FastEGNN passes them) — check data.edge_block "
                    "and the loader's split_remote flag")
            w1, b1, w2, b2, w3, b3, w4 = FusedEdgeParams(
                H, 1 + self.edge_attr_nf, name="phi_e_fused")()
            c = (lambda a: a.astype(dt)) if dt is not None else (lambda a: a)
            tx = self.tensor_axis
            if tx is not None:
                # Tensor-parallel dispatch of the SAME kernel: the hoisted
                # node-axis products are column-sliced then gathered (phi_e's
                # collective), and the phi_x head weights (w3/b3/w4) flow in
                # as 1/T slices — the kernel derives every internal shape from
                # its operands, so no kernel change. Its trans_sum output
                # becomes a rank-local partial (closed by one node-level psum
                # below); ef_sum/count stay replicated. Kernel inputs carrying
                # gradients are wrapped in tp_copy (bwd psum) because the
                # kernel's cotangents mix the partial phi_x path with the
                # replicated phi_e path; the replicated outputs are wrapped in
                # tp_once (bwd /T) so that psum counts their cotangent once.
                hcp = tp_copy(c(h), tx)
                hr = tp_gather(hcp @ tp_slice(c(w1[:H]), tx), tx)
                hc = tp_gather(hcp @ tp_slice(c(w1[H:2 * H]), tx), tx)
                hr, hc = tp_copy(hr, tx), tp_copy(hc, tx)
                kw = EdgeWeights(ws=tp_copy(w1[2 * H:], tx),
                                 b1=tp_copy(b1, tx)[None],
                                 w2=tp_copy(w2, tx), b2=tp_copy(b2, tx)[None],
                                 w3=tp_slice(w3, tx), b3=tp_slice(b3, tx)[None],
                                 w4=tp_slice(w4.T, tx))
                xk = tp_copy(x, tx)
            else:
                hr = c(h) @ c(w1[:H])          # hoisted node-axis products
                hc = c(h) @ c(w1[H:2 * H])     # (HoistedEdgeMLP algebra)
                kw = EdgeWeights(ws=w1[2 * H:], b1=b1[None], w2=w2, b2=b2[None],
                                 w3=w3, b3=b3[None], w4=w4.T)
                xk = x
            dname = "bf16" if dt is jnp.bfloat16 else "f32"
            row_t, col_l, kblk, scal = fused_arrs
            outs = [fused_edge_layer(xk[b], hr[b], hc[b], row_t[b], col_l[b],
                                     kblk[b], scal[b], kw, g.edge_block, dname)
                    for b in range(h.shape[0])]
            trans_sum = jnp.stack([o[0] for o in outs])          # [B, N, 3]
            count = jnp.stack([o[1] for o in outs])              # [B, N]
            ef_sum = jnp.stack([o[2] for o in outs])             # [B, N, H]

            # remote tail (~5-8% of E): identical math, dense over the
            # compact out-of-window edge list carried on the batch. Under
            # tensor parallelism it dispatches with the SAME weight slicing
            # as the kernel so the combined trans_sum stays one partial.
            if tx is not None:
                cws, cb1 = tp_copy(c(w1[2 * H:]), tx), tp_copy(c(b1), tx)
                cw2, cb2 = tp_copy(c(w2), tx), tp_copy(c(b2), tx)
                cw3, cb3 = tp_slice(c(w3), tx), tp_slice(c(b3), tx)
                w4r = tp_slice(w4.T, tx).T                       # [H/T, 1]
            else:
                cws, cb1, cw2, cb2, cw3, cb3, w4r = (
                    c(w1[2 * H:]), c(b1), c(w2), c(b2), c(w3), c(b3), w4)
            rr, rc = g.remote_edge_index[:, 0], g.remote_edge_index[:, 1]
            rm = g.remote_edge_mask[..., None]                   # [B, R, 1]
            cd_r = (gather_nodes(xk, rr) - gather_nodes(xk, rc)) * rm
            radial_r = jnp.sum(cd_r * cd_r, axis=-1, keepdims=True)
            sfeat = c(jnp.concatenate(
                [radial_r, g.remote_edge_attr[..., :2]], axis=-1))
            t1 = (gather_nodes(hr, rr) + gather_nodes(hc, rc)
                  + sfeat @ cws + cb1)
            ef_r = nn.silu(nn.silu(t1) @ cw2 + cb2)              # [B, R, H]
            y2 = nn.silu(ef_r @ cw3 + cb3)
            g_r = (y2.astype(jnp.float32) @ w4r) * rm            # [B, R, 1]
            N_ = x.shape[1]
            seg = jax.vmap(
                lambda val, r: jax.ops.segment_sum(val, r, num_segments=N_))
            trans_sum = trans_sum + seg(cd_r * g_r, rr)
            count = count + seg(g.remote_edge_mask, rr)
            ef_sum = ef_sum + seg(ef_r.astype(jnp.float32) * rm, rr)
            if tx is not None:
                # close phi_x with its ONE node-level psum; ef_sum/count were
                # computed redundantly on every tensor rank — tp_once makes
                # the tp_copy-psum'd input cotangents count them exactly once
                trans_sum = tp_reduce(trans_sum, tx)
                ef_sum = tp_once(ef_sum, tx)
                count = tp_once(count, tx)

            cnt = jnp.maximum(count, 1.0)[..., None]
            agg = trans_sum / cnt if self.coords_agg == "mean" else trans_sum
            agg_h_f = ef_sum / cnt
        else:
            # --- real-edge geometry (reference coord2radial, :237-246)
            coord_diff = ops.gather_rows(x) - ops.gather_cols(x)        # [B, E, 3]
            radial = jnp.sum(coord_diff**2, axis=-1, keepdims=True)     # [B, E, 1]
            if self.normalize:
                norm = jax.lax.stop_gradient(jnp.sqrt(radial)) + self.epsilon
                coord_diff = coord_diff / norm

            # --- real edge messages phi_e (:144-150)
            if self.hoist_edge_mlp:
                scalars = (jnp.concatenate([radial, g.edge_attr], axis=-1)
                           if self.edge_attr_nf else radial)
                edge_feat = HoistedEdgeMLP(H, 1 + self.edge_attr_nf,
                                           name="phi_e", dtype=dt,
                                           tensor_axis=self.tensor_axis)(
                                               h, scalars, ops)
            else:
                if self.tensor_axis is not None:
                    raise ValueError(
                        "tensor parallelism requires hoist_edge_mlp=True "
                        "(phi_e's collective is the node-level gather of the "
                        "hoisted products; the concat-shaped phi_e would "
                        "need a per-edge gather)")
                e_in = [ops.gather_rows(h), ops.gather_cols(h), radial]
                if self.edge_attr_nf:
                    e_in.append(g.edge_attr)
                edge_feat = MLP([H, H], act_last=True, name="phi_e", dtype=dt)(
                    jnp.concatenate(e_in, axis=-1))
            if self.attention:
                gate_e = jax.nn.sigmoid(TorchDense(1, name="att", dtype=dt)(edge_feat))
                edge_feat = edge_feat * gate_e                           # [B, E, H]
            edge_feat = edge_feat * edge_mask[..., None].astype(edge_feat.dtype)

        # --- virtual-edge geometry (:252-253): every node sees all C virtual nodes
        vcd = X[:, None, :, :] - x[..., None]                           # [B, N, 3, C]
        virtual_radial = jnp.linalg.norm(vcd, axis=2, keepdims=True)    # [B, N, 1, C]

        # ---------- psum #1: exact global coordinate mean (:258-261)
        coord_mean = (tile_coord_mean if tile_coord_mean is not None
                      else global_node_mean(x, node_mask, self.axis_name))  # [B, 3]

        # --- invariant virtual mixing m_X: Gram of centered virtual coords (:263-264)
        Xc = X - coord_mean[:, :, None]                                  # [B, 3, C]
        m_X = jnp.einsum("bdc,bde->bce", Xc, Xc)                        # [B, C, C]

        # --- virtual edge messages phi_ev (:153-163): [B, N, C, 2H+1+C] -> [B, N, C, H]
        B, N = h.shape[0], h.shape[1]
        v_in = jnp.concatenate(
            [
                jnp.broadcast_to(h[:, :, None, :], (B, N, C, H)),
                jnp.broadcast_to(jnp.swapaxes(Hv, 1, 2)[:, None, :, :], (B, N, C, H)),
                jnp.swapaxes(virtual_radial, 2, 3),                      # [B, N, C, 1]
                jnp.broadcast_to(m_X[:, None, :, :], (B, N, C, C)),
            ],
            axis=-1,
        )
        vef = MLP([H, H], act_last=True, name="phi_ev", dtype=dt)(v_in)  # [B, N, C, H]
        if self.attention:
            gate = jax.nn.sigmoid(TorchDense(1, name="att_v", dtype=dt)(vef))
            vef = vef * gate
        vef = vef * node_mask[:, :, None, None].astype(vef.dtype)        # zero padded nodes

        # --- real coordinate update (coord_model_vel, :166-188); the fused
        # path already holds the aggregated translations in `agg`
        if not fused:
            # tensor-parallel phi_x returns a rank-local PARTIAL scalar; it
            # rides coord_diff and the row aggregation (all linear) to the
            # node axis, where ONE psum of [B, N, 3] closes the MLP —
            # per-edge traffic never crosses the tensor axis. coord_diff is
            # tp_copy-wrapped so its cotangent (partial per rank) is summed.
            cdm = (tp_copy(coord_diff, self.tensor_axis)
                   if self.tensor_axis is not None else coord_diff)
            trans = cdm * CoordMLP(H, tanh=self.tanh, name="phi_x", dtype=dt,
                                   tensor_axis=self.tensor_axis)(edge_feat)  # [B, E, 3]
            if self.fuse_agg:
                # both per-layer aggregations (+ the count) in ONE pass (blocked
                # layouts keep two calls inside but honor the agg_dtype knob)
                agg, agg_h_f = ops.agg_rows_pair(
                    trans, edge_feat, a_mean=(self.coords_agg == "mean"),
                    agg_dtype=self.agg_dtype)
            else:
                agg = (ops.agg_rows_sum(trans) if self.coords_agg == "sum"
                       else ops.agg_rows_mean(trans))                    # [B, N, 3]
                agg_h_f = None
            if self.tensor_axis is not None:
                agg = tp_reduce(agg, self.tensor_axis)
        x = x + agg

        phi_xv = CoordMLP(H, tanh=self.tanh, name="phi_xv", dtype=dt)(vef)  # [B, N, C, 1]
        trans_v = jnp.mean(-vcd * jnp.swapaxes(phi_xv, 2, 3), axis=-1)   # [B, N, 3]
        x = x + trans_v
        x = x + MLP([H, 1], name="phi_v", dtype=dt)(h).astype(jnp.float32) * v
        if self.has_gravity:
            x = x + MLP([H, 1], name="phi_g", dtype=dt)(h).astype(jnp.float32) * gravity
        x = x * nm  # keep padding clean

        # ---------- psum #2: virtual coordinate update (coord_model_virtual, :191-200)
        trans_X = vcd * jnp.swapaxes(CoordMLP(H, tanh=self.tanh, name="phi_X", dtype=dt)(vef), 2, 3)  # [B, N, 3, C]
        if tile_partials:
            transX_part = masked_sum(trans_X, node_mask, axis=1)         # [B, 3, C]
        else:
            X = X + global_node_mean(trans_X, node_mask, self.axis_name)  # [B, 3, C]

        # --- node feature update (node_model, :203-217)
        agg_h = agg_h_f if agg_h_f is not None else ops.agg_rows_mean(edge_feat)
        agg_v = jnp.mean(vef, axis=2)                                    # [B, N, H]
        n_in = [h, agg_h, agg_v]
        if self.node_attr_nf:
            n_in.append(g.node_attr)
        out = MLP([H, H], name="phi_h", dtype=dt,
                  tensor_axis=self.tensor_axis)(jnp.concatenate(
                      [a.astype(jnp.float32) for a in n_in], axis=-1))
        h = (h + out) if self.residual else out
        h = h * nm

        # ---------- psum #3: virtual feature update (node_model_virtual, :220-234)
        if tile_partials:
            # same numerator/denominator as the two global_node_means above,
            # summed across tiles by the executor — phi_hv is applied there
            # (flax ignores the unused phi_hv subtree in this mode)
            vef_part = masked_sum(vef.astype(jnp.float32), node_mask, axis=1)  # [B, C, H]
            count = jnp.sum(node_mask.astype(jnp.float32), axis=1)       # [B]
            return h, x, transX_part, vef_part, count
        agg_Hv = global_node_mean(vef.astype(jnp.float32), node_mask, self.axis_name)  # [B, C, H]
        hv_in = jnp.concatenate([jnp.swapaxes(Hv, 1, 2), agg_Hv], axis=-1)  # [B, C, 2H]
        out_v = jnp.swapaxes(MLP([H, H], name="phi_hv", dtype=dt)(hv_in), 1, 2)  # [B, H, C]
        Hv = (Hv + out_v) if self.residual else out_v

        return h, x, Hv, X


def tiled_virtual_update(gcl_params, Hv, X, transX_sum, vef_sum, count, *,
                         residual: bool = True,
                         compute_dtype: Optional[str] = None):
    """Close one tiled layer's virtual-node state from per-tile partials.

    ``transX_sum`` [B,3,C], ``vef_sum`` [B,C,H] and ``count`` [B] are the
    sums of the ``tile_partials=True`` outputs over ALL tiles of the scene;
    dividing by the total count reproduces psums #2/#3 of the monolithic
    EGCLVel exactly (same numerator, same denominator, different summation
    order), then phi_hv — whose subtree EGCLVel skipped in tile mode — is
    applied here, once per layer instead of once per tile."""
    dt = resolve_dtype(compute_dtype)
    H = Hv.shape[1]
    cnt = jnp.maximum(count, 1.0)[:, None, None]
    X = X + transX_sum / cnt
    agg_Hv = vef_sum / cnt                                           # [B, C, H]
    hv_in = jnp.concatenate([jnp.swapaxes(Hv, 1, 2), agg_Hv], axis=-1)
    out_v = jnp.swapaxes(
        MLP([H, H], dtype=dt).apply({"params": gcl_params["phi_hv"]},
                                    hv_in), 1, 2)                    # [B, H, C]
    Hv = (Hv + out_v) if residual else out_v
    return Hv, X


def reduce_tile_partials(transX_part, vef_part, count, valid, axis_name):
    """Cross-device reduction of one tile ROUND's virtual-node partials
    (serve/mesh_tiled.py): each device of the round holds ONE tile's
    ``tile_partials=True`` outputs; masking by the slot's validity flag
    (ragged rounds carry zero-filled pad slots — their node_mask is already
    all-zero, the flag hard-guarantees it) and psumming over the round's
    device axis gives every device the round's summed partials. The host
    accumulates these round sums across rounds and feeds the layer total to
    :func:`tiled_virtual_update` — the same numerators/denominator as the
    sequential per-tile accumulation, in a different summation order."""
    v = valid.astype(jnp.float32)
    transX = jax.lax.psum(transX_part * v, axis_name)
    vef = jax.lax.psum(vef_part * v, axis_name)
    cnt = jax.lax.psum(count * v, axis_name)
    return transX, vef, cnt


class FastEGNN(nn.Module):
    """FastEGNN / DistEGNN wrapper (reference models/FastEGNN.py:279-307).

    Forward takes a GraphBatch and returns (node_loc_pred [B,N,3],
    virtual_node_loc [B,3,C]). Set ``axis_name='graph'`` under shard_map for
    the distributed (DistEGNN) mode — same weights, same math, exact global
    means via psum.
    """

    node_feat_nf: int
    node_attr_nf: int = 0
    edge_attr_nf: int = 0
    hidden_nf: int = 64
    virtual_channels: int = 3
    n_layers: int = 4
    residual: bool = True
    attention: bool = False
    normalize: bool = False
    tanh: bool = False
    gravity: Optional[Tuple[float, float, float]] = None
    axis_name: Optional[str] = None
    # mesh axis for hidden-dim tensor parallelism ('tensor') or None; see
    # EGCLVel.tensor_axis. hidden_nf must be divisible by the axis size.
    tensor_axis: Optional[str] = None
    compute_dtype: Optional[str] = None  # 'bf16' -> MXU-native message MLPs
    hoist_edge_mlp: bool = True  # phi_e first Dense on the node axis (see EGCLVel)
    # lowering of the blocked-layout edge ops (used only when the batch
    # carries edge_block > 0): 'einsum' = one-hot materialized once per
    # forward, ops are batched dots (default — no Pallas grid overhead);
    # 'pallas' = one-hot built in VMEM per kernel
    blocked_impl: str = "einsum"
    # plain-layout aggregation lowering (ops/segment.py): 'scatter' (XLA
    # sorted scatter, bit-exact), 'cumsum' (scatter-free prefix-sum
    # differences — f32-accumulated, sums carry ~|prefix|*eps rounding), or
    # 'ell' (scatter-free fixed-degree gathers — exact)
    segment_impl: str = "scatter"
    # recompute each layer's activations in the backward pass instead of
    # keeping them in HBM: layer activations are O(E*H) (hundreds of MB at
    # LargeFluid scale), so remat trades cheap recompute FLOPs for the
    # memory that bounds graph size / batch per chip (jax.checkpoint)
    remat: bool = False
    fuse_agg: bool = True          # packed per-layer aggregation (EGCLVel)
    agg_dtype: Optional[str] = None  # 'bf16' packed-aggregation stream (EGCLVel)
    # real-edge lowering (EGCLVel): 'plain', 'fused' (single Pallas pass
    # per layer over the blocked in-window edges, ops/edge_pipeline), or
    # 'fused_stack' (ONE Pallas megakernel running all n_layers with the
    # blocked edge stream VMEM-resident, ops/layer_pipeline — same
    # constraints as 'fused' plus the whole graph must fit the VMEM budget;
    # raises layer_pipeline.StackVmemBudgetError otherwise). 'fused' and
    # 'fused_stack' require a blocked batch (edge_block >= 512, multiple of
    # 512, N >= 3 blocks) built with split_remote=True, and
    # edge_attr_nf == 2. 'fused' <-> 'fused_stack' share the param tree
    # bitwise (checkpoints interchangeable); 'plain' does not. Under a
    # graph/tensor mesh 'fused_stack' falls back to the per-layer fused
    # path (identical math and tree): the layer-boundary collectives cannot
    # cross a Pallas grid — the megakernel is the single-chip lowering that
    # serving replicas and single-host training use.
    edge_impl: str = "plain"
    # optional VMEM budget override (bytes) for the fused_stack residency
    # guard; 0 = layer_pipeline.DEFAULT_STACK_VMEM_BUDGET (16 MiB/core)
    stack_vmem_budget: int = 0

    @nn.compact
    def __call__(self, g: GraphBatch) -> Tuple[jnp.ndarray, jnp.ndarray]:
        assert self.virtual_channels > 0, "virtual_channels must be > 0"
        B = g.batch_size
        H, C = self.hidden_nf, self.virtual_channels

        # learnable virtual feature seed, shared across graphs (:288, torch.randn init)
        Hv0 = self.param("virtual_node_feat", nn.initializers.normal(1.0), (1, H, C))
        Hv = jnp.broadcast_to(Hv0, (B, H, C))
        # virtual coords start at the global location mean, replicated C times (:300)
        X = jnp.repeat(g.loc_mean[:, :, None], C, axis=2)                # [B, 3, C]

        h = TorchDense(H, name="embedding_in")(g.node_feat)  # f32: one small matmul
        x, v = g.loc, g.vel
        gravity = jnp.asarray(self.gravity, jnp.float32) if self.gravity is not None else None

        # blocked layout: slot ids + in-degree reciprocal (+ einsum incidence),
        # shared by all layers
        slot, inv_deg, oh = blocked_slot_inv_deg(g, self.blocked_impl)

        # fused edge pipeline: the kernel's blocked HBM layout of the edge
        # stream is layer-invariant too — build it once per forward
        fused_arrs = None
        if self.edge_impl in ("fused", "fused_stack"):
            if g.edge_block <= 0:
                raise ValueError(
                    f"edge_impl='{self.edge_impl}' requires a blocked batch "
                    "(data.edge_block >= 512, a multiple of 512)")
            fused_arrs = jax.vmap(
                lambda r, c, ea, em: build_edge_blocks(
                    r, c, ea, em, block=g.edge_block, n_nodes=g.max_nodes)
            )(g.row, g.col, g.edge_attr, g.edge_mask)

        if self.edge_impl == "fused_stack":
            # megakernel constraints, hoisted to the model because the
            # megakernel bypasses EGCLVel entirely (mirrors its fused checks)
            if self.attention or self.normalize or self.tanh:
                raise ValueError(
                    "edge_impl='fused_stack' supports the flagship EGCL "
                    "only: attention/normalize/tanh are baked out of the "
                    "megakernel — use edge_impl='plain' with those heads")
            if self.edge_attr_nf != 2:
                raise ValueError(
                    f"edge_impl='fused_stack' requires edge_attr_nf=2 (the "
                    f"kernel scalar lanes are [radial, attr0, attr1]); got "
                    f"{self.edge_attr_nf}")
            if self.n_layers < 1:
                raise ValueError(
                    f"edge_impl='fused_stack' needs n_layers >= 1 (the "
                    f"megakernel grid is (n_layers,)); got {self.n_layers}")
            if g.remote_edge_index is None:
                raise ValueError(
                    "edge_impl='fused_stack' needs a blocked batch built "
                    "with split_remote=True (the megakernel folds the "
                    "compact remote tail in per layer) — check "
                    "data.edge_block and the loader's split_remote flag")

        if (self.edge_impl == "fused_stack" and self.axis_name is None
                and self.tensor_axis is None):
            return self._fused_stack_forward(g, h, x, v, X, Hv, gravity,
                                             fused_arrs)

        layer_cls = nn.remat(EGCLVel) if self.remat else EGCLVel
        # under a graph/tensor mesh fused_stack lowers to the per-layer
        # fused path: collectives cannot cross the megakernel's Pallas grid,
        # and the param tree is identical so the fallback is exact
        layer_impl = ("fused" if self.edge_impl == "fused_stack"
                      else self.edge_impl)
        for i in range(self.n_layers):
            h, x, Hv, X = layer_cls(
                hidden_nf=H,
                virtual_channels=C,
                node_attr_nf=self.node_attr_nf,
                edge_attr_nf=self.edge_attr_nf,
                residual=self.residual,
                attention=self.attention,
                normalize=self.normalize,
                tanh=self.tanh,
                has_gravity=self.gravity is not None,
                axis_name=self.axis_name,
                tensor_axis=self.tensor_axis,
                compute_dtype=self.compute_dtype,
                hoist_edge_mlp=self.hoist_edge_mlp,
                seg_impl=self.segment_impl,
                fuse_agg=self.fuse_agg,
                agg_dtype=self.agg_dtype,
                edge_impl=layer_impl,
                name=f"gcl_{i}",
            )(h, x, v, X, Hv, g, gravity=gravity, slot=slot, inv_deg=inv_deg,
              oh=oh, fused_arrs=fused_arrs)

        return x, X

    def _fused_stack_forward(self, g: GraphBatch, h, x, v, X, Hv, gravity,
                             fused_arrs):
        """Dispatch the whole layer loop as ONE megakernel per graph.

        Params are declared through the _EGCLVelStackParams shadows (same
        ``gcl_{i}/...`` subtree as the per-layer path, bitwise-identical
        init) and stacked along a leading layer axis at runtime; the
        blocked edge stream is read from HBM once for all n_layers."""
        H, C, B = self.hidden_nf, self.virtual_channels, g.batch_size
        dt = resolve_dtype(self.compute_dtype)
        cfg = StackConfig(
            n_layers=self.n_layers, block=g.edge_block, hidden=H, channels=C,
            node_attr_nf=self.node_attr_nf,
            has_gravity=self.gravity is not None, residual=self.residual,
            coords_mean=True,  # FastEGNN always aggregates with 'mean'
            dtype_name="bf16" if dt is jnp.bfloat16 else "f32",
            vmem_budget=self.stack_vmem_budget or DEFAULT_STACK_VMEM_BUDGET)
        wlayers = [
            _EGCLVelStackParams(H, C, self.node_attr_nf, self.edge_attr_nf,
                                self.gravity is not None, name=f"gcl_{i}")()
            for i in range(self.n_layers)]
        wstack = {k: jnp.stack([wl[k] for wl in wlayers])
                  for k in wlayers[0]}
        row_t, col_l, kblk, scal = fused_arrs
        xs, Xs = [], []
        for b in range(B):
            edge_arrs = (row_t[b], col_l[b], kblk[b], scal[b])
            remote_arrs = (g.remote_edge_index[b, 0],
                           g.remote_edge_index[b, 1],
                           g.remote_edge_attr[b], g.remote_edge_mask[b])
            _, x_b, X_b, _ = fused_egnn_stack(
                cfg, h[b], x[b], v[b], X[b], Hv[b], g.node_mask[b],
                g.node_attr[b] if self.node_attr_nf else None, gravity,
                edge_arrs, remote_arrs, wstack)
            xs.append(x_b)
            Xs.append(X_b)
        return jnp.stack(xs), jnp.stack(Xs)
