"""FastEGNN / DistEGNN — the paper's core model, TPU-native.

Re-design of reference models/FastEGNN.py (E_GCL_vel + FastEGNN, 336 LoC):
EGNN with C learnable *virtual nodes* per graph; in distributed (DistEGNN)
mode each device owns one spatial partition of the graph and the virtual-node
state is the only cross-partition channel — exactly three global weighted
means per layer (reference models/FastEGNN.py:258-261, 191-200, 220-234),
realized here as `psum` over the mesh 'graph' axis instead of NCCL allreduces.

Layout: dense batched GraphBatch ([B,N,...] + masks, see ops/graph.py). Every
MLP application is one large matmul over [B*N(*C), F] — MXU-shaped — and the
whole L-layer forward traces into a single XLA program with no host sync.

Shape legend: B graphs, N padded nodes (per partition), E padded edges,
H hidden, C virtual channels.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from distegnn_tpu.models.common import (
    MLP, CoordMLP, HoistedEdgeMLP, TorchDense, resolve_dtype,
)
from distegnn_tpu.ops.blocked import EdgeOps, blocked_slot_inv_deg
from distegnn_tpu.ops.graph import GraphBatch
from distegnn_tpu.parallel.collectives import global_node_mean


class EGCLVel(nn.Module):
    """E(n)-equivariant conv layer with velocity + virtual-node channels.

    Mirrors reference E_GCL_vel (models/FastEGNN.py:46-276): MLPs phi_e,
    phi_ev, phi_x, phi_xv, phi_X, phi_v, phi_h, phi_hv (+ optional attention
    gates and gravity head), with the three distributed global means marked.
    """

    hidden_nf: int
    virtual_channels: int
    node_attr_nf: int = 0
    edge_attr_nf: int = 0
    residual: bool = True
    attention: bool = False
    normalize: bool = False
    coords_agg: str = "mean"
    tanh: bool = False
    has_gravity: bool = False
    axis_name: Optional[str] = None  # mesh axis of graph partitions ('graph') or None
    epsilon: float = 1e-8
    # compute dtype of the invariant-message MLPs ('bf16' or None=f32). All
    # GEOMETRY (coord_diff, radial, coordinate updates, aggregations) stays
    # f32, so equivariance is exact at math level — bf16 only widens noise in
    # invariant channels. See tests/test_equivariance.py::test_bf16.
    compute_dtype: Optional[str] = None
    # evaluate phi_e's first Dense on the node axis (HoistedEdgeMLP): same
    # math, E/N x fewer matmul rows, no [E, 2H+S] concat. False restores the
    # reference-shaped concat MLP (different param tree — not ckpt-compatible)
    hoist_edge_mlp: bool = True
    seg_impl: str = "scatter"  # plain-layout aggregation lowering ('scatter'|'cumsum'|'ell')
    # one packed aggregation pass per layer (translations + edge features +
    # count ride a single segment sum — EdgeOps.agg_rows_pair) instead of
    # two aggregations and a count. Same math; accumulation is ALWAYS f32 in
    # the fused path, so under compute_dtype=bf16 it is slightly MORE
    # precise than the legacy two-call path (whose bf16 edge_feat
    # aggregation accumulated in bf16) — not bit-identical for bf16 models;
    # fuse_agg=False restores the legacy numerics exactly.
    fuse_agg: bool = True
    # stream dtype of the packed aggregation ('bf16' halves the [E,3+H] read
    # bytes; accumulation stays f32). bf16 ROUNDS THE COORDINATE
    # TRANSLATIONS — equivariance becomes approximate at bf16 noise level.
    # Measured opt-in (VERDICT r3 #1), None = f32.
    agg_dtype: Optional[str] = None

    @nn.compact
    def __call__(
        self,
        h: jnp.ndarray,          # [B, N, H] node features
        x: jnp.ndarray,          # [B, N, 3] coordinates
        v: jnp.ndarray,          # [B, N, 3] velocities
        X: jnp.ndarray,          # [B, 3, C] virtual coordinates (global objects)
        Hv: jnp.ndarray,         # [B, H, C] virtual features (global objects)
        g: GraphBatch,
        gravity: Optional[jnp.ndarray] = None,  # [3]
        slot: Optional[jnp.ndarray] = None,     # [B, E] blocked-layout slots
        inv_deg: Optional[jnp.ndarray] = None,  # [B, N, 1] 1/max(in-degree, 1)
        oh: Optional[jnp.ndarray] = None,       # [B, nb, epb, block] einsum incidence
    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        H, C = self.hidden_nf, self.virtual_channels
        dt = resolve_dtype(self.compute_dtype)
        node_mask = g.node_mask                      # [B, N]
        edge_mask = g.edge_mask                      # [B, E]
        nm = node_mask[..., None]
        ops = EdgeOps(g, slot, inv_deg, oh, seg_impl=self.seg_impl)

        # --- real-edge geometry (reference coord2radial, :237-246)
        coord_diff = ops.gather_rows(x) - ops.gather_cols(x)            # [B, E, 3]
        radial = jnp.sum(coord_diff**2, axis=-1, keepdims=True)         # [B, E, 1]
        if self.normalize:
            norm = jax.lax.stop_gradient(jnp.sqrt(radial)) + self.epsilon
            coord_diff = coord_diff / norm

        # --- virtual-edge geometry (:252-253): every node sees all C virtual nodes
        vcd = X[:, None, :, :] - x[..., None]                           # [B, N, 3, C]
        virtual_radial = jnp.linalg.norm(vcd, axis=2, keepdims=True)    # [B, N, 1, C]

        # --- real edge messages phi_e (:144-150)
        if self.hoist_edge_mlp:
            scalars = (jnp.concatenate([radial, g.edge_attr], axis=-1)
                       if self.edge_attr_nf else radial)
            edge_feat = HoistedEdgeMLP(H, 1 + self.edge_attr_nf,
                                       name="phi_e", dtype=dt)(h, scalars, ops)
        else:
            e_in = [ops.gather_rows(h), ops.gather_cols(h), radial]
            if self.edge_attr_nf:
                e_in.append(g.edge_attr)
            edge_feat = MLP([H, H], act_last=True, name="phi_e", dtype=dt)(
                jnp.concatenate(e_in, axis=-1))
        if self.attention:
            gate_e = jax.nn.sigmoid(TorchDense(1, name="att", dtype=dt)(edge_feat))
            edge_feat = edge_feat * gate_e                               # [B, E, H]
        edge_feat = edge_feat * edge_mask[..., None].astype(edge_feat.dtype)

        # ---------- psum #1: exact global coordinate mean (:258-261)
        coord_mean = global_node_mean(x, node_mask, self.axis_name)     # [B, 3]

        # --- invariant virtual mixing m_X: Gram of centered virtual coords (:263-264)
        Xc = X - coord_mean[:, :, None]                                  # [B, 3, C]
        m_X = jnp.einsum("bdc,bde->bce", Xc, Xc)                        # [B, C, C]

        # --- virtual edge messages phi_ev (:153-163): [B, N, C, 2H+1+C] -> [B, N, C, H]
        B, N = h.shape[0], h.shape[1]
        v_in = jnp.concatenate(
            [
                jnp.broadcast_to(h[:, :, None, :], (B, N, C, H)),
                jnp.broadcast_to(jnp.swapaxes(Hv, 1, 2)[:, None, :, :], (B, N, C, H)),
                jnp.swapaxes(virtual_radial, 2, 3),                      # [B, N, C, 1]
                jnp.broadcast_to(m_X[:, None, :, :], (B, N, C, C)),
            ],
            axis=-1,
        )
        vef = MLP([H, H], act_last=True, name="phi_ev", dtype=dt)(v_in)  # [B, N, C, H]
        if self.attention:
            gate = jax.nn.sigmoid(TorchDense(1, name="att_v", dtype=dt)(vef))
            vef = vef * gate
        vef = vef * node_mask[:, :, None, None].astype(vef.dtype)        # zero padded nodes

        # --- real coordinate update (coord_model_vel, :166-188)
        if self.coords_agg not in ("sum", "mean"):
            raise ValueError(f"Wrong coords_agg parameter {self.coords_agg!r}")
        trans = coord_diff * CoordMLP(H, tanh=self.tanh, name="phi_x", dtype=dt)(edge_feat)  # [B, E, 3]
        if self.fuse_agg:
            # both per-layer aggregations (+ the count) in ONE pass (blocked
            # layouts keep two calls inside but honor the agg_dtype knob)
            agg, agg_h_f = ops.agg_rows_pair(
                trans, edge_feat, a_mean=(self.coords_agg == "mean"),
                agg_dtype=self.agg_dtype)
        else:
            agg = (ops.agg_rows_sum(trans) if self.coords_agg == "sum"
                   else ops.agg_rows_mean(trans))                        # [B, N, 3]
            agg_h_f = None
        x = x + agg

        phi_xv = CoordMLP(H, tanh=self.tanh, name="phi_xv", dtype=dt)(vef)  # [B, N, C, 1]
        trans_v = jnp.mean(-vcd * jnp.swapaxes(phi_xv, 2, 3), axis=-1)   # [B, N, 3]
        x = x + trans_v
        x = x + MLP([H, 1], name="phi_v", dtype=dt)(h).astype(jnp.float32) * v
        if self.has_gravity:
            x = x + MLP([H, 1], name="phi_g", dtype=dt)(h).astype(jnp.float32) * gravity
        x = x * nm  # keep padding clean

        # ---------- psum #2: virtual coordinate update (coord_model_virtual, :191-200)
        trans_X = vcd * jnp.swapaxes(CoordMLP(H, tanh=self.tanh, name="phi_X", dtype=dt)(vef), 2, 3)  # [B, N, 3, C]
        X = X + global_node_mean(trans_X, node_mask, self.axis_name)     # [B, 3, C]

        # --- node feature update (node_model, :203-217)
        agg_h = agg_h_f if agg_h_f is not None else ops.agg_rows_mean(edge_feat)
        agg_v = jnp.mean(vef, axis=2)                                    # [B, N, H]
        n_in = [h, agg_h, agg_v]
        if self.node_attr_nf:
            n_in.append(g.node_attr)
        out = MLP([H, H], name="phi_h", dtype=dt)(jnp.concatenate(
            [a.astype(jnp.float32) for a in n_in], axis=-1))
        h = (h + out) if self.residual else out
        h = h * nm

        # ---------- psum #3: virtual feature update (node_model_virtual, :220-234)
        agg_Hv = global_node_mean(vef.astype(jnp.float32), node_mask, self.axis_name)  # [B, C, H]
        hv_in = jnp.concatenate([jnp.swapaxes(Hv, 1, 2), agg_Hv], axis=-1)  # [B, C, 2H]
        out_v = jnp.swapaxes(MLP([H, H], name="phi_hv", dtype=dt)(hv_in), 1, 2)  # [B, H, C]
        Hv = (Hv + out_v) if self.residual else out_v

        return h, x, Hv, X


class FastEGNN(nn.Module):
    """FastEGNN / DistEGNN wrapper (reference models/FastEGNN.py:279-307).

    Forward takes a GraphBatch and returns (node_loc_pred [B,N,3],
    virtual_node_loc [B,3,C]). Set ``axis_name='graph'`` under shard_map for
    the distributed (DistEGNN) mode — same weights, same math, exact global
    means via psum.
    """

    node_feat_nf: int
    node_attr_nf: int = 0
    edge_attr_nf: int = 0
    hidden_nf: int = 64
    virtual_channels: int = 3
    n_layers: int = 4
    residual: bool = True
    attention: bool = False
    normalize: bool = False
    tanh: bool = False
    gravity: Optional[Tuple[float, float, float]] = None
    axis_name: Optional[str] = None
    compute_dtype: Optional[str] = None  # 'bf16' -> MXU-native message MLPs
    hoist_edge_mlp: bool = True  # phi_e first Dense on the node axis (see EGCLVel)
    # lowering of the blocked-layout edge ops (used only when the batch
    # carries edge_block > 0): 'einsum' = one-hot materialized once per
    # forward, ops are batched dots (default — no Pallas grid overhead);
    # 'pallas' = one-hot built in VMEM per kernel
    blocked_impl: str = "einsum"
    # plain-layout aggregation lowering (ops/segment.py): 'scatter' (XLA
    # sorted scatter, bit-exact), 'cumsum' (scatter-free prefix-sum
    # differences — f32-accumulated, sums carry ~|prefix|*eps rounding), or
    # 'ell' (scatter-free fixed-degree gathers — exact)
    segment_impl: str = "scatter"
    # recompute each layer's activations in the backward pass instead of
    # keeping them in HBM: layer activations are O(E*H) (hundreds of MB at
    # LargeFluid scale), so remat trades cheap recompute FLOPs for the
    # memory that bounds graph size / batch per chip (jax.checkpoint)
    remat: bool = False
    fuse_agg: bool = True          # packed per-layer aggregation (EGCLVel)
    agg_dtype: Optional[str] = None  # 'bf16' packed-aggregation stream (EGCLVel)

    @nn.compact
    def __call__(self, g: GraphBatch) -> Tuple[jnp.ndarray, jnp.ndarray]:
        assert self.virtual_channels > 0, "virtual_channels must be > 0"
        B = g.batch_size
        H, C = self.hidden_nf, self.virtual_channels

        # learnable virtual feature seed, shared across graphs (:288, torch.randn init)
        Hv0 = self.param("virtual_node_feat", nn.initializers.normal(1.0), (1, H, C))
        Hv = jnp.broadcast_to(Hv0, (B, H, C))
        # virtual coords start at the global location mean, replicated C times (:300)
        X = jnp.repeat(g.loc_mean[:, :, None], C, axis=2)                # [B, 3, C]

        h = TorchDense(H, name="embedding_in")(g.node_feat)  # f32: one small matmul
        x, v = g.loc, g.vel
        gravity = jnp.asarray(self.gravity, jnp.float32) if self.gravity is not None else None

        # blocked layout: slot ids + in-degree reciprocal (+ einsum incidence),
        # shared by all layers
        slot, inv_deg, oh = blocked_slot_inv_deg(g, self.blocked_impl)

        layer_cls = nn.remat(EGCLVel) if self.remat else EGCLVel
        for i in range(self.n_layers):
            h, x, Hv, X = layer_cls(
                hidden_nf=H,
                virtual_channels=C,
                node_attr_nf=self.node_attr_nf,
                edge_attr_nf=self.edge_attr_nf,
                residual=self.residual,
                attention=self.attention,
                normalize=self.normalize,
                tanh=self.tanh,
                has_gravity=self.gravity is not None,
                axis_name=self.axis_name,
                compute_dtype=self.compute_dtype,
                hoist_edge_mlp=self.hoist_edge_mlp,
                seg_impl=self.segment_impl,
                fuse_agg=self.fuse_agg,
                agg_dtype=self.agg_dtype,
                name=f"gcl_{i}",
            )(h, x, v, X, Hv, g, gravity=gravity, slot=slot, inv_deg=inv_deg,
              oh=oh)

        return x, X
