"""Model factory — parity with reference get_model (main.py:58-92).

Dispatches model_name -> flax module. Per-dataset SchNet interatomic cutoffs
mirror reference main.py:69-76 (nbody 1, protein 10, Water-3D 0.035).
"""

from __future__ import annotations

from typing import Optional

_SCHNET_CUTOFFS = {"nbody_100": 1.0, "protein": 10.0, "Water-3D": 0.035}


def _import_model(module: str, cls: str):
    """Import a model class, turning a missing module into a clear error
    (some families land in later build stages; see SURVEY.md §7.2)."""
    import importlib

    try:
        mod = importlib.import_module(f"distegnn_tpu.models.{module}")
    except ModuleNotFoundError as e:
        raise NotImplementedError(
            f"model class {cls} (distegnn_tpu.models.{module}) is not implemented yet"
        ) from e
    return getattr(mod, cls)


def get_model(model_config, world_size: int = 1, dataset_name: Optional[str] = None,
              axis_name: Optional[str] = None, tensor_axis: Optional[str] = None):
    """model_config: attribute-style config (see distegnn_tpu.config).

    ``axis_name`` is the mesh axis for distributed (DistEGNN-style) runs; pass
    'graph' when calling under shard_map, None single-device — replaces the
    reference's world_size branches inside the model.

    ``tensor_axis`` is the mesh axis for hidden-dim tensor parallelism
    ('tensor' when parallel.mesh.tensor > 1, else None). Only FastEGNN
    supports it; config validation rejects tensor>1 for other families.
    """
    name = model_config.model_name
    if tensor_axis is not None and name != "FastEGNN":
        raise ValueError(
            f"tensor parallelism (parallel.mesh.tensor > 1) is only "
            f"implemented for FastEGNN, not {name!r}")
    if name == "FastEGNN":
        from distegnn_tpu.models.fast_egnn import FastEGNN
        return FastEGNN(
            node_feat_nf=model_config.node_feat_nf,
            node_attr_nf=model_config.node_attr_nf,
            edge_attr_nf=model_config.edge_attr_nf,
            hidden_nf=model_config.hidden_nf,
            virtual_channels=model_config.virtual_channels,
            n_layers=model_config.n_layers,
            normalize=model_config.normalize,
            gravity=None,
            axis_name=axis_name,
            tensor_axis=tensor_axis,
            compute_dtype=model_config.get("compute_dtype"),
            remat=bool(model_config.get("remat", False)),
            blocked_impl=model_config.get("blocked_impl", "einsum"),
            hoist_edge_mlp=bool(model_config.get("hoist_edge_mlp", True)),
            segment_impl=model_config.get("segment_impl", "scatter"),
            fuse_agg=bool(model_config.get("fuse_agg", True)),
            agg_dtype=model_config.get("agg_dtype"),
            edge_impl=model_config.get("edge_impl", "plain"),
            stack_vmem_budget=int(
                model_config.get("stack_vmem_budget", 0) or 0),
        )
    if name == "FastRF":
        FastRF = _import_model("fast_rf", "FastRF")
        return FastRF(
            edge_attr_nf=model_config.edge_attr_nf,
            hidden_nf=model_config.hidden_nf,
            n_layers=model_config.n_layers,
            virtual_channels=model_config.virtual_channels,
            axis_name=axis_name,
            blocked_impl=model_config.get("blocked_impl", "einsum"),
            segment_impl=model_config.get("segment_impl", "scatter"),
        )
    if name in ("FastSchNet", "SchNet"):
        cutoff = _SCHNET_CUTOFFS.get(dataset_name)
        if cutoff is None:
            raise ValueError(f"no SchNet cutoff known for dataset {dataset_name!r}")
        if name == "FastSchNet":
            FastSchNet = _import_model("fast_schnet", "FastSchNet")
            return FastSchNet(
                node_feat_nf=model_config.node_feat_nf,
                node_attr_nf=model_config.node_attr_nf,
                edge_attr_nf=model_config.edge_attr_nf,
                hidden_nf=model_config.hidden_nf,
                virtual_channels=model_config.virtual_channels,
                n_layers=model_config.n_layers,
                normalize=model_config.normalize,
                cutoff=cutoff,
                axis_name=axis_name,
                blocked_impl=model_config.get("blocked_impl", "einsum"),
                hoist_edge_mlp=bool(model_config.get("hoist_edge_mlp", True)),
                segment_impl=model_config.get("segment_impl", "scatter"),
                fuse_agg=bool(model_config.get("fuse_agg", True)),
                agg_dtype=model_config.get("agg_dtype"),
            )
        SchNet = _import_model("schnet", "SchNet")
        return SchNet(hidden_channels=model_config.hidden_nf, cutoff=cutoff)
    if name == "EGNN":
        EGNN = _import_model("basic", "EGNN")
        return EGNN(
            n_layers=model_config.n_layers,
            in_node_nf=model_config.node_feat_nf,
            in_edge_nf=model_config.edge_attr_nf,
            hidden_nf=model_config.hidden_nf,
            with_v=True,
        )
    if name == "RF":
        RFVel = _import_model("basic", "RFVel")
        return RFVel(
            hidden_nf=model_config.hidden_nf,
            edge_attr_nf=model_config.edge_attr_nf,
            n_layers=model_config.n_layers,
        )
    if name == "TFN":
        TFNDynamics = _import_model("se3.dynamics", "TFNDynamics")
        return TFNDynamics(nf=model_config.hidden_nf // 2, n_layers=model_config.n_layers,
                           num_degrees=2)
    if name == "SE3Transformer":
        # capability extension: the reference assembles OurSE3Transformer
        # (models.py:207) but never serves it from its factory
        SE3TransformerDynamics = _import_model("se3.dynamics", "SE3TransformerDynamics")
        return SE3TransformerDynamics(nf=model_config.hidden_nf // 2,
                                      n_layers=model_config.n_layers, num_degrees=2)
    if name == "FastTFN":
        FastTFN = _import_model("fast_tfn", "FastTFN")
        return FastTFN(
            node_feat_nf=model_config.node_feat_nf,
            node_attr_nf=model_config.node_attr_nf,
            edge_attr_nf=model_config.edge_attr_nf,
            hidden_nf=model_config.hidden_nf,
            virtual_channels=model_config.virtual_channels,
            n_layers=model_config.n_layers,
            normalize=model_config.normalize,
        )
    if name == "Linear":
        LinearDynamics = _import_model("basic", "LinearDynamics")
        return LinearDynamics()
    raise NotImplementedError(f"Model {name} not implemented")
