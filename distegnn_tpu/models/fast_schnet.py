"""FastSchNet — the FastEGNN virtual-node skeleton whose real-node coordinate
update is a 1-interaction SchNet, TPU-native.

Re-design of reference models/FastSchNet.py (SchNet_GCL_vel + FastSchNet,
256 LoC): per layer, (a) real coordinates move by the SchNet equivariant
update (embedding bypassed: the layer feeds its own hidden features,
FastSchNet.py:121-126 with embedding=False), (b) the virtual-node machinery is
exactly FastEGNN's (phi_ev / phi_xv / phi_X / phi_h / phi_hv) minus the real
phi_x/phi_v paths (SchNet provides those), (c) all global means are LOCAL —
the reference model carries no distributed code (SURVEY.md §2.4). The
``axis_name`` hook still generalizes it to the mesh (a capability the
reference lacks); default None preserves reference behavior.

The reference's 1-interaction SchNet sublayer also allocates a CFConv feature
path whose output is discarded (SchNet.forward updates h after pos and
FastSchNet keeps only pos, FastSchNet.py:121-126) — dead weights (the reason
its DDP runs need find_unused_parameters=True); not replicated here. Its
unused ``W`` parameter (FastSchNet.py:219) is likewise dropped.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from distegnn_tpu.models.common import (
    MLP, CoordMLP, HoistedEdgeMLP, HoistedGate, TorchDense,
)
from distegnn_tpu.ops.blocked import EdgeOps, blocked_slot_inv_deg
from distegnn_tpu.models.schnet import GaussianSmearing
from distegnn_tpu.ops.graph import GraphBatch
from distegnn_tpu.parallel.collectives import global_node_mean


class SchNetGCLVel(nn.Module):
    """One FastSchNet layer (reference SchNet_GCL_vel, FastSchNet.py:8-204)."""

    hidden_nf: int
    virtual_channels: int
    node_attr_nf: int = 0
    edge_attr_nf: int = 0
    cutoff: float = 10.0
    num_gaussians: int = 50
    residual: bool = True
    attention: bool = False
    normalize: bool = False
    tanh: bool = False
    has_gravity: bool = False
    axis_name: Optional[str] = None
    epsilon: float = 1e-8
    hoist_edge_mlp: bool = True  # phi_e + gate first Dense on the node axis
    seg_impl: str = "scatter"
    # one packed aggregation pass for the layer's two row aggregations
    # (coordinate update + edge features; EdgeOps.agg_rows_pair — the same
    # fusion FastEGNN applies)
    fuse_agg: bool = True
    agg_dtype: Optional[str] = None

    @nn.compact
    def __call__(self, h, x, v, X, Hv, g: GraphBatch, gravity=None,
                 slot=None, inv_deg=None, oh=None):
        H, C = self.hidden_nf, self.virtual_channels
        node_mask, edge_mask = g.node_mask, g.edge_mask
        nm = node_mask[..., None]
        B, N = h.shape[0], h.shape[1]
        ops = EdgeOps(g, slot, inv_deg, oh, seg_impl=self.seg_impl)

        # normalize is accepted for config parity but is a no-op here AS IN THE
        # REFERENCE: its coord2radial normalizes coord_diff, which FastSchNet
        # then never consumes (only radial and the SchNet sublayer's raw
        # positions are used, FastSchNet.py:169-186)
        raw_diff = ops.gather_rows(x) - ops.gather_cols(x)
        radial = jnp.sum(raw_diff**2, axis=-1, keepdims=True)
        vcd = X[:, None, :, :] - x[..., None]                            # [B, N, 3, C]
        virtual_radial = jnp.linalg.norm(vcd, axis=2, keepdims=True)

        # real edge messages phi_e (FastSchNet.py:102-108); hoisted mode never
        # gathers raw h at all — phi_e AND the SchNet gate below move node-side
        # matmul products instead
        e_scalars = (jnp.concatenate([radial, g.edge_attr], axis=-1)
                     if self.edge_attr_nf else radial)
        if self.hoist_edge_mlp:
            edge_feat = HoistedEdgeMLP(H, 1 + self.edge_attr_nf,
                                       name="phi_e")(h, e_scalars, ops)
        else:
            h_row, h_col = ops.gather_rows(h), ops.gather_cols(h)
            edge_feat = MLP([H, H], act_last=True, name="phi_e")(
                jnp.concatenate([h_row, h_col, e_scalars], axis=-1))
        if self.attention:
            edge_feat = edge_feat * jax.nn.sigmoid(TorchDense(1, name="att")(edge_feat))
        edge_feat = edge_feat * edge_mask[..., None]

        # LOCAL coordinate mean + virtual Gram (FastSchNet.py:190-193)
        coord_mean = global_node_mean(x, node_mask, axis_name=None)
        Xc = X - coord_mean[:, :, None]
        m_X = jnp.einsum("bdc,bde->bce", Xc, Xc)

        v_in = jnp.concatenate(
            [
                jnp.broadcast_to(h[:, :, None, :], (B, N, C, H)),
                jnp.broadcast_to(jnp.swapaxes(Hv, 1, 2)[:, None, :, :], (B, N, C, H)),
                jnp.swapaxes(virtual_radial, 2, 3),
                jnp.broadcast_to(m_X[:, None, :, :], (B, N, C, C)),
            ],
            axis=-1,
        )
        vef = MLP([H, H], act_last=True, name="phi_ev")(v_in)
        if self.attention:
            vef = vef * jax.nn.sigmoid(TorchDense(1, name="att_v")(vef))
        vef = vef * node_mask[:, :, None, None]

        # real coordinate update = 1-interaction SchNet (coord_model_by_schnet,
        # FastSchNet.py:121-126 -> SchNet.py:191-198): RAW interatomic
        # distances and directions regardless of normalize — the reference's
        # SchNet sublayer always works on bare positions
        edge_weight = jnp.linalg.norm(raw_diff + 1e-30, axis=-1)
        gauss = GaussianSmearing(0.0, self.cutoff, self.num_gaussians, name="smearing")(edge_weight)
        if self.hoist_edge_mlp:
            gate = HoistedGate(1, self.num_gaussians, H,
                               name="schnet_coord_update")(h, gauss, ops)
        else:
            gate = TorchDense(1, name="schnet_coord_update")(
                jnp.concatenate([gauss, h_row, h_col], axis=-1))
        if self.fuse_agg:
            agg_x, agg_h_f = ops.agg_rows_pair(
                raw_diff * gate, edge_feat, a_mean=True,
                agg_dtype=self.agg_dtype)
        else:
            agg_x, agg_h_f = ops.agg_rows_mean(raw_diff * gate), None
        x = x + agg_x

        # virtual pull on real nodes (phi_xv / coord_mlp_r_virtual)
        phi_xv = CoordMLP(H, tanh=self.tanh, name="phi_xv")(vef)
        x = x + jnp.mean(-vcd * jnp.swapaxes(phi_xv, 2, 3), axis=-1)
        if self.has_gravity:
            x = x + MLP([H, 1], name="phi_g")(h) * gravity
        x = x * nm

        # virtual coordinate update (phi_X / coord_mlp_v_virtual)
        trans_X = vcd * jnp.swapaxes(CoordMLP(H, tanh=self.tanh, name="phi_X")(vef), 2, 3)
        X = X + global_node_mean(trans_X, node_mask, self.axis_name)

        # feature updates phi_h / phi_hv (FastSchNet.py:140-166)
        agg_h = agg_h_f if agg_h_f is not None else ops.agg_rows_mean(edge_feat)
        agg_v = jnp.mean(vef, axis=2)
        n_in = [h, agg_h, agg_v]
        if self.node_attr_nf:
            n_in.append(g.node_attr)
        out = MLP([H, H], name="phi_h")(jnp.concatenate(n_in, axis=-1))
        h = ((h + out) if self.residual else out) * nm

        agg_Hv = global_node_mean(vef, node_mask, self.axis_name)        # [B, C, H]
        hv_in = jnp.concatenate([jnp.swapaxes(Hv, 1, 2), agg_Hv], axis=-1)
        out_v = jnp.swapaxes(MLP([H, H], name="phi_hv")(hv_in), 1, 2)
        Hv = (Hv + out_v) if self.residual else out_v

        return h, x, Hv, X


class FastSchNet(nn.Module):
    """FastSchNet wrapper (reference FastSchNet.py:207-238)."""

    node_feat_nf: int
    node_attr_nf: int = 0
    edge_attr_nf: int = 0
    hidden_nf: int = 64
    virtual_channels: int = 3
    n_layers: int = 4
    cutoff: float = 10.0
    residual: bool = True
    attention: bool = False
    normalize: bool = False
    tanh: bool = False
    gravity: Optional[Tuple[float, float, float]] = None
    axis_name: Optional[str] = None
    blocked_impl: str = "einsum"  # blocked-layout edge-op lowering ('pallas'|'einsum')
    hoist_edge_mlp: bool = True   # phi_e + gate first Dense on the node axis
    segment_impl: str = "scatter"  # plain-layout lowering ('scatter'|'cumsum'|'ell')
    fuse_agg: bool = True          # packed per-layer aggregation (SchNetGCLVel)
    agg_dtype: Optional[str] = None

    @nn.compact
    def __call__(self, g: GraphBatch) -> Tuple[jnp.ndarray, jnp.ndarray]:
        assert self.virtual_channels > 0, "virtual_channels must be > 0"
        B = g.batch_size
        H, C = self.hidden_nf, self.virtual_channels

        Hv0 = self.param("virtual_node_feat", nn.initializers.normal(1.0), (1, H, C))
        Hv = jnp.broadcast_to(Hv0, (B, H, C))
        X = jnp.repeat(g.loc_mean[:, :, None], C, axis=2)

        h = TorchDense(H, name="embedding_in")(g.node_feat)
        x, v = g.loc, g.vel
        gravity = jnp.asarray(self.gravity, jnp.float32) if self.gravity is not None else None

        slot, inv_deg, oh = blocked_slot_inv_deg(g, self.blocked_impl)

        for i in range(self.n_layers):
            h, x, Hv, X = SchNetGCLVel(
                hidden_nf=H, virtual_channels=C,
                node_attr_nf=self.node_attr_nf, edge_attr_nf=self.edge_attr_nf,
                cutoff=self.cutoff, residual=self.residual,
                attention=self.attention, normalize=self.normalize,
                tanh=self.tanh, has_gravity=self.gravity is not None,
                axis_name=self.axis_name, hoist_edge_mlp=self.hoist_edge_mlp,
                seg_impl=self.segment_impl,
                fuse_agg=self.fuse_agg,
                agg_dtype=self.agg_dtype,
                name=f"gcl_{i}",
            )(h, x, v, X, Hv, g, gravity=gravity, slot=slot, inv_deg=inv_deg,
              oh=oh)
        return x, X
