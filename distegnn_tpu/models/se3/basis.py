"""Runtime equivariant basis — jnp, traced inside the jitted step.

Reference get_basis/get_basis_and_r (modules.py:19-77) computes, per forward
pass under no_grad, the kernel bases K_J(d) = Y_J(d) @ Q_J^T for every
(d_in, d_out) degree pair. Here the spherical harmonics are the closed-form
jnp evaluation of the SAME formulas as the host solver (so3.real_sph_harm with
xp=jnp), the Q_J are float32 constants baked into the traced program, and the
whole computation is stop_gradient'ed (parity with the reference's no_grad)."""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from distegnn_tpu.models.se3.so3 import q_matrices, real_sph_harm


def cart_to_deg1(v: jnp.ndarray) -> jnp.ndarray:
    """Cartesian vector -> degree-1 irrep component order. Our l=1 real
    harmonics are sqrt(3/4pi) * (y, z, x)/r (m = -1, 0, 1), so a cartesian
    vector enters the representation basis by the (y, z, x) permutation."""
    return v[..., jnp.array([1, 2, 0])]


def deg1_to_cart(f: jnp.ndarray) -> jnp.ndarray:
    """Inverse of cart_to_deg1."""
    return f[..., jnp.array([2, 0, 1])]


def compute_basis_and_r(rel_pos: jnp.ndarray, max_degree: int
                        ) -> Tuple[Dict[Tuple[int, int], jnp.ndarray], jnp.ndarray]:
    """rel_pos [B, E, 3] (x_dst - x_src, padded edges may be zero) ->
      basis dict[(d_in, d_out)] -> [B, E, 2 d_out+1, 2 d_in+1, num_freq]
      r [B, E, 1] distances.

    Mirrors reference get_basis_and_r; padded zero edges produce the guarded
    north-pole harmonic value, masked out downstream."""
    Y = {l: real_sph_harm(l, rel_pos, xp=jnp) for l in range(2 * max_degree + 1)}
    Q = q_matrices(max_degree)
    basis = {}
    for (d_in, d_out), Q_Js in Q.items():
        K_Js = []
        for J, Q_J in zip(range(abs(d_in - d_out), d_in + d_out + 1), Q_Js):
            K_J = jnp.einsum("bej,mj->bem", Y[J], jnp.asarray(Q_J))  # [B,E,(2do+1)(2di+1)]
            K_Js.append(K_J.reshape(K_J.shape[:2] + (2 * d_out + 1, 2 * d_in + 1)))
        basis[(d_in, d_out)] = jax.lax.stop_gradient(jnp.stack(K_Js, axis=-1))
    r = jnp.sqrt(jnp.sum(rel_pos**2, axis=-1, keepdims=True))
    return basis, r
