"""Fiber algebra — degree/multiplicity bookkeeping for SE(3) features
(reference equivariant_attention/fibers.py:13-66).

A feature dict maps degree d -> array [B, N, m_d, 2d+1]."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class Fiber:
    """structure: list of (multiplicity, degree), sorted by degree."""

    def __init__(self, num_degrees: Optional[int] = None,
                 num_channels: Optional[int] = None,
                 structure: Optional[List[Tuple[int, int]]] = None,
                 dictionary: Optional[Dict[int, int]] = None):
        if structure is not None:
            self.structure = sorted(structure, key=lambda t: t[1])
        elif dictionary is not None:
            self.structure = [(dictionary[d], d) for d in sorted(dictionary)]
        else:
            self.structure = [(num_channels, d) for d in range(num_degrees)]
        self.multiplicities, self.degrees = zip(*self.structure)
        self.max_degree = max(self.degrees)
        self.structure_dict = {d: m for m, d in self.structure}
        self.n_features = sum(m * (2 * d + 1) for m, d in self.structure)

    @staticmethod
    def combine_max(f1: "Fiber", f2: "Fiber") -> "Fiber":
        d = dict(f1.structure_dict)
        for k, m in f2.structure_dict.items():
            d[k] = max(m, d.get(k, 0))
        return Fiber(dictionary=d)

    def __repr__(self):
        return f"Fiber({self.structure})"
