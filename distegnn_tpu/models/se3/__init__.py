"""SE(3)-equivariant stack (TFN / SE(3)-Transformer), TPU-native.

Replaces the reference's vendored Fuchs et al. code
(models/se3_dynamics/**, ~1.8K LoC on DGL + lie_learn): spherical-harmonic /
Wigner math lives in so3.py (host numpy, float64), the runtime basis is
closed-form jnp (basis.py), and the conv/attention layers are einsums over
padded edge arrays (tfn.py) — no graph library, MXU-shaped contractions.
"""

from distegnn_tpu.models.se3.fibers import Fiber
from distegnn_tpu.models.se3.tfn import GConvSE3, GNormSE3, G1x1SE3, TFN
from distegnn_tpu.models.se3.attention import (
    GConvSE3Partial,
    GMABSE3,
    GSE3Res,
    SE3Transformer,
)
from distegnn_tpu.models.se3.dynamics import SE3TransformerDynamics, TFNDynamics

__all__ = ["Fiber", "GConvSE3", "GNormSE3", "G1x1SE3", "TFN", "TFNDynamics",
           "GConvSE3Partial", "GMABSE3", "GSE3Res", "SE3Transformer",
           "SE3TransformerDynamics"]
