"""SO(3) representation math — host-side, numpy float64.

Replaces the reference's lie_learn dependency and vendored SO3.py /
utils_steerable.py (reference models/se3_dynamics/equivariant_attention/
from_se3cnn/): real spherical harmonics, real Wigner-D matrices, and the
Q_J change-of-basis matrices solved from the equivariance constraint
(reference _basis_transformation_Q_J, utils_steerable.py:35-68).

Design delta (TPU-first, simpler and self-consistent): instead of porting
lie_learn's complex Wigner-D + change-of-basis pipeline, the real Wigner-D
for degree l is DEFINED by the identity Y_l(R v) = D_l(R) Y_l(v) and
recovered from our own spherical-harmonic implementation by least squares
over generic sample directions (float64, residual ~1e-12). Any consistent
real irrep basis yields a valid equivariant kernel basis; consistency with
the runtime Y (basis.py evaluates the SAME formulas in jnp) is what matters.

Q_J matrices are a few tiny SVDs (milliseconds) — cached in-process via
lru_cache; the reference's gzip-pickle disk cache + fcntl lock
(cache_file.py) existed because lie_learn's J-matrix solve was slow, and is
unnecessary here.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Tuple

import numpy as np


# --------------------------------------------------------------------------
# Real (tesseral) spherical harmonics — generic l, module-agnostic (np/jnp)
# --------------------------------------------------------------------------

def _double_factorial(n: int) -> float:
    out = 1.0
    while n > 1:
        out *= n
        n -= 2
    return out


def real_sph_harm(l: int, xyz, xp=np, eps: float = 1e-12):
    """Real spherical harmonics Y_l of unit(xyz), shape [..., 2l+1], m=-l..l.

    Tesseral convention without Condon-Shortley phase:
      m>0: sqrt(2) K_lm cos(m phi) P_l^m(cos theta)
      m=0: K_l0 P_l(cos theta)
      m<0: sqrt(2) K_l|m| sin(|m| phi) P_l^|m|(cos theta)
    Evaluated entirely from cartesian components (no trig of angles), so it
    traces cleanly in jnp with xp=jax.numpy. Zero vectors map to the
    north-pole value (guarded), which padded edges then mask away.
    """
    x, y, z = xyz[..., 0], xyz[..., 1], xyz[..., 2]
    r = xp.sqrt(x * x + y * y + z * z)
    r = xp.maximum(r, eps)
    ct = z / r                       # cos(theta)
    rxy = xp.sqrt(x * x + y * y)
    safe_rxy = xp.maximum(rxy, eps)
    cphi = xp.where(rxy > eps, x / safe_rxy, xp.ones_like(x))
    sphi = xp.where(rxy > eps, y / safe_rxy, xp.zeros_like(y))
    st = rxy / r                     # sin(theta) >= 0

    # associated Legendre P_l^m(ct) with sin(theta) factors, no CS phase
    # P[m] holds P_l^m for the target l, built by the standard recursions
    P = {}
    for m in range(l + 1):
        pmm = _double_factorial(2 * m - 1) * st**m if m > 0 else xp.ones_like(ct)
        if l == m:
            P[m] = pmm
            continue
        pmm1 = (2 * m + 1) * ct * pmm
        if l == m + 1:
            P[m] = pmm1
            continue
        p_prev, p_curr = pmm, pmm1
        for ll in range(m + 2, l + 1):
            p_next = ((2 * ll - 1) * ct * p_curr - (ll + m - 1) * p_prev) / (ll - m)
            p_prev, p_curr = p_curr, p_next
        P[m] = p_curr

    # cos(m phi), sin(m phi) by Chebyshev recurrence
    cos_m = [xp.ones_like(cphi), cphi]
    sin_m = [xp.zeros_like(sphi), sphi]
    for m in range(2, l + 1):
        cos_m.append(2 * cphi * cos_m[-1] - cos_m[-2])
        sin_m.append(2 * cphi * sin_m[-1] - sin_m[-2])

    import math

    comps = []
    for m in range(-l, l + 1):
        am = abs(m)
        K = math.sqrt((2 * l + 1) / (4 * math.pi)
                      * math.factorial(l - am) / math.factorial(l + am))
        if m > 0:
            comps.append(math.sqrt(2.0) * K * cos_m[am] * P[am])
        elif m == 0:
            comps.append(K * P[0])
        else:
            comps.append(math.sqrt(2.0) * K * sin_m[am] * P[am])
    return xp.stack(comps, axis=-1)


# --------------------------------------------------------------------------
# Real Wigner-D from the transform identity (host only)
# --------------------------------------------------------------------------

def wigner_d_real(l: int, R: np.ndarray) -> np.ndarray:
    """D_l(R) [2l+1, 2l+1] with Y_l(R v) = D_l(R) Y_l(v), solved from our Y
    by least squares over generic directions (float64, exact to ~1e-12)."""
    if l == 0:
        return np.ones((1, 1))
    rng = np.random.default_rng(12345 + l)
    v = rng.normal(size=(4 * (2 * l + 1), 3))
    v /= np.linalg.norm(v, axis=1, keepdims=True)
    A = real_sph_harm(l, v).T                    # [2l+1, n]
    B = real_sph_harm(l, v @ R.T).T              # [2l+1, n]
    D, *_ = np.linalg.lstsq(A.T, B.T, rcond=None)
    return D.T


def _random_rotations(n: int, seed: int = 7) -> list:
    from scipy.spatial.transform import Rotation

    return list(Rotation.random(n, random_state=seed).as_matrix())


@lru_cache(maxsize=None)
def basis_transformation_Q_J(J: int, order_in: int, order_out: int) -> np.ndarray:
    """Q_J [(2 order_out+1)(2 order_in+1), 2J+1]: the unique (up to scale)
    intertwiner with (D_out x D_in)(R) Q_J = Q_J D_J(R) — solved as the common
    null space of Sylvester constraints at generic rotations (reference
    _basis_transformation_Q_J, utils_steerable.py:35-68)."""
    mats = []
    for R in _random_rotations(5):
        D_t = np.kron(wigner_d_real(order_out, R), wigner_d_real(order_in, R))
        D_J = wigner_d_real(J, R)
        mats.append(np.kron(D_t, np.eye(2 * J + 1))
                    - np.kron(np.eye(D_t.shape[0]), D_J.T))
    A = np.concatenate(mats, axis=0)
    _, s, vh = np.linalg.svd(A)
    null = vh[s.size - np.sum(s < 1e-8):] if np.sum(s < 1e-8) else vh[-1:]
    assert null.shape[0] == 1, f"non-unique intertwiner space: {null.shape}"
    Q = null[0].reshape((2 * order_out + 1) * (2 * order_in + 1), 2 * J + 1)

    # verify on fresh rotations
    for R in _random_rotations(3, seed=99):
        D_t = np.kron(wigner_d_real(order_out, R), wigner_d_real(order_in, R))
        assert np.allclose(D_t @ Q, Q @ wigner_d_real(J, R), atol=1e-8)
    return Q


def q_matrices(max_degree: int):
    """All Q_J needed up to max_degree: dict[(d_in, d_out)] -> float32 array
    [num_freq(=2 min+1), 2J+1 varies] stacked per-J list."""
    out = {}
    for d_in in range(max_degree + 1):
        for d_out in range(max_degree + 1):
            out[(d_in, d_out)] = [
                basis_transformation_Q_J(J, d_in, d_out).astype(np.float32)
                for J in range(abs(d_in - d_out), d_in + d_out + 1)
            ]
    return out
