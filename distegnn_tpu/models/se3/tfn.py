"""TFN layers — SE(3)-equivariant graph conv as batched einsums.

Re-design of reference equivariant_attention/modules.py (GConvSE3 + PairwiseConv
+ RadialFunc + GNormSE3 + G1x1SE3, DGL update_all message passing): features
are dicts degree -> [B, N, m, 2d+1]; messages are one einsum per degree pair
over padded [B, E, ...] arrays followed by a masked segment mean — no graph
library, contraction-shaped for the MXU.

Normalization delta (documented, deliberate): the reference's RadialFunc and
GNormSE3 use BatchNorm1d over the flat edge/node axis (modules.py:211-218,
351-358). Batch statistics over a padded, partition-sharded axis are
ill-defined (pad rows and device boundaries would leak into the stats), so
LayerNorm over the channel axis replaces it — same role (pre-activation
normalization), deterministic, mask- and mesh-safe.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from distegnn_tpu.models.common import gather_nodes
from distegnn_tpu.models.se3.basis import compute_basis_and_r
from distegnn_tpu.models.se3.fibers import Fiber
from distegnn_tpu.ops.graph import GraphBatch
from distegnn_tpu.ops.segment import segment_mean

kaiming = nn.initializers.he_uniform()


class RadialFunc(nn.Module):
    """Radial profile R(r, w) -> [B, E, m_out, m_in, num_freq]
    (reference RadialFunc, modules.py:193-230; BN -> LayerNorm, see module
    docstring)."""

    num_freq: int
    in_dim: int
    out_dim: int
    mid_dim: int = 32

    @nn.compact
    def __call__(self, feat):
        y = nn.Dense(self.mid_dim, kernel_init=kaiming)(feat)
        y = nn.relu(nn.LayerNorm()(y))
        y = nn.Dense(self.mid_dim, kernel_init=kaiming)(y)
        y = nn.relu(nn.LayerNorm()(y))
        y = nn.Dense(self.num_freq * self.in_dim * self.out_dim, kernel_init=kaiming)(y)
        return y.reshape(y.shape[:-1] + (self.out_dim, self.in_dim, self.num_freq))


class GConvSE3(nn.Module):
    """Tensor-field conv f_in -> f_out with mean aggregation and optional
    per-edge self-interaction (reference GConvSE3, modules.py:82-190)."""

    f_in: Fiber
    f_out: Fiber
    self_interaction: bool = False
    edge_dim: int = 0

    @nn.compact
    def __call__(self, h: Dict[int, jnp.ndarray], g: GraphBatch, r, basis):
        row, col = g.row, g.col                    # dst, src
        N = g.loc.shape[1]
        feat = jnp.concatenate([g.edge_attr, r], axis=-1) if self.edge_dim else r

        out = {}
        for m_out, d_out in self.f_out.structure:
            msg = 0.0
            for m_in, d_in in self.f_in.structure:
                R = RadialFunc(2 * min(d_in, d_out) + 1, m_in, m_out,
                               name=f"radial_{d_in}_{d_out}")(feat)
                src = gather_nodes(h[d_in].reshape(h[d_in].shape[0], N, -1), col)
                src = src.reshape(src.shape[:2] + (m_in, 2 * d_in + 1))
                # kernel contraction (reference PairwiseConv.forward + matmul,
                # modules.py:260-265,140-144) fused into one einsum
                msg = msg + jnp.einsum("beoif,bepqf,beiq->beop",
                                       R, basis[(d_in, d_out)], src)
            if self.self_interaction and d_out in self.f_in.structure_dict:
                m_in = self.f_in.structure_dict[d_out]
                W = self.param(f"self_{d_out}", nn.initializers.normal(1.0 / np.sqrt(m_in)),
                               (m_out, m_in))
                dst = gather_nodes(h[d_out].reshape(h[d_out].shape[0], N, -1), row)
                dst = dst.reshape(dst.shape[:2] + (m_in, 2 * d_out + 1))
                msg = msg + jnp.einsum("oi,beip->beop", W, dst)
            # masked mean over incoming edges (reference fn.mean)
            flat = (msg * g.edge_mask[..., None, None]).reshape(msg.shape[:2] + (-1,))
            agg = jax.vmap(lambda m, rr, e: segment_mean(m, rr, N, mask=e))(flat, row, g.edge_mask)
            out[d_out] = agg.reshape(agg.shape[:2] + (m_out, 2 * d_out + 1))
        return out


class GNormSE3(nn.Module):
    """Norm nonlinearity: out = fnc(|v|) * v/|v| per degree (reference
    GNormSE3, modules.py:301-372; BN -> LayerNorm)."""

    fiber: Fiber
    num_layers: int = 0

    @nn.compact
    def __call__(self, h: Dict[int, jnp.ndarray]):
        out = {}
        for m, d in self.fiber.structure:
            v = h[d]
            norm = jnp.linalg.norm(v + 1e-30, axis=-1, keepdims=True)
            norm = jnp.maximum(norm, 1e-12)
            phase = v / norm
            s = norm[..., 0]                                      # [B, N, m]
            if self.num_layers == 0:
                s = nn.relu(nn.LayerNorm(name=f"ln_{d}")(s))
            else:
                for i in range(self.num_layers):
                    s = nn.relu(nn.LayerNorm(name=f"ln_{d}_{i}")(s))
                    s = nn.Dense(m, kernel_init=kaiming, use_bias=(i == self.num_layers - 1),
                                 name=f"lin_{d}_{i}")(s)
            out[d] = s[..., None] * phase
        return out


class G1x1SE3(nn.Module):
    """Per-degree linear mixing (reference G1x1SE3, modules.py:268-298)."""

    f_in: Fiber
    f_out: Fiber

    @nn.compact
    def __call__(self, h: Dict[int, jnp.ndarray]):
        out = {}
        for m_out, d in self.f_out.structure:
            if d in self.f_in.structure_dict:
                m_in = self.f_in.structure_dict[d]
                W = self.param(f"w_{d}", nn.initializers.normal(1.0 / np.sqrt(m_in)),
                               (m_out, m_in))
                out[d] = jnp.einsum("oi,bnip->bnop", W, h[d])
        return out


class TFN(nn.Module):
    """The OursTFN assembly (reference models.py:78-139): (num_layers-1) x
    [GConvSE3(self_int) -> GNormSE3] then a final GConvSE3 to the out fiber.

    in_types/out_types are degree->multiplicity dicts; call with a feature
    dict and a GraphBatch."""

    num_layers: int
    num_channels: int
    num_degrees: int = 4
    num_nlayers: int = 1
    edge_dim: int = 0
    in_types: Optional[dict] = None
    out_types: Optional[dict] = None

    @nn.compact
    def __call__(self, h: Dict[int, jnp.ndarray], g: GraphBatch):
        fin = Fiber(dictionary=self.in_types or {0: 1, 1: 1})
        fmid = Fiber(self.num_degrees, self.num_channels)
        fout = Fiber(dictionary=self.out_types or {1: 1})

        rel = gather_nodes(g.loc, g.row) - gather_nodes(g.loc, g.col)   # x_dst - x_src
        basis, r = compute_basis_and_r(rel, self.num_degrees - 1)

        f = fin
        for i in range(self.num_layers - 1):
            h = GConvSE3(f, fmid, self_interaction=True, edge_dim=self.edge_dim,
                         name=f"conv_{i}")(h, g, r, basis)
            h = GNormSE3(fmid, num_layers=self.num_nlayers, name=f"norm_{i}")(h)
            f = fmid
        h = GConvSE3(f, fout, self_interaction=True, edge_dim=self.edge_dim,
                     name=f"conv_{self.num_layers - 1}")(h, g, r, basis)
        return h
