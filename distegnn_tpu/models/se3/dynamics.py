"""TFN dynamics wrapper — the 'TFN' baseline (reference
se3_dynamics/dynamics.py OurDynamics with model='tfn', built by main.py:87-89
as nf=hidden//2, num_degrees=2).

Features: degree-0 = charges [B,N,1,1], degree-1 = velocity [B,N,1,3]
(reference dynamics.py:85-91: ndata f/f1); output = degree-1 channel + input
positions (dynamics.py:103)."""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from flax import linen as nn

from distegnn_tpu.models.se3.basis import cart_to_deg1, deg1_to_cart
from distegnn_tpu.models.se3.tfn import TFN
from distegnn_tpu.ops.graph import GraphBatch


def _in_features(g: GraphBatch):
    charges = g.node_attr if g.node_attr.shape[-1] else g.node_feat[..., -1:]
    return {0: charges[..., None],                         # [B, N, 1, 1]
            1: cart_to_deg1(g.vel)[:, :, None, :]}         # [B, N, 1, 3] irrep basis


class TFNDynamics(nn.Module):
    nf: int = 32
    n_layers: int = 3
    num_degrees: int = 2

    @nn.compact
    def __call__(self, g: GraphBatch) -> Tuple[jnp.ndarray, None]:
        out = TFN(num_layers=self.n_layers, num_channels=self.nf,
                  num_degrees=self.num_degrees, in_types={0: 1, 1: 1},
                  out_types={1: 1}, name="tfn")(_in_features(g), g)
        x = g.loc + deg1_to_cart(out[1][:, :, 0, :]) * g.node_mask[..., None]
        return x, None


class SE3TransformerDynamics(nn.Module):
    """OurDynamics with model='se3_transformer' (reference dynamics.py:16-18):
    attention stack instead of plain TFN convs, same feature plumbing."""

    nf: int = 32
    n_layers: int = 3
    num_degrees: int = 2
    div: float = 1
    n_heads: int = 1

    @nn.compact
    def __call__(self, g: GraphBatch) -> Tuple[jnp.ndarray, None]:
        from distegnn_tpu.models.se3.attention import SE3Transformer

        out = SE3Transformer(num_layers=self.n_layers, num_channels=self.nf,
                             num_degrees=self.num_degrees, div=self.div,
                             n_heads=self.n_heads, in_types={0: 1, 1: 1},
                             out_types={1: 1}, name="se3t")(_in_features(g), g)
        x = g.loc + deg1_to_cart(out[1][:, :, 0, :]) * g.node_mask[..., None]
        return x, None
