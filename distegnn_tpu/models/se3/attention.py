"""SE(3)-equivariant attention (SE(3)-Transformer) — TPU-native.

Re-design of reference equivariant_attention/modules.py attention half:
GConvSE3Partial (per-edge kernel values, :386-470), GMABSE3 (multi-head
attention with edge_softmax, :473-552), GSE3Res (attention block, :555-608),
GSum/GCat (:614-685), GAvgPooling/GMaxPooling (:688-716), and the
OurSE3Transformer assembly with its scalar_trick output scaling
(models.py:207-295). DGL's edge_softmax becomes a masked segment softmax
(ops/segment.segment_softmax)."""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from distegnn_tpu.models.common import gather_nodes
from distegnn_tpu.models.se3.basis import compute_basis_and_r
from distegnn_tpu.models.se3.fibers import Fiber
from distegnn_tpu.models.se3.tfn import G1x1SE3, GConvSE3, GNormSE3, RadialFunc
from distegnn_tpu.ops.graph import GraphBatch
from distegnn_tpu.ops.segment import segment_softmax, segment_sum


class GConvSE3Partial(nn.Module):
    """Node -> edge partial conv: per-edge kernel application WITHOUT the
    aggregation (value/key embeddings for attention)."""

    f_in: Fiber
    f_out: Fiber
    edge_dim: int = 0

    @nn.compact
    def __call__(self, h: Dict[int, jnp.ndarray], g: GraphBatch, r, basis):
        N = g.loc.shape[1]
        col = g.col
        feat = jnp.concatenate([g.edge_attr, r], axis=-1) if self.edge_dim else r
        out = {}
        for m_out, d_out in self.f_out.structure:
            msg = 0.0
            for m_in, d_in in self.f_in.structure:
                R = RadialFunc(2 * min(d_in, d_out) + 1, m_in, m_out,
                               name=f"radial_{d_in}_{d_out}")(feat)
                src = gather_nodes(h[d_in].reshape(h[d_in].shape[0], N, -1), col)
                src = src.reshape(src.shape[:2] + (m_in, 2 * d_in + 1))
                msg = msg + jnp.einsum("beoif,bepqf,beiq->beop",
                                       R, basis[(d_in, d_out)], src)
            out[d_out] = msg                                # [B, E, m_out, 2d_out+1]
        return out


def fiber2head(F: Dict[int, jnp.ndarray], n_heads: int, structure: Fiber) -> jnp.ndarray:
    """Stack a fiber dict into per-head flat vectors [..., heads, feat]
    (reference fibers.py:145-152)."""
    parts = [F[d].reshape(F[d].shape[:-2] + (n_heads, -1)) for d in structure.degrees]
    return jnp.concatenate(parts, axis=-1)


class GMABSE3(nn.Module):
    """Multi-head attention: score = <k_edge, q_dst>/sqrt(F); masked softmax
    over each node's incoming edges; attention-weighted value sum."""

    f_value: Fiber
    f_key: Fiber
    n_heads: int = 1

    @nn.compact
    def __call__(self, v: Dict, k: Dict, q: Dict, g: GraphBatch):
        N = g.loc.shape[1]
        row = g.row
        k_h = fiber2head(k, self.n_heads, self.f_key)                   # [B, E, H, F]
        q_h = fiber2head(q, self.n_heads, self.f_key)                   # [B, N, H, F]
        q_edge = gather_nodes(q_h.reshape(q_h.shape[0], N, -1), row)
        q_edge = q_edge.reshape(k_h.shape)
        scores = jnp.sum(k_h * q_edge, axis=-1) / np.sqrt(self.f_key.n_features)  # [B, E, H]
        attn = jax.vmap(lambda s, rr, m: segment_softmax(s, rr, N, mask=m))(
            scores, row, g.edge_mask)                                   # [B, E, H]

        out = {}
        for m, d in self.f_value.structure:
            val = v[d].reshape(v[d].shape[:2] + (self.n_heads, m // self.n_heads, 2 * d + 1))
            weighted = attn[..., None, None] * val
            flat = weighted.reshape(weighted.shape[:2] + (-1,))
            agg = jax.vmap(lambda t, rr, e: segment_sum(t, rr, N, mask=e))(flat, row, g.edge_mask)
            out[d] = agg.reshape(agg.shape[:2] + (m, 2 * d + 1))
        return out


class GSE3Res(nn.Module):
    """Attention block: value/key partial convs + query projection + GMABSE3
    (reference GSE3Res; its skip connection is commented out upstream and
    likewise omitted here)."""

    f_in: Fiber
    f_out: Fiber
    edge_dim: int = 0
    div: float = 1
    n_heads: int = 1

    @nn.compact
    def __call__(self, h: Dict, g: GraphBatch, r, basis):
        f_mid_out = Fiber(dictionary={d: int(m // self.div)
                                      for d, m in self.f_out.structure_dict.items()})
        f_mid_in = Fiber(dictionary={d: m for d, m in f_mid_out.structure_dict.items()
                                     if d in self.f_in.structure_dict})
        v = GConvSE3Partial(self.f_in, f_mid_out, edge_dim=self.edge_dim, name="v")(h, g, r, basis)
        k = GConvSE3Partial(self.f_in, f_mid_in, edge_dim=self.edge_dim, name="k")(h, g, r, basis)
        q = G1x1SE3(self.f_in, f_mid_in, name="q")(h)
        return GMABSE3(f_mid_out, f_mid_in, n_heads=self.n_heads, name="attn")(v, k, q, g)


def gsum(x: Dict, y: Dict) -> Dict:
    """Residual sum with zero-padding of mismatched multiplicities
    (reference GSum, modules.py:645-680)."""
    out = {}
    for d in set(x) | set(y):
        if d in x and d in y:
            a, b = x[d], y[d]
            if a.shape[-2] != b.shape[-2]:
                m = max(a.shape[-2], b.shape[-2])
                pad = lambda t: jnp.pad(t, [(0, 0)] * (t.ndim - 2)
                                        + [(0, m - t.shape[-2]), (0, 0)])
                a, b = pad(a), pad(b)
            out[d] = a + b
        else:
            out[d] = x.get(d, y.get(d))
    return out


def gcat(x: Dict, y: Dict) -> Dict:
    """Concat multiplicities for degrees present in x (reference GCat)."""
    return {d: (jnp.concatenate([x[d], y[d]], axis=-2) if d in y else x[d]) for d in x}


def g_avg_pool(features: Dict, g: GraphBatch, degree: int = 0) -> jnp.ndarray:
    """Masked mean over nodes (reference GAvgPooling)."""
    h = features[degree]
    m = g.node_mask[..., None, None]
    return jnp.sum(h * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0)


def g_max_pool(features: Dict, g: GraphBatch) -> jnp.ndarray:
    """Masked max over nodes of the last degree-0 channel (reference GMaxPooling)."""
    h = features[0][..., -1]
    mask = g.node_mask[:, :, None].astype(bool)
    return jnp.max(jnp.where(mask, h, -1e30), axis=1)


class SE3Transformer(nn.Module):
    """OurSE3Transformer assembly (reference models.py:207-295): num_layers x
    [GSE3Res -> GNormSE3], final GConvSE3 to the out fiber, every output
    degree scaled by the learnable scalar_trick (init 0.01, models.py:234,293)."""

    num_layers: int
    num_channels: int
    num_degrees: int = 4
    edge_dim: int = 0
    div: float = 1
    n_heads: int = 1
    in_types: Optional[dict] = None
    out_types: Optional[dict] = None

    @nn.compact
    def __call__(self, h: Dict[int, jnp.ndarray], g: GraphBatch):
        fin = Fiber(dictionary=self.in_types or {0: 1, 1: 1})
        fmid = Fiber(self.num_degrees, self.num_channels)
        fout = Fiber(dictionary=self.out_types or {1: 1})

        rel = gather_nodes(g.loc, g.row) - gather_nodes(g.loc, g.col)
        basis, r = compute_basis_and_r(rel, self.num_degrees - 1)

        f = fin
        for i in range(self.num_layers):
            h = GSE3Res(f, fmid, edge_dim=self.edge_dim, div=self.div,
                        n_heads=self.n_heads, name=f"res_{i}")(h, g, r, basis)
            h = GNormSE3(fmid, name=f"norm_{i}")(h)
            f = fmid
        h = GConvSE3(f, fout, self_interaction=True, edge_dim=self.edge_dim,
                     name=f"conv_out")(h, g, r, basis)

        scalar_trick = self.param("scalar_trick", lambda k, s: 0.01 * jnp.ones(s), (1,))
        return {d: v * scalar_trick for d, v in h.items()}
