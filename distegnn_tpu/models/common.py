"""Shared building blocks for all model families.

Initializer parity notes (vs torch defaults used throughout the reference):
  - torch nn.Linear default: kaiming_uniform(a=sqrt(5)) == U(+-1/sqrt(fan_in));
    we match its variance with variance_scaling(1/3, fan_in, uniform).
  - coordinate heads: xavier_uniform with gain=0.001, no bias (reference
    models/FastEGNN.py:96-107) — variance_scaling(1e-6, fan_avg, uniform).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax.numpy as jnp
from flax import linen as nn

from distegnn_tpu.parallel.collectives import (
    tp_copy, tp_gather, tp_reduce, tp_slice, tp_slice_rows,
)

# torch nn.Linear default weight init (same variance): U(+-1/sqrt(fan_in))
torch_linear_init = nn.initializers.variance_scaling(1.0 / 3.0, "fan_in", "uniform")
# xavier_uniform(gain=0.001): bound = gain*sqrt(6/(fan_in+fan_out)) -> scale = gain^2
coord_head_init = nn.initializers.variance_scaling(1e-6, "fan_avg", "uniform")


def _torch_bias_init(fan_in: int):
    """torch nn.Linear default bias init: U(+-1/sqrt(fan_in))."""
    bound = 1.0 / (fan_in ** 0.5)
    def init(key, shape, dtype=jnp.float32):
        import jax
        return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)
    return init


class TorchDense(nn.Module):
    """Dense with full torch nn.Linear default init parity (weight AND bias).

    ``dtype`` is the COMPUTE dtype (params stay float32): set jnp.bfloat16 to
    run the matmul on the MXU's native precision — TPU bf16 matmul throughput
    is ~2x fp32 (pallas_guide: MXU natively consumes bf16)."""

    features: int
    use_bias: bool = True
    kernel_init: Optional[Callable] = None
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x):
        fan_in = x.shape[-1]
        return nn.Dense(
            self.features,
            use_bias=self.use_bias,
            kernel_init=self.kernel_init or torch_linear_init,
            bias_init=_torch_bias_init(fan_in),
            dtype=self.dtype,
        )(x)


class _DenseParams(nn.Module):
    """Shadow of nn.Dense's param subtree: declares the identical
    kernel/bias (same names, shapes, f32 param dtype, init functions) WITHOUT
    applying the matmul, and returns the full arrays. Instantiated with
    ``name='Dense_0'`` inside a ``name='TorchDense_i'`` shadow so the param
    path — and therefore flax's path-folded init RNG stream — is bitwise
    identical to the TorchDense it stands in for. This is how the
    tensor-parallel compute branches consume FULL replicated params (sliced at
    compute time via collectives.tp_slice*) while keeping the param tree
    invariant in the mesh shape, so checkpoints cross mesh layouts freely."""

    features: int
    use_bias: bool = True
    kernel_init: Optional[Callable] = None

    @nn.compact
    def __call__(self, fan_in):
        k = self.param("kernel", self.kernel_init or torch_linear_init,
                       (fan_in, self.features), jnp.float32)
        b = (self.param("bias", _torch_bias_init(fan_in), (self.features,), jnp.float32)
             if self.use_bias else None)
        return k, b


class _TorchDenseParams(nn.Module):
    """Shadow of TorchDense's param subtree (see :class:`_DenseParams`)."""

    features: int
    use_bias: bool = True
    kernel_init: Optional[Callable] = None

    @nn.compact
    def __call__(self, fan_in):
        return _DenseParams(self.features, use_bias=self.use_bias,
                            kernel_init=self.kernel_init, name="Dense_0")(fan_in)


class MLP(nn.Module):
    """Plain MLP: Dense(+act) stack; optionally activation after the last layer.

    ``tensor_axis`` enables Megatron-style tensor parallelism over the hidden
    dim (2-layer MLPs only): the first Dense is column-parallel (each tensor
    rank computes a contiguous 1/T hidden slice — exact, just fewer columns),
    the activation runs on the slice, and the second Dense is row-parallel.
    ``tensor_out='reduce'`` closes with ONE psum back to the full output
    (the per-MLP layer-boundary collective); ``tensor_out='partial'`` returns
    the rank-local partial sum so a linear consumer (phi_x's coordinate
    aggregation) can defer the psum to the node axis. Params stay full and
    replicated — the tree is identical to tensor_axis=None."""

    sizes: Sequence[int]
    act: Callable = nn.silu
    act_last: bool = False
    use_bias_last: bool = True
    kernel_init_last: Optional[Callable] = None
    dtype: Optional[Any] = None
    tensor_axis: Optional[str] = None
    tensor_out: str = "reduce"

    @nn.compact
    def __call__(self, x):
        n = len(self.sizes)
        if self.tensor_axis is not None:
            return self._tp_call(x)
        for i, size in enumerate(self.sizes):
            last = i == n - 1
            x = TorchDense(
                size,
                use_bias=self.use_bias_last if last else True,
                kernel_init=(self.kernel_init_last or torch_linear_init) if last else torch_linear_init,
                dtype=self.dtype,
            )(x)
            if not last or self.act_last:
                x = self.act(x)
        return x

    def _tp_call(self, x):
        ax = self.tensor_axis
        if len(self.sizes) != 2:
            raise ValueError(
                f"tensor-parallel MLP supports exactly 2 dense layers, got "
                f"sizes={list(self.sizes)}")
        if self.tensor_out not in ("reduce", "partial"):
            raise ValueError(f"unknown tensor_out {self.tensor_out!r}")
        if self.tensor_out == "partial" and self.use_bias_last:
            raise ValueError(
                "tensor_out='partial' requires use_bias_last=False (a bias "
                "on a partial sum would be counted T times)")
        fan0 = x.shape[-1]
        k0, b0 = _TorchDenseParams(self.sizes[0], name="TorchDense_0")(fan0)
        k1, b1 = _TorchDenseParams(
            self.sizes[1], use_bias=self.use_bias_last,
            kernel_init=self.kernel_init_last, name="TorchDense_1")(self.sizes[0])
        c = (lambda a: a.astype(self.dtype)) if self.dtype is not None else (lambda a: a)
        # column-parallel first Dense: exact 1/T column slice of the full
        # kernel; activation is elementwise so the slice stays exact
        h = self.act(tp_copy(c(x), ax) @ tp_slice(c(k0), ax) + tp_slice(c(b0), ax))
        # row-parallel second Dense: rank-local partial contraction
        y = h @ tp_slice_rows(c(k1), ax)
        if self.tensor_out == "partial":
            return y
        y = tp_reduce(y, ax)                 # the one psum at the MLP boundary
        if b1 is not None:
            y = y + c(b1)
        if self.act_last:
            y = self.act(y)
        return y


class CoordMLP(nn.Module):
    """Dense(H) -> act -> Dense(1, no bias, xavier gain 1e-3) [-> tanh].

    The scalar head that turns an invariant message into a displacement
    magnitude (reference get_coord_mlp, models/FastEGNN.py:96-107)."""

    hidden_nf: int
    act: Callable = nn.silu
    tanh: bool = False
    dtype: Optional[Any] = None
    # tensor-parallel hidden dim: the head returns a rank-local PARTIAL
    # scalar (row-parallel second Dense, psum deferred); the caller multiplies
    # it into coord_diff, segment-sums to the node axis, and closes with one
    # tp_reduce there — all linear ops, so deferring the psum is exact.
    # Incompatible with tanh (nonlinear in the partial sum).
    tensor_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        if self.tensor_axis is not None and self.tanh:
            raise ValueError(
                "CoordMLP: tanh=True cannot be tensor-parallel (the psum is "
                "deferred through linear ops only) — use tanh=False or T=1")
        x = MLP(
            [self.hidden_nf, 1],
            act=self.act,
            use_bias_last=False,
            kernel_init_last=coord_head_init,
            dtype=self.dtype,
            tensor_axis=self.tensor_axis,
            tensor_out="partial",
        )(x)
        # the scalar head feeds geometry (coord_diff multiplies it): return f32
        x = x.astype(jnp.float32)
        if self.tanh:
            x = jnp.tanh(x)
        return x


def _hoisted_linear(w, b, h, scalars, ops, hidden, scalars_first, dtype):
    """The shared hoisted-linear core: a fused concat-Dense over
    (h_row, h_col, scalars) — in either concat order — evaluated with the
    matmul on the node axis (gathering commutes with linear maps)."""
    if dtype is not None:
        h, scalars, w, b = (a.astype(dtype) for a in (h, scalars, w, b))
    H = hidden
    S = w.shape[0] - 2 * H
    if scalars_first:
        ws, wr, wc = w[:S], w[S:S + H], w[S + H:]
    else:
        wr, wc, ws = w[:H], w[H:2 * H], w[2 * H:]
    return ops.gather_rows(h @ wr) + ops.gather_cols(h @ wc) + scalars @ ws + b


class HoistedEdgeMLP(nn.Module):
    """phi_e with its first Dense algebraically hoisted to the node axis.

    The edge-message MLP's first layer is linear, and gathering commutes with
    a linear map, so

        concat([h_row, h_col, s]) @ W
            == gather_row(h @ W[:H]) + gather_col(h @ W[H:2H]) + s @ W[2H:]

    which (a) never materializes the [E, 2H+S] concat, (b) runs the big
    matmul over N rows instead of E (E/N = mean degree, ~15 at LargeFluid
    scale), and (c) gathers compute-dtype (bf16) products instead of f32
    features — all exactly the same math as MLP([H, H], act_last=True) on
    the concat, in a cheaper order (BASELINE.md round-2 optimization list).
    Parameters: one fused (2H+S, H) kernel + bias with torch nn.Linear
    defaults at the FULL fan-in, so init parity matches the fused Dense.

    ``ops`` is the EdgeOps dispatch — the gathers ride the blocked one-hot
    fast path when the batch carries it.
    """

    hidden_nf: int
    scalar_nf: int           # per-edge scalar features: radial (+ edge_attr)
    act: Callable = nn.silu
    dtype: Optional[Any] = None
    # tensor-parallel hidden dim: only the two hoisted NODE-axis matmuls
    # (h @ wr, h @ wc — the dominant cost) are column-sliced; ONE node-level
    # all-gather per product restores the full hidden dim before the cheap
    # per-edge work, so everything per-edge (and the second Dense) stays
    # replicated. Column slicing + tiled gather is bitwise-exact.
    tensor_axis: Optional[str] = None

    @nn.compact
    def __call__(self, h, scalars, ops):
        H = self.hidden_nf
        fan_in = 2 * H + self.scalar_nf
        w = self.param("kernel", torch_linear_init, (fan_in, H), jnp.float32)
        b = self.param("bias", _torch_bias_init(fan_in), (H,), jnp.float32)
        if self.tensor_axis is not None:
            ax = self.tensor_axis
            dt = self.dtype
            hc_, sc_, wc_, bc_ = ((a.astype(dt) for a in (h, scalars, w, b))
                                  if dt is not None else (h, scalars, w, b))
            hin = tp_copy(hc_, ax)
            hr = tp_gather(hin @ tp_slice(wc_[:H], ax), ax)
            hcv = tp_gather(hin @ tp_slice(wc_[H:2 * H], ax), ax)
            y = self.act(ops.gather_rows(hr) + ops.gather_cols(hcv)
                         + sc_ @ wc_[2 * H:] + bc_)
        else:
            y = self.act(_hoisted_linear(w, b, h, scalars, ops, H,
                                         scalars_first=False, dtype=self.dtype))
        return self.act(TorchDense(H, dtype=self.dtype)(y))


class HoistedGate(nn.Module):
    """Single Dense over concat([scalars, h_row, h_col]) hoisted to the node
    axis (same algebra as :class:`HoistedEdgeMLP`, scalars-first concat order,
    no activation) — FastSchNet's coordinate gate. Init parity: fused kernel
    + bias with torch nn.Linear defaults at the full fan-in."""

    features: int
    scalar_nf: int
    hidden_nf: int
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, h, scalars, ops):
        S, H = self.scalar_nf, self.hidden_nf
        fan_in = S + 2 * H
        w = self.param("kernel", torch_linear_init, (fan_in, self.features), jnp.float32)
        b = self.param("bias", _torch_bias_init(fan_in), (self.features,), jnp.float32)
        return _hoisted_linear(w, b, h, scalars, ops, H,
                               scalars_first=True, dtype=self.dtype)


def resolve_dtype(d):
    """Normalize a compute-dtype spec (None | 'bf16' | 'bfloat16' | dtype) to
    a jnp dtype or None (= float32 compute)."""
    if d is None or d in ("none", "None", "f32", "float32"):
        return None
    if d in ("bf16", "bfloat16") or d is jnp.bfloat16:
        return jnp.bfloat16
    return jnp.dtype(d)


def gather_nodes(data: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Batched node gather: data [B, N, F], idx [B, E] -> [B, E, F].

    One XLA gather per call — the TPU form of the reference's ``coord[row]``
    advanced indexing on flat arrays."""
    return jnp.take_along_axis(data, idx[..., None], axis=1)
