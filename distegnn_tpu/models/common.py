"""Shared building blocks for all model families.

Initializer parity notes (vs torch defaults used throughout the reference):
  - torch nn.Linear default: kaiming_uniform(a=sqrt(5)) == U(+-1/sqrt(fan_in));
    we match its variance with variance_scaling(1/3, fan_in, uniform).
  - coordinate heads: xavier_uniform with gain=0.001, no bias (reference
    models/FastEGNN.py:96-107) — variance_scaling(1e-6, fan_avg, uniform).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax.numpy as jnp
from flax import linen as nn

# torch nn.Linear default weight init (same variance): U(+-1/sqrt(fan_in))
torch_linear_init = nn.initializers.variance_scaling(1.0 / 3.0, "fan_in", "uniform")
# xavier_uniform(gain=0.001): bound = gain*sqrt(6/(fan_in+fan_out)) -> scale = gain^2
coord_head_init = nn.initializers.variance_scaling(1e-6, "fan_avg", "uniform")


def _torch_bias_init(fan_in: int):
    """torch nn.Linear default bias init: U(+-1/sqrt(fan_in))."""
    bound = 1.0 / (fan_in ** 0.5)
    def init(key, shape, dtype=jnp.float32):
        import jax
        return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)
    return init


class TorchDense(nn.Module):
    """Dense with full torch nn.Linear default init parity (weight AND bias).

    ``dtype`` is the COMPUTE dtype (params stay float32): set jnp.bfloat16 to
    run the matmul on the MXU's native precision — TPU bf16 matmul throughput
    is ~2x fp32 (pallas_guide: MXU natively consumes bf16)."""

    features: int
    use_bias: bool = True
    kernel_init: Optional[Callable] = None
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x):
        fan_in = x.shape[-1]
        return nn.Dense(
            self.features,
            use_bias=self.use_bias,
            kernel_init=self.kernel_init or torch_linear_init,
            bias_init=_torch_bias_init(fan_in),
            dtype=self.dtype,
        )(x)


class MLP(nn.Module):
    """Plain MLP: Dense(+act) stack; optionally activation after the last layer."""

    sizes: Sequence[int]
    act: Callable = nn.silu
    act_last: bool = False
    use_bias_last: bool = True
    kernel_init_last: Optional[Callable] = None
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x):
        n = len(self.sizes)
        for i, size in enumerate(self.sizes):
            last = i == n - 1
            x = TorchDense(
                size,
                use_bias=self.use_bias_last if last else True,
                kernel_init=(self.kernel_init_last or torch_linear_init) if last else torch_linear_init,
                dtype=self.dtype,
            )(x)
            if not last or self.act_last:
                x = self.act(x)
        return x


class CoordMLP(nn.Module):
    """Dense(H) -> act -> Dense(1, no bias, xavier gain 1e-3) [-> tanh].

    The scalar head that turns an invariant message into a displacement
    magnitude (reference get_coord_mlp, models/FastEGNN.py:96-107)."""

    hidden_nf: int
    act: Callable = nn.silu
    tanh: bool = False
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x):
        x = MLP(
            [self.hidden_nf, 1],
            act=self.act,
            use_bias_last=False,
            kernel_init_last=coord_head_init,
            dtype=self.dtype,
        )(x)
        # the scalar head feeds geometry (coord_diff multiplies it): return f32
        x = x.astype(jnp.float32)
        if self.tanh:
            x = jnp.tanh(x)
        return x


def _hoisted_linear(w, b, h, scalars, ops, hidden, scalars_first, dtype):
    """The shared hoisted-linear core: a fused concat-Dense over
    (h_row, h_col, scalars) — in either concat order — evaluated with the
    matmul on the node axis (gathering commutes with linear maps)."""
    if dtype is not None:
        h, scalars, w, b = (a.astype(dtype) for a in (h, scalars, w, b))
    H = hidden
    S = w.shape[0] - 2 * H
    if scalars_first:
        ws, wr, wc = w[:S], w[S:S + H], w[S + H:]
    else:
        wr, wc, ws = w[:H], w[H:2 * H], w[2 * H:]
    return ops.gather_rows(h @ wr) + ops.gather_cols(h @ wc) + scalars @ ws + b


class HoistedEdgeMLP(nn.Module):
    """phi_e with its first Dense algebraically hoisted to the node axis.

    The edge-message MLP's first layer is linear, and gathering commutes with
    a linear map, so

        concat([h_row, h_col, s]) @ W
            == gather_row(h @ W[:H]) + gather_col(h @ W[H:2H]) + s @ W[2H:]

    which (a) never materializes the [E, 2H+S] concat, (b) runs the big
    matmul over N rows instead of E (E/N = mean degree, ~15 at LargeFluid
    scale), and (c) gathers compute-dtype (bf16) products instead of f32
    features — all exactly the same math as MLP([H, H], act_last=True) on
    the concat, in a cheaper order (BASELINE.md round-2 optimization list).
    Parameters: one fused (2H+S, H) kernel + bias with torch nn.Linear
    defaults at the FULL fan-in, so init parity matches the fused Dense.

    ``ops`` is the EdgeOps dispatch — the gathers ride the blocked one-hot
    fast path when the batch carries it.
    """

    hidden_nf: int
    scalar_nf: int           # per-edge scalar features: radial (+ edge_attr)
    act: Callable = nn.silu
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, h, scalars, ops):
        H = self.hidden_nf
        fan_in = 2 * H + self.scalar_nf
        w = self.param("kernel", torch_linear_init, (fan_in, H), jnp.float32)
        b = self.param("bias", _torch_bias_init(fan_in), (H,), jnp.float32)
        y = self.act(_hoisted_linear(w, b, h, scalars, ops, H,
                                     scalars_first=False, dtype=self.dtype))
        return self.act(TorchDense(H, dtype=self.dtype)(y))


class HoistedGate(nn.Module):
    """Single Dense over concat([scalars, h_row, h_col]) hoisted to the node
    axis (same algebra as :class:`HoistedEdgeMLP`, scalars-first concat order,
    no activation) — FastSchNet's coordinate gate. Init parity: fused kernel
    + bias with torch nn.Linear defaults at the full fan-in."""

    features: int
    scalar_nf: int
    hidden_nf: int
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, h, scalars, ops):
        S, H = self.scalar_nf, self.hidden_nf
        fan_in = S + 2 * H
        w = self.param("kernel", torch_linear_init, (fan_in, self.features), jnp.float32)
        b = self.param("bias", _torch_bias_init(fan_in), (self.features,), jnp.float32)
        return _hoisted_linear(w, b, h, scalars, ops, H,
                               scalars_first=True, dtype=self.dtype)


def resolve_dtype(d):
    """Normalize a compute-dtype spec (None | 'bf16' | 'bfloat16' | dtype) to
    a jnp dtype or None (= float32 compute)."""
    if d is None or d in ("none", "None", "f32", "float32"):
        return None
    if d in ("bf16", "bfloat16") or d is jnp.bfloat16:
        return jnp.bfloat16
    return jnp.dtype(d)


def gather_nodes(data: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Batched node gather: data [B, N, F], idx [B, E] -> [B, E, F].

    One XLA gather per call — the TPU form of the reference's ``coord[row]``
    advanced indexing on flat arrays."""
    return jnp.take_along_axis(data, idx[..., None], axis=1)
