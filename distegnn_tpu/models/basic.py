"""Baseline models (reference models/basic.py, 750 LoC): the scalarization
O(n)-equivariant nets and the three factory-served baselines — EGNN (with
velocity), RF_vel, Linear dynamics — plus the plain GNN.

The scalarization trick (EquivariantScalarNet / InvariantScalarNet, reference
basic.py:194-277): stack input vectors Z [.., 3, K], form the Gram matrix
Z^T Z [.., K, K] (rotation-invariant), run MLPs on it, and recombine the
original vectors with predicted coefficients — O(n)-equivariant by
construction, MXU-friendly (everything is batched matmuls).

Batched GraphBatch layout; all aggregations masked. Baselines return
(loc_pred, None) — no virtual nodes (the trainer's MMD path is off for them,
reference utils/train.py:119).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from distegnn_tpu.models.common import MLP, TorchDense, coord_head_init, gather_nodes
from distegnn_tpu.ops.graph import GraphBatch
from distegnn_tpu.ops.segment import segment_mean

from functools import partial

_leaky = partial(nn.leaky_relu, negative_slope=0.2)


class BaseMLP(nn.Module):
    """2-layer MLP (reference BaseMLP, basic.py:167-191); flat mode switches
    to tanh with 4x hidden width."""

    hidden_dim: int
    output_dim: int
    act: Callable = nn.silu
    last_act: bool = False
    residual: bool = False
    flat: bool = False

    @nn.compact
    def __call__(self, x):
        act = jnp.tanh if self.flat else self.act
        hidden = 4 * self.hidden_dim if self.flat else self.hidden_dim
        out = MLP([hidden, self.output_dim], act=act, act_last=self.last_act)(x)
        return x + out if self.residual else out


def _gram(Z: jnp.ndarray, norm: bool) -> jnp.ndarray:
    """Z [..., 3, K] -> flattened Gram [..., K*K], optionally L2-normalized."""
    K = Z.shape[-1]
    scalar = jnp.einsum("...dk,...de->...ke", Z, Z)
    scalar = scalar.reshape(scalar.shape[:-2] + (K * K,))
    if norm:
        scalar = scalar / jnp.maximum(jnp.linalg.norm(scalar, axis=-1, keepdims=True), 1e-12)
    return scalar


class EquivariantScalarNet(nn.Module):
    """vectors [.., 3, K] (+ scalars) -> (equivariant vector [.., 3],
    invariant scalar [.., H]) (reference basic.py:194-238)."""

    n_vector_input: int
    hidden_dim: int
    norm: bool = True
    flat: bool = True

    @nn.compact
    def __call__(self, vectors, scalars=None):
        Z = jnp.stack(vectors, axis=-1) if isinstance(vectors, (list, tuple)) else vectors
        s = _gram(Z, self.norm)
        if scalars is not None:
            s = jnp.concatenate([s, scalars], axis=-1)
        s = BaseMLP(self.hidden_dim, self.hidden_dim, last_act=True, flat=self.flat,
                    name="in_scalar_net")(s)
        coef = BaseMLP(self.hidden_dim, self.n_vector_input, flat=self.flat,
                       name="out_vector_net")(s)
        vector = jnp.einsum("...dk,...k->...d", Z, coef)
        scalar = BaseMLP(self.hidden_dim, self.hidden_dim, flat=self.flat,
                         name="out_scalar_net")(s)
        return vector, scalar


class InvariantScalarNet(nn.Module):
    """vectors [.., 3, K] (+ scalars) -> invariant [.., output_dim]
    (reference basic.py:241-277)."""

    n_vector_input: int
    hidden_dim: int
    output_dim: int
    norm: bool = True
    last_act: bool = False
    flat: bool = False

    @nn.compact
    def __call__(self, vectors, scalars=None):
        Z = jnp.stack(vectors, axis=-1) if isinstance(vectors, (list, tuple)) else vectors
        s = _gram(Z, self.norm)
        if scalars is not None:
            s = jnp.concatenate([s, scalars], axis=-1)
        return BaseMLP(self.hidden_dim, self.output_dim, last_act=self.last_act,
                       flat=self.flat, name="scalar_net")(s)


class EquivariantEdgeScalarNet(nn.Module):
    """Per-edge O(n)-equivariant net (reference basic.py:467-507): cross-Gram
    Z_j^T Z_i -> MLP -> KxK recombination matrix applied to Z_j. Returns
    (vectors [.., 3, K], scalars [.., H]). The vector count K comes from the
    input shape."""

    hidden_dim: int
    norm: bool = True
    flat: bool = False

    @nn.compact
    def __call__(self, vectors_i, vectors_j, scalars=None):
        K = vectors_i.shape[-1]
        s = jnp.einsum("...dj,...dk->...jk", vectors_j, vectors_i)
        s = s.reshape(s.shape[:-2] + (K * K,))
        if self.norm:
            s = s / jnp.maximum(jnp.linalg.norm(s, axis=-1, keepdims=True), 1e-12)
        if scalars is not None:
            s = jnp.concatenate([s, scalars], axis=-1)
        s = BaseMLP(self.hidden_dim, self.hidden_dim, last_act=True, flat=self.flat,
                    name="in_scalar_net")(s)
        coef = BaseMLP(self.hidden_dim, K * K, flat=self.flat, name="out_vector_net")(s)
        coef = coef.reshape(coef.shape[:-1] + (K, K))
        vector = jnp.einsum("...dj,...jk->...dk", vectors_j, coef)
        return vector, s


class EGMN(nn.Module):
    """Stacked EquivariantScalarNet over a growing vector list (reference
    EGMN, basic.py:339-356)."""

    n_layers: int
    n_vector_input: int
    hidden_dim: int
    norm: bool = False
    flat: bool = False

    @nn.compact
    def __call__(self, vectors, scalars):
        cur = list(vectors)
        for i in range(self.n_layers):
            vector, scalars = EquivariantScalarNet(
                n_vector_input=self.n_vector_input + i, hidden_dim=self.hidden_dim,
                norm=self.norm, flat=self.flat, name=f"layer_{i}",
            )(cur, scalars)
            cur.append(vector)
        return cur[-1], scalars


class EGCLClassic(nn.Module):
    """The classic EGNN conv (reference E_GCL, basic.py:69-164; superseded by
    EGNNLayer in the factory but part of the model library): sum-aggregated
    edge messages, (1+|r|)-normalized coordinate differences, residual node
    update."""

    hidden_nf: int
    edge_attr_nf: int = 0
    recurrent: bool = True
    attention: bool = False
    clamp: bool = False
    tanh: bool = False
    coords_weight: float = 1.0

    @nn.compact
    def __call__(self, h, x, g: GraphBatch):
        N = x.shape[1]
        row, col = g.row, g.col
        coord_diff = gather_nodes(x, row) - gather_nodes(x, col)
        radial = jnp.sum(coord_diff**2, axis=-1, keepdims=True)
        coord_diff = coord_diff / (jnp.sqrt(radial + 1e-8) + 1.0)

        e_in = [gather_nodes(h, row), gather_nodes(h, col), radial]
        if self.edge_attr_nf:
            e_in.append(g.edge_attr)
        ef = MLP([self.hidden_nf, self.hidden_nf], act_last=True,
                 name="edge_mlp")(jnp.concatenate(e_in, axis=-1))
        if self.attention:
            ef = ef * jax.nn.sigmoid(TorchDense(1, name="att_mlp")(ef))
        ef = ef * g.edge_mask[..., None]

        gate = MLP([self.hidden_nf, 1], use_bias_last=False,
                   kernel_init_last=coord_head_init, name="coord_mlp")(ef)
        if self.tanh:
            gate = jnp.tanh(gate)
        trans = coord_diff * gate
        if self.clamp:
            trans = jnp.clip(trans, -100.0, 100.0)
        from distegnn_tpu.ops.segment import segment_sum

        agg_x = jax.vmap(lambda t, r, e: segment_mean(t, r, N, mask=e))(trans, row, g.edge_mask)
        x = x + agg_x * self.coords_weight

        agg_h = jax.vmap(lambda t, r, e: segment_sum(t, r, N, mask=e))(ef, row, g.edge_mask)
        out = MLP([self.hidden_nf, self.hidden_nf],
                  name="node_mlp")(jnp.concatenate([h, agg_h], axis=-1))
        h = h + out if self.recurrent else out
        return h * g.node_mask[..., None], x * g.node_mask[..., None]


class EGNNLayer(nn.Module):
    """Scalarization-based EGNN conv with velocity head and the +-100 force
    clamp (reference EGNN_Layer, basic.py:280-306)."""

    hidden_nf: int
    edge_attr_nf: int = 0
    with_v: bool = False
    flat: bool = False
    norm: bool = False

    @nn.compact
    def __call__(self, x, h, v, g: GraphBatch):
        N = x.shape[1]
        row, col = g.row, g.col
        rij = gather_nodes(x, row) - gather_nodes(x, col)                # [B, E, 3]
        hij = [gather_nodes(h, row), gather_nodes(h, col)]
        if self.edge_attr_nf:
            hij.append(g.edge_attr)
        message = InvariantScalarNet(
            n_vector_input=1, hidden_dim=self.hidden_nf, output_dim=self.hidden_nf,
            norm=self.norm, last_act=True, flat=self.flat, name="edge_message_net",
        )(rij[..., None], scalars=jnp.concatenate(hij, axis=-1))         # [B, E, H]
        message = message * g.edge_mask[..., None]

        coord_message = BaseMLP(self.hidden_nf, 1, flat=self.flat, name="coord_net")(message)
        f = rij * coord_message
        tot_f = jax.vmap(lambda m, r, e: segment_mean(m, r, N, mask=e))(f, row, g.edge_mask)
        tot_f = jnp.clip(tot_f, -100.0, 100.0)

        if v is not None:
            x = x + BaseMLP(self.hidden_nf, 1, flat=self.flat, name="node_v_net")(h) * v + tot_f
        else:
            x = x + tot_f
        x = x * g.node_mask[..., None]

        tot_message = jax.vmap(lambda m, r, e: segment_mean(m, r, N, mask=e))(message, row, g.edge_mask)
        h = BaseMLP(self.hidden_nf, self.hidden_nf, flat=self.flat, name="node_net")(
            jnp.concatenate([h, tot_message], axis=-1))
        h = h * g.node_mask[..., None]
        return x, v, h


class EGNN(nn.Module):
    """EGNN baseline (reference EGNN, basic.py:309-336; factory main.py:82-84
    with with_v=True). Returns (loc_pred, None)."""

    n_layers: int
    in_node_nf: int
    in_edge_nf: int
    hidden_nf: int
    with_v: bool = True
    flat: bool = False
    norm: bool = False

    @nn.compact
    def __call__(self, g: GraphBatch) -> Tuple[jnp.ndarray, None]:
        h = TorchDense(self.hidden_nf, name="embedding")(g.node_feat)
        x, v = g.loc, (g.vel if self.with_v else None)
        for i in range(self.n_layers):
            x, v, h = EGNNLayer(
                hidden_nf=self.hidden_nf, edge_attr_nf=self.in_edge_nf,
                with_v=self.with_v, flat=self.flat, norm=self.norm, name=f"layer_{i}",
            )(x, h, v, g)
        return x, None


class RFVel(nn.Module):
    """RF baseline (reference RF_vel + GCL_rf_vel, basic.py:413-464): per
    layer m_ij = (x_i - x_j) * tanh(phi(|x_i - x_j|, e_ij)) with the bias-free
    xavier(0.001) scalar head, x += mean-agg + v * psi(|v|). Activation is
    SiLU — RF_vel forwards its act_fn default into the layers (basic.py:419),
    unlike FastRF which drops it. Returns (loc_pred, None)."""

    hidden_nf: int
    edge_attr_nf: int = 0
    n_layers: int = 4

    @nn.compact
    def __call__(self, g: GraphBatch) -> Tuple[jnp.ndarray, None]:
        x, v = g.loc, g.vel
        vel_norm = jnp.linalg.norm(v + 1e-30, axis=-1, keepdims=True)
        N = x.shape[1]
        row, col = g.row, g.col
        for i in range(self.n_layers):
            x_diff = gather_nodes(x, row) - gather_nodes(x, col)
            radial = jnp.sqrt(jnp.sum(x_diff**2, axis=-1, keepdims=True) + 1e-30)
            e_in = (jnp.concatenate([radial, g.edge_attr], axis=-1)
                    if self.edge_attr_nf else radial)
            gate = MLP([self.hidden_nf, 1], use_bias_last=False,
                       kernel_init_last=coord_head_init, name=f"phi_{i}")(e_in)
            m = x_diff * jnp.tanh(gate)
            agg = jax.vmap(lambda mm, r, e: segment_mean(mm, r, N, mask=e))(m, row, g.edge_mask)
            x = x + agg
            x = x + v * MLP([self.hidden_nf, 1], name=f"coord_mlp_vel_{i}")(vel_norm)
            x = x * g.node_mask[..., None]
        return x, None


class GNN(nn.Module):
    """Plain message-passing GNN with a 3-dim decoder (reference GNN_Layer +
    GNN, basic.py:359-399): non-equivariant baseline predicting absolute
    positions (decoder output returned directly)."""

    n_layers: int
    in_node_nf: int
    in_edge_nf: int
    hidden_nf: int

    @nn.compact
    def __call__(self, g: GraphBatch) -> Tuple[jnp.ndarray, None]:
        N = g.loc.shape[1]
        row, col = g.row, g.col
        h = TorchDense(self.hidden_nf, name="embedding")(
            jnp.concatenate([g.node_feat, g.loc, g.vel], axis=-1))
        for i in range(self.n_layers):
            msg_in = [gather_nodes(h, row), gather_nodes(h, col)]
            if self.in_edge_nf:
                msg_in.append(g.edge_attr)
            msg = MLP([self.hidden_nf, self.hidden_nf],
                      name=f"edge_mlp_{i}")(jnp.concatenate(msg_in, axis=-1))
            msg = msg * g.edge_mask[..., None]
            agg = jax.vmap(lambda m, r, e: segment_mean(m, r, N, mask=e))(msg, row, g.edge_mask)
            h = h + MLP([self.hidden_nf, self.hidden_nf],
                        name=f"node_mlp_{i}")(jnp.concatenate([agg, h], axis=-1))
            h = h * g.node_mask[..., None]
        out = MLP([self.hidden_nf, 3], name="decoder")(h)
        return out * g.node_mask[..., None], None


class LinearDynamics(nn.Module):
    """x + v * t with learnable scalar t (reference Linear_dynamics,
    basic.py:402-410)."""

    @nn.compact
    def __call__(self, g: GraphBatch) -> Tuple[jnp.ndarray, None]:
        t = self.param("time", nn.initializers.ones, (1,))
        return g.loc + g.vel * t, None


class FullMLP(nn.Module):
    """Flat MLP over concatenated per-node state (reference FullMLP,
    basic.py:734-749) — the weakest baseline."""

    hidden_nf: int = 64

    @nn.compact
    def __call__(self, g: GraphBatch) -> Tuple[jnp.ndarray, None]:
        inp = jnp.concatenate([g.node_feat, g.loc, g.vel], axis=-1)
        out = MLP([self.hidden_nf, self.hidden_nf, 3], name="mlp")(inp)
        return g.loc + out * g.node_mask[..., None], None
