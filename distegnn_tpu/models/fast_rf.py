"""FastRF — Radial-Field dynamics + virtual nodes, TPU-native.

Re-design of reference models/FastRF.py (GCL_RF_vel + FastRF, 222 LoC): a
radial-field layer (no node features — messages are pure functions of
geometry) augmented with C global virtual nodes; in distributed mode the
virtual coordinate update is the only cross-partition channel (reference
FastRF.py:140-144 — its single weighted_average_reduce).

Reference quirks preserved on purpose:
  - the coordinate mean entering the virtual Gram m_X is the LOCAL
    (per-partition) mean — the reference does not allreduce it here, unlike
    FastEGNN (FastRF.py:166 vs FastEGNN.py:258-261);
  - the layer activation is LeakyReLU(0.2) (GCL_RF_vel's default; FastRF's
    act_fn=SiLU argument is never forwarded, FastRF.py:52,178-186).

Layout identical to FastEGNN: dense [B,N,...]/[B,E,...] GraphBatch with masks.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from distegnn_tpu.models.common import MLP, TorchDense, coord_head_init
from distegnn_tpu.ops.blocked import EdgeOps, blocked_slot_inv_deg
from distegnn_tpu.ops.graph import GraphBatch
from distegnn_tpu.parallel.collectives import global_node_mean

_leaky = partial(nn.leaky_relu, negative_slope=0.2)


class _RadialField(nn.Module):
    """phi: invariants -> tanh'd H-vector; last layer bias-free xavier(0.001)
    (reference GCL_RF_vel.__init__, FastRF.py:62-76)."""

    hidden_nf: int

    @nn.compact
    def __call__(self, x):
        x = MLP([self.hidden_nf, self.hidden_nf], act=_leaky,
                use_bias_last=False, kernel_init_last=coord_head_init)(x)
        return jnp.tanh(x)


class _ScalarHead(nn.Module):
    """Linear(H) -> LeakyReLU -> Linear(1) (edge_mlp / edge_mlp_rv / edge_mlp_vr,
    FastRF.py:79-95)."""

    hidden_nf: int

    @nn.compact
    def __call__(self, x):
        return MLP([self.hidden_nf, 1], act=_leaky)(x)


class GCLRFVel(nn.Module):
    """One radial-field conv layer with velocity + virtual channels
    (reference GCL_RF_vel.forward, FastRF.py:155-172)."""

    hidden_nf: int
    virtual_channels: int
    edge_attr_nf: int = 0
    axis_name: Optional[str] = None
    seg_impl: str = "scatter"

    @nn.compact
    def __call__(self, x, v, X, g: GraphBatch, slot=None, inv_deg=None, oh=None
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        H, C = self.hidden_nf, self.virtual_channels
        node_mask = g.node_mask
        B, N = x.shape[0], x.shape[1]
        ops = EdgeOps(g, slot, inv_deg, oh, seg_impl=self.seg_impl)

        coord_diff = ops.gather_rows(x) - ops.gather_cols(x)             # [B, E, 3]
        radial = jnp.sum(coord_diff**2, axis=-1, keepdims=True)          # [B, E, 1]
        vcd = X[:, None, :, :] - x[..., None]                            # [B, N, 3, C]
        virtual_radial = jnp.linalg.norm(vcd, axis=2, keepdims=True)     # [B, N, 1, C]

        e_in = jnp.concatenate([radial, g.edge_attr], axis=-1) if self.edge_attr_nf else radial
        edge_feat = _RadialField(H, name="phi")(e_in)                    # [B, E, H]

        # LOCAL per-graph coordinate mean (reference keeps this un-reduced)
        coord_mean = global_node_mean(x, node_mask, axis_name=None)      # [B, 3]
        Xc = X - coord_mean[:, :, None]
        m_X = jnp.einsum("bdc,bde->bce", Xc, Xc)                         # [B, C, C]

        v_in = jnp.concatenate(
            [jnp.swapaxes(virtual_radial, 2, 3),                          # [B, N, C, 1]
             jnp.broadcast_to(m_X[:, None, :, :], (B, N, C, C))],
            axis=-1,
        )
        vef = _RadialField(H, name="phi_v")(v_in) * node_mask[:, :, None, None]  # [B, N, C, H]

        # real coordinate update (node_model, FastRF.py:119-131)
        trans = coord_diff * _ScalarHead(H, name="edge_mlp")(edge_feat)
        agg = ops.agg_rows_mean(trans)
        trans_v = jnp.mean(-vcd * jnp.swapaxes(_ScalarHead(H, name="edge_mlp_rv")(vef), 2, 3), axis=-1)
        speed = jnp.linalg.norm(v, axis=-1, keepdims=True)
        x = x + agg + trans_v + v * MLP([H, 1], act=_leaky, name="coord_mlp_vel")(speed)
        x = x * node_mask[..., None]

        # virtual coordinate update — the one cross-partition psum
        # (node_model_virtual, FastRF.py:134-144)
        trans_X = vcd * jnp.swapaxes(_ScalarHead(H, name="edge_mlp_vr")(vef), 2, 3)
        X = X + global_node_mean(trans_X, node_mask, self.axis_name)     # [B, 3, C]
        return x, X


class FastRF(nn.Module):
    """FastRF wrapper (reference FastRF.py:177-194): no embeddings, no node
    features — n_layers of GCL_RF_vel over (loc, vel, virtual loc)."""

    edge_attr_nf: int = 0
    hidden_nf: int = 64
    virtual_channels: int = 3
    n_layers: int = 4
    axis_name: Optional[str] = None
    blocked_impl: str = "einsum"  # blocked-layout edge-op lowering ('pallas'|'einsum')
    segment_impl: str = "scatter"  # plain-layout lowering ('scatter'|'cumsum'|'ell')

    @nn.compact
    def __call__(self, g: GraphBatch) -> Tuple[jnp.ndarray, jnp.ndarray]:
        assert self.virtual_channels > 0, "virtual_channels must be > 0"
        C = self.virtual_channels
        X = jnp.repeat(g.loc_mean[:, :, None], C, axis=2)                # [B, 3, C]
        x, v = g.loc, g.vel
        slot, inv_deg, oh = blocked_slot_inv_deg(g, self.blocked_impl)
        for i in range(self.n_layers):
            x, X = GCLRFVel(
                hidden_nf=self.hidden_nf, virtual_channels=C,
                edge_attr_nf=self.edge_attr_nf, axis_name=self.axis_name,
                seg_impl=self.segment_impl,
                name=f"gcl_{i}",
            )(x, v, X, g, slot=slot, inv_deg=inv_deg, oh=oh)
        return x, X
