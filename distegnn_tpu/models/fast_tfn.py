"""FastTFN — the FastEGNN virtual-node skeleton whose real-node coordinate
update is a 1-layer TFN, TPU-native.

Re-design of reference models/FastTFN.py (TFN_GCL_vel + FastTFN, 281 LoC): per
layer the real coordinates move by a tiny TFN (num_layers=1, num_channels=1,
num_degrees=2 — FastTFN.py:37) fed with charges (degree 0) and velocity
(degree 1) over the same edges, while the virtual-node machinery is exactly
FastEGNN's. The reference builds a DGL graph per forward (FastTFN.py:129-141);
here the TFN runs on the same padded GraphBatch arrays. Single-device model in
the reference (no dist code, SURVEY.md §2.4); axis_name generalizes it to the
mesh anyway."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from distegnn_tpu.models.common import MLP, CoordMLP, TorchDense, gather_nodes
from distegnn_tpu.models.se3.basis import cart_to_deg1, deg1_to_cart
from distegnn_tpu.models.se3.tfn import TFN
from distegnn_tpu.ops.graph import GraphBatch
from distegnn_tpu.ops.segment import segment_mean
from distegnn_tpu.parallel.collectives import global_node_mean


class TFNGCLVel(nn.Module):
    """One FastTFN layer (reference TFN_GCL_vel, FastTFN.py:9-204)."""

    hidden_nf: int
    virtual_channels: int
    node_attr_nf: int = 0
    edge_attr_nf: int = 0
    residual: bool = True
    attention: bool = False
    normalize: bool = False
    tanh: bool = False
    has_gravity: bool = False
    axis_name: Optional[str] = None
    epsilon: float = 1e-8

    @nn.compact
    def __call__(self, h, x, v, X, Hv, g: GraphBatch, charges, gravity=None):
        H, C = self.hidden_nf, self.virtual_channels
        row, col = g.row, g.col
        node_mask, edge_mask = g.node_mask, g.edge_mask
        nm = node_mask[..., None]
        B, N = h.shape[0], h.shape[1]

        raw_diff = gather_nodes(x, row) - gather_nodes(x, col)
        radial = jnp.sum(raw_diff**2, axis=-1, keepdims=True)
        vcd = X[:, None, :, :] - x[..., None]
        virtual_radial = jnp.linalg.norm(vcd, axis=2, keepdims=True)

        e_in = [gather_nodes(h, row), gather_nodes(h, col), radial]
        if self.edge_attr_nf:
            e_in.append(g.edge_attr)
        edge_feat = MLP([H, H], act_last=True, name="phi_e")(jnp.concatenate(e_in, axis=-1))
        if self.attention:
            edge_feat = edge_feat * jax.nn.sigmoid(TorchDense(1, name="att")(edge_feat))
        edge_feat = edge_feat * edge_mask[..., None]

        # LOCAL with the default axis_name=None (reference FastTFN is
        # single-device, FastTFN.py:217); honors the mesh axis when set
        coord_mean = global_node_mean(x, node_mask, self.axis_name)
        Xc = X - coord_mean[:, :, None]
        m_X = jnp.einsum("bdc,bde->bce", Xc, Xc)

        v_in = jnp.concatenate(
            [
                jnp.broadcast_to(h[:, :, None, :], (B, N, C, H)),
                jnp.broadcast_to(jnp.swapaxes(Hv, 1, 2)[:, None, :, :], (B, N, C, H)),
                jnp.swapaxes(virtual_radial, 2, 3),
                jnp.broadcast_to(m_X[:, None, :, :], (B, N, C, C)),
            ],
            axis=-1,
        )
        vef = MLP([H, H], act_last=True, name="phi_ev")(v_in)
        if self.attention:
            vef = vef * jax.nn.sigmoid(TorchDense(1, name="att_v")(vef))
        vef = vef * node_mask[:, :, None, None]

        # real coordinate update by a 1-layer TFN over the same graph, on a
        # GraphBatch whose loc is the CURRENT x (coord_model_by_tfn,
        # FastTFN.py:129-150): in {charges:0, vel:1} -> out {1:1}
        g_now = g.replace(loc=x)
        tfn_in = {0: charges[..., None], 1: cart_to_deg1(v)[:, :, None, :]}
        tfn_out = TFN(num_layers=1, num_channels=1, num_degrees=2,
                      in_types={0: 1, 1: 1}, out_types={1: 1}, name="tfn_layer")(tfn_in, g_now)
        x = x + deg1_to_cart(tfn_out[1][:, :, 0, :])

        phi_xv = CoordMLP(H, tanh=self.tanh, name="phi_xv")(vef)
        x = x + jnp.mean(-vcd * jnp.swapaxes(phi_xv, 2, 3), axis=-1)
        if self.has_gravity:
            x = x + MLP([H, 1], name="phi_g")(h) * gravity
        x = x * nm

        trans_X = vcd * jnp.swapaxes(CoordMLP(H, tanh=self.tanh, name="phi_X")(vef), 2, 3)
        X = X + global_node_mean(trans_X, node_mask, self.axis_name)

        agg_h = jax.vmap(lambda t, r, m: segment_mean(t, r, N, mask=m))(edge_feat, row, edge_mask)
        agg_v = jnp.mean(vef, axis=2)
        n_in = [h, agg_h, agg_v]
        if self.node_attr_nf:
            n_in.append(g.node_attr)
        out = MLP([H, H], name="phi_h")(jnp.concatenate(n_in, axis=-1))
        h = ((h + out) if self.residual else out) * nm

        agg_Hv = global_node_mean(vef, node_mask, self.axis_name)
        hv_in = jnp.concatenate([jnp.swapaxes(Hv, 1, 2), agg_Hv], axis=-1)
        out_v = jnp.swapaxes(MLP([H, H], name="phi_hv")(hv_in), 1, 2)
        Hv = (Hv + out_v) if self.residual else out_v

        return h, x, Hv, X


class FastTFN(nn.Module):
    """FastTFN wrapper (reference FastTFN.py:207-260). Forward takes the extra
    ``charges`` from node_attr (reference model_forward passes charges,
    utils/train.py:67-70)."""

    node_feat_nf: int
    node_attr_nf: int = 0
    edge_attr_nf: int = 0
    hidden_nf: int = 64
    virtual_channels: int = 3
    n_layers: int = 4
    residual: bool = True
    attention: bool = False
    normalize: bool = False
    tanh: bool = False
    gravity: Optional[Tuple[float, float, float]] = None
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, g: GraphBatch) -> Tuple[jnp.ndarray, jnp.ndarray]:
        assert self.virtual_channels > 0, "virtual_channels must be > 0"
        B = g.batch_size
        H, C = self.hidden_nf, self.virtual_channels

        charges = g.node_attr[..., 0] if g.node_attr.shape[-1] else g.node_feat[..., -1]
        Hv0 = self.param("virtual_node_feat", nn.initializers.normal(1.0), (1, H, C))
        Hv = jnp.broadcast_to(Hv0, (B, H, C))
        X = jnp.repeat(g.loc_mean[:, :, None], C, axis=2)

        h = TorchDense(H, name="embedding_in")(g.node_feat)
        x, v = g.loc, g.vel
        gravity = jnp.asarray(self.gravity, jnp.float32) if self.gravity is not None else None

        for i in range(self.n_layers):
            h, x, Hv, X = TFNGCLVel(
                hidden_nf=H, virtual_channels=C,
                node_attr_nf=self.node_attr_nf, edge_attr_nf=self.edge_attr_nf,
                residual=self.residual, attention=self.attention,
                normalize=self.normalize, tanh=self.tanh,
                has_gravity=self.gravity is not None, axis_name=self.axis_name,
                name=f"gcl_{i}",
            )(h, x, v, X, Hv, g, charges, gravity=gravity)
        return x, X
