"""EGHN — Equivariant Hierarchical Network (reference EGHN + PoolingLayer/
PoolingNet, basic.py:510-731; present in the reference model library but
never served by its factory).

Pipeline per forward: low-level EGNN force -> learned soft cluster assignment
(PoolingNet over equivariant edge messages) -> cluster-pooled high-level graph
(full P x P edges weighted by the pooled adjacency, self-loops included as in
the reference, whose construct_edges mask is built then ignored,
basic.py:723-731) -> high-level EGNN -> equivariant kinematics decode
(EquivariantScalarNet / EGMN) back onto nodes. The normalized-cut auxiliary
loss is returned alongside the prediction.

Dense-batch delta: the reference flattens [B*N] and reshapes around every
einsum; the [B, N, ...] GraphBatch layout removes all of that."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from distegnn_tpu.models.basic import (
    BaseMLP,
    EGMN,
    EGNNLayer,
    EquivariantEdgeScalarNet,
    EquivariantScalarNet,
)
from distegnn_tpu.models.common import TorchDense, gather_nodes
from distegnn_tpu.ops.graph import GraphBatch
from distegnn_tpu.ops.segment import segment_mean, segment_sum


class PoolingLayer(nn.Module):
    """Vector+scalar message passing step of the pooling net (reference
    basic.py:510-540)."""

    hidden_nf: int
    n_vector_input: int
    edge_attr_nf: int = 0
    flat: bool = False

    @nn.compact
    def __call__(self, vectors, h, g: GraphBatch):
        N = h.shape[1]
        row, col = g.row, g.col
        hij = [gather_nodes(h, row), gather_nodes(h, col)]
        if self.edge_attr_nf:
            hij.append(g.edge_attr)
        B = vectors.shape[0]
        vec_flat = vectors.reshape(B, N, -1)
        v_i = gather_nodes(vec_flat, row).reshape(vectors.shape[:1] + (row.shape[1],) + vectors.shape[2:])
        v_j = gather_nodes(vec_flat, col).reshape(v_i.shape)
        vec_out, msg = EquivariantEdgeScalarNet(
            hidden_dim=self.hidden_nf, norm=True, flat=self.flat,
            name="edge_message_net",
        )(v_i, v_j, scalars=jnp.concatenate(hij, axis=-1))
        vec_out = vec_out * g.edge_mask[..., None, None]
        msg = msg * g.edge_mask[..., None]

        vflat = vec_out.reshape(vec_out.shape[:2] + (-1,))
        agg_v = jax.vmap(lambda t, r, e: segment_mean(t, r, N, mask=e))(vflat, row, g.edge_mask)
        vectors = vectors + agg_v.reshape(vectors.shape)
        agg_m = jax.vmap(lambda t, r, e: segment_sum(t, r, N, mask=e))(msg, row, g.edge_mask)
        h = h + BaseMLP(self.hidden_nf, self.hidden_nf, flat=self.flat, name="node_net")(
            jnp.concatenate([h, agg_m], axis=-1))
        return vectors, h


class PoolingNet(nn.Module):
    """Stacked PoolingLayers + a tanh MLP head to cluster logits (reference
    basic.py:543-563)."""

    n_layers: int
    n_vector_input: int
    hidden_nf: int
    output_nf: int
    edge_attr_nf: int = 0
    flat: bool = False

    @nn.compact
    def __call__(self, vectors, h, g: GraphBatch):
        if isinstance(vectors, (list, tuple)):
            vectors = jnp.stack(vectors, axis=-1)       # [B, N, 3, V]
        for i in range(self.n_layers):
            vectors, h = PoolingLayer(
                hidden_nf=self.hidden_nf, n_vector_input=self.n_vector_input,
                edge_attr_nf=self.edge_attr_nf, flat=self.flat, name=f"layer_{i}",
            )(vectors, h, g)
        h = TorchDense(8 * self.hidden_nf, name="pool_0")(h)
        h = jnp.tanh(h)
        return TorchDense(self.output_nf, name="pool_1")(h)


def _full_cluster_batch(X, V, H_feat, AA, P):
    """GraphBatch over the P-cluster graph: full P x P edges (self-loops
    included, matching the reference's effective behavior), edge_attr = pooled
    adjacency weights."""
    import numpy as np

    B = X.shape[0]
    row = jnp.asarray(np.repeat(np.arange(P), P))[None, :].repeat(B, axis=0)
    col = jnp.asarray(np.tile(np.arange(P), P))[None, :].repeat(B, axis=0)
    edge_attr = AA.reshape(B, P * P, 1)
    ones_e = jnp.ones((B, P * P), X.dtype)
    ones_n = jnp.ones((B, P), X.dtype)
    return GraphBatch(
        node_feat=H_feat, node_attr=jnp.zeros((B, P, 0), X.dtype), loc=X, vel=V,
        target=jnp.zeros_like(X), loc_mean=jnp.mean(X, axis=1),
        node_mask=ones_n, edge_index=jnp.stack([row, col], axis=1),
        edge_attr=edge_attr, edge_mask=ones_e,
    )


class EGHN(nn.Module):
    """Reference EGHN (basic.py:566-711). Returns (loc_pred, None).

    The normalized-cut auxiliary loss is sown into the 'aux' collection; to
    consume it, call ``out, state = model.apply(params, g, mutable=['aux'])``
    and read ``state['aux']['cut_loss']`` — a plain ``apply(params, g)``
    silently drops it (flax semantics), so a trainer adding the reference's
    cut-loss term (basic.py:713-716) MUST pass mutable=['aux']."""

    in_node_nf: int
    in_edge_nf: int
    hidden_nf: int
    n_cluster: int = 4
    layer_per_block: int = 3
    layer_pooling: int = 3
    layer_decoder: int = 1
    with_v: bool = True
    flat: bool = False
    norm: bool = False

    @nn.compact
    def __call__(self, g: GraphBatch) -> Tuple[jnp.ndarray, None]:
        P = self.n_cluster
        x, v = g.loc, g.vel
        nmask = g.node_mask[..., None]
        h = TorchDense(self.hidden_nf, name="embedding")(g.node_feat)

        # low-level force
        hx, hv, hh = x, v, h
        for i in range(self.layer_per_block):
            hx, hv, hh = EGNNLayer(hidden_nf=self.hidden_nf, edge_attr_nf=self.in_edge_nf,
                                   with_v=self.with_v, flat=self.flat, norm=self.norm,
                                   name=f"low_{i}")(hx, hh, hv, g)
        nf = hx - x

        # pooling assignment (local edges := the same graph edges; the
        # reference's factory never wires a separate local edge set)
        x_mean = jnp.sum(x * nmask, axis=1, keepdims=True) / jnp.maximum(
            jnp.sum(nmask, axis=1, keepdims=True), 1.0)
        vecs = [x - x_mean, nf, v] if self.with_v else [x - x_mean, nf]
        pooling_fea = PoolingNet(
            n_layers=self.layer_pooling, n_vector_input=len(vecs),
            hidden_nf=self.hidden_nf, output_nf=P, edge_attr_nf=self.in_edge_nf,
            flat=self.flat, name="low_pooling",
        )(vecs, hh, g)                                             # [B, N, P]
        s = jax.nn.softmax(pooling_fea, axis=-1) * nmask           # [B, N, P]

        # cluster aggregation
        count = jnp.maximum(jnp.sum(s, axis=1), 1e-5)[..., None]   # [B, P, 1]
        X = jnp.einsum("bnp,bnd->bpd", s, x) / count
        H = jnp.einsum("bnp,bnd->bpd", s, hh) / count
        NF = jnp.einsum("bnp,bnd->bpd", s, nf) / count
        V = jnp.einsum("bnp,bnd->bpd", s, v) / count if self.with_v else None

        # pooled adjacency + cut loss (reference basic.py:667-676,713-716)
        N = x.shape[1]
        a = jax.vmap(lambda sp, r, c, e: segment_sum(
            sp[c] * e[:, None], r, N))(s, g.row, g.col, g.edge_mask)  # [B, N, P]
        A = jnp.einsum("bnp,bnq->bpq", s, a)                          # [B, P, P]
        A_n = A / jnp.maximum(jnp.linalg.norm(A, axis=2, keepdims=True), 1e-12)
        cut_loss = jnp.mean(jnp.linalg.norm(
            (A_n - jnp.eye(P)).reshape(A.shape[0], -1), axis=-1))
        self.sow("aux", "cut_loss", cut_loss)

        # high-level message passing on the full cluster graph
        gc = _full_cluster_batch(X, V if V is not None else jnp.zeros_like(X), H, A, P)
        cx, cv, ch = gc.loc, (gc.vel if self.with_v else None), H
        for i in range(self.layer_per_block):
            cx, cv, ch = EGNNLayer(hidden_nf=self.hidden_nf, edge_attr_nf=1,
                                   with_v=self.with_v, flat=self.flat,
                                   name=f"high_{i}")(cx, ch, cv, gc)
        h_nf = cx - X
        X2 = X + h_nf

        # low-level kinematics decode
        l_nf = jnp.einsum("bnp,bpd->bnd", s, h_nf)
        l_X = jnp.einsum("bnp,bpd->bnd", s, X)
        l_H = jnp.einsum("bnp,bpd->bnd", s, ch)
        if self.with_v:
            l_V = jnp.einsum("bnp,bpd->bnd", s, cv)
            vectors = [l_nf, x - l_X, v - l_V, nf]
        else:
            vectors = [l_nf, x - l_X, nf]
        scalars = jnp.concatenate([hh, l_H], axis=-1)
        if self.layer_decoder == 1:
            l_kin, _ = EquivariantScalarNet(
                n_vector_input=len(vectors), hidden_dim=self.hidden_nf,
                norm=True, flat=self.flat, name="kinematics_net",
            )(vectors, scalars)
        else:
            l_kin, _ = EGMN(n_layers=self.layer_decoder, n_vector_input=len(vectors),
                            hidden_dim=self.hidden_nf, norm=True, flat=self.flat,
                            name="kinematics_net")(vectors, scalars)
        x_out = jnp.einsum("bnp,bpd->bnd", s, X2) + l_kin
        return x_out * nmask, None
